"""Synthetic traffic generators — the five BASELINE.json configs.

The reference has no load generator at all (its TODO says "Need to
create the testing phase", ``TODO.md:272``); these model the scenarios
BASELINE.json names so benches and tests share one traffic vocabulary.
Each generator yields ``FLOW_RECORD_DTYPE`` arrays — the same records
the kernel's feature extractor emits (``kern/fsx_kern.c``
``extract_features``) — at a configurable packet rate on a synthetic
clock, so a scenario is reproducible and rate-exact regardless of how
fast the host happens to run it.

Feature values are *streaming estimates as the kernel would emit them*:
attack flows get flood-like statistics (tiny IATs, uniform sizes),
benign flows get interactive-like ones.  They exercise the classifier
realistically without pretending to be a packet parser.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np

from flowsentryx_tpu.core import schema


class Scenario(enum.Enum):
    """BASELINE.json configs 1-5 (plus a benign-only control)."""

    BENIGN = "benign"
    ICMP_FLOOD_SINGLE = "icmp_flood_single"     # config 1
    UDP_FLOOD_MULTI = "udp_flood_multi"         # config 2
    OFFLINE_BATCH = "offline_batch"             # config 3 (classifier only)
    SYN_BENIGN_MIX = "syn_benign_mix"           # config 4
    MIXED_L34_1M = "mixed_l34_1m"               # config 5


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """Shape of one scenario's packet stream.

    ``burst_period_s`` > 0 turns the arrival process into a PULSE
    WAVE: the MEAN rate stays ``rate_pps``, but all of each period's
    packets arrive inside its first ``duty_cycle`` fraction at
    ``rate_pps / duty_cycle`` — the adversarial load the latency-budget
    serving mode (``fsx serve --slo-us``) exists for, because a
    drain-rate-tuned dispatch policy queues the burst head behind
    coalescing decisions sized for the mean.  Size the period against
    the batcher deadline (a burst a few ``deadline_us`` long is the
    regime where deadline-flush and coalescing policy interact);
    0 (default) is the steady process, bit-identical to every prior
    artifact."""

    scenario: Scenario = Scenario.SYN_BENIGN_MIX
    rate_pps: float = 10_000_000.0     # synthetic-clock packet rate
    attack_fraction: float = 0.8       # fraction of packets that are attack
    n_attack_ips: int = 1024           # attack source pool
    n_benign_ips: int = 4096           # benign source pool
    seed: int = 0
    burst_period_s: float = 0.0        # 0 = steady (the historical stream)
    duty_cycle: float = 1.0            # on-fraction of each burst period

    def with_(self, **kw) -> "TrafficSpec":
        return dataclasses.replace(self, **kw)


def pulse_offsets_ns(
    idx,
    rate_pps: float,
    burst_period_s: float,
    duty_cycle: float,
):
    """Scheduled arrival offsets (ns from stream start) of 0-based
    record indices ``idx`` under the pulse-wave process — THE one copy
    of the schedule, shared by the synthetic-clock generator
    (:class:`TrafficGen`) and the open-loop wall-clock generator
    (:class:`~flowsentryx_tpu.engine.sources.PacedSource`), so a bench
    and a test can never disagree about when packet k "arrived".

    Steady degenerate case (period 0 / duty 1): ``(k+1)/rate`` — the
    k-th record lands one inter-arrival after start, matching
    ``PacedSource``'s historical schedule exactly.  Pulse case: record
    k of period ``p = k // per_period`` arrives at
    ``p * period + (k % per_period + 1) * on_window / per_period`` —
    every period's quota compressed into its on-window at
    ``rate / duty``."""
    idx = np.asarray(idx, np.int64)
    if rate_pps <= 0:
        raise ValueError("rate_pps must be positive")
    if burst_period_s < 0:
        raise ValueError("burst_period_s must be >= 0")
    if not 0.0 < duty_cycle <= 1.0:
        raise ValueError("duty_cycle must be in (0, 1]")
    if burst_period_s <= 0 or duty_cycle >= 1.0:
        return np.round((idx + 1) * (1e9 / rate_pps)).astype(np.int64)
    per_exact = rate_pps * burst_period_s
    if per_exact < 1.0:
        # clamping to one record per period would silently multiply
        # the offered mean rate (a 100 pps spec with a 1 ms period
        # would really offer 1000 pps) — refuse, the repo idiom
        raise ValueError(
            f"burst_period_s {burst_period_s} holds fewer than one "
            f"record at rate_pps {rate_pps} — the pulse schedule "
            "cannot honor the mean rate; lengthen the period or "
            "raise the rate")
    per_period = int(round(per_exact))
    if abs(per_period - per_exact) / per_exact > 0.05:
        # integerizing the per-period quota shifts the REALIZED mean
        # rate by the rounding ratio — a 1.4-record period would
        # offer 29 % under spec with no error anywhere, and the
        # pulse A/B evidence would record the spec rate against a
        # different offered load.  5 % is well under the effects the
        # benches claim; real pulse shapes carry tens+ records/period.
        raise ValueError(
            f"rate_pps {rate_pps} x burst_period_s {burst_period_s} "
            f"= {per_exact:.3f} records/period rounds to {per_period} "
            f"(> 5% mean-rate error); choose a period holding a "
            "near-integer record count")
    period_ns = burst_period_s * 1e9
    on_ns = period_ns * duty_cycle
    p, k = np.divmod(idx, per_period)
    return np.round(p * period_ns + (k + 1) * (on_ns / per_period)
                    ).astype(np.int64)


#: Per-scenario overrides applied on top of a user spec.
_SCENARIO_SHAPE: dict[Scenario, dict] = {
    Scenario.BENIGN: dict(attack_fraction=0.0),
    Scenario.ICMP_FLOOD_SINGLE: dict(n_attack_ips=1),
    Scenario.UDP_FLOOD_MULTI: dict(n_attack_ips=4096),
    Scenario.OFFLINE_BATCH: dict(),
    Scenario.SYN_BENIGN_MIX: dict(attack_fraction=0.5),
    Scenario.MIXED_L34_1M: dict(n_attack_ips=1 << 19, n_benign_ips=1 << 19),
}

_PROTO = {"icmp": 1, "tcp": 6, "udp": 17}


class TrafficGen:
    """Streaming generator: ``next_records(n)`` → n records on a
    synthetic clock advancing at ``spec.rate_pps``."""

    def __init__(self, spec: TrafficSpec):
        # Scenario shape supplies defaults; explicit user settings win
        # (only fields still at their dataclass default are shaped).
        defaults = TrafficSpec()
        shape = {
            k: v
            for k, v in _SCENARIO_SHAPE[spec.scenario].items()
            if getattr(spec, k) == getattr(defaults, k)
        }
        shaped = spec.with_(**shape)
        self.spec = shaped
        self.rng = np.random.default_rng(shaped.seed)
        self.now_ns = 1_000_000_000  # synthetic boot-relative clock
        self._dt_ns = max(1, int(1e9 / shaped.rate_pps))
        # pulse-wave arrivals: ALL schedule validation (period/duty
        # ranges, the rounding-honesty refusals) lives in the shared
        # schedule function — one unconditional probe call here, the
        # same eager-validation idiom PacedSource uses, so the rules
        # can never drift between the two generators
        pulse_offsets_ns(np.zeros(1, np.int64), shaped.rate_pps,
                         shaped.burst_period_s, shaped.duty_cycle)
        self._pulse = (shaped.burst_period_s > 0
                       and shaped.duty_cycle < 1.0)
        self._t0_ns = self.now_ns  # pulse offsets anchor
        self._emitted = 0
        # disjoint IP pools: attack = [1, A], benign = [2^24, 2^24+B)
        self._attack_ips = self.rng.integers(
            1, 1 << 24, shaped.n_attack_ips, dtype=np.uint32
        ) if shaped.scenario is not Scenario.ICMP_FLOOD_SINGLE else np.array(
            [0xBADBAD], np.uint32  # single flooder, inside the <2^24 attack pool
        )
        self._benign_ips = (
            self.rng.integers(0, 1 << 24, shaped.n_benign_ips, dtype=np.uint32)
            + np.uint32(1 << 24)
        )

    @property
    def attack_ips(self) -> np.ndarray:
        """Ground-truth attack source pool (stable for a given seed)."""
        return self._attack_ips

    @property
    def benign_ips(self) -> np.ndarray:
        """Ground-truth benign source pool (stable for a given seed)."""
        return self._benign_ips

    # -- feature synthesis (kernel-estimator statistics) --------------------

    def _attack_feat(self, n: int) -> np.ndarray:
        """Flood statistics: fixed small packets, machine-gun IATs,
        short intense flows (kernel-estimator semantics: duration from
        first/last stamps, rate = pkts/duration)."""
        f = np.zeros((n, schema.NUM_FEATURES), np.uint32)
        f[:, schema.Feature.DST_PORT] = self.rng.choice([80, 443, 53], n)
        size = self.rng.integers(60, 80, n)
        f[:, schema.Feature.PKT_LEN_MEAN] = size
        f[:, schema.Feature.PKT_LEN_STD] = self.rng.integers(0, 3, n)
        iat = self.rng.integers(1, 50, n)  # µs: flood-rate arrivals
        npkts = self.rng.integers(100, 5000, n).astype(np.uint64)
        dur_us = np.maximum(iat.astype(np.uint64) * npkts, 1)
        f[:, schema.Feature.FLOW_DUR_MS] = dur_us // 1000
        f[:, schema.Feature.FLOW_PPS_X1000] = np.minimum(
            npkts * np.uint64(1_000_000_000) // dur_us, 0xFFFFFFFF)
        f[:, schema.Feature.FWD_IAT_MEAN] = iat
        f[:, schema.Feature.FWD_IAT_STD] = self.rng.integers(0, 20, n)
        f[:, schema.Feature.FWD_IAT_MAX] = iat * self.rng.integers(1, 4, n)
        return f

    def _benign_feat(self, n: int) -> np.ndarray:
        """Interactive statistics: varied sizes, human-scale IATs,
        short-to-medium flows at interactive rates."""
        f = np.zeros((n, schema.NUM_FEATURES), np.uint32)
        f[:, schema.Feature.DST_PORT] = self.rng.choice(
            [443, 443, 443, 80, 22, 8443], n
        )
        size = self.rng.integers(100, 1500, n)
        std = self.rng.integers(100, 600, n)
        f[:, schema.Feature.PKT_LEN_MEAN] = size
        f[:, schema.Feature.PKT_LEN_STD] = std
        iat = self.rng.integers(5_000, 500_000, n)  # µs: ms-scale arrivals
        npkts = self.rng.integers(2, 200, n).astype(np.uint64)
        dur_us = np.maximum(iat.astype(np.uint64) * npkts, 1)
        f[:, schema.Feature.FLOW_DUR_MS] = dur_us // 1000
        f[:, schema.Feature.FLOW_PPS_X1000] = np.minimum(
            npkts * np.uint64(1_000_000_000) // dur_us, 0xFFFFFFFF)
        f[:, schema.Feature.FWD_IAT_MEAN] = iat
        f[:, schema.Feature.FWD_IAT_STD] = iat // self.rng.integers(1, 4, n)
        f[:, schema.Feature.FWD_IAT_MAX] = iat * self.rng.integers(2, 8, n)
        return f

    # -- record stream ------------------------------------------------------

    def next_records(self, n: int) -> np.ndarray:
        """The next ``n`` packets of the scenario as ring records."""
        spec = self.spec
        buf = np.zeros(n, dtype=schema.FLOW_RECORD_DTYPE)
        is_attack = self.rng.random(n) < spec.attack_fraction

        na = int(is_attack.sum())
        nb = n - na
        feat = np.zeros((n, schema.NUM_FEATURES), np.uint32)
        if na:
            feat[is_attack] = self._attack_feat(na)
            buf["saddr"][is_attack] = self.rng.choice(self._attack_ips, na)
        if nb:
            feat[~is_attack] = self._benign_feat(nb)
            buf["saddr"][~is_attack] = self.rng.choice(self._benign_ips, nb)
        buf["feat"] = feat

        if spec.scenario is Scenario.ICMP_FLOOD_SINGLE:
            proto = np.where(is_attack, _PROTO["icmp"], _PROTO["tcp"])
        elif spec.scenario is Scenario.UDP_FLOOD_MULTI:
            proto = np.where(is_attack, _PROTO["udp"], _PROTO["tcp"])
        elif spec.scenario is Scenario.SYN_BENIGN_MIX:
            proto = np.full(n, _PROTO["tcp"], np.uint8)
            buf["flags"][is_attack] |= schema.FLAG_TCP_SYN | schema.FLAG_TCP
        else:  # mixed L3/L4
            proto = self.rng.choice(list(_PROTO.values()), n)
        buf["ip_proto"] = proto

        buf["pkt_len"] = np.where(
            is_attack,
            self.rng.integers(60, 80, n),
            self.rng.integers(100, 1500, n),
        )
        if self._pulse:
            # pulse-wave synthetic clock: same mean rate, arrivals
            # compressed into each period's on-window (one shared
            # schedule with PacedSource — pulse_offsets_ns docstring)
            offs = pulse_offsets_ns(
                self._emitted + np.arange(n, dtype=np.int64),
                spec.rate_pps, spec.burst_period_s, spec.duty_cycle)
            buf["ts_ns"] = np.uint64(self._t0_ns) + offs.astype(np.uint64)
            self.now_ns = int(buf["ts_ns"][-1]) if n else self.now_ns
        else:
            buf["ts_ns"] = (self.now_ns
                            + np.arange(n, dtype=np.uint64) * self._dt_ns)
            self.now_ns += n * self._dt_ns
        self._emitted += n
        return buf

    def labels_for(self, buf: np.ndarray) -> np.ndarray:
        """Ground truth for a generated buffer (attack pool membership)."""
        return buf["saddr"] < (1 << 24)
