"""Headline benchmark: Mpps classified through the fused TPU pipeline step.

Measures the full user-plane hot path on whatever accelerator the session
exposes (real TPU chip under axon; CPU elsewhere): raw flow records →
one contiguous host→device transfer → fused step (on-device decode →
aggregate → hash-table → limiter → int8 classifier → verdict → state
scatter) → verdict readback.

The reference publishes no throughput numbers (SURVEY.md §6); the target
is BASELINE.json's north star: >=10 Mpps classified, <1 ms p99
feature→verdict, on one chip.  ``vs_baseline`` is the ratio of measured
Mpps to the 10 Mpps target.

Budget discipline (round-1 failure mode: the whole run forfeited on one
900 s subprocess timeout, BENCH_r01.json):

* ``--budget-s`` (default $FSX_BENCH_BUDGET_S or 840) is a HARD wall-
  clock ceiling for the entire run.  The parent slices it across phases
  and always prints its one JSON line before the ceiling.
* each phase child checkpoints every completed measurement to a JSONL
  sidecar file as it lands; if the child stalls or dies, the parent
  kills it at its deadline and recovers the partial results from the
  sidecar.  A stalled tunnel costs the remaining chunks, not the round.
* iteration counts adapt: the child times one probe chunk first, then
  sizes chunks to ~5 s and runs as many as fit in its slice.

Environment honesty — the dev/CI environment reaches the TPU through the
axon tunnel, which has measured pathologies that real (locally attached)
TPU runtimes do not (each auto-detected and engineered around, see
flowsentryx_tpu/ops/fused.py:donation_supported):

* device init alone can take minutes (tunnel warm-up);
* every device→host readback of a computed result costs a fixed ~70 ms
  RPC round trip regardless of payload size — reported as
  ``sync_floor_ms`` so p99 can be read net of the floor;
* the first such readback permanently drops the process's dispatch rate
  ~40×, so each phase below runs in its own subprocess with readbacks
  only at the end;
* buffer donation wedges the client on first readback (compute keeps
  full speed), so the donated steady-state throughput phase is a
  compute-only epoch that reports before exiting.

Because the tunnel's capability swings >50x within a day, the run is
GATED on transport state: a cheap probe subprocess measures H2D
bandwidth and dispatch rate first, and while the link is degraded the
bench sleeps/retries across its budget (keeping a reserve so the final
attempt always happens), labels the run ``link_state``, and records
every probe.  ``artifacts/link_baseline.json`` persists the best
capability ever observed; ``transport_limited`` is judged against that
persisted baseline, never against numbers taken through the same
degraded path.

Usage: ``python bench.py`` prints exactly ONE JSON line on stdout;
progress chatter goes to stderr.  (``--phase=...`` runs a single phase —
used internally via subprocess.)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

TARGET_MPPS = 10.0  # BASELINE.json north_star: >=10 Mpps on one v5e chip
B = 16384  # 2048-record kernel micro-batches, coalesced 8:1 under load
TABLE_CAP = 1 << 20  # BASELINE config 5: 1M concurrent source IPs

if "--smoke" in sys.argv:  # CI-shape run: small and CPU-friendly
    sys.argv.remove("--smoke")
    B = 1024
    TABLE_CAP = 1 << 12


def _argval(name: str, default: float) -> float:
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return float(a.split("=", 1)[1])
    return default


BUDGET_S = _argval("budget-s", float(os.environ.get("FSX_BENCH_BUDGET_S", "840")))
T_START = time.perf_counter()


def remaining() -> float:
    return BUDGET_S - (time.perf_counter() - T_START)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- link-state awareness (VERDICT r3 next #1/#8) ---------------------------
#
# The axon tunnel's capability swings >50x within a day (see
# BENCH_EVIDENCE_r03.json and artifacts/link_monitor_r04.jsonl); a run
# taken in a degraded window measures the tunnel, not the pipeline.  So:
# probe transport FIRST in a throwaway subprocess, and if the link is
# degraded, sleep/retry across the budget instead of burning the run —
# keeping a reserve large enough that the final attempt always happens.
# Every probe is recorded in the output (`link_probes`), and the run is
# labeled `link_state` against fixed criteria, not against itself.
#
# `artifacts/link_baseline.json` persists the best capability ever
# observed; `transport_limited` compares the measured e2e rate against
# that persisted healthy baseline (a tunnel whose entire dispatch path
# degrades uniformly must NOT read as "not transport limited").

from pathlib import Path

from flowsentryx_tpu.core import linkhealth  # light: no accelerator import

HEALTHY_H2D_MBPS = linkhealth.HEALTHY_H2D_MBPS
LINK_BASELINE_PATH = Path(__file__).parent / "artifacts" / "link_baseline.json"
PROBE_SCRIPT = Path(__file__).parent / "scripts" / "link_probe.py"


def _load_link_baseline() -> dict:
    try:
        return json.loads(LINK_BASELINE_PATH.read_text())
    except (OSError, json.JSONDecodeError):
        return {}


def _update_link_baseline(**obs) -> dict:
    """Fold run observations into the persisted best-ever capability.
    Higher is better except dispatch_ms_best."""
    bl = _load_link_baseline()
    changed = False
    for k, v in obs.items():
        if v is None:
            continue
        best = bl.get(k)
        better = (best is None or v < best) if k == "dispatch_ms_best" \
            else (best is None or v > best)
        if better:
            bl[k] = v
            changed = True
    if changed:
        bl["updated"] = time.strftime("%Y-%m-%d %H:%M:%S")
        try:
            LINK_BASELINE_PATH.parent.mkdir(parents=True, exist_ok=True)
            LINK_BASELINE_PATH.write_text(json.dumps(bl, indent=2) + "\n")
        except OSError as e:  # read-only checkout: keep the run alive
            log(f"link baseline not persisted: {e}")
    return bl


def _probe_link(timeout_s: float = 120.0) -> dict:
    """Run scripts/link_probe.py in a throwaway subprocess (the first
    D2H readback permanently degrades a process's dispatch rate on the
    tunnel, so probes must never share a process with a phase)."""
    try:
        r = subprocess.run(
            [sys.executable, str(PROBE_SCRIPT)],
            capture_output=True, timeout=timeout_s,
        )
        lines = r.stdout.decode(errors="replace").strip().splitlines()
        return json.loads(lines[-1]) if lines else {"error": "no output"}
    except subprocess.TimeoutExpired:
        return {"error": f"probe timeout after {timeout_s:.0f}s"}
    except (OSError, json.JSONDecodeError, IndexError) as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _probe_state(p: dict) -> str:
    # The probe self-labels: it compiles and times the REAL fused step
    # (trivial-dispatch health provably diverges from step-dispatch
    # health on this tunnel — see scripts/link_probe.py).
    if p.get("state"):
        return p["state"]
    # No self-label: never infer health from trivial-dispatch numbers
    # (they provably diverge ~100x from real fused-step health on this
    # tunnel) — a probe that failed to classify itself is not evidence
    # of a healthy window.
    return "wedged" if p.get("error") else "degraded"


class Sidecar:
    """Append-only JSONL checkpoint stream the parent can recover from."""

    def __init__(self, path: str | None):
        self.f = open(path, "a", buffering=1) if path else None

    def emit(self, kind: str, **kv) -> None:
        if self.f:
            self.f.write(json.dumps({"kind": kind, **kv}) + "\n")
            self.f.flush()


def make_raw_batches(n_batches: int, batch: int, n_ips: int, seed: int = 0):
    """Synthetic flood traffic, pre-packed to the device wire format
    (BASELINE config 4/5 shape: mixed traffic, many concurrent IPs)."""
    from flowsentryx_tpu.core import schema

    rng = np.random.default_rng(seed)
    bufs = []
    for i in range(n_batches):
        buf = np.zeros(batch, dtype=schema.FLOW_RECORD_DTYPE)
        buf["saddr"] = rng.integers(1, n_ips + 1, batch).astype(np.uint32)
        buf["pkt_len"] = rng.integers(64, 1500, batch)
        buf["ts_ns"] = (i * batch + np.arange(batch)) * 100  # 10 Mpps spacing
        buf["ip_proto"] = rng.choice([1, 6, 17], batch)  # ICMP/TCP/UDP mix
        buf["feat"] = rng.integers(0, 1 << 20, (batch, schema.NUM_FEATURES))
        bufs.append(buf)
    return bufs


def _device_init(side: Sidecar):
    """Breadcrumbed device init shared by every phase child.

    Breadcrumbs BEFORE and DURING device init (round-2 failure: the
    axon tunnel can wedge inside jax.devices() for many minutes; with
    no pre-init sidecar record the parent couldn't tell a wedged init
    from a wedged measurement).  The parent watches for the "device"
    record and kills + retries / falls back to CPU if it doesn't land
    within the init deadline — this protocol must stay identical across
    phases, hence one copy."""
    side.emit("init", stage="import_jax",
              at_s=round(time.perf_counter() - T_START, 1))
    import jax

    # The session's sitecustomize force-registers the axon TPU platform
    # and overrides JAX_PLATFORMS from the environment; honor an explicit
    # cpu request (CI smoke + fallback runs) via the config API, which
    # still wins.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    side.emit("init", stage="devices_call",
              at_s=round(time.perf_counter() - T_START, 1))
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    init_s = round(time.perf_counter() - t0, 1)
    side.emit("device", backend=dev.platform, device_kind=dev.device_kind,
              init_s=init_s)
    log(f"device: {dev.platform}/{dev.device_kind} (init {init_s:.1f}s)")
    return jax, dev, init_s


def _setup(donate: bool, side: Sidecar):
    jax, dev, init_s = _device_init(side)

    from flowsentryx_tpu.core import schema
    from flowsentryx_tpu.core.config import BatchConfig, FsxConfig, TableConfig
    from flowsentryx_tpu.models import get_model
    from flowsentryx_tpu.ops import fused

    cfg = FsxConfig(
        table=TableConfig(capacity=TABLE_CAP), batch=BatchConfig(max_batch=B)
    )
    spec = get_model(cfg.model.name)
    params = spec.init()
    # Production hot path: the COMPACT 16 B/record wire format in
    # bit-exact "model" quantization (core/schema.py) — 3× fewer
    # host→device bytes than the 48 B ring record, which is the
    # bandwidth-critical hop at 10 Mpps (480 → 160 MB/s).
    quant = schema.model_quant_args(params)
    step = fused.make_jitted_compact_step(
        cfg, spec.classify_batch, donate=donate, **quant
    )
    table = jax.device_put(schema.make_table(cfg.table.capacity))
    stats = jax.device_put(schema.make_stats())
    raws = [
        schema.encode_compact(b, B, t0_ns=0, **quant)
        for b in make_raw_batches(16, B, n_ips=1 << 20)
    ]
    return jax, schema, cfg, params, step, table, stats, raws, init_s


def phase_throughput(side: Sidecar, deadline_rel: float) -> dict:
    """Donated steady-state loop; compute-only (see module docstring).

    Adaptive: sizes chunks to ~5 s from a timed probe chunk, then runs
    as many as fit before the deadline; every chunk checkpoints to the
    sidecar so a mid-phase stall still leaves a measurable median."""
    deadline = time.perf_counter() + deadline_rel
    jax, schema, cfg, params, step, table, stats, raws, init_s = _setup(True, side)
    dev = jax.devices()[0]

    t0 = time.perf_counter()
    table, stats, out = step(table, stats, params, raws[0])
    jax.block_until_ready(out.verdict)
    compile_s = time.perf_counter() - t0
    side.emit("compile", compile_s=round(compile_s, 1))
    log(f"compile: {compile_s:.1f}s")

    result = {
        "mpps": 0.0, "chunk_mpps": [], "iters": 0,
        "compile_s": compile_s, "backend": dev.platform,
        "device_kind": dev.device_kind, "init_s": init_s,
    }

    # Transport + device capability diagnostics FIRST, before the e2e
    # chunks below consume the link's burst budget: the dev tunnel
    # meters H2D in tiers (measured: ~150 MB burst at 1.3-1.6 GB/s,
    # then ~250 MB/s, then ~25 MB/s with dispatch penalties; idle
    # restores it), so diagnostics taken after 500 MB of chunks would
    # describe the drained tunnel, not the chip.
    #   device_mpps — device-resident step rate, no H2D in the loop:
    #   the chip's actual feature→verdict capability (what a local-PCIe
    #   deployment sees; production never binds on 16 B/record wire).
    if remaining() > 30 and time.perf_counter() + 20 < deadline:
        big = np.concatenate([np.ascontiguousarray(r).reshape(-1)
                              for r in raws])
        jax.block_until_ready(jax.device_put(big[:1024]))  # warm path
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(big))
        result["h2d_mbps"] = round(big.nbytes / (time.perf_counter() - t0)
                                   / 1e6, 1)

        dev_feeds = [jax.device_put(r) for r in raws]
        jax.block_until_ready(dev_feeds)
        iters = 200
        t0 = time.perf_counter()
        for i in range(iters):
            table, stats, out = step(table, stats, params,
                                     dev_feeds[i % len(dev_feeds)])
        jax.block_until_ready(out.verdict)
        dt = (time.perf_counter() - t0) / iters
        result["device_mpps"] = round(B / dt / 1e6, 2)
        del dev_feeds
        side.emit("transport", h2d_mbps=result["h2d_mbps"],
                  device_mpps=result["device_mpps"])
        log(f"device-resident: {result['device_mpps']:.1f} Mpps, "
            f"link {result['h2d_mbps']:.0f} MB/s")

    # Explicit H2D prefetch: device_put is async, so enqueueing the
    # next wire buffers keeps the transfer engine ahead of the compute
    # stream (the step consumes buffers whose transfer already started).
    # Depth 3 bounds host memory pinned in flight.
    PREFETCH = 3

    def feed(k: int):
        return jax.device_put(raws[k % len(raws)])

    # Probe chunk: small, times a single dispatch round trip.  The
    # pre-staged transfers complete before the clock starts so they
    # can't inflate the probe.
    probe_iters = 10 if dev.platform != "cpu" else 3
    k = 0
    pre = [feed(i) for i in range(PREFETCH)]
    jax.block_until_ready(pre)
    t0 = time.perf_counter()
    for _ in range(probe_iters):
        pre.append(feed(k + PREFETCH))
        table, stats, out = step(table, stats, params, pre.pop(0))
        k += 1
    jax.block_until_ready(out.verdict)
    dt = time.perf_counter() - t0
    probe_mpps = probe_iters * B / dt / 1e6
    per_iter = dt / probe_iters
    result["chunk_mpps"].append(round(probe_mpps, 2))
    result["iters"] += probe_iters
    side.emit("chunk", mpps=round(probe_mpps, 2), iters=probe_iters)
    log(f"probe chunk: {probe_mpps:.2f} Mpps ({per_iter * 1e3:.1f} ms/iter)")

    # Size real chunks to ~5 s each, capped; run while time permits,
    # keeping a reserve for the final block_until_ready + JSON write.
    chunk_iters = max(5, min(200, int(5.0 / max(per_iter, 1e-6))))
    reserve = max(5.0, 4 * per_iter * chunk_iters)
    max_chunks = 10
    while len(result["chunk_mpps"]) < max_chunks + 1:
        if time.perf_counter() + chunk_iters * per_iter * 2 + reserve > deadline:
            break
        t0 = time.perf_counter()
        for _ in range(chunk_iters):
            pre.append(feed(k + PREFETCH))
            table, stats, out = step(table, stats, params, pre.pop(0))
            k += 1
        jax.block_until_ready(out.verdict)
        dt = time.perf_counter() - t0
        mpps = chunk_iters * B / dt / 1e6
        per_iter = 0.5 * per_iter + 0.5 * dt / chunk_iters  # smooth estimate
        result["chunk_mpps"].append(round(mpps, 2))
        result["iters"] += chunk_iters
        side.emit("chunk", mpps=round(mpps, 2), iters=chunk_iters)
        log(f"chunk: {mpps:.2f} Mpps ({chunk_iters} iters)")

    # -- mega-dispatch chunks: N batches per jit call (lax.scan over a
    # stacked wire group) — one dispatch round trip per N batches, so
    # per-dispatch overhead (the tunnel's RPC floor above all) is paid
    # once per group.  Same records, same state chain; whichever mode
    # sustains more is the honest headline (mode recorded).  A deeper
    # N=32 tier runs after N=8 when time and its win justify it: on a
    # dispatch-floor-bound transport each 4x depth amortizes 4x more.
    MEGA_N = 8

    def run_mega_tier(n_mega: int, max_groups: int) -> list:
        from flowsentryx_tpu.models import get_model
        from flowsentryx_tpu.ops import fused as _fused

        nonlocal table, stats
        spec = get_model(cfg.model.name)
        quant_m = schema.model_quant_args(params)
        mega = _fused.make_jitted_compact_megastep(
            cfg, spec.classify_batch, n_chunks=n_mega, donate=True,
            **quant_m)
        # groups staged in a page-aligned dispatch arena, exactly like
        # the serving engine's zero-copy pipeline: the timed device_put
        # below reads DMA-able memory, not an ad-hoc np.stack
        # allocation (jax-free import: engine/arena.py is numpy+mmap)
        from flowsentryx_tpu.engine.arena import DispatchArena

        arena = DispatchArena(slots=4, group_max=n_mega,
                              max_batch=cfg.batch.max_batch,
                              words=schema.COMPACT_RECORD_WORDS)
        stacked = []
        for g in range(4):
            rows = arena.rows(arena.claim())
            for i in range(n_mega):
                rows[i][...] = raws[(g * n_mega + i) % len(raws)]
            stacked.append(rows[:n_mega])
        t0 = time.perf_counter()
        table, stats, outs = mega(table, stats, params,
                                  jax.device_put(stacked[0]))
        jax.block_until_ready(outs.verdict)
        side.emit("mega_compile", n=n_mega,
                  s=round(time.perf_counter() - t0, 1))
        chunks: list = []
        gk = 0
        mpre = [jax.device_put(stacked[i % len(stacked)]) for i in range(2)]
        jax.block_until_ready(mpre)
        giters = max(2, min(25, int(5.0 / max(per_iter * n_mega, 1e-6))))
        while len(chunks) < max_groups:
            if time.perf_counter() + giters * per_iter * n_mega * 2 \
                    + reserve > deadline:
                break
            t0 = time.perf_counter()
            for _ in range(giters):
                mpre.append(jax.device_put(stacked[(gk + 2) % len(stacked)]))
                table, stats, outs = mega(table, stats, params, mpre.pop(0))
                gk += 1
            jax.block_until_ready(outs.verdict)
            dt = time.perf_counter() - t0
            mpps = giters * n_mega * B / dt / 1e6
            chunks.append(round(mpps, 2))
            side.emit("mega_chunk", n=n_mega, mpps=round(mpps, 2),
                      iters=giters)
            log(f"mega chunk (N={n_mega}): {mpps:.2f} Mpps")
        return chunks

    def run_devloop_tier(ring: int, n_mega: int, max_rounds: int) -> list:
        """Drain-ring chunks: ``ring`` arena slots of ``n_mega``
        batches per deep-scan dispatch (fused/device_loop.py) — the
        device consumes a whole staging ring per host round-trip, so
        the per-dispatch fixed cost is paid once per ``ring * n_mega``
        batches and the next round's slots upload while the current
        computes."""
        from flowsentryx_tpu.engine.arena import DispatchArena
        from flowsentryx_tpu.fused import device_loop as _dl
        from flowsentryx_tpu.models import get_model

        nonlocal table, stats
        spec = get_model(cfg.model.name)
        quant_m = schema.model_quant_args(params)
        loop = _dl.make_compact_device_loop(
            cfg, spec.classify_batch, ring, n_mega, donate=True,
            **quant_m)
        arena = DispatchArena(slots=2 * ring + 2, group_max=n_mega,
                              max_batch=cfg.batch.max_batch,
                              words=schema.COMPACT_RECORD_WORDS)

        def stage_round(r0: int) -> list:
            slots = []
            for r in range(ring):
                rows = arena.rows(arena.claim())
                for i in range(n_mega):
                    rows[i][...] = raws[(r0 + r * n_mega + i) % len(raws)]
                slots.append(jax.device_put(rows[:n_mega]))
            return slots

        t0 = time.perf_counter()
        table, stats, outs = loop(table, stats, params, *stage_round(0))
        jax.block_until_ready(outs.wire)
        side.emit("devloop_compile", ring=ring, n=n_mega,
                  s=round(time.perf_counter() - t0, 1))
        per_round = ring * n_mega
        chunks: list = []
        rk = 0
        riters = max(2, min(12, int(5.0 / max(per_iter * per_round,
                                              1e-6))))
        while len(chunks) < max_rounds:
            if time.perf_counter() + riters * per_iter * per_round * 2 \
                    + reserve > deadline:
                break
            t0 = time.perf_counter()
            for _ in range(riters):
                table, stats, outs = loop(table, stats, params,
                                          *stage_round(rk * per_round))
                rk += 1
            jax.block_until_ready(outs.wire)
            dt = time.perf_counter() - t0
            mpps = riters * per_round * B / dt / 1e6
            chunks.append(round(mpps, 2))
            side.emit("devloop_chunk", ring=ring, n=n_mega,
                      mpps=round(mpps, 2), iters=riters)
            log(f"devloop chunk ({ring}x{n_mega}): {mpps:.2f} Mpps")
        return chunks

    def _finalize(res: dict) -> None:
        """Fold chunk series into the headline fields.  mega_chunk_mpps
        is ALWAYS the N=8 series, mega32_chunk_mpps always N=32, and
        devloop_chunk_mpps always the 2x8 drain ring — keys never
        change meaning across rounds; dispatch_mode records which mode
        won the headline."""
        steady_ = res["chunk_mpps"][1:] or res["chunk_mpps"]
        res["single_mpps"] = float(np.median(steady_))
        res["mpps"] = res["single_mpps"]
        res["burst_mpps"] = float(np.max(steady_))
        res.pop("dispatch_mode", None)
        res.pop("mega_mpps", None)
        for key, label in (("mega_chunk_mpps", "mega8"),
                           ("mega32_chunk_mpps", "mega32"),
                           ("devloop_chunk_mpps", "devloop2x8")):
            chunks_ = res.get(key) or []
            if not chunks_:
                continue
            med = float(np.median(chunks_))
            if med > res["mpps"]:
                res["mpps"] = med
                res["mega_mpps"] = med
                res["dispatch_mode"] = label
            res["burst_mpps"] = max(res["burst_mpps"],
                                    float(np.max(chunks_)))
        res.setdefault("dispatch_mode", "single")

    if time.perf_counter() + 30 < deadline:
        result["mega_chunk_mpps"] = run_mega_tier(MEGA_N, 6)
        m8 = result["mega_chunk_mpps"]
        if (m8 and float(np.median(m8)) > 1.2 * float(np.median(
                result["chunk_mpps"][1:] or result["chunk_mpps"]))
                and time.perf_counter() + 40 < deadline):
            # Dispatch overhead is a real binder here — try 4x deeper.
            # The 32-deep scan's COMPILE is unbounded on a cache miss,
            # so snapshot a complete result first: if the child dies
            # inside the tier, sidecar recovery returns this snapshot
            # instead of downgrading the whole phase to partial.
            _finalize(result)
            side.emit("result", **result)
            m32 = run_mega_tier(32, 4)
            if m32:
                result["mega32_chunk_mpps"] = m32
        if m8 and time.perf_counter() + 40 < deadline:
            # the drain ring rides the same amortization curve one
            # level up: snapshot first (unbounded compile, same
            # sidecar-recovery discipline as the 32-deep scan)
            _finalize(result)
            side.emit("result", **result)
            dl8 = run_devloop_tier(2, MEGA_N, 4)
            if dl8:
                result["devloop_chunk_mpps"] = dl8

    # Median over steady-state chunks (exclude the probe when real
    # chunks exist: the probe is tiny and noisy).  The max chunk is
    # reported separately as burst_mpps: under the tunnel's tiered
    # throttle the first chunks run from burst credit at link speed,
    # later ones at the metered sustained rate — the median is the
    # honest sustained number, the max shows the burst regime a
    # local-PCIe deployment would sustain continuously.
    # single_mpps stays the cross-round comparable series: the link
    # baseline and the transport_limited judgment key on it (folding
    # mega numbers into those would let an amortized-dispatch win mask
    # a genuinely collapsed transport).  The HEADLINE may be a mega
    # median — it is a real serving mode — labeled by dispatch_mode.
    _finalize(result)
    # transport_limited is judged by the PARENT against the persisted
    # healthy baseline — a same-run flag here would re-introduce the r3
    # defect (a uniformly degraded tunnel reading as "not limited").
    side.emit("result", **result)
    return result


def phase_latency(side: Sidecar, deadline_rel: float) -> dict:
    """The latency mode (VERDICT r3 next #2): decompose the <1 ms
    feature→verdict budget AND measure real per-record latency under
    deadline-triggered small batches at fixed offered loads.

    Four sub-measurements, ordered so the dispatch-degrading first D2H
    readback (module docstring) happens only after the compute timings:

    1. ``step_ms[B]`` — isolated on-device step time per batch size,
       device-resident feeds, amortized over a dispatch chain with one
       ``block_until_ready`` at the end (which does NOT trigger the
       tunnel's readback degradation; ``np.asarray`` does).
    2. ``micro`` — host fill (encode_compact) and one-wire-buffer H2D
       time for the decomposition batch.
    3. ``sync_floor_ms`` — the tunnel's fixed RPC round-trip cost,
       measured on a 32-byte readback; everything after this line runs
       in the degraded-dispatch regime, which is why it comes late.
    4. ``paced`` — per-record arrival→verdict-sunk latency through the
       REAL engine (open-loop PacedSource at fixed offered loads,
       readback_depth 0-1, 200 µs deadline batches): p99 = f(batch,
       depth, load), queueing included.
    """
    deadline = time.perf_counter() + deadline_rel
    side.emit("init", stage="import_jax",
              at_s=round(time.perf_counter() - T_START, 1))
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from flowsentryx_tpu.core import schema
    from flowsentryx_tpu.core.config import BatchConfig, FsxConfig, TableConfig
    from flowsentryx_tpu.models import get_model
    from flowsentryx_tpu.ops import fused

    side.emit("init", stage="devices_call",
              at_s=round(time.perf_counter() - T_START, 1))
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    init_s = round(time.perf_counter() - t0, 1)
    side.emit("device", backend=dev.platform, device_kind=dev.device_kind,
              init_s=init_s)
    log(f"device: {dev.platform}/{dev.device_kind} (init {init_s:.1f}s)")

    small = B == 1024 or dev.platform == "cpu"  # --smoke / CPU fallback
    sizes = [256, 1024] if small else [1024, 2048, 16384]
    decomp_b = 1024 if small else 2048

    spec = get_model("logreg_int8")
    params = spec.init()
    quant = schema.model_quant_args(params)
    result: dict = {
        "backend": dev.platform, "device_kind": dev.device_kind,
        "init_s": init_s, "step_ms": {}, "paced": [],
    }

    # -- 1. isolated on-device step time per batch size --------------------
    for size in sizes:
        if time.perf_counter() + 25 > deadline:
            break
        cfg = FsxConfig(table=TableConfig(capacity=TABLE_CAP),
                        batch=BatchConfig(max_batch=size))
        step = fused.make_jitted_compact_step(
            cfg, spec.classify_batch, donate=None, **quant
        )  # donate=None: auto — off only on axon, where a donated
        # step's first readback wedges the client; everywhere else an
        # undonated 1M-row table pays a ~50 MB copy per step, which
        # would be the latency phase measuring its own harness
        table = jax.device_put(schema.make_table(TABLE_CAP))
        stats = jax.device_put(schema.make_stats())
        feeds = [
            jax.device_put(schema.encode_compact(b, size, t0_ns=0, **quant))
            for b in make_raw_batches(4, size, n_ips=1 << 14)
        ]
        jax.block_until_ready(feeds)
        t0 = time.perf_counter()
        table, stats, out = step(table, stats, params, feeds[0])
        jax.block_until_ready(out.verdict)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(5):
            table, stats, out = step(table, stats, params, feeds[i % 4])
        jax.block_until_ready(out.verdict)
        per = (time.perf_counter() - t0) / 5
        iters = max(20, min(1000, int(3.0 / max(per, 1e-6))))
        t0 = time.perf_counter()
        for i in range(iters):
            table, stats, out = step(table, stats, params, feeds[i % 4])
        jax.block_until_ready(out.verdict)
        ms = (time.perf_counter() - t0) / iters * 1e3
        result["step_ms"][str(size)] = round(ms, 4)
        side.emit("steptime", batch=size, step_ms=round(ms, 4), iters=iters,
                  compile_s=round(compile_s, 1))
        log(f"steptime B={size}: {ms:.3f} ms/step ({iters} iters, "
            f"compile {compile_s:.1f}s)")

    # -- 2. host fill + single-buffer H2D for the decomposition batch ------
    raw = make_raw_batches(1, decomp_b, n_ips=1 << 14)[0]
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        wire = schema.encode_compact(raw, decomp_b, t0_ns=0, **quant)
    fill_ms = (time.perf_counter() - t0) / reps * 1e3
    jax.block_until_ready(jax.device_put(wire))  # warm the transfer path
    h2d = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(wire))
        h2d.append(time.perf_counter() - t0)
    result["micro"] = {
        "batch": decomp_b,
        "fill_ms": round(fill_ms, 4),
        "h2d_ms": round(float(np.median(h2d)) * 1e3, 4),
        "wire_bytes": int(wire.nbytes),
    }
    side.emit("micro", **result["micro"])
    log(f"micro B={decomp_b}: fill {fill_ms:.3f} ms, "
        f"h2d {result['micro']['h2d_ms']:.3f} ms ({wire.nbytes} B)")

    # -- 3. tunnel RPC floor (degrades this process's dispatch from here) --
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jax.device_put(jnp.zeros((8,), jnp.float32))
    np.asarray(f(x))
    floors = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(f(x))
        floors.append(time.perf_counter() - t0)
    sync_floor_ms = float(np.median(floors) * 1e3)
    result["sync_floor_ms"] = round(sync_floor_ms, 2)
    side.emit("sync_floor", sync_floor_ms=round(sync_floor_ms, 1))
    log(f"sync floor: {sync_floor_ms:.1f} ms")

    # verdict D2H for the decomposition batch (includes the floor once):
    # the steady-state readback is the COMPACT verdict wire — one
    # [2K+4]-word buffer per batch; the full-array fetch is also timed
    # as the overflow-fallback cost.
    cfg = FsxConfig(table=TableConfig(capacity=TABLE_CAP),
                    batch=BatchConfig(max_batch=decomp_b))
    step = fused.make_jitted_compact_step(
        cfg, spec.classify_batch, donate=None, **quant
    )
    table = jax.device_put(schema.make_table(TABLE_CAP))
    stats = jax.device_put(schema.make_stats())
    feed = jax.device_put(wire)
    table, stats, out = step(table, stats, params, feed)
    np.asarray(out.wire)
    d2h, d2h_full = [], []
    for _ in range(reps):
        table, stats, out = step(table, stats, params, feed)
        jax.block_until_ready(out.wire)
        t0 = time.perf_counter()
        np.asarray(out.wire)
        d2h.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(out.block_key)
        np.asarray(out.block_until)
        d2h_full.append(time.perf_counter() - t0)
    result["micro"]["d2h_ms"] = round(float(np.median(d2h)) * 1e3, 4)
    result["micro"]["d2h_wire_bytes"] = int(
        fused.verdict_wire_words(cfg.batch.verdict_k) * 4)
    result["micro"]["d2h_fallback_ms"] = round(
        float(np.median(d2h_full)) * 1e3, 4)
    side.emit("micro", **result["micro"])

    # -- 4. paced per-record latency through the real engine ---------------
    from flowsentryx_tpu.engine import Engine, NullSink, PacedSource

    pool = make_raw_batches(1, 1 << 14, n_ips=1 << 13)[0]
    if small:
        loads = [0.02, 0.05]
        grid = [(sizes[0], 0), (sizes[0], 1)]
    else:
        loads = [0.25, 1.0, 5.0, 10.0]
        grid = [(1024, 0), (2048, 0), (2048, 1)]
    engines: dict = {}

    def run_paced(bsz: int, depth: int, load: float,
                  auto: bool = False) -> dict | None:
        rate = load * 1e6
        total = int(max(min(rate * 2.0, 2e6), 1))
        eng = engines.get(bsz)
        src = PacedSource(pool, rate_pps=rate, total=total)
        if eng is None:
            cfg = FsxConfig(
                table=TableConfig(capacity=TABLE_CAP),
                batch=BatchConfig(max_batch=bsz, deadline_us=200),
            )
            eng = Engine(cfg, src, NullSink(), params=params,
                         donate=None, readback_depth=depth,
                         wire=schema.WIRE_COMPACT16)
            engines[bsz] = eng
            # Compile OUTSIDE the paced run: the open-loop clock
            # starts at the first poll, so seconds of XLA compile
            # inside the run would read as seconds of queueing.
            warm = schema.encode_compact(pool[:bsz], bsz, t0_ns=0, **quant)
            eng.table, eng.stats, wout = eng.step(
                eng.table, eng.stats, eng.params, warm)
            jax.block_until_ready(wout.verdict)
            # Zero the counters the warmup batch just bumped, so the
            # summed drop-attribution block reconciles exactly against
            # the paced runs' record counts.
            eng.stats = jax.device_put(schema.make_stats())
        from flowsentryx_tpu.benchmarks import (
            paced_latency_run, summarize_latencies,
        )

        lats, wall, erep = paced_latency_run(eng, src, readback_depth=depth)
        if not len(lats):
            return None
        rec = {
            "batch": bsz, "depth": depth, "load_mpps": load,
            **summarize_latencies(lats),
            "achieved_mpps": round(len(lats) / wall / 1e6, 4),
            # the engine's own in-band seal->verdict measurement (HDR
            # plane, ISSUE 11) — cross-checks the hook-measured
            # percentiles above
            "engine_latency": erep.latency,
            # consumed == reaped (lats), not merely released by the
            # source: a run stopped by the wall cap can leave a batcher
            # residue that was offered but never classified.
            "offered_all_consumed": bool(len(lats) >= total),
            # verdict-readback accounting: D2H bytes per sunk batch,
            # compact vs K_MAX-overflow-fallback sink counts, and the
            # sink thread's busy fraction of the run wall
            "readback": erep.readback,
        }
        if auto:
            rec["auto_load"] = True
        result["paced"].append(rec)
        side.emit("paced", **rec)
        log(f"paced B={bsz} d={depth} {load}Mpps"
            + (" (auto)" if auto else "") +
            f": p50={rec['p50_ms']:.1f} p99={rec['p99_ms']:.1f} "
            f"({rec['n']} recs, achieved {rec['achieved_mpps']:.2f}Mpps)")
        return rec

    for bsz, depth in grid:
        for load in loads:
            if time.perf_counter() + 20 > deadline:
                log("paced grid: deadline reached; stopping early")
                break
            run_paced(bsz, depth, load)
        else:
            continue
        break

    # Auto tier: when none of a config's fixed loads were sustainable
    # (this transport drains slower than the lowest offered load —
    # every p99 above measured backlog, not latency), add one run at
    # 0.5x the config's measured drain rate: the queueing-free
    # operating point, so the grid always contains a latency number
    # that means latency.
    drain: dict = {}
    for r in result["paced"]:
        key = (r["batch"], r["depth"])
        drain[key] = max(drain.get(key, 0.0), r["achieved_mpps"])
    for (bsz, depth), a in sorted(drain.items()):
        sustained = [r for r in result["paced"]
                     if (r["batch"], r["depth"]) == (bsz, depth)
                     and r["achieved_mpps"] >= 0.8 * r["load_mpps"]]
        if sustained or a <= 0:
            continue
        if time.perf_counter() + 20 > deadline:
            break
        run_paced(bsz, depth, max(round(0.5 * a, 4), 1e-4), auto=True)

    # -- 5. pulse-wave SLO tier (ISSUE 11): the adversarial load the
    # latency-budget mode exists for.  One pulse stream (mean rate
    # modest, bursts at 1/duty x the mean, period a few batcher
    # deadlines) served twice through mega-auto engines — throughput-
    # tuned (--slo-us 0) vs budget-bounded — reporting the per-record
    # percentiles AND the engine's own latency block for both.  The
    # same-build A/B of artifacts/LATENCY_r15.json's paced half.
    from flowsentryx_tpu.benchmarks import (
        paced_latency_run, summarize_latencies,
    )

    result["pulse"] = []
    pulse_rate = (0.02 if small else 0.25) * 1e6
    pulse_kw = dict(burst_period_s=0.008, duty_cycle=0.25)
    pulse_b = sizes[0]
    slo_us = 4000 if small else 2000
    for slo in (0, slo_us):
        if time.perf_counter() + 30 > deadline:
            log("pulse tier: deadline reached; skipping")
            break
        cfg = FsxConfig(
            table=TableConfig(capacity=TABLE_CAP),
            batch=BatchConfig(max_batch=pulse_b, deadline_us=200),
        )
        total = int(max(min(pulse_rate * 2.0, 2e6), 1))
        src = PacedSource(pool, rate_pps=pulse_rate, total=total,
                          **pulse_kw)
        eng = Engine(cfg, src, NullSink(), params=params, donate=None,
                     readback_depth=2, wire=schema.WIRE_COMPACT16,
                     mega_n="auto", slo_us=slo)
        eng.warm()  # compiles every rung; seeds the SLO EWMA table
        eng.stats = jax.device_put(schema.make_stats())
        lats, wall, erep = paced_latency_run(eng, src, readback_depth=2)
        if not len(lats):
            # the grid path's guard, mirrored: a throttle-stalled run
            # that reaped nothing is a void trial, not a percentile row
            log(f"pulse slo={slo}us: no records reaped (trial void)")
            continue
        rec = {
            "slo_us": slo, "batch": pulse_b,
            "load_mpps": round(pulse_rate / 1e6, 3), **pulse_kw,
            **summarize_latencies(lats),
            "achieved_mpps": round(len(lats) / max(wall, 1e-9) / 1e6, 4),
            "engine_latency": erep.latency,
            "dispatch_slo": erep.dispatch.get("slo"),
            "group_hist": erep.dispatch["group_hist"],
        }
        result["pulse"].append(rec)
        side.emit("pulse", **rec)
        log(f"pulse slo={slo}us: p50={rec.get('p50_ms')} "
            f"p99={rec.get('p99_ms')} ({rec.get('n', 0)} recs)")

    # Cumulative verdict stats across the paced engine runs (the
    # drop-attribution block prior rounds' evidence files carry).
    if engines:
        # Sum across ALL batch-size engines — with a two-batch grid a
        # single engine's counters silently omit the other's verdicts.
        totals: dict = {}
        for eng in engines.values():
            for k, v in schema.GlobalStats(
                    *(np.asarray(s) for s in eng.stats)).to_dict().items():
                totals[k] = totals.get(k, 0) + v
        result["stats"] = totals

    side.emit("result", **result)
    return result


def _recover_sidecar(path: str) -> dict | None:
    """Rebuild the best partial result from a dead child's sidecar.

    Per-line parsing: a child SIGKILLed mid-write leaves one truncated
    final line, which must not void the valid checkpoints before it."""
    lines = []
    try:
        for l in open(path):
            try:
                lines.append(json.loads(l))
            except json.JSONDecodeError:
                continue
    except OSError:
        return None
    if not lines:
        return None
    out: dict = {"partial": True}
    chunks = []
    mega_tiers: dict[int, list] = {}
    devloop_chunks: list = []
    last_result = None
    for rec in lines:
        kind = rec.pop("kind")
        if kind == "result":
            # keep scanning: a phase may snapshot a complete result
            # before an optional extra tier — the LAST one wins
            rec.pop("partial", None)
            last_result = rec
        elif kind == "chunk":
            chunks.append(rec["mpps"])
        elif kind == "mega_chunk":
            mega_tiers.setdefault(int(rec.get("n", 8)), []).append(
                rec["mpps"])
        elif kind == "devloop_chunk":
            devloop_chunks.append(rec["mpps"])
        elif kind == "init":
            # Post-mortem trail: which init stage the child reached
            # (import_jax vs devices_call) and when.
            out.setdefault("init_stages", []).append(rec)
        elif kind == "steptime":
            out.setdefault("step_ms", {})[str(rec["batch"])] = rec["step_ms"]
        elif kind == "micro":
            out["micro"] = rec
        elif kind == "paced":
            out.setdefault("paced", []).append(rec)
        elif kind == "pulse":
            out.setdefault("pulse", []).append(rec)
        elif kind in ("device", "compile", "sync_floor", "lat_partial"):
            out.update(rec)
    if last_result is not None:
        return {**last_result, "partial": False}
    if chunks:
        steady = chunks[1:] or chunks
        out["chunk_mpps"] = chunks
        out["single_mpps"] = float(np.median(steady))
        out["mpps"] = out["single_mpps"]
    for n, series in sorted(mega_tiers.items()):
        key = "mega_chunk_mpps" if n == 8 else f"mega{n}_chunk_mpps"
        out[key] = series
        med = float(np.median(series))
        if med > out.get("mpps", 0.0):
            out["mpps"] = med
            out["mega_mpps"] = med
            out["dispatch_mode"] = f"mega{n}"
    if devloop_chunks:
        out["devloop_chunk_mpps"] = devloop_chunks
        med = float(np.median(devloop_chunks))
        if med > out.get("mpps", 0.0):
            out["mpps"] = med
            out["mega_mpps"] = med
            out["dispatch_mode"] = "devloop2x8"
    return out


def _sidecar_has(path: str, kind: str) -> bool:
    try:
        with open(path) as f:
            for l in f:
                try:
                    if json.loads(l).get("kind") == kind:
                        return True
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return False


def _run_phase(phase: str, deadline_rel: float, *,
               force_cpu: bool = False,
               init_deadline: float | None = None) -> dict | None:
    """Run one phase in a subprocess with a hard kill at its deadline;
    recover partial results from the sidecar if it dies or stalls.

    ``init_deadline``: if set, the child must publish its sidecar
    "device" record (i.e. ``jax.devices()`` must return) within that
    many seconds or it is killed early — this is how a wedged axon
    tunnel init costs its deadline, not the whole phase slice.  The
    returned partial dict then carries ``init_wedged=True``.

    ``force_cpu``: run the child with JAX_PLATFORMS=cpu (honored by
    ``_setup`` via the config API, which beats the sitecustomize's
    platform override) — the labeled-CPU fallback path.

    The kill fires at deadline_rel + 10 s — callers must leave at least
    that margin before the overall budget ceiling.  (The child's own
    SIGALRM backstop cannot fire while wedged inside a blocking C call,
    so this parent timeout is the real hard stop.)"""
    smoke = ["--smoke"] if B == 1024 else []
    fd, side_path = tempfile.mkstemp(prefix=f"fsx_bench_{phase}_",
                                     suffix=".jsonl")
    os.close(fd)
    argv = [sys.executable, __file__, f"--phase={phase}",
            f"--deadline-rel={deadline_rel:.1f}", f"--sidecar={side_path}"] + smoke
    env = dict(os.environ)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    log(f"phase {phase}: deadline {deadline_rel:.0f}s"
        + (f", init deadline {init_deadline:.0f}s" if init_deadline else "")
        + (", forced cpu" if force_cpu else ""))
    rec: dict | None = None
    init_wedged = False
    t0 = time.perf_counter()
    # Both streams go to temp files (binary, decoded with replace): a
    # PIPE would deadlock a chatty child against the 64 KB pipe buffer,
    # and a SIGKILL mid-write can truncate a multibyte sequence.
    with tempfile.TemporaryFile() as outf, tempfile.TemporaryFile() as errf:
        proc = subprocess.Popen(
            argv, stdout=outf, stderr=errf, env=env,
            cwd=str(__import__("pathlib").Path(__file__).parent),
        )
        device_seen = init_deadline is None
        while True:
            try:
                ret = proc.wait(timeout=2.0)
                break
            except subprocess.TimeoutExpired:
                pass
            now = time.perf_counter() - t0
            if not device_seen and _sidecar_has(side_path, "device"):
                device_seen = True
                log(f"phase {phase}: device init ok at {now:.0f}s")
            if not device_seen and now > init_deadline:
                log(f"phase {phase}: no device record by {now:.0f}s; "
                    f"killing wedged init")
                init_wedged = True
                proc.kill()
                proc.wait()
                ret = None
                break
            if now > deadline_rel + 10:
                log(f"phase {phase}: killed at deadline; recovering sidecar")
                proc.kill()
                proc.wait()
                ret = None
                break
        errf.seek(0)
        sys.stderr.write(errf.read().decode(errors="replace"))
        if ret == 0:
            outf.seek(0)
            out = outf.read().decode(errors="replace").strip()
            if out:
                try:
                    rec = json.loads(out.splitlines()[-1])
                except json.JSONDecodeError:
                    log(f"phase {phase}: unparseable stdout; recovering sidecar")
        elif ret is not None:
            log(f"phase {phase}: rc={ret}; recovering sidecar")
    try:
        if rec is None:
            rec = _recover_sidecar(side_path)
            if rec:
                log(f"phase {phase}: recovered partial {list(rec.keys())}")
        if init_wedged:
            rec = dict(rec or {}, partial=True, init_wedged=True,
                       init_wedged_after_s=round(time.perf_counter() - t0, 1))
    finally:
        try:
            os.unlink(side_path)
        except OSError:
            pass
    return rec


def _child_main(phase: str) -> int:
    deadline_rel = _argval("deadline-rel", 600.0)
    side_path = None
    for a in sys.argv[1:]:
        if a.startswith("--sidecar="):
            side_path = a.split("=", 1)[1]
    side = Sidecar(side_path)

    # Soft stop between bytecodes (a wedge inside a blocking C call
    # outlives this; the parent's subprocess timeout is the hard stop —
    # either way the parent recovers from the sidecar).
    def on_alarm(sig, frm):
        side.emit("alarm", at_s=round(time.perf_counter() - T_START, 1))
        log(f"phase {phase}: SIGALRM hard stop")
        os._exit(3)

    # Armed BEFORE the parent's kill at deadline_rel+10 so a pure-Python
    # overrun exits cleanly (sidecar 'alarm' record, flushed stderr)
    # instead of taking the SIGKILL.
    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(max(1, int(deadline_rel) + 5))

    fn = {"throughput": phase_throughput, "latency": phase_latency}[phase]
    result = fn(side, deadline_rel)
    print(json.dumps(result), flush=True)
    return 0


def main() -> int:
    for a in sys.argv[1:]:
        if a.startswith("--phase="):
            return _child_main(a.split("=", 1)[1])

    # Persistent XLA compilation cache, inherited by every phase child
    # and probe: the fused step costs ~6-9 s to compile per process;
    # cached it loads in <1 s, which is what makes repeated probing and
    # window-retry affordable inside the budget.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        str(Path(__file__).parent / ".jax_cache"),
    )

    detail = {
        "metric": "mpps_classified",
        "value": 0.0,
        "unit": "Mpps",
        "vs_baseline": 0.0,
        "target_mpps": TARGET_MPPS,
        "target_p99_ms": 1.0,
        "batch": B,
        "table_capacity": TABLE_CAP,
        "wire_format": "compact16",  # 16 B/record, bit-exact model quant
        "bytes_per_record": 16,
        "budget_s": BUDGET_S,
    }
    try:
        forced_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"

        # -- sharded host-ingest mode (--host-ingest=N) ---------------------
        # Tunnel-independent: the sharded ingest subsystem
        # (flowsentryx_tpu/ingest/) is a HOST ceiling, so it is measured
        # by the shm stress harness on CPU and merged into this round's
        # evidence.  Opt-in — the default bench spends its whole budget
        # on the accelerator phases.
        host_ingest_n = int(_argval("host-ingest", 0))
        if host_ingest_n > 0:
            hi_dur = _argval("host-ingest-dur", 8.0)
            log(f"host-ingest phase: {host_ingest_n} drain workers, "
                f"{hi_dur:.0f}s per row")
            env = dict(os.environ, FSX_STRESS_DUR=str(hi_dur),
                       JAX_PLATFORMS="cpu")
            r = subprocess.run(
                [sys.executable,
                 str(Path(__file__).parent / "scripts" / "shm_stress.py"),
                 "--shards", str(host_ingest_n)],
                capture_output=True, text=True, env=env,
                timeout=max(120.0, 20 * hi_dur + 120),
            )
            for line in r.stdout.splitlines()[::-1]:
                if line.strip().startswith("{"):
                    detail["host_ingest"] = json.loads(line)
                    detail["host_ingest"]["artifact"] = (
                        "artifacts/SHMSTRESS_sharded_r06.json")
                    break
            else:
                detail["host_ingest"] = {
                    "error": (r.stderr or "no output").strip()[-500:]}

        # -- healthy-window gate (VERDICT r3 next #1) -----------------------
        # Probe the tunnel before committing the run.  On a degraded
        # link, sleep/retry while enough budget remains for a full
        # throughput+latency pass; the LAST probe's state labels the
        # run either way (never burn the whole budget waiting: a
        # degraded measurement with link_state recorded beats none).
        # The probe compiles and times the REAL fused step — r04 showed
        # trivial-dispatch health diverging 100x from step-dispatch
        # health, so only a miniature of the actual pipeline is a
        # trustworthy gate.
        link_state = "unprobed"
        probes: list = []
        probe_e2e: float | None = None

        def probe_until_healthy(last_resort_s: float) -> None:
            nonlocal link_state, probe_e2e
            backoff = 45.0
            while True:
                # A wedged probe may burn its whole timeout: cap it so
                # it can never eat into the last-resort reserve (the
                # reserve exists to guarantee the final full pass).
                probe_to = min(240.0, remaining() - last_resort_s)
                if probe_to < 30.0:
                    if probes:
                        return
                    probe_to = 30.0  # always probe at least once
                p = _probe_link(timeout_s=probe_to)
                p["at_s"] = round(time.perf_counter() - T_START, 1)
                p["state"] = _probe_state(p)
                probes.append({k: p[k] for k in
                               ("at_s", "state", "step_ms", "h2d_mbps",
                                "e2e_mpps", "dispatch_ms", "init_s",
                                "error") if k in p})
                link_state = p["state"]
                log(f"link probe at {p['at_s']:.0f}s: {link_state} "
                    f"(step {p.get('step_ms')} ms, h2d {p.get('h2d_mbps')} "
                    f"MB/s, e2e {p.get('e2e_mpps')} Mpps)")
                # CPU-fallback probes (tunnel down, jax falls back) would
                # persist host-memcpy GB/s as the "link" baseline forever
                if p.get("backend") not in (None, "cpu"):
                    _update_link_baseline(
                        h2d_mbps_best=p.get("h2d_mbps"),
                        dispatch_ms_best=p.get("dispatch_ms"),
                        probe_e2e_mpps_best=p.get("e2e_mpps"),
                    )
                if link_state == "healthy":
                    probe_e2e = p.get("e2e_mpps")
                    return
                if remaining() - backoff < last_resort_s:
                    log("no healthy window left in budget; "
                        "running on the degraded link (labeled)")
                    return
                log(f"degraded link; retrying in {backoff:.0f}s "
                    f"({remaining():.0f}s budget left)")
                time.sleep(backoff)
                backoff = min(backoff * 1.5, 180.0)

        # Attempt structure: up to two WINDOW attempts (probe-gate, run
        # the phase, and if the probe said healthy but the phase's own
        # transport numbers show the window closed mid-run — flap —
        # re-gate and re-run with what's left), each with the existing
        # wedged-init retry inside.  Fallback: a forced-CPU run, clearly
        # labeled — a measured CPU number beats another 0.0.
        init_attempts = []
        tput: dict = {}
        if not forced_cpu:
            for window_attempt in (1, 2):
                if PROBE_SCRIPT.exists():
                    probe_until_healthy(
                        last_resort_s=430.0 if window_attempt == 1 else 250.0)
                tput_budget = max(60.0, min(0.55 * remaining(),
                                            remaining() - 220))
                init_dl1 = min(300.0, 0.5 * tput_budget)
                t = _run_phase("throughput", tput_budget,
                               init_deadline=init_dl1) or {}
                init_attempts.append(
                    {"deadline_s": round(init_dl1),
                     "wedged": bool(t.get("init_wedged")),
                     "init_s": t.get("init_s")})
                if t.get("init_wedged") and remaining() > 240:
                    init_dl2 = min(150.0, 0.4 * remaining())
                    t2 = _run_phase(
                        "throughput",
                        max(60.0, min(tput_budget, remaining() - 150)),
                        init_deadline=init_dl2) or {}
                    init_attempts.append(
                        {"deadline_s": round(init_dl2),
                         "wedged": bool(t2.get("init_wedged")),
                         "init_s": t2.get("init_s")})
                    t = t2
                if t.get("mpps", 0) and t["mpps"] > tput.get("mpps", 0):
                    tput = t
                # flap detection keys on the SINGLE-dispatch number:
                # mega amortization can hold the headline up through a
                # mid-run transport collapse the probe (single-dispatch)
                # would never have sustained.
                flapped = bool(
                    link_state == "healthy" and probe_e2e
                    and t.get("mpps")
                    and t.get("single_mpps", t["mpps"]) < 0.3 * probe_e2e
                )
                if flapped:
                    detail["window_flaps"] = detail.get("window_flaps", 0) + 1
                    if window_attempt == 1 and remaining() > 300:
                        log(f"window flapped mid-run ({t['mpps']:.1f} vs "
                            f"probe {probe_e2e:.1f} Mpps); re-gating")
                        continue
                break
        if not tput.get("mpps") and remaining() > 90:
            # TPU never produced a number (or cpu was requested):
            # labeled CPU fallback so the round records real data.
            if not forced_cpu:
                log("falling back to CPU throughput (TPU init wedged "
                    f"{len(init_attempts)}x)")
                detail["tpu_fallback"] = "cpu"
            cpu_t = _run_phase("throughput",
                               max(60.0, remaining() - 120),
                               force_cpu=True) or {}
            if cpu_t.get("mpps"):
                tput = cpu_t
        if init_attempts:
            detail["tpu_init_attempts"] = init_attempts
        if probes:
            detail["link_probes"] = probes
            detail["link_state"] = link_state
            detail["healthy_link_criteria"] = linkhealth.criteria()

        if tput and tput.get("mpps"):
            mpps = tput["mpps"]
            detail.update(
                value=round(mpps, 3),
                vs_baseline=round(mpps / TARGET_MPPS, 3),
                chunk_mpps=tput.get("chunk_mpps"),
                compile_s=tput.get("compile_s"),
                backend=tput.get("backend"),
                device_kind=tput.get("device_kind"),
                throughput_partial=tput.get("partial", False),
            )
            for k in ("h2d_mbps", "device_mpps", "burst_mpps",
                      "single_mpps", "mega_mpps", "mega_chunk_mpps",
                      "mega32_chunk_mpps", "devloop_chunk_mpps",
                      "dispatch_mode"):
                if k in tput:
                    detail[k] = tput[k]
            # transport_limited vs the PERSISTED healthy baseline (r3
            # weak #5: a uniformly degraded tunnel must not read as
            # "not transport limited" just because its same-run
            # device-resident number degraded too).
            if tput.get("backend") != "cpu":
                # baseline + transport judgment use the SINGLE-dispatch
                # number: mega amortizes the per-dispatch RPC floor, so
                # a mega value can look healthy on a collapsed link and
                # would poison the cross-round comparable series.
                single_mpps = tput.get("single_mpps", mpps)
                bl = _update_link_baseline(
                    h2d_mbps_best=tput.get("h2d_mbps"),
                    device_mpps_best=tput.get("device_mpps"),
                    e2e_mpps_best=single_mpps,
                )
                best_dev = bl.get("device_mpps_best")
                if best_dev:
                    detail["transport_limited"] = bool(
                        mpps < TARGET_MPPS and best_dev > 2 * single_mpps
                    )
                    detail["device_mpps_healthy_baseline"] = best_dev
            log(f"throughput: {mpps:.2f} Mpps median over {tput.get('chunk_mpps')}")
        else:
            detail["error"] = "throughput phase produced no chunks"

        # Reserve 20 s past the child-kill margin (+10 in _run_phase) so
        # the final JSON always lands inside the budget ceiling.  Run on
        # the backend that actually produced the throughput number: if
        # TPU init wedged there, don't pay the wedge again here.
        # backend unset means nothing measured — default the latency
        # phase to CPU rather than paying a likely TPU wedge again.
        lat_cpu = forced_cpu or detail.get("backend", "cpu") == "cpu"
        lat_budget = remaining() - 30
        if lat_budget > 45:
            lat = _run_phase("latency", lat_budget, force_cpu=lat_cpu,
                             init_deadline=None if lat_cpu
                             else min(240.0, 0.6 * lat_budget)) or {}
            detail["latency_backend"] = "cpu" if lat_cpu else \
                lat.get("backend", detail.get("backend"))
            latd: dict = {}
            for key in ("step_ms", "micro", "sync_floor_ms", "paced"):
                if lat.get(key):
                    latd[key] = lat[key]
            if lat.get("sync_floor_ms") is not None:
                detail["sync_floor_ms"] = round(lat["sync_floor_ms"], 2)

            # Budget decomposition (r3 next #2): fill + H2D + compute +
            # D2H for the decomposition batch, with tunnel-independent
            # transfer times modeled at the persisted healthy link rate
            # and the tunnel RPC floor reported separately.
            micro = lat.get("micro") or {}
            comp_ms = (lat.get("step_ms") or {}).get(str(micro.get("batch")))
            if micro and comp_ms is not None:
                bl = _load_link_baseline()
                healthy = bl.get("h2d_mbps_best") or HEALTHY_H2D_MBPS
                # steady-state readback = the compact verdict wire (the
                # 8 B/record full fetch is the overflow fallback only)
                d2h_bytes = micro.get("d2h_wire_bytes", micro["batch"] * 8)
                h2d_healthy = micro["wire_bytes"] / (healthy * 1e6) * 1e3
                d2h_healthy = d2h_bytes / (healthy * 1e6) * 1e3
                floor = lat.get("sync_floor_ms") or 0.0
                total = (micro["fill_ms"] + h2d_healthy + comp_ms
                         + d2h_healthy)
                latd["budget"] = {
                    "batch": micro["batch"],
                    "fill_ms": micro["fill_ms"],
                    "h2d_ms_measured": micro.get("h2d_ms"),
                    "h2d_ms_at_healthy_link": round(h2d_healthy, 4),
                    "compute_ms": comp_ms,
                    "d2h_ms_measured": micro.get("d2h_ms"),
                    "d2h_ms_net_floor": round(max(
                        0.0, (micro.get("d2h_ms") or 0.0) - floor), 4),
                    "d2h_ms_at_healthy_link": round(d2h_healthy, 4),
                    "total_ms_net_of_tunnel": round(total, 4),
                    "sub_ms_budget": bool(total < 1.0),
                    "tunnel_rpc_floor_ms": round(floor, 2),
                    "healthy_link_mbps": healthy,
                }
                log(f"latency budget B={micro['batch']}: "
                    f"{total:.3f} ms net of tunnel "
                    f"(floor {floor:.1f} ms separately)")
            if latd:
                detail["latency"] = latd

            # Headline p50/p99: the canonical latency config — depth 0
            # and SUSTAINED (achieved >= 0.8x offered, so the number is
            # latency, not backlog), at the highest sustained load;
            # fallback: the lowest-load depth-0 run, unsustained,
            # labeled by its achieved rate.
            paced = lat.get("paced") or []
            canon = [r for r in paced if r["depth"] == 0
                     and r["achieved_mpps"] >= 0.8 * r["load_mpps"]]
            if canon:
                canon.sort(key=lambda r: (-r["load_mpps"], r["batch"]))
            else:
                canon = sorted((r for r in paced if r["depth"] == 0),
                               key=lambda r: (r["batch"], r["load_mpps"]))
            if canon:
                r0 = canon[0]
                detail["p50_ms"] = r0["p50_ms"]
                detail["p99_ms"] = r0["p99_ms"]
                detail["n_lat_records"] = r0["n"]
                detail["latency_config"] = {
                    "batch": r0["batch"], "depth": 0,
                    "load_mpps": r0["load_mpps"],
                    "achieved_mpps": r0["achieved_mpps"],
                    "sustained": bool(
                        r0["achieved_mpps"] >= 0.8 * r0["load_mpps"]),
                }
                floor = lat.get("sync_floor_ms") or 0.0
                detail["p99_minus_floor_ms"] = round(
                    max(0.0, r0["p99_ms"] - floor), 3)
                log(f"latency: p50={r0['p50_ms']:.1f}ms "
                    f"p99={r0['p99_ms']:.1f}ms "
                    f"(B={r0['batch']} depth=0 {r0['load_mpps']}Mpps)")
            if lat.get("stats") is not None:
                detail["stats"] = lat["stats"]
            if lat:
                detail["latency_partial"] = lat.get("partial", False)
        else:
            log(f"skipping latency phase ({lat_budget:.0f}s left)")
    except Exception as e:  # noqa: BLE001 — one JSON line, always
        msg = f"{type(e).__name__}: {e}"
        detail["error"] = f"{detail['error']}; {msg}" if "error" in detail else msg
    finally:
        _merge_best_tpu_attempt(detail)
        detail["wall_s"] = round(time.perf_counter() - T_START, 1)
        print(json.dumps(detail), flush=True)
    return 0


#: Throughput-evidence keys adopted from a better same-round TPU
#: attempt (latency keys are NOT merged — they stay labeled with the
#: backend that measured them via latency_backend).
_ATTEMPT_KEYS = (
    "value", "vs_baseline", "backend", "device_kind", "chunk_mpps",
    "single_mpps", "mega_mpps", "mega_chunk_mpps", "mega32_chunk_mpps",
    "devloop_chunk_mpps", "dispatch_mode",
    "h2d_mbps", "device_mpps", "burst_mpps", "transport_limited",
    "device_mpps_healthy_baseline", "compile_s", "throughput_partial",
)


def _merge_best_tpu_attempt(detail: dict) -> None:
    """Adopt the best same-round TPU attempt's throughput evidence
    (VERDICT r4 next #1a: a CPU fallback must never DISPLACE real-TPU
    evidence recorded earlier in the round).

    The link-window watcher saves ``artifacts/bench_attempt_<ts>.json``
    whenever the monitor catches a live tunnel window.  If the best
    such attempt beats this run's number — always true when this run
    fell back to CPU — its throughput keys become the headline, the
    displaced result is preserved under ``displaced_result``, and the
    merge is labeled with the attempt's link state.  Attempt runs
    themselves set FSX_BENCH_NO_MERGE=1 so evidence never chains."""
    if os.environ.get("FSX_BENCH_NO_MERGE"):
        return
    import glob as _glob
    import re as _re

    best: tuple[str, dict, int] | None = None
    now_ts = int(time.time())
    for p in sorted(_glob.glob(
            str(Path(__file__).parent / "artifacts" / "bench_attempt_*.json"))):
        # "same-round" is enforced by the unix timestamp the watcher
        # bakes into the filename (immutable in git, unlike mtime):
        # attempts older than 16 h belong to a previous round.
        m = _re.search(r"bench_attempt_(?:r\d+_)?(\d{9,})\.json$",
                       os.path.basename(p))
        if not m or now_ts - int(m.group(1)) > 16 * 3600:
            continue
        try:
            with open(p) as f:
                d = json.loads(f.read().strip().splitlines()[-1])
        except (OSError, json.JSONDecodeError, IndexError):
            continue
        if d.get("backend") in (None, "cpu") or not d.get("value"):
            continue
        if best is None or d["value"] > best[1]["value"]:
            best = (p, d, int(m.group(1)))
    if best is None:
        return
    path, att, att_ts = best
    this_is_tpu = detail.get("backend") not in (None, "cpu")
    if this_is_tpu and detail.get("value", 0) >= att["value"]:
        # this run IS the best TPU evidence; record that attempts exist
        detail["tpu_attempts_considered"] = os.path.basename(path)
        return
    detail["displaced_result"] = {
        k: detail.get(k) for k in _ATTEMPT_KEYS if k in detail
    }
    for k in _ATTEMPT_KEYS:
        if k in att:
            detail[k] = att[k]
        elif k in detail:
            del detail[k]
    detail["merged_from_attempt"] = {
        "file": os.path.basename(path),
        "attempt_unix_ts": att_ts,
        "link_state": att.get("link_state")
        or (att.get("link_probes") or [{}])[-1].get("state"),
        "note": ("headline throughput adopted from the best same-round "
                 "TPU attempt; latency keys remain from this run, "
                 "labeled by latency_backend"),
    }


if __name__ == "__main__":
    sys.exit(main())
