"""The explicit health state machine: HEALTHY / DEGRADED / FAILED.

Before PR 13 "degraded" was a log line: a dead ingest shard, a gossip
mailbox dropping wires, a quarantined batch each printed once and
vanished — an operator asking "is this engine OK?" had no state to
query.  This module derives one explicit ladder from the signals the
reports ALREADY carry, so health is a pure function of observable
counters (deterministic, unit-testable, and impossible to let drift
from the counters themselves):

* **HEALTHY** — every shard served, nothing dropped, nothing
  quarantined, watchdog quiet.
* **DEGRADED(reasons)** — serving continues but something fail-opened:
  dead/stalled ingest shards (their flows fall to the kernel limiter),
  sealed-queue emit drops, sequence gaps, quarantined poisoned
  batches, corrupt-slot skips, gossip TX drops / RX seq gaps, the
  multi-host transport's drop/gap/dup/reorder/skew accounting
  (``net_*``, cluster/transport.py), a watchdog soft trip, a restore
  that fell back to the ``.prev`` generation.  Each reason is a
  ``name:count`` string an alert can key on.
* **FAILED** — the engine cannot serve its span: every ingest shard is
  dead, or the watchdog hard-tripped (the process is already dying
  loudly; the state is its last words).

Carried in ``EngineReport.health``, aggregated across ranks by the
cluster supervisor (worst-of, with per-rank detail), shown by
``fsx status --engine-report`` and alertable via ``fsx monitor
--alert-degraded``.

Jax-free and numpy-free: the supervisor and the CLI monitoring path
import this without an engine boot.
"""

from __future__ import annotations

HEALTHY = "healthy"
DEGRADED = "degraded"
FAILED = "failed"

#: Ladder order for worst-of aggregation.
_RANK = {HEALTHY: 0, DEGRADED: 1, FAILED: 2}


def engine_health(
    ingest: dict | None = None,
    gossip: dict | None = None,
    watchdog: dict | None = None,
    restore_fallbacks: int = 0,
    rebalance: dict | None = None,
    elastic: dict | None = None,
) -> dict:
    """Derive one engine's health from its report blocks (module
    docstring).  Every argument is the corresponding
    ``EngineReport``/``ingest_stats`` dict (or None when that plane is
    off); the return is ``{"state": ..., "reasons": [...]}``."""
    reasons: list[str] = []
    failed = False
    if ingest:
        dead = ingest.get("dead_workers") or []
        n_workers = int(ingest.get("n_workers") or 0)
        if dead:
            reasons.append(f"ingest_shards_dead:{len(dead)}")
            if n_workers and len(dead) == n_workers:
                # nothing left serving this span: the kernel limiter
                # stands alone for every flow the engine owned
                failed = True
        stalled = [k for k, w in (ingest.get("workers") or {}).items()
                   if w.get("stalled")]
        if stalled:
            reasons.append(f"ingest_shards_stalled:{len(stalled)}")
        gaps = sum(w.get("seq_gaps", 0)
                   for w in (ingest.get("workers") or {}).values())
        if gaps:
            reasons.append(f"ingest_seq_gaps:{gaps}")
        drops = int(ingest.get("dropped_emit_batches") or 0)
        if drops:
            reasons.append(f"ingest_emit_drops:{drops}")
        tail = int(ingest.get("dropped_tail_batches") or 0)
        if tail:
            reasons.append(f"ingest_tail_drops:{tail}")
        quarantined = int(ingest.get("quarantined_batches") or 0)
        if quarantined:
            reasons.append(f"quarantined_batches:{quarantined}")
        bad = int(ingest.get("bad_wire_slots") or 0)
        if bad:
            reasons.append(f"bad_wire_slots:{bad}")
    if gossip:
        tx = int(gossip.get("tx_dropped") or 0)
        if tx:
            reasons.append(f"gossip_tx_dropped:{tx}")
        rx = int(gossip.get("rx_seq_gaps") or 0)
        if rx:
            reasons.append(f"gossip_rx_seq_gaps:{rx}")
        net = gossip.get("net")
        if net:
            # the multi-host transport's fail-open accounting
            # (cluster/transport.py): every one of these means a
            # verdict wire was dropped, delayed past the reorder
            # window, or refused for a lying epoch — serving
            # continues (DEGRADED, never FAILED: the local span is
            # still mitigated; remote convergence is what degraded)
            for key, name in (("tx_drop", "net_tx_drop"),
                              ("rx_gap", "net_rx_gap"),
                              ("rx_dup", "net_rx_dup"),
                              ("reorder_evict", "net_reorder_evict"),
                              ("epoch_skew_dropped",
                               "net_epoch_skew_dropped")):
                v = int(net.get(key) or 0)
                if v:
                    reasons.append(f"{name}:{v}")
            if int(net.get("epoch_skew_dropped") or 0):
                # the gauge behind the drops: how far out of frame
                # the worst wire was (seconds) — names the lying
                # epoch's magnitude for the operator
                reasons.append(
                    f"net_epoch_skew_max:{net.get('epoch_skew_max')}")
    if watchdog:
        trips = int(watchdog.get("soft_trips") or 0)
        if trips:
            reasons.append(f"watchdog_soft_trips:{trips}")
        if watchdog.get("hard_tripped"):
            failed = True
    if restore_fallbacks:
        reasons.append(f"restore_fallbacks:{restore_fallbacks}")
    if rebalance:
        # live-handoff loss accounting (cluster/rebalance.py): each
        # of these means rows or a stream went somewhere other than
        # the happy path — DEGRADED, never FAILED (the span is still
        # served by whoever owned it; conservation is the chaos
        # campaign's invariant, these are the operator's breadcrumbs)
        for key, name in (
                ("adopt_dropped", "rebalance_adopt_dropped"),
                ("staged_discarded", "rebalance_staged_discarded"),
                ("streams_refused", "rebalance_streams_refused"),
                ("foreign_dropped", "rebalance_foreign_dropped")):
            v = int(rebalance.get(key) or 0)
            if v:
                reasons.append(f"{name}:{v}")
    if elastic:
        # autoscaler friction (cluster/elastic.py): suppressed plans
        # mean the fleet WANTED to reshape and could not (cooldown or
        # clamp) — visible so an operator can raise max_engines
        # instead of discovering the clamp in a postmortem
        v = int(elastic.get("suppressed") or 0)
        if v:
            reasons.append(f"elastic_plans_suppressed:{v}")
        v = int(elastic.get("aborts") or 0)
        if v:
            reasons.append(f"elastic_handoff_aborts:{v}")
    state = FAILED if failed else (DEGRADED if reasons else HEALTHY)
    return {"state": state, "reasons": reasons}


def worst(*states: str) -> str:
    """Worst-of fold over ladder states (unknown reads as DEGRADED:
    a rank whose health cannot be read is not healthy)."""
    return max((s if s in _RANK else DEGRADED for s in states),
               key=lambda s: _RANK[s], default=HEALTHY)


def cluster_health(per_rank: dict, failed_ranks: list,
                   stalled_ranks: list,
                   dead_hosts: list | None = None) -> dict:
    """Supervisor-side aggregation: worst-of every rank's reported
    health, with supervisor-observed terminal states layered on top
    (a rank parked as failed is FAILED even if its last report said
    healthy — the report predates the park).  ``dead_hosts`` is the
    federation beacon's verdict (multi-host fleets): a silent peer
    HOST means whole IP spans are down to that host's kernel tier —
    the fleet is FAILED until it returns."""
    states = [h.get("state", DEGRADED) for h in per_rank.values()]
    reasons: list[str] = []
    for r, h in sorted(per_rank.items()):
        for reason in h.get("reasons", []):
            reasons.append(f"r{r}:{reason}")
    state = worst(*states) if states else HEALTHY
    if failed_ranks:
        state = FAILED
        reasons.append(
            f"ranks_failed:{','.join(str(r) for r in failed_ranks)}")
    elif stalled_ranks:
        state = worst(state, DEGRADED)
        reasons.append(
            f"ranks_stalled:{','.join(str(r) for r in stalled_ranks)}")
    if dead_hosts:
        state = FAILED
        reasons.append(
            f"hosts_dead:{','.join(str(h) for h in dead_hosts)}")
    return {
        "state": state,
        "reasons": reasons,
        "per_rank": {str(r): h for r, h in sorted(per_rank.items())},
    }
