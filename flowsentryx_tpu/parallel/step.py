"""Multi-device fused step: IP-hash-sharded state + DP scoring.

This is the scale-out analog of SURVEY.md §2.3's parallelism table:

* **"Sequence parallelism" analog** — the per-IP state table shards by
  IP hash across the mesh's ``ip`` axis.  A flow's owner device is
  given by the *top* hash bits, its slot within the owner's shard by
  the *low* bits — ownership and probing use disjoint bits, and a key's
  owner never changes, so limiter state never migrates between devices.
* **Data parallelism** — classifier scoring splits the packet batch
  across the same axis; an ``all_gather`` (ICI) rebuilds the full score
  vector.
* **Collectives** — one ``all_gather`` for scores + one ``psum`` for
  verdicts/writebacks per step.  Flow ownership is disjoint, so a sum
  over devices *is* the global verdict vector (non-owners contribute
  PASS=0).

Everything runs under ``jax.shard_map`` over a
:func:`~flowsentryx_tpu.parallel.mesh.make_mesh` mesh; the same code
compiles for 8 virtual CPU devices (tests) or a v5e pod slice.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flowsentryx_tpu.core.config import FsxConfig
from flowsentryx_tpu.core.schema import GlobalStats, IpTableState, Verdict, make_table
from flowsentryx_tpu.ops import agg, fused, hashtable


def shard_table(table: IpTableState, mesh: Mesh) -> IpTableState:
    """Place a state table row-sharded over the mesh's first axis."""
    spec = NamedSharding(mesh, P(mesh.axis_names[0]))
    return jax.tree.map(lambda a: jax.device_put(a, spec), table)


def make_sharded_table(cfg: FsxConfig, mesh: Mesh) -> IpTableState:
    """Fresh empty table of ``cfg.table.capacity`` rows, row-sharded."""
    return shard_table(make_table(cfg.table.capacity), mesh)


def make_sharded_step(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    donate: bool | None = None,
):
    """Build the jitted multi-device step.

    Signature matches the single-device
    :func:`~flowsentryx_tpu.ops.fused.make_jitted_step`:
    ``step(table, stats, params, batch) -> (table, stats, out)`` — the
    engine swaps one for the other based on mesh size.  ``table`` must
    be sharded with :func:`shard_table`; batch/params/stats replicated.
    """
    if donate is None:
        donate = fused.donation_supported()
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    k_bits = n_dev.bit_length() - 1  # n_dev = 2**k_bits (validated by make_mesh)
    if cfg.table.capacity % n_dev:
        raise ValueError("table capacity must divide by device count")
    local_tbl = dataclasses.replace(cfg.table, capacity=cfg.table.capacity // n_dev)
    local_cfg = dataclasses.replace(cfg, table=local_tbl)

    def device_step(table_shard, stats, params, batch):
        d = jax.lax.axis_index(axis)

        # replicated aggregation (cheap; avoids a shuffle of raw packets)
        fa = agg.aggregate(batch.key, batch.pkt_len, batch.ts, batch.valid)
        now = jnp.max(jnp.where(batch.valid, batch.ts, 0.0))

        # --- DP scoring: each device scores B/n_dev packets, ICI gather ----
        b = batch.feat.shape[0]
        if b % n_dev:
            raise ValueError(
                f"batch size {b} must divide by the {n_dev}-device mesh "
                "(pad the batch; decode_records already pads to a static size)"
            )
        local_b = b // n_dev
        feat_local = jax.lax.dynamic_slice_in_dim(batch.feat, d * local_b, local_b)
        score_local = classify_batch(params, feat_local)
        score = jax.lax.all_gather(score_local, axis, tiled=True)  # [B]
        ml_flow = fused.ml_flow_verdict(cfg, score, batch.valid, fa.inv)

        # --- hash ownership: top k bits pick the device --------------------
        h1 = hashtable.hash_u32(fa.rep_key)
        owner = (h1 >> (32 - k_bits)).astype(jnp.int32) if k_bits else jnp.zeros_like(h1, jnp.int32)
        mine = fa.rep_valid & (owner == d)

        new_shard, dec = fused.flow_step(
            local_cfg, table_shard, fa, mine, ml_flow, now
        )

        # --- combine disjoint per-owner decisions (PASS=0 identity) --------
        flow_verdict = jax.lax.psum(
            jnp.where(mine, dec.flow_verdict, 0), axis
        )
        newly = jax.lax.psum(
            jnp.where(mine & dec.newly_blocked, 1, 0), axis
        ).astype(bool)
        block_until = jax.lax.psum(
            jnp.where(mine & dec.newly_blocked, dec.new_blocked_until, 0.0), axis
        )

        verdict = jnp.where(batch.valid, flow_verdict[fa.inv], int(Verdict.PASS))
        new_stats = fused.update_stats(stats, verdict, batch.valid)

        out = fused.StepOutput(
            verdict=verdict,
            score=score,
            block_key=jnp.where(newly, fa.rep_key, agg.INVALID_KEY),
            block_until=block_until,
            now=now,
        )
        return new_shard, new_stats, out

    table_specs = IpTableState(*([P(axis)] * len(IpTableState._fields)))
    stats_specs = GlobalStats(*([P()] * len(GlobalStats._fields)))
    out_specs = fused.StepOutput(*([P()] * len(fused.StepOutput._fields)))

    sharded = jax.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(table_specs, stats_specs, P(), P()),
        out_specs=(table_specs, stats_specs, out_specs),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _make_sharded_wire_step(cfg, classify_batch, mesh, donate, decode):
    """Shared wrapper: replicated wire buffer → on-device ``decode`` →
    the shard-mapped step.  The wire enters as ONE contiguous H2D
    transfer (tiny next to the sharded state); all field extraction
    fuses into the jit."""
    if donate is None:
        donate = fused.donation_supported()
    base = make_sharded_step(cfg, classify_batch, mesh, donate=False)

    def step(table, stats, params, raw):
        return base(table, stats, params, decode(raw))

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_sharded_raw_step(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    donate: bool | None = None,
):
    """Sharded step over the RAW ring wire format — the multi-device
    twin of :func:`~flowsentryx_tpu.ops.fused.make_jitted_raw_step`,
    with the same ``step(table, stats, params, raw)`` signature, so the
    serving :class:`~flowsentryx_tpu.engine.engine.Engine` swaps it in
    whenever its mesh spans more than one device."""
    from flowsentryx_tpu.core import schema

    return _make_sharded_wire_step(cfg, classify_batch, mesh, donate,
                                   schema.decode_raw)


def make_sharded_compact_step(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    donate: bool | None = None,
    **quant,
):
    """Sharded step over the COMPACT 16 B wire format — the multi-device
    twin of :func:`~flowsentryx_tpu.ops.fused.make_jitted_compact_step`.
    ``**quant`` are the wire-quantizer kwargs
    (:func:`~flowsentryx_tpu.core.schema.wire_quant_for`); the batch
    enters replicated and dequantizes on device before the shard-mapped
    step, so the multi-chip engine keeps the 3× wire-byte saving."""
    import functools

    from flowsentryx_tpu.core import schema

    return _make_sharded_wire_step(
        cfg, classify_batch, mesh, donate,
        functools.partial(schema.decode_compact, **quant),
    )
