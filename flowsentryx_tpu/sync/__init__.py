"""The host-concurrency plane: runtime primitives + static analysis.

Third leg of the static-analysis suite (``fsx check`` proves the BPF
layer, ``fsx audit`` the device graphs, ``fsx sync`` the host threads
— docs/CONCURRENCY.md is the operator view):

* :mod:`flowsentryx_tpu.sync.tuning` — THE table of idle/backoff
  timing constants the engine and ingest share, each with its measured
  rationale.
* :mod:`flowsentryx_tpu.sync.channel` — :class:`SinkChannel`, the
  cv-guarded dispatch↔worker handoff protocol extracted from the
  engine so the model checker can drive the REAL code.
* :mod:`flowsentryx_tpu.sync.contracts` — the declarative registry of
  shared mutable state plus the AST pass that enforces each field's
  thread discipline (``fsx sync`` / the ``sync_contracts`` lint stage).
* :mod:`flowsentryx_tpu.sync.interleave` — the bounded-interleaving
  model checker: exhaustive cooperative schedules over the real
  protocol objects, including the arena reuse-bound tightness proof.

Everything here is deliberately jax-free: the ingest workers import
:mod:`tuning` on their sub-second boot path, and the checkers must run
in the lint gate without paying a backend init.
"""

from flowsentryx_tpu.sync.channel import SinkChannel, WorkerCrash

__all__ = ["SinkChannel", "WorkerCrash"]
