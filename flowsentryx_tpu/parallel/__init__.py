from flowsentryx_tpu.parallel import mesh, step  # noqa: F401
from flowsentryx_tpu.parallel.mesh import make_mesh  # noqa: F401
from flowsentryx_tpu.parallel.step import make_sharded_step, shard_table  # noqa: F401
