"""Mega-step dispatch-amortization profile (VERDICT r4 #5 evidence).

Measures, device-resident and donated (the production regime):

* ``single``  — K iterations of the one-batch compact step;
* ``mega_N``  — K/N iterations of the N-in-one-dispatch lax.scan
  mega-step over the SAME records (N in 4/8/16);
* ``h2d_group_ms`` — host→device transfer of one stacked [N, B+1, 4]
  wire group (the per-group transport the engine's mega mode pays).

From these it derives per-batch dispatch overhead (single minus
amortized mega cost) and a latency budget through the mega loop at
1/5/10 Mpps offered: group-fill residency + H2D + scan — the
"e2e p99 net of transport" the persistent-loop story is judged on.

Usage: [FSX_FORCE_CPU=1] python scripts/megastep_profile.py [out.json]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

B = 1024
CAP = 1 << 20
K = 64  # total micro-batches timed per variant


def main() -> int:
    import jax

    from _probe_common import setup_backend

    setup_backend()

    from flowsentryx_tpu.core import schema
    from flowsentryx_tpu.core.config import BatchConfig, FsxConfig, TableConfig
    from flowsentryx_tpu.models import get_model
    from flowsentryx_tpu.ops import fused

    dev = jax.devices()[0]
    out = {"ts": time.time(), "backend": dev.platform,
           "device_kind": dev.device_kind, "batch": B, "table_capacity": CAP}

    spec = get_model("logreg_int8")
    params = jax.device_put(spec.init())
    quant = schema.wire_quant_for(params)
    cfg = FsxConfig(table=TableConfig(capacity=CAP),
                    batch=BatchConfig(max_batch=B))

    rng = np.random.default_rng(0)
    raws_np = []
    for i in range(K):
        buf = np.zeros(B, dtype=schema.FLOW_RECORD_DTYPE)
        buf["saddr"] = rng.integers(1, 1 << 20, B).astype(np.uint32)
        buf["pkt_len"] = rng.integers(64, 1500, B)
        buf["ts_ns"] = (i * B + np.arange(B)) * 100
        buf["feat"] = rng.integers(0, 1 << 20, (B, 8))
        raws_np.append(schema.encode_compact(buf, B, t0_ns=0, **quant))

    donate = fused.donation_supported()
    out["donated"] = donate

    # -- single-step loop ---------------------------------------------------
    step = fused.make_jitted_compact_step(
        cfg, spec.classify_batch, donate=donate, **quant)
    raws_dev = [jax.device_put(r) for r in raws_np]
    table = jax.device_put(schema.make_table(CAP))
    stats = jax.device_put(schema.make_stats())
    table, stats, o = step(table, stats, params, raws_dev[0])
    jax.block_until_ready(o.verdict)
    t0 = time.perf_counter()
    for r in raws_dev:
        table, stats, o = step(table, stats, params, r)
    jax.block_until_ready(o.verdict)
    single_ms = (time.perf_counter() - t0) / K * 1e3
    out["single_ms_per_batch"] = round(single_ms, 4)

    # -- mega loops ---------------------------------------------------------
    out["mega"] = {}
    for n in (4, 8, 16):
        mega = fused.make_jitted_compact_megastep(
            cfg, spec.classify_batch, n_chunks=n, donate=donate, **quant)
        groups = [jax.device_put(np.stack(raws_np[i:i + n]))
                  for i in range(0, K, n)]
        table = jax.device_put(schema.make_table(CAP))
        stats = jax.device_put(schema.make_stats())
        table, stats, outs = mega(table, stats, params, groups[0])
        jax.block_until_ready(outs.verdict)
        t0 = time.perf_counter()
        for g in groups:
            table, stats, outs = mega(table, stats, params, g)
        jax.block_until_ready(outs.verdict)
        per_batch = (time.perf_counter() - t0) / K * 1e3
        # one stacked-group H2D (the engine's per-group transport)
        gnp = np.stack(raws_np[:n])
        t0 = time.perf_counter()
        for _ in range(8):
            jax.block_until_ready(jax.device_put(gnp))
        h2d = (time.perf_counter() - t0) / 8 * 1e3
        out["mega"][str(n)] = {
            "ms_per_batch": round(per_batch, 4),
            "mpps": round(B / per_batch / 1e3, 3),
            "h2d_group_ms": round(h2d, 4),
            "dispatch_overhead_recovered_ms": round(single_ms - per_batch, 4),
        }

    # -- latency budget through the mega loop -------------------------------
    # per-record e2e net of transport = group-fill residency (oldest
    # record waits N*B/L) + H2D + scan(N batches)
    out["latency_budget_net_of_transport"] = {}
    for load_mpps in (1.0, 5.0, 10.0):
        budgets = {}
        for n in (4, 8, 16):
            m = out["mega"][str(n)]
            fill_ms = n * B / (load_mpps * 1e3)
            scan_ms = m["ms_per_batch"] * n
            budgets[str(n)] = {
                "group_fill_ms": round(fill_ms, 3),
                "h2d_ms": m["h2d_group_ms"],
                "scan_ms": round(scan_ms, 3),
                "e2e_oldest_record_ms": round(
                    fill_ms + m["h2d_group_ms"] + scan_ms, 3),
            }
        # single-batch dispatch comparison at the same load
        budgets["single_dispatch"] = {
            "fill_ms": round(B / (load_mpps * 1e3), 3),
            "step_ms": out["single_ms_per_batch"],
            "e2e_oldest_record_ms": round(
                B / (load_mpps * 1e3) + out["single_ms_per_batch"], 3),
        }
        out["latency_budget_net_of_transport"][f"{load_mpps}Mpps"] = budgets

    print(json.dumps(out))
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except Exception as e:
        out = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(out))
        raise SystemExit(1)
