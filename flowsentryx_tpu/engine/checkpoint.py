"""Checkpoint/resume of the device-resident serving state.

The reference's only persistence is BPF map pinning under /sys/fs/bpf
(``src/Makefile:22``, ``TODO.md:289``) — kernel state survives loader
restarts, user state does not exist.  Here the TPU-plane state (per-IP
limiter/blacklist table + global stats + the t0 clock anchor) round-
trips through one ``.npz``, so a restarted engine resumes with every
tracked flow, window counter, and blacklist expiry intact — the
user-plane analog of map pinning.

Production-scale upgrades (PR 8):

* **Atomic + durable writes** — the snapshot publishes through
  :func:`flowsentryx_tpu.core.durable.atomic_write` (same-directory
  temp, fsync the bytes, atomic ``os.replace``, fsync the parent
  dir), so a crash mid-snapshot can never truncate the live
  checkpoint, and a POWER crash after ``save_state`` returns can
  never lose it either (the periodic ``--checkpoint-every`` loop
  overwrites the same path forever; a torn or un-synced write there
  would destroy the only copy).  The ``fsx crash`` model checker
  drives this exact code against a simulated fs at every crash point
  (docs/CRASH.md).
* **Geometry header** — ``hash_salt`` (as before) plus ``n_shards``
  and ``capacity``: a table's global row indices are meaningful ONLY
  under the geometry that wrote them (owner = top hash bits, slot =
  probed low bits), so the header is what lets a restore detect a
  mesh/capacity change and RESHARD
  (:func:`flowsentryx_tpu.engine.table.reshard_rows`) instead of
  silently mislocating every key.  Arrays stay the flat per-column
  global layout (shard-major when sharded — exactly what
  ``device_get`` of a row-sharded array yields), so every pre-header
  snapshot still loads (``n_shards`` defaults to 1).

Integrity + retention (PR 13, the chaos campaign's forcing function):

* **CRC32 integrity** — the snapshot carries one CRC32 folded over
  every header AND payload section (each entry's name + raw bytes, in
  sorted-name order).  A bit-flipped or torn file can therefore never
  be *silently* loaded: :func:`load_checkpoint` refuses with
  :class:`CheckpointCorrupt` — a named ``ValueError`` — whether the
  damage shows as a zip/zlib decode error, a missing member, or clean
  decompression of wrong bytes (the case only the CRC catches).
  Pre-CRC snapshots (no ``integrity_crc32`` member) still load; their
  ``crc_checked`` flag reads False so callers can tell "verified" from
  "grandfathered".
* **Previous-generation retention** — before the atomic
  ``os.replace`` publishes a new snapshot, the incumbent is rotated to
  ``<name>.prev`` (same-directory rename, atomic on POSIX).  Restore
  paths that hit a corrupt checkpoint fall back to that retained good
  generation LOUDLY (``Engine.restore``), instead of crashing — or
  worse, resuming from garbage — on the only copy.

(Plain npz rather than orbax: the state is a flat dict of arrays,
~40 MB at 1M rows; zero-dependency and byte-inspectable wins here.)
"""

from __future__ import annotations

import io
import zipfile
import zlib
from pathlib import Path
from typing import NamedTuple

import numpy as np

from flowsentryx_tpu.core import durable, schema

CHECKPOINT_SCHEMA_VERSION = 1


class CheckpointCorrupt(ValueError):
    """A checkpoint file failed validation: empty, truncated, torn,
    bit-flipped (CRC mismatch), or structurally unreadable.  A
    ``ValueError`` subclass so existing ``except ValueError`` callers
    keep refusing loudly; the distinct type lets restore paths fall
    back to the retained ``.prev`` generation on corruption while
    still propagating genuine contract errors (schema/salt mismatch)."""


#: np.load errors that mean "this file is damaged", not "this file
#: disagrees with me": zip central-directory tears, zlib stream
#: corruption, short reads, struct decode failures on truncated
#: members.  (KeyError/IndexError cover a torn-at-create file whose
#: zip opens but whose members are absent or empty.)
_DAMAGE_ERRORS = (OSError, EOFError, zipfile.BadZipFile, zlib.error,
                  KeyError, IndexError, ValueError)


def _fold_crc(entries: dict) -> int:
    """CRC32 over every section, sorted by name: ``name bytes`` then
    the array's raw bytes.  Folding the NAMES in means a section
    swapped for another section's bytes (or dropped entirely at
    truncation) also mismatches — header and payload are both under
    the same checksum, per the chaos campaign's torn-write faults."""
    crc = 0
    for name in sorted(entries):
        arr = np.ascontiguousarray(np.asarray(entries[name]))
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(arr.tobytes(), crc)
    return crc & 0xFFFFFFFF


def prev_path(path: str | Path) -> Path:
    """The retained previous-generation twin of a checkpoint path
    (``snap.npz`` -> ``snap.npz.prev``), kept same-directory so the
    rotation rides the existing atomic ``os.replace``."""
    p = Path(path)
    if p.suffix != ".npz":
        p = p.with_suffix(p.suffix + ".npz")
    return p.with_name(p.name + ".prev")


class Checkpoint(NamedTuple):
    """A loaded snapshot, HOST-side (numpy): the caller owns placement
    (direct when the geometry matches, through
    :func:`~flowsentryx_tpu.engine.table.reshard_rows` when not)."""

    table: schema.IpTableState   # numpy leaves, global shard-major rows
    stats: schema.GlobalStats    # numpy [2] u32 pairs
    t0_ns: int
    hash_salt: int
    n_shards: int                # geometry the rows were laid out under
    capacity: int
    missing_columns: tuple       # table columns the snapshot predates
    missing_stats: tuple         # stats counters the snapshot predates
    #: False only for pre-CRC snapshots (grandfathered in unverified);
    #: any snapshot written since PR 13 carries ``integrity_crc32`` and
    #: loads only after the fold re-verifies.
    crc_checked: bool = True


def save_state(
    path: str | Path,
    table: schema.IpTableState,
    stats: schema.GlobalStats,
    t0_ns: int,
    hash_salt: int = 0,
    n_shards: int = 1,
) -> Path:
    """Snapshot serving state ATOMICALLY (module docstring).  Arrays
    are fetched from device (the one deliberate D2H of the engine's
    lifetime); ``hash_salt``/``n_shards`` record the geometry the slot
    layout was built under, so a restore can detect and reshard a
    geometry change instead of mislocating keys."""
    path = Path(path)
    # np.savez silently appends .npz to a suffix-less path; normalize so
    # the returned path is the file actually written (same contract as
    # models.logreg._npz_path).
    if path.suffix != ".npz":
        path = path.with_suffix(path.suffix + ".npz")
    # One array per column (not the in-memory [N, 12] matrix): the
    # column-per-key format predates the matrix layout, keeps old
    # snapshots loadable, and lets future columns default cleanly.
    state = np.asarray(table.state)
    key = np.asarray(table.key)  # fetched ONCE (shared with the header)
    cols = {f"table_{name}": state[:, i]
            for i, name in enumerate(schema.TABLE_COLUMN_NAMES)}
    entries = {
        "table_key": key,
        **cols,
        **{f"stats_{k}": np.asarray(v)
           for k, v in stats._asdict().items()},
        "t0_ns": np.uint64(t0_ns),
        "hash_salt": np.uint64(hash_salt),
        "n_shards": np.uint64(n_shards),
        "capacity": np.uint64(key.shape[0]),
        "schema_version": np.int64(CHECKPOINT_SCHEMA_VERSION),
    }
    buf = io.BytesIO()
    np.savez_compressed(
        buf,
        integrity_crc32=np.uint32(_fold_crc(entries)),
        **entries,
    )
    # atomic_write fsyncs the bytes then the rename, and retains the
    # incumbent GOOD generation at .prev before publishing: a later
    # restore that finds `path` corrupt (torn disk, bit flip) falls
    # back to .prev instead of dying on the only copy.
    durable.atomic_write(path, buf.getvalue(),
                         rotate_prev=prev_path(path))
    return path


def peek_header(path: str | Path) -> dict:
    """The geometry header WITHOUT loading the arrays — salt, shard
    count, capacity, schema version — so servers and the CLI can
    validate (or plan a reshard) before the multi-second JAX boot.
    Pre-header snapshots read as salt 0 / 1 shard; capacity falls back
    to the key column's length.

    A zero-length, truncated, or otherwise unreadable file raises
    :class:`CheckpointCorrupt` (a named ``ValueError``) — previously a
    file torn at create time leaked a raw struct/IndexError through
    the pre-boot validation path, which read as a code bug instead of
    the operational fact it is."""
    path = Path(path)
    fs = durable.get_fs()
    try:
        size = fs.size(path)
    except OSError as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable: {e}") from e
    if size == 0:
        raise CheckpointCorrupt(
            f"checkpoint {path} is empty (0 bytes): a file torn at "
            "create time, not a snapshot")
    try:
        with np.load(io.BytesIO(fs.read_bytes(path))) as z:
            cap = (int(z["capacity"]) if "capacity" in z
                   else int(z["table_key"].shape[0]))
            return {
                "schema_version": int(z["schema_version"]),
                "hash_salt": (int(z["hash_salt"])
                              if "hash_salt" in z else 0),
                "n_shards": int(z["n_shards"]) if "n_shards" in z else 1,
                "capacity": cap,
                "has_crc": "integrity_crc32" in z,
            }
    except CheckpointCorrupt:
        raise
    except _DAMAGE_ERRORS as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is corrupt or truncated "
            f"({size} bytes): {type(e).__name__}: {e}") from e


def peek_salt(path: str | Path) -> int:
    """The hash salt a checkpoint's table was built under, WITHOUT
    loading the arrays — so a server can adopt it before compiling its
    step (pre-salt checkpoints read as 0, the unsalted hash)."""
    return peek_header(path)["hash_salt"]


def load_checkpoint(path: str | Path) -> Checkpoint:
    """Load a snapshot to HOST arrays (placement is the caller's job —
    see :class:`Checkpoint`).  Columns or stats counters added after
    the snapshot was written load zero-filled and are named in the
    ``missing_*`` fields so the caller can apply the right default
    (e.g. ``Engine.restore`` refills byte-bucket credit).

    Integrity (module docstring): every member is decompressed and the
    folded CRC32 recomputed; a mismatch — or any structural damage on
    the way in — raises :class:`CheckpointCorrupt`.  A corrupt file
    can therefore never be silently loaded.  Snapshots predating the
    CRC load with ``crc_checked=False``."""
    path = Path(path)
    fs = durable.get_fs()
    entries: dict[str, np.ndarray] = {}
    stored_crc = None
    try:
        if fs.size(path) == 0:
            raise CheckpointCorrupt(
                f"checkpoint {path} is empty (0 bytes)")
        with np.load(io.BytesIO(fs.read_bytes(path))) as z:
            for name in z.files:
                if name == "integrity_crc32":
                    stored_crc = int(z[name])
                else:
                    entries[name] = np.asarray(z[name])
        if "schema_version" not in entries or "table_key" not in entries:
            raise CheckpointCorrupt(
                f"checkpoint {path} is missing its "
                "schema_version/table_key sections (torn write?)")
    except CheckpointCorrupt:
        raise
    except _DAMAGE_ERRORS as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is corrupt or truncated: "
            f"{type(e).__name__}: {e}") from e
    if stored_crc is not None:
        actual = _fold_crc(entries)
        if actual != stored_crc:
            raise CheckpointCorrupt(
                f"checkpoint {path} failed its integrity check: "
                f"stored CRC32 {stored_crc:#010x} != recomputed "
                f"{actual:#010x} — the bytes decompressed cleanly but "
                "are not the bytes that were written (bit flip or "
                "spliced sections); refusing to resume from garbage")
    version = int(entries["schema_version"])
    if version != CHECKPOINT_SCHEMA_VERSION:
        raise ValueError(
            f"checkpoint schema {version} != {CHECKPOINT_SCHEMA_VERSION}"
        )
    cap = int(entries["table_key"].shape[0])
    state = np.zeros((cap, schema.NUM_TABLE_COLS), np.float32)
    missing = []
    for i, name in enumerate(schema.TABLE_COLUMN_NAMES):
        if f"table_{name}" in entries:
            state[:, i] = entries[f"table_{name}"]
        else:
            missing.append(name)
    missing_stats = []
    stats_vals = {}
    for k in schema.GlobalStats._fields:
        if f"stats_{k}" in entries:
            stats_vals[k] = np.asarray(entries[f"stats_{k}"])
        else:
            # a counter added after the snapshot (e.g. ``evicted``
            # on pre-eviction-era snapshots): zero is the correct
            # resume value for a monotone counter
            stats_vals[k] = np.zeros((2,), np.uint32)
            missing_stats.append(k)
    return Checkpoint(
        table=schema.IpTableState(
            key=np.asarray(entries["table_key"]), state=state),
        stats=schema.GlobalStats(**stats_vals),
        t0_ns=int(entries["t0_ns"]),
        hash_salt=(int(entries["hash_salt"])
                   if "hash_salt" in entries else 0),
        n_shards=(int(entries["n_shards"])
                  if "n_shards" in entries else 1),
        capacity=cap,
        missing_columns=tuple(missing),
        missing_stats=tuple(missing_stats),
        crc_checked=stored_crc is not None,
    )


def load_state(
    path: str | Path,
) -> tuple[schema.IpTableState, schema.GlobalStats, int, int, tuple]:
    """Compatibility shim over :func:`load_checkpoint`: the historical
    5-tuple, with table/stats already on the default device.  The ONE
    jax touch in this module, imported lazily — everything else is
    host-side numpy, which is what lets the supervisor plane and the
    ``fsx crash`` checker drive the real checkpoint protocol on the
    sub-second jax-free import path."""
    import jax

    ck = load_checkpoint(path)
    table = schema.IpTableState(key=jax.device_put(ck.table.key),
                                state=jax.device_put(ck.table.state))
    stats = schema.GlobalStats(
        *(jax.device_put(v) for v in ck.stats))
    return table, stats, ck.t0_ns, ck.hash_salt, ck.missing_columns
