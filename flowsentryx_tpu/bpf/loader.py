"""Raw ``bpf(2)`` syscall loader: the kernel handshake without libbpf.

Creates maps, patches map fds into program relocations, loads programs
through the real in-kernel verifier (surfacing its log on rejection),
executes them against crafted packets via ``BPF_PROG_TEST_RUN``, and
drains ``BPF_MAP_TYPE_RINGBUF`` maps through the mmap consumer protocol.

This is the kernel↔user seam done with the same syscalls libbpf makes —
the reference's intended path was ``bpftool prog load``
(/root/reference/TODO.md:282-289) plus a BCC stub that never ran
(/root/reference/src/fsx_load.py:10-17).  PROG_TEST_RUN is the
SURVEY.md §4 "fake backend": XDP programs run against synthetic frames
with no NIC, no root networking, inside any container whose seccomp
policy admits bpf().

All struct layouts below are the stable kernel uapi ABI (union
bpf_attr), re-derived from the documented field order.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import platform
import struct
from dataclasses import dataclass

from flowsentryx_tpu.bpf.asm import Program

_SYS_BPF = {  # bpf(2) syscall number is per-architecture
    "x86_64": 321,
    "aarch64": 280,
    "riscv64": 280,
    "s390x": 351,
    "ppc64le": 361,
}.get(platform.machine(), 321)
_libc = ctypes.CDLL(None, use_errno=True)

# ---- commands ----
CMD_MAP_CREATE = 0
CMD_MAP_LOOKUP_ELEM = 1
CMD_MAP_UPDATE_ELEM = 2
CMD_MAP_DELETE_ELEM = 3
CMD_MAP_GET_NEXT_KEY = 4
CMD_PROG_LOAD = 5
CMD_OBJ_PIN = 6
CMD_OBJ_GET = 7
CMD_PROG_TEST_RUN = 10

# ---- map types ----
MAP_TYPE_HASH = 1
MAP_TYPE_ARRAY = 2
MAP_TYPE_PERCPU_HASH = 5
MAP_TYPE_PERCPU_ARRAY = 6
MAP_TYPE_LRU_HASH = 9
MAP_TYPE_RINGBUF = 27

# ---- program types ----
PROG_TYPE_SOCKET_FILTER = 1
PROG_TYPE_XDP = 6

# ---- update flags ----
BPF_ANY = 0
BPF_NOEXIST = 1
BPF_EXIST = 2

_PAGE = mmap.PAGESIZE
_RINGBUF_BUSY_BIT = 1 << 31
_RINGBUF_DISCARD_BIT = 1 << 30


class BpfError(OSError):
    pass


class VerifierError(BpfError):
    """PROG_LOAD rejection; carries the verifier log."""

    def __init__(self, errno_: int, log: str):
        super().__init__(errno_, os.strerror(errno_))
        self.log = log

    def __str__(self) -> str:  # pragma: no cover - repr aid
        tail = "\n".join(self.log.strip().splitlines()[-25:])
        return f"{super().__str__()}\nverifier log (tail):\n{tail}"


def _bpf(cmd: int, attr: bytes) -> int:
    buf = ctypes.create_string_buffer(attr, len(attr))
    r = _libc.syscall(_SYS_BPF, cmd, buf, len(attr))
    if r < 0:
        raise BpfError(ctypes.get_errno(), os.strerror(ctypes.get_errno()))
    return r


def bpf_available() -> bool:
    """True when this process may create BPF maps (seccomp/caps allow)."""
    try:
        attr = struct.pack("<IIII", MAP_TYPE_ARRAY, 4, 8, 1) + b"\0" * 112
        fd = _bpf(CMD_MAP_CREATE, attr)
    except BpfError:
        return False
    os.close(fd)
    return True


def n_possible_cpus() -> int:
    """Per-CPU map value arrays are sized by possible CPUs, not online."""
    try:
        txt = open("/sys/devices/system/cpu/possible").read().strip()
        lo, _, hi = txt.partition("-")
        return int(hi or lo) + 1
    except OSError:  # pragma: no cover
        return os.cpu_count() or 1


@dataclass
class Map:
    fd: int
    map_type: int
    key_size: int
    value_size: int
    max_entries: int
    name: str = ""

    @property
    def percpu(self) -> bool:
        return self.map_type in (MAP_TYPE_PERCPU_HASH, MAP_TYPE_PERCPU_ARRAY)

    def _vbuf_size(self) -> int:
        if self.percpu:
            return ((self.value_size + 7) & ~7) * n_possible_cpus()
        return self.value_size

    def lookup(self, key: bytes) -> bytes | None:
        kb = ctypes.create_string_buffer(key, self.key_size)
        vb = ctypes.create_string_buffer(self._vbuf_size())
        attr = struct.pack("<IxxxxQQQ", self.fd, ctypes.addressof(kb),
                           ctypes.addressof(vb), 0) + b"\0" * 96
        try:
            _bpf(CMD_MAP_LOOKUP_ELEM, attr)
        except BpfError as e:
            if e.errno == 2:  # ENOENT
                return None
            raise
        return vb.raw

    def lookup_percpu(self, key: bytes) -> list[bytes]:
        """Per-CPU lookup: one value per possible CPU."""
        raw = self.lookup(key)
        if raw is None:
            return []
        stride = (self.value_size + 7) & ~7
        return [raw[i * stride: i * stride + self.value_size]
                for i in range(n_possible_cpus())]

    def update(self, key: bytes, value: bytes, flags: int = BPF_ANY) -> None:
        kb = ctypes.create_string_buffer(key, self.key_size)
        vb = ctypes.create_string_buffer(value, self._vbuf_size())
        attr = struct.pack("<IxxxxQQQ", self.fd, ctypes.addressof(kb),
                           ctypes.addressof(vb), flags) + b"\0" * 96
        _bpf(CMD_MAP_UPDATE_ELEM, attr)

    def delete(self, key: bytes) -> bool:
        kb = ctypes.create_string_buffer(key, self.key_size)
        attr = struct.pack("<IxxxxQQQ", self.fd, ctypes.addressof(kb), 0, 0) \
            + b"\0" * 96
        try:
            _bpf(CMD_MAP_DELETE_ELEM, attr)
        except BpfError as e:
            if e.errno == 2:
                return False
            raise
        return True

    def keys(self) -> list[bytes]:
        """Iterate all keys via MAP_GET_NEXT_KEY."""
        out: list[bytes] = []
        kb = ctypes.create_string_buffer(self.key_size)
        nb = ctypes.create_string_buffer(self.key_size)
        key_ptr = 0  # NULL: first key
        while True:
            attr = struct.pack("<IxxxxQQQ", self.fd, key_ptr,
                               ctypes.addressof(nb), 0) + b"\0" * 96
            try:
                _bpf(CMD_MAP_GET_NEXT_KEY, attr)
            except BpfError as e:
                if e.errno == 2:
                    return out
                raise
            out.append(nb.raw[:])
            kb = ctypes.create_string_buffer(nb.raw, self.key_size)
            key_ptr = ctypes.addressof(kb)

    def pin(self, path: str) -> None:
        pb = ctypes.create_string_buffer(path.encode())
        attr = struct.pack("<QI", ctypes.addressof(pb), self.fd) + b"\0" * 108
        _bpf(CMD_OBJ_PIN, attr)

    @staticmethod
    def obj_get(path: str) -> int:
        """Open a pinned BPF object (map or prog); returns its fd."""
        return obj_get(path)

    def close(self) -> None:
        if self.fd >= 0:
            os.close(self.fd)
            self.fd = -1


def obj_get(path: str) -> int:
    """Open a pinned BPF object (map or prog) from bpffs; returns fd."""
    pb = ctypes.create_string_buffer(path.encode())
    attr = struct.pack("<QI", ctypes.addressof(pb), 0) + b"\0" * 116
    return _bpf(CMD_OBJ_GET, attr)


def map_create(map_type: int, key_size: int, value_size: int,
               max_entries: int, name: str = "", flags: int = 0) -> Map:
    nm = name.encode()[:15].ljust(16, b"\0")
    attr = struct.pack("<IIIIIII", map_type, key_size, value_size,
                       max_entries, flags, 0, 0) + nm + b"\0" * 84
    fd = _bpf(CMD_MAP_CREATE, attr)
    return Map(fd, map_type, key_size, value_size, max_entries, name)


def prog_load(prog: Program | bytes, prog_type: int = PROG_TYPE_XDP,
              map_fds: dict[str, int] | None = None, license_: str = "GPL",
              log_size: int = 1 << 20, name: str = "") -> int:
    """Load through the verifier; raises VerifierError with the log.

    A :class:`Program` is first run through the IN-REPO static verifier
    (``bpf/verifier.py``), so a generation bug surfaces as a precise
    instruction-level diagnostic instead of a kernel ``EACCES`` — and
    surfaces at all in environments where bpf(2) is unavailable.  Raw
    bytes skip the static pass (no relocation table to interpret);
    ``FSX_SKIP_STATIC_VERIFY=1`` skips it explicitly.
    """
    if isinstance(prog, Program) and \
            os.environ.get("FSX_SKIP_STATIC_VERIFY") != "1":
        from flowsentryx_tpu.bpf import verifier

        verifier.check_program_cached(prog)
    code = prog.pack(map_fds) if isinstance(prog, Program) else prog
    insn_cnt = len(code) // 8
    ib = ctypes.create_string_buffer(code, len(code))
    lb = ctypes.create_string_buffer(license_.encode())
    logb = ctypes.create_string_buffer(log_size)
    nm = (name or getattr(prog, "name", "prog")).encode()[:15].ljust(16, b"\0")
    attr = struct.pack(
        "<IIQQIIQI",
        prog_type, insn_cnt, ctypes.addressof(ib), ctypes.addressof(lb),
        1, log_size, ctypes.addressof(logb), 0,
    ) + struct.pack("<I", 0) + nm + b"\0" * 60
    try:
        return _bpf(CMD_PROG_LOAD, attr)
    except BpfError as e:
        raise VerifierError(e.errno, logb.value.decode(errors="replace")) from None


def prog_test_run(prog_fd: int, data_in: bytes, repeat: int = 1,
                  data_out_size: int = 4096) -> tuple[int, int, bytes]:
    """Returns (retval, duration_ns_mean, data_out)."""
    din = ctypes.create_string_buffer(data_in, len(data_in))
    dout = ctypes.create_string_buffer(data_out_size)
    attr_buf = ctypes.create_string_buffer(
        struct.pack("<IIIIQQII", prog_fd, 0, len(data_in), data_out_size,
                    ctypes.addressof(din), ctypes.addressof(dout),
                    repeat, 0) + b"\0" * 80)
    r = _libc.syscall(_SYS_BPF, CMD_PROG_TEST_RUN, attr_buf, len(attr_buf.raw) - 1)
    if r < 0:
        raise BpfError(ctypes.get_errno(), os.strerror(ctypes.get_errno()))
    _, retval, _, out_sz, _, _, _, duration = struct.unpack(
        "<IIIIQQII", attr_buf.raw[:40])
    return retval, duration, dout.raw[:out_sz]


class RingbufReader:
    """mmap consumer for BPF_MAP_TYPE_RINGBUF (single consumer).

    Layout (kernel ABI): page 0 = consumer pos (we write it), page 1 =
    producer pos (read-only), then the data area mapped twice so records
    never wrap mid-read.  Records carry an 8-byte header: u32 len with
    BUSY/DISCARD bits, u32 pgoff; total stride rounds up to 8.
    """

    def __init__(self, ring_map: Map):
        if ring_map.map_type != MAP_TYPE_RINGBUF:
            raise ValueError("not a ringbuf map")
        self.size = ring_map.max_entries
        self.mask = self.size - 1
        self.cons_mm = mmap.mmap(ring_map.fd, _PAGE, mmap.MAP_SHARED,
                                 mmap.PROT_READ | mmap.PROT_WRITE, offset=0)
        self.prod_mm = mmap.mmap(ring_map.fd, _PAGE + 2 * self.size,
                                 mmap.MAP_SHARED, mmap.PROT_READ,
                                 offset=_PAGE)

    def _consumer_pos(self) -> int:
        return struct.unpack_from("<Q", self.cons_mm, 0)[0]

    def _producer_pos(self) -> int:
        return struct.unpack_from("<Q", self.prod_mm, 0)[0]

    def read(self, max_records: int = 1 << 20) -> list[bytes]:
        out: list[bytes] = []
        pos = self._consumer_pos()
        prod = self._producer_pos()
        while pos < prod and len(out) < max_records:
            off = _PAGE + (pos & self.mask)
            hdr_len = struct.unpack_from("<I", self.prod_mm, off)[0]
            if hdr_len & _RINGBUF_BUSY_BIT:
                break  # producer mid-commit
            rec_len = hdr_len & ~(_RINGBUF_BUSY_BIT | _RINGBUF_DISCARD_BIT)
            if not hdr_len & _RINGBUF_DISCARD_BIT:
                out.append(self.prod_mm[off + 8: off + 8 + rec_len])
            pos += (8 + rec_len + 7) & ~7
        struct.pack_into("<Q", self.cons_mm, 0, pos)
        return out

    def close(self) -> None:
        self.cons_mm.close()
        self.prod_mm.close()
