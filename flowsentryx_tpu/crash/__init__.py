"""``fsx crash`` — crash-consistency model checking over the durable
protocols (the fifth static leg; see ``checker.py`` and docs/CRASH.md).

jax-free by construction: the checker imports only the cluster/core
modules plus numpy, so it rides the same sub-second CI path as the
other static legs.
"""

from .checker import (CrashSchedule, INVARIANTS, Violation,  # noqa: F401
                      explore_scenario, run_crash)
from .simfs import SimFS, Tracer  # noqa: F401
from .world import World  # noqa: F401
