"""The serving engine: drain → batch → TPU step → verdict writeback.

The online loop of BASELINE configs 4/5.  TWO threads:

* the **dispatch thread** (the caller of :meth:`Engine.run`) only polls
  the source and enqueues device steps — JAX dispatch is asynchronous,
  so "dispatch batch N, fill batch N+1" overlaps host fill with device
  compute exactly as before;
* a **sink thread** harvests finished step futures, fetches the compact
  verdict wire (one O(verdict_k) D2H buffer per batch, see
  ``ops/fused.py``), and runs writeback/metrics/``on_reap`` — the fixed
  host cost per sunk batch no longer blocks the dispatch loop, which
  was the host-side ceiling VERDICT r5 flagged.

A bounded handoff queue provides backpressure: ``readback_depth`` caps
how many BATCHES may be dispatched-but-unsunk before the dispatch
thread blocks — a pipe bound, not a readback schedule (scheduling
readback BY depth deferred every verdict by depth × batch-fill time,
the r4 open-loop latency collapse).  The sink thread sinks each batch
the moment its wire is ready, oldest first, and coalesces whatever else
already finished into the same group.  A crash in the sink thread fails
the engine loudly on the next dispatch-iteration; shutdown drains the
queue, then joins.  ``sink_thread=False`` restores the single-thread
loop (readiness-reaped, same semantics — parity is test-pinned); the
default is AUTO — threaded only where the host has ≥3 cores, because
on 1-2 core hosts the extra thread merely contends with dispatch and
XLA's own pool (the ``donate=None`` auto-detect idiom).

The blacklist tolerates the remaining small writeback delay by design —
the kernel limiter stands alone during the gap (fail-open, SURVEY.md
§5.3).

**Device-loop mode** (``device_loop=N`` / ``fsx serve --device-loop``)
replaces the sink thread with the device-PIPELINE worker: the second
thread both LAUNCHES the deep-scan rounds (fused/device_loop.py — on
XLA:CPU the step's scatter custom-calls execute synchronously, so the
launch call blocks for the whole round's compute; putting it on the
worker is what lets staging overlap compute at all) and harvests their
per-slot verdict wires.  The dispatch thread's steady state becomes
poll → stage → upload → submit, with the upload↔compute overlap
measured in ``EngineReport.dispatch["device_loop"]["h2d"]``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, NamedTuple

import jax
# The ONE module-level jax.numpy import for the deep-drain device-side
# concat paths — previously duplicated as function-local imports in
# every branch of the group sink.  Free here: ``import jax`` above has
# already initialized jax.numpy, so there is nothing to defer.
import jax.numpy as jnp
import numpy as np

from flowsentryx_tpu.core import schema
from flowsentryx_tpu.core.config import FsxConfig
from flowsentryx_tpu.engine.arena import DispatchArena
from flowsentryx_tpu.engine.batcher import MicroBatcher
from flowsentryx_tpu.engine import health
from flowsentryx_tpu.engine.metrics import LatencyRecorder, PipelineMetrics
from flowsentryx_tpu.engine.sources import RecordSource
from flowsentryx_tpu.engine.watchdog import DispatchWatchdog
from flowsentryx_tpu.engine.writeback import (
    VerdictSink, decode_verdict_wire, extract_updates,
)
from flowsentryx_tpu.models import get_model
from flowsentryx_tpu.ops import fused, pallas_kernels
from flowsentryx_tpu.sync import tuning
from flowsentryx_tpu.sync.channel import SinkChannel


#: ``Engine(mega_n="auto")`` / ``fsx serve --mega auto``: the largest
#: group size of the adaptive power-of-two coalescing ladder.  8 holds
#: the staged-variant count at three scan artifacts (2/4/8) while
#: already amortizing ~8x of the per-dispatch fixed cost — the
#: measured knee of the mega-tier curves (bench.py; past 8 the tunnel
#: RPC floor is no longer dominant).
MEGA_AUTO_MAX = 8


class EngineReport(NamedTuple):
    batches: int
    records: int
    wall_s: float
    records_per_s: float
    stats: dict
    stages_ms: dict
    blocked_sources: int
    table: dict           # live-table summary (pallas single-pass scan)
    #: Precompact drains at risk of 16-bit kernel-ts unwrap aliasing
    #: (drain-gap > 50 ms; see MicroBatcher.add_precompact).  Always 0
    #: outside compact-emit serving.
    ts_wrap_risk_polls: int = 0
    #: Packets fail-opened because their flow overflowed owner routing
    #: in the sharded step (adversarial hash skew; parallel/step.py
    #: module docstring).  Always 0 single-device.
    route_drop: int = 0
    #: Sharded-ingest summary (per-worker batches/records/seq-gaps and
    #: fill/queue p50/p99) when the source is a sealed-batch fleet
    #: (flowsentryx_tpu/ingest/); None on the inline record path.
    ingest: dict | None = None
    #: Verdict-readback accounting: wire mode and size, compact vs
    #: fallback sink counts, D2H bytes per sunk batch, and sink-thread
    #: occupancy (busy fraction of the run wall; None single-threaded).
    readback: dict | None = None
    #: Dispatch-pipeline accounting: coalescing mode and staged group
    #: sizes, per-group-size dispatch histogram, dispatch rate, bytes
    #: staged through the arena and HOST copies per dispatched batch
    #: (the zero-copy pipeline's invariant: 1.0 on the sealed compact16
    #: path — one shm-slot-view → arena memcpy, then the device_put
    #: boundary), plus the arena geometry.  None before the first run.
    dispatch: dict | None = None
    #: Two-tier escalation accounting (kernel-distilled classifier,
    #: flowsentryx_tpu/distill/): band thresholds plus per-band record
    #: counts — kernel drops / suppressed passes / escalations — and
    #: the derived escalation ratio and kernel-drop Hz.  Filled from a
    #: simulated tier (``Engine(kernel_tier=SimKernelTier(...))`` /
    #: ``fsx serve --sim-kernel-tier``; rootless CI path); a real
    #: deployment reads the same split off the kernel stats map
    #: (``fsx status --pin``: dropped_ml / ml_pass / ml_escalated).
    #: None when no kernel tier fronts the engine.
    escalation: dict | None = None
    #: Cluster gossip accounting (``flowsentryx_tpu/cluster/``): rank,
    #: published/merged blacklist digests and wire/drop counters of the
    #: coordinator-less verdict plane.  None outside cluster serving.
    cluster: dict | None = None
    #: Per-record seal→verdict latency plane (engine/metrics.py
    #: LatencyRecorder): HDR log-bucketed percentiles of the
    #: feature→verdict path (p50/p90/p99/p999/max, µs), the
    #: staged-wait / upload / compute / sink stage decomposition, the
    #: mergeable bucket counts (cluster aggregate, ``fsx status
    #: --engine-report``), and — when serving under ``--slo-us`` — the
    #: budget-miss accounting.  Always measured; None only before the
    #: first run.
    latency: dict | None = None
    #: Explicit health ladder (engine/health.py): HEALTHY /
    #: DEGRADED(reasons) / FAILED, derived from the signals this report
    #: already carries — dead/stalled ingest shards, seq gaps, emit
    #: drops, quarantined poisoned batches, corrupt-slot skips, gossip
    #: TX-drop / RX-gap counters, watchdog trips, ``.prev`` restore
    #: fallbacks.  Aggregated worst-of across ranks by the cluster
    #: supervisor; queryable via ``fsx status --engine-report`` and
    #: alertable via ``fsx monitor --alert-degraded``.
    health: dict | None = None
    #: Live-rebalance audit (cluster/rebalance.py): rows shipped /
    #: adopted / dropped-post-flip, handoffs donated/adopted, refused
    #: streams, staged discards, boot-time foreign-row drops.  None
    #: until the first handoff touches this engine.
    rebalance: dict | None = None
    #: Predictive dispatch governor (engine/predict.py): the burst
    #: estimator's period/duty/confidence, pre-warm hit/miss and
    #: early-flush/hold actuation counters, and the budget-pressure
    #: shed counts (anti-entropy ticks / resyncs deferred).  Merged
    #: across ranks by the supervisor
    #: (``DispatchGovernor.merge_reports``).  None unless serving with
    #: ``predict=True`` (``fsx serve --predict``).
    predict: dict | None = None
    #: Boot-latency accounting (ISSUE 20): per-variant compile vs
    #: cache-hit timings from :meth:`Engine.warm`, the persistent AOT
    #: compile-cache counters (hits / misses / corrupt / version_drift
    #: — engine/compile_cache.py), serving-ready and background-fill
    #: walls, import time (``Engine.boot_import_s``, stamped by the
    #: CLI/runner) and time-to-first-verdict.  Aggregated per rank by
    #: the cluster supervisor and alertable via ``fsx monitor
    #: --alert-cold-boot``.  None until warm() runs.
    boot: dict | None = None


class _InFlight(NamedTuple):
    out: Any            # StepOutput of device futures
    t_enqueue: float    # when the batch's first record entered the batcher
    n_records: int      # valid records in the batch (wire meta row)
    n_chunks: int = 1   # batches in this entry (mega_n for a mega dispatch)
    # latency-plane stamps (engine/metrics.py LatencyRecorder): when
    # the launch section picked the entry up, how long its explicit
    # H2D put took, and the step call's own wall — on synchronously-
    # dispatching backends (XLA:CPU scatter custom-calls) the latter
    # IS the compute time; see EngineReport.latency["compute_is_wall"].
    t_launch: float = 0.0
    put_s: float = 0.0
    launch_s: float = 0.0


class _Uploaded(NamedTuple):
    """One staged-and-uploaded ring slot awaiting its round."""

    dev: Any            # device buffer ([chunks, B+1, words])
    t_enqueue: float    # oldest member batch's first-record arrival
    n_records: int
    put_s: float        # the slot's explicit H2D wall


class Engine:
    """Owns the device state (table/stats/params) and runs the loop.

    ``donate`` defaults to the backend capability; with donation the
    table updates in place in HBM (no 40 MB copy per batch).
    ``readback_depth`` is how many batches may be in flight before the
    oldest verdicts are fetched and sunk (``None`` = the config's
    ``BatchConfig.readback_depth``).

    ``device_loop`` (0 = off) is the drain-ring depth: N staged ring
    slots — one top-rung ``mega_n`` group each — consumed by ONE
    deep-scan dispatch per host round-trip, with the next round's
    slots uploading while the current one computes (module docstring;
    requires mega grouping and ``verdict_k >= 1``; ``readback_depth``
    must cover one round — the config default is auto-raised, an
    explicit smaller value refused).

    ``audit`` (``None`` = on when ``FSX_AUDIT=1``) statically audits
    the serving step's graph contracts at boot — dtypes, donation
    aliasing, transfer budget, retrace stability, collectives
    (:mod:`flowsentryx_tpu.audit`) — and raises rather than serve on a
    violated contract.  Results are cached per staged shape, so a
    fleet of engines in one process pays the audit trace once.

    The engine's own host↔device boundary is EXPLICIT: batches enter
    via ``jax.device_put`` and results leave via ``jax.device_get``,
    so tests can run the whole loop under
    ``jax.transfer_guard("disallow")`` and any *implicit* transfer that
    sneaks into the hot path fails loudly in CI.
    """

    def __init__(
        self,
        cfg: FsxConfig,
        source: RecordSource,
        sink: VerdictSink,
        params: Any | None = None,
        donate: bool | None = None,
        readback_depth: int | None = None,
        t0_ns: int | None = None,
        mesh: Any | None = None,
        wire: str | None = None,
        mega_n: int | str = 0,
        mega_auto: bool = False,
        device_loop: int = 0,
        sink_thread: bool | None = None,
        audit: bool | None = None,
        kernel_tier: Any | None = None,
        gossip: Any | None = None,
        slo_us: int = 0,
        watchdog_s: float | None = None,
        predict: bool = False,
        compile_cache: Any | None = None,
    ):
        #: Boot-latency anchor: everything in EngineReport.boot —
        #: serving-ready, background-fill-done, time-to-first-verdict
        #: — is measured from construction start.
        self._boot_t0 = time.perf_counter()
        self.cfg = cfg
        self.source = source
        self.sink = sink
        #: Cluster verdict-gossip plane (cluster/gossip.py GossipPlane
        #: protocol: ``publish(upd, now)`` from the sink section,
        #: ``tick()`` from the dispatch thread, ``report() -> dict``).
        #: None = single-engine serving, the byte-identical baseline.
        self.gossip = gossip
        #: Simulated kernel tier (distill.SimKernelTier protocol:
        #: ``filter(records) -> records`` + ``report() -> dict``): band-
        #: splits drained records BEFORE the batcher, exactly where the
        #: real XDP stage splits them before the ringbuf.  Record-path
        #: only — sealed-ingest workers and precompact rings deliver
        #: records the tier cannot rescore (quantized / already sealed).
        self.kernel_tier = kernel_tier
        if kernel_tier is not None:
            if getattr(source, "provides_sealed", False):
                raise ValueError(
                    "kernel_tier needs the inline record path; sealed-"
                    "batch ingest bypasses the record stream (run the "
                    "real kernel tier via fsx distill --pin instead)")
            if getattr(source, "precompact", False):
                raise ValueError(
                    "kernel_tier cannot rescore a compact-emit ring: "
                    "records arrive kernel-quantized; the distilled "
                    "bands are defined on raw u32 features")
        #: Compact-verdict-wire slots (cfg.batch.verdict_k; 0 = the
        #: legacy full [B] fetch per batch).
        self.verdict_k = cfg.batch.verdict_k
        #: Latency-budget serving mode (``fsx serve --slo-us N``): the
        #: feature→verdict budget, µs, that the coalescing ladder, the
        #: device-loop round sizer and the batcher deadline flush are
        #: bounded by (docs/ENGINE.md §latency).  0 — the default — is
        #: the throughput-tuned engine, BIT-IDENTICAL to every prior
        #: PR (test-pinned like every other mode flag): no EWMA
        #: bookkeeping, no policy checks on the hot path.
        self.slo_us = int(slo_us)
        if self.slo_us < 0:
            raise ValueError(f"slo_us must be >= 0, got {slo_us}")
        self._slo_budget_s = self.slo_us * 1e-6
        #: Warm-measured per-group-size step-time EWMA (seconds), keyed
        #: by dispatched chunk count (1 and each ladder rung); a ring
        #: ROUND keys as the NEGATED round size (a ``device_loop=1``
        #: round spans exactly the top rung's chunk count, and the
        #: round wall includes uploads+reap — sharing the key would
        #: cross-contaminate the two estimates).  Seeded by
        #: :meth:`warm`'s timed second pass when SLO mode is on;
        #: refined online by the launch section whenever a launch call
        #: absorbed its compute (synchronous backends).  The
        #: deadline-aware policy reads it advisorily — a stale
        #: estimate can only mis-size a group, never corrupt state.
        self._rung_ewma_s: dict[int, float] = {}
        #: Warm-seed floors for the NEGATED ring-round keys: the seed
        #: is the only measurement whose wall covers uploads AND reap,
        #: so :meth:`_note_round_s` may refine the round EWMA upward
        #: but never below it (the decaying-optimistic-estimate
        #: hazard PR 11 documented).  Written by :meth:`warm` only.
        self._round_floor_s: dict[int, float] = {}
        #: Per-record seal→verdict latency plane (always on; the sink
        #: section is its single writer — sync/contracts.py).
        self._lat = LatencyRecorder()
        #: Run the verdict sink on a dedicated thread (module
        #: docstring); False = single-thread readiness reaping.
        #: None = auto, the ``donate=None`` idiom: a sink thread needs
        #: a core to run on — on 1-2 core hosts (CI containers) it just
        #: contends with the dispatch thread and XLA's own pool
        #: (measured: saturated drain ~5-25 % slower), so auto enables
        #: it only where the host has cores to spare.
        if sink_thread is None:
            import os

            try:
                # affinity, not cpu_count: a CI container pinned to 2
                # CPUs of a 64-core host must read as 2, or auto lands
                # in exactly the contention regime it exists to avoid
                n_cpus = len(os.sched_getaffinity(0))
            except AttributeError:  # non-Linux
                n_cpus = os.cpu_count() or 1
            sink_thread = n_cpus >= 3
        self.sink_thread = bool(sink_thread)
        spec = get_model(cfg.model.name)
        self.params = params if params is not None else spec.init()
        # Mesh spanning >1 device: serve through the IP-hash-sharded
        # multi-device step (parallel/step.py) — state rows live
        # sharded across the mesh, the wire batch enters replicated.
        self.mesh = mesh if mesh is not None and mesh.devices.size > 1 else None
        # The wire batch's device placement, made EXPLICIT (class
        # docstring): replicated over the mesh when sharded, default
        # device otherwise.  None = plain device_put.
        if self.mesh is not None:
            from flowsentryx_tpu.parallel import layout as par_layout

            # derived from the declarative partition rules — the same
            # table the shard_map specs and checkpoint restore use
            self._in_sharding = par_layout.replicated(self.mesh)
        else:
            self._in_sharding = None
        # Params go to the device ONCE at boot.  A numpy artifact
        # (load_artifact .npz leaves) passed straight through otherwise
        # re-crosses the host->device link on EVERY dispatch — eight
        # silent H2D transfers per batch of pure overhead.
        self.params = jax.tree.map(self._put, self.params)
        # A compact-emit data plane (fsxd --compact) delivers records
        # the KERNEL already quantized to the minifloat wire: the
        # engine must speak compact16/minifloat end to end, whatever
        # was requested.
        self.precompact = bool(getattr(source, "precompact", False))
        if self.precompact:
            wire = schema.WIRE_COMPACT16
        elif wire is None:
            # Default wire: compact16 only when it is bit-exact (the
            # artifact exposes an input observer, so the wire carries
            # the model's own quantization); raw48 otherwise.  A model
            # without an observer must not be silently degraded to
            # minifloat-quantized features by a constructor default —
            # callers opt into that by passing wire="compact16".
            wire = (schema.WIRE_COMPACT16
                    if hasattr(self.params, "in_scale")
                    else schema.WIRE_RAW48)
        self.wire = wire
        # compact16 quantizes features on the way into the batcher with
        # the model's own input observer when the artifact exposes one
        # (bit-exact scores vs raw48 for identity-transform artifacts;
        # ±1 output quant step for log1p ones), else the minifloat
        # fallback (≤6.25 % per-feature error) — announced, since it
        # changes borderline scores vs the raw48 wire.
        if self.precompact:
            quant = dict(feat_mode="minifloat")
            if hasattr(self.params, "in_scale"):
                import sys

                print(
                    "fsx engine: compact-emit data plane delivers "
                    "kernel-quantized minifloat features (<=6.25% "
                    "relative error); the artifact's own input observer "
                    "is bypassed. Serve a 48B plane for bit-exact "
                    "model-mode quantization.",
                    file=sys.stderr,
                )
        elif wire == schema.WIRE_COMPACT16:
            quant = schema.wire_quant_for(self.params)
        else:
            quant = None
        if (not self.precompact and quant is not None
                and quant.get("feat_mode") == "minifloat"):
            import sys

            print(
                "fsx engine: params expose no input observer; compact16 "
                "wire uses minifloat feature quantization (<=6.25% "
                "relative error). Pass wire='raw48' for full fidelity.",
                file=sys.stderr,
            )
        if self.mesh is not None:
            from flowsentryx_tpu import parallel as par

            if wire == schema.WIRE_COMPACT16:
                self.step = par.make_sharded_compact_step(
                    cfg, spec.classify_batch, self.mesh, donate=donate,
                    **quant,
                )
            else:
                self.step = par.make_sharded_raw_step(
                    cfg, spec.classify_batch, self.mesh, donate=donate
                )
            self.table = par.make_sharded_table(cfg, self.mesh)
        elif wire == schema.WIRE_COMPACT16:
            self.step = fused.make_jitted_compact_step(
                cfg, spec.classify_batch, donate=donate, **quant
            )
            self.table = jax.device_put(schema.make_table(cfg.table.capacity))
        else:
            self.step = fused.make_jitted_raw_step(
                cfg, spec.classify_batch, donate=donate
            )
            self.table = jax.device_put(schema.make_table(cfg.table.capacity))
        # _put, not bare device_put: sharded engines need the stats
        # replicated OVER THE MESH from boot — committed to device 0
        # they'd be implicitly resharded (a D2D transfer) on the first
        # dispatch, which the transfer-guard contract forbids.
        self.stats = self._put(schema.make_stats())
        # None = the config's pipe depth (BatchConfig.readback_depth,
        # validated >= 1 at construction); an explicit int overrides.
        # The explicitness is remembered: a device-loop engine may
        # auto-raise a config-default depth to cover one ring round but
        # must REFUSE an explicit depth that can't.
        self._depth_explicit = readback_depth is not None
        if readback_depth is None:
            readback_depth = cfg.batch.readback_depth
        self.readback_depth = readback_depth
        # Mega-dispatch (SURVEY.md §7.4.1 brought into SERVING): when
        # the source backlog holds ≥ a staged group size of sealed
        # batches, they go to the device as ONE lax.scan dispatch — the
        # fixed per-dispatch cost (the tunneled runtime's RPC floor
        # above all) is paid once per group instead of per batch.
        # Purely backlog-triggered: the moment a poll comes back short
        # the pending batches dispatch through the largest staged group
        # they still fill (adaptive mode) or singly, so low-load
        # latency behavior is unchanged.
        #
        # ``mega_n="auto"`` (or ``mega_auto=True`` with an explicit
        # cap) = ADAPTIVE coalescing: stage one megastep per
        # power-of-two group size ≤ the cap (fused.pow2_group_sizes)
        # and let each iteration dispatch the largest rung the
        # instantaneous backlog fills — fixed-``mega_n`` amortization
        # was all-or-nothing (backlog < mega_n ⇒ every batch paid the
        # full per-dispatch tax as a single).
        if mega_n == "auto":
            mega_auto = True
            mega_n = MEGA_AUTO_MAX
        elif isinstance(mega_n, str):
            raise ValueError(
                f"mega_n must be an int or 'auto', got {mega_n!r}")
        self.mega_auto = bool(mega_auto)
        self.mega_n = int(mega_n)
        if self.mega_n < 0:
            raise ValueError(f"mega_n must be >= 0, got {mega_n}")
        if self.mega_auto and self.mega_n < 2:
            raise ValueError(
                "adaptive coalescing needs a group-size cap >= 2 "
                f"(got mega_n={self.mega_n})")
        if self.mega_auto:
            mega_sizes = fused.pow2_group_sizes(self.mega_n)
        elif self.mega_n > 0:
            mega_sizes = (self.mega_n,)
        else:
            mega_sizes = ()
        #: Staged group sizes, largest first — the coalescing ladder.
        self._mega_sizes: tuple[int, ...] = mega_sizes
        self.megasteps: dict[int, Any] = {}
        self.megastep = None
        if mega_sizes:
            if wire != schema.WIRE_COMPACT16:
                raise ValueError("mega_n requires the compact16 wire")
            if self.mesh is not None:
                from flowsentryx_tpu import parallel as par

                self.megasteps = par.make_sharded_compact_megastep_family(
                    cfg, spec.classify_batch, self.mesh, mega_sizes,
                    donate=donate, **quant,
                )
            else:
                self.megasteps = fused.make_compact_megastep_family(
                    cfg, spec.classify_batch, mega_sizes, donate=donate,
                    **quant,
                )
            self.megastep = self.megasteps[max(self.megasteps)]
        # -- device-resident drain ring (fused/device_loop.py) ----------
        # ``device_loop=R`` makes the steady-state loop pull-based from
        # the device: R staged ring slots (one top-rung group each) go
        # to the device as ONE deep-scan dispatch carrying table/stats
        # across all R*C batches, while the NEXT round's slots upload
        # during the current round's compute (double-buffered H2D).
        # 0 = today's per-group dispatch (the fallback and the parity
        # baseline — short backlogs always drain through it).
        self.ring = int(device_loop)
        if self.ring < 0:
            raise ValueError(
                f"device_loop must be >= 0, got {device_loop}")
        self._ring_chunks = 0
        self.ring_step = None
        if self.ring:
            if not self.megasteps:
                raise ValueError(
                    "device_loop requires mega grouping (mega_n >= 2 or "
                    "'auto'): each ring slot carries one top-rung group")
            if self.verdict_k < 1:
                raise ValueError(
                    "device_loop requires the compact verdict wire "
                    "(verdict_k >= 1): the ring's steady-state readback "
                    "is one [ring, 2K+4] buffer per round")
            self._ring_chunks = max(self.megasteps)
            round_b = self.ring * self._ring_chunks
            if self._depth_explicit and readback_depth < round_b:
                raise ValueError(
                    f"device_loop={self.ring} with readback_depth="
                    f"{readback_depth} < {round_b} (one ring round of "
                    f"{self.ring}x{self._ring_chunks} batches): the pipe "
                    "could never keep a round in flight while the next "
                    "stages, so every H2D upload would serialize behind "
                    "the drain — raise readback_depth to >= "
                    f"{round_b} or shrink the ring")
            if not self._depth_explicit:
                # a config-default depth grows to cover one full round,
                # or the ring would be refused for every default
                # config; an EXPLICIT depth below the round is refused
                # above instead of silently inflated.
                readback_depth = max(readback_depth, round_b)
                self.readback_depth = readback_depth
            from flowsentryx_tpu.fused import device_loop as dl

            if self.mesh is not None:
                self.ring_step = dl.make_sharded_compact_device_loop(
                    cfg, spec.classify_batch, self.mesh, self.ring,
                    self._ring_chunks, donate=donate, **quant)
            else:
                self.ring_step = dl.make_compact_device_loop(
                    cfg, spec.classify_batch, self.ring,
                    self._ring_chunks, donate=donate, **quant)
        # Static graph audit at boot (class docstring): prove the
        # serving variant's dtype/donation/transfer/retrace/collective
        # contracts on the staged jaxpr + executable BEFORE the first
        # batch, and refuse to serve on a violation.  Flag-gated (the
        # audit trace+compile costs seconds) and cached per shape.
        if audit is None:
            import os as _os

            audit = _os.environ.get("FSX_AUDIT", "").lower() in (
                "1", "true", "on")
        if audit:
            from flowsentryx_tpu.audit import boot_audit

            # every staged group size is its own compiled scan
            # artifact: each rung of the adaptive ladder is audited
            # (and the boot cache keyed) individually
            boot_audit(cfg, wire=self.wire, mesh=self.mesh,
                       mega_n=self.mega_n if self._mega_sizes else 0,
                       mega_sizes=self._mega_sizes or None,
                       device_loop=self.ring,
                       params=self.params)
        #: Sealed-but-undispatched (raw, t_seal) group candidates.
        self._pending: list[tuple[np.ndarray, float]] = []
        # Sealed-batch sources (flowsentryx_tpu/ingest/ShardedIngest)
        # deliver finished wire buffers instead of raw records: the run
        # loop switches to dequeue → dispatch → reap, and the worker
        # fleet is spawned HERE, after the engine has fixed the wire and
        # quantizer — the workers must seal with exactly the engine's
        # choices or the N=0 inline path and the sharded path would
        # score differently.
        self.sealed = bool(getattr(source, "provides_sealed", False))
        if self.sealed:
            source.start(cfg.batch, self.wire, quant)
        # -- dispatch arena (engine/arena.py) ---------------------------
        # Page-aligned staging rows for the zero-copy pipeline: sealed
        # sources memcpy shm-slot VIEWS straight into arena rows (the
        # ONE host copy) and mega groups assemble contiguously in one
        # slot, so the device_put slice needs no np.stack.  Slot count
        # follows the reuse safety rule (arena module docstring):
        # readback_depth + 2 guarantees every batch staged in a slot is
        # SUNK before the slot recycles.  Inline engines without
        # grouping never stage, so they skip the allocation.
        words = (schema.COMPACT_RECORD_WORDS
                 if self.wire == schema.WIRE_COMPACT16
                 else schema.RECORD_WORDS)
        if self.sealed or self.megasteps:
            group_max = max(self.megasteps) if self.megasteps else 1
            # Slot count: the plain readback_depth+2 rule assumes ONE
            # in-flight device buffer; a device-loop ring holds up to
            # ``ring`` uploaded slices in flight per unsunk round, so
            # the bound is recomputed (ring_safe_slots docstring has
            # the proof — the non-ring rule is its ring=chunks=1 case).
            slots = DispatchArena.ring_safe_slots(
                readback_depth, self.ring or 1)
            self._arena = DispatchArena(
                slots=slots,
                # sealed singles still batch their queue drains: give
                # the slot a few rows even when no megastep is staged
                group_max=max(group_max, 4) if self.sealed else group_max,
                max_batch=cfg.batch.max_batch,
                words=words,
            )
        else:
            self._arena = None
        # dispatch-block accounting (EngineReport.dispatch)
        self._group_hist: dict[int, int] = {}
        self._dispatch_calls = 0
        self._dispatched_chunks = 0
        self._staged_batches = 0
        self._staged_bytes = 0
        # device-loop accounting (EngineReport.dispatch["device_loop"])
        self._ring_rounds = 0
        self._ring_partial_slots = 0
        self._h2d_put_s = 0.0
        self._h2d_overlap_s = 0.0
        self._h2d_puts = 0
        self._h2d_puts_overlapped = 0
        #: How many sealed-but-undispatched batches the loops
        #: accumulate before the coalescing policy must fire: one ring
        #: round in device-loop mode, one top-rung group otherwise.
        self._pending_cap = ((self.ring * self._ring_chunks)
                             if self.ring else self.mega_n)
        # A wire buffer may be reused only after its batch is off the
        # in-flight queue (or, for a pending group member, dispatched):
        # keep more buffers than in-flight batches + the pending group
        # (a whole ring round in device-loop mode).
        self.batcher = MicroBatcher(
            cfg.batch, t0_ns=t0_ns or 0,
            n_buffers=readback_depth + 2 + self._pending_cap,
            wire=wire, quant=quant,
        )
        # t0 anchors the device clock (f32 seconds).  None = auto: take
        # the first record's kernel timestamp, which is the documented
        # contract of decode_raw (a boot-relative bpf_ktime_get_ns can
        # be ~1e6 s, where f32 spacing is far too coarse for 1 s
        # windows — anchoring near the stream start keeps µs precision).
        self._t0_auto = t0_ns is None
        # An explicit t0 must also anchor the sink (the auto-t0 and
        # restore() paths already do this); otherwise a ShmVerdictSink
        # stays at t0_ns=0 and emits until_ns values ~t0 in the past,
        # so the daemon/kernel blacklist never fires.
        if t0_ns is not None and hasattr(sink, "t0_ns"):
            sink.t0_ns = t0_ns
        self.metrics = PipelineMetrics()
        #: Optional per-batch reap hook ``(n_records, t_done) -> None``,
        #: called after a batch's verdicts are fetched AND sunk.  Batches
        #: are reaped in record-FIFO order, so a caller pairing this with
        #: :class:`~flowsentryx_tpu.engine.sources.PacedSource` can pop
        #: ``n_records`` scheduled arrival times per call and obtain
        #: exact per-record arrival→verdict-sunk latencies (the latency
        #: bench's measurement; batch-level ``metrics.e2e`` conflates
        #: queueing with readback-group policy, which is fine for
        #: throughput mode but not for judging the 1 ms budget).
        self.on_reap = None
        self._inflight: list[_InFlight] = []
        self._blocked: set[int] = set()
        self._device_now = 0.0  # newest stream time seen in reaped outputs
        self._route_drop = 0    # routing-overflow fail-opens (sharded step)
        # ready-reap coalescing (see _reap_ready): each sink has a fixed
        # host cost, so cap the sink rate when the pipe is shallow —
        # but never above half the flush deadline, which is the
        # configured latency budget (a fixed floor would silently
        # override small deadline_us values).  Only the single-thread
        # mode needs this: a threaded sink's host cost doesn't block
        # dispatch, and its worker coalesces naturally when behind.
        self._last_sink_t = 0.0
        self._min_sink_gap_s = min(tuning.MIN_SINK_GAP_S,
                                   cfg.batch.deadline_us * 1e-6 / 2)
        # -- sink-thread machinery (module docstring) -------------------
        # The SinkChannel (sync/channel.py) is the ONLY shared state
        # between the dispatch and sink/pipeline threads: the handoff
        # queue, the dispatched-but-unsunk BATCH count backpressure
        # waits on (chunks, not entries — a mega entry is mega_n
        # batches), the stop flag, and the crash slot a worker death
        # lands in atomically with its accounting.  _check_sink
        # surfaces that crash loudly on the next dispatch-thread reap.
        self._chan = SinkChannel("sink thread")
        self._sink_active = False
        self._sink_thread_obj: threading.Thread | None = None
        # Device-loop mode replaces the post-launch sink thread with
        # the device-PIPELINE worker: the queue carries pre-launch
        # submissions (the jit call itself runs on the worker), so the
        # dispatch thread's steady state is pure stage→upload→submit.
        # On backends whose step graphs execute synchronously at
        # dispatch (XLA:CPU runs the step's scatter custom-calls
        # inline), this is what makes "upload slot i+1 while round i
        # computes" REAL rather than aspirational — the launch blocks
        # the worker, not the stager.
        self._pipe_active = False
        # readback accounting (EngineReport.readback)
        self._d2h_bytes = 0
        self._sink_compact = 0
        self._sink_fallback = 0
        self._sunk_batches = 0
        # live artifact hot-swap (watch_artifact / hot_swap)
        self._watch_path: str | None = None
        self._watch_mtime = 0
        self._watch_next = 0.0
        self._hot_swaps = 0
        # -- robustness plane (PR 13; engine/health.py derives the
        # -- ladder, engine/watchdog.py owns the no-progress detector)
        #: restores that fell back to the retained .prev generation
        #: (a DEGRADED reason: flow memory resumed one generation
        #: stale).  Written only in the quiescent restore().
        self._restore_fallbacks = 0
        #: Live-rebalance audit counters (cluster/rebalance.py drives
        #: the quiescent span methods below; engine/health.py folds
        #: the loss-shaped ones — adopt_dropped, staged_discarded,
        #: foreign_dropped — into the DEGRADED ladder).  Written only
        #: between run chunks, read by _build_report: single-thread.
        self._rebalance: dict[str, int] = {}
        #: Dispatch watchdog (engine/watchdog.py): trips when batches
        #: are in flight but nothing sinks for the stall bound —
        #: dumping per-thread stacks and surfacing loudly instead of
        #: letting a drain hang forever.  ``watchdog_s=0`` disables;
        #: None = sync/tuning.py WATCHDOG_STALL_S.  Pure observer on
        #: the null path: it never changes results, only refuses to
        #: hang (test-pinned byte-identical at defaults).
        if watchdog_s is None:
            watchdog_s = tuning.WATCHDOG_STALL_S
        self._watchdog = DispatchWatchdog(watchdog_s)
        #: Predictive dispatch governor (``fsx serve --predict``;
        #: engine/predict.py): forecasts the arrival process from the
        #: per-poll stamps this thread already takes and steers the
        #: flush/pre-warm/shed decisions AROUND the hot path — every
        #: hook below is gated ``if self._gov is not None``, so
        #: ``predict=False`` (the default) stays bit-identical to the
        #: reactive engine (test-pinned like every mode flag).
        #: Dispatch-thread-only state (sync/contracts.py).
        if predict and not self.slo_us:
            # the governor's every actuation is phrased in budget
            # headroom — without --slo-us there is no budget to
            # pre-size against or shed under, only silent no-ops
            raise ValueError(
                "predict=True requires slo_us > 0: the governor "
                "actuates the latency-budget machinery (pre-sizing, "
                "early flush, pressure shedding are all phrased in "
                "budget headroom)")
        if predict:
            from flowsentryx_tpu.engine.predict import DispatchGovernor

            self._gov = DispatchGovernor(
                rung_sizes=self._mega_sizes,
                batch_records=cfg.batch.max_batch)
        else:
            self._gov = None
        # lazily-built masked zero batch for pre-warm dispatches
        # (one allocation, reused; _prewarm_dispatch)
        self._warm_buf: np.ndarray | None = None
        # -- boot-latency engine (ISSUE 20) -----------------------------
        #: Persistent AOT executable store (engine/compile_cache.py):
        #: staged variants lower().compile() once, serialize to disk,
        #: and later boots of the same staged shape (the audit boot
        #: cache's signature discipline, core/signature.py) reload in
        #: tens of ms.  None = no cache (every warm compiles, exactly
        #: the historical path).  Fail-open throughout: the jit
        #: wrappers below stay captured as the fallback, so a cold or
        #: corrupt cache only ever costs the compile it always cost.
        if compile_cache is not None:
            from flowsentryx_tpu.core.signature import staging_signature
            from flowsentryx_tpu.engine.compile_cache import CompileCache

            if isinstance(compile_cache, CompileCache):
                self._cache = compile_cache
            else:
                sig = staging_signature(
                    cfg, wire=self.wire,
                    mesh_devices=(int(self.mesh.devices.size)
                                  if self.mesh is not None else 1),
                    mega_sizes=self._mega_sizes, device_loop=self.ring,
                    params=self.params,
                    donate=(fused.donation_supported()
                            if donate is None else bool(donate)))
                self._cache = CompileCache(compile_cache, sig)
        else:
            self._cache = None
        #: Pristine jit wrappers + abstract arg specs per staged
        #: variant, captured HERE (quiescent, the live device state in
        #: scope) so AOT lowering — including on the background warm
        #: fill thread — never touches launch-section fields.  Keys:
        #: ("single",), ("mega", g), ("ring",).
        self._aot_specs = self._capture_aot_specs(words)
        #: The READY rung set: the rungs of the coalescing ladder whose
        #: executables are installed and safe to dispatch without an
        #: inline compile.  Defaults to the whole ladder (legacy warm
        #: and un-warmed engines: byte-identical behavior); a tiered
        #: warm shrinks it to the serving tier and the background fill
        #: re-grows it rung by rung — grouping is dispatch-granularity
        #: only, so the SHAPES dispatched change but the results never
        #: do (the PR 5 invariant the partial-ladder parity test pins).
        self._ready_sizes: tuple[int, ...] = self._mega_sizes
        #: Whether the deep-scan ring may engage (same tiered-warm
        #: story: rings not yet filled degrade to top-rung megastep
        #: slot flushes, byte-identical by construction).
        self._ring_ready: bool = bool(self.ring)
        #: Background warm-fill plan + thread (warm(tiered=True)).
        self._warm_plan: tuple = ()
        self._warm_thread_obj: threading.Thread | None = None
        #: Boot-latency block (EngineReport.boot); built by warm(),
        #: extended by the warm fill thread via whole-dict rebinds.
        self._boot: dict | None = None
        #: Wall from construction to the FIRST real verdict sunk
        #: (stamped in the sink section; masked warm batches carry no
        #: records and never trip it).
        self._first_verdict_s: float | None = None
        #: Engine-stack import wall, stamped by the CLI/runner that
        #: measured it (the engine cannot observe its own import).
        self.boot_import_s = 0.0

    def _capture_aot_specs(self, words: int) -> dict:
        """Abstract (ShapeDtypeStruct) argument specs and the pristine
        jit wrapper for every staged variant — the inputs to
        ``wrapper.lower(*specs).compile()``.  Shardings are taken from
        the LIVE arrays (mesh engines lower against the real sharded
        layout; replicated wire entry), so the AOT executable is the
        same artifact the jit path would build."""

        def _abs(t):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype,
                    sharding=getattr(a, "sharding", None)), t)

        state = (_abs(self.table), _abs(self.stats), _abs(self.params))
        b = self.cfg.batch.max_batch

        def _wire(shape):
            return jax.ShapeDtypeStruct(shape, np.uint32,
                                        sharding=self._in_sharding)

        specs: dict[tuple, tuple] = {
            ("single",): (self.step, (*state, _wire((b + 1, words)))),
        }
        for g, fn in self.megasteps.items():
            specs[("mega", g)] = (fn, (*state,
                                       _wire((g, b + 1, words))))
        if self.ring:
            slot = _wire((self._ring_chunks, b + 1, words))
            specs[("ring",)] = (self.ring_step,
                                (*state, *([slot] * self.ring)))
        return specs

    # -- pipeline stages ----------------------------------------------------

    def _put(self, a):
        """EXPLICIT H2D: wire buffers/params cross to the device via
        device_put (replicated over the mesh when sharded), never as
        implicit jit-argument transfers — the whole loop runs clean
        under ``jax.transfer_guard("disallow")``."""
        return (jax.device_put(a, self._in_sharding)
                if self._in_sharding is not None else jax.device_put(a))

    def _note_step_s(self, key: int, dt: float, out: Any) -> None:
        """Online refinement of the per-rung step-time EWMA (SLO mode
        only — the default path records nothing).  Only launches whose
        call absorbed the compute count: the output being READY the
        moment the call returns proves the backend executed
        synchronously (XLA:CPU's scatter custom-calls), so ``dt`` is a
        true step time; on async backends the call is a cheap enqueue
        and the warm-pass seed stands unrefined."""
        if not self._slo_budget_s or not self._out_ready(out):
            return
        prev = self._rung_ewma_s.get(key)
        self._rung_ewma_s[key] = (
            dt if prev is None
            else prev + tuning.SLO_EWMA_ALPHA * (dt - prev))

    def _note_round_s(self, key: int, dt: float, out: Any) -> None:
        """Guarded online refinement of the ring-ROUND EWMA key (the
        PR 11 follow-up: rounds previously had NO refinement at all).

        Three guards keep the hazard documented in PR 11 closed:
        launch-absorbed rounds only (the readiness proof of
        :meth:`_note_step_s`); ``dt`` must already carry the round's
        upload wall on top of the launch wall (the caller sums them —
        the reap is still invisible to a launch-side observation); and
        the refined value is FLOORED at the warm seed, which is the
        only measurement that saw uploads AND reap.  Net effect: a
        round that measures slower than the seed raises the estimate
        (a throttled host degrades to smaller rungs sooner), while a
        round that measures faster — necessarily missing cost the
        seed saw — leaves the conservative seed standing.  The key is
        never CREATED here: warm() owns the seed, and an unseeded
        engine self-warms at run() start."""
        if not self._slo_budget_s or not self._out_ready(out):
            return
        prev = self._rung_ewma_s.get(key)
        if prev is None:
            return
        floor = self._round_floor_s.get(key, prev)
        self._rung_ewma_s[key] = max(
            prev + tuning.SLO_EWMA_ALPHA * (dt - prev), floor)

    def _launch_single(self, raw: Any, t_enqueue: float,
                       n_records: int) -> _InFlight:
        """The step call + accounting of a single-batch dispatch (runs
        on the dispatch thread directly, or on the device-pipeline
        worker in device-loop mode)."""
        with self.metrics.dispatch.time():
            t_l = time.perf_counter()
            dev = self._put(raw)
            t_p = time.perf_counter()
            self.table, self.stats, out = self.step(
                self.table, self.stats, self.params, dev
            )
            t_d = time.perf_counter()
        self._dispatch_calls += 1
        self._dispatched_chunks += 1
        self._group_hist[1] = self._group_hist.get(1, 0) + 1
        self._note_step_s(1, t_d - t_p, out)
        return _InFlight(out, t_enqueue, n_records,
                         t_launch=t_l, put_s=t_p - t_l,
                         launch_s=t_d - t_p)

    def _dispatch(self, raw: np.ndarray, t_enqueue: float) -> None:
        n_records = int(raw[self.cfg.batch.max_batch, 0])
        if self._pipe_active:
            self._submit("single", raw, t_enqueue, n_records, 1)
            return
        self._inflight.append(self._launch_single(raw, t_enqueue,
                                                  n_records))

    def _launch_group(self, raws: Any, t_enqueue: float, n_records: int,
                      on_device: bool = False,
                      put_s: float = 0.0) -> _InFlight:
        """The megastep call + accounting of a group dispatch.
        ``on_device=True`` skips the H2D put — the buffer is an
        already-uploaded ring slot (``put_s`` then carries the upload
        wall :meth:`_upload_slot` already paid for it)."""
        g = int(raws.shape[0])
        with self.metrics.dispatch.time():
            t_l = time.perf_counter()
            dev = raws if on_device else self._put(raws)
            t_p = time.perf_counter()
            self.table, self.stats, out = self.megasteps[g](
                self.table, self.stats, self.params, dev
            )
            t_d = time.perf_counter()
        self._dispatch_calls += 1
        self._dispatched_chunks += g
        self._group_hist[g] = self._group_hist.get(g, 0) + 1
        if on_device:
            self._ring_partial_slots += 1
        self._note_step_s(g, t_d - t_p, out)
        return _InFlight(out, t_enqueue, n_records, n_chunks=g,
                         t_launch=t_l,
                         put_s=put_s if on_device else t_p - t_l,
                         launch_s=t_d - t_p)

    def _dispatch_group(self, raws: np.ndarray, t_enqueue: float,
                        n_records: int) -> None:
        """One lax.scan dispatch over a CONTIGUOUS ``[g, B+1, words]``
        staged wire group (a dispatch-arena slice — no np.stack copy).

        Queued as ONE in-flight entry whose StepOutput fields are
        stacked ``[g, B]`` (``now``/``route_drop``: ``[g]``) —
        :meth:`_sink_group` ravels, so verdict extraction is unchanged.
        e2e is anchored at the OLDEST member's first-record arrival (the
        honest group latency: earlier members waited for the group)."""
        if self._pipe_active:
            self._submit("group", raws, t_enqueue, n_records,
                         int(raws.shape[0]))
            return
        self._inflight.append(self._launch_group(raws, t_enqueue,
                                                 n_records))

    def _dispatch_mega(self, group: list[tuple[np.ndarray, float]]) -> None:
        """Group dispatch of INLINE-path pending buffers: stage the
        group's wire buffers into one arena slot (replacing the old
        per-group ``np.stack`` allocation with the arena's reusable
        page-aligned rows) and scan-dispatch the contiguous slice."""
        b = self.cfg.batch.max_batch
        g = len(group)
        rows = self._arena.rows(self._arena.claim())
        with self.metrics.stage.time():
            for i, (raw, _) in enumerate(group):
                rows[i][...] = raw
        self._staged_batches += g
        self._staged_bytes += int(rows[0].nbytes) * g
        n_records = int(sum(int(raw[b, 0]) for raw, _ in group))
        self._dispatch_group(rows[:g], min(t for _, t in group), n_records)

    # -- device-loop (drain ring) dispatch ----------------------------------

    def _upload_slot(self, rows: np.ndarray, t_enqueue: float,
                     n_records: int) -> _Uploaded:
        """EXPLICIT H2D of one staged ring slice — issued the moment
        the slot fills, so the transfer overlaps whatever round is
        still computing (the double-buffered half of the ring).  The
        overlap accounting feeds
        ``EngineReport.dispatch["device_loop"]["h2d"]``: an upload
        issued while dispatched-but-unsunk work exists counts as
        overlapped — that is the "device never waits on the host"
        claim, measured rather than asserted."""
        busy = self._busy_depth() > 0
        t0 = time.perf_counter()
        buf = self._put(rows)
        dt = time.perf_counter() - t0
        self._h2d_put_s += dt
        self._h2d_puts += 1
        if busy:
            self._h2d_overlap_s += dt
            self._h2d_puts_overlapped += 1
        return _Uploaded(buf, t_enqueue, n_records, dt)

    def _launch_ring(self, devs: list, t_enqueue: float,
                     n_records: int, put_s: float = 0.0) -> _InFlight:
        """The deep-scan call + accounting of a full ring round."""
        g = self.ring * self._ring_chunks
        with self.metrics.dispatch.time():
            t_l = time.perf_counter()
            self.table, self.stats, out = self.ring_step(
                self.table, self.stats, self.params, *devs
            )
            t_d = time.perf_counter()
        self._dispatch_calls += 1
        self._dispatched_chunks += g
        self._group_hist[g] = self._group_hist.get(g, 0) + 1
        self._ring_rounds += 1
        # Ring-round refinement is GUARDED (PR 11 follow-up closed;
        # :meth:`_note_round_s`): the launch wall alone omits the
        # uploads+reap the warm seed deliberately includes, so the
        # observation fed in is launch + the round's own upload wall,
        # launch-absorbed rounds only, and the EWMA is floored at the
        # warm seed — the estimate may sharpen UP toward the true
        # round cost but can never decay below the seed and let
        # _slo_round_fits keep waiting for rounds that land past the
        # budget (the decaying-optimistic-estimate hazard).
        self._note_round_s(-g, (t_d - t_l) + put_s, out)
        return _InFlight(out, t_enqueue, n_records, n_chunks=g,
                         t_launch=t_l, put_s=put_s, launch_s=t_d - t_l)

    def _dispatch_ring(self, uploaded: list[_Uploaded]) -> None:
        """ONE deep-scan dispatch over a full ring round (R uploaded
        slot buffers; fused/device_loop.py): one in-flight entry of
        ``ring * chunks`` batches whose RingOutput carries one merged
        verdict wire PER SLOT — the sink harvests the round as a
        single ``[R, 2K+4]`` fetch."""
        devs = [u.dev for u in uploaded]
        t_enqueue = min(u.t_enqueue for u in uploaded)
        n_records = sum(u.n_records for u in uploaded)
        put_s = sum(u.put_s for u in uploaded)
        if self._pipe_active:
            self._submit("ring", devs, t_enqueue, n_records,
                         self.ring * self._ring_chunks, put_s)
            return
        self._inflight.append(self._launch_ring(devs, t_enqueue,
                                                n_records, put_s))

    def _dispatch_group_dev(self, dev: Any, t_enqueue: float,
                            n_records: int, put_s: float = 0.0) -> None:
        """Megastep dispatch of an ALREADY-UPLOADED ring slot (a short
        backlog left the round partial: the uploaded slices flush
        through the ordinary top-rung megastep, byte-identical by
        construction — the ring's slot body IS that megastep)."""
        if self._pipe_active:
            self._submit("group_dev", dev, t_enqueue, n_records,
                         self._ring_chunks, put_s)
            return
        self._inflight.append(self._launch_group(dev, t_enqueue,
                                                 n_records,
                                                 on_device=True,
                                                 put_s=put_s))

    def _ring_from_pending(self) -> None:
        """Stage one full ring round out of the inline pending list:
        R arena slots of C wire buffers each, uploaded slot-by-slot
        (each ``device_put`` overlapping in-flight compute), then one
        deep-scan dispatch."""
        b = self.cfg.batch.max_batch
        c = self._ring_chunks
        uploaded: list[tuple] = []
        for _ in range(self.ring):
            rows = self._arena.rows(self._arena.claim())
            group = self._pending[:c]
            del self._pending[:c]
            with self.metrics.stage.time():
                for i, (raw, _) in enumerate(group):
                    rows[i][...] = raw
            self._staged_batches += c
            self._staged_bytes += int(rows[0].nbytes) * c
            uploaded.append(self._upload_slot(
                rows[:c], min(t for _, t in group),
                int(sum(int(raw[b, 0]) for raw, _ in group))))
        self._dispatch_ring(uploaded)

    def _rung_for(self, backlog: int) -> int:
        """THE coalescing policy, shared by the inline and sealed
        loops so the two paths can never dispatch different group
        shapes for the same backlog: the largest staged rung the
        backlog fills, else 1 (a single).  Delegates to
        :func:`flowsentryx_tpu.ops.fused.rung_for_volume` — the ONE
        copy of the rule, also read by the predictive governor's
        pre-warm sizing (engine/predict.py), so a forecast can never
        pre-warm a rung the backlog dispatch would not pick.

        Ranges over the READY rung set, not the staged ladder: while a
        tiered warm's background fill is still installing executables,
        the greedy flush picks the largest rung that is actually warm
        (grouping is dispatch-granularity only — byte-identity to the
        full ladder is pinned by test), and once the fill completes
        the two sets are equal again (legacy warm: always equal)."""
        return fused.rung_for_volume(backlog, self._ready_sizes)

    def _prewarm_dispatch(self, rung: int) -> None:
        """The governor's pre-warm actuation (engine/predict.py): ONE
        masked zero-valid dispatch through the forecast rung.
        :meth:`warm`'s masking argument makes it result-free — every
        row carries n_valid=0, so table/stats/verdicts are untouched
        and the latency plane ignores the entry (0 records).  The
        observable effects are exactly the point: the rung's EWMA
        refreshes launch-absorbed (so :meth:`_slo_cap` prices the
        incoming burst off a HOT measurement) and the rung's
        executable/arena path is warm when the burst lands.  Reaped
        to empty before returning — the pipe must read idle again
        before real traffic arrives."""
        if self._warm_buf is None:
            words = (schema.COMPACT_RECORD_WORDS
                     if self.wire == schema.WIRE_COMPACT16
                     else schema.RECORD_WORDS)
            self._warm_buf = np.zeros(
                (self.cfg.batch.max_batch + 1, words), np.uint32)
        t0 = time.perf_counter()
        if rung > 1 and self._arena is not None:
            self._dispatch_mega([(self._warm_buf, t0)] * rung)
        else:
            self._dispatch(self._warm_buf, t0)
        self._reap(0)

    # -- latency-budget (SLO) policy ----------------------------------------
    # Three advisory predicates over the warm-measured per-rung step-
    # time EWMA, all no-ops at --slo-us 0.  They bound COALESCING, not
    # results: whatever group shapes they pick, the verdict state is
    # byte-identical (grouping is dispatch-granularity only, the PR 5
    # invariant) — only latency and amortization change.

    def _slo_cap(self, t_oldest: float) -> int:
        """Largest staged rung whose expected completion — the oldest
        pending record's age plus the rung's EWMA step time — still
        fits the budget; 1 when even the smallest rung would breach
        (minimum-work path: a single is the least latency the engine
        can add).  A record ALREADY past its budget gets no cap: a
        late record cannot be saved by a rung choice, and shrinking
        groups exactly when a backlog exists collapses drain capacity
        into a queueing spiral (measured: forced singles under a
        saturating pulse took p99 from ~100 ms to ~700 ms) — the
        budget-exceeded path is the greedy flush at FULL
        amortization, which recovers the backlog fastest and so
        minimizes how many MORE records go late.  A rung without a
        measurement yet is assumed free: the dispatch that follows
        seeds it (self-correcting, and warm() pre-seeds every rung in
        SLO mode anyway)."""
        headroom = self._slo_budget_s - (time.perf_counter() - t_oldest)
        if headroom <= 0.0:
            # the top rung is ALWAYS in the ready set (serving tier of
            # a tiered warm), so the budget-exceeded full-amortization
            # path never waits on the background fill
            return self._mega_sizes[0] if self._mega_sizes else 1
        for s in self._ready_sizes:
            if self._rung_ewma_s.get(s, 0.0) <= headroom:
                return s
        return 1

    def _slo_pressed(self, t_oldest: float) -> bool:
        """Stop holding for a deeper backlog: once a TOP-rung step no
        longer fits the oldest pending record's remaining headroom —
        including a record already late, whose headroom is gone —
        waiting can only make things worse; flush now through
        :meth:`_slo_cap`'s choice (the existing greedy flush IS the
        budget-exceeded path).  Before this point, holding is free (a
        fuller group dispatched within budget is strictly better
        amortization)."""
        top = self._mega_sizes[0] if self._mega_sizes else 1
        headroom = self._slo_budget_s - (time.perf_counter() - t_oldest)
        return self._rung_ewma_s.get(top, 0.0) >= headroom

    def _slo_round_fits(self, t_oldest: float) -> bool:
        """Device-loop round sizer: whether waiting to launch a FULL
        deep-scan round can still land the oldest staged record
        inside the budget.  With positive headroom smaller than a
        round, the loops degrade to megastep slot flushes / the
        ladder — smaller rungs instead of queueing; once the record
        is already late the ring is BACK on (it is the
        highest-throughput recovery path, the same reasoning as
        :meth:`_slo_cap`'s no-cap rule)."""
        headroom = self._slo_budget_s - (time.perf_counter() - t_oldest)
        if headroom <= 0.0:
            return True
        key = -(self.ring * self._ring_chunks)  # ring-round EWMA key
        return self._rung_ewma_s.get(key, 0.0) < headroom

    def _drain_pending(self, short: bool) -> None:
        """Apply the coalescing ladder to the inline pending list.

        Full TOP-rung groups always dispatch (a deep backlog keeps
        amortization maximal); a short poll — no backlog left behind
        the pending batches — flushes the remainder greedily through
        the largest rung it still fills, then singles.  With a fixed
        ``mega_n`` the ladder is one rung, which reduces to the
        original all-or-nothing policy; adaptive mode
        (``mega_n="auto"``) is where partial backlogs stop paying the
        full per-dispatch tax batch by batch.

        Device-loop mode adds one rung ABOVE the ladder: a backlog
        holding a whole ring round (``ring * top_rung``) goes as one
        deep-scan dispatch; anything less falls through to the ladder
        exactly as before — the ring only ever engages on backlogs
        that were queueing anyway, so light-load latency is untouched
        and ``device_loop=0`` remains the byte-identical baseline.

        Under ``--slo-us`` the WAITING is budget-bounded (policy block
        above): budget pressure turns the hold-for-backlog into the
        greedy flush — the existing flush IS the budget-exceeded
        path, just entered earlier — and the greedy flush skips
        CLIMBING to a rung whose expected step time the oldest
        record's remaining headroom no longer covers.  Existing
        full-rung/round backlogs dispatch at full amortization either
        way (the sub-linear-step argument in the SLO note below)."""
        # SLO note: the full-amortization paths below (an EXISTING
        # round/top-rung backlog) deliberately stay un-capped even in
        # budget mode — step time is sub-linear in group size, so for
        # a backlog that already exists the largest rung finishes
        # EVERY record soonest (splitting it only delays the tail and
        # collapses capacity; measured: capping a saturated drain's
        # rungs cost ~35 % throughput and spiralled pulse p99 ~50x).
        # The budget bounds what the engine WAITS for — holds, round
        # fills, batcher residency — and the greedy flush's climb.
        slo = self._slo_budget_s
        # ring gating also covers the tiered-warm fill window: until
        # the background thread installs the deep-scan executable
        # (_ring_ready), backlogs drain through the ladder below —
        # byte-identical, the ring's slot body IS the top megastep.
        if self.ring and self._ring_ready:
            while len(self._pending) >= self._pending_cap:
                self._ring_from_pending()
                self._reap(self.readback_depth)
            if not short and not (
                    slo and self._pending
                    and self._slo_pressed(self._pending[0][1])):
                # a full poll means the backlog is still building
                # toward the next round — hold the remainder (unless
                # the budget says holding is no longer free)
                return
        top = self._mega_sizes[0]
        while len(self._pending) >= top:
            self._dispatch_mega(self._pending[:top])
            del self._pending[:top]
            self._reap(self.readback_depth)
        if not self._pending or not (short or (
                slo and self._slo_pressed(self._pending[0][1]))):
            return
        while self._pending:
            g = self._rung_for(len(self._pending))
            if slo:
                g = min(g, self._slo_cap(self._pending[0][1]))
            if g > 1:
                self._dispatch_mega(self._pending[:g])
                del self._pending[:g]
            else:
                raw, t_seal = self._pending.pop(0)
                self._dispatch(raw, t_seal)
            self._reap(self.readback_depth)

    @staticmethod
    def _out_ready(out) -> bool:
        """Whether a step output's sink fetch would not block: the
        compact wire is the LAST thing the step computes, so its
        readiness covers the whole output."""
        return (out.wire if out.wire is not None else out.block_key).is_ready()

    def _busy_depth(self) -> int:
        """Batches dispatched but not yet sunk (staging + sink queue +
        in-sink) — the 'pipe is busy' predicate the deadline-flush and
        idle-sleep decisions key on."""
        return sum(g.n_chunks for g in self._inflight) + self._chan.pending

    def _deadline_flush_due(self) -> bool:
        """THE idle-pipe deadline-flush rule (previously inline in
        :meth:`_run_inline`; extracted because it is load-bearing for
        the SLO path and must be testable directly).

        Deadline flush ONLY into an idle pipe: while batches are in
        flight — including batches queued to the sink thread,
        dispatched-but-unsunk is still a busy pipe — an early flush
        cannot reduce latency (the new batch queues behind them
        anyway) but it does burn a full padded step per near-empty
        buffer; the r4 open-loop collapse at tiny loads was exactly
        this flush-faster-than-the-step-drains spiral.  When the pipe
        drains (<= one step time) the deadline fires.

        Under ``--slo-us`` the batcher's residency is ALSO bounded by
        the budget: once the oldest pending record's age plus a
        single-batch EWMA step would land on the budget, the flush
        fires even before ``deadline_us`` — but never into a busy
        pipe; the rule above dominates.  The flush age is floored at
        HALF the budget: when the single-step estimate inflates past
        the budget itself (a throttled host), the naive ``age + step
        >= budget`` fires on any nonzero age and degenerates into
        flush-every-poll — the r4 spiral in budget clothing, measured
        decaying the SLO arm trial over trial; a record that cannot
        make the budget anyway still batches for up to budget/2."""
        if self._busy_depth() != 0:
            return False
        if self.batcher.flush_due():
            return True
        if not self._slo_budget_s:
            return False
        age = self.batcher.pending_age_s()
        if age <= 0.0:
            return False
        if self._gov is not None:
            # Predictive override (engine/predict.py): during a
            # forecast on-window, HOLD the flush so the burst's
            # records coalesce into one dispatch — but only while the
            # governor proves the held records still land inside the
            # budget (hold-safety bound); in the post-burst off-window
            # flush EARLY at the forecast burst end instead of waiting
            # for records to age into the reactive rule — the p99
            # lever.  None = no confident forecast, fall through to
            # the reactive rule below unchanged (the quiescent
            # fallback the confidence gate guarantees).
            d = self._gov.flush_decision(
                time.perf_counter(), age,
                self._rung_ewma_s.get(1, 0.0), self._slo_budget_s)
            if d is not None:
                return d
        return age >= max(
            self._slo_budget_s - self._rung_ewma_s.get(1, 0.0),
            self._slo_budget_s / 2)

    def _check_sink(self) -> None:
        """Propagate a worker crash into the dispatch thread — the
        engine must fail LOUDLY, not serve on with verdicts silently
        discarded (SinkChannel.check is THE unified worker-death
        path; strict-mode ingest death raises the same way)."""
        self._chan.check()

    def _handoff(self) -> None:
        """Move staged in-flight entries to the sink thread's queue."""
        if not self._inflight:
            return
        self._chan.submit_many(self._inflight, lambda g: g.n_chunks)
        self._inflight.clear()

    def _reap(self, down_to: int) -> None:
        """Ensure at most ``down_to`` BATCHES remain dispatched-but-
        unsunk — BLOCKING if needed.  This is the pipeline-depth cap;
        the latency path is :meth:`_reap_ready`.  Counted in batches,
        not queue entries: a mega dispatch is one entry of ``mega_n``
        batches, and letting it count as one would silently multiply
        the configured pipe depth (and its device output memory / tail
        latency) by ``mega_n``.

        Threaded mode: hand entries to the sink thread and wait on the
        pending count (backpressure); single-thread mode: fetch + sink
        here, blocking on device completion."""
        if self._sink_active:
            self._handoff()
            # the watchdog rides the backpressure wait's wakeup
            # quantum: a wedged-but-alive worker (no WorkerCrash to
            # break the wait) must dump stacks and fail loudly instead
            # of parking this wait forever (engine/watchdog.py)
            self._chan.wait_below(
                down_to,
                on_wait=lambda: self._watchdog.check(self._chan.pending))
            self._check_sink()
            return
        total = sum(g.n_chunks for g in self._inflight)
        group: list[_InFlight] = []
        while self._inflight and total > down_to:
            g = self._inflight.pop(0)
            total -= g.n_chunks
            group.append(g)
        if group:
            self._sink_group(group)

    def _reap_ready(self) -> None:
        """Sink every batch the device has ALREADY finished, oldest
        first, without blocking on anything unfinished.

        Threaded mode: the sink thread already does exactly this the
        moment futures complete — just hand over anything staged and
        surface a sink crash.  Single-thread mode (the original loop):
        called every iteration, because without it a batch's verdicts
        waited until ``readback_depth`` MORE batches had been
        dispatched — at an offered load L and batch size B that is
        ``depth × B/L`` of pure queueing added to every record (the r4
        open-loop collapse: p99 20×+ the step time at trivial loads).
        Readiness is a local future check, not a device round trip; the
        sink itself has a fixed host cost, so reaps COALESCE — a sink
        happens only when one is due (minimum gap) or the pipe is
        stacking up, and consecutive ready batches go as one group."""
        # every serving loop passes through here each iteration — the
        # one place the artifact watcher's throttled mtime check covers
        # inline, sealed, and ring loops alike (and the dispatch
        # watchdog's no-progress poll, same coverage argument)
        self._maybe_reload_artifact()
        self._watchdog.check(self._busy_depth())
        pressure = 0.0
        if self._gov is not None:
            # governor heartbeat: re-estimate (throttled inside), then
            # measure the SLO headroom of the OLDEST work anywhere on
            # the host side — batcher residency or a staged pending
            # group — as the shed-pressure signal.  Pure host floats;
            # nothing here touches the device path.
            now = time.perf_counter()
            self._gov.update(now)
            age = self.batcher.pending_age_s()
            if self._pending:
                age = max(age, now - self._pending[0][1])
            pressure = self._gov.pressure(age, self._slo_budget_s)
        if self.gossip is not None:
            # merge peers' gossiped verdicts between dispatches (also
            # on idle iterations — a quiet engine still mitigates what
            # its peers condemn).  RX mailboxes + the plane's own sink
            # are dispatch-thread-owned; the engine sink is not touched
            # here (its producer is the sink section).  Under measured
            # budget pressure the governor defers the plane's
            # anti-entropy pacing (never its verdict publish — that
            # happens in the sink section, untouched here).
            if pressure:
                self.gossip.tick(pressure=pressure)
            else:
                self.gossip.tick()
        if self._sink_active:
            self._handoff()
            self._check_sink()
            return
        if not self._inflight or not self._out_ready(self._inflight[0].out):
            return
        t = time.perf_counter()
        if (len(self._inflight) < 2
                and t - self._last_sink_t < self._min_sink_gap_s):
            return
        group = [self._inflight.pop(0)]
        while self._inflight and self._out_ready(self._inflight[0].out):
            group.append(self._inflight.pop(0))
        self._sink_group(group)

    # -- the sink thread ----------------------------------------------------

    def _start_sink_thread(self) -> None:
        if self._sink_active:
            return
        if self.ring:
            # device-loop mode: the pipeline worker (launch + sink)
            # runs regardless of the sink_thread flag — it IS the
            # mechanism that overlaps host staging with device compute
            target, name = self._ring_worker, "fsx-devpipe"
        elif self.sink_thread:
            target, name = self._sink_worker, "fsx-sink"
        else:
            return
        self._chan.name = ("device-pipeline worker" if self.ring
                           else "sink thread")
        self._chan.reset()
        self._sink_thread_obj = threading.Thread(
            target=target, name=name, daemon=True)
        self._sink_active = True
        self._pipe_active = bool(self.ring)
        self._sink_thread_obj.start()

    def _stop_sink_thread(self) -> None:
        """Drain-preserving shutdown: the worker finishes everything
        queued (each fetch completes — device futures always resolve),
        then exits; join is unbounded by design.  Never raises — the
        caller re-checks ``_check_sink`` after."""
        if not self._sink_active:
            return
        self._chan.request_stop()
        if self._watchdog.tripped:
            # the watchdog hard-tripped: the worker is WEDGED, not
            # draining — an unbounded join here would turn "fail
            # loudly" back into "hang forever".  Bounded join, then
            # abandon the daemon thread; the WatchdogStall propagating
            # through run() is the loud failure.
            self._sink_thread_obj.join(timeout=2.0)
        else:
            self._sink_thread_obj.join()
        self._sink_thread_obj = None
        self._sink_active = False
        self._pipe_active = False

    def _sink_worker(self) -> None:
        """Sink-thread main: pop the oldest entry (blocking on its
        fetch paces us to the device), coalesce whatever else already
        finished into the same group, fetch + sink, repeat.  FIFO pop
        by a single worker preserves record order for ``on_reap``."""
        try:
            while True:
                group = self._chan.pop(
                    coalesce=lambda e: self._out_ready(e.out))
                if group is None:
                    return  # stop requested and queue drained
                t0 = time.perf_counter()
                exc: BaseException | None = None
                try:
                    self._sink_group(group)
                except BaseException as e:  # noqa: BLE001
                    exc = e
                # exception recorded ATOMICALLY with the pending
                # decrement (SinkChannel.complete): a backpressure
                # waiter woken by this notify must never observe
                # (pending drained, exc unset) for a group that
                # actually crashed.
                self._chan.complete(sum(g.n_chunks for g in group),
                                    time.perf_counter() - t0, exc)
                if exc is not None:
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced by _check_sink
            self._chan.record_exc(e)

    def _submit(self, kind: str, payload: Any, t_enqueue: float,
                n_records: int, n_chunks: int,
                put_s: float = 0.0) -> None:
        """Hand one pre-launch work item to the device-pipeline worker
        (device-loop mode).  The channel's pending count rises at
        SUBMIT time, so the ``readback_depth`` backpressure bound
        covers queued-but-unlaunched work too — the wire/arena
        reuse-safety arguments both lean on that."""
        self._chan.submit((kind, payload, t_enqueue, n_records, n_chunks,
                           put_s),
                          n_chunks)

    def _ring_worker(self) -> None:
        """Device-pipeline worker main (device-loop mode): pop the
        oldest submission, LAUNCH it (the jit call — which on backends
        whose step graphs execute synchronously, like XLA:CPU with its
        inline scatter custom-calls, blocks for the whole round's
        compute), then sink its output immediately.  FIFO by a single
        worker: the carry chain (table/stats donation) stays
        sequential, and ``on_reap`` still sees records in exact
        arrival order.  Meanwhile the dispatch thread keeps polling,
        staging and ``device_put``-ing the NEXT round's slots — the
        double-buffered H2D overlap the report measures."""
        try:
            while True:
                got = self._chan.pop()
                if got is None:
                    return  # stop requested and queue drained
                kind, payload, t_e, n_rec, n_chunks, put_s = got[0]
                t0 = time.perf_counter()
                exc: BaseException | None = None
                try:
                    if kind == "ring":
                        entry = self._launch_ring(payload, t_e, n_rec,
                                                  put_s)
                    elif kind == "group_dev":
                        entry = self._launch_group(payload, t_e, n_rec,
                                                   on_device=True,
                                                   put_s=put_s)
                    elif kind == "group":
                        entry = self._launch_group(payload, t_e, n_rec)
                    else:
                        entry = self._launch_single(payload, t_e, n_rec)
                    self._sink_group([entry])
                except BaseException as e:  # noqa: BLE001
                    exc = e
                # exception recorded ATOMICALLY with the pending
                # decrement (the SinkChannel.complete discipline)
                self._chan.complete(n_chunks,
                                    time.perf_counter() - t0, exc)
                if exc is not None:
                    return
        except BaseException as e:  # noqa: BLE001 — _check_sink surfaces
            self._chan.record_exc(e)

    def _sink_group(self, group: list[_InFlight]) -> None:
        """Fetch + sink a reap group.

        COMPACT path (verdict_k > 0, the steady state): each entry's
        whole sink payload — keys, untils, count, overflow flag,
        route_drop, batch clock — is ONE small device buffer, so the
        fetch is O(verdict_k) bytes per batch instead of two full [B]
        arrays (8 B/record).  An entry whose overflow flag is set falls
        back to the full block-array fetch for THAT batch, so a block
        is never lost.  Small groups fetch wires with plain
        ``np.asarray``; LARGE groups (deep drains, post-stall bursts)
        fetch one device-side stack so the per-readback fixed cost —
        the RPC floor on tunneled runtimes — is paid per group, not
        per batch.

        LEGACY path (verdict_k == 0): the full-array fetch, kept as the
        parity/measurement baseline.  Host-side concat for small groups
        (composing a device-side concat cost three extra jit dispatches
        per sink, ~1.5 ms each — measured dominating the paced loop),
        one device-side concat for large ones."""
        if group[0].out.wire is not None:
            self._sink_group_wire(group)
            return
        t_fetch = time.perf_counter()
        # .reshape(-1) everywhere: a mega-dispatch entry carries stacked
        # [N, B] fields (now/route_drop [N]); single entries are [B]/[].
        with self.metrics.readback.time():
            # jax.device_get, not np.asarray: the D2H boundary stays
            # EXPLICIT (class docstring / transfer_guard contract)
            if len(group) <= 2:
                keys = np.concatenate(
                    [jax.device_get(g.out.block_key).reshape(-1)
                     for g in group]) \
                    if len(group) > 1 \
                    else jax.device_get(group[0].out.block_key).reshape(-1)
                untils = np.concatenate(
                    [jax.device_get(g.out.block_until).reshape(-1)
                     for g in group]) \
                    if len(group) > 1 \
                    else jax.device_get(group[0].out.block_until).reshape(-1)
            else:
                keys = jax.device_get(jnp.concatenate(
                    [g.out.block_key.reshape(-1) for g in group]))
                untils = jax.device_get(jnp.concatenate(
                    [g.out.block_until.reshape(-1) for g in group]))
            now = float(np.max(jax.device_get(group[-1].out.now)))
            self._d2h_bytes += keys.nbytes + untils.nbytes
            self._sink_fallback += len(group)
            # routing-overflow fail-opens (sharded step): single-device
            # steps carry a module-level numpy zero here — free, no
            # device fetch.  Sharded jax scalars: per-batch fetch on the
            # small-group fast path; ONE device-side sum for deep
            # groups (the whole point of that branch is one RPC round
            # trip per group).
            rds = [g.out.route_drop for g in group]
            if all(isinstance(rd, (int, np.integer, np.generic))
                   for rd in rds):
                self._route_drop += sum(int(rd) for rd in rds)
            elif len(group) <= 2:
                # .sum() not int(): a mega entry's route_drop is [N]
                self._route_drop += sum(
                    int(np.sum(jax.device_get(rd))) for rd in rds)
            else:
                self._route_drop += int(jax.device_get(jnp.sum(
                    jnp.concatenate([jnp.ravel(jnp.asarray(rd))
                                     for rd in rds]))))
        self._apply_updates(extract_updates(keys, untils), now, group,
                            t_fetch)

    def _sink_group_wire(self, group: list[_InFlight]) -> None:
        """The compact-wire sink (see :meth:`_sink_group`).

        An entry's wire is either one ``[2K+4]`` buffer (single / mega
        dispatch) or a ``[R, 2K+4]`` stack of per-slot wires (a
        device-loop round, harvested at ring granularity: still ONE
        D2H fetch for the whole round).  A round with ANY overflowed
        slot wire falls back to the full block-array fetch for the
        whole entry — the arrays cover every slot in chunk order, so
        last-wins decode stays exact and no block is lost."""
        t_fetch = time.perf_counter()
        with self.metrics.readback.time():
            if len(group) <= 2 or any(g.out.wire.ndim == 2
                                      for g in group):
                # per-entry fetch: ring wires are already deep-
                # amortized, and mixed [2K+4]/[R, 2K+4] shapes cannot
                # stack anyway
                wires = [jax.device_get(g.out.wire) for g in group]
            else:
                wires = jax.device_get(
                    jnp.stack([g.out.wire for g in group]))
            parts_k: list[np.ndarray] = []
            parts_u: list[np.ndarray] = []
            now = 0.0
            for g, w in zip(group, wires):
                rows = w.reshape(-1, w.shape[-1])
                self._d2h_bytes += w.nbytes
                overflow = False
                entry_k: list[np.ndarray] = []
                entry_u: list[np.ndarray] = []
                for row in rows:
                    vw = decode_verdict_wire(row)
                    overflow |= vw.overflow
                    entry_k.append(vw.key)
                    entry_u.append(vw.until_s)
                    self._route_drop += vw.route_drop
                    now = max(now, vw.now)
                if overflow:
                    # K_MAX-overflow fallback: a batch (or a ring
                    # slot's merged window) condemned more flows than
                    # its wire holds — pay the full fetch once rather
                    # than lose a single block.  The wire slots of the
                    # WHOLE entry are discarded: the full arrays carry
                    # every block in the same chunk order.
                    fk = jax.device_get(g.out.block_key).reshape(-1)
                    fu = jax.device_get(g.out.block_until).reshape(-1)
                    self._d2h_bytes += fk.nbytes + fu.nbytes
                    self._sink_fallback += 1
                    parts_k.append(fk)
                    parts_u.append(fu)
                else:
                    self._sink_compact += len(rows)
                    parts_k.extend(entry_k)
                    parts_u.extend(entry_u)
            keys = (np.concatenate(parts_k) if len(parts_k) > 1
                    else parts_k[0])
            untils = (np.concatenate(parts_u) if len(parts_u) > 1
                      else parts_u[0])
        self._apply_updates(extract_updates(keys, untils), now, group,
                            t_fetch)

    def _apply_updates(self, upd, now: float, group: list[_InFlight],
                       t_fetch: float) -> None:
        """Shared sink tail: writeback, clock/metric bookkeeping, the
        per-record latency plane, and the per-batch reap hook
        (record-FIFO order — both sink modes process groups
        oldest-first on a single thread).  ``t_fetch`` is when the
        group's wire fetch began — the sink-stage anchor of the
        latency decomposition."""
        self.sink.apply(upd)
        if self.gossip is not None:
            # republish to every peer engine RIGHT where the local
            # sink applied — the gossip TX mailboxes' single producer
            # is this sink section, whichever thread owns it
            self.gossip.publish(upd, now)
        self._blocked.update(upd.key.tolist())
        self._device_now = max(self._device_now, now)
        self._sunk_batches += sum(g.n_chunks for g in group)
        t_done = time.perf_counter()
        if (self._first_verdict_s is None
                and any(g.n_records for g in group)):
            # time-to-first-verdict (EngineReport.boot): anchored at
            # construction; masked warm batches carry zero records and
            # never trip it, so this is the first REAL verdict served
            self._first_verdict_s = t_done - self._boot_t0
        self._last_sink_t = t_done
        sink_s = t_done - t_fetch
        for g in group:
            self.metrics.e2e.add(t_done - g.t_enqueue)
            # per-record accounting: every record of the entry is
            # charged the entry's OLDEST-record path (a conservative
            # upper bound — earlier members waited for the group, the
            # same anchoring e2e has always used)
            self._lat.record(
                total_s=t_done - g.t_enqueue,
                staged_s=(g.t_launch - g.t_enqueue
                          if g.t_launch else 0.0),
                upload_s=g.put_s,
                compute_s=g.launch_s,
                sink_s=sink_s,
                n=g.n_records,
                budget_s=self._slo_budget_s,
            )
            if self.on_reap is not None:
                self.on_reap(g.n_records, t_done)
        # a completed sink group is the watchdog's progress signal —
        # one float store, whichever thread owns the sink section
        self._watchdog.note_progress()

    def warm(self, tiered: bool = False) -> None:
        """Stage every serving executable with zero-fill batches.

        A long-lived server pays the multi-second compile once at boot;
        a benchmark or test that skips this charges it to the first
        measured window instead (and, fed by a live ring, drops the
        seconds of records that arrive meanwhile).  The batch's meta
        row carries n_valid=0, so every row is masked — table, stats,
        and verdicts are unchanged.  Call before attaching a live
        stream; must not be called with batches in flight.

        With a persistent compile cache configured
        (``Engine(compile_cache=dir)``; engine/compile_cache.py) each
        variant is AOT-installed first: a cache hit deserializes the
        executable in tens of ms and the ladder below pays no compile;
        a miss compiles once via ``lower().compile()`` and publishes
        the entry for the next boot.  Fail-open at every step — the
        jit wrappers stay captured as the fallback path.

        ``tiered=True`` is the boot-latency mode: only the SERVING
        TIER — singles plus the top rung, the shapes every drain
        starts from — warms in the foreground (plus, under ``--slo-us``
        with a drain ring, the ring itself: the round sizer's EWMA
        seed must cover uploads AND reap, which only this quiescent
        pass can measure).  The engine is serving the moment this
        returns; a background thread (:meth:`_warm_worker`) fills the
        remaining rungs/ring AOT-only — it never dispatches — and
        publishes each executable with one reference rebind, growing
        the ready set until the full ladder is live.  Byte-identity to
        a full-ladder warm is pinned by test: grouping is
        dispatch-granularity only."""
        if (self._warm_thread_obj is not None
                and self._warm_thread_obj.is_alive()):
            raise RuntimeError(
                "warm() called while a background warm fill is active "
                "— warm_fill_join() first (nothing else may touch the "
                "staged executables while the fill thread installs)")
        self._warm_thread_obj = None
        serving_sizes = self._mega_sizes
        ring_now = bool(self.ring)
        fill_plan: list[tuple] = []
        if tiered and self._mega_sizes:
            serving_sizes = self._mega_sizes[:1]
            # SLO + ring keeps the ring in the serving tier: run()'s
            # auto-warm gate needs the negated round key seeded by a
            # quiescent pass (the only measurement covering uploads
            # AND reap), and the fill thread may never dispatch.
            ring_now = bool(self.ring) and bool(self._slo_budget_s)
            fill_plan = [("mega", g) for g in self._mega_sizes[1:]]
            if self.ring and not ring_now:
                fill_plan.append(("ring",))
        boot: dict[str, Any] = {
            "tiered": bool(fill_plan),
            "variants": {},
            "fill_pending": [self._variant_label(n) for n in fill_plan],
        }
        # AOT install (cache load or lower().compile()) BEFORE the
        # dispatch ladder: installed executables replace the jit
        # wrappers on self.step/self.megasteps/self.ring_step, so the
        # ladder below triggers no compile on a warm cache.  Without a
        # cache the ladder itself is the compile trigger, exactly the
        # historical path (tiered mode still AOT-compiles so the
        # background fill has executables to install).
        if self._cache is not None or fill_plan:
            names: list[tuple] = [("single",)]
            names += [("mega", g) for g in serving_sizes]
            if ring_now:
                names.append(("ring",))
            for name in names:
                exe, entry = self._aot_build(name)
                if exe is not None:
                    self._aot_install(name, exe)
                boot["variants"][self._variant_label(name)] = entry
        words = (schema.COMPACT_RECORD_WORDS
                 if self.wire == schema.WIRE_COMPACT16
                 else schema.RECORD_WORDS)
        warm = np.zeros((self.cfg.batch.max_batch + 1, words), np.uint32)
        # ONE dispatch ladder, run once to compile every staged
        # variant (each ladder rung and the deep-scan ring graph are
        # their own XLA artifacts) and — in SLO mode — a second,
        # TIMED time to seed the per-rung step-time EWMA with
        # compile-free launch→sunk walls (backend-agnostic: the reap
        # blocks on the fetch, so the measure covers the compute the
        # launch call alone would hide on async backends).  Masked
        # batches run the full fused graph, so the costs are the
        # served ones; the online refinement (``_note_step_s``)
        # would otherwise start from compile-poisoned values.  A new
        # staged variant added here is automatically both compiled
        # AND seeded — the two passes can never drift apart.  In
        # tiered mode the ladder covers the serving tier only;
        # background-filled rungs follow the documented unseeded-rung
        # rule (assumed free, first dispatch seeds).
        for timed in (False, True) if self._slo_budget_s else (False,):
            if timed:
                self._rung_ewma_s.clear()
            t0 = time.perf_counter()
            self._dispatch(warm, t0)
            self._reap(0)
            if timed:
                self._rung_ewma_s[1] = time.perf_counter() - t0
            for g in serving_sizes:
                t0 = time.perf_counter()
                self._dispatch_mega([(warm, t0)] * g)
                self._reap(0)
                if timed:
                    self._rung_ewma_s[g] = time.perf_counter() - t0
            if ring_now:
                zero_slot = np.zeros(
                    (self._ring_chunks,) + warm.shape, np.uint32)
                t0 = time.perf_counter()
                self._dispatch_ring([
                    self._upload_slot(zero_slot, t0, 0)
                    for _ in range(self.ring)])
                self._reap(0)
                if timed:
                    # ring ROUNDS key negated (attribute docstring):
                    # a depth-1 round spans the top rung's chunk
                    # count but its wall includes uploads+reap —
                    # never share slots.  The seed is also the FLOOR
                    # the online refinement may never dip below
                    # (_note_round_s).
                    key = -(self.ring * self._ring_chunks)
                    self._rung_ewma_s[key] = time.perf_counter() - t0
                    self._round_floor_s[key] = self._rung_ewma_s[key]
        # publish the ready set LAST: every executable above is
        # installed and compile-free before a drain may pick its rung
        self._ready_sizes = serving_sizes
        self._ring_ready = ring_now
        # warm dispatches are compile triggers, not traffic — keep them
        # out of the dispatch-block accounting
        self._reset_dispatch_counters()
        boot["serving_ready_s"] = round(
            time.perf_counter() - self._boot_t0, 4)
        if self._cache is not None:
            boot["cache"] = self._cache.report()
        self._boot = boot
        if fill_plan:
            self._warm_plan = tuple(fill_plan)
            self._warm_thread_obj = threading.Thread(
                target=self._warm_worker, name="fsx-warm", daemon=True)
            self._warm_thread_obj.start()

    # -- AOT executable staging (ISSUE 20) ----------------------------------

    @staticmethod
    def _variant_label(name: tuple) -> str:
        return name[0] if len(name) == 1 else f"{name[0]}{name[1]}"

    def _aot_build(self, name: tuple) -> tuple[Any | None, dict]:
        """Load-or-compile ONE staged variant ahead of time.

        Worker-safe by construction: touches only the pristine jit
        wrappers and abstract arg specs captured at __init__
        (``_aot_specs``) and the compile cache — never the live device
        state, never a dispatch.  Returns ``(executable, entry)``
        where entry is the per-variant boot record (source:
        cache | compile | error, seconds); executable is None on
        failure (fail-open: the jit wrapper keeps serving)."""
        label = self._variant_label(name)
        fn, args = self._aot_specs[name]
        t0 = time.perf_counter()
        if self._cache is not None:
            exe = self._cache.load(label)
            if exe is not None:
                return exe, {
                    "source": "cache",
                    "seconds": round(time.perf_counter() - t0, 4)}
        try:
            exe = fn.lower(*args).compile()
        except Exception as e:  # noqa: BLE001 — fail-open by contract
            import sys

            print(f"fsx warm: AOT staging of {label} failed ({e!r}); "
                  "the jit path serves this variant (fail-open)",
                  file=sys.stderr)
            return None, {
                "source": "error", "error": repr(e),
                "seconds": round(time.perf_counter() - t0, 4)}
        if self._cache is not None:
            self._cache.store(label, exe)
        return exe, {"source": "compile",
                     "seconds": round(time.perf_counter() - t0, 4)}

    def _aot_install(self, name: tuple, exe: Any) -> None:
        """Publish one AOT executable over its jit wrapper — plain
        whole-object rebinds only (the atomic-ref discipline: launch
        sites read each reference once per dispatch, so an install
        from the warm fill thread is safe mid-serve; either the jit
        wrapper or the executable runs, byte-identical results)."""
        if name[0] == "single":
            self.step = exe
        elif name[0] == "mega":
            self.megasteps = {**self.megasteps, name[1]: exe}
        else:
            self.ring_step = exe

    def _warm_worker(self) -> None:
        """Background warm fill (warm(tiered=True)): AOT-stage the
        remaining ladder rungs / ring, largest value first, and grow
        the ready set as each lands.  NEVER dispatches — the launch
        and sink sections keep their single owners; everything this
        thread publishes (executables, ready set, boot block) is one
        reference rebind.  Fail-open: an error leaves the jit
        fallback serving that variant and is recorded in the boot
        block, never raised into serving."""
        try:
            for name in self._warm_plan:
                exe, entry = self._aot_build(name)
                label = self._variant_label(name)
                if exe is not None:
                    self._aot_install(name, exe)
                    if name[0] == "mega":
                        self._ready_sizes = tuple(sorted(
                            set(self._ready_sizes) | {name[1]},
                            reverse=True))
                    elif name[0] == "ring":
                        self._ring_ready = True
                boot = dict(self._boot or {})
                boot["variants"] = {**boot.get("variants", {}),
                                    label: entry}
                boot["fill_pending"] = [
                    v for v in boot.get("fill_pending", ())
                    if v != label]
                self._boot = boot
            boot = dict(self._boot or {})
            boot["fill_done_s"] = round(
                time.perf_counter() - self._boot_t0, 4)
            if self._cache is not None:
                boot["cache"] = self._cache.report()
            self._boot = boot
        except BaseException as e:  # noqa: BLE001 — fail-open, counted
            self._boot = {**(self._boot or {}), "fill_error": repr(e)}

    def warm_fill_active(self) -> bool:
        """Whether a tiered warm's background fill is still running."""
        t = self._warm_thread_obj
        return t is not None and t.is_alive()

    def warm_fill_join(self, timeout: float | None = None) -> bool:
        """Wait for the background warm fill; True when it is done
        (including when none was started)."""
        t = self._warm_thread_obj
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def _reset_dispatch_counters(self) -> None:
        self._group_hist = {}
        self._dispatch_calls = 0
        self._dispatched_chunks = 0
        self._staged_batches = 0
        self._staged_bytes = 0
        self._ring_rounds = 0
        self._ring_partial_slots = 0
        self._h2d_put_s = 0.0
        self._h2d_overlap_s = 0.0
        self._h2d_puts = 0
        self._h2d_puts_overlapped = 0

    # -- stream rebinding ---------------------------------------------------

    def reset_stream(
        self,
        source: RecordSource,
        sink: VerdictSink | None = None,
        readback_depth: int | None = None,
        t0_ns: int | None = None,
    ) -> None:
        """Rebind the engine to a new record stream WITHOUT recompiling.

        The jitted step is the expensive part of an Engine (~seconds of
        XLA compile per batch shape); the stream plumbing around it is
        cheap.  Benchmarks and restarted feeds reuse one engine across
        many runs by swapping the source/sink and resetting the
        batcher, metrics, and in-flight queue.  Device state (table,
        stats) deliberately persists — it is the engine's long-lived
        flow memory, surviving stream restarts just like the kernel
        maps survive a daemon reconnect; use :meth:`restore` to reset
        it.  Because that memory holds t0-relative stream-seconds
        (last-seen, blacklist expiry), the clock EPOCH persists with
        it: ``t0_ns=None`` keeps the current anchor (re-anchoring to a
        new stream's first record would time-shift every persisted
        expiry — the same invariant :meth:`restore` protects).
        Per-stream report counters (metrics, blocked set, route drops)
        reset; ``_device_now`` survives, being a high-water mark on the
        persisting clock.  Must not be called with batches in flight."""
        if self._inflight or self._pending:
            raise RuntimeError("reset_stream with batches in flight")
        self.source = source
        self.sealed = bool(getattr(source, "provides_sealed", False))
        if self.sealed and not getattr(source, "started", False):
            source.start(self.cfg.batch, self.wire,
                         self.batcher.quant or None)
        if sink is not None:
            self.sink = sink
        if readback_depth is not None:
            self.readback_depth = readback_depth
        quant = self.batcher.quant or None
        keep_t0 = self.batcher.t0_ns if t0_ns is None else t0_ns
        self.batcher = MicroBatcher(
            self.cfg.batch,
            t0_ns=keep_t0,
            n_buffers=self.readback_depth + 2 + self._pending_cap,
            wire=self.wire,
            quant=quant,
        )
        if t0_ns is not None:
            self._t0_auto = False
            if hasattr(self.sink, "t0_ns"):
                self.sink.t0_ns = t0_ns
        elif not self._t0_auto and hasattr(self.sink, "t0_ns"):
            self.sink.t0_ns = keep_t0  # a swapped-in sink needs the anchor
        self.metrics = PipelineMetrics()
        # per-stream latency plane restarts with the metrics; the
        # per-rung EWMA table deliberately SURVIVES a rebind — it is a
        # property of the compiled step graphs, not of the stream
        # (paying a re-warm per paced trial would poison short runs)
        self._lat = LatencyRecorder()
        self._blocked = set()
        self._route_drop = 0
        # per-stream readback accounting restarts with the metrics
        self._d2h_bytes = 0
        self._sink_compact = 0
        self._sink_fallback = 0
        self._sunk_batches = 0
        self._reset_dispatch_counters()
        if self._gov is not None:
            # per-stream governor counters restart with the metrics;
            # the predictor's arrival window and any live forecast
            # deliberately survive — like the EWMA table, they are
            # properties of the traffic process, not of one stream
            self._gov.reset_counters()
        # A reap hook is per-stream plumbing: every current caller binds
        # it as a closure over the previous stream's source, so keeping
        # it across a rebind would yield silently wrong latencies (or a
        # mid-run pop_scheduled ValueError).  Callers re-attach.
        self.on_reap = None

    # -- checkpoint/resume (SURVEY.md §5.4: the map-pinning analog) ---------

    def _n_shards(self) -> int:
        return int(self.mesh.devices.size) if self.mesh is not None else 1

    def checkpoint(self, path) -> str:
        """Snapshot table+stats+clock so a restarted engine resumes with
        every tracked flow and blacklist expiry intact.  The write is
        atomic and the header records the table GEOMETRY (salt, shard
        count, capacity) so a restore under a different mesh reshards
        instead of mislocating keys (engine/checkpoint.py docstring)."""
        from flowsentryx_tpu.engine import checkpoint as ckpt

        return str(ckpt.save_state(path, self.table, self.stats,
                                   self.batcher.t0_ns,
                                   hash_salt=self.cfg.table.salt,
                                   n_shards=self._n_shards()))

    def restore(self, path) -> dict:
        """Resume from a snapshot.  Same geometry → bit-identical
        placement; a different mesh size or capacity re-places every
        occupied row for THIS engine's geometry
        (:func:`flowsentryx_tpu.engine.table.reshard_rows` — announced,
        with unplaceable rows counted, never silent).  A salt mismatch
        is refused outright: proceeding under either salt would break
        one side's slot layout.  Returns a summary dict
        (``resharded``/``dropped_rows``/``from``/``to``).

        A CORRUPT snapshot (failed CRC, torn/truncated file —
        :class:`~flowsentryx_tpu.engine.checkpoint.CheckpointCorrupt`)
        is never loaded: restore falls back to the retained previous
        generation (``checkpoint.prev_path``; the periodic-snapshot
        loop rotates it on every save), announced loudly and counted
        in ``EngineReport.health`` as a DEGRADED reason — flow memory
        resumes one generation stale, which fail-open serving absorbs
        the same way it absorbs a restart.  No ``.prev`` (or a
        ``.prev`` that is itself corrupt) re-raises: there is nothing
        safe to resume from, and inventing an empty table silently
        would unblock every previously-blocked source."""
        import sys

        from flowsentryx_tpu.engine import checkpoint as ckpt
        from flowsentryx_tpu.engine import table as tbl

        fallback_from = None
        try:
            ck = ckpt.load_checkpoint(path)
        except ckpt.CheckpointCorrupt as e:
            prev = ckpt.prev_path(path)
            if not prev.exists():
                raise
            print(
                f"fsx engine: checkpoint {path} REFUSED ({e}); "
                f"falling back to the retained previous generation "
                f"{prev}", file=sys.stderr)
            ck = ckpt.load_checkpoint(prev)  # corrupt too -> raises
            fallback_from = str(path)
            self._restore_fallbacks += 1
        if ck.hash_salt != self.cfg.table.salt:
            # A different salt relocates every slot: lookups would miss
            # all persisted flows and silently rebuild the table from
            # scratch while the stale rows rot.  Refuse; the caller
            # adopts the checkpoint's salt (checkpoint.peek_salt) before
            # building the engine, as `fsx serve --restore` does.
            raise ValueError(
                f"checkpoint hash salt {ck.hash_salt} != configured "
                f"{self.cfg.table.salt}; rebuild the engine with "
                "TableConfig(salt=<checkpoint salt>)"
            )
        key = np.asarray(ck.table.key)
        state = np.asarray(ck.table.state)
        if ("tok_bytes" in ck.missing_columns
                and self.cfg.limiter.bucket_burst_bytes > 0):
            # Pre-byte-bucket snapshot under a byte-limited config:
            # zero credit would spuriously rate-block every restored
            # flow's first batch (refill is elapsed-based, not full).
            # Occupied slots start with the full burst, matching the
            # is_new semantics their flows got on first sight.
            state = state.copy()
            state[:, int(schema.TableCol.TOK_BYTES)] = np.where(
                key != 0,
                np.float32(self.cfg.limiter.bucket_burst_bytes),
                np.float32(0.0))
        n_shards = self._n_shards()
        info = {
            "resharded": False, "dropped_rows": 0,
            "from": {"capacity": ck.capacity, "n_shards": ck.n_shards},
            "to": {"capacity": self.cfg.table.capacity,
                   "n_shards": n_shards},
            "crc_checked": ck.crc_checked,
            "fallback_from": fallback_from,
        }
        if (ck.capacity != self.cfg.table.capacity
                or ck.n_shards != n_shards):
            plan = tbl.TablePlan(capacity=self.cfg.table.capacity,
                                 n_shards=n_shards,
                                 salt=self.cfg.table.salt,
                                 probes=self.cfg.table.probes)
            key, state, dropped = tbl.reshard_rows(key, state, plan)
            info["resharded"] = True
            info["dropped_rows"] = dropped
            import sys

            print(
                f"fsx engine: resharding checkpoint "
                f"{ck.capacity} rows x {ck.n_shards} shard(s) -> "
                f"{plan.capacity} rows x {plan.n_shards} shard(s)"
                + (f"; {dropped} row(s) dropped (probe sequences "
                   "exhausted - table too full for the new geometry)"
                   if dropped else ""),
                file=sys.stderr,
            )
        table = schema.IpTableState(key=key, state=state)
        if self.mesh is not None:
            from flowsentryx_tpu import parallel as par

            table = par.shard_table(table, self.mesh)
        else:
            table = schema.IpTableState(key=jax.device_put(key),
                                        state=jax.device_put(state))
        # restored stats re-enter through _put for the same replication
        # reason as the boot-time make_stats()
        stats = schema.GlobalStats(*(np.asarray(v) for v in ck.stats))
        self.table, self.stats = table, self._put(stats)
        self.batcher.t0_ns = ck.t0_ns
        self._t0_auto = False
        if hasattr(self.sink, "t0_ns"):
            self.sink.t0_ns = ck.t0_ns
        return info

    # -- live shard handoff (cluster/rebalance.py; ISSUE 16) ----------------
    #
    # All three methods are QUIESCENT: the rebalancer calls them
    # between run() chunks, where no dispatch is in flight, so the
    # host fetch / re-place round-trip sees (and publishes) a stable
    # table — the same contract as checkpoint()/restore().

    def count_rebalance(self, name: str, n: int = 1) -> None:
        self._rebalance[name] = self._rebalance.get(name, 0) + int(n)

    def _host_table(self) -> tuple[np.ndarray, np.ndarray]:
        return (np.asarray(self.table.key),
                np.asarray(self.table.state))

    def _replace_table(self, key: np.ndarray, state: np.ndarray) -> None:
        """Re-place host arrays on device — the restore() placement
        idiom (sharded over the mesh, or plain device_put)."""
        table = schema.IpTableState(key=key, state=state)
        if self.mesh is not None:
            from flowsentryx_tpu import parallel as par

            table = par.shard_table(table, self.mesh)
        else:
            table = schema.IpTableState(key=jax.device_put(key),
                                        state=jax.device_put(state))
        self.table = table

    def extract_span_rows(
        self, shards, total_shards: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Occupied ``(keys, states)`` of the given RING shards (the
        ingest-affinity hash ``schema.shard_of`` over the table keys —
        the table key IS the folded saddr, so the donor's wire rows
        are selected by exactly the rule producers route by).  Pure
        read: the table is untouched (the donor keeps serving the
        span until the flip commits)."""
        key, state = self._host_table()
        occ = key != 0
        sel = occ & np.isin(schema.shard_of(key, total_shards),
                            np.asarray(list(shards), np.uint32))
        return key[sel].copy(), state[sel].copy()

    def drop_span_rows(self, shards, total_shards: int) -> int:
        """Zero every row of the given ring shards (donor post-flip,
        or boot-time foreign-row reconcile).  Returns the count."""
        key, state = self._host_table()
        key, state = key.copy(), state.copy()
        sel = (key != 0) & np.isin(schema.shard_of(key, total_shards),
                                   np.asarray(list(shards), np.uint32))
        n = int(np.sum(sel))
        if n:
            key[sel] = 0
            state[sel] = 0.0
            self._replace_table(key, state)
        return n

    def adopt_rows(self, keys, states) -> tuple[int, int]:
        """Probe-insert handed-off rows into the live table
        (:func:`flowsentryx_tpu.engine.table.insert_rows`).  Returns
        ``(inserted, dropped)`` — dropped rows (key collision or probe
        exhaustion) are the caller's to count as a DEGRADED reason,
        never silent."""
        from flowsentryx_tpu.engine import table as tbl

        keys = np.asarray(keys, np.uint32).reshape(-1)
        if not len(keys):
            return 0, 0
        key, state = self._host_table()
        plan = tbl.TablePlan(capacity=self.cfg.table.capacity,
                             n_shards=self._n_shards(),
                             salt=self.cfg.table.salt,
                             probes=self.cfg.table.probes)
        key, state, dropped = tbl.insert_rows(key, state, keys, states,
                                              plan)
        self._replace_table(key, state)
        return len(keys) - dropped, dropped

    # -- live model hot-swap ------------------------------------------------

    def hot_swap(self, params) -> None:
        """Replace the served artifact WITHOUT draining the pipeline or
        recompiling (the TPU-tier analog of ``fsx distill --pin``'s
        live map push).  The jitted step takes params as an ARGUMENT,
        so the swap is one atomic reference assignment: dispatches
        launched after it score with the new artifact, in-flight
        rounds finish with the old one — no serving gap, no verdict
        lost.  Safe from any thread (``on_reap`` hooks, the artifact
        watcher, an operator REPL): launch sites read ``self.params``
        exactly once per dispatch.

        Refused (ValueError) when the swap would invalidate compiled
        state rather than just re-parameterize it: a different leaf
        structure/shape/dtype would silently retrace mid-serve, and a
        compact16 ``model``-mode wire quantizes with the BOOT
        artifact's observer constants (baked into the traced decode
        and the sealed-ingest workers), so a new artifact must carry
        the same ``in_scale``/``in_zp``/``log1p`` — or be served over
        raw48."""
        old_leaves = jax.tree_util.tree_leaves(self.params)
        new_leaves = jax.tree_util.tree_leaves(params)
        if (jax.tree_util.tree_structure(self.params)
                != jax.tree_util.tree_structure(params)):
            raise ValueError(
                "hot_swap: artifact tree structure differs from the "
                "served model (different family?); boot a fresh engine")
        for i, (a, b) in enumerate(zip(old_leaves, new_leaves)):
            sa, sb = np.shape(a), np.shape(b)
            da = np.dtype(getattr(a, "dtype", type(a)))
            db = np.dtype(getattr(b, "dtype", type(b)))
            if sa != sb or da != db:
                raise ValueError(
                    f"hot_swap: params leaf {i} is {db}{list(sb)}, "
                    f"served model has {da}{list(sa)} — a shape/dtype "
                    "change would retrace the step mid-serve")
        q = self.batcher.quant or None
        if q and q.get("feat_mode") == "model":
            nq = schema.model_quant_args(params)
            drift = {k: (q.get(k), nq[k])
                     for k in ("in_scale", "in_zp", "log1p")
                     if nq[k] != q.get(k)}
            if drift:
                raise ValueError(
                    "hot_swap: the compact16 wire quantizes with the "
                    "boot artifact's input observer, but the new "
                    f"artifact's differs: {drift}; serve raw48 or "
                    "reboot with the new artifact")
        self.params = jax.tree.map(self._put, params)
        self._hot_swaps += 1

    def watch_artifact(self, path: str) -> None:
        """Live artifact reload (``fsx serve --artifact-reload``): the
        serving loops re-stat ``path`` at most twice a second and
        :meth:`hot_swap` when its mtime changes.  A failed reload
        (half-written file, wrong family) is announced on stderr and
        serving continues on the incumbent model — fail-open, the data
        plane never dies for a bad artifact push."""
        import os

        self._watch_path = str(path)
        try:
            self._watch_mtime = os.stat(self._watch_path).st_mtime_ns
        except OSError:
            self._watch_mtime = 0
        self._watch_next = 0.0

    def _maybe_reload_artifact(self) -> None:
        if self._watch_path is None:
            return
        t = time.monotonic()
        if t < self._watch_next:
            return
        self._watch_next = t + 0.5
        import os

        try:
            m = os.stat(self._watch_path).st_mtime_ns
        except OSError:
            return  # mid-replace or gone; try again next tick
        if m == self._watch_mtime:
            return
        self._watch_mtime = m
        import sys
        import zipfile

        try:
            from flowsentryx_tpu.models.registry import load_artifact

            self.hot_swap(load_artifact(self.cfg.model.name,
                                        self._watch_path))
            print(f"fsx engine: hot-swapped artifact "
                  f"{self._watch_path} (swap #{self._hot_swaps})",
                  file=sys.stderr)
        # BadZipFile: a non-atomic deploy caught mid-write hands
        # np.load a partial zip — the headline case the fail-open
        # contract exists for (a later poll picks up the finished file)
        except (ValueError, KeyError, OSError,
                zipfile.BadZipFile) as e:
            print("fsx engine: artifact reload failed (serving "
                  f"continues on the incumbent model): {e}",
                  file=sys.stderr)

    # -- main loop ----------------------------------------------------------

    def run(
        self,
        max_batches: int | None = None,
        max_seconds: float | None = None,
    ) -> EngineReport:
        """Run until the source is exhausted (or a bound trips).

        With ``sink_thread`` (auto-on where the host has ≥3 cores) the
        verdict sink runs on a dedicated thread for the duration of
        this call: started here, drained and joined before the report
        is built, crash surfaced as a RuntimeError (module
        docstring)."""
        if (self._slo_budget_s and self.ring
                and -(self.ring * self._ring_chunks)
                not in self._rung_ewma_s):
            # the device-loop round sizer has NO online refinement
            # (its estimate must include uploads+reap, which only the
            # warm pass measures) — an unseeded key would silently
            # disable the degrade-to-smaller-rungs behavior the SLO
            # flag advertises on the ring path.  Nothing is in flight
            # at run() start, so self-warming here is safe; callers
            # that already warmed skip it (the key persists).
            self.warm()
        self._start_sink_thread()
        try:
            rep = (self._run_sealed(max_batches, max_seconds)
                   if self.sealed
                   else self._run_inline(max_batches, max_seconds))
        finally:
            self._stop_sink_thread()
            # Serving is over; do not hand a daemon fill thread to
            # interpreter teardown mid-XLA-compile (measured segfault
            # in short-lived `fsx serve --batches N --tiered-warm`
            # runs whose drain outpaces the ladder fill).  Bounded: a
            # compile always terminates, and a long-lived server's
            # fill finished long before its drain did.
            if not self.warm_fill_join(300.0):
                import sys

                print("fsx engine: warm fill still compiling 300 s "
                      "after the drain finished — abandoning it "
                      "(report's fill_done_s will be missing)",
                      file=sys.stderr)
        self._check_sink()  # a crash in the very last drain group
        return rep

    def _run_inline(
        self,
        max_batches: int | None = None,
        max_seconds: float | None = None,
    ) -> EngineReport:
        """The record-source serving loop (the batcher lives here; the
        sealed-batch twin is :meth:`_run_sealed`)."""
        t_start = time.perf_counter()
        cfg_b = self.cfg.batch

        def bounded() -> bool:
            if max_batches is not None and self.batcher.batches_emitted >= max_batches:
                return True
            if max_seconds is not None and time.perf_counter() - t_start >= max_seconds:
                return True
            return False

        while not bounded():
            with self.metrics.fill.time():
                # Mega mode polls up to the remaining GROUP capacity
                # (one whole ring round in device-loop mode) so a deep
                # source backlog can seal several batches in one
                # drain; otherwise exactly one batch's worth.
                group_room = max(self._pending_cap - len(self._pending), 1)
                requested = group_room * cfg_b.max_batch - self.batcher.fill
                records = self.source.poll(requested)
                if self._t0_auto and len(records):
                    if self.precompact:
                        t0 = int(schema.unwrap_kernel_ts16(
                            records["w3"][:1],
                            time.clock_gettime_ns(time.CLOCK_MONOTONIC),
                        )[0])
                    else:
                        t0 = int(records["ts_ns"][0])
                    self.batcher.t0_ns = t0
                    if hasattr(self.sink, "t0_ns"):
                        self.sink.t0_ns = t0  # sinks translate s -> abs ns
                    self._t0_auto = False
                # the (simulated) kernel tier splits records exactly
                # where XDP would: after the drain, before the batcher.
                # n_polled drives the idle backoff below — a hot source
                # whose records all drop in-kernel is not an idle link.
                n_polled = len(records)
                if self._gov is not None and n_polled:
                    # the governor observes the PRE-filter arrival
                    # process (like the idle backoff): the burst shape
                    # it forecasts is the link's, not the survivors'
                    self._gov.note_arrivals(time.perf_counter(),
                                            n_polled)
                if self.kernel_tier is not None and n_polled:
                    records = self.kernel_tier.filter(records)
                if not len(records):
                    sealed = []
                    if self.precompact:
                        # A drain opportunity with no records: note it so
                        # the wrap-risk heuristic keys on drain cadence,
                        # not traffic cadence (a lull is not a stall).
                        self.batcher.note_poll()
                elif self.precompact:
                    sealed = self.batcher.add_precompact(records)
                else:
                    sealed = self.batcher.add(records)
                # The idle-pipe deadline-flush rule lives in
                # _deadline_flush_due (flush ONLY when the pipe is
                # fully drained, never mid-flight; SLO mode adds the
                # budget bound on batcher residency) — extracted so
                # the rule is tested directly, not just documented.
                if not sealed and self._deadline_flush_due():
                    took = self.batcher.take()
                    sealed = [took] if took is not None else []
            if self.mega_n > 0:
                # Backlog-triggered grouping: full top-rung groups go
                # as one dispatch; the moment the source comes back
                # short (no deep backlog) the stragglers flush through
                # the largest staged rung they still fill (adaptive),
                # then singly — so grouping only ever ADDS latency to
                # batches that were queueing behind a backlog anyway.
                # Shortness is judged PRE-filter (n_polled): a flood
                # the kernel tier mostly drops still means a deep
                # source backlog, exactly when coalescing pays most.
                for raw in sealed:
                    self._pending.append((raw, self.batcher.pop_seal_time()))
                self._drain_pending(short=n_polled < requested)
            else:
                for raw in sealed:
                    self._dispatch(raw, self.batcher.pop_seal_time())
                    self._reap(self.readback_depth)
            # Latency path: sink whatever the device has finished, every
            # iteration — including iterations that sealed nothing (the
            # depth cap above only bounds the pipe; waiting for it to
            # fill would defer verdicts by depth × batch-fill time).
            self._reap_ready()
            if not sealed and self.source.exhausted():
                if self.batcher.fill:
                    self._dispatch(self.batcher.take(), self.batcher.pop_seal_time())
                break
            if not sealed and not n_polled:
                if self._busy_depth() == 0:
                    # Proactive rung pre-sizing (engine/predict.py):
                    # inside the pre-warm lead window before a
                    # forecast burst onset, spend this otherwise-idle
                    # iteration re-dispatching the predicted rung with
                    # a masked zero-valid batch — results untouched
                    # (warm()'s masking argument), but the rung's
                    # step-time EWMA refreshes launch-absorbed, so
                    # _slo_cap prices the incoming burst with a HOT
                    # measurement instead of a stale one and the XLA
                    # executable/arena path is warm when the burst
                    # lands.  Idle iterations only: a pre-warm must
                    # never queue ahead of real traffic.
                    if self._gov is not None:
                        rung = self._gov.prewarm_rung(
                            time.perf_counter(),
                            self._rung_ewma_s.get(1, 0.0))
                        if rung:
                            # clamp the forecast rung to the READY set
                            # (a tiered warm may still be filling):
                            # pre-warming an uninstalled rung would
                            # spend the idle window on an inline
                            # compile instead of a hot re-dispatch
                            self._prewarm_dispatch(
                                self._rung_for(rung) if rung > 1
                                else rung)
                            continue
                    # Idle link: back off instead of spinning poll() at
                    # 100% CPU (sync/tuning.py IDLE_SLEEP_S, the
                    # daemon-matched cadence).  A fraction of the batch
                    # deadline keeps added latency under the flush
                    # budget.
                    time.sleep(tuning.idle_sleep_s(cfg_b.deadline_us))
                elif self._sink_active:
                    # Pipe busy, nothing new to dispatch: YIELD the GIL
                    # (sync/tuning.py GIL_YIELD_S — a spinning dispatch
                    # loop starved the sink thread's pure-Python
                    # decode/writeback, measured 10-25 ms sinks).
                    time.sleep(tuning.GIL_YIELD_S)

        # A bounded exit (max_batches/max_seconds) can in principle trip
        # with sealed group candidates still pending (span-boundary
        # partial seals make the per-iteration invariants fragile):
        # dispatch them singly — their records are already counted in
        # records_emitted, and leaving them would also wedge a later
        # reset_stream on a genuinely idle engine.
        for raw, t_seal in self._pending:
            self._dispatch(raw, t_seal)
        self._pending.clear()
        self._reap(0)
        return self._build_report(time.perf_counter() - t_start)

    def _run_sealed(
        self,
        max_batches: int | None = None,
        max_seconds: float | None = None,
    ) -> EngineReport:
        """The sharded-ingest serving loop: stage → dispatch → reap.

        Everything per-record — ring drain, decode, quantization, batch
        assembly — already happened in the drain workers; what is left
        on this thread is ONE shm-slot-view → dispatch-arena memcpy per
        batch (``poll_batches_into`` staging; the queue slot is
        released the moment the bytes land in the arena, before the
        batch is even dispatched) and the async dispatch, so the loop's
        cost scales with BATCHES, not records.  Groups dispatch as
        contiguous arena slices — no ``np.stack``, no consume copy.
        Semantics otherwise mirror :meth:`run`: depth-capped pipe,
        readiness reaping, ladder grouping on backlog
        (:meth:`_drain_pending`'s policy), deadline behavior delegated
        to the workers (they own the micro-batchers now).  A source
        without the staging API (a stub fleet) falls back to the
        copying ``poll_batches`` protocol with arena staging at
        dispatch time."""
        t_start = time.perf_counter()
        src = self.source
        if not self._t0_auto and hasattr(src, "set_t0"):
            # A fixed epoch (explicit t0_ns, or a restored checkpoint's
            # via restore()) must reach the worker fleet before its
            # min-first_ts handshake resolves: the workers seal device
            # times against THEIR t0, the sink translates until-ns with
            # OURS, and nothing downstream can reconcile the two.
            src.set_t0(self.batcher.t0_ns)

        def bounded() -> bool:
            if (max_batches is not None
                    and self.batcher.batches_emitted >= max_batches):
                return True
            if (max_seconds is not None
                    and time.perf_counter() - t_start >= max_seconds):
                return True
            return False

        if self._arena is not None and hasattr(src, "poll_batches_into"):
            if self.ring:
                self._sealed_loop_ring(src, bounded)
            else:
                self._sealed_loop_arena(src, bounded)
        else:
            self._sealed_loop_copy(src, bounded)
        for raw, t_seal in self._pending:
            self._dispatch(raw, t_seal)
        self._pending.clear()
        self._reap(0)
        return self._build_report(time.perf_counter() - t_start)

    def _adopt_fleet_t0(self, src) -> None:
        """The fleet's epoch handshake picked t0; adopt it for the
        device clock and the sink's ns translation."""
        self.batcher.t0_ns = src.t0_ns
        if hasattr(self.sink, "t0_ns"):
            self.sink.t0_ns = src.t0_ns
        self._t0_auto = False

    def _sealed_idle(self, src) -> bool:
        """Shared empty-poll tail of the sealed loops: True = source
        exhausted, stop serving."""
        if src.exhausted():
            return True
        if self._busy_depth() == 0:
            time.sleep(tuning.idle_sleep_s(self.cfg.batch.deadline_us))
        elif self._sink_active:
            # yield the GIL to the sink thread (sync/tuning.py)
            time.sleep(tuning.GIL_YIELD_S)
        return False

    def _sealed_loop_arena(self, src, bounded) -> None:
        """The zero-copy sealed loop (single-copy staging tentpole).

        One arena SLOT is live at a time: ``poll_batches_into`` stages
        sealed payloads into its rows at ``fill`` (releasing the shm
        slots immediately), the ladder dispatches contiguous
        ``rows[done:done+g]`` slices, and a fresh slot is claimed only
        after a USED slot fully dispatches — never on an empty poll, so
        the arena's reuse-safety rule (a slot recycles only after its
        batches are sunk; engine/arena.py) holds by construction."""
        top = self._mega_sizes[0] if self._mega_sizes else 0
        slo = self._slo_budget_s
        rows: np.ndarray | None = None
        fill = done = 0
        metas: list[tuple[float, int]] = []  # (t_enqueue, n_records)/row
        while not bounded():
            if rows is None or (fill and fill == done):
                rows = self._arena.rows(self._arena.claim())
                fill = done = 0
                metas = []
            want = len(rows) - fill
            if top:
                want = min(want, max(top - (fill - done), 0))
            batches = (src.poll_batches_into(
                rows[fill:], want,
                pop_timer=self.metrics.pop,
                stage_timer=self.metrics.stage) if want > 0 else [])
            if self._t0_auto and batches and src.t0_ns:
                self._adopt_fleet_t0(src)
            for sb in batches:
                # workers sealed these; mirror into the engine-side
                # counters the report and bounds are built on
                self.batcher.batches_emitted += 1
                self.batcher.records_emitted += sb.n_records
                self._staged_batches += 1
                self._staged_bytes += int(sb.raw.nbytes)
                metas.append((sb.t_enqueue, sb.n_records))
                fill += 1
            if self._gov is not None and batches:
                self._gov.note_arrivals(
                    time.perf_counter(),
                    sum(sb.n_records for sb in batches))
            # ``want == 0`` (slot rows exhausted under a pending carry)
            # must flush, not poll: treat it as a short poll.
            short = len(batches) < want or want == 0

            def flush(g: int) -> None:
                nonlocal done
                if g > 1:
                    t_e = min(m[0] for m in metas[done:done + g])
                    n = sum(m[1] for m in metas[done:done + g])
                    self._dispatch_group(rows[done:done + g], t_e, n)
                else:
                    self._dispatch(rows[done], metas[done][0])
                done += g
                self._reap(self.readback_depth)

            while top and fill - done >= top:
                # an existing top-rung backlog stays un-capped in SLO
                # mode (the sub-linear-step argument; _drain_pending)
                flush(top)
            # no ladder staged → singles dispatch as they arrive;
            # with a ladder, the remainder flushes only on a short
            # poll (a full poll means a backlog is still building) —
            # or, under --slo-us, the moment holding would cost the
            # oldest staged record its budget
            if short or not top or (
                    slo and fill - done
                    and self._slo_pressed(metas[done][0])):
                while fill - done:
                    g = self._rung_for(fill - done)
                    if slo:
                        g = min(g, self._slo_cap(metas[done][0]))
                    flush(g)
            self._reap_ready()
            if not batches and self._sealed_idle(src):
                break
        # bounded exit with staged-but-undispatched rows: flush singly
        # (their records are already counted in records_emitted, and a
        # wedged slot would also poison the next claim's safety rule)
        while fill - done:
            self._dispatch(rows[done], metas[done][0])
            done += 1

    def _sealed_loop_ring(self, src, bounded) -> None:
        """The device-loop sealed loop: the zero-copy staging protocol
        of :meth:`_sealed_loop_arena` feeding the drain ring.

        One arena slot at a time fills to exactly ``chunks`` batches
        (the staging memcpy is still the pipeline's ONE host copy);
        the moment a slot fills it is ``device_put`` — while the
        previous round still computes, which is the double-buffered
        H2D — and when ``ring`` slots are uploaded they launch as ONE
        deep-scan dispatch carrying table/stats across the whole round.
        A short poll degrades gracefully: uploaded slots flush through
        the ordinary top-rung megastep (byte-identical — the ring's
        slot body IS that megastep) and the partial slot drains through
        the coalescing ladder, so the ring only ever engages on
        backlogs that were queueing anyway.  The claim discipline is
        unchanged (a fresh slot only after the current one is staged
        away, never on an empty poll); the ring-aware slot bound
        (``DispatchArena.ring_safe_slots``) covers the up-to-``ring``
        in-flight uploads this loop adds."""
        c = self._ring_chunks
        slo = self._slo_budget_s
        uploaded: list[_Uploaded] = []
        rows: np.ndarray | None = None
        fill = 0
        metas: list[tuple[float, int]] = []  # (t_enqueue, n_records)/row
        while not bounded():
            if rows is None:
                rows = self._arena.rows(self._arena.claim())
                fill = 0
                metas = []
            want = c - fill
            batches = src.poll_batches_into(
                rows[fill:c], want,
                pop_timer=self.metrics.pop,
                stage_timer=self.metrics.stage) if want > 0 else []
            if self._t0_auto and batches and src.t0_ns:
                self._adopt_fleet_t0(src)
            for sb in batches:
                self.batcher.batches_emitted += 1
                self.batcher.records_emitted += sb.n_records
                self._staged_batches += 1
                self._staged_bytes += int(sb.raw.nbytes)
                metas.append((sb.t_enqueue, sb.n_records))
                fill += 1
            if self._gov is not None and batches:
                self._gov.note_arrivals(
                    time.perf_counter(),
                    sum(sb.n_records for sb in batches))
            short = len(batches) < want
            if fill == c:
                # slot full: upload NOW (overlapping in-flight compute)
                uploaded.append(self._upload_slot(
                    rows[:c], min(m[0] for m in metas),
                    sum(m[1] for m in metas)))
                rows = None
                if len(uploaded) == self.ring:
                    if self._ring_ready:
                        self._dispatch_ring(uploaded)
                    else:
                        # tiered warm still filling the deep-scan
                        # executable: flush the round's slots through
                        # the top-rung megastep (byte-identical — the
                        # ring's slot body IS that megastep), exactly
                        # the partial-round path below
                        for u in uploaded:
                            self._dispatch_group_dev(
                                u.dev, u.t_enqueue, u.n_records,
                                u.put_s)
                    uploaded = []
                    self._reap(self.readback_depth)
            elif short:
                # partial round: flush uploaded slots as megasteps
                # (arrival order before the younger partial slot)...
                for u in uploaded:
                    self._dispatch_group_dev(u.dev, u.t_enqueue,
                                             u.n_records, u.put_s)
                    self._reap(self.readback_depth)
                uploaded = []
                # ...then the partial slot through the ladder (rungs
                # budget-capped under --slo-us, like every ladder)
                if fill:
                    done = 0
                    while fill - done:
                        g = self._rung_for(fill - done)
                        if slo:
                            g = min(g, self._slo_cap(metas[done][0]))
                        if g > 1:
                            self._dispatch_group(
                                rows[done:done + g],
                                min(m[0] for m in metas[done:done + g]),
                                sum(m[1] for m in metas[done:done + g]))
                        else:
                            self._dispatch(rows[done], metas[done][0])
                        done += g
                        self._reap(self.readback_depth)
                    rows = None
            if (slo and uploaded
                    and not self._slo_round_fits(uploaded[0].t_enqueue)):
                # the device-loop round sizer: waiting to fill the
                # whole ring would cost the oldest uploaded slot its
                # budget — flush the uploaded slots through the
                # ordinary top-rung megastep NOW (byte-identical; the
                # ring's slot body IS that megastep) and let the next
                # round start fresh.  Degrade-to-smaller, not queue.
                for u in uploaded:
                    self._dispatch_group_dev(u.dev, u.t_enqueue,
                                             u.n_records, u.put_s)
                    self._reap(self.readback_depth)
                uploaded = []
            self._reap_ready()
            if not batches and self._sealed_idle(src):
                break
        # bounded exit: drain uploaded slots, then any staged rows
        for u in uploaded:
            self._dispatch_group_dev(u.dev, u.t_enqueue, u.n_records,
                                     u.put_s)
        if rows is not None and fill:
            for i in range(fill):
                self._dispatch(rows[i], metas[i][0])

    def _sealed_loop_copy(self, src, bounded) -> None:
        """Legacy copying protocol (sources without
        ``poll_batches_into``): dequeue private copies, group through
        the inline pending ladder (arena staging happens at dispatch
        time in :meth:`_dispatch_mega`)."""
        while not bounded():
            with self.metrics.fill.time():
                want = (max(self._pending_cap - len(self._pending), 1)
                        if self.mega_n > 0 else 4)
                batches = src.poll_batches(want)
                if self._t0_auto and batches and src.t0_ns:
                    self._adopt_fleet_t0(src)
                for sb in batches:
                    self.batcher.batches_emitted += 1
                    self.batcher.records_emitted += sb.n_records
                if self._gov is not None and batches:
                    self._gov.note_arrivals(
                        time.perf_counter(),
                        sum(sb.n_records for sb in batches))
            if self.mega_n > 0:
                for sb in batches:
                    self._pending.append((sb.raw, sb.t_enqueue))
                self._drain_pending(short=len(batches) < want)
            else:
                for sb in batches:
                    self._dispatch(sb.raw, sb.t_enqueue)
                    self._reap(self.readback_depth)
            self._reap_ready()
            if not batches and self._sealed_idle(src):
                break

    def _build_report(self, wall: float) -> EngineReport:
        # "now" on the device clock (t0-anchored stream seconds, not wall
        # time) comes from the reaped step outputs — no extra reduction.
        table_sum = pallas_kernels.table_summary(
            self.table, now=self._device_now, stale_s=self.cfg.table.stale_s
        )

        readback = {
            "mode": "compact" if self.verdict_k else "full",
            "k_max": self.verdict_k,
            "wire_bytes": (fused.verdict_wire_words(self.verdict_k) * 4
                           if self.verdict_k else None),
            "compact_sinks": self._sink_compact,
            "fallback_sinks": self._sink_fallback,
            "d2h_bytes": self._d2h_bytes,
            "bytes_per_batch": round(
                self._d2h_bytes / max(self._sunk_batches, 1), 1),
            "sink_thread": self.sink_thread,
            "sink_occupancy": (round(
                self._chan.busy_s / max(wall, 1e-9), 4)
                if self.sink_thread else None),
        }

        # Dispatch-pipeline accounting.  host_copies_per_batch counts
        # ENGINE-side host memcpys per dispatched batch: arena staging
        # is the zero-copy pipeline's one copy (sealed path == 1.0);
        # the subsequent device_put of the page-aligned slice is the
        # host↔device boundary itself, not a host copy.  Inline singles
        # dispatch the batcher's own buffer (no staging), so a pure
        # inline single-dispatch run reads 0.0.
        # Device-loop accounting: rounds, the per-round shape, ring
        # occupancy (how much of the staged flow went through full
        # rounds vs partial-backlog slot flushes) and the measured H2D
        # overlap — the "device never waits on the host" claim as a
        # number, re-proved per run by scripts/device_loop_smoke.py.
        device_loop = None
        if self.ring:
            full = self._ring_rounds * self.ring
            staged_slots = full + self._ring_partial_slots
            device_loop = {
                "ring": self.ring,
                "chunks_per_slot": self._ring_chunks,
                "batches_per_round": self.ring * self._ring_chunks,
                "rounds": self._ring_rounds,
                "steps_per_round": self.ring,   # megasteps / round trip
                "partial_slot_flushes": self._ring_partial_slots,
                "ring_occupancy": round(full / staged_slots, 4)
                if staged_slots else 0.0,
                "h2d": {
                    "puts": self._h2d_puts,
                    "puts_overlapped": self._h2d_puts_overlapped,
                    "put_s": round(self._h2d_put_s, 6),
                    "overlap_s": round(self._h2d_overlap_s, 6),
                    "overlap_fraction": round(
                        self._h2d_overlap_s / self._h2d_put_s, 4)
                    if self._h2d_put_s else 0.0,
                },
            }
        dispatch = {
            "mode": ("device_loop" if self.ring
                     else "adaptive" if self.mega_auto
                     else "fixed" if self.mega_n else "single"),
            "mega_n": self.mega_n,
            "device_loop": device_loop,
            # latency-budget serving (--slo-us): the budget and the
            # warm-measured per-rung step-time EWMA the deadline-aware
            # policy bounded coalescing with.  None = throughput mode.
            "slo": ({
                "slo_us": self.slo_us,
                # negated keys are ring ROUNDS (attribute docstring)
                "rung_ewma_ms": {
                    (str(k) if k > 0 else f"round{-k}"):
                        round(v * 1e3, 4)
                    for k, v in sorted(self._rung_ewma_s.items())},
            } if self.slo_us else None),
            "group_sizes": list(self._mega_sizes),
            "group_hist": {str(k): v for k, v in
                           sorted(self._group_hist.items())},
            "dispatches": self._dispatch_calls,
            "dispatch_hz": round(
                self._dispatch_calls / max(wall, 1e-9), 1),
            "staged_batches": self._staged_batches,
            "staged_bytes": self._staged_bytes,
            "host_copies_per_batch": round(
                self._staged_batches / max(self._dispatched_chunks, 1),
                3),
            "arena": (self._arena.info()
                      if self._arena is not None else None),
        }

        escalation = None
        if self.kernel_tier is not None:
            escalation = self.kernel_tier.report()
            escalation["kernel_drop_hz"] = round(
                (escalation.get("kernel_drops", 0)
                 + escalation.get("blacklist_hits", 0)) / max(wall, 1e-9),
                1)

        # explicit D2H for the report counters (transfer-guard contract)
        st = schema.GlobalStats(*jax.device_get(tuple(self.stats)))
        ingest_stats = (self.source.ingest_stats()
                        if self.sealed and hasattr(self.source,
                                                   "ingest_stats")
                        else None)
        cluster_rep = (self.gossip.report()
                       if self.gossip is not None else None)
        # Boot-latency block (ISSUE 20): one consistent snapshot of the
        # warm/fill story (the fill thread publishes whole-dict
        # rebinds, so a single read is coherent even mid-fill) plus
        # the sink-stamped time-to-first-verdict and the caller-
        # stamped import wall.
        boot_rep = None
        boot_snap = self._boot
        if boot_snap is not None:
            boot_rep = dict(boot_snap)
            boot_rep["import_s"] = round(self.boot_import_s, 4)
            boot_rep["time_to_first_verdict_s"] = (
                round(self._first_verdict_s, 4)
                if self._first_verdict_s is not None else None)
            boot_rep["fill_active"] = self.warm_fill_active()
        predict_rep = None
        if self._gov is not None:
            predict_rep = self._gov.report()
            if cluster_rep is not None:
                # fold the shed counters in next to the actuation
                # counters they motivate — one block to alert on
                predict_rep["gossip_ticks_deferred"] = cluster_rep.get(
                    "ticks_deferred", 0)
                predict_rep["net_resync_deferred"] = (
                    cluster_rep.get("net") or {}).get(
                        "resync_deferred", 0)
        return EngineReport(
            batches=self.batcher.batches_emitted,
            records=self.batcher.records_emitted,
            wall_s=round(wall, 4),
            records_per_s=round(self.batcher.records_emitted / max(wall, 1e-9), 1),
            stats=st.to_dict(),
            stages_ms=self.metrics.to_dict(),
            blocked_sources=len(self._blocked),
            table=table_sum,
            ts_wrap_risk_polls=self.batcher.ts_wrap_risk_polls,
            route_drop=self._route_drop,
            ingest=ingest_stats,
            readback=readback,
            dispatch=dispatch,
            escalation=escalation,
            cluster=cluster_rep,
            # compute_is_wall: on backends that execute the step graph
            # synchronously at dispatch (XLA:CPU scatter custom-calls)
            # the launch wall IS the compute; a CPU backend is the
            # honest proxy for that here
            latency=self._lat.to_dict(
                self.slo_us,
                compute_is_wall=jax.devices()[0].platform == "cpu"),
            # the health ladder is a pure function of the blocks above
            # (engine/health.py): impossible to drift from the counters
            health=health.engine_health(
                ingest=ingest_stats,
                gossip=cluster_rep,
                watchdog=self._watchdog.to_dict(),
                restore_fallbacks=self._restore_fallbacks,
                rebalance=self._rebalance or None),
            rebalance=dict(self._rebalance) or None,
            predict=predict_rep,
            boot=boot_rep,
        )


# ---------------------------------------------------------------------------
# ring-depth autotuning (fsx serve --device-loop auto)
# ---------------------------------------------------------------------------

def calibrate_ring_depth(
    cfg: FsxConfig,
    params: Any | None = None,
    mesh: Any | None = None,
    mega_n: int | str = "auto",
    candidates: tuple[int, ...] = (2, 4, 8),
    batches: int = 48,
    seed: int = 17,
) -> tuple[int, dict]:
    """Measure a short synthetic calibration drain at each candidate
    ring depth and pick one (``fsx serve --device-loop auto``).

    The drive half of the autotuner: for every candidate depth a
    throwaway engine serves a deep prefilled synthetic backlog through
    the inline ring path, and the measured
    ``dispatch["device_loop"]`` block — H2D ``overlap_fraction`` above
    all, the number the ring exists to maximize — feeds the pure
    policy in :func:`flowsentryx_tpu.fused.device_loop
    .choose_ring_depth`.  Each candidate stages its own deep-scan
    graph, so calibration costs one XLA compile per depth — seconds,
    paid once at the boot of a long-lived server (announced by the
    CLI), exactly like ``warm()``.

    Table/stats state never leaks into serving: every candidate runs
    its own engine and the caller boots a FRESH engine at the chosen
    depth.
    """
    from flowsentryx_tpu.engine.sources import ArraySource
    from flowsentryx_tpu.engine.traffic import (
        Scenario, TrafficGen, TrafficSpec,
    )
    from flowsentryx_tpu.engine.writeback import NullSink

    recs = TrafficGen(TrafficSpec(
        scenario=Scenario.UDP_FLOOD_MULTI, rate_pps=1e7,
        n_attack_ips=8, n_benign_ips=24, attack_fraction=0.8,
        seed=seed,
    )).next_records(batches * cfg.batch.max_batch)
    measurements: list[dict] = []
    for d in sorted(set(int(c) for c in candidates)):
        eng = Engine(cfg, ArraySource(np.copy(recs)), NullSink(),
                     params=params, mesh=mesh, mega_n=mega_n,
                     device_loop=d, sink_thread=False)
        eng.warm()
        t0 = time.perf_counter()
        rep = eng.run()
        wall = time.perf_counter() - t0
        dl = rep.dispatch["device_loop"]
        measurements.append({
            "ring": d,
            "rounds": dl["rounds"],
            "ring_occupancy": dl["ring_occupancy"],
            "overlap_fraction": dl["h2d"]["overlap_fraction"],
            "records_per_s": round(rep.records / max(wall, 1e-9), 1),
        })
    from flowsentryx_tpu.fused.device_loop import choose_ring_depth

    depth, detail = choose_ring_depth(measurements)
    detail["calibration_batches"] = batches
    return depth, detail
