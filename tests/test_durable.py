"""core/durable.py — the one atomic-write helper every durable-state
protocol publishes through, and the fs seam the crash checker injects
its simulated filesystem into."""

import os
from pathlib import Path

import pytest

from flowsentryx_tpu.core import durable


class TestAtomicWrite:
    def test_publish_bytes_and_str(self, tmp_path):
        p = tmp_path / "layout.json"
        durable.atomic_write(p, b'{"generation": 1}')
        assert p.read_bytes() == b'{"generation": 1}'
        durable.atomic_write(p, '{"generation": 2}')
        assert p.read_text() == '{"generation": 2}'

    def test_no_temp_residue(self, tmp_path):
        p = tmp_path / "handoff.json"
        durable.atomic_write(p, b"x" * 4096)
        assert [f.name for f in tmp_path.iterdir()] == ["handoff.json"]

    def test_rotate_prev_retains_incumbent(self, tmp_path):
        p = tmp_path / "ckpt.npz"
        prev = tmp_path / "ckpt.npz.prev"
        durable.atomic_write(p, b"gen1", rotate_prev=prev)
        assert not prev.exists()  # first publish: nothing to retain
        durable.atomic_write(p, b"gen2", rotate_prev=prev)
        assert p.read_bytes() == b"gen2"
        assert prev.read_bytes() == b"gen1"
        durable.atomic_write(p, b"gen3", rotate_prev=prev)
        assert prev.read_bytes() == b"gen2"  # exactly one generation back

    def test_overwrite_without_rotation(self, tmp_path):
        p = tmp_path / "f"
        durable.atomic_write(p, b"a")
        durable.atomic_write(p, b"b")
        assert p.read_bytes() == b"b"
        assert not (tmp_path / "f.prev").exists()

    def test_failed_write_cleans_tmp_and_keeps_incumbent(self, tmp_path):
        p = tmp_path / "f"
        durable.atomic_write(p, b"good")
        with pytest.raises(TypeError):
            durable.atomic_write(p, 12345)  # not bytes-like: os.write raises
        assert p.read_bytes() == b"good"
        assert [f.name for f in tmp_path.iterdir()] == ["f"]


class TestRealFSSurface:
    def test_read_side(self, tmp_path):
        fs = durable.get_fs()
        p = tmp_path / "x"
        assert not fs.exists(p)
        durable.atomic_write(p, b"abc")
        assert fs.exists(p)
        assert fs.size(p) == 3
        assert fs.read_bytes(p) == b"abc"
        assert fs.read_text(p) == "abc"
        fs.unlink(p)
        assert not fs.exists(p)


class _SpyFS:
    name = "spy"

    def __init__(self):
        self.writes = []

    def write_atomic(self, path, data, *, fsync=True, rotate_prev=None):
        self.writes.append((Path(path).name, bytes(data)
                            if not isinstance(data, str)
                            else data.encode(), rotate_prev))


class TestSeam:
    def test_use_fs_scopes_and_restores(self, tmp_path):
        real = durable.get_fs()
        spy = _SpyFS()
        with durable.use_fs(spy):
            assert durable.get_fs() is spy
            # module-level atomic_write resolves through the seam AT
            # CALL TIME — this is what routes every protocol publish
            # into the crash checker's simulated fs
            durable.atomic_write(tmp_path / "layout.json", b"sim")
        assert durable.get_fs() is real
        assert spy.writes == [("layout.json", b"sim", None)]
        assert not (tmp_path / "layout.json").exists()

    def test_use_fs_restores_on_error(self):
        real = durable.get_fs()
        with pytest.raises(RuntimeError):
            with durable.use_fs(_SpyFS()):
                raise RuntimeError("boom")
        assert durable.get_fs() is real

    def test_protocol_modules_publish_through_seam(self, tmp_path):
        # the three deduped idioms: layout.json, the staged spool, and
        # checkpoint save all surface as seam writes
        import numpy as np

        from flowsentryx_tpu.cluster import rebalance as rb

        spy = _SpyFS()
        with durable.use_fs(spy):
            rb.ShardAssignment.initial(4, 2, 2).save(tmp_path)
            rb.save_spool(tmp_path / "sp.npz",
                          np.asarray([1], np.uint32),
                          np.zeros((1, 12), np.float32),
                          handoff_id=1, to_gen=1)
        names = [w[0] for w in spy.writes]
        assert names == ["layout.json", "sp.npz"]
        assert not (tmp_path / "layout.json").exists()

    def test_fsync_durability_contract_real_disk(self, tmp_path):
        # "returns => durable" can't be power-tested here (that is the
        # crash checker's job on the sim fs); on the real fs we assert
        # the weaker observable: the publish is complete and readable
        # the moment atomic_write returns, no flush step owed
        p = tmp_path / "ck"
        durable.atomic_write(p, b"payload", fsync=True)
        fd = os.open(p, os.O_RDONLY)
        try:
            assert os.read(fd, 16) == b"payload"
        finally:
            os.close(fd)
