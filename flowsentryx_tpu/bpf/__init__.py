"""In-repo eBPF toolchain: assembler, raw-syscall loader, XDP programs.

This package replaces the clang/libbpf build dependency the image lacks
(there is no clang with a BPF target anywhere in the environment — see
docs/BPF_BUILD.md) with a first-party toolchain:

* :mod:`isa` — BPF instruction encodings (the stable kernel uapi ISA);
* :mod:`asm` — a macro assembler (labels, map relocations, helpers);
* :mod:`loader` — raw ``bpf(2)`` syscall loader: map create/update,
  PROG_LOAD with the real in-kernel verifier, PROG_TEST_RUN with
  crafted packets, and an mmap'd ringbuf consumer;
* :mod:`progs` — the fsx XDP fast path, hand-assembled, mirroring
  kern/fsx_kern.c instruction for instruction in semantics;
* :mod:`verifier` — an in-repo static verifier: the kernel verifier's
  safety contract (packet bounds proofs, stack init, map-value bounds,
  helper contracts, CFG checks) checkable with no kernel in the loop;
  runs automatically before every prog_load and image seal;
* :mod:`contracts` — the cross-layer wire-format contract checker
  (schema ↔ generated header ↔ baked progs.py offsets ↔ sealed
  images), surfaced with the verifier as ``fsx check``;
* :mod:`elf` — emits a standard relocatable ELF object (kern/fsx_kern.o
  successor of the reference's checked-in src/fsx_kern.o).

The reference loads its program with ``bpftool prog load``
(/root/reference/TODO.md:282-289) and a broken BCC stub
(/root/reference/src/fsx_load.py:10-17); this package performs the same
kernel handshake (BPF_MAP_CREATE/BPF_PROG_LOAD/BPF_PROG_TEST_RUN
syscalls) without external tooling, so the data plane is testable
against the real verifier inside any container that grants bpf().
"""

from flowsentryx_tpu.bpf.loader import bpf_available  # noqa: F401
