"""The interval domain of the ``fsx ranges`` prover.

An abstract value (:class:`IVal`) is a pair of numpy *object* arrays
``(lo, hi)`` holding exact Python ints (integer/bool variables) or
floats (float variables).  Object dtype is load-bearing: interval
arithmetic on u32/u64 operands routinely produces intermediates past
2^64 (that is exactly what the prover exists to catch), and an int64
carrier would wrap inside the checker itself.

Shapes are deliberately restricted to two canonical forms:

* **scalar** — ``()``: one interval covering every element of the
  variable (the common case; a table column, a batch vector);
* **full** — exactly the variable's aval shape: one interval per
  element (the wire buffers, where the metadata row and the record
  rows carry different contracts and per-element precision is what
  keeps e.g. ``n = meta[0]`` provably within ``[0, B]``).

Anything whose full form would exceed :data:`FULL_CAP` elements
collapses to the scalar join — sound, merely less precise.
"""

from __future__ import annotations

import math

import numpy as np

#: Elements above which a per-element interval array collapses to its
#: scalar join.  The wire buffers ([B+1, 12] at the default batch) and
#: the device-loop's on-device ``[R, C, B+1, 4]`` slot stack are far
#: inside it; a 1M-row table column is outside (and needs no per-row
#: precision: its seed is one contract for every row).  Object arrays
#: store pointers, so even the cap costs ~16 MB transiently.
FULL_CAP = 1 << 21

_INF = float("inf")


def _as_obj(x) -> np.ndarray:
    """Normalize to an object ndarray (numpy ops on 0-d object arrays
    return bare Python scalars; every IVal re-wraps them)."""
    if isinstance(x, np.ndarray):
        return x
    a = np.empty((), dtype=object)
    a[()] = x
    return a


class IVal:
    """One abstract value: elementwise ``[lo, hi]`` (see module doc)."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo, hi):
        self.lo = _as_obj(lo)  # object dtype; () or the var's shape
        self.hi = _as_obj(hi)

    def is_scalar(self) -> bool:
        return self.lo.shape == ()

    def bounds(self) -> tuple:
        """Collapsed global (lo, hi) as Python numbers."""
        return (self.lo.min() if self.lo.shape else self.lo[()],
                self.hi.max() if self.hi.shape else self.hi[()])

    def collapse(self) -> "IVal":
        lo, hi = self.bounds()
        return scalar(lo, hi)

    def __repr__(self) -> str:  # diagnostics
        lo, hi = self.bounds()
        shape = "" if self.is_scalar() else f" shape{self.lo.shape}"
        return f"IVal[{lo}, {hi}]{shape}"


def _obj(x) -> np.ndarray:
    a = np.empty((), dtype=object)
    a[()] = x
    return a


def scalar(lo, hi) -> IVal:
    return IVal(_obj(lo), _obj(hi))


def const_of(value) -> IVal:
    """Exact IVal of a concrete numpy array / scalar (jaxpr literals
    and consts).  Small arrays keep per-element precision; big ones
    collapse to their min/max."""
    a = np.asarray(value)
    if a.dtype == np.bool_:
        a = a.astype(np.int64)
    if a.size == 0:
        return scalar(0, 0)
    if a.size <= FULL_CAP and a.shape != ():
        if a.dtype.kind in "iub":
            o = np.frompyfunc(int, 1, 1)(a)
        else:
            o = np.frompyfunc(float, 1, 1)(a)
        return IVal(o, o.copy())
    if a.dtype.kind in "iub":
        return scalar(int(a.min()), int(a.max()))
    lo, hi = float(a.min()), float(a.max())
    if math.isnan(lo) or math.isnan(hi):
        return scalar(-_INF, _INF)
    return scalar(lo, hi)


def dtype_bounds(dtype) -> tuple:
    """(min, max) representable in ``dtype`` — the escape-check fence.
    Floats and complex get ``(-inf, inf)`` (never escape-checked)."""
    dt = np.dtype(dtype)
    if dt.kind == "b":
        return 0, 1
    if dt.kind in "iu":
        info = np.iinfo(dt)
        return int(info.min), int(info.max)
    return -_INF, _INF


def is_int_dtype(dtype) -> bool:
    return np.dtype(dtype).kind in "iub"


def top_for(dtype) -> IVal:
    lo, hi = dtype_bounds(dtype)
    return scalar(lo, hi)


def join(a: IVal, b: IVal) -> IVal:
    """Elementwise union (numpy broadcasting); incompatible shapes
    collapse both sides first."""
    try:
        return IVal(emin(a.lo, b.lo), emax(a.hi, b.hi))
    except ValueError:
        a, b = a.collapse(), b.collapse()
        return IVal(emin(a.lo, b.lo), emax(a.hi, b.hi))


def join_all(vals: list[IVal]) -> IVal:
    out = vals[0]
    for v in vals[1:]:
        out = join(out, v)
    return out


def equal(a: IVal, b: IVal) -> bool:
    return (a.lo.shape == b.lo.shape and bool(np.all(a.lo == b.lo))
            and bool(np.all(a.hi == b.hi)))


def guard_cap(v: IVal) -> IVal:
    """Collapse a full array past :data:`FULL_CAP` (the materialization
    fence every structural handler routes through)."""
    if v.lo.size > FULL_CAP:
        return v.collapse()
    return v


# -- exact elementwise arithmetic -------------------------------------------

def add(a: IVal, b: IVal) -> IVal:
    return IVal(a.lo + b.lo, a.hi + b.hi)


def sub(a: IVal, b: IVal) -> IVal:
    return IVal(a.lo - b.hi, a.hi - b.lo)


def neg(a: IVal) -> IVal:
    return IVal(-a.hi, -a.lo)


def emin(a, b):
    """Elementwise min that survives arbitrary-magnitude Python ints:
    numpy's ufunc degrades 0-d object results to bare scalars, and a
    bare int past 2^63 then fails the C-long coercion on the next
    call — so the all-scalar case stays in pure Python."""
    a, b = _as_obj(a), _as_obj(b)
    if a.shape == () and b.shape == ():
        return _as_obj(min(a[()], b[()]))
    return np.minimum(a, b)


def emax(a, b):
    a, b = _as_obj(a), _as_obj(b)
    if a.shape == () and b.shape == ():
        return _as_obj(max(a[()], b[()]))
    return np.maximum(a, b)


def _minmax4(p1, p2, p3, p4) -> IVal:
    lo = emin(emin(p1, p2), emin(p3, p4))
    hi = emax(emax(p1, p2), emax(p3, p4))
    return IVal(lo, hi)


def mul(a: IVal, b: IVal) -> IVal:
    return _minmax4(a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi)


_shl = np.frompyfunc(lambda x, s: x * (1 << max(int(s), 0)), 2, 1)


def shift_left(a: IVal, s: IVal) -> IVal:
    """Mathematical ``x * 2^s`` (pre-wrap; the escape check decides
    whether the dtype can hold it)."""
    return _minmax4(_shl(a.lo, s.lo), _shl(a.lo, s.hi),
                    _shl(a.hi, s.lo), _shl(a.hi, s.hi))


_ashr = np.frompyfunc(lambda x, s: int(x) >> max(int(s), 0), 2, 1)


def shift_right_arith(a: IVal, s: IVal) -> IVal:
    return _minmax4(_ashr(a.lo, s.lo), _ashr(a.lo, s.hi),
                    _ashr(a.hi, s.lo), _ashr(a.hi, s.hi))


def shift_right_logical(a: IVal, s: IVal, dtype) -> IVal:
    lo, _ = a.bounds()
    if lo < 0:
        # negative lanes reinterpret as huge unsigned values; the
        # result only narrows back to [0, 2^bits-1 >> s] — dtype-top
        # is the sound cover for a signed carrier
        return top_for(dtype)
    return shift_right_arith(a, s)


_bitlen = np.frompyfunc(lambda x: int(x).bit_length(), 1, 1)


def bit_and(a: IVal, b: IVal, dtype) -> IVal:
    alo, _ = a.bounds()
    blo, _ = b.bounds()
    if alo < 0 or blo < 0:
        return top_for(dtype)
    hi = _as_obj(emin(a.hi, b.hi))
    return IVal(hi * 0, hi)


def bit_or_xor(a: IVal, b: IVal, dtype, is_or: bool) -> IVal:
    alo, _ = a.bounds()
    blo, _ = b.bounds()
    if alo < 0 or blo < 0:
        return top_for(dtype)
    bits = _as_obj(emax(_bitlen(a.hi), _bitlen(b.hi)))
    hi = _as_obj(_shl(bits * 0 + 1, bits)) - 1
    lo = emax(a.lo, b.lo) if is_or else _as_obj(hi) * 0
    return IVal(lo, hi)


def _fd(x, y):
    if not y:
        return 0
    if isinstance(x, int) and isinstance(y, int):
        return x // y  # exact — float division rounds past 2^53
    return math.floor(x / y)


def _cd(x, y):
    if not y:
        return 0
    if isinstance(x, int) and isinstance(y, int):
        return -(-x // y)
    return math.ceil(x / y)


_floordiv = np.frompyfunc(_fd, 2, 1)
_ceildiv = np.frompyfunc(_cd, 2, 1)


def div(a: IVal, b: IVal, dtype) -> IVal:
    """Integer division (covers both trunc and floor semantics: the
    result always lies in [floor(min), ceil(max)] over the operand
    corners).  A divisor range containing 0 yields dtype-top."""
    blo, bhi = b.bounds()
    if blo <= 0 <= bhi:
        if is_int_dtype(dtype):
            return top_for(dtype)
        return scalar(-_INF, _INF)
    if not is_int_dtype(dtype):
        c = [a.lo / b.lo, a.lo / b.hi, a.hi / b.lo, a.hi / b.hi]
        return _minmax4(*c)
    lo = emin(emin(_floordiv(a.lo, b.lo), _floordiv(a.lo, b.hi)),
              emin(_floordiv(a.hi, b.lo), _floordiv(a.hi, b.hi)))
    hi = emax(emax(_ceildiv(a.lo, b.lo), _ceildiv(a.lo, b.hi)),
              emax(_ceildiv(a.hi, b.lo), _ceildiv(a.hi, b.hi)))
    return IVal(lo, hi)


def rem(a: IVal, b: IVal, dtype) -> IVal:
    """lax.rem (sign follows the dividend)."""
    blo, bhi = b.bounds()
    if blo <= 0 <= bhi or not is_int_dtype(dtype):
        return top_for(dtype)
    m = max(abs(blo), abs(bhi)) - 1
    alo, _ = a.bounds()
    return scalar(-m if alo < 0 else 0, m)


def vmin(a: IVal, b: IVal) -> IVal:
    return IVal(emin(a.lo, b.lo), emin(a.hi, b.hi))


def vmax(a: IVal, b: IVal) -> IVal:
    return IVal(emax(a.lo, b.lo), emax(a.hi, b.hi))


def clamp(lo_b: IVal, x: IVal, hi_b: IVal) -> IVal:
    return vmin(vmax(x, lo_b), hi_b)


def absolute(a: IVal) -> IVal:
    lo = emax(emax(a.lo, -a.hi), 0 * a.lo)
    hi = emax(np.abs(a.lo), np.abs(a.hi))
    return IVal(lo, hi)


def int_pow(a: IVal, y: int) -> IVal:
    c1, c2 = a.lo ** y, a.hi ** y
    lo, hi = _as_obj(emin(c1, c2)), emax(c1, c2)
    if y % 2 == 0:
        straddle = (a.lo <= 0) & (a.hi >= 0)
        lo = np.where(straddle, 0 * lo, lo)
    return IVal(lo, hi)


# -- float helpers ----------------------------------------------------------

def float_top() -> IVal:
    return scalar(-_INF, _INF)


def finite(v: IVal) -> bool:
    lo, hi = v.bounds()
    try:
        return math.isfinite(lo) and math.isfinite(hi)
    except TypeError:  # huge ints are fine
        return True


_MONOTONE_F = {
    "exp": math.exp,
    "exp2": lambda x: 2.0 ** x,
    "log1p": math.log1p,
    "expm1": math.expm1,
    "sqrt": lambda x: math.sqrt(max(x, 0.0)),
    "floor": math.floor,
    "ceil": math.ceil,
    "round_nearest_even": round,
    "round": round,
    "tanh": math.tanh,
    "erf": math.erf,
    "sin": None, "cos": None,  # non-monotone: handled as [-1, 1]
}


def float_unary(name: str, a: IVal) -> IVal:
    if name == "logistic":
        return scalar(0.0, 1.0)
    if name in ("sin", "cos"):
        return scalar(-1.0, 1.0)
    f = _MONOTONE_F.get(name)
    if f is None or not finite(a):
        if name in ("tanh", "erf"):
            return scalar(-1.0, 1.0)
        return float_top()
    lo, hi = a.bounds()
    try:
        return scalar(f(float(lo)), f(float(hi)))
    except (OverflowError, ValueError):
        return float_top()
