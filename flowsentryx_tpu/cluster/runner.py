"""Cluster engine-process entrypoints.

:func:`engine_main` is what one ``fsx cluster`` engine process runs: a
full serving engine (jax, drain workers, dispatch arena, optional
device loop) owning one IP-space shard span, wired into the gossip
plane, honoring the supervisor's lifecycle protocol (status block
states, heartbeats via the gossip tick, stop-drain on ``c_stop``) and
writing its :class:`~flowsentryx_tpu.engine.engine.EngineReport` as
JSON where the supervisor can aggregate it.

:func:`stub_engine_main` is the lifecycle-protocol conformance stub:
it speaks the SAME status-block protocol (spawning → serving →
done/failed, heartbeats, stop, scripted crash) but boots in
milliseconds with no jax import — the supervisor's restart machinery
is tested against it in tier-1 without paying four engine boots, and
the real-engine integration is proved once per verify run by
``scripts/cluster_smoke.py``.

Both run as ``multiprocessing`` spawn targets and immediately move
into their OWN process group: the engine's drain workers inherit it,
so the supervisor can ``killpg`` the whole tree when cleaning up a
crashed engine — an orphaned worker left consuming a ring shard while
its replacement boots would be a second consumer on an SPSC ring.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback
from pathlib import Path

from flowsentryx_tpu.cluster.gossip import GossipPlane
from flowsentryx_tpu.cluster.mailbox import StatusBlock, status_path
from flowsentryx_tpu.core import schema
from flowsentryx_tpu.sync import tuning


def _own_process_group() -> None:
    try:
        os.setpgid(0, 0)
    except OSError:
        pass  # already a group leader, or a platform without setpgid


def pin_core_for(rank: int, n_engines: int, mode: str = "auto",
                 ncpu: int | None = None) -> int | None:
    """Pinning policy (pure): which core rank ``rank`` of ``n_engines``
    should own, or None to leave placement to the scheduler.

    ``auto`` pins rank r to core r exactly when the fleet fits the
    host (``n_engines <= ncpu``) — the per-core deployment shape
    (FENXI-style parallel pipelines): each engine and the drain
    workers that inherit its mask own one core, so co-scheduled
    engines never thrash each other's XLA pools.  An oversubscribed
    fleet is left unpinned (forcing two engines to time-slice one
    core while another idles is strictly worse than letting the
    scheduler balance).  ``on`` pins regardless (modulo the host);
    ``off`` never pins.
    """
    ncpu = ncpu or os.cpu_count() or 1
    if mode == "off":
        return None
    if mode == "auto" and n_engines > ncpu:
        return None
    return rank % ncpu


def pin_to_core(core: int) -> None:
    """Pin this engine process to ``core`` and right-size the XLA:CPU
    intra-op pool to match.  The pool is sized from
    ``hardware_concurrency``, which ignores the affinity mask — a
    pinned rank would otherwise time-slice an ncpu-thread pool on its
    single core (measured ~10-20% per-core throughput loss on the
    sealed-drain shape).  XLA reads ``XLA_FLAGS`` at backend
    initialization, not at import, so setting it here — before the
    engine's first jax use — is early enough even though the spawn
    target's module imports already pulled jax in."""
    os.sched_setaffinity(0, {core})
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_multi_thread_eigen=false"
          " intra_op_parallelism_threads=1").strip()


def _wait_for_token(path: str, timeout_s: float = 120.0) -> None:
    deadline = time.monotonic() + timeout_s
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError(f"start token {path} never appeared")
        time.sleep(0.005)


def engine_main(spec: dict) -> int:
    """One cluster engine process (module docstring).  ``spec`` is a
    plain JSON-able dict assembled by the supervisor/CLI — see
    ``supervisor.py::engine_spec`` for the fields."""
    _own_process_group()
    os.environ.setdefault("JAX_PLATFORMS",
                          spec.get("jax_platform", "cpu"))
    if spec.get("pin_core") is not None:
        pin_to_core(spec["pin_core"])
    net = None
    if spec.get("net"):
        # the multi-host gossip leg (cluster/transport.py): built in
        # the child — the socket must live in the engine process, its
        # counters ride EngineReport.cluster.net.  Jax-free, so this
        # stays on the fast half of the boot.
        from flowsentryx_tpu.cluster.transport import engine_net_mailbox

        net = engine_net_mailbox(spec["net"], spec["rank"],
                                 spec["t0_ns"], spec["t0_wall_ns"])
    plane = GossipPlane(spec["cluster_dir"], spec["rank"],
                        spec["n_engines"], net=net)
    # pid in the status block: the adopt path's liveness probe
    # (``boot(adopt=True)`` judges an unowned rank by os.kill(pid, 0)
    # + heartbeat freshness — a proc handle it never had can't help)
    plane.status.ctl_set("c_pid", os.getpid())
    plane.set_state(schema.CSTATE_SPAWNING)
    try:
        _serve(spec, plane)
        plane.set_state(schema.CSTATE_DONE)
        return 0
    except BaseException:  # noqa: BLE001 — the crash IS the payload
        traceback.print_exc()
        plane.set_state(schema.CSTATE_FAILED)
        return 1
    finally:
        if net is not None:
            net.close()


def _serve(spec: dict, plane: GossipPlane) -> None:
    # jax and the engine import only here, inside the child — timed,
    # because import wall is part of boot-to-serving and the compile
    # cache cannot help with it (EngineReport.boot["import_s"])
    _t_imp = time.perf_counter()
    from flowsentryx_tpu.core.config import FsxConfig
    from flowsentryx_tpu.engine import Engine, NullSink
    from flowsentryx_tpu.ingest import ShardedIngest

    import_s = time.perf_counter() - _t_imp

    rank, n = spec["rank"], spec["n_engines"]
    w = spec["workers"]
    cfg = FsxConfig.from_json(spec["cfg_json"])
    source = ShardedIngest(
        spec["ring_base"], w,
        shard_offset=rank * w,
        total_shards=spec["total_shards"],
        precompact=spec.get("precompact"),
        queue_slots=spec.get("queue_slots", 8),
        quarantine_dir=spec.get("quarantine_dir"),
    )
    if spec.get("verdict_ring"):
        from flowsentryx_tpu.engine.shm import ShmVerdictSink

        sink = ShmVerdictSink(spec["verdict_ring"])
    else:
        sink = NullSink()
    if spec.get("gossip_ring"):
        # multi-host deployments: merged PEER verdicts also reach this
        # host's daemon (single-host clusters leave it unset — the
        # peer's own verdict ring already fed the shared kernel map)
        from flowsentryx_tpu.engine.shm import ShmVerdictSink

        plane.sink = ShmVerdictSink(spec["gossip_ring"])
    params = None
    if spec.get("artifact"):
        from flowsentryx_tpu.models.registry import load_artifact

        params = load_artifact(cfg.model.name, spec["artifact"])
    eng = Engine(
        cfg, source, sink,
        params=params,
        t0_ns=spec["t0_ns"],
        mega_n=spec.get("mega") or 0,
        device_loop=spec.get("device_loop", 0),
        slo_us=spec.get("slo_us") or 0,
        predict=bool(spec.get("predict")),
        watchdog_s=spec.get("watchdog_s"),
        gossip=plane,
        compile_cache=spec.get("compile_cache"),
    )
    eng.boot_import_s = round(import_s, 4)
    restore_info = None
    if spec.get("restore"):
        restore_info = eng.restore(spec["restore"])
    # live-rebalance hooks (cluster/rebalance.py): boot-time reconcile
    # first — adopt a committed-but-uninserted staged spool and drop
    # rows the committed layout says this rank no longer owns (the two
    # post-flip death windows) — then step the handoff state machine
    # between run chunks below, where the engine is quiescent.
    from flowsentryx_tpu.cluster.rebalance import EngineRebalancer

    rebalancer = EngineRebalancer(
        spec["cluster_dir"], rank, plane.status,
        crash_midship=bool(spec.get("handoff_crash_midship")))
    reconciled = rebalancer.reconcile(eng)
    # tiered: SERVING opens on the top-rung tier while a background
    # thread fills the rest of the ladder from the compile cache —
    # the sub-second-boot path for crash-respawns and GROW spares
    eng.warm(tiered=bool(spec.get("tiered_warm")))
    if spec.get("ready_token"):
        Path(spec["ready_token"]).touch()
    if spec.get("start_token"):
        _wait_for_token(spec["start_token"])
    if plane.net is not None:
        # peer discovery with retry/backoff — and FAIL OPEN on
        # timeout: a silent peer host is its supervisor's incident,
        # not a reason to withhold serving this span; when it appears
        # its first HELLO triggers a full-map resync (transport.py)
        from flowsentryx_tpu.cluster.transport import NetHandshakeTimeout

        try:
            plane.net.handshake(
                spec["net"].get("handshake_timeout_s",
                                tuning.NET_HANDSHAKE_TIMEOUT_S))
        except NetHandshakeTimeout as e:
            print(f"fsx cluster rank {rank}: {e} — serving fail-open",
                  file=sys.stderr)
    plane.set_state(schema.CSTATE_SERVING)

    chunk_s = spec.get("chunk_s", 0.5)
    ckpt = spec.get("checkpoint")
    every = spec.get("checkpoint_every") or 0
    max_seconds = spec.get("max_seconds")
    max_batches = spec.get("max_batches")
    t0 = time.perf_counter()
    next_ckpt = time.monotonic() + every if (ckpt and every) else None
    rep = None
    stopped = False
    if spec.get("drain"):
        # drain mode (bench/smoke): the ring shards are prefilled and
        # the fleet runs stop-to-exhaustion in ONE timed run — the
        # sealed-drain trial shape every paced artifact uses, with no
        # chunk-boundary overhead inside the measured wall
        source.request_stop()
        rep = eng.run()
        plane.note_progress(rep.batches, rep.records)
    else:
        while True:
            rep = eng.run(max_seconds=chunk_s)
            plane.note_progress(rep.batches, rep.records)
            rebalancer.step(eng)
            if next_ckpt is not None and time.monotonic() >= next_ckpt:
                eng.checkpoint(ckpt)
                # the checkpoint now covers any adopted rows: release
                # the staged spool (their durable copy until this save)
                rebalancer.note_checkpointed()
                next_ckpt = time.monotonic() + every
            if plane.stop_requested() and not stopped:
                # drain-on-stop: workers empty their ring shards, the
                # engine serves the tail, THEN we exit — the fleet's
                # drain-on-shutdown contract, cluster-wide
                stopped = True
                source.request_stop()
                rep = eng.run()
                plane.note_progress(rep.batches, rep.records)
                break
            if source.exhausted():
                break
            if (max_seconds is not None
                    and time.perf_counter() - t0 >= max_seconds):
                break
            if max_batches is not None and rep.batches >= max_batches:
                break
    wall = time.perf_counter() - t0
    # Converge-on-shutdown: serving is done and the LOCAL wall is
    # closed, but peers draining the same fleet may still be sinking
    # their tails — stamp DRAINING (every publish this engine will
    # ever make happened-before the store) and keep force-merging
    # peers' wires until each peer has ALSO left SERVING and the
    # mailboxes run dry, so co-terminating drains write byte-identical
    # blacklist views into their reports (the smoke's convergence
    # check).
    plane.set_state(schema.CSTATE_DRAINING)
    peers = {p: StatusBlock(status_path(spec["cluster_dir"], p))
             for p in range(n) if p != rank}
    _QUIET = (schema.CSTATE_DRAINING, schema.CSTATE_DONE,
              schema.CSTATE_FAILED)
    plane.quiesce(
        spec.get("gossip_quiesce_s", tuning.GOSSIP_QUIESCE_S),
        peers_quiet=lambda: all(st.ctl_get("c_state") in _QUIET
                                for st in peers.values()))
    # re-snapshot the gossip accounting: the quiesce merges above are
    # exactly what the report's convergence digests exist to show
    rep = rep._replace(cluster=plane.report())
    if ckpt:
        eng.checkpoint(ckpt)
    source.close()
    rep = rep._replace(
        wall_s=round(wall, 4),
        records_per_s=round(rep.records / max(wall, 1e-9), 1),
        ingest=source.ingest_stats(),
    )
    if spec.get("report_path"):
        out = {
            "rank": rank, "n_engines": n, "gen": spec.get("gen", 0),
            "restored": restore_info,
            "reconciled": reconciled,
            "report": rep._asdict(),
        }
        p = Path(spec["report_path"])
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(out, indent=2) + "\n")  # noqa: report file, informational


def prewarm_main(spec: dict) -> int:
    """One-shot fleet pre-warm: compile the fleet's staged geometry
    into the persistent compile cache so a later GROW spare (or a
    crash respawn) warms on pure cache hits — sub-second to SERVING
    while the burst it was spawned for is still landing.

    Spawned by the supervisor at elastic-fleet boot when the engine
    specs carry ``compile_cache``.  Spare ranks are provisioned at max
    with the SAME spec (same cfg/mega/device-loop/params geometry), so
    one child with a null source covers every rank: ``warm()`` the
    FULL ladder — every rung plus the deep-scan ring — storing each
    executable, then exit.  Best-effort and non-blocking: the fleet
    never waits on it, and any failure just means the spare compiles
    (fail-open, like every cache path)."""
    _own_process_group()
    os.environ.setdefault("JAX_PLATFORMS",
                          spec.get("jax_platform", "cpu"))
    try:
        import numpy as np

        from flowsentryx_tpu.core.config import FsxConfig
        from flowsentryx_tpu.core.schema import RECORD_WORDS
        from flowsentryx_tpu.engine import Engine, NullSink
        from flowsentryx_tpu.engine.sources import ArraySource

        cfg = FsxConfig.from_json(spec["cfg_json"])
        params = None
        if spec.get("artifact"):
            from flowsentryx_tpu.models.registry import load_artifact

            params = load_artifact(cfg.model.name, spec["artifact"])
        eng = Engine(
            cfg,
            ArraySource(np.empty((0, RECORD_WORDS), np.uint32)),
            NullSink(),
            params=params,
            mega_n=spec.get("mega") or 0,
            device_loop=spec.get("device_loop", 0),
            slo_us=spec.get("slo_us") or 0,
            sink_thread=False,
            compile_cache=spec["compile_cache"],
        )
        eng.warm()
        rep = eng._cache.report() if eng._cache is not None else {}
        print(f"fsx cluster prewarm: cache ready at {rep.get('dir')} "
              f"(stores {rep.get('stores', 0)}, hits "
              f"{rep.get('hits', 0)}) — GROW spares warm from it",
              file=sys.stderr)
        return 0
    except BaseException:  # noqa: BLE001 — best-effort, announced
        traceback.print_exc()
        return 1


def stub_engine_main(spec: dict) -> int:
    """Lifecycle-protocol stub (module docstring): heartbeats, honors
    stop, optionally crashes on schedule (``stub_crash_after_s``, first
    generation only — the restart must then succeed; with
    ``stub_crash_every_gen`` EVERY generation — the chaos campaign's
    crash-loop fault, which the supervisor must park, not chase), and
    records the restore path the supervisor handed it, so tier-1 can
    prove the supervision protocol in milliseconds."""
    _own_process_group()
    plane = GossipPlane(spec["cluster_dir"], spec["rank"],
                        spec["n_engines"])
    plane.status.ctl_set("c_pid", os.getpid())  # adopt-path liveness
    plane.set_state(schema.CSTATE_SPAWNING)
    gen = spec.get("gen", 0)
    crash_after = spec.get("stub_crash_after_s")
    serve_s = spec.get("stub_serve_s", 0.5)
    plane.set_state(schema.CSTATE_SERVING)
    t0 = time.monotonic()
    while time.monotonic() - t0 < serve_s:
        plane.tick(force=True)  # heartbeat + merge, the engine cadence
        if plane.stop_requested() and not spec.get("stub_ignore_stop"):
            break
        if crash_after is not None \
                and (gen == 0 or spec.get("stub_crash_every_gen")) \
                and time.monotonic() - t0 >= crash_after:
            os._exit(17)  # simulated hard death: no cleanup, no DONE
        time.sleep(0.01)
    if spec.get("report_path"):
        p = Path(spec["report_path"])
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps({  # noqa: report file, informational
            "rank": spec["rank"], "gen": gen, "stub": True,
            "restored": spec.get("restore"),
            "report": {"records": 0, "batches": 0},
        }) + "\n")
    plane.set_state(schema.CSTATE_DONE)
    return 0
