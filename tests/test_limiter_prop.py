"""Randomized C <-> JAX limiter equivalence (VERDICT r2 item 6).

Drives >=1000 randomized aggregated-delta steps per limiter through BOTH
implementations:

* the kernel's integer limiters (``kern/fsx_compute.h``), via the
  ``kern/prop_driver`` harness, which expands each aggregated delta into
  per-packet calls (the kernel plane is per-packet);
* the TPU plane's vectorized float limiters
  (:mod:`flowsentryx_tpu.ops.limiters`), one aggregated transition per
  step.

Comparison is *step-synchronized*: the JAX limiter is re-seeded from the
C trajectory's pre-state at every step (all steps evaluated in one
vectorized call, the steps axis acting as the flow axis).  Divergence
therefore cannot compound, and every step is an independent randomized
test of the transition function.

Exactness discipline: timestamps live on a 1/1024 s grid, which is
dyadic (exact in f32 seconds) and whose ns rounding (+-0.5 ns) provably
cannot flip a window-boundary comparison (boundaries are exact multiples
of 976562.5 ns away).  Counters stay below 2^24 so f32 holds them
exactly; the only permitted divergence is the sliding window's 1/1024
fixed-point estimate and the token bucket's milli-token truncation, and
each disagreement must be adjudicated to sit within that documented
bound of the decision threshold.
"""

from __future__ import annotations

import json
import subprocess
from pathlib import Path

import numpy as np
import pytest

KERN = Path(__file__).resolve().parents[1] / "kern"
TICK_S = 1.0 / 1024.0
WINDOW_NS = 1_000_000_000

PPS_THR = 300
BPS_THR = 200_000
RATE_PPS = 100
BURST = 150
# token-bucket byte dimension (README.md:153-162 bandwidth limit).
# Kept under 2^24 so f32 holds balances exactly.
RATE_BPS = 60_000
BURST_BYTES = 90_000

N_STEPS = 1200


def tick_to_ns(k: np.ndarray) -> np.ndarray:
    """round(k * 976562.5) in exact integer arithmetic."""
    return (k.astype(np.uint64) * 9765625 + 5) // 10


@pytest.fixture(scope="module")
def driver() -> Path:
    r = subprocess.run(["make", "-C", str(KERN), "prop_driver"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    return KERN / "prop_driver"


def make_trace(seed: int) -> dict[str, np.ndarray]:
    """Bursty random trace: mixed in-window, one-roll, and stale gaps.

    IATs are >= 8 ticks so a 1024-tick window holds <= 128 steps; with
    <= 65535 bytes/step the per-window byte sums stay f32-exact."""
    rng = np.random.default_rng(seed)
    kind_p = rng.random(N_STEPS)
    iat_ticks = np.where(
        kind_p < 0.70, rng.integers(8, 300, N_STEPS),        # in-window-ish
        np.where(kind_p < 0.88, rng.integers(1024, 2048, N_STEPS),  # one roll
                 rng.integers(2048, 8192, N_STEPS)))          # stale
    ticks = np.cumsum(iat_ticks).astype(np.uint64)
    n_pkts = rng.integers(1, 200, N_STEPS).astype(np.uint64)
    n_bytes = np.minimum(n_pkts * rng.integers(40, 330, N_STEPS), 65535)
    return {"ticks": ticks, "n_pkts": n_pkts,
            "n_bytes": n_bytes.astype(np.uint64)}


def run_c(driver: Path, kind: int, trace: dict[str, np.ndarray],
          rate_bps: int = 0, burst_bytes: int = 0) -> list[dict]:
    lines = [f"{kind} {PPS_THR} {BPS_THR} {WINDOW_NS} {RATE_PPS} {BURST} "
             f"{rate_bps} {burst_bytes}",
             str(N_STEPS)]
    t_ns = tick_to_ns(trace["ticks"])
    for n, b, t in zip(trace["n_pkts"], trace["n_bytes"], t_ns):
        lines.append(f"{n} {b} {t}")
    r = subprocess.run([str(driver)], input="\n".join(lines) + "\n",
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out = [json.loads(l) for l in r.stdout.splitlines()]
    assert len(out) == N_STEPS
    return out


def pre_states(posts: list[dict]) -> dict[str, np.ndarray]:
    """C trajectory's pre-state per step (zeros, then post[i-1])."""
    cols = {}
    for f in ("win_start_ns", "win_pps", "win_bps", "prev_pps", "prev_bps",
              "tokens_milli", "tok_ts_ns", "tok_bytes"):
        v = np.array([0] + [p[f] for p in posts[:-1]], dtype=np.float64)
        cols[f] = v
    return cols


def jax_window_args(trace, pre):
    import jax.numpy as jnp

    from flowsentryx_tpu.ops import limiters

    st = limiters.WindowState(
        jnp.asarray((pre["win_start_ns"] / 1e9).astype(np.float32)),
        jnp.asarray(pre["win_pps"].astype(np.float32)),
        jnp.asarray(pre["win_bps"].astype(np.float32)),
        jnp.asarray(pre["prev_pps"].astype(np.float32)),
        jnp.asarray(pre["prev_bps"].astype(np.float32)),
    )
    d_pkts = jnp.asarray(trace["n_pkts"].astype(np.float32))
    d_bytes = jnp.asarray(trace["n_bytes"].astype(np.float32))
    now = jnp.asarray((trace["ticks"].astype(np.float64) * TICK_S)
                      .astype(np.float32))
    return st, d_pkts, d_bytes, now


def cfg(rate_bps: float = 0.0, burst_bytes: float = 0.0):
    from flowsentryx_tpu.core.config import LimiterConfig

    return LimiterConfig(pps_threshold=float(PPS_THR),
                         bps_threshold=float(BPS_THR), window_s=1.0,
                         bucket_rate_pps=float(RATE_PPS),
                         bucket_burst=float(BURST),
                         bucket_rate_bps=rate_bps,
                         bucket_burst_bytes=burst_bytes)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fixed_window_trace_equivalence(driver, seed):
    """Fixed window must agree EXACTLY: integer counters, dyadic times,
    no fixed-point anywhere."""
    from flowsentryx_tpu.ops import limiters

    trace = make_trace(seed)
    posts = run_c(driver, 0, trace)
    pre = pre_states(posts)
    st, d_pkts, d_bytes, now = jax_window_args(trace, pre)
    new, over = limiters.fixed_window(cfg(), st, d_pkts, d_bytes, now)

    c_over = np.array([p["over"] for p in posts], bool)
    np.testing.assert_array_equal(np.asarray(over), c_over)
    np.testing.assert_array_equal(
        np.asarray(new.win_pps), np.array([p["win_pps"] for p in posts], np.float32))
    np.testing.assert_array_equal(
        np.asarray(new.win_bps), np.array([p["win_bps"] for p in posts], np.float32))
    np.testing.assert_allclose(
        np.asarray(new.win_start),
        np.array([p["win_start_ns"] / 1e9 for p in posts], np.float32),
        rtol=0, atol=1e-6)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_sliding_window_trace_equivalence(driver, seed):
    """Sliding window: counters/state exact; decisions may diverge only
    within the documented 1/1024 fixed-point bound of the threshold."""
    from flowsentryx_tpu.ops import limiters

    trace = make_trace(seed)
    posts = run_c(driver, 1, trace)
    pre = pre_states(posts)
    st, d_pkts, d_bytes, now = jax_window_args(trace, pre)
    new, over = limiters.sliding_window(cfg(), st, d_pkts, d_bytes, now)

    # post-state counters are pure integer bookkeeping: exact
    for jf, cf in ((new.win_pps, "win_pps"), (new.win_bps, "win_bps"),
                   (new.prev_pps, "prev_pps"), (new.prev_bps, "prev_bps")):
        np.testing.assert_array_equal(
            np.asarray(jf), np.array([p[cf] for p in posts], np.float32), cf)
    np.testing.assert_allclose(
        np.asarray(new.win_start),
        np.array([p["win_start_ns"] / 1e9 for p in posts], np.float32),
        rtol=0, atol=1e-6)

    # decisions: adjudicate each disagreement against the f64 estimate
    c_over = np.array([p["over"] for p in posts], bool)
    j_over = np.asarray(over)
    dis = np.nonzero(c_over != j_over)[0]
    # fixed-point error bound per dimension: prev/1024 (overlap
    # quantization) + 2 (one >>10 truncation each in frac and in the
    # prev*overlap product)
    post_pps = np.array([p["win_pps"] for p in posts], np.float64)
    post_bps = np.array([p["win_bps"] for p in posts], np.float64)
    post_prev_pps = np.array([p["prev_pps"] for p in posts], np.float64)
    post_prev_bps = np.array([p["prev_bps"] for p in posts], np.float64)
    post_start = np.array([p["win_start_ns"] for p in posts], np.float64)
    now_ns = tick_to_ns(trace["ticks"]).astype(np.float64)
    frac = np.clip((now_ns - post_start) / WINDOW_NS, 0.0, 1.0)
    est_pps = post_prev_pps * (1.0 - frac) + post_pps
    est_bps = post_prev_bps * (1.0 - frac) + post_bps
    for i in dis:
        near_pps = abs(est_pps[i] - PPS_THR) <= post_prev_pps[i] / 1024 + 2
        near_bps = abs(est_bps[i] - BPS_THR) <= post_prev_bps[i] / 1024 + 2
        assert near_pps or near_bps, (
            f"step {i}: C={c_over[i]} JAX={j_over[i]} but est "
            f"({est_pps[i]:.1f} pps / {est_bps[i]:.1f} bps) is not within "
            f"the fixed-point bound of either threshold")
    # and they must not diverge often
    assert len(dis) <= N_STEPS * 0.02, f"{len(dis)} disagreements"


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_token_bucket_trace_equivalence(driver, seed):
    """Token bucket: decisions agree except within the milli-token
    truncation bound of the exact balance; post-balance agrees to
    <1 token when over (refused packets do not drain the C bucket),
    tightly otherwise."""
    import jax.numpy as jnp

    from flowsentryx_tpu.ops import limiters

    trace = make_trace(seed)
    posts = run_c(driver, 2, trace)
    pre = pre_states(posts)
    bst = limiters.BucketState(
        jnp.asarray((pre["tokens_milli"] / 1000.0).astype(np.float32)),
        jnp.asarray((pre["tok_ts_ns"] / 1e9).astype(np.float32)),
        jnp.asarray(pre["tok_bytes"].astype(np.float32)),
    )
    d_pkts = jnp.asarray(trace["n_pkts"].astype(np.float32))
    d_bytes = jnp.asarray(trace["n_bytes"].astype(np.float32))
    now = jnp.asarray((trace["ticks"].astype(np.float64) * TICK_S)
                      .astype(np.float32))
    new, over = limiters.token_bucket(cfg(), bst, d_pkts, d_bytes, now)

    c_over = np.array([p["over"] for p in posts], bool)
    j_over = np.asarray(over)

    # f64 reference balance after refill, from the shared pre-state
    now_ns = tick_to_ns(trace["ticks"]).astype(np.float64)
    elapsed = np.minimum(now_ns - pre["tok_ts_ns"], 1e12)
    bal = np.minimum(pre["tokens_milli"] / 1000.0 + elapsed * RATE_PPS / 1e9,
                     BURST)
    d = trace["n_pkts"].astype(np.float64)
    dis = np.nonzero(c_over != j_over)[0]
    for i in dis:
        assert abs(bal[i] - d[i]) <= 0.01, (
            f"step {i}: C={c_over[i]} JAX={j_over[i]} with balance "
            f"{bal[i]:.4f} vs demand {d[i]} — outside truncation bound")
    assert len(dis) <= N_STEPS * 0.02, f"{len(dis)} disagreements"

    j_tokens = np.asarray(new.tokens, np.float64)
    c_tokens = np.array([p["tokens_milli"] for p in posts], np.float64) / 1000.0
    tol = np.where(c_over, 1.0, 0.005)
    assert (np.abs(j_tokens - c_tokens) <= tol).all(), (
        np.abs(j_tokens - c_tokens).max())


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_token_bucket_byte_dimension_equivalence(driver, seed):
    """Dual-dimension bucket over byte-heavy randomized traces
    (VERDICT r4 #8): decisions agree except where either dimension's
    exact balance sits within its documented truncation/split bound of
    the demand; byte post-balances agree tightly when admitted."""
    import jax.numpy as jnp

    from flowsentryx_tpu.ops import limiters

    trace = make_trace(seed)
    posts = run_c(driver, 2, trace, rate_bps=RATE_BPS,
                  burst_bytes=BURST_BYTES)
    pre = pre_states(posts)
    bst = limiters.BucketState(
        jnp.asarray((pre["tokens_milli"] / 1000.0).astype(np.float32)),
        jnp.asarray((pre["tok_ts_ns"] / 1e9).astype(np.float32)),
        jnp.asarray(pre["tok_bytes"].astype(np.float32)),
    )
    d_pkts = jnp.asarray(trace["n_pkts"].astype(np.float32))
    d_bytes = jnp.asarray(trace["n_bytes"].astype(np.float32))
    now = jnp.asarray((trace["ticks"].astype(np.float64) * TICK_S)
                      .astype(np.float32))
    new, over = limiters.token_bucket(
        cfg(float(RATE_BPS), float(BURST_BYTES)), bst, d_pkts, d_bytes, now)

    c_over = np.array([p["over"] for p in posts], bool)
    j_over = np.asarray(over)

    # f64 reference balances after refill, from the shared pre-state
    now_ns = tick_to_ns(trace["ticks"]).astype(np.float64)
    elapsed = np.minimum(now_ns - pre["tok_ts_ns"], 1e12)
    bal_pkt = np.minimum(
        pre["tokens_milli"] / 1000.0 + elapsed * RATE_PPS / 1e9, BURST)
    bal_byte = np.minimum(
        pre["tok_bytes"] + elapsed * RATE_BPS / 1e9, BURST_BYTES)
    dp = trace["n_pkts"].astype(np.float64)
    db = trace["n_bytes"].astype(np.float64)
    # C splits a step's bytes into per-packet spends (remainder on the
    # first), so step-level decisions may differ from the aggregate
    # wherever the balance is within one per-packet slice of the
    # demand; plus <= 2 bytes of elapsed_us/1e6 refill truncation.
    b_slice = np.ceil(db / np.maximum(dp, 1)) + 1
    dis = np.nonzero(c_over != j_over)[0]
    for i in dis:
        near_pkt = abs(bal_pkt[i] - dp[i]) <= 0.01
        near_byte = abs(bal_byte[i] - db[i]) <= b_slice[i] + 2
        assert near_pkt or near_byte, (
            f"step {i}: C={c_over[i]} JAX={j_over[i]} with balances "
            f"pkt {bal_pkt[i]:.3f}/{dp[i]} byte {bal_byte[i]:.1f}/{db[i]}"
            " — outside every truncation bound")
    assert len(dis) <= N_STEPS * 0.02, f"{len(dis)} disagreements"

    # byte post-balance: exact-ish when admitted; when refused the C
    # twin keeps every refused packet's bytes while the JAX aggregate
    # drains (clamped at 0), so only the ordering is guaranteed there
    j_bytes = np.asarray(new.tok_bytes, np.float64)
    c_bytes = np.array([p["tok_bytes"] for p in posts], np.float64)
    admitted = ~c_over & ~j_over
    assert (np.abs(j_bytes - c_bytes)[admitted] <= 3.0).all(), (
        np.abs(j_bytes - c_bytes)[admitted].max())
    assert (j_bytes <= c_bytes + 3.0).all()
    assert (j_bytes >= -1e-6).all() and (c_bytes <= BURST_BYTES).all()
