"""One-shot axon-tunnel health probe: prints ONE JSON line.

Measures the two transport axes that gate the e2e benchmark
(BENCH_EVIDENCE_r03.json showed them degrading independently):

* ``h2d_mbps``   — host->device bandwidth on a 24 MB transfer (small
  enough not to drain the tunnel's metered burst budget, large enough
  to amortize the per-transfer RPC cost);
* ``dispatch_ms`` — per-iteration cost of a 100-deep async dispatch
  chain (the RPC path that collapsed ~100x in the degraded r03 window).

Used by bench.py's probe phase and by the round's link monitor
(artifacts/link_monitor_*.jsonl).  Runs in its own process because the
first D2H readback permanently degrades a process's dispatch rate on
the tunnel (bench.py module docstring).
"""
import json
import sys
import time

out = {"ts": time.time()}
try:
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    out["init_s"] = round(time.perf_counter() - t0, 1)
    out["backend"] = dev.platform
    out["device_kind"] = dev.device_kind

    big = np.zeros(24 * 1024 * 1024, np.uint8)
    jax.block_until_ready(jax.device_put(big[:1024]))  # warm the path
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(big))
    out["h2d_mbps"] = round(big.nbytes / (time.perf_counter() - t0) / 1e6, 1)

    f = jax.jit(lambda x: jnp.tanh(x @ x))
    x = jax.device_put(jnp.ones((1024, 1024), jnp.bfloat16))
    jax.block_until_ready(f(x))
    t0 = time.perf_counter()
    for _ in range(100):
        y = f(x)
    jax.block_until_ready(y)
    out["dispatch_ms"] = round((time.perf_counter() - t0) / 100 * 1e3, 3)
except Exception as e:  # noqa: BLE001 — a probe must never crash the caller
    out["error"] = f"{type(e).__name__}: {e}"
print(json.dumps(out), flush=True)
