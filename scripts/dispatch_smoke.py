"""Bounded CPU dispatch-pipeline smoke — the single-copy CI gate.

Serves a prefilled shm ring shard through a REAL one-worker
``ShardedIngest`` fleet into an adaptive-coalescing engine
(``mega_n="auto"``) and checks the zero-copy pipeline invariants on
the report's ``dispatch`` block:

* ``host_copies_per_batch == 1.0`` — every dispatched batch crossed
  the host exactly once (shm slot view → page-aligned dispatch arena;
  ``device_put`` of the arena slice is the host↔device boundary, not a
  host copy), bytes-staged accounting;
* every batch went through the arena (``staged_batches == batches``)
  and the group histogram accounts for every one of them;
* coalescing actually engaged (some rung > 1 fired under the deep
  prefilled backlog);
* verdict parity: the sealed adaptive run blocks the same sources with
  the same stats as the inline singles run on the same records.

Results merge into ``artifacts/DISPATCH_r09.json`` under ``"smoke"``
(the ``"paced"`` PR-4-comparison evidence in the same artifact is
preserved), so the invariant is re-proved by every
``scripts/verify_tier1.sh`` run, not benched once and trusted forever.

Usage: JAX_PLATFORMS=cpu python scripts/dispatch_smoke.py [out.json]
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_BATCHES = 24
BATCH = 256


def _records(n: int):
    from flowsentryx_tpu.engine.traffic import Scenario, TrafficGen, TrafficSpec

    return TrafficGen(TrafficSpec(
        scenario=Scenario.UDP_FLOOD_MULTI, rate_pps=1e7,
        n_attack_ips=8, n_benign_ips=24, attack_fraction=0.8, seed=29,
    )).next_records(n)


def _cfg():
    import dataclasses

    from flowsentryx_tpu.core.config import FsxConfig

    cfg = FsxConfig()
    return dataclasses.replace(
        cfg,
        batch=dataclasses.replace(cfg.batch, max_batch=BATCH),
        table=dataclasses.replace(cfg.table, capacity=1 << 14),
        limiter=dataclasses.replace(
            cfg.limiter, pps_threshold=200.0, bps_threshold=1e9),
    )


def main() -> int:
    from flowsentryx_tpu.core import schema
    from flowsentryx_tpu.engine import ArraySource, CollectSink, Engine
    from flowsentryx_tpu.engine.shm import ShmRing
    from flowsentryx_tpu.ingest import ShardedIngest

    t_start = time.perf_counter()
    recs = _records(BATCH * N_BATCHES)

    # inline singles reference (same records, same config)
    sink0 = CollectSink()
    rep0 = Engine(_cfg(), ArraySource(recs.copy()), sink0,
                  readback_depth=4, sink_thread=False).run()

    # sealed adaptive run over a real worker fleet
    tmpdir = tempfile.mkdtemp(prefix="fsx_dsmoke_")
    base = os.path.join(tmpdir, "fring")
    ring = ShmRing.create(schema.shard_ring_path(base, 0, 1), 1 << 13,
                          schema.FLOW_RECORD_DTYPE)
    assert ring.produce(recs) == len(recs)
    src = ShardedIngest(base, 1, queue_slots=16, precompact=False,
                        t0_grace_s=0.2)
    sink1 = CollectSink()
    eng = Engine(_cfg(), src, sink1, readback_depth=4, sink_thread=False,
                 mega_n="auto")
    try:
        deadline = time.monotonic() + 60
        while src.t0_ns is None:
            src.poll_batches(0)
            if time.monotonic() > deadline:
                raise TimeoutError("ingest t0 handshake did not resolve")
            time.sleep(0.01)
        src.request_stop()
        rep1 = eng.run()
    finally:
        src.close()
        # verify_tier1.sh runs this every time: don't leak the ~0.6 MB
        # of ring + batch-queue files per run
        shutil.rmtree(tmpdir, ignore_errors=True)

    d = rep1.dispatch
    failures: list[str] = []
    if d["host_copies_per_batch"] != 1.0:
        failures.append(
            f"host_copies_per_batch {d['host_copies_per_batch']} != 1.0 "
            "(the single-copy invariant)")
    if d["staged_batches"] != rep1.batches:
        failures.append(
            f"staged {d['staged_batches']} != served {rep1.batches} "
            "batches (a batch bypassed the arena)")
    hist_chunks = sum(int(g) * n for g, n in d["group_hist"].items())
    if hist_chunks != rep1.batches:
        failures.append(
            f"group histogram covers {hist_chunks} != {rep1.batches}")
    if not any(int(g) > 1 for g in d["group_hist"]):
        failures.append("no coalesced group fired under a deep backlog")
    if rep1.records != rep0.records or rep1.stats != rep0.stats:
        failures.append("sealed adaptive stats != inline singles stats")
    if sink1.blocked != sink0.blocked:
        failures.append("sealed adaptive blacklist != inline singles")

    smoke = {
        "ts": time.time(),
        "wall_s": round(time.perf_counter() - t_start, 2),
        "records": rep1.records,
        "batches": rep1.batches,
        "dispatch": d,
        "stages_ms": {k: rep1.stages_ms[k]
                      for k in ("pop", "stage", "dispatch")},
        "copy_inventory": {
            "before": [
                "SealedBatchQueue.consume_batch payload copy-out",
                "np.stack mega-group assembly",
                "device_put staging copy from the unaligned stack",
            ],
            "before_copies_per_batch": 3,
            "after": [
                "shm slot view -> page-aligned dispatch arena "
                "(ShardedIngest.poll_batches_into); device_put of the "
                "arena slice is the H2D boundary itself",
            ],
            "after_copies_per_batch": d["host_copies_per_batch"],
        },
        "ok": not failures,
        "failures": failures,
    }

    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "DISPATCH_r09.json")
    try:
        artifact = json.loads(open(out_path).read())
    except (OSError, ValueError):
        artifact = {}
    artifact["smoke"] = smoke
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"dispatch smoke: wrote {out_path}")
    print(f"dispatch smoke: copies/batch={d['host_copies_per_batch']} "
          f"groups={d['group_hist']} dispatches={d['dispatches']}")
    for msg in failures:
        print(f"dispatch smoke: FAIL {msg}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
