"""BPF instruction-set encodings (kernel uapi, linux/bpf.h).

Every eBPF instruction is 8 bytes: ``op:8 dst_reg:4 src_reg:4 off:16
imm:32`` (little-endian), except ``BPF_LD|BPF_DW|BPF_IMM`` which takes a
second 8-byte slot carrying the upper 32 bits of a 64-bit immediate.
These encodings are a stable kernel ABI; the values below are the uapi
constants, re-derived from the instruction-class layout (3 low bits =
class, etc.), not copied from any header.

The reference compiles its programs with clang -target bpf
(/root/reference/src/Makefile:12-18); this module is the bottom of the
in-repo replacement toolchain (see package docstring).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

# ---- instruction classes (low 3 bits of op) ----
BPF_LD = 0x00
BPF_LDX = 0x01
BPF_ST = 0x02
BPF_STX = 0x03
BPF_ALU = 0x04
BPF_JMP = 0x05
BPF_JMP32 = 0x06
BPF_ALU64 = 0x07

# ---- size modifiers (bits 3-4) for load/store ----
BPF_W = 0x00  # 4 bytes
BPF_H = 0x08  # 2 bytes
BPF_B = 0x10  # 1 byte
BPF_DW = 0x18  # 8 bytes

# ---- mode modifiers (bits 5-7) for load/store ----
BPF_IMM = 0x00
BPF_MEM = 0x60
BPF_ATOMIC = 0xC0

# ---- ALU/JMP source (bit 3) ----
BPF_K = 0x00  # immediate
BPF_X = 0x08  # register

# ---- ALU ops (high 4 bits) ----
BPF_ADD = 0x00
BPF_SUB = 0x10
BPF_MUL = 0x20
BPF_DIV = 0x30
BPF_OR = 0x40
BPF_AND = 0x50
BPF_LSH = 0x60
BPF_RSH = 0x70
BPF_NEG = 0x80
BPF_MOD = 0x90
BPF_XOR = 0xA0
BPF_MOV = 0xB0
BPF_ARSH = 0xC0
BPF_END = 0xD0

# ---- JMP ops (high 4 bits) ----
BPF_JA = 0x00
BPF_JEQ = 0x10
BPF_JGT = 0x20
BPF_JGE = 0x30
BPF_JSET = 0x40
BPF_JNE = 0x50
BPF_JSGT = 0x60
BPF_JSGE = 0x70
BPF_CALL = 0x80
BPF_EXIT = 0x90
BPF_JLT = 0xA0
BPF_JLE = 0xB0
BPF_JSLT = 0xC0
BPF_JSLE = 0xD0

# ---- atomic op immediates (stored in imm field of BPF_ATOMIC) ----
BPF_FETCH = 0x01
ATOMIC_ADD = BPF_ADD  # imm=0x00: atomic add; |BPF_FETCH for fetch-add

# ---- registers ----
R0, R1, R2, R3, R4, R5, R6, R7, R8, R9, R10 = range(11)

# ---- pseudo src_reg values for BPF_LD|BPF_DW|BPF_IMM ----
PSEUDO_MAP_FD = 1  # imm = map fd; verifier rewrites to map pointer
PSEUDO_MAP_VALUE = 2  # imm = map fd, next_imm = offset into value

# ---- helper function ids (kernel uapi enum bpf_func_id; stable ABI) ----
FN_map_lookup_elem = 1
FN_map_update_elem = 2
FN_map_delete_elem = 3
FN_ktime_get_ns = 5
FN_trace_printk = 6
FN_get_smp_processor_id = 8
FN_xdp_adjust_head = 44
FN_ringbuf_output = 130
FN_ringbuf_reserve = 131
FN_ringbuf_submit = 132
FN_ringbuf_discard = 133

# ---- XDP return codes ----
XDP_ABORTED = 0
XDP_DROP = 1
XDP_PASS = 2
XDP_TX = 3
XDP_REDIRECT = 4

# ---- struct xdp_md field offsets (uapi, 6 x u32) ----
XDP_MD_DATA = 0
XDP_MD_DATA_END = 4
XDP_MD_DATA_META = 8


@dataclass(frozen=True)
class Insn:
    """One 8-byte BPF instruction slot."""

    op: int
    dst: int = 0
    src: int = 0
    off: int = 0
    imm: int = 0

    def pack(self) -> bytes:
        imm = self.imm & 0xFFFFFFFF
        off = self.off & 0xFFFF
        return struct.pack(
            "<BBHI", self.op & 0xFF, (self.src << 4 | self.dst) & 0xFF, off, imm
        )


def _s32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


# ---- encoders: each returns a list[Insn] so ld_imm64 composes ----

def mov64(dst: int, src: int) -> list[Insn]:
    return [Insn(BPF_ALU64 | BPF_MOV | BPF_X, dst, src)]


def mov64_imm(dst: int, imm: int) -> list[Insn]:
    return [Insn(BPF_ALU64 | BPF_MOV | BPF_K, dst, imm=_s32(imm))]


def mov32(dst: int, src: int) -> list[Insn]:
    """32-bit move: zero-extends dst's upper half (ALU class)."""
    return [Insn(BPF_ALU | BPF_MOV | BPF_X, dst, src)]


def mov32_imm(dst: int, imm: int) -> list[Insn]:
    return [Insn(BPF_ALU | BPF_MOV | BPF_K, dst, imm=_s32(imm))]


def alu64(op: int, dst: int, src: int) -> list[Insn]:
    return [Insn(BPF_ALU64 | op | BPF_X, dst, src)]


def alu64_imm(op: int, dst: int, imm: int) -> list[Insn]:
    return [Insn(BPF_ALU64 | op | BPF_K, dst, imm=_s32(imm))]


def alu32(op: int, dst: int, src: int) -> list[Insn]:
    return [Insn(BPF_ALU | op | BPF_X, dst, src)]


def alu32_imm(op: int, dst: int, imm: int) -> list[Insn]:
    return [Insn(BPF_ALU | op | BPF_K, dst, imm=_s32(imm))]


def neg64(dst: int) -> list[Insn]:
    return [Insn(BPF_ALU64 | BPF_NEG, dst)]


def endian_be(dst: int, bits: int) -> list[Insn]:
    """bpf_htobe / to-big-endian byte swap (imm = 16/32/64)."""
    return [Insn(BPF_ALU | BPF_END | BPF_X, dst, imm=bits)]


def ld_imm64(dst: int, imm: int) -> list[Insn]:
    lo = imm & 0xFFFFFFFF
    hi = (imm >> 32) & 0xFFFFFFFF
    return [
        Insn(BPF_LD | BPF_DW | BPF_IMM, dst, 0, 0, _s32(lo)),
        Insn(0, 0, 0, 0, _s32(hi)),
    ]


def ld_map_fd(dst: int, map_fd: int) -> list[Insn]:
    """Load a map pointer (verifier rewrites PSEUDO_MAP_FD)."""
    return [
        Insn(BPF_LD | BPF_DW | BPF_IMM, dst, PSEUDO_MAP_FD, 0, map_fd),
        Insn(0, 0, 0, 0, 0),
    ]


def ldx(size: int, dst: int, src: int, off: int) -> list[Insn]:
    return [Insn(BPF_LDX | size | BPF_MEM, dst, src, off)]


def stx(size: int, dst: int, off: int, src: int) -> list[Insn]:
    return [Insn(BPF_STX | size | BPF_MEM, dst, src, off)]


def st_imm(size: int, dst: int, off: int, imm: int) -> list[Insn]:
    return [Insn(BPF_ST | size | BPF_MEM, dst, 0, off, _s32(imm))]


def atomic_add64(dst: int, off: int, src: int, fetch: bool = False) -> list[Insn]:
    """*(u64 *)(dst + off) += src; with fetch, src = old value.

    Plain atomic add is supported by every eBPF kernel; the FETCH form
    needs kernel >= 5.12 (this image runs 6.18).
    """
    imm = ATOMIC_ADD | (BPF_FETCH if fetch else 0)
    return [Insn(BPF_STX | BPF_DW | BPF_ATOMIC, dst, src, off, imm)]


def jmp(op: int, dst: int, src: int, off: int) -> list[Insn]:
    return [Insn(BPF_JMP | op | BPF_X, dst, src, off)]


def jmp_imm(op: int, dst: int, imm: int, off: int) -> list[Insn]:
    return [Insn(BPF_JMP | op | BPF_K, dst, 0, off, _s32(imm))]


def ja(off: int) -> list[Insn]:
    return [Insn(BPF_JMP | BPF_JA, 0, 0, off)]


def call(fn: int) -> list[Insn]:
    return [Insn(BPF_JMP | BPF_CALL, 0, 0, 0, fn)]


def exit_() -> list[Insn]:
    return [Insn(BPF_JMP | BPF_EXIT)]
