"""The robustness plane PR 13 added: checkpoint integrity + ``.prev``
retention, the dispatch watchdog, the health ladder, the supervisor's
crash-loop discipline, and the chaos campaign's own plumbing.

The full seeded campaign (every fault class + every planted
regression over the real stack) is re-proved per verify run by
``scripts/chaos_smoke.py``; what lives here is the unit layer — each
hardening mechanism pinned in isolation, fast."""

import io
import time

import numpy as np
import pytest

from flowsentryx_tpu.core import schema
from flowsentryx_tpu.engine import checkpoint as ckpt
from flowsentryx_tpu.engine import health
from flowsentryx_tpu.engine.watchdog import DispatchWatchdog, WatchdogStall


def _snapshot(tmp_path, name="snap", t0_ns=777, salt=0):
    table = schema.IpTableState(
        key=np.arange(1, 257, dtype=np.uint32),
        state=np.ones((256, schema.NUM_TABLE_COLS), np.float32))
    stats = schema.GlobalStats(
        *(np.full((2,), i, np.uint32)
          for i in range(len(schema.GlobalStats._fields))))
    return ckpt.save_state(tmp_path / name, table, stats, t0_ns,
                           hash_salt=salt)


class TestCheckpointIntegrity:
    def test_crc_roundtrip(self, tmp_path):
        p = _snapshot(tmp_path)
        ck = ckpt.load_checkpoint(p)
        assert ck.crc_checked is True
        assert ckpt.peek_header(p)["has_crc"] is True
        np.testing.assert_array_equal(
            ck.table.key, np.arange(1, 257, dtype=np.uint32))

    def test_peek_header_empty_file_named_error(self, tmp_path):
        """Satellite: a zero-length (torn-at-create) file must raise
        the NAMED ValueError through pre-boot validation — not a raw
        struct/IndexError."""
        p = tmp_path / "empty.npz"
        p.write_bytes(b"")
        with pytest.raises(ValueError, match="empty"):
            ckpt.peek_header(p)
        with pytest.raises(ckpt.CheckpointCorrupt):
            ckpt.peek_header(p)

    def test_peek_header_short_file_named_error(self, tmp_path):
        p = _snapshot(tmp_path)
        data = p.read_bytes()
        p.write_bytes(data[: max(8, len(data) // 3)])
        with pytest.raises(ckpt.CheckpointCorrupt,
                           match="corrupt or truncated"):
            ckpt.peek_header(p)
        missing = tmp_path / "never_written.npz"
        with pytest.raises(ckpt.CheckpointCorrupt, match="unreadable"):
            ckpt.peek_header(missing)

    def test_clean_splice_caught_by_crc(self, tmp_path):
        """Corruption that decompresses CLEANLY — valid zip, wrong
        bytes — is exactly what only the folded CRC32 can catch."""
        p = _snapshot(tmp_path)
        with np.load(p) as z:
            data = {k: np.array(z[k]) for k in z.files}
        data["table_key"] = data["table_key"].copy()
        data["table_key"][7] ^= 1
        np.savez_compressed(p, **data)
        with pytest.raises(ckpt.CheckpointCorrupt, match="CRC32"):
            ckpt.load_checkpoint(p)

    def test_pre_crc_snapshot_grandfathered(self, tmp_path):
        """Legacy snapshots (no integrity member) still load, flagged
        unverified — refusing the whole pre-PR-13 era would be a
        self-inflicted outage."""
        p = _snapshot(tmp_path)
        with np.load(p) as z:
            data = {k: np.array(z[k]) for k in z.files
                    if k != "integrity_crc32"}
        np.savez_compressed(p, **data)
        assert ckpt.load_checkpoint(p).crc_checked is False
        assert ckpt.peek_header(p)["has_crc"] is False

    def test_prev_generation_retained_on_rotation(self, tmp_path):
        p1 = _snapshot(tmp_path, t0_ns=111)
        assert not ckpt.prev_path(p1).exists()
        _snapshot(tmp_path, t0_ns=222)
        prev = ckpt.prev_path(p1)
        assert prev.exists()
        assert ckpt.load_checkpoint(prev).t0_ns == 111
        assert ckpt.load_checkpoint(p1).t0_ns == 222


class TestDispatchWatchdog:
    def test_disabled_and_idle_never_trip(self):
        wd = DispatchWatchdog(0.0)
        wd.check(busy=5)  # disabled: no-op regardless of stall
        wd = DispatchWatchdog(10.0)
        wd._last_progress -= 100.0
        wd.check(busy=0)  # idle pipe re-arms, never trips
        assert wd.trips == 0 and not wd.tripped

    def test_soft_then_hard_trip(self, capsys):
        wd = DispatchWatchdog(0.05)
        wd._last_progress -= 1.0
        wd.check(busy=3)  # soft: stacks dumped, counted, no raise
        assert wd.trips == 1 and not wd.tripped
        assert "per-thread stacks" in capsys.readouterr().err
        wd._soft_at -= 1.0
        with pytest.raises(WatchdogStall, match="refusing to hang"):
            wd.check(busy=3)
        assert wd.tripped
        assert wd.to_dict()["hard_tripped"] is True

    def test_progress_rearms(self):
        wd = DispatchWatchdog(0.05)
        wd._last_progress -= 1.0
        wd.check(busy=1)
        assert wd.trips == 1
        wd.note_progress()  # the pipe recovered
        wd.check(busy=1)    # fresh stall clock: no further trip
        assert wd.trips == 1 and not wd.tripped


class TestHealthLadder:
    def test_healthy_when_quiet(self):
        h = health.engine_health(
            ingest={"n_workers": 2, "dead_workers": [], "workers": {}},
            gossip={"tx_dropped": 0, "rx_seq_gaps": 0},
            watchdog={"soft_trips": 0, "hard_tripped": False})
        assert h == {"state": "healthy", "reasons": []}

    def test_degraded_reasons_are_enumerable(self):
        h = health.engine_health(
            ingest={
                "n_workers": 2, "dead_workers": [1],
                "dropped_emit_batches": 3, "quarantined_batches": 2,
                "bad_wire_slots": 1,
                "workers": {"0": {"seq_gaps": 4, "stalled": True}},
            },
            gossip={"tx_dropped": 7, "rx_seq_gaps": 1},
            restore_fallbacks=1)
        assert h["state"] == "degraded"
        assert set(h["reasons"]) == {
            "ingest_shards_dead:1", "ingest_shards_stalled:1",
            "ingest_seq_gaps:4", "ingest_emit_drops:3",
            "quarantined_batches:2", "bad_wire_slots:1",
            "gossip_tx_dropped:7", "gossip_rx_seq_gaps:1",
            "restore_fallbacks:1"}

    def test_failed_rungs(self):
        all_dead = health.engine_health(
            ingest={"n_workers": 2, "dead_workers": [0, 1],
                    "workers": {}})
        assert all_dead["state"] == "failed"
        wd = health.engine_health(
            watchdog={"soft_trips": 2, "hard_tripped": True})
        assert wd["state"] == "failed"

    def test_cluster_worst_of_and_supervisor_overlay(self):
        agg = health.cluster_health(
            {0: {"state": "healthy", "reasons": []},
             1: {"state": "degraded", "reasons": ["bad_wire_slots:1"]}},
            failed_ranks=[], stalled_ranks=[])
        assert agg["state"] == "degraded"
        assert agg["reasons"] == ["r1:bad_wire_slots:1"]
        # a parked rank is FAILED even if its last report said healthy
        agg = health.cluster_health(
            {0: {"state": "healthy", "reasons": []}},
            failed_ranks=[1], stalled_ranks=[])
        assert agg["state"] == "failed"
        assert "ranks_failed:1" in agg["reasons"]


class TestSupervisorCrashLoop:
    def test_instant_crasher_backs_off_then_parks(self, tmp_path):
        """The crash-loop discipline end-to-end against real child
        processes: exponential spacing between deaths, then the park
        with its span announced (the campaign re-proves this plus the
        backoff-removed plant every verify run)."""
        import contextlib

        from flowsentryx_tpu.cluster.runner import stub_engine_main
        from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor

        sup = ClusterSupervisor(
            tmp_path / "cl",
            [{"stub_serve_s": 3.0},
             {"stub_serve_s": 30.0, "stub_crash_after_s": 0.0,
              "stub_crash_every_gen": True, "workers": 2}],
            entry=stub_engine_main,
            max_restarts=2, restart_backoff_s=0.05,
            restart_window_s=60.0)
        sup.boot()
        stderr = io.StringIO()
        deadline = time.monotonic() + 20.0
        try:
            with contextlib.redirect_stderr(stderr):
                while 1 not in sup._failed \
                        and time.monotonic() < deadline:
                    sup.poll()
                    time.sleep(0.01)
        finally:
            sup.close()
        assert 1 in sup._failed
        assert sup.restarts[1] == 2  # the budget, not budget+ spins
        deaths = sup._death_times[1]
        gaps = [b - a for a, b in zip(deaths, deaths[1:])]
        # deaths spaced by at least the (slack-adjusted) backoff ladder
        assert len(gaps) == 2
        assert gaps[0] >= 0.7 * 0.05 and gaps[1] >= 0.7 * 0.10
        msg = stderr.getvalue()
        assert "PARKED as failed" in msg
        assert "ring shards [2, 4)" in msg  # the span, announced
        assert 0 not in sup._failed  # fail-open: the survivor serves


class TestNullPathParity:
    def test_watchdog_health_defaults_byte_identical(self):
        """Acceptance pin: with no faults injected, the watchdog +
        health plane AT DEFAULTS changes nothing — stats, blocked
        set, and table bytes identical to a watchdog-disabled run,
        under ``transfer_guard("disallow")``."""
        import jax

        from flowsentryx_tpu.core.config import (
            BatchConfig, FsxConfig, LimiterConfig, TableConfig,
        )
        from flowsentryx_tpu.engine import ArraySource, CollectSink, Engine
        from flowsentryx_tpu.engine.traffic import (
            Scenario, TrafficGen, TrafficSpec,
        )

        recs = TrafficGen(TrafficSpec(
            scenario=Scenario.UDP_FLOOD_MULTI, rate_pps=1e7,
            n_attack_ips=32, attack_fraction=0.8, seed=13,
        )).next_records(256 * 6)
        cfg = FsxConfig(
            table=TableConfig(capacity=1 << 12),
            batch=BatchConfig(max_batch=256),
            limiter=LimiterConfig(pps_threshold=200.0,
                                  bps_threshold=1e9))

        def run(watchdog_s):
            sink = CollectSink()
            eng = Engine(cfg, ArraySource(recs.copy()), sink,
                         readback_depth=4, sink_thread=False,
                         watchdog_s=watchdog_s)
            with jax.transfer_guard("disallow"):
                rep = eng.run()
            return rep, sink, eng

        rep_def, sink_def, eng_def = run(None)   # PR-13 defaults
        rep_off, sink_off, eng_off = run(0.0)    # plane disabled
        assert rep_def.stats == rep_off.stats
        assert rep_def.records == rep_off.records
        assert sink_def.blocked == sink_off.blocked
        for a, b in zip(jax.tree_util.tree_leaves(eng_def.table),
                        jax.tree_util.tree_leaves(eng_off.table)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the plane observed a fault-free run as exactly that
        assert rep_def.health["state"] == "healthy"
        assert rep_def.health["reasons"] == []
