"""Jaxpr/HLO-level contract checks — the auditor's instruction layer.

Every check here takes a staged artifact (a ``ClosedJaxpr`` from
``jitted.trace(...)`` or the compiled module's HLO text) and returns a
list of :class:`Finding`\\s, each naming the offending equation (by its
path through the nested jaxpr) or executable parameter — the same
diagnostic shape :mod:`flowsentryx_tpu.bpf.verifier` gives for rejected
BPF instructions.  Nothing in this module executes device code: the
point is that the contracts are *properties of the compiled graph*,
provable before the first batch is dispatched.

Contract catalog (docs/AUDIT.md has the operator view):

* :func:`check_dtypes` — no f64/complex anywhere in the graph (the
  all-quantized-lanes claim; one stray ``float(...)`` promotion doubles
  every buffer it touches).
* :func:`check_quantized_lane` — the int8 classifier matmul really is
  integer-domain ``dot_general`` (a silent dequantize-then-float-dot
  keeps the numbers and loses the MXU int path).
* :func:`check_callbacks` — no ``pure_callback``/``io_callback``/
  ``debug_callback``/infeed/outfeed host round-trips hiding in the hot
  step.
* :func:`check_collectives` — the sharded step's cross-device traffic
  is exactly the designed set: two routing ``all_to_all``\\s, the
  O(verdict_k) ``all_gather`` on the compact wire, scalar reductions.
* :func:`check_donation` — ``donate_argnums`` buffers actually appear
  in the executable's ``input_output_alias`` map (a dropped donation is
  a silent HBM copy of the 1M-row table per batch).
* :func:`check_inplace` — the in-place/copy census: the donated table
  incurs zero ``copy``/``convert`` HLO ops and never rides a
  ``lax.cond`` or dynamic-offset ``dynamic_update_slice`` — the two
  measured XLA:CPU cliffs (PR 8) pinned as graph facts instead of
  bench-only findings.
* :func:`staging_cache_check` — staging twice under identical
  host-side construction hits the jit tracing cache (weak_type /
  dtype / static-arg drift means the serving loop recompiles forever).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Iterator

#: Primitives that round-trip through the host mid-graph.  Any of these
#: in a serving step turns the "one D2H wire per batch" budget into an
#: unbounded sync point (and wedges donation on tunneled runtimes).
CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "infeed", "outfeed",
})

#: Cross-device primitives the sharded step is *designed* to contain.
#: Anything else crossing devices is accidental traffic.
EXPECTED_COLLECTIVES = frozenset({
    "all_to_all",   # flow partials out + verdicts back (2 per step)
    "all_gather",   # the compact verdict wire only (K-sized operands)
    "psum", "pmax", "pmin",  # scalar stat/clock reductions
    "axis_index",   # device id, no traffic at all
})

#: All primitives we classify as collectives (superset of the expected
#: set — an unexpected member is a finding, not a crash).
COLLECTIVE_PRIMITIVES = EXPECTED_COLLECTIVES | frozenset({
    "ppermute", "pbroadcast", "all_gather_invariant", "reduce_scatter",
    "psum_scatter", "pgather", "pdot", "collective_permute",
})

#: Scalar-reduction operand ceiling (elements): psum/pmax carry the
#: [4+1] stat-count vector and the batch clock, never per-record data.
REDUCTION_MAX_ELEMS = 8

#: all_to_all count per staged step graph: partials out, verdicts back.
MAX_ALL_TO_ALL = 2


@dataclasses.dataclass
class Finding:
    """One violated contract, pinned to an equation or parameter."""

    contract: str   # dtype | quantized | transfer | donation | ...
    reason: str     # human-actionable sentence
    where: str = ""  # eqn path ("eqns[3]:convert_element_type/...") or
    #                  output/param name ("table.key", "out.wire")
    eqn: str = ""   # the offending equation's text (trimmed)

    def to_json(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v}

    def __str__(self) -> str:
        loc = f" at {self.where}" if self.where else ""
        eqn = f"\n    {self.eqn}" if self.eqn else ""
        return f"[{self.contract}]{loc}: {self.reason}{eqn}"


class AuditError(RuntimeError):
    """Raised when an audited variant violates a contract (engine boot
    refuses to serve on it; ``fsx audit`` exits 1)."""

    def __init__(self, variant: str, findings: list[Finding]):
        self.variant = variant
        self.findings = findings
        lines = "\n  ".join(str(f) for f in findings)
        super().__init__(
            f"fsx audit: step variant {variant!r} violates "
            f"{len(findings)} contract(s):\n  {lines}")


# -- jaxpr traversal --------------------------------------------------------

def _sub_jaxprs(value: Any) -> Iterator[Any]:
    """Yield nested Jaxprs hiding inside one eqn param value (pjit /
    scan carry ClosedJaxpr, shard_map carries a bare Jaxpr, cond
    carries lists of branches)."""
    items = value if isinstance(value, (list, tuple)) else (value,)
    for v in items:
        if hasattr(v, "eqns"):           # bare Jaxpr
            yield v
        elif hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
            yield v.jaxpr                # ClosedJaxpr


def iter_eqns(jaxpr: Any, path: str = "") -> Iterator[tuple[str, Any]]:
    """Depth-first ``(path, eqn)`` walk over a (possibly closed) jaxpr,
    descending into every nested sub-jaxpr (pjit bodies, scan bodies,
    shard_map bodies, cond branches)."""
    if hasattr(jaxpr, "jaxpr"):          # ClosedJaxpr -> Jaxpr
        jaxpr = jaxpr.jaxpr
    for i, eqn in enumerate(jaxpr.eqns):
        where = f"{path}eqns[{i}]:{eqn.primitive.name}"
        yield where, eqn
        for pname, pval in eqn.params.items():
            for sub in _sub_jaxprs(pval):
                yield from iter_eqns(sub, f"{where}/{pname}/")


def _eqn_txt(eqn: Any, limit: int = 160) -> str:
    txt = " ".join(str(eqn).split())
    return txt if len(txt) <= limit else txt[: limit - 3] + "..."


def _avals(vars_: Any) -> Iterator[Any]:
    for v in vars_:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "dtype"):
            yield aval


# -- contract 1: dtype / precision ------------------------------------------

#: Width-doubling dtypes that must never appear in a serving graph.
BANNED_DTYPES = ("float64", "complex64", "complex128")


def dtype_histogram(closed_jaxpr: Any) -> dict[str, int]:
    """``dtype name -> eqn-output count`` over the whole graph (the
    report's precision inventory)."""
    hist: dict[str, int] = {}
    for _, eqn in iter_eqns(closed_jaxpr):
        for aval in _avals(eqn.outvars):
            name = str(aval.dtype)
            hist[name] = hist.get(name, 0) + 1
    return hist


def check_dtypes(closed_jaxpr: Any,
                 banned: tuple[str, ...] = BANNED_DTYPES) -> list[Finding]:
    """No banned dtype may appear on any equation input or output."""
    out: list[Finding] = []
    for where, eqn in iter_eqns(closed_jaxpr):
        for aval in _avals(list(eqn.outvars) + list(eqn.invars)):
            if str(aval.dtype) in banned:
                out.append(Finding(
                    contract="dtype", where=where, eqn=_eqn_txt(eqn),
                    reason=(f"{aval.dtype} value of shape "
                            f"{tuple(aval.shape)} in the step graph — "
                            "the serving plane is quantized/f32-only"),
                ))
                break  # one finding per eqn is enough to act on
    return out


def check_quantized_lane(closed_jaxpr: Any) -> list[Finding]:
    """A quantized model's classifier matmul must be an integer-domain
    ``dot_general`` — if every dot in the graph runs on floats, the int8
    weights were silently dequantized before the MXU."""
    saw_dot = False
    for _, eqn in iter_eqns(closed_jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        saw_dot = True
        if any(str(a.dtype).startswith(("int", "uint"))
               for a in _avals(eqn.invars)):
            return []
    if not saw_dot:
        return []  # no matmul at all (non-MXU model): nothing to pin
    return [Finding(
        contract="quantized",
        reason=("model is configured quantized but no integer-domain "
                "dot_general exists in the graph — the int8 lane was "
                "silently promoted to float before the matmul"),
    )]


# -- contract 3b: host round-trips ------------------------------------------

def check_callbacks(closed_jaxpr: Any) -> list[Finding]:
    """No host-callback / infeed / outfeed primitive may hide in the
    step: each one is an unbounded mid-graph host sync."""
    out = []
    for where, eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMITIVES or "callback" in name:
            out.append(Finding(
                contract="transfer", where=where, eqn=_eqn_txt(eqn),
                reason=(f"host round-trip primitive {name!r} in the "
                        "step graph — the serving step's only host "
                        "contact is the post-step wire fetch"),
            ))
    return out


# -- contract 5: collectives ------------------------------------------------

def check_collectives(closed_jaxpr: Any, verdict_k: int,
                      expect_sharded: bool) -> tuple[list[Finding], dict]:
    """Enumerate cross-device primitives and hold them to the design:

    * single-device variants contain none at all;
    * sharded variants contain at most :data:`MAX_ALL_TO_ALL`
      ``all_to_all``\\s (flow routing), ``all_gather`` only on
      verdict_k-sized operands (the compact wire fold), and scalar
      ``psum``/``pmax`` reductions — nothing may gather or reduce a
      ``[B]``-shaped per-record array across the mesh.

    The budget holds for EVERY staged variant, the device-loop ring
    included: megasteps and the ring are (nested) ``lax.scan``\\s, and
    a scan stages its body jaxpr once — so the graph text carries the
    designed per-step collective set exactly once regardless of group
    size or ring depth (test-pinned by the sharded_device_loop audit
    acceptance)."""
    findings: list[Finding] = []
    counts: dict[str, int] = {}
    for where, eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMITIVES:
            continue
        counts[name] = counts.get(name, 0) + 1
        sizes = [int(a.size) for a in _avals(eqn.invars)]
        if not expect_sharded:
            findings.append(Finding(
                contract="collectives", where=where, eqn=_eqn_txt(eqn),
                reason=(f"collective {name!r} in a single-device step "
                        "variant"),
            ))
            continue
        if name not in EXPECTED_COLLECTIVES:
            findings.append(Finding(
                contract="collectives", where=where, eqn=_eqn_txt(eqn),
                reason=(f"unexpected collective {name!r} — the sharded "
                        "step's traffic is all_to_all routing, the "
                        "wire all_gather, and scalar reductions only"),
            ))
        elif name == "all_gather":
            bad = [s for s in sizes if s != verdict_k]
            if bad:
                findings.append(Finding(
                    contract="collectives", where=where,
                    eqn=_eqn_txt(eqn),
                    reason=(f"all_gather on a {bad[0]}-element operand; "
                            f"only the [{verdict_k}]-slot compact wire "
                            "may be gathered (per-record arrays stay "
                            "on their shard)"),
                ))
        elif name in ("psum", "pmax", "pmin"):
            bad = [s for s in sizes if s > REDUCTION_MAX_ELEMS]
            if bad:
                findings.append(Finding(
                    contract="collectives", where=where,
                    eqn=_eqn_txt(eqn),
                    reason=(f"{name} over a {bad[0]}-element operand "
                            f"(> {REDUCTION_MAX_ELEMS}): cross-device "
                            "reductions carry stat counts and clocks, "
                            "never batch data"),
                ))
    if counts.get("all_to_all", 0) > MAX_ALL_TO_ALL:
        findings.append(Finding(
            contract="collectives",
            reason=(f"{counts['all_to_all']} all_to_all ops in one step "
                    f"(design: {MAX_ALL_TO_ALL} — flow partials out, "
                    "verdicts back); extra ones double-route the batch"),
        ))
    return findings, counts


# -- contract 2: donation ---------------------------------------------------

_ALIAS_RE = re.compile(r"\(\s*(\d+)\s*,")
_SHAPE_TOKEN = re.compile(
    r"(?:pred|s4|u4|s8|u8|s16|u16|s32|u32|s64|u64|f8\w*|f16|bf16|f32|f64|"
    r"c64|c128)\[[^\]]*\]")


def _entry_param_tokens(hlo_text: str) -> list[str]:
    """Shape tokens of the entry parameters, in declaration order, off
    the ``entry_computation_layout`` header ([] when absent)."""
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo_text,
                  re.DOTALL)
    return _SHAPE_TOKEN.findall(m.group(1)) if m else []


def parse_alias_map(hlo_text: str) -> tuple[set[int], int]:
    """Parse the compiled module header: returns (aliased parameter
    numbers from ``input_output_alias``, total entry parameter count
    from ``entry_computation_layout``)."""
    aliased: set[int] = set()
    i = hlo_text.find("input_output_alias={")
    if i >= 0:
        # entries look like "{out_idx}: (param, {param_idx}, kind)" —
        # scan forward to the balanced close of the outer map
        depth, k = 0, i + len("input_output_alias=")
        start = k
        while k < len(hlo_text):
            if hlo_text[k] == "{":
                depth += 1
            elif hlo_text[k] == "}":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        body = hlo_text[start:k + 1]
        aliased = {int(m.group(1)) for m in _ALIAS_RE.finditer(body)}
    return aliased, len(_entry_param_tokens(hlo_text))


def check_donation(hlo_text: str, donated_names: list[str],
                   donated_avals: list[Any],
                   n_inputs: int) -> tuple[list[Finding], dict]:
    """Every donated input leaf must appear as an alias source in the
    executable's ``input_output_alias`` map.

    Donated leaves are the *first* ``len(donated_names)`` flattened
    parameters (``donate_argnums`` always covers the leading table/stats
    args here); ``n_inputs`` is the flattened input count, used to
    detect parameter dropping (``keep_unused=False`` elides unused
    params, which would shift numbering — that itself is a finding: a
    donated buffer the graph never reads means the state isn't
    threading through the step at all)."""
    findings: list[Finding] = []
    aliased, n_params = parse_alias_map(hlo_text)
    if n_params and n_params != n_inputs:
        findings.append(Finding(
            contract="donation",
            reason=(f"executable has {n_params} parameters for "
                    f"{n_inputs} traced inputs — unused (dropped) "
                    "arguments; donated state must be live in the "
                    "graph for in-place updates to mean anything"),
        ))
        return findings, {"aliased_params": sorted(aliased),
                          "n_params": n_params}
    for idx, (name, aval) in enumerate(zip(donated_names, donated_avals)):
        if idx not in aliased:
            nbytes = int(aval.size) * aval.dtype.itemsize
            findings.append(Finding(
                contract="donation", where=name,
                reason=(f"donated buffer {name} ({aval.dtype}"
                        f"{tuple(aval.shape)}, {nbytes} B) is NOT in "
                        "the executable's input_output_alias map — "
                        "every batch would allocate and copy it "
                        "instead of updating HBM in place"),
            ))
    return findings, {"aliased_params": sorted(aliased),
                      "n_params": n_params or n_inputs}


# -- contract 6: in-place / copy census -------------------------------------

#: numpy dtype name -> HLO shape-token prefix (the subset the serving
#: plane can produce; anything else simply won't match a table leaf).
_HLO_DTYPE = {
    "uint8": "u8", "uint16": "u16", "uint32": "u32", "uint64": "u64",
    "int8": "s8", "int16": "s16", "int32": "s32", "int64": "s64",
    "float16": "f16", "bfloat16": "bf16", "float32": "f32",
    "bool": "pred",
}


def _is_literal(var: Any) -> bool:
    # test the POSITIVE property (Literal carries .val) so a jax
    # upgrade reshaping Var internals fails closed, not open
    return hasattr(var, "val")


def check_inplace(closed_jaxpr: Any, hlo_text: str | None,
                  table_avals: list[Any],
                  table_names: list[str],
                  n_shards: int = 1) -> tuple[list[Finding], dict]:
    """The donated table must stay on XLA's in-place path end to end.

    Two measured cliffs (PR 8) defeat it, each ~2 orders of magnitude
    at production capacity, and both are *graph facts* this contract
    pins statically instead of leaving to the bench:

    * a ``lax.cond`` carrying the table copies operands and results
      through the ``conditional`` every batch, even when the branch
      never fires;
    * a dynamic-offset ``dynamic_slice``/``dynamic_update_slice``
      touching the table defeats in-place buffer reuse for the whole
      donated chain (a CONSTANT-offset window is fine, and so are the
      single-index scatters XLA itself fuses into DUS — the checked
      property is table-shaped jaxpr-level DUS with computed starts,
      which the fast gather + victim-only-scatter form never emits).

    The jaxpr half catches both at their source equation (matching
    the global table shapes AND, given ``n_shards``, the per-shard
    shapes staged inside ``shard_map`` bodies); the HLO half is the
    executable-level census — zero ``copy``/``convert`` ops producing
    a table-shaped buffer, and no ``conditional`` whose operands carry
    one (shapes are read per-executable, so sharded variants census
    their local shard shapes)."""
    findings: list[Finding] = []
    sigs: dict[tuple, str] = {}
    for a, n in zip(table_avals, table_names):
        shp = tuple(int(d) for d in a.shape)
        sigs[(shp, str(a.dtype))] = n
        # shard_map bodies stage SHARD-LOCAL avals (the layout shards
        # table.* along the leading ip axis), so the per-shard shape
        # must be a table signature too — otherwise the production
        # scan-over-shard_map variants are blind to both cliffs at
        # the jaxpr level
        if n_shards > 1 and shp and shp[0] % n_shards == 0:
            local = (shp[0] // n_shards,) + shp[1:]
            sigs.setdefault((local, str(a.dtype)), n)

    def sig_of(aval: Any) -> str | None:
        if aval is None or not hasattr(aval, "dtype"):
            return None
        return sigs.get((tuple(int(d) for d in getattr(aval, "shape",
                                                       ()) or ()),
                         str(aval.dtype)))

    for where, eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name == "cond":
            carried = sorted({
                s for v in list(eqn.invars) + list(eqn.outvars)
                if (s := sig_of(getattr(v, "aval", None))) is not None})
            if carried:
                findings.append(Finding(
                    contract="inplace", where=where, eqn=_eqn_txt(eqn),
                    reason=(f"lax.cond carries the donated table "
                            f"({', '.join(carried)}) — XLA:CPU copies "
                            "conditional operands and results every "
                            "batch even when the branch never fires "
                            "(the PR 8 in-place cliff); hoist the "
                            "table out of the cond or rewrite as a "
                            "lax.select/where on the rows"),
                ))
        elif name in ("dynamic_slice", "dynamic_update_slice"):
            operand = sig_of(getattr(eqn.invars[0], "aval", None))
            idx_start = 2 if name == "dynamic_update_slice" else 1
            dynamic = any(not _is_literal(v)
                          for v in eqn.invars[idx_start:])
            if operand is not None and dynamic:
                findings.append(Finding(
                    contract="inplace", where=where, eqn=_eqn_txt(eqn),
                    reason=(f"dynamic-offset {name} on the donated "
                            f"table ({operand}) — a computed start "
                            "index defeats XLA:CPU in-place reuse for "
                            "the whole donated chain (the PR 8 DUS "
                            "cliff); use gather reads + victim-only "
                            "scatter writes (the eviction sweep's "
                            "proven form)"),
                ))

    census = {"checked": hlo_text is not None,
              "copies": 0, "converts": 0, "conditionals": 0}
    if hlo_text is not None:
        # executable-local table types come off the entry layout — the
        # leading parameters are the donated leaves, so sharded
        # variants census their per-device shard shapes automatically;
        # the no-header fallback covers both signature sets (a global
        # token would never match a shard-local executable's text)
        tokens = _entry_param_tokens(hlo_text)[:len(table_avals)] or [
            f"{_HLO_DTYPE.get(dt, dt)}[{','.join(map(str, shp))}]"
            for (shp, dt) in sigs]
        toks = sorted({t.split("{")[0] for t in tokens})
        pat = "|".join(re.escape(t) for t in toks)
        census["table_types"] = toks
        for op, key in (("copy", "copies"), ("convert", "converts")):
            n = len(re.findall(
                rf"= ({pat})\{{[^}}]*\}} {op}\(", hlo_text))
            census[key] = n
            if n:
                findings.append(Finding(
                    contract="inplace",
                    reason=(f"{n} {op} op(s) producing a table-shaped "
                            f"buffer ({', '.join(toks)}) in the "
                            "compiled executable — the donated table "
                            "must flow copy-free through every step "
                            "variant (each one is a full-table "
                            "materialization per batch)"),
                ))
        # operand lists nest parens (tuple-typed operands), so walk to
        # the balanced close of each call — a single [^)]* scan would
        # stop at the first inner ')' and miss a table operand sitting
        # after an earlier tuple operand
        pat_re = re.compile(pat)
        n_cond = 0
        for mc in re.finditer(r"conditional\(", hlo_text):
            depth, k = 1, mc.end()
            while k < len(hlo_text) and depth:
                c = hlo_text[k]
                if c == "(":
                    depth += 1
                elif c == ")":
                    depth -= 1
                k += 1
            if pat_re.search(hlo_text, mc.end(), k):
                n_cond += 1
        census["conditionals"] = n_cond
        if n_cond:
            findings.append(Finding(
                contract="inplace",
                reason=(f"{n_cond} conditional op(s) carry a "
                        f"table-shaped operand ({', '.join(toks)}) in "
                        "the compiled executable — XLA:CPU copies "
                        "conditional operands/results every batch "
                        "(the PR 8 cond cliff)"),
            ))
    return findings, census


# -- contract 4: retrace sentinel -------------------------------------------

def staging_cache_check(jitted: Any, make_args: Callable[[], tuple],
                        arg_names: Callable[[int], str] = lambda i: f"arg[{i}]",
                        ) -> tuple[list[Finding], Any]:
    """Stage ``jitted`` twice with independently constructed inputs and
    require the second trace to hit the tracing cache.

    A miss means two host-side constructions of "the same" batch differ
    in aval (dtype / shape / weak_type) or static metadata — exactly
    the drift that makes a serving loop silently recompile per
    dispatch.  Returns ``(findings, traced)`` with the first trace for
    further graph checks.  The diagnostic names the first differing
    input."""
    t1 = jitted.trace(*make_args())
    t2 = jitted.trace(*make_args())
    if t2.jaxpr is t1.jaxpr:  # the tracing cache returns one object
        return [], t1
    diffs = []
    a1, a2 = list(t1.jaxpr.in_avals), list(t2.jaxpr.in_avals)
    for i, (x, y) in enumerate(zip(a1, a2)):
        if (x.shape, x.dtype, getattr(x, "weak_type", False)) != (
                y.shape, y.dtype, getattr(y, "weak_type", False)):
            diffs.append(f"{arg_names(i)}: {x.str_short()} vs "
                         f"{y.str_short()}")
    if len(a1) != len(a2):
        diffs.append(f"input leaf count {len(a1)} vs {len(a2)}")
    reason = ("staging twice under one BatchConfig re-traced (jit cache "
              "miss) — the serving loop would recompile every batch. ")
    reason += ("Differing inputs: " + "; ".join(diffs[:4])) if diffs else (
        "Avals identical: static-argument or donation metadata drift.")
    return [Finding(contract="retrace", reason=reason)], t1


def check_carry_avals(closed_jaxpr: Any, n_carry: int,
                      names: list[str]) -> list[Finding]:
    """The step's carried state (table, stats — outputs fed back as the
    next batch's inputs) must come out with avals identical to how it
    went in; any weak_type/dtype wobble retraces on the *second* batch
    and every batch after."""
    out = []
    ins = list(closed_jaxpr.in_avals)[:n_carry]
    outs = list(closed_jaxpr.out_avals)[:n_carry]
    for name, i, o in zip(names, ins, outs):
        if (i.shape, i.dtype, getattr(i, "weak_type", False)) != (
                o.shape, o.dtype, getattr(o, "weak_type", False)):
            out.append(Finding(
                contract="retrace", where=name,
                reason=(f"carried state {name} changes aval through the "
                        f"step ({i.str_short()} in, {o.str_short()} "
                        "out): feeding outputs back would retrace "
                        "every serving iteration"),
            ))
    return out
