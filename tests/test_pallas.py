"""Pallas kernels vs their XLA-composed twins.

These tests run the kernels in interpret mode (CPU harness).  The
Mosaic/TPU lowering is exercised by selecting the registered
``logreg_int8_pallas`` model (registry.py) in an engine/bench config on
real hardware; the kernels were validated bit-exact under Mosaic at
batch 2048/16384/131072 during development."""

import numpy as np
import pytest

import jax.numpy as jnp

from flowsentryx_tpu.core import schema
from flowsentryx_tpu.models import logreg
from flowsentryx_tpu.ops import pallas_kernels as pk


class TestScoreInt8:
    @pytest.mark.parametrize("b", [1, 7, 512, 1000])
    def test_matches_xla_twin_golden(self, rng, b):
        params = logreg.golden_params()
        x = rng.uniform(0, 2e6, (b, schema.NUM_FEATURES)).astype(np.float32)
        want = np.asarray(logreg.classify_batch_int8_matmul(params, jnp.asarray(x)))
        got = np.asarray(pk.score_int8(params, jnp.asarray(x)))
        np.testing.assert_array_equal(got, want)

    def test_matches_xla_twin_log1p_artifact(self, rng):
        """Trained (log-domain) artifacts score identically too."""
        from flowsentryx_tpu.train import data, qat

        X, y = data.synthetic_dataset(4000, seed=21)
        res = qat.train_logreg_qat(X, y, epochs=60)
        xt = rng.uniform(0, 1e6, (256, schema.NUM_FEATURES)).astype(np.float32)
        want = np.asarray(
            logreg.classify_batch_int8_matmul(res.params, jnp.asarray(xt))
        )
        got = np.asarray(pk.score_int8(res.params, jnp.asarray(xt)))
        np.testing.assert_array_equal(got, want)

    def test_output_domain(self, rng):
        params = logreg.golden_params()
        x = rng.uniform(0, 1e7, (64, 8)).astype(np.float32)
        p = np.asarray(pk.score_int8(params, jnp.asarray(x)))
        q = p * 256.0
        np.testing.assert_array_equal(q, np.round(q))  # exact 1/256 grid
        assert (p >= 0).all() and (p <= 255 / 256).all()


class TestTableSummary:
    def test_counts_match_numpy(self, rng):
        cap = 4096
        table = schema.make_table(cap)
        n_fill = 600
        keys = rng.choice(np.arange(1, 1 << 24), n_fill, replace=False)
        slots = rng.choice(cap, n_fill, replace=False)
        key = np.zeros(cap, np.uint32)
        key[slots] = keys
        seen = np.zeros(cap, np.float32)
        seen[slots] = rng.uniform(0, 100, n_fill)
        blocked = np.zeros(cap, np.float32)
        blocked[slots[:200]] = rng.uniform(100, 200, 200)  # future expiry
        table = table._replace(key=jnp.asarray(key)).with_columns(
            last_seen=jnp.asarray(seen),
            blocked_until=jnp.asarray(blocked),
        )
        now, stale_s = 90.0, 30.0
        s = pk.table_summary(table, now=now, stale_s=stale_s)
        tracked = key != 0
        assert s["tracked"] == int(tracked.sum()) == n_fill
        assert s["blocked"] == int((tracked & (blocked > now)).sum())
        assert s["stale"] == int((tracked & (now - seen > stale_s)).sum())
        assert s["newest_seen_s"] == pytest.approx(seen.max(), rel=1e-6)

    def test_empty_table(self):
        table = schema.make_table(2048)
        s = pk.table_summary(table, now=5.0)
        assert s == {"tracked": 0, "blocked": 0, "stale": 0, "newest_seen_s": 0.0}

    def test_small_table_falls_back_to_xla(self, rng):
        """Capacities below one kernel chunk use the XLA twin."""
        table = schema.make_table(512)  # < one 1024-element chunk
        key = np.zeros(512, np.uint32)
        key[:40] = rng.integers(1, 1 << 24, 40)
        table = table._replace(key=jnp.asarray(key))
        s = pk.table_summary(table, now=1.0)
        assert s["tracked"] == 40 and s["blocked"] == 0

    def test_mosaic_kernel_parity_with_xla_twin(self, rng):
        """The Pallas kernel (the real-TPU path) stays in lockstep
        with the XLA twin.  CPU serving now routes to the twin —
        interpret mode walks the grid step by step, measured ~100 s
        per 4M-row report scan — so the kernel is exercised here
        DIRECTLY to keep it from rotting."""
        cap = 8192
        key = np.zeros(cap, np.uint32)
        slots = rng.choice(cap, 900, replace=False)
        key[slots] = rng.integers(1, 1 << 24, 900)
        state = np.zeros((cap, schema.NUM_TABLE_COLS), np.float32)
        state[slots, int(schema.TableCol.LAST_SEEN)] = rng.uniform(
            0, 100, 900)
        state[slots[:300], int(schema.TableCol.BLOCKED_UNTIL)] = (
            rng.uniform(100, 200, 300))
        args = (jnp.asarray(key), jnp.asarray(state),
                jnp.float32(90.0), 30.0)
        cp, np_ = pk._table_summary(*args, use_pallas=True)
        cx, nx = pk._table_summary(*args, use_pallas=False)
        np.testing.assert_array_equal(np.asarray(cp), np.asarray(cx))
        assert float(np_) == float(nx)
