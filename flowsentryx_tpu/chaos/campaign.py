"""The seed-driven chaos campaign: real stack in, verdicts out.

Every scenario below drives REAL protocol objects — a compiled serving
:class:`~flowsentryx_tpu.engine.engine.Engine`, a live
:class:`~flowsentryx_tpu.ingest.sharded.ShardedIngest` worker fleet
over real shm rings, the :class:`~flowsentryx_tpu.cluster.supervisor
.ClusterSupervisor` with real child processes, real
:class:`~flowsentryx_tpu.cluster.gossip.GossipPlane` mailbox pairs —
and judges the outcome by the named invariants of
:mod:`~flowsentryx_tpu.chaos.invariants`.  One jitted engine is booted
per campaign and shared across the engine-side scenarios (compile is
the dominant cost; the scenarios are ordered so each leaves the engine
in the state the next needs, ending with the watchdog wedge that
deliberately fails it).

The PLANTED regressions at the end are the campaign's negative
controls, per the ``fsx ranges``/``fsx sync`` discipline: each
re-introduces a pre-hardening weakness (split-atomicity crash
accounting, CRC-less checkpoint loads, no-backoff respawn, datagram
dup-suppression removed, epoch rebase skipped, handoff conservation
unverified) and PASSES only when
the named invariant FAILS under it — proving the invariants have
teeth, not just green lights.

Determinism: every random choice flows from one
``numpy.random.default_rng(seed)``; wall-clock only bounds waits.
"""

from __future__ import annotations

import contextlib
import io
import json
import time
from pathlib import Path

import numpy as np

from flowsentryx_tpu.chaos import faults
from flowsentryx_tpu.chaos.invariants import all_ok, check

#: Bound (seconds) inside which a killed rank must be re-serving (its
#: next generation heartbeating) — generous against CI throttling, yet
#: three orders of magnitude under "an operator noticed".
RECOVERY_BOUND_S = 15.0


def _scenario(name: str, invs: list, **extra) -> dict:
    cls, desc = faults.FAULTS[name]
    return {
        "fault": name,
        "fault_class": cls,
        "description": desc,
        "ok": all_ok(invs),
        "invariants": [r.to_json() for r in invs],
        **extra,
    }


# ---------------------------------------------------------------------------
# supervisor scenarios (stub ranks: the real supervision protocol in ms)
# ---------------------------------------------------------------------------

def scenario_engine_kill(tmp: Path, rng: np.random.Generator) -> dict:
    """SIGKILL a supervised rank mid-serve at a seeded point; the
    crash-fail-open contract must hold: respawn from checkpoint within
    the bound, survivor untouched, aggregation counting each rank's
    latest generation once."""
    from flowsentryx_tpu.cluster.mailbox import StatusBlock, status_path
    from flowsentryx_tpu.cluster.runner import stub_engine_main
    from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor

    ck = tmp / "kill_ck_r1.npz"
    ck.write_bytes(b"stub flow memory")
    kill_at = faults.pick_kill_delay_s(rng)
    sup = ClusterSupervisor(
        tmp / "kill_cl",
        [{"stub_serve_s": 2.0, "workers": 1},
         {"stub_serve_s": 2.0, "checkpoint": str(ck), "workers": 1}],
        entry=stub_engine_main)
    sup.boot()
    st1 = StatusBlock(status_path(tmp / "kill_cl", 1))
    t0 = time.monotonic()
    killed_t = None
    recovered_t = None
    hbeat_floor = 0
    deadline = t0 + 30.0
    try:
        while time.monotonic() < deadline:
            sup.poll()
            hb = st1.ctl_get("c_hbeat")
            if killed_t is None:
                if hb and time.monotonic() - t0 >= kill_at:
                    hbeat_floor = hb
                    sup.kill(1)
                    killed_t = time.monotonic()
            elif (st1.ctl_get("c_gen") == 1 and hb > hbeat_floor):
                recovered_t = time.monotonic()
                break
            time.sleep(0.02)
        sup.run()  # serve the remainder to completion
    finally:
        sup.close()
    agg = sup.aggregate()
    recovery_s = (recovered_t - killed_t) if recovered_t else None
    invs = [
        check("recovery_within_bound",
              recovery_s is not None and recovery_s < RECOVERY_BOUND_S,
              f"kill->gen1-heartbeat {recovery_s!r}s "
              f"(bound {RECOVERY_BOUND_S}s, incl. backoff)"),
        check("fail_open_holds",
              agg["failed_ranks"] == [] and agg["restarts"] == [0, 1],
              f"restarts={agg['restarts']} failed={agg['failed_ranks']}"),
        check("counters_conserved",
              len({(r["rank"], r["gen"]) for r in agg["reports"]})
              == len(agg["reports"])
              and any(r["rank"] == 1 and r["gen"] == 1
                      and r.get("restored") == str(ck)
                      for r in agg["reports"]),
              "latest-gen dedup held and gen-1 restored its checkpoint"),
    ]
    return _scenario("engine_kill", invs, kill_at_s=round(kill_at, 3),
                     recovery_s=(round(recovery_s, 3)
                                 if recovery_s else None))


def scenario_crash_loop(tmp: Path, rng: np.random.Generator,
                        *, window_s: float = 60.0,
                        backoff_s: float = 0.05,
                        max_restarts: int = 2,
                        name: str = "crash_loop") -> dict:
    """A rank that dies instantly EVERY generation: the crash-loop
    discipline must back off exponentially and park it as failed
    within the sliding-window budget — instead of the pre-PR-13
    spin (respawn in ms, budget gone before a human reads line one).
    The ``backoff_removed`` plant re-runs this with the window
    disabled and must see ``crash_loop_parks`` FAIL."""
    del rng  # the crash schedule is "always, immediately" by design
    from flowsentryx_tpu.cluster.runner import stub_engine_main
    from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor

    sup = ClusterSupervisor(
        tmp / f"{name}_cl",
        [{"stub_serve_s": 3.0, "workers": 1},
         {"stub_serve_s": 30.0, "stub_crash_after_s": 0.0,
          "stub_crash_every_gen": True, "workers": 1}],
        entry=stub_engine_main,
        max_restarts=max_restarts,
        restart_backoff_s=backoff_s,
        restart_window_s=window_s)
    sup.boot()
    deadline = time.monotonic() + 20.0
    stderr = io.StringIO()
    try:
        with contextlib.redirect_stderr(stderr):
            while (1 not in sup._failed
                   and sup.restarts[1] <= max_restarts + 2
                   and time.monotonic() < deadline):
                sup.poll()
                time.sleep(0.01)
    finally:
        sup.close()
    deaths = sup._death_times[1]
    gaps = [round(b - a, 4) for a, b in zip(deaths, deaths[1:])]
    # death k+1 happens >= the backoff delay after death k (the stub
    # dies instantly, so the inter-death gap IS the respawn delay);
    # 0.7x slack absorbs scheduler jitter without hiding a no-backoff
    # regression (which respawns in ~10 ms)
    expected = [min(backoff_s * (2 ** k), 5.0)
                for k in range(len(gaps))]
    spacing_ok = all(g >= 0.7 * e for g, e in zip(gaps, expected))
    parked_announced = "PARKED as failed" in stderr.getvalue()
    parked = (1 in sup._failed and sup.restarts[1] == max_restarts
              and parked_announced)
    invs = [
        check("crash_loop_parks", parked,
              f"restarts={sup.restarts[1]} (budget {max_restarts}), "
              f"failed={sorted(sup._failed)}, span "
              f"announced={parked_announced}"),
        check("respawn_backoff_spacing",
              spacing_ok and len(gaps) >= 1,
              f"inter-death gaps {gaps}s vs backoff ladder "
              f"{expected}s"),
        check("fail_open_holds", 0 not in sup._failed,
              "rank 0 never entered failed"),
    ]
    return _scenario("crash_loop", invs, inter_death_gaps_s=gaps,
                     restarts=sup.restarts[1])


# ---------------------------------------------------------------------------
# checkpoint scenarios
# ---------------------------------------------------------------------------

def _tiny_snapshot(tmp: Path, name: str = "tiny_snap",
                   salt: int = 0) -> Path:
    from flowsentryx_tpu.core import schema
    from flowsentryx_tpu.engine import checkpoint as ckpt

    tmp.mkdir(parents=True, exist_ok=True)
    table = schema.make_table(256)
    table = type(table)(key=np.asarray(table.key),
                        state=np.asarray(table.state))
    stats = type(schema.make_stats())(
        *(np.asarray(v) for v in schema.make_stats()))
    return ckpt.save_state(tmp / name, table, stats,
                           t0_ns=12345, hash_salt=salt)


def scenario_ckpt_truncate(tmp: Path, rng: np.random.Generator) -> dict:
    """Truncated and zero-length checkpoints must raise the NAMED
    error through the pre-boot validation path — a torn-at-create file
    used to leak a raw struct/IndexError out of ``peek_header``."""
    from flowsentryx_tpu.engine import checkpoint as ckpt

    path = _tiny_snapshot(tmp, "snap_truncate")
    frac = float(0.2 + 0.6 * rng.random())
    faults.truncate_file(path, frac)
    named_trunc, err_trunc = False, ""
    try:
        ckpt.peek_header(path)
    except ckpt.CheckpointCorrupt as e:
        named_trunc, err_trunc = True, str(e)
    except Exception as e:  # noqa: BLE001 — the raw-leak regression
        err_trunc = f"RAW {type(e).__name__}: {e}"
    faults.truncate_file(path, 0.0)
    named_empty, err_empty = False, ""
    try:
        ckpt.peek_header(path)
    except ckpt.CheckpointCorrupt as e:
        named_empty, err_empty = True, str(e)
    except Exception as e:  # noqa: BLE001
        err_empty = f"RAW {type(e).__name__}: {e}"
    load_refused = False
    try:
        ckpt.load_checkpoint(path)
    except ckpt.CheckpointCorrupt:
        load_refused = True
    except ValueError:
        pass
    invs = [
        check("corrupt_ckpt_refused",
              named_trunc and named_empty and load_refused,
              f"truncated->({err_trunc!r}) empty->({err_empty!r})"),
    ]
    return _scenario("ckpt_truncate", invs,
                     truncate_fraction=round(frac, 3))


def scenario_ckpt_bitflip(tmp: Path, rng: np.random.Generator) -> dict:
    """Two corruption legs: raw byte flips (structural/zlib refusal)
    and a CLEAN-DECODE splice — valid zip, wrong bytes — that only the
    folded CRC32 can catch.  Both must refuse with the named error."""
    from flowsentryx_tpu.engine import checkpoint as ckpt

    # leg 1: raw flips
    p1 = _tiny_snapshot(tmp, "snap_flip")
    offs = faults.flip_bytes(p1, rng)
    raw_refused = False
    try:
        ckpt.load_checkpoint(p1)
    except ckpt.CheckpointCorrupt:
        raw_refused = True
    # leg 2: clean splice — re-encode with one flipped value but the
    # ORIGINAL stored CRC (a valid zip whose contents lie)
    p2 = _tiny_snapshot(tmp, "snap_splice")
    with np.load(p2) as z:
        data = {k: np.array(z[k]) for k in z.files}
    data["table_key"] = data["table_key"].copy()
    data["table_key"][int(rng.integers(0, len(data["table_key"])))] ^= 1
    np.savez_compressed(p2, **data)
    crc_refused, crc_msg = False, ""
    try:
        ckpt.load_checkpoint(p2)
    except ckpt.CheckpointCorrupt as e:
        crc_refused, crc_msg = True, str(e)
    invs = [
        check("corrupt_ckpt_refused", raw_refused and crc_refused,
              f"raw-flip refused={raw_refused} (offsets {offs[:4]}...), "
              f"clean-splice refused={crc_refused}"),
        check("no_silent_verdict_loss",
              "CRC32" in crc_msg or "integrity" in crc_msg,
              f"the clean splice was caught BY the CRC leg: {crc_msg!r}"),
    ]
    return _scenario("ckpt_bitflip", invs, flip_offsets=offs)


def scenario_ckpt_fallback(engine, tmp: Path,
                           rng: np.random.Generator) -> dict:
    """REAL-engine restore fallback: corrupt the live checkpoint of a
    serving engine (clean splice, so the CRC is what refuses) and
    restore — the engine must fall back to the retained ``.prev``
    generation, loudly, with the restored table provably that
    generation's."""
    from flowsentryx_tpu.engine import checkpoint as ckpt
    import jax

    path = tmp / "eng_ck.npz"
    engine.checkpoint(path)          # generation A (becomes .prev)
    engine.checkpoint(path)          # generation B (rotates A out)
    prev = ckpt.prev_path(path)
    prev_key = np.asarray(ckpt.load_checkpoint(prev).table.key)
    with np.load(path) as z:
        data = {k: np.array(z[k]) for k in z.files}
    data["stats_allowed"] = data["stats_allowed"].copy()
    data["stats_allowed"][0] ^= 0xFFFF
    np.savez_compressed(path, **data)
    stderr = io.StringIO()
    with contextlib.redirect_stderr(stderr):
        info = engine.restore(path)
    restored_key = np.asarray(jax.device_get(engine.table.key)) \
        .reshape(-1)
    direct_refused = False
    try:
        ckpt.load_checkpoint(path)
    except ckpt.CheckpointCorrupt:
        direct_refused = True
    invs = [
        check("corrupt_ckpt_refused", direct_refused,
              "the spliced checkpoint cannot be loaded directly"),
        check("ckpt_fallback_to_prev",
              info.get("fallback_from") == str(path)
              and np.array_equal(np.sort(restored_key),
                                 np.sort(prev_key))
              and "REFUSED" in stderr.getvalue(),
              f"fallback_from={info.get('fallback_from')!r}, table == "
              ".prev generation, announced on stderr"),
        check("health_degraded_reasons",
              engine._restore_fallbacks >= 1,
              f"restore_fallbacks={engine._restore_fallbacks} feeds "
              "the DEGRADED ladder"),
    ]
    del rng
    out = _scenario("ckpt_bitflip", invs)
    out["fault"] = "ckpt_fallback"
    out["description"] = ("the ckpt_bitflip fault exercised through "
                          "the REAL engine's restore path: corrupt "
                          "live checkpoint -> loud .prev fallback")
    return out


# ---------------------------------------------------------------------------
# real engine + sharded ingest: slot corruption / poison / watchdog
# ---------------------------------------------------------------------------

def _engine_cfg(max_batch: int = 64):
    import dataclasses

    from flowsentryx_tpu.core.config import FsxConfig

    cfg = FsxConfig()
    return dataclasses.replace(
        cfg,
        batch=dataclasses.replace(cfg.batch, max_batch=max_batch,
                                  deadline_us=2000),
        table=dataclasses.replace(cfg.table, capacity=1 << 12),
    )


def build_engine_fleet(tmp: Path, rng: np.random.Generator,
                       n_records: int):
    """One real serving engine over a real 1-worker sealed-ingest
    fleet, with ``n_records`` of seeded traffic already in the shard
    ring.  Shared by the engine-side scenarios (one compile per
    campaign)."""
    from flowsentryx_tpu.core import schema
    from flowsentryx_tpu.engine import CollectSink, Engine
    from flowsentryx_tpu.engine.shm import ShmRing
    from flowsentryx_tpu.engine.traffic import (
        Scenario, TrafficGen, TrafficSpec,
    )
    from flowsentryx_tpu.ingest import ShardedIngest

    recs = TrafficGen(TrafficSpec(
        scenario=Scenario.UDP_FLOOD_MULTI, rate_pps=1e6,
        n_attack_ips=8, n_benign_ips=24, attack_fraction=0.8,
        seed=int(rng.integers(0, 1 << 31)),
    )).next_records(n_records)
    base = str(tmp / "chaos_fring")
    ring = ShmRing.create(schema.shard_ring_path(base, 0, 1), 1 << 13,
                          schema.FLOW_RECORD_DTYPE)
    assert ring.produce(recs) == len(recs)
    src = ShardedIngest(base, 1, queue_slots=16, precompact=False,
                        t0_grace_s=0.2,
                        quarantine_dir=str(tmp / "quarantine"))
    sink = CollectSink()
    eng = Engine(_engine_cfg(), src, sink, readback_depth=4,
                 sink_thread=True)
    return eng, src, sink, recs


def scenario_slot_corruption(eng, src, recs,
                             rng: np.random.Generator,
                             tmp: Path) -> dict:
    """Corrupt three SEALED shm slots in place — bad wire-id magic, a
    forward seq jump, and a well-formed-but-poisoned metadata row
    (n_records past max_batch, the RANGE_* premise the fsx ranges
    proof assumes) — then drain through the REAL engine.  The drain
    must survive, every loss must be counted, and the health ladder
    must read DEGRADED with exactly these reasons."""
    del rng
    # resolve the t0 handshake (the workers buffer, unsealed, until
    # the engine publishes the agreed epoch — dispatch_smoke idiom)
    deadline = time.monotonic() + 30.0
    while src.t0_ns is None:
        src.poll_batches(0)
        if time.monotonic() > deadline:
            raise TimeoutError("ingest t0 handshake did not resolve")
        time.sleep(0.01)
    q = src._queues[0]
    faults._wait_readable(q, 4)
    # true record count of the bad-magic slot, read BEFORE corrupting:
    # the conservation invariant needs it (its header is untrusted
    # after)
    from flowsentryx_tpu.core import schema as _schema

    t = int(q._tail[0])
    bad_n_true = int(q._cells[t & (q.slots - 1)][
        _schema.BATCHQ_N_RECORDS_WORD])
    inj = [
        faults.corrupt_sealed_slot(q, "bad_magic", slot_back=0),
        faults.poison_sealed_meta(
            q, words_per_record=src._payload_shape[1],
            max_batch=src._max_batch, slot_back=1),
        faults.corrupt_sealed_slot(q, "seq_gap", slot_back=3),
    ]
    src.request_stop()
    stderr = io.StringIO()
    with contextlib.redirect_stderr(stderr):
        rep = eng.run()
    stats = rep.ingest
    served = rep.records
    quarantined = stats["quarantined_records"]
    conserved = served + quarantined + bad_n_true == len(recs)
    dumps = list((tmp / "quarantine").glob("quarantine_*.npy"))
    reasons = set(rep.health["reasons"])
    invs = [
        check("bad_slot_skipped_counted",
              stats["bad_wire_slots"] == 1
              and "REFUSED" in stderr.getvalue(),
              f"bad_wire_slots={stats['bad_wire_slots']}, announced"),
        check("poison_quarantined",
              stats["quarantined_batches"] == 1 and len(dumps) == 1,
              f"quarantined={stats['quarantined_batches']}, "
              f"spooled={len(dumps)} file(s) in {tmp / 'quarantine'}"),
        check("seq_gap_counted",
              sum(w["seq_gaps"]
                  for w in stats["workers"].values()) >= 1,
              "the seq jump surfaced in the gap counters"),
        check("no_silent_verdict_loss", conserved,
              f"{len(recs)} produced == {served} served + "
              f"{quarantined} quarantined + {bad_n_true} in the "
              "bad-magic slot"),
        check("fail_open_holds",
              not stats["crashed"] and stats["dead_workers"] == [],
              "the drain worker survived all three corruptions"),
        check("health_degraded_reasons",
              rep.health["state"] == "degraded"
              and any(r.startswith("bad_wire_slots:") for r in reasons)
              and any(r.startswith("quarantined_batches:")
                      for r in reasons)
              and any(r.startswith("ingest_seq_gaps:")
                      for r in reasons),
              f"health={rep.health['state']} reasons={sorted(reasons)}"),
    ]
    out = _scenario("shm_bad_magic", invs, injections=inj,
                    records={"produced": len(recs), "served": served,
                             "quarantined": quarantined,
                             "bad_slot": bad_n_true})
    out["fault"] = "shm_bad_magic+poison_batch+shm_seq_gap"
    return out


def scenario_watchdog(eng, rng: np.random.Generator) -> dict:
    """Wedge the verdict sink forever with batches in flight: the
    dispatch watchdog must dump per-thread stacks, count a soft trip,
    and fail the drain with the named error within 2x its stall bound
    — never hang.  Runs LAST: it deliberately leaves the engine
    failed (the wedged worker is released and abandoned)."""
    del rng
    from flowsentryx_tpu.engine.sources import ArraySource
    from flowsentryx_tpu.engine.traffic import (
        Scenario, TrafficGen, TrafficSpec,
    )
    from flowsentryx_tpu.engine.watchdog import (
        DispatchWatchdog, WatchdogStall,
    )

    recs = TrafficGen(TrafficSpec(
        scenario=Scenario.UDP_FLOOD_MULTI, seed=7)).next_records(256)
    wedge = faults.WedgeSink()
    stall_s = 0.4
    eng.reset_stream(ArraySource(recs), sink=wedge)
    eng._watchdog = DispatchWatchdog(stall_s)  # quiescent swap
    stderr = io.StringIO()
    t0 = time.monotonic()
    raised = None
    try:
        with contextlib.redirect_stderr(stderr):
            eng.run(max_seconds=30.0)
    except WatchdogStall as e:
        raised = e
    elapsed = time.monotonic() - t0
    wedge.release()  # let the abandoned worker drain and exit
    err = stderr.getvalue()
    invs = [
        check("watchdog_trips_within_bound",
              raised is not None and elapsed < 10 * stall_s,
              f"WatchdogStall in {elapsed:.2f}s "
              f"(stall bound {stall_s}s): {raised}"),
        check("no_silent_verdict_loss",
              "per-thread stacks" in err
              and "fsx-sink" in err,
              "the stack dump names the wedged sink thread — the "
              "diagnostic an operator needs, automated"),
        check("health_degraded_reasons",
              eng._watchdog.trips >= 1 and eng._watchdog.tripped,
              f"soft trips={eng._watchdog.trips}, hard tripped — the "
              "FAILED rung of the ladder"),
    ]
    return _scenario("sink_wedge", invs,
                     elapsed_s=round(elapsed, 3))


# ---------------------------------------------------------------------------
# gossip + clock scenarios
# ---------------------------------------------------------------------------

def scenario_gossip_stall_flood(tmp: Path,
                                rng: np.random.Generator) -> dict:
    """Flood a 4-slot pair mailbox while the peer's merge tick is
    stalled: the publisher must drop-and-count without ever blocking
    the sink path, and once the peer resumes, every wire that WAS
    delivered must merge last-wins — drops + merges accounting every
    publish."""
    from flowsentryx_tpu.cluster import gossip as gplane
    from flowsentryx_tpu.engine.writeback import BlacklistUpdate

    d = tmp / "gossip_cl"
    k_max, slots = 8, 4
    gplane.create_plane(d, 2, k_max=k_max, slots=slots)
    a = gplane.GossipPlane(d, 0, 2)
    b = gplane.GossipPlane(d, 1, 2)

    def update(n, base):
        keys = (base + np.arange(n)).astype(np.uint32)
        untils = (10.0 + 0.25 * np.arange(n)).astype(np.float32)
        return BlacklistUpdate(key=keys, until_s=untils)

    t0 = time.perf_counter()
    a.publish(update(40, 1000), now=1.0)   # 5 wires; peer stalled
    a.publish(update(40, 2000), now=2.0)   # 5 more into a full box
    publish_wall = time.perf_counter() - t0
    b.tick(force=True)                      # peer resumes: merges 4
    a.publish(update(8, 3000), now=3.0)    # 1 wire; lands after gap
    b.tick(force=True)
    ra, rb = a.report(), b.report()
    # expected delivered set: the first `slots` wires of round 1
    # (32 keys) + the round-3 wire (8 keys), last-wins
    expected = {}
    for upd in (update(40, 1000), ):
        ks = np.asarray(upd.key, np.uint32)[:slots * k_max]
        us = np.asarray(upd.until_s, np.float32)[:slots * k_max]
        expected.update(zip(ks.tolist(),
                            us.view(np.uint32).tolist()))
    u3 = update(8, 3000)
    expected.update(zip(np.asarray(u3.key, np.uint32).tolist(),
                        np.asarray(u3.until_s, np.float32)
                        .view(np.uint32).tolist()))
    del rng
    invs = [
        check("gossip_drop_counted_never_blocks",
              ra["tx_dropped"] == 6 and ra["tx_wires"] == 5
              and publish_wall < 0.5,
              f"11 wires published: {ra['tx_wires']} delivered, "
              f"{ra['tx_dropped']} dropped; flood publish wall "
              f"{publish_wall * 1e3:.1f} ms"),
        check("counters_conserved",
              ra["tx_wires"] + ra["tx_dropped"] == 11
              and rb["rx_wires"] == ra["tx_wires"],
              "drops + merges account every publish"),
        check("seq_gap_counted", rb["rx_seq_gaps"] >= 1,
              f"rx_seq_gaps={rb['rx_seq_gaps']} (the dropped wires' "
              "hole in the sequence space)"),
        check("gossip_delivered_converges",
              rb["merged_digest"]
              == gplane.GossipPlane._digest(expected),
              f"merged digest {rb['merged_digest']} == last-wins of "
              f"the {len(expected)} delivered sources"),
    ]
    return _scenario("gossip_stall_flood", invs)


def scenario_clock_jump(rng: np.random.Generator) -> dict:
    """Feed the latency plane stage intervals derived from a clock
    that jumped backwards: negatives must be counted (the stamp-
    monotonicity gauge), percentiles must stay finite and ordered,
    and nothing may raise."""
    from flowsentryx_tpu.engine.metrics import LatencyRecorder

    stamps = faults.jumped_stamps(rng, 64)
    lat = LatencyRecorder()
    neg_expected = 0
    for i in range(1, len(stamps)):
        dt = stamps[i] - stamps[i - 1]
        if dt < 0:
            neg_expected += 1
        lat.record(total_s=dt, staged_s=dt / 2, upload_s=0.0,
                   compute_s=dt / 4, sink_s=dt / 4, n=4)
    d = lat.to_dict()
    sv = d["seal_to_verdict"]
    pcts = [sv.get(k) for k in ("p50", "p90", "p99")]
    finite = all(p is not None and np.isfinite(p) and p >= 0
                 for p in pcts)
    ordered = pcts == sorted(pcts)
    invs = [
        check("clock_jump_counted_finite",
              d["negatives"] > 0 and finite and ordered,
              f"negatives={d['negatives']} (>= 1 injected jump, "
              f"{neg_expected} negative deltas), percentiles "
              f"{pcts} finite+ordered"),
        check("no_silent_verdict_loss",
              sv["n"] == 63 * 4,
              f"every record accounted: n={sv['n']}"),
    ]
    return _scenario("clock_jump", invs)


# ---------------------------------------------------------------------------
# network scenarios: the multi-host gossip leg (cluster/transport.py)
# ---------------------------------------------------------------------------

#: Host B's epoch predates host A's by this much in every pair below,
#: so EVERY cross-host merge exercises the tx-epoch -> rx-epoch rebase
#: (a zero-delta pair would pass even with the rebase deleted — the
#: epoch_rebase_skipped plant proves the delta has teeth).
NET_EPOCH_DELTA_S = 250.0


class _CountSink:
    """CollectSink plus exact apply accounting: ``no_double_apply``
    needs how many verdicts were APPLIED, not just the last-wins
    map."""

    def __init__(self):
        self.blocked: dict[int, float] = {}
        self.applies = 0
        self.applied_keys = 0

    def apply(self, update) -> None:
        self.applies += 1
        self.applied_keys += len(update.key)
        self.blocked.update(zip(update.key.tolist(),
                                update.until_s.tolist()))


def _net_pair(tmp: Path, name: str, k_max: int = 8,
              resync_s: float = 1000.0, **mbx_kw):
    """Two single-engine loopback 'hosts': REAL GossipPlanes over a
    REAL UDP NetMailbox pair, epochs offset by NET_EPOCH_DELTA_S.
    ``resync_s`` defaults inert so scenarios see exactly the packets
    they inject; the heal/loss scenarios turn it down."""
    from flowsentryx_tpu.cluster import gossip as gplane
    from flowsentryx_tpu.cluster.transport import NetMailbox

    mono = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
    wall = time.time_ns()
    d_ns = int(NET_EPOCH_DELTA_S * 1e9)
    na = NetMailbox(0, 0, mono, wall, k_max=k_max,
                    resync_interval_s=resync_s, **mbx_kw)
    nb = NetMailbox(1, 0, mono - d_ns, wall - d_ns, k_max=k_max,
                    resync_interval_s=resync_s, **mbx_kw)
    na.add_peer((1, 0), nb.addr)
    nb.add_peer((0, 0), na.addr)
    planes = []
    for h, net in ((0, na), (1, nb)):
        d = tmp / f"{name}_h{h}"
        gplane.create_plane(d, 1, k_max=k_max, net=True)
        planes.append(gplane.GossipPlane(
            d, 0, 1, sink=_CountSink(), merge_interval_s=0.0,
            net=net))
    return planes[0], planes[1]


def _local_now(plane) -> float:
    return (time.clock_gettime_ns(time.CLOCK_MONOTONIC)
            - plane.net.t0_ns) * 1e-9


def _nupd(plane, base: int, n: int):
    """One publisher-local update: keys ``base..base+n``, untils 10 s
    out on the PUBLISHER's clock (so the rebased copy is ~10 s out on
    the receiver's)."""
    from flowsentryx_tpu.engine.writeback import BlacklistUpdate

    ln = _local_now(plane)
    return BlacklistUpdate(
        key=(base + np.arange(n)).astype(np.uint32),
        until_s=(ln + 10.0 + 0.25 * np.arange(n)).astype(np.float32))


def _mk_wire(keys, untils, k: int, now: float = 0.0) -> np.ndarray:
    """One raw [2K+4] wire with the device-clock `now` word stamped in
    the SENDER's epoch — a zero `now` from an offset peer is exactly
    the lying-epoch shape the skew bound refuses (net_stale_epoch),
    so honest harness wires must stamp it."""
    wire = np.zeros(2 * k + 4, np.uint32)
    keys = np.asarray(keys, np.uint32)
    untils = np.asarray(untils, np.float32)
    wire[:len(keys)] = keys
    wire[k:k + len(untils)] = untils.view(np.uint32)
    wire[2 * k] = len(keys)
    wire[2 * k + 3] = np.float32(now).view(np.uint32)
    return wire


def _digests(a, b) -> tuple[str, str]:
    from flowsentryx_tpu.cluster.transport import map_digest

    return map_digest(a.net.net_map), map_digest(b.net.net_map)


def _close_pair(a, b) -> None:
    a.net.close()
    b.net.close()


def scenario_net_partition(tmp: Path, rng: np.random.Generator) -> dict:
    """Cut the wire between two converged hosts mid-publish: the
    publisher must stay non-blocking (fail-open — a partitioned peer
    is a mailbox that drops, not a coordinator that stalls), and
    everything delivered BEFORE the cut must stay converged."""
    del rng
    a, b = _net_pair(tmp, "net_part")
    try:
        a.publish(_nupd(a, 1000, 12), now=_local_now(a))
        deadline = time.monotonic() + 5.0
        while (_digests(a, b)[0] != _digests(a, b)[1]
               or not b.net.net_map):
            a.tick(force=True)
            b.tick(force=True)
            if time.monotonic() > deadline:
                break
            time.sleep(0.005)
        pre_a, pre_b = _digests(a, b)
        pre_applied = b.sink.applied_keys
        chaos = faults.NetChaos(a.net)
        chaos.partition()
        t0 = time.perf_counter()
        a.publish(_nupd(a, 2000, 20), now=_local_now(a))
        for _ in range(5):
            a.tick(force=True)
            b.tick(force=True)
        cut_wall = time.perf_counter() - t0
        post_a, post_b = _digests(a, b)
        chaos.uninstall()
        invs = [
            check("net_partition_fail_open",
                  cut_wall < 0.5 and chaos.dropped >= 3
                  and b.sink.applied_keys == pre_applied,
                  f"publish+5 ticks into the cut took "
                  f"{cut_wall * 1e3:.1f} ms, {chaos.dropped} "
                  "datagram(s) eaten, nothing leaked through"),
            check("gossip_delivered_converges",
                  pre_a == pre_b and post_b == pre_b,
                  f"pre-cut digests converged ({pre_b}) and B's view "
                  "is untouched by the cut"),
            check("fail_open_holds",
                  post_a != pre_a and len(a.net.net_map) == 32,
                  "A kept publishing into its own map during the "
                  "cut (serving never waited on the network)"),
        ]
        return _scenario("net_partition", invs,
                         cut_wall_ms=round(cut_wall * 1e3, 2))
    finally:
        _close_pair(a, b)


def scenario_net_heal(tmp: Path, rng: np.random.Generator) -> dict:
    """Publish INTO a partition (every wire lost), then heal: the
    anti-entropy resync must re-converge the canonical digests within
    a bounded number of gossip ticks — no retransmit protocol, no
    operator action."""
    del rng
    a, b = _net_pair(tmp, "net_heal", resync_s=0.05)
    try:
        chaos = faults.NetChaos(a.net)
        chaos.partition()
        a.publish(_nupd(a, 3000, 12), now=_local_now(a))
        for _ in range(3):
            a.tick(force=True)
            b.tick(force=True)
        lost = chaos.dropped
        da, db = _digests(a, b)
        in_cut_ok = da != db and not b.net.net_map
        chaos.heal()
        ticks = None
        for i in range(80):
            a.tick(force=True)
            b.tick(force=True)
            da, db = _digests(a, b)
            if da == db and b.net.net_map:
                ticks = i + 1
                break
            time.sleep(0.01)
        chaos.uninstall()
        invs = [
            check("net_heal_converges",
                  ticks is not None and ticks <= 60
                  and len(b.net.net_map) == 12,
                  f"digests re-converged ({db}) {ticks} tick(s) after "
                  f"heal; {lost} wire(s) had been eaten by the cut"),
            check("net_loss_accounted", lost >= 1 and in_cut_ok,
                  f"{lost} datagram(s) provably lost in the cut, B "
                  "empty until heal"),
        ]
        return _scenario("net_heal", invs, ticks_to_converge=ticks)
    finally:
        _close_pair(a, b)


def scenario_net_reorder(tmp: Path, rng: np.random.Generator) -> dict:
    """Two legs.  (1) Reordered datagrams must deliver in per-peer
    sequence order through the bounded buffer.  (2) Packets injected
    one at a time around a never-filling hole must NEVER grow the
    buffer past its window — the overflow evicts-and-counts instead
    of stalling or growing (bounded reorder memory)."""
    del rng
    from flowsentryx_tpu.cluster import transport
    from flowsentryx_tpu.core import schema as _schema

    window = 4
    a, b = _net_pair(tmp, "net_reorder", reorder_window=window)
    try:
        # leg 1: 8 wires flushed in reversed chunks of 4
        chaos = faults.NetChaos(b.net)
        chaos.reorder(depth=4)
        ln = _local_now(b)
        for j in range(8):
            b.net.queue_tx(
                _mk_wire([5000 + j], [ln + 10.0 + j], 8, now=ln), 1)
            b.net.pump()
        chaos.uninstall()
        time.sleep(0.02)
        a.net.pump()
        got = a.net.pop_wires(64)
        seqs = [seq for _s, seq, *_ in got]
        ordered = seqs == sorted(seqs) and len(seqs) == 8
        leg1_ok = (ordered and a.net.rx_dup == 0
                   and a.net.reorder_evict == 0
                   and chaos.reordered == 8)
        # leg 2: seqs 15..10 one at a time (hole at 9): the buffer
        # must cap at `window`, then concede-and-count
        buf = a.net._rx_state[(1, 0)]["buf"]
        bounded = True
        sock = transport.socket.socket(transport.socket.AF_INET,
                                       transport.socket.SOCK_DGRAM)
        try:
            for s in range(15, 9, -1):
                pkt = transport.pack_packet(
                    _schema.NET_KIND_WIRE, 1, 0, s, 1,
                    b.net.t0_wall_ns,
                    _mk_wire([6000 + s], [ln + 20.0], 8, now=ln))
                sock.sendto(pkt, a.net.addr)
                time.sleep(0.005)
                a.net.pump()
                bounded = bounded and len(buf) <= window
        finally:
            sock.close()
        invs = [
            check("net_reorder_bounded",
                  leg1_ok and bounded and a.net.reorder_evict >= 1
                  and a.net.rx_gap >= 1,
                  f"8 reordered wires delivered as seqs {seqs}; "
                  f"buffer stayed <= {window} under a never-filling "
                  f"hole (evictions={a.net.reorder_evict}, "
                  f"gap={a.net.rx_gap})"),
            check("seq_gap_counted", a.net.rx_gap >= 1,
                  "the conceded hole surfaced in rx_gap, not as "
                  "silence"),
        ]
        return _scenario("net_reorder", invs, delivered_seqs=seqs)
    finally:
        _close_pair(a, b)


def scenario_net_duplicate(tmp: Path,
                           rng: np.random.Generator) -> dict:
    """Every datagram delivered twice: duplicate suppression must
    count and drop the copies — a verdict reaches the sink exactly
    once (the ``dup_suppression_removed`` plant re-runs this path
    with the suppression bypassed and must see this FAIL)."""
    del rng
    a, b = _net_pair(tmp, "net_dup")
    try:
        chaos = faults.NetChaos(b.net)
        chaos.duplicate()
        b.publish(_nupd(b, 7000, 12), now=_local_now(b))
        b.tick(force=True)
        chaos.uninstall()
        time.sleep(0.02)
        a.tick(force=True)
        da, db = _digests(a, b)
        invs = [
            check("no_double_apply",
                  a.sink.applied_keys == 12 and a.net.rx_wires == 2
                  and a.net.rx_dup == 2 and chaos.duplicated == 2,
                  f"2 wires sent twice: {a.net.rx_wires} delivered, "
                  f"{a.net.rx_dup} duplicate(s) suppressed, "
                  f"{a.sink.applied_keys} verdict(s) applied (== 12 "
                  "unique)"),
            check("gossip_delivered_converges", da == db,
                  f"digests byte-identical through the duplication "
                  f"({da})"),
        ]
        return _scenario("net_duplicate", invs)
    finally:
        _close_pair(a, b)


def scenario_net_loss_burst(tmp: Path,
                            rng: np.random.Generator) -> dict:
    """Silently drop a contiguous burst of wires: the holes must be
    conceded and counted (rx_gap) within the reorder timeout so the
    survivors deliver, and the resync must then close the hole."""
    burst_at = int(rng.integers(1, 4))
    # resync stays INERT through the burst (a resync wire sneaking
    # through the chaos seam mid-burst would shift the dropped
    # indices and break the exact counts on a slow host); the heal
    # phase below turns it on explicitly
    a, b = _net_pair(tmp, "net_loss", reorder_timeout_s=0.05)
    try:
        chaos = faults.NetChaos(b.net)
        chaos.drop_burst(burst_at, 3)
        ln = _local_now(b)
        for j in range(8):
            b.net.queue_tx(
                _mk_wire([8000 + j], [ln + 10.0 + j], 8, now=ln), 1)
            b.net.pump()
        time.sleep(0.02)
        a.tick(force=True)
        survivors_early = a.sink.applied_keys
        time.sleep(0.08)   # past the reorder timeout: concede holes
        a.tick(force=True)
        gap = a.net.rx_gap
        delivered = a.net.rx_wires
        conceded_ok = (gap == 3 and delivered == 5
                       and a.net.gap_timeouts >= 1
                       and survivors_early >= 1)
        chaos.uninstall()
        # the resync closes the hole (enabled only now: single-
        # threaded scenario, both fields merge-section-owned)
        for net in (a.net, b.net):
            net.resync_interval_s = 0.15
            net._next_resync = 0.0
        converged = False
        for _ in range(60):
            b.tick(force=True)
            a.tick(force=True)
            da, db = _digests(a, b)
            if da == db and len(a.net.net_map) == 8:
                converged = True
                break
            time.sleep(0.01)
        invs = [
            check("net_loss_accounted", conceded_ok,
                  f"8 sent, burst of 3 eaten at index {burst_at}: "
                  f"{delivered} delivered + {gap} conceded-and-"
                  f"counted == 8 (gap_timeouts="
                  f"{a.net.gap_timeouts})"),
            check("net_heal_converges", converged,
                  "the anti-entropy resync closed the hole "
                  f"(digest {_digests(a, b)[0]}, 8 sources)"),
        ]
        return _scenario("net_loss_burst", invs, burst_index=burst_at)
    finally:
        _close_pair(a, b)


def scenario_net_stale_epoch(tmp: Path,
                             rng: np.random.Generator) -> dict:
    """A peer publishing under a LYING epoch stamp (its pre-reboot
    t0_wall, hours stale): the rebased skew bound must refuse-and-
    count every wire — and still accept a truthfully-stamped wire
    from the same peer (the bound discriminates, not censors)."""
    skew_s = float(3600.0 + 1800.0 * rng.random())
    from flowsentryx_tpu.cluster import transport
    from flowsentryx_tpu.core import schema as _schema

    a, b = _net_pair(tmp, "net_stale")
    try:
        pkts = faults.stale_epoch_packets(
            1, 0, b.net.t0_wall_ns, skew_s,
            keys=[9001, 9002, 9003], untils=[10.0, 11.0, 12.0],
            k_max=8, start_seq=1)
        sock = transport.socket.socket(transport.socket.AF_INET,
                                       transport.socket.SOCK_DGRAM)
        try:
            for p in pkts:
                sock.sendto(p, a.net.addr)
            time.sleep(0.02)
            a.tick(force=True)
            refused = (a.net.epoch_skew_dropped == len(pkts)
                       and a.sink.applied_keys == 0
                       and not a.net.net_map)
            skew_seen = a.net.epoch_skew_max
            # control: a truthful wire from the same peer is accepted
            ln_b = ((time.clock_gettime_ns(time.CLOCK_MONOTONIC)
                     - b.net.t0_ns) * 1e-9)
            wire = _mk_wire([9100], [ln_b + 10.0], 8)
            wire[2 * 8 + 3] = np.float32(ln_b).view(np.uint32)
            sock.sendto(transport.pack_packet(
                _schema.NET_KIND_WIRE, 1, 0, len(pkts) + 1, 1,
                b.net.t0_wall_ns, wire), a.net.addr)
            time.sleep(0.02)
            a.tick(force=True)
        finally:
            sock.close()
        # the liar's wire `now` is its post-reboot clock (~0) while its
        # stamp predates even B's real epoch, so the observed skew is
        # the injected lie PLUS the pair's epoch delta
        skew_expect = skew_s + NET_EPOCH_DELTA_S
        invs = [
            check("stale_epoch_refused",
                  refused and abs(skew_seen - skew_expect) < 60.0,
                  f"{len(pkts)} lying-epoch wire(s) refused-and-"
                  f"counted (epoch_skew_max {skew_seen:.0f}s ~ "
                  f"expected {skew_expect:.0f}s), none applied"),
            check("epoch_rebase_exact",
                  a.sink.applied_keys == 1
                  and abs((a.sink.blocked[9100]
                           + a.net.t0_wall_ns * 1e-9)
                          - (10.0 + ln_b
                             + b.net.t0_wall_ns * 1e-9)) < 0.01,
                  "the truthful control wire was accepted and its "
                  "ABSOLUTE expiry survived the rebase"),
        ]
        return _scenario("net_stale_epoch", invs,
                         injected_skew_s=round(skew_s, 1))
    finally:
        _close_pair(a, b)


# ---------------------------------------------------------------------------
# elastic-fleet scenarios: live shard handoff under the worst interruptions
# (ISSUE 16; cluster/rebalance.py)
# ---------------------------------------------------------------------------

def _handoff_rows(rng: np.random.Generator, n: int):
    """``n`` occupied table rows: unique nonzero u32 keys + a full
    f32 state matrix (schema.NUM_TABLE_COLS columns)."""
    from flowsentryx_tpu.core import schema

    keys = rng.choice(np.arange(1, 1 << 20, dtype=np.uint32), n,
                      replace=False).astype(np.uint32)
    states = rng.random((n, schema.NUM_TABLE_COLS)).astype(np.float32)
    return keys, states


def scenario_handoff_kill_midship(tmp: Path,
                                  rng: np.random.Generator) -> dict:
    """SIGKILL a REAL donor process mid-stream: a child ships 1000
    rows over a real shm handoff mailbox (one slot every ~30 ms); the
    parent kills it at a seed-chosen point in the stream.  The
    recipient must refuse the unsealed stream — no STAGED ack, zero
    rows inserted — and the donor's copy must still account every row
    exactly (it never stopped owning the span).  This is the worst
    interruption point of the handoff state machine: rows in flight,
    nothing committed."""
    import os
    import signal
    import subprocess
    import sys

    import flowsentryx_tpu
    from flowsentryx_tpu.cluster import rebalance as rb

    keys, states = _handoff_rows(rng, 1000)
    rows_npz = tmp / "midship_rows.npz"
    np.savez(rows_npz, keys=keys, states=states)
    mbx_path = str(tmp / "midship.mbx")
    # 64-row slots -> a 1000-row stream is ~16 slots: wide enough to
    # kill inside, small enough to stay fast
    mbx = rb.HandoffMailbox.create(mbx_path, slots=64, rows_per_slot=64)
    kill_after = int(rng.integers(2, 6))
    child_src = (
        "import sys, time\n"
        "import numpy as np\n"
        "from flowsentryx_tpu.cluster import rebalance as rb\n"
        "d = np.load(sys.argv[1])\n"
        "mbx = rb.HandoffMailbox(sys.argv[2])\n"
        "rb.ship_rows(mbx, d['keys'], d['states'],\n"
        "             on_slot=lambda i, n: time.sleep(0.03))\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(Path(flowsentryx_tpu.__file__).parent.parent)
    child = subprocess.Popen(
        [sys.executable, "-c", child_src, str(rows_npz), mbx_path],
        env=env)
    deadline = time.monotonic() + 30.0
    while mbx.readable() < kill_after:
        if child.poll() is not None or time.monotonic() > deadline:
            break
        time.sleep(0.005)
    child.send_signal(signal.SIGKILL)
    rc = child.wait()
    shipped = mbx.readable()
    # the recipient drains whatever arrived, then the stream goes
    # quiet forever — exactly what an unsealed stream looks like
    recv = rb.HandoffReceiver()
    for _ in range(10):
        recv.drain(mbx)
        time.sleep(0.01)
    got_keys, _got_states = recv.rows()
    # conservation: the donor died pre-flip, so its copy IS the
    # post-state; the recipient inserted nothing
    conserved = rb.rows_conserved((keys, states), [(keys, states)])
    invs = [
        check("handoff_rows_conserved",
              conserved["ok"] and not recv.done and not recv.ok,
              f"donor killed (rc={rc}) after {shipped} slot(s): "
              f"stream never sealed (done={recv.done}), the "
              f"{len(got_keys)} staged row(s) may never be inserted, "
              f"donor copy accounts {conserved['pre_rows']} == "
              f"{conserved['post_rows']} rows"),
        check("fail_open_holds",
              rc == -signal.SIGKILL and 0 < shipped < 17,
              f"the kill landed mid-stream: {shipped} of ~17 slots "
              "shipped, then silence — no crash leaked to the "
              "recipient side"),
    ]
    return _scenario("handoff_kill_midship", invs,
                     kill_after_slots=kill_after,
                     shipped_slots=int(shipped))


def scenario_layout_flip_lost(tmp: Path,
                              rng: np.random.Generator) -> dict:
    """Commit a REAL handoff through the REAL coordinator, then lose
    the layout-flip 'message' to one bystander rank (it never acks the
    new generation).  The fence must NOT lift — ticked repeatedly, the
    coordinator must keep waiting — until the late ack arrives, and
    then lift completely.  The engines are played by the harness (the
    gossip-scenario idiom): their acks are plain status-block writes,
    so the timing is fully scripted."""
    del rng
    from flowsentryx_tpu.cluster import rebalance as rb
    from flowsentryx_tpu.cluster.mailbox import StatusBlock, status_path
    from flowsentryx_tpu.cluster.runner import stub_engine_main
    from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor
    from flowsentryx_tpu.core import schema as _schema

    sup = ClusterSupervisor(
        tmp / "flip_cl",
        [{"stub_serve_s": 30.0, "workers": 1},
         {"stub_serve_s": 30.0, "workers": 1},
         {"stub_serve_s": 30.0, "workers": 1}],
        entry=stub_engine_main)
    sup.boot()
    try:
        st = [StatusBlock(status_path(tmp / "flip_cl", r))
              for r in range(3)]
        hid = sup.start_handoff([2], donor=2, recipient=0)
        to_gen = sup._handoff["to_gen"]
        fenced = (st[2].ctl_get("c_fence") == hid
                  and st[0].ctl_get("c_fence") == hid)
        # the harness plays both parties: donor shipped, recipient
        # staged — the coordinator may now commit
        st[2].ctl_set("c_handoff", hid * 8 + _schema.HP_SHIPPED)
        st[0].ctl_set("c_handoff", hid * 8 + _schema.HP_STAGED)
        sup.poll()
        committed = (sup._handoff is not None
                     and sup._handoff["phase"] == "committing"
                     and rb.ShardAssignment.load(
                         tmp / "flip_cl").generation == to_gen)
        # ranks 0 and 2 converge; rank 1's flip message is 'lost'
        st[0].ctl_set("c_layout_ack", to_gen)
        st[2].ctl_set("c_layout_ack", to_gen)
        held = True
        for _ in range(8):
            sup.poll()
            held = (held and sup._handoff is not None
                    and st[0].ctl_get("c_fence") == hid)
            time.sleep(0.01)
        # the late ack (the respawn-reconcile path in a real fleet)
        st[1].ctl_set("c_layout_ack", to_gen)
        sup.poll()
        lifted = (sup._handoff is None
                  and all(s.ctl_get("c_fence") == 0 for s in st)
                  and sup.rebalance_counters["flips"] == 1
                  and not rb.handoff_json_path(tmp / "flip_cl").exists())
        owners = rb.ShardAssignment.load(tmp / "flip_cl").owners
        invs = [
            check("layout_flip_converges",
                  fenced and committed and held and lifted,
                  f"fence {hid} stamped on both parties, commit wrote "
                  f"generation {to_gen}, the fence HELD through 8 "
                  "ticks with rank 1's ack missing, and lifted "
                  "completely on the late ack"),
            check("counters_conserved",
                  owners[2] == 0 and list(owners[:2]) == [0, 1],
                  f"shard 2 reassigned to rank 0 exactly once "
                  f"(owners={list(owners)})"),
        ]
        return _scenario("layout_flip_lost", invs, to_gen=to_gen)
    finally:
        sup.close()


def scenario_adopt_half_dead(tmp: Path,
                             rng: np.random.Generator) -> dict:
    """Supervisor A boots a 2-rank stub fleet, rank 1 is SIGKILLed,
    and A 'dies' (simply stops supervising).  A replacement supervisor
    B boots with ``adopt=True``: its census must classify rank 0 as
    live (adopt untouched — NEVER a second consumer for a span a live
    rank still drains), rank 1 as dead (respawn gen+1 from its
    checkpoint), and then run the fleet to completion with every
    generation accounted once."""
    del rng
    from flowsentryx_tpu.cluster.mailbox import StatusBlock, status_path
    from flowsentryx_tpu.cluster.runner import stub_engine_main
    from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor
    from flowsentryx_tpu.core import schema as _schema

    d = tmp / "adopt_cl"
    ck = tmp / "adopt_ck_r1.npz"
    ck.write_bytes(b"stub flow memory")
    # long serve: the replacement supervisor's stop-drain ends the
    # scenario, not the clock — rank 0 must still be mid-serve at
    # census time
    specs = [{"stub_serve_s": 60.0, "workers": 1},
             {"stub_serve_s": 60.0, "checkpoint": str(ck), "workers": 1}]
    sup_a = ClusterSupervisor(d, specs, entry=stub_engine_main)
    sup_a.boot()
    st = [StatusBlock(status_path(d, r)) for r in range(2)]
    deadline = time.monotonic() + 60.0
    # the adopt census judges liveness by pid + HEARTBEAT: wait for
    # the first tick's c_hbeat stamp, not just SERVING
    while not all(s.ctl_get("c_state") == _schema.CSTATE_SERVING
                  and s.ctl_get("c_pid") and s.ctl_get("c_hbeat")
                  for s in st):
        if time.monotonic() > deadline:
            raise TimeoutError("stub fleet never reached SERVING")
        time.sleep(0.01)
    faults.kill_process_group(sup_a._procs[1].pid)
    sup_a._procs[1].join(timeout=10.0)  # reap: the pid must truly die
    # supervisor A is now 'dead': it never polls again
    sup_b = ClusterSupervisor(d, specs, entry=stub_engine_main)
    sup_b.boot(adopt=True)
    census_ok = (sup_b._adopted == {0} and sup_b.restarts[0] == 0
                 and sup_b.restarts[1] == 1 and sup_b._gen[1] == 1)
    try:
        agg = sup_b.run(max_seconds=1.0)  # stop-drain the fleet
    finally:
        sup_b.close()
        sup_a.close()
    r1 = [r for r in agg["reports"] if r["rank"] == 1]
    invs = [
        check("adopt_no_second_consumer",
              census_ok and agg.get("adopted_ranks") == [],
              f"census adopted rank 0 untouched, respawned only the "
              f"dead rank (restarts={sup_b.restarts}); the adopted "
              "rank drained to DONE and left the live-adopted set"),
        check("recovery_within_bound",
              bool(r1) and r1[0]["gen"] == 1
              and r1[0].get("restored") == str(ck),
              f"rank 1 re-served as gen 1 restored from its "
              f"checkpoint ({r1[0].get('restored') if r1 else None})"),
        check("counters_conserved",
              agg["failed_ranks"] == []
              and len({(r['rank'], r['gen'])
                       for r in agg["reports"]})
              == len(agg["reports"]),
              f"restarts={agg['restarts']}, latest-gen dedup held"),
        check("fail_open_holds",
              st[0].ctl_get("c_gen") == 0,
              "rank 0 served start to finish as generation 0 — "
              "adoption never touched it"),
    ]
    return _scenario("adopt_half_dead", invs)


# ---------------------------------------------------------------------------
# planted regressions (negative controls: the invariant must FAIL)
# ---------------------------------------------------------------------------

def plant_split_atomicity() -> dict:
    """Re-introduce the split-complete weakness the SinkChannel's
    atomic ``complete()`` exists to prevent: decrement pending and
    record the crash under SEPARATE lock acquisitions.  A waiter
    observing between them sees (pending drained, crash unset) — the
    silent-verdict-loss window.  ``sink_crash_atomicity`` must FAIL
    under the plant and HOLD for the real protocol."""
    from flowsentryx_tpu.sync.channel import SinkChannel

    # plant: the split sequence, observed at its midpoint
    chan = SinkChannel("sink thread")
    chan.submit("group", 1)
    with chan.cv:
        chan._pending -= 1
        chan.cv.notify_all()
    with chan.cv:  # a woken backpressure waiter's view, mid-split
        planted_bad = (chan._pending == 0 and chan._exc is None)
    with chan.cv:
        chan._exc = RuntimeError("worker crashed")
        chan.cv.notify_all()
    planted = check(
        "sink_crash_atomicity", not planted_bad,
        "under the split plant a waiter observed (pending drained, "
        "crash unset)")
    # control: the real atomic complete() on the same protocol object
    chan2 = SinkChannel("sink thread")
    chan2.submit("group", 1)
    chan2.complete(1, 0.0, RuntimeError("worker crashed"))
    with chan2.cv:
        control_ok = not (chan2._pending == 0 and chan2._exc is None)
    return {
        "plant": "split_atomicity",
        "reintroduces": "pre-PR9 split crash accounting "
                        "(SinkChannel.complete's atomicity removed)",
        "caught_by": "sink_crash_atomicity",
        "caught": not planted.ok,
        "control_holds": bool(control_ok),
        "ok": (not planted.ok) and bool(control_ok),
    }


def plant_crc_skipped(tmp: Path, rng: np.random.Generator) -> dict:
    """Strip the integrity member and flip a value — the pre-PR-13
    CRC-less format.  The file is a perfectly valid zip, so the
    structural checks pass and ``corrupt_ckpt_refused`` FAILS: exactly
    the silent load the CRC exists to prevent (grandfathered legacy
    snapshots accept this by documented choice; new writes always
    carry the CRC)."""
    from flowsentryx_tpu.engine import checkpoint as ckpt

    p = _tiny_snapshot(tmp, "snap_plant_crc")
    with np.load(p) as z:
        data = {k: np.array(z[k]) for k in z.files
                if k != "integrity_crc32"}
    data["table_key"] = data["table_key"].copy()
    data["table_key"][int(rng.integers(0, 256))] ^= 1
    np.savez_compressed(p, **data)
    refused = False
    try:
        ckpt.load_checkpoint(p)
    except ckpt.CheckpointCorrupt:
        refused = True
    return {
        "plant": "crc_skipped",
        "reintroduces": "CRC-less checkpoint loads (the corrupt file "
                        "decompresses cleanly and loads silently)",
        "caught_by": "corrupt_ckpt_refused",
        "caught": not refused,
        "ok": not refused,
    }


def plant_dup_suppression_removed(tmp: Path,
                                  rng: np.random.Generator) -> dict:
    """Re-introduce the pre-discipline transport: every received
    datagram delivered straight to the sink, no sequence suppression
    (``NetMailbox._accept`` called per COPY — exactly what the rx path
    is with the ``_rx_wire`` machinery deleted).  ``no_double_apply``
    must FAIL under the plant and HOLD for the real path on the same
    duplicated traffic."""
    del rng
    a, b = _net_pair(tmp, "plant_dup")
    try:
        ln_b = _local_now(b)
        wire = _mk_wire([9901, 9902], [ln_b + 10.0, ln_b + 11.0], 8)
        wire[2 * 8 + 3] = np.float32(ln_b).view(np.uint32)
        # control: the same duplicate through the REAL rx path
        a.net._rx_wire((1, 0), 1, 2, b.net.t0_wall_ns, wire.copy())
        a.net._rx_wire((1, 0), 1, 2, b.net.t0_wall_ns, wire.copy())
        control_applied = sum(
            len(keys) for _s, _q, _w, keys, _u in a.net.pop_wires(16))
        control_ok = control_applied == 2 and a.net.rx_dup == 1
        # plant: suppression removed — each copy delivered
        a.net._accept((1, 0), 7, 2, b.net.t0_wall_ns, wire.copy())
        a.net._accept((1, 0), 7, 2, b.net.t0_wall_ns, wire.copy())
        planted_applied = sum(
            len(keys) for _s, _q, _w, keys, _u in a.net.pop_wires(16))
        caught = planted_applied > 2  # the double apply happened
        return {
            "plant": "dup_suppression_removed",
            "reintroduces": "raw datagram delivery with the per-peer "
                            "u64-seq duplicate suppression deleted "
                            "(a resent/reflected wire re-applies)",
            "caught_by": "no_double_apply",
            "caught": caught,
            "control_holds": bool(control_ok),
            "ok": caught and bool(control_ok),
            "detail": f"planted path applied {planted_applied} "
                      f"verdicts for 2 unique; real path applied "
                      f"{control_applied} with rx_dup=1",
        }
    finally:
        _close_pair(a, b)


def plant_epoch_rebase_skipped(tmp: Path,
                               rng: np.random.Generator) -> dict:
    """Re-introduce the single-host assumption across hosts: merge a
    peer's untils RAW, as if both monotonic epochs were one (the
    rebase deleted).  With the pair's NET_EPOCH_DELTA_S offset the
    planted verdict's ABSOLUTE expiry is off by exactly that delta —
    ``epoch_rebase_exact`` must FAIL; the real ``_accept`` path holds
    within f32 quantization on the same wire."""
    del rng
    a, b = _net_pair(tmp, "plant_epoch")
    try:
        ln_b = _local_now(b)
        until_b = ln_b + 10.0
        wire = _mk_wire([9950], [until_b], 8)
        wire[2 * 8 + 3] = np.float32(ln_b).view(np.uint32)
        abs_true = until_b + b.net.t0_wall_ns * 1e-9

        def abs_err(until_on_a: float) -> float:
            return abs((until_on_a + a.net.t0_wall_ns * 1e-9)
                       - abs_true)

        # control: the real rebase path
        a.net._rx_wire((1, 0), 1, 1, b.net.t0_wall_ns, wire.copy())
        [(_, _, _, _, untils)] = a.net.pop_wires(4)
        control_err = abs_err(float(untils[0]))
        # plant: rebase skipped — the raw f32 until read in A's epoch
        planted_err = abs_err(
            float(wire[8:9].view(np.float32)[0]))
        caught = planted_err > 1.0
        return {
            "plant": "epoch_rebase_skipped",
            "reintroduces": "cross-host merge without the tx-epoch -> "
                            "rx-epoch rebase (the single-host "
                            "byte-identical-untils assumption applied "
                            "across hosts)",
            "caught_by": "epoch_rebase_exact",
            "caught": caught,
            "control_holds": bool(control_err < 0.01),
            "ok": caught and control_err < 0.01,
            "detail": f"planted absolute-expiry error "
                      f"{planted_err:.1f}s (~ the "
                      f"{NET_EPOCH_DELTA_S:.0f}s epoch delta); real "
                      f"rebase error {control_err * 1e3:.2f} ms",
        }
    finally:
        _close_pair(a, b)


def plant_backoff_removed(tmp: Path, rng: np.random.Generator) -> dict:
    """Disable the sliding window (every death sees an empty window,
    so the rank ALWAYS respawns): the crash-loop scenario's
    ``crash_loop_parks`` invariant must FAIL — the rank burns past its
    budget instead of parking."""
    res = scenario_crash_loop(tmp / "plant_backoff", rng,
                              window_s=0.0, backoff_s=0.02,
                              max_restarts=2, name="plant_backoff")
    parks = next(i for i in res["invariants"]
                 if i["name"] == "crash_loop_parks")
    return {
        "plant": "backoff_removed",
        "reintroduces": "pre-PR-13 unbounded respawn (no sliding-"
                        "window budget: a crash-looping rank never "
                        "parks)",
        "caught_by": "crash_loop_parks",
        "caught": not parks["ok"],
        "ok": not parks["ok"],
        "detail": parks["detail"],
    }


def plant_conservation_removed(tmp: Path,
                               rng: np.random.Generator) -> dict:
    """Delete the handoff stream verification: stage whatever arrived
    without checking the SEAL (the recipient's ``ok`` gate removed).
    A single flipped payload word in flight then inserts a row the
    donor never owned — ``handoff_rows_conserved`` must FAIL on the
    staged rows; the real gate (``HandoffReceiver.ok``) catches the
    same tamper via the stream CRC on the same mailbox."""
    from flowsentryx_tpu.cluster import rebalance as rb
    from flowsentryx_tpu.core import schema as _schema

    keys, states = _handoff_rows(rng, 256)
    mbx = rb.HandoffMailbox.create(tmp / "plant_conserve.mbx",
                                   slots=16, rows_per_slot=64)
    rb.ship_rows(mbx, keys, states)
    # one bit flips in flight: a payload word of a published,
    # undrained ROWS cell
    word = int(rng.integers(0, 64 * rb.ROW_WORDS))
    mbx._cells[0][_schema.HANDOFF_SLOT_HDR_WORDS + word] ^= 1
    recv = rb.HandoffReceiver()
    while not recv.done:
        recv.drain(mbx)
    control_ok = recv.done and not recv.ok and "CRC" in recv.detail
    # plant: the ok gate removed — the staged rows insert anyway
    conserved = rb.rows_conserved((keys, states), [recv.rows()])
    caught = not conserved["ok"]
    return {
        "plant": "conservation_removed",
        "reintroduces": "handoff staging without the SEAL "
                        "count+CRC verification (a corrupted "
                        "in-flight row inserts silently)",
        "caught_by": "handoff_rows_conserved",
        "caught": caught,
        "control_holds": bool(control_ok),
        "ok": caught and bool(control_ok),
        "detail": f"payload word {word} flipped: real receiver "
                  f"refused ({recv.detail}); unguarded staging "
                  f"broke conservation ({conserved['detail']})",
    }


# ---------------------------------------------------------------------------
# the campaign
# ---------------------------------------------------------------------------

def run_campaign(seed: int = 17, quick: bool = False,
                 workdir: str | Path | None = None,
                 out: str | Path | None = None) -> dict:
    """Run every scenario + every planted regression; return (and
    optionally write) the artifact.  ``quick`` trims the traffic
    volume, not the coverage — every fault class and every plant runs
    either way (the tier-1 smoke IS the quick campaign)."""
    import tempfile

    rng = np.random.default_rng(seed)
    tmp = Path(workdir) if workdir else Path(
        tempfile.mkdtemp(prefix="fsx_chaos_"))
    tmp.mkdir(parents=True, exist_ok=True)
    t_start = time.perf_counter()
    results: list[dict] = []

    # jax-free scenarios first (they also serve as a fast smoke of the
    # campaign plumbing itself)
    results.append(scenario_ckpt_truncate(tmp, rng))
    results.append(scenario_ckpt_bitflip(tmp, rng))
    results.append(scenario_engine_kill(tmp, rng))
    results.append(scenario_crash_loop(tmp, rng))
    results.append(scenario_gossip_stall_flood(tmp, rng))
    results.append(scenario_clock_jump(rng))

    # the multi-host network leg (ISSUE 15): loopback UDP pairs of
    # REAL GossipPlane+NetMailbox stacks with epochs 250 s apart —
    # partition, heal, reorder, duplication, loss, lying epochs
    results.append(scenario_net_partition(tmp, rng))
    results.append(scenario_net_heal(tmp, rng))
    results.append(scenario_net_reorder(tmp, rng))
    results.append(scenario_net_duplicate(tmp, rng))
    results.append(scenario_net_loss_burst(tmp, rng))
    results.append(scenario_net_stale_epoch(tmp, rng))

    # the elastic fleet: handoff/flip/adopt under interruption
    results.append(scenario_handoff_kill_midship(tmp, rng))
    results.append(scenario_layout_flip_lost(tmp, rng))
    results.append(scenario_adopt_half_dead(tmp, rng))

    # the real engine + fleet (one compile, three scenarios)
    n_records = 64 * (6 if quick else 24)
    eng, src, sink, recs = build_engine_fleet(tmp, rng, n_records)
    try:
        results.append(scenario_slot_corruption(eng, src, recs, rng,
                                                tmp))
        results.append(scenario_ckpt_fallback(eng, tmp, rng))
        results.append(scenario_watchdog(eng, rng))
    finally:
        src.close()

    planted = [
        plant_split_atomicity(),
        plant_crc_skipped(tmp, rng),
        plant_backoff_removed(tmp, rng),
        plant_dup_suppression_removed(tmp, rng),
        plant_epoch_rebase_skipped(tmp, rng),
        plant_conservation_removed(tmp, rng),
    ]

    fault_classes = sorted({r["fault_class"] for r in results})
    n_inv = sum(len(r["invariants"]) for r in results)
    ok = (all(r["ok"] for r in results)
          and all(p["ok"] for p in planted))
    artifact = {
        "seed": seed,
        "quick": bool(quick),
        "ok": ok,
        "wall_s": round(time.perf_counter() - t_start, 2),
        "fault_classes": fault_classes,
        "n_fault_classes": len(fault_classes),
        "invariants_checked": n_inv,
        "faults": results,
        "planted_regressions": planted,
        "registry": {k: {"class": c, "description": d}
                     for k, (c, d) in faults.FAULTS.items()},
    }
    if out:
        Path(out).parent.mkdir(parents=True, exist_ok=True)
        Path(out).write_text(json.dumps(artifact, indent=2) + "\n")
    return artifact
