"""``fsx live`` scenarios: the real protocols under the liveness
explorer.

Five protocol scenarios (each a ``mk()`` builder over REAL objects —
``SinkChannel``, the supervisor's fenced handoff over the crash
harness's sim plane, ``ElasticPolicy``, ``GossipPlane``) are proved
deadlock-free, livelock-free under weak fairness, and
bounded-starvation by :func:`flowsentryx_tpu.sync.interleave
.explore_live`; four planted regressions (the PR's negative controls)
each print the schedule that catches them, with the clean run of the
same scenario re-proved as the control.

Checker design notes (the traps that shaped it):

* **No obligations on the handoff scenario.**  Obligation clocks
  count steps along EVERY path, including the weakly-unfair spin
  paths a ``while not converged()`` loop necessarily has, so a
  starvation bound there would flag schedules the fairness assumption
  excludes.  Deadlock and fair-cycle livelock are the sound detectors
  for spin-loop protocols; obligations are used only where the
  threads are finite scripts.
* **Plant bounds freeze at import.**  ``streak_cap_removed`` patches
  ``tuning.SHED_MAX_DEFER`` at runtime (gossip reads the attribute at
  call time, so the patch changes the RUNTIME cap); the obligation
  bound below is a module constant computed at import, so the checker
  still holds the regression to the declared bound — exactly how a
  real regression is caught.
* **Model clock in the fingerprint is capped** (``min(clk,
  DEADLINE+1)``) and only advances while a handoff is in flight, so
  timeout paths stay explorable without the clock unboundedly
  splitting states.
"""

from __future__ import annotations

import contextlib
import tempfile
import time

import numpy as np

from flowsentryx_tpu.live import registry
from flowsentryx_tpu.sync import tuning
from flowsentryx_tpu.sync.channel import SinkChannel
from flowsentryx_tpu.sync.interleave import (
    CvWait, InstrumentedCv, LiveCheckResult, LiveSpec, ModelViolation,
    Obligation, explore_live,
)

SCHEMA = "fsx-live-report-v1"

# Bounds FROZEN at import time (see module docstring): the
# streak-cap plant patches the tuning attribute the runtime reads,
# not these.
_SHED_BOUND = tuning.SHED_MAX_DEFER + 2
_SHED_ITERS = tuning.SHED_MAX_DEFER + 4
#: Model-clock handoff deadline (ticks, not seconds): long enough for
#: a full ship+commit+ack round, short enough that the abort path is
#: explored too.
_H_DEADLINE = 6


# ---------------------------------------------------------------------------
# scenario 1: SinkChannel submit → backpressure → stop → drain
# ---------------------------------------------------------------------------

def _mk_channel_live(n_items: int = 2):
    """Dispatch submits, parks on ``wait_below(0)``, then requests
    stop; the worker pops and completes.  Proves the channel's wake
    graph is closed: every park has a live notify edge."""

    def mk():
        chan = SinkChannel("sink thread")
        chan.cv = InstrumentedCv()
        st = {"completes": 0}

        def dispatch():
            for i in range(n_items):
                yield f"submit#{i}"
                chan.submit(i, 1)
            yield CvWait(
                lambda: chan._pending <= 0 or chan._exc is not None,
                "wait_below(0)", chan.cv,
                source="complete() notify_all")
            chan.wait_below(0)
            yield "request_stop"
            chan.request_stop()

        def worker():
            while True:
                yield CvWait(
                    lambda: bool(chan._q) or chan._stop,
                    "pop", chan.cv,
                    source="submit()/request_stop() notify_all")
                got = chan.pop()
                if got is None:
                    return
                yield "complete"
                chan.complete(len(got), 0.0, None)
                st["completes"] += len(got)

        def finale():
            if st["completes"] != n_items or not chan.drained():
                raise ModelViolation(
                    f"drain broken: {st['completes']}/{n_items} "
                    f"completed, drained={chan.drained()}")

        spec = LiveSpec(
            fingerprint=lambda: (chan._pending, tuple(chan._q),
                                 chan._stop, chan._exc is not None,
                                 st["completes"]),
            progress=lambda: (st["completes"],),
            obligations=[Obligation(
                "drain", lambda: chan._pending > 0,
                lambda: st["completes"], 8)],
            finale=finale)
        return [("dispatch", dispatch()), ("worker", worker())], spec

    return mk


def _check_channel(*, expect_violation=False, expect_marker=None,
                   check="channel_stop_drain_live") -> LiveCheckResult:
    return explore_live(check, _mk_channel_live(),
                        expect_violation=expect_violation,
                        expect_marker=expect_marker)


# ---------------------------------------------------------------------------
# scenario 2: fenced handoff with a dropped stamp at every edge
# ---------------------------------------------------------------------------

class _DropStatus:
    """Status proxy that swallows the FIRST ctl write matching the
    drop spec — the model's 'lost message' (torn write, respawn racing
    the stamp).  Everything else delegates."""

    def __init__(self, inner, drop, counter):
        self._inner = inner
        self._drop = drop
        self._counter = counter

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def ctl_get(self, name):
        return self._inner.ctl_get(name)

    def ctl_set(self, name, value):
        key, rank, match = self._drop
        if (self._counter["left"] > 0 and name == key
                and self._inner.rank == rank
                and (match is None
                     or (match == "nonzero" and value)
                     or (match == "zero" and not value))):
            self._counter["left"] -= 1
            return  # dropped on the floor
        self._inner.ctl_set(name, value)


#: (edge name, drop spec) — drop spec is (ctl key, rank, value match).
#: One ``explore_live`` run per edge; a dropped stamp must RECOVER
#: (abort pre-commit, re-delivery post-commit), never deadlock.
_DROP_EDGES = [
    ("clean", None),
    ("fence_set@donor", ("c_fence", 0, "nonzero")),
    ("fence_set@recipient", ("c_fence", 1, "nonzero")),
    ("fence_lift@donor", ("c_fence", 0, "zero")),
    ("fence_lift@recipient", ("c_fence", 1, "zero")),
    ("layout_gen@donor", ("c_layout_gen", 0, None)),
    ("layout_gen@recipient", ("c_layout_gen", 1, None)),
]
_QUICK_EDGES = ("clean", "fence_lift@donor", "layout_gen@recipient")


def _mk_handoff(drop=None, holder=None):
    """Donor rank0 ships shard 1 to recipient rank1 over the crash
    harness's sim plane (same setup as ``fsx crash``'s handoff
    scenario, smaller rows); the supervisor tick, donor step and
    recipient step interleave freely under a capped model clock."""
    from flowsentryx_tpu.cluster import rebalance as rb
    from flowsentryx_tpu.crash.checker import _keys_for_shard, _states_for
    from flowsentryx_tpu.crash.world import (
        MiniEngine, SimSupervisor, World, ckpt_path,
    )

    if holder is None:
        holder = {"ctx": None}

    def mk():
        if holder["ctx"] is not None:
            holder["ctx"].close()
        w = World(n=2, w=2)
        holder["ctx"] = w.installed()
        rb.ShardAssignment.initial(w.n * w.w, w.w, w.n).save(w.dir)
        d_keys = np.concatenate([_keys_for_shard(0, 4, 1),
                                 _keys_for_shard(1, 4, 2)])
        r_keys = _keys_for_shard(2, 4, 1)
        expect_keys = sorted(int(k) for k in
                             np.concatenate([d_keys, r_keys]))
        for r, keys in ((0, d_keys), (1, r_keys)):
            eng = MiniEngine()
            eng.adopt_rows(keys, _states_for(keys))
            w.engines[r] = eng
            eng.save(ckpt_path(w.dir, r), 1)
            rz = rb.EngineRebalancer(w.dir, r, w.statuses[r])
            rz.reconcile(eng)
            w.rebalancers[r] = rz
        sup = SimSupervisor(w)
        counter = {"left": 1}
        if drop is not None:
            sup._status = [_DropStatus(st, drop, counter)
                           for st in w.statuses]
        clk = {"t": 0}
        run = {"started": False}

        def converged():
            return (run["started"] and sup._handoff is None
                    and all(w.statuses[r].ctl_get("c_fence") == 0
                            for r in range(2)))

        def sup_thread():
            yield "start_handoff"
            sup.start_handoff([1], 0, 1)
            sup._handoff["deadline"] = _H_DEADLINE
            run["started"] = True
            while not converged():
                yield "handoff_tick"
                if sup._handoff is not None and clk["t"] <= _H_DEADLINE:
                    clk["t"] += 1
                sup._handoff_tick(clk["t"])

        def rank_thread(r):
            def gen():
                while not converged():
                    yield "rebalance_step"
                    w.rebalancers[r].step(w.engines[r])
            return gen()

        def finale():
            got = sorted(int(k)
                         for r in range(2)
                         for k in w.engines[r].rows()[0])
            if got != expect_keys:
                raise ModelViolation(
                    f"row conservation broken: engines hold {got}, "
                    f"expected {expect_keys}")

        def fingerprint():
            h = sup._handoff
            rz_state = []
            for r in range(2):
                rz = w.rebalancers[r]
                rx = rz._receiver
                rz_state.append((
                    rz._acked_gen, rz._fence_seen, rz._mbx_hid,
                    rz._staged is not None,
                    None if rx is None
                    else (rx._next_seq, rx.done, rx.ok,
                          len(rx._chunks))))
            return (
                None if h is None else (h["phase"], h["n_rows"]),
                tuple(tuple(sorted(w.statuses[r].ctl.items()))
                      for r in range(2)),
                tuple(tuple(sorted(int(k)
                                   for k in w.engines[r].rows()[0]))
                      for r in range(2)),
                tuple(sorted((name, len(box._q))
                             for name, box in w.hub.boxes.items())),
                tuple(sorted((name, len(w.fs.files[fid].data))
                             for name, fid in w.fs.ns.items())),
                tuple(rz_state),
                min(clk["t"], _H_DEADLINE + 1),
                counter["left"] if drop is not None else 0,
            )

        spec = LiveSpec(
            fingerprint=fingerprint,
            progress=lambda: (sup.rebalance_counters["flips"],
                              sup.rebalance_counters["aborts"],
                              sup.rebalance_counters["fences"]),
            # NO obligations: spin-loop protocol — starvation clocks
            # would count weakly-unfair paths (module docstring)
            finale=finale)
        return [("supervisor", sup_thread()),
                ("rank0", rank_thread(0)),
                ("rank1", rank_thread(1))], spec

    return mk


def _check_handoff(edge_name, drop, *, expect_violation=False,
                   expect_marker=None) -> LiveCheckResult:
    holder = {"ctx": None}
    try:
        return explore_live(
            f"handoff_drop[{edge_name}]",
            _mk_handoff(drop, holder),
            expect_violation=expect_violation,
            expect_marker=expect_marker)
    finally:
        if holder["ctx"] is not None:
            holder["ctx"].close()
            holder["ctx"] = None


# ---------------------------------------------------------------------------
# scenario 3: autoscale hysteresis + cooldown is flap-free
# ---------------------------------------------------------------------------

def _mk_autoscale(cooldown_s: float | None = None):
    """A surge→lull regime flip races a scaler ticking the REAL
    ``ElasticPolicy``.  The flap invariant: no SHRINK may execute
    within the cooldown window after a GROW, under ANY interleaving of
    the flip against the ticks."""
    from flowsentryx_tpu.cluster.elastic import GROW, SHRINK, ElasticPolicy

    SURGE = {"backlog_per_engine": 20000.0, "backlog_max": 20000.0}
    LULL = {"backlog_per_engine": 4.0, "backlog_max": 4.0}
    N_TICKS = 12

    def mk():
        kw = {} if cooldown_s is None else {"cooldown_s": cooldown_s}
        pol = ElasticPolicy(min_engines=1, max_engines=4, **kw)
        st = {"regime": SURGE, "flips": 0, "t": 0.0, "n_live": 2,
              "ticks": 0, "execs": 0, "last_grow": None}

        def env():
            yield "lull"
            st["regime"] = LULL
            st["flips"] += 1

        def scaler():
            for _ in range(N_TICKS):
                yield "tick"
                st["t"] += tuning.ELASTIC_TICK_S
                now = st["t"]
                plan = pol.decide(st["regime"], st["n_live"], now)
                if plan["action"] == GROW and st["n_live"] < 4:
                    st["n_live"] += 1
                    pol.executed(now)
                    st["execs"] += 1
                    st["last_grow"] = now
                elif plan["action"] == SHRINK and st["n_live"] > 1:
                    lg = st["last_grow"]
                    if (lg is not None
                            and now - lg < tuning.ELASTIC_COOLDOWN_S):
                        raise ModelViolation(
                            f"flap: SHRINK executed {now - lg:.1f}s "
                            f"after a GROW — inside the "
                            f"{tuning.ELASTIC_COOLDOWN_S:.0f}s cooldown")
                    st["n_live"] -= 1
                    pol.executed(now)
                    st["execs"] += 1
                st["ticks"] += 1

        spec = LiveSpec(
            fingerprint=lambda: (st["flips"], st["ticks"], st["n_live"],
                                 tuple(sorted(pol._streak.items())),
                                 pol._cooldown_until, st["execs"]),
            progress=lambda: (st["ticks"],),
            obligations=[Obligation(
                "scaler_reacts",
                lambda: st["regime"] is SURGE and st["n_live"] < 4,
                lambda: st["execs"], 24)])
        return [("env", env()), ("scaler", scaler())], spec

    return mk


def _check_autoscale(*, cooldown_s=None, expect_violation=False,
                     expect_marker=None,
                     check="autoscale_flap") -> LiveCheckResult:
    return explore_live(check, _mk_autoscale(cooldown_s),
                        expect_violation=expect_violation,
                        expect_marker=expect_marker)


# ---------------------------------------------------------------------------
# scenario 4: gossip shedding deferrals are bounded
# ---------------------------------------------------------------------------

def _mk_shed(plane_dir: str):
    """Every tick arrives under pressure; the streak cap must force an
    anti-entropy run within the registry's declared bound anyway."""
    from flowsentryx_tpu.cluster.gossip import GossipPlane

    def mk():
        plane = GossipPlane(plane_dir, 0, 2)
        st = {"i": 0, "runs": 0}

        def driver():
            for _ in range(_SHED_ITERS):
                yield "tick(pressure=1)"
                st["i"] += 1
                plane._next_tick = 0.0
                plane.tick(pressure=1.0)
                if plane._defer_streak == 0:
                    st["runs"] += 1

        spec = LiveSpec(
            fingerprint=lambda: (st["i"],
                                 min(plane._defer_streak,
                                     _SHED_ITERS + 1),
                                 st["runs"]),
            progress=lambda: (st["i"],),
            obligations=[Obligation(
                "anti_entropy_runs", lambda: True,
                lambda: st["runs"], _SHED_BOUND)])
        return [("gossip", driver())], spec

    return mk


def _check_shed(plane_dir, *, expect_violation=False,
                expect_marker=None,
                check="shed_bounded") -> LiveCheckResult:
    return explore_live(check, _mk_shed(plane_dir),
                        expect_violation=expect_violation,
                        expect_marker=expect_marker)


# ---------------------------------------------------------------------------
# scenario 5: quiesce terminates (idle streak, quiet peers, deadline)
# ---------------------------------------------------------------------------

def _mk_quiesce(plane_dir: str):
    """The REAL ``_quiesce_steps`` generator under a model clock and a
    scripted tick (busy, busy, then idle), racing the peers-go-quiet
    event.  Must return on every interleaving — by convergence or by
    its deadline."""
    from flowsentryx_tpu.cluster.gossip import GossipPlane

    TIMEOUT = 1.0
    INTERVAL = 0.1
    MAX_ITERS = 12

    def mk():
        plane = GossipPlane(plane_dir, 0, 2)
        st = {"busy": 2, "quiet": False, "t": 0.0,
              "returned": False, "iters": 0}

        def scripted_tick(force=False, pressure=0.0):
            if st["busy"] > 0:
                st["busy"] -= 1
                return 7
            return 0

        plane.tick = scripted_tick
        gen = plane._quiesce_steps(TIMEOUT,
                                   peers_quiet=lambda: st["quiet"],
                                   clock=lambda: st["t"])

        def quiescer():
            while True:
                yield "quiesce_iter"
                st["iters"] += 1
                try:
                    next(gen)
                except StopIteration:
                    st["returned"] = True
                    return
                st["t"] += INTERVAL

        def peers():
            yield "peers_quiet"
            st["quiet"] = True

        def finale():
            if not st["returned"]:
                raise ModelViolation(
                    "quiesce did not return within its deadline")

        spec = LiveSpec(
            fingerprint=lambda: (st["busy"], st["quiet"],
                                 round(st["t"], 3), st["returned"]),
            progress=lambda: (st["iters"],),
            obligations=[Obligation(
                "quiesce_returns", lambda: True,
                lambda: st["returned"], MAX_ITERS + 4)],
            finale=finale)
        return [("quiescer", quiescer()), ("peers", peers())], spec

    return mk


def _check_quiesce(plane_dir, *, expect_violation=False,
                   expect_marker=None,
                   check="quiesce_terminates") -> LiveCheckResult:
    return explore_live(check, _mk_quiesce(plane_dir),
                        expect_violation=expect_violation,
                        expect_marker=expect_marker)


# ---------------------------------------------------------------------------
# plants: the regressions this leg exists to catch
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _plant_notify_deleted():
    """``cv.notify_all()`` deleted from ``SinkChannel.complete`` —
    the classic lost-wakeup: backpressure waiters park forever."""
    from flowsentryx_tpu.sync import channel as channel_mod

    orig = channel_mod.SinkChannel.complete

    def complete(self, n_chunks, busy_s=0.0, exc=None):
        with self.cv:
            self.busy_s += busy_s
            self._pending -= n_chunks
            if exc is not None:
                self._exc = exc
            # regression under test: the notify_all() is gone

    channel_mod.SinkChannel.complete = complete
    try:
        yield
    finally:
        channel_mod.SinkChannel.complete = orig


@contextlib.contextmanager
def _plant_fence_lift_dropped():
    """Supervisor stamp re-delivery removed: one lost fence-lift (or
    commit stamp) wedges the fleet forever — the bug
    ``_redeliver_stamps`` fixes."""
    from flowsentryx_tpu.cluster import supervisor as sup_mod

    orig = sup_mod.ClusterSupervisor._redeliver_stamps
    sup_mod.ClusterSupervisor._redeliver_stamps = (
        lambda self, h: None)
    try:
        yield
    finally:
        sup_mod.ClusterSupervisor._redeliver_stamps = orig


@contextlib.contextmanager
def _plant_streak_cap_removed():
    """``SHED_MAX_DEFER`` effectively removed (set astronomically
    high): pressure defers anti-entropy forever."""
    orig = tuning.SHED_MAX_DEFER
    tuning.SHED_MAX_DEFER = 1 << 30
    try:
        yield
    finally:
        tuning.SHED_MAX_DEFER = orig


def run_plants(plane_dir: str,
               controls: dict[str, LiveCheckResult] | None = None
               ) -> list[dict]:
    """Run all four planted regressions; each record carries the
    catching schedule and the clean control's verdict.  ``controls``
    maps plant name → an already-proved clean run of the same
    scenario (the driver's checks phase); any missing control is
    re-proved here."""
    controls = dict(controls or {})
    out = []

    with _plant_notify_deleted():
        r = _check_channel(expect_violation=True,
                           expect_marker="wait_below(0)",
                           check="plant:notify_deleted")
    ctl = controls.get("notify_deleted") or _check_channel()
    out.append(_plant_record(
        "notify_deleted",
        "cv.notify_all() deleted from SinkChannel.complete",
        r, ctl))

    with _plant_fence_lift_dropped():
        r = _check_handoff("fence_lift@donor~noredeliver",
                           ("c_fence", 0, "zero"),
                           expect_violation=True,
                           expect_marker="livelock")
    ctl = (controls.get("fence_lift_dropped")
           or _check_handoff("fence_lift@donor", ("c_fence", 0, "zero")))
    out.append(_plant_record(
        "fence_lift_dropped",
        "supervisor stamp re-delivery removed: one lost fence-lift "
        "wedges the fleet", r, ctl))

    with _plant_streak_cap_removed():
        r = _check_shed(plane_dir, expect_violation=True,
                        expect_marker="starvation: obligation "
                                      "'anti_entropy_runs'",
                        check="plant:streak_cap_removed")
    ctl = controls.get("streak_cap_removed") or _check_shed(plane_dir)
    out.append(_plant_record(
        "streak_cap_removed",
        "SHED_MAX_DEFER cap removed: pressure defers anti-entropy "
        "forever", r, ctl))

    r = _check_autoscale(cooldown_s=0.0, expect_violation=True,
                         expect_marker="flap",
                         check="plant:cooldown_zeroed")
    ctl = controls.get("cooldown_zeroed") or _check_autoscale()
    out.append(_plant_record(
        "cooldown_zeroed",
        "elastic cooldown zeroed: GROW→SHRINK flap inside the window",
        r, ctl))
    return out


def _plant_record(name: str, description: str, r: LiveCheckResult,
                  ctl: LiveCheckResult) -> dict:
    cx = r.counterexample
    return {
        "plant": name,
        "description": description,
        "caught": bool(r.ok),
        "caught_by": r.detector,
        "control_ok": bool(ctl.ok),
        "schedule": list(cx.schedule) if cx is not None else [],
        "detail": cx.detail if cx is not None else "",
    }


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run_live(quick: bool = False) -> dict:
    """Run the full liveness leg: protocol proofs, planted
    regressions with controls, and the PROGRESS registry audit."""
    from flowsentryx_tpu.cluster.gossip import create_plane

    t0 = time.perf_counter()
    checks: list[LiveCheckResult] = []
    with tempfile.TemporaryDirectory(prefix="fsx-live-") as td:
        create_plane(td, 2)
        checks.append(_check_channel())
        for edge_name, drop in _DROP_EDGES:
            if quick and edge_name not in _QUICK_EDGES:
                continue
            checks.append(_check_handoff(edge_name, drop))
        checks.append(_check_autoscale())
        checks.append(_check_shed(td))
        checks.append(_check_quiesce(td))
        by_name = {c.check: c for c in checks}
        plants = run_plants(td, controls={
            "notify_deleted": by_name.get("channel_stop_drain_live"),
            "fence_lift_dropped":
                by_name.get("handoff_drop[fence_lift@donor]"),
            "streak_cap_removed": by_name.get("shed_bounded"),
            "cooldown_zeroed": by_name.get("autoscale_flap"),
        })

    exercised = {c.check.split("[")[0] for c in checks}
    reg = registry.validate(exercised=exercised)

    checks_ok = all(c.ok for c in checks)
    plants_ok = all(p["caught"] and p["control_ok"] for p in plants)
    report = {
        "schema": SCHEMA,
        "quick": bool(quick),
        "ok": bool(checks_ok and plants_ok and reg["ok"]),
        "registry": reg,
        "checks": [c.to_json() for c in checks],
        "plants": plants,
        "totals": {
            "checks": len(checks),
            "states": sum(c.states for c in checks),
            "edges": sum(c.edges for c in checks),
            "steps": sum(c.steps for c in checks),
            "plants": len(plants),
        },
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
    return report
