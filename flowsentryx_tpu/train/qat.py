"""Quantization-aware training in JAX — torch ``prepare_qat``/``convert``
semantics (``model.py:163-166,221-222``), functionally.

The reference QAT-trains ``QuantStub → Linear(8,1) → sigmoid →
DeQuantStub`` with MinMax observers, then converts to int8.  Here the
same pieces are explicit pure functions:

* **observers** are ``(min, max)`` carried in the train state, updated
  from each batch (quint8 affine for activations, int8 symmetric for
  weights — torch's default QAT qconfig);
* **fake-quant** with a straight-through estimator stands in for
  torch's FakeQuantize modules;
* **convert** reads the final observers into a deployable
  :class:`~flowsentryx_tpu.models.logreg.LogRegParams` — the actual
  quantized artifact (the reference's script saved the *unconverted*
  model by mistake, SURVEY.md §7.5).

Loss/optimizer mirror the reference: summed BCE + Adagrad full-batch
(``model.py:169-190``), both configurable.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from flowsentryx_tpu.core.schema import NUM_FEATURES
from flowsentryx_tpu.models.logreg import LogRegParams, make_params


class Observer(NamedTuple):
    """Moving-average min/max (torch MovingAverageMinMaxObserver, the
    default QAT activation observer).  A sticky min/max would be
    poisoned forever by one early-training excursion — e.g. a first
    epoch that swings the linear output to -2e5 locks in a quant step
    of ~1e3 and saturates the sigmoid for the rest of training."""

    lo: jnp.ndarray  # [] f32
    hi: jnp.ndarray  # [] f32
    momentum: float = 0.9

    def update(self, x: jnp.ndarray) -> "Observer":
        return self.update_minmax(x.min(), x.max())

    def update_minmax(self, blo: jnp.ndarray, bhi: jnp.ndarray) -> "Observer":
        """Momentum update from a precomputed batch range — the seam the
        data-parallel trainer uses: each device contributes its shard's
        min/max, ``pmin``/``pmax`` merge them into the GLOBAL batch
        range, and this update then runs identically (replicated) on
        every device, so observers never diverge across the mesh."""
        fresh = ~jnp.isfinite(self.lo)
        m = self.momentum
        return Observer(
            lo=jnp.where(fresh, blo, m * self.lo + (1 - m) * blo),
            hi=jnp.where(fresh, bhi, m * self.hi + (1 - m) * bhi),
            momentum=self.momentum,
        )

    def quint8_qparams(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Affine quint8 (scale, zero_point), torch determination rules:
        range always includes 0; zp clamped to [0, 255]."""
        lo = jnp.minimum(self.lo, 0.0)
        hi = jnp.maximum(self.hi, 0.0)
        scale = jnp.maximum((hi - lo) / 255.0, 1e-12)
        zp = jnp.clip(jnp.round(-lo / scale), 0, 255)
        return scale, zp


def fresh_observer() -> Observer:
    return Observer(lo=jnp.float32(jnp.inf), hi=jnp.float32(-jnp.inf))


def fake_quant(
    x: jnp.ndarray, scale: jnp.ndarray, zp: jnp.ndarray, qmin: float, qmax: float
) -> jnp.ndarray:
    """Quantize→dequantize with a straight-through gradient."""
    q = jnp.clip(jnp.round(x / scale) + zp, qmin, qmax)
    dq = (q - zp) * scale
    return x + jax.lax.stop_gradient(dq - x)


class QatState(NamedTuple):
    w: jnp.ndarray          # [8] f32 master weights
    b: jnp.ndarray          # [] f32
    obs_in: Observer
    obs_out: Observer
    opt_state: optax.OptState


class TrainResult(NamedTuple):
    state: QatState
    losses: np.ndarray      # [epochs] f32
    params: LogRegParams    # converted int8 artifact


def _weight_scale(w: jnp.ndarray) -> jnp.ndarray:
    """Per-tensor symmetric int8 weight scale (zp=0), torch
    ``default_weight_observer``: scale = absmax / 127."""
    return jnp.maximum(jnp.abs(w).max() / 127.0, 1e-12)


def qat_forward(
    w: jnp.ndarray,
    b: jnp.ndarray,
    obs_in: Observer,
    obs_out: Observer,
    x: jnp.ndarray,
    quantize: bool = True,
) -> tuple[jnp.ndarray, Observer, Observer]:
    """One QAT forward pass: returns probabilities + updated observers.

    ``quantize=False`` is the observer-only warmup phase (observers
    track ranges but the forward stays float) — fake-quant switches on
    once ranges reflect a roughly-converged model, the standard cure
    for early-training range thrash."""
    obs_in = obs_in.update(x)
    if quantize:
        in_s, in_zp = obs_in.quint8_qparams()
        x = fake_quant(x, in_s, in_zp, 0, 255)

        w_s = _weight_scale(w)
        w = fake_quant(w, w_s, jnp.float32(0.0), -127, 127)

    y = x @ w + b
    obs_out = obs_out.update(y)
    if quantize:
        out_s, out_zp = obs_out.quint8_qparams()
        y = fake_quant(y, out_s, out_zp, 0, 255)
    return jax.nn.sigmoid(y), obs_in, obs_out


def train_logreg_qat(
    X: np.ndarray,
    y: np.ndarray,
    epochs: int = 200,
    lr: float = 0.05,
    warmup_fraction: float = 0.5,
    log_features: bool = True,
    optimizer: optax.GradientTransformation | None = None,
    log_every: int = 0,
    sample_weight: np.ndarray | None = None,
) -> TrainResult:
    """Full-batch QAT (the reference trains full-batch 1000 epochs with
    Adagrad lr=0.05, ``model.py:169-190``; 200 epochs converges for the
    synthetic sets and is a flag for the real ones).

    ``log_features`` trains in the log1p domain (recorded in the
    exported artifact, see LogRegParams.log1p): raw CIC features span
    1e0..1e6, where a per-tensor quint8 input step wipes out every
    small-magnitude feature — the reference artifact's exact pathology.
    The first ``warmup_fraction`` of epochs run observer-only, and the
    optimizer restarts when fake-quant engages (warmup-scale Adagrad
    accumulators would otherwise freeze the quant-finetune phase).

    ``sample_weight`` scales each row's BCE term — the lever for
    minority-mode recall (a slow-attack upweight trades a little benign
    precision for the recall a uniform loss averages away)."""
    X = jnp.asarray(X, jnp.float32)
    if log_features:
        X = jnp.log1p(X)
    y = jnp.asarray(y, jnp.float32)
    sw = (None if sample_weight is None
          else jnp.asarray(sample_weight, jnp.float32))
    opt = optimizer or optax.adagrad(lr)

    w0 = jnp.zeros((NUM_FEATURES,), jnp.float32)
    b0 = jnp.float32(0.0)
    state = QatState(
        w=w0, b=b0,
        obs_in=fresh_observer(), obs_out=fresh_observer(),
        opt_state=opt.init((w0, b0)),
    )

    def loss_fn(wb, obs_in, obs_out, X, y, quantize):
        w, b = wb
        p, obs_in, obs_out = qat_forward(w, b, obs_in, obs_out, X, quantize)
        eps = 1e-7  # BCE on probabilities, summed (BCELoss(sum))
        losses = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
        if sw is not None:
            losses = losses * sw
        return losses.sum(), (obs_in, obs_out)

    @partial(jax.jit, static_argnames=("quantize",))
    def epoch(state: QatState, X, y, quantize: bool):
        (loss, (obs_in, obs_out)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )((state.w, state.b), state.obs_in, state.obs_out, X, y, quantize)
        updates, opt_state = opt.update(grads, state.opt_state)
        w, b = optax.apply_updates((state.w, state.b), updates)
        return QatState(w, b, obs_in, obs_out, opt_state), loss

    n_warm = int(epochs * warmup_fraction)
    losses = np.zeros(epochs, np.float32)
    for e in range(epochs):
        if e == n_warm:  # phase switch: fresh optimizer for finetune
            state = state._replace(opt_state=opt.init((state.w, state.b)))
        state, loss = epoch(state, X, y, quantize=e >= n_warm)
        losses[e] = float(loss)
        if log_every and (e + 1) % log_every == 0:
            print(f"epoch {e + 1}/{epochs}: loss {losses[e]:.1f}")

    return TrainResult(
        state=state, losses=losses, params=convert(state, log_features)
    )


def convert(state: QatState, log_features: bool = True) -> LogRegParams:
    """torch ``convert``: bake observers + weights into the deployable
    int8 artifact (this is what the reference FAILED to save)."""
    w_s = _weight_scale(state.w)
    w_int8 = np.clip(
        np.round(np.asarray(state.w) / float(w_s)), -127, 127
    ).astype(np.int8)
    in_s, in_zp = state.obs_in.quint8_qparams()
    out_s, out_zp = state.obs_out.quint8_qparams()
    return make_params(
        w_int8=w_int8,
        bias=float(state.b),
        w_scale=float(w_s),
        in_scale=float(in_s),
        in_zp=int(in_zp),
        out_scale=float(out_s),
        out_zp=int(out_zp),
        log1p=log_features,
    )


# ---------------------------------------------------------------------------
# Data-parallel QAT over a device mesh
# ---------------------------------------------------------------------------


def train_logreg_qat_dp(
    X: np.ndarray,
    y: np.ndarray,
    mesh,
    epochs: int = 200,
    lr: float = 0.05,
    warmup_fraction: float = 0.5,
    log_features: bool = True,
    optimizer: optax.GradientTransformation | None = None,
) -> TrainResult:
    """:func:`train_logreg_qat` sharded over a ``jax.sharding.Mesh``.

    Same full-batch semantics, data-parallel: each device holds an
    ``N/n`` shard of the training set; per epoch it computes its
    shard's loss terms and gradients, which ``psum`` into the exact
    full-batch sums (the loss is summed BCE, so data parallelism is
    lossless up to float reassociation).  The interesting correctness
    question is the **observers**: min/max ranges are NOT additive, so
    each device contributes its shard's range and ``pmin``/``pmax``
    merge them into the global batch range *before* the momentum
    update, which then runs replicated — observers stay bit-identical
    across the mesh and match the single-device trainer (asserted in
    tests/test_train.py).  Ragged ``N`` is zero-padded and masked out
    of loss, gradients, and ranges.
    """
    axis = mesh.axis_names[0]
    n_dev = int(mesh.devices.size)
    X = jnp.asarray(X, jnp.float32)
    if log_features:
        X = jnp.log1p(X)
    y = jnp.asarray(y, jnp.float32)
    n = X.shape[0]
    pad = (-n) % n_dev
    mask = jnp.concatenate([jnp.ones((n,), bool), jnp.zeros((pad,), bool)])
    X = jnp.concatenate([X, jnp.zeros((pad, X.shape[1]), jnp.float32)])
    y = jnp.concatenate([y, jnp.zeros((pad,), jnp.float32)])
    opt = optimizer or optax.adagrad(lr)

    w0 = jnp.zeros((NUM_FEATURES,), jnp.float32)
    b0 = jnp.float32(0.0)
    state = QatState(
        w=w0, b=b0,
        obs_in=fresh_observer(), obs_out=fresh_observer(),
        opt_state=opt.init((w0, b0)),
    )

    def device_epoch(state: QatState, X_l, y_l, m_l, quantize: bool):
        # Observer updates run PRIMAL-ONLY, before autodiff: pmin/pmax
        # have no differentiation rule, and none is needed — fake-quant's
        # straight-through estimator blocks every gradient path through
        # the quant params, so computing them outside value_and_grad is
        # gradient-identical to the single-device trainer (which updates
        # observers inside the differentiated forward).
        x = X_l
        blo = jax.lax.pmin(jnp.min(jnp.where(m_l[:, None], x, jnp.inf)), axis)
        bhi = jax.lax.pmax(jnp.max(jnp.where(m_l[:, None], x, -jnp.inf)), axis)
        obs_in = state.obs_in.update_minmax(blo, bhi)
        in_s, in_zp = obs_in.quint8_qparams()
        xq = fake_quant(x, in_s, in_zp, 0, 255) if quantize else x
        wq = (fake_quant(state.w, _weight_scale(state.w), jnp.float32(0.0),
                         -127, 127) if quantize else state.w)
        yl = xq @ wq + state.b
        ylo = jax.lax.pmin(jnp.min(jnp.where(m_l, yl, jnp.inf)), axis)
        yhi = jax.lax.pmax(jnp.max(jnp.where(m_l, yl, -jnp.inf)), axis)
        obs_out = state.obs_out.update_minmax(ylo, yhi)
        out_s, out_zp = obs_out.quint8_qparams()

        def loss_fn(wb):
            w, b = wb
            x = X_l
            if quantize:
                x = fake_quant(x, in_s, in_zp, 0, 255)
                w = fake_quant(w, _weight_scale(w), jnp.float32(0.0),
                               -127, 127)
            yl = x @ w + b
            if quantize:
                yl = fake_quant(yl, out_s, out_zp, 0, 255)
            p = jax.nn.sigmoid(yl)
            eps = 1e-7  # BCE on probabilities, summed (BCELoss(sum))
            losses = -(y_l * jnp.log(p + eps)
                       + (1 - y_l) * jnp.log(1 - p + eps))
            return jax.lax.psum(jnp.sum(jnp.where(m_l, losses, 0.0)), axis)

        loss, grads = jax.value_and_grad(loss_fn)((state.w, state.b))
        # shard_map AD leaves each device with d(local loss)/dw; the
        # full-batch gradient is their sum
        grads = jax.lax.psum(grads, axis)
        updates, opt_state = opt.update(grads, state.opt_state)
        w, b = optax.apply_updates((state.w, state.b), updates)
        return QatState(w, b, obs_in, obs_out, opt_state), loss

    state_specs = jax.tree.map(lambda _: P(), state,
                               is_leaf=lambda x: x is None)
    epochs_jit = {}
    for quantize in (False, True):
        from flowsentryx_tpu.parallel.mesh import shard_map

        epochs_jit[quantize] = jax.jit(shard_map(
            partial(device_epoch, quantize=quantize),
            mesh=mesh,
            in_specs=(state_specs, P(axis), P(axis), P(axis)),
            out_specs=(state_specs, P()),
            check_vma=False,
        ))

    n_warm = int(epochs * warmup_fraction)
    losses = np.zeros(epochs, np.float32)
    for e in range(epochs):
        if e == n_warm:  # phase switch: fresh optimizer (see train_logreg_qat)
            state = state._replace(opt_state=opt.init((state.w, state.b)))
        state, loss = epochs_jit[e >= n_warm](state, X, y, mask)
        losses[e] = float(loss)

    return TrainResult(
        state=state, losses=losses, params=convert(state, log_features)
    )


# ---------------------------------------------------------------------------
# Float trainers (logreg without quant; MLP family)
# ---------------------------------------------------------------------------


def train_mlp(
    X: np.ndarray,
    y: np.ndarray,
    epochs: int = 100,
    batch_size: int = 4096,
    lr: float = 1e-3,
    hidden: int = 32,
    seed: int = 0,
):
    """Minibatch Adam for the MLP family (models/mlp.py)."""
    from flowsentryx_tpu.models import mlp

    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    params = mlp.init_params(jax.random.PRNGKey(seed), hidden=hidden)
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(mlp.loss_fn)(params, xb, yb)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    n = len(X)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(epochs):
        order = rng.permutation(n)
        for s in range(0, n, batch_size):
            idx = order[s : s + batch_size]
            params, opt_state, loss = step(params, opt_state, X[idx], y[idx])
        losses.append(float(loss))
    return params, np.asarray(losses, np.float32)


def train_multiclass(
    X: np.ndarray,
    y_class: np.ndarray,
    epochs: int = 60,
    batch_size: int = 4096,
    lr: float = 1e-3,
    hidden: int = 32,
    seed: int = 0,
):
    """Minibatch Adam for the per-attack-class expert heads
    (models/multiclass.py — the SURVEY §2.3 EP extension point)."""
    from flowsentryx_tpu.models import multiclass

    X = jnp.asarray(X, jnp.float32)
    y_class = jnp.asarray(y_class, jnp.int32)
    params = multiclass.init_params(jax.random.PRNGKey(seed), hidden=hidden)
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(multiclass.loss_fn)(params, xb, yb)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    n = len(X)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(epochs):
        order = rng.permutation(n)
        for s in range(0, n, batch_size):
            idx = order[s : s + batch_size]
            params, opt_state, loss = step(params, opt_state,
                                           X[idx], y_class[idx])
        losses.append(float(loss))
    return params, np.asarray(losses, np.float32)
