// shm_ring.hpp — file-backed SPSC ring shared with the Python engine.
//
// The daemon produces flow records into the feature ring and consumes
// blacklist updates from the verdict ring; the engine does the reverse.
// Layout is struct fsx_shm_ring_hdr (kern/fsx_schema.h, GENERATED from
// flowsentryx_tpu/core/schema.py) followed by `capacity` fixed-size
// records.  Cursors are monotonic record counts; acquire/release pairs
// order record payloads against cursor publication.
#pragma once

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <stdexcept>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include "fsx_schema.h"

namespace fsx {

class ShmRing {
public:
    // Create (producer side, truncates) or open (consumer side) a ring.
    static ShmRing create(const std::string &path, uint64_t capacity,
                          uint64_t record_size) {
        if (capacity == 0 || (capacity & (capacity - 1)))
            throw std::invalid_argument("capacity must be a power of two");
        int fd = ::open(path.c_str(), O_RDWR | O_CREAT, 0644);
        if (fd < 0)
            throw std::runtime_error("open " + path + ": " + strerror(errno));
        size_t bytes = sizeof(fsx_shm_ring_hdr) + capacity * record_size;
        if (ftruncate(fd, (off_t)bytes) != 0) {
            ::close(fd);
            throw std::runtime_error("ftruncate: " + std::string(strerror(errno)));
        }
        ShmRing r(fd, bytes);
        std::memset(r.base_, 0, sizeof(fsx_shm_ring_hdr));
        r.hdr()->capacity = capacity;
        r.hdr()->record_size = record_size;
        // publish last with release ordering (a release STORE rather
        // than a fence: identical cross-process semantics, and TSAN
        // can model it — fences are unsupported under -fsanitize=thread)
        __atomic_store_n(&r.hdr()->magic, FSX_SHM_MAGIC, __ATOMIC_RELEASE);
        return r;
    }

    static ShmRing open(const std::string &path) {
        int fd = ::open(path.c_str(), O_RDWR);
        if (fd < 0)
            throw std::runtime_error("open " + path + ": " + strerror(errno));
        struct stat st {};
        if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(fsx_shm_ring_hdr)) {
            ::close(fd);
            throw std::runtime_error("ring file too small: " + path);
        }
        ShmRing r(fd, (size_t)st.st_size);
        // acquire pairs with create()'s release store: once magic is
        // observed, capacity/record_size reads below it are ordered
        if (__atomic_load_n(&r.hdr()->magic, __ATOMIC_ACQUIRE)
            != FSX_SHM_MAGIC)
            throw std::runtime_error("bad ring magic in " + path);
        return r;
    }

    ShmRing(ShmRing &&o) noexcept : fd_(o.fd_), bytes_(o.bytes_), base_(o.base_) {
        o.fd_ = -1;
        o.base_ = nullptr;
    }
    ShmRing(const ShmRing &) = delete;
    ~ShmRing() {
        if (base_)
            munmap(base_, bytes_);
        if (fd_ >= 0)
            ::close(fd_);
    }

    fsx_shm_ring_hdr *hdr() const { return (fsx_shm_ring_hdr *)base_; }
    uint64_t capacity() const { return hdr()->capacity; }
    uint64_t record_size() const { return hdr()->record_size; }
    char *slot(uint64_t i) const {
        return (char *)base_ + sizeof(fsx_shm_ring_hdr) +
               (i & (capacity() - 1)) * record_size();
    }

    // Cursor access via __atomic builtins on the mmap'd u64s (std::atomic
    // can't legally be overlaid on plain struct fields).
    uint64_t load_head(int order) const { return __atomic_load_n(&hdr()->head, order); }
    uint64_t load_tail(int order) const { return __atomic_load_n(&hdr()->tail, order); }

    // ---- producer ----
    // Copy up to n records in; returns how many fit (drops the rest —
    // the ring-full policy mirrors bpf_ringbuf_reserve failing: the
    // consumer lags, fail open and let the kernel limiter stand alone).
    uint64_t produce(const void *records, uint64_t n) {
        uint64_t h = load_head(__ATOMIC_RELAXED);
        uint64_t t = load_tail(__ATOMIC_ACQUIRE);
        uint64_t space = capacity() - (h - t);
        if (n > space)
            n = space;
        for (uint64_t i = 0; i < n; i++)
            std::memcpy(slot(h + i),
                        (const char *)records + i * record_size(),
                        record_size());
        __atomic_store_n(&hdr()->head, h + n, __ATOMIC_RELEASE);
        return n;
    }

    // ---- consumer ----
    uint64_t consume(void *out, uint64_t max) {
        uint64_t t = load_tail(__ATOMIC_RELAXED);
        uint64_t h = load_head(__ATOMIC_ACQUIRE);
        uint64_t n = h - t;
        if (n > max)
            n = max;
        for (uint64_t i = 0; i < n; i++)
            std::memcpy((char *)out + i * record_size(), slot(t + i),
                        record_size());
        __atomic_store_n(&hdr()->tail, t + n, __ATOMIC_RELEASE);
        return n;
    }

    uint64_t readable() const {
        return load_head(__ATOMIC_ACQUIRE) - load_tail(__ATOMIC_ACQUIRE);
    }

private:
    ShmRing(int fd, size_t bytes) : fd_(fd), bytes_(bytes) {
        base_ = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
        if (base_ == MAP_FAILED) {
            ::close(fd);
            throw std::runtime_error("mmap: " + std::string(strerror(errno)));
        }
    }

    int fd_ = -1;
    size_t bytes_ = 0;
    void *base_ = nullptr;
};

}  // namespace fsx
