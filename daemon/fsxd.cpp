// fsxd — the kernel-facing drain daemon (successor of src/fsx_load.py,
// which was a broken 46-line BCC stub: fsx_load.py:15 NameError).
//
// Jobs (SURVEY.md §7.2 "daemon"):
//   1. feature egress: drain per-flow feature records from the kernel's
//      BPF feature ring and republish them into the shared-memory ring
//      the Python/TPU engine consumes;
//   2. verdict ingress: consume blacklist updates from the engine's
//      verdict ring and write them into the kernel blacklist map;
//   3. stand-alone operation: when the TPU plane is absent, the kernel
//      limiter continues alone (fail-open; nothing to do here).
//
// Backends:
//   --sim     in-process traffic generator (no root/NIC; the eBPF-world
//             "fake backend" of SURVEY.md §4) — drives integration tests
//             and benches end-to-end over the real shm transport.
//   --replay  stream fsx_flow_record arrays from a file (pcap-derived).
//   --bpf     libbpf: real BPF ring + map (compiled only where libbpf
//             exists; this image has no libbpf, so it is #ifdef-gated).
//
// Output: one JSON line on stdout at exit with counters; progress on
// stderr.  The Python integration test asserts on the JSON.

#include <chrono>
#include <cinttypes>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "fsx_schema.h"
#include "shm_ring.hpp"

namespace {

volatile std::sig_atomic_t g_stop = 0;
void on_signal(int) { g_stop = 1; }

uint64_t now_ns() {
    return std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
}

struct Options {
    std::string mode = "sim";
    std::string feature_ring = "/tmp/fsx_feature_ring";
    std::string verdict_ring = "/tmp/fsx_verdict_ring";
    std::string replay_file;
    uint64_t ring_capacity = 1 << 16;  // feature-ring record slots
    double rate_pps = 1e6;             // sim packet rate
    uint64_t total_packets = 0;        // 0 = unbounded
    double duration_s = 0;             // 0 = unbounded
    double attack_fraction = 0.8;
    uint32_t n_attack_ips = 64;
    uint32_t n_benign_ips = 1024;
    uint64_t seed = 1;
};

[[noreturn]] void usage(const char *argv0) {
    std::fprintf(stderr,
                 "usage: %s [--sim|--replay FILE|--bpf IFACE] [options]\n"
                 "  --feature-ring PATH   shm feature ring (default /tmp/fsx_feature_ring)\n"
                 "  --verdict-ring PATH   shm verdict ring (default /tmp/fsx_verdict_ring)\n"
                 "  --ring-capacity N     feature ring slots, power of 2 (default 65536)\n"
                 "  --rate PPS            sim packet rate (default 1e6)\n"
                 "  --packets N           stop after N packets\n"
                 "  --duration S          stop after S seconds\n"
                 "  --attack-fraction F   sim attack share (default 0.8)\n"
                 "  --attack-ips N        sim attack pool (default 64)\n"
                 "  --seed N              sim rng seed\n",
                 argv0);
    std::exit(2);
}

Options parse(int argc, char **argv) {
    Options o;
    for (int i = 1; i < argc; i++) {
        std::string a = argv[i];
        auto next = [&]() -> std::string {
            if (++i >= argc)
                usage(argv[0]);
            return argv[i];
        };
        if (a == "--sim")
            o.mode = "sim";
        else if (a == "--replay") {
            o.mode = "replay";
            o.replay_file = next();
        } else if (a == "--bpf") {
            o.mode = "bpf";
            next();  // interface name (used by the libbpf build)
        } else if (a == "--feature-ring")
            o.feature_ring = next();
        else if (a == "--verdict-ring")
            o.verdict_ring = next();
        else if (a == "--ring-capacity")
            o.ring_capacity = std::stoull(next());
        else if (a == "--rate")
            o.rate_pps = std::stod(next());
        else if (a == "--packets")
            o.total_packets = std::stoull(next());
        else if (a == "--duration")
            o.duration_s = std::stod(next());
        else if (a == "--attack-fraction")
            o.attack_fraction = std::stod(next());
        else if (a == "--attack-ips")
            o.n_attack_ips = (uint32_t)std::stoul(next());
        else if (a == "--seed")
            o.seed = std::stoull(next());
        else
            usage(argv[0]);
    }
    return o;
}

// Minimal mirror of the Python TrafficGen's statistics so --sim produces
// model-meaningful features (flowsentryx_tpu/engine/traffic.py is the
// reference implementation; both emit kernel-estimator-style records).
class SimSource {
public:
    explicit SimSource(const Options &o) : o_(o), rng_(o.seed) {
        attack_ips_.resize(o.n_attack_ips);
        benign_ips_.resize(o.n_benign_ips);
        std::uniform_int_distribution<uint32_t> low(1, (1u << 24) - 1);
        for (auto &ip : attack_ips_)
            ip = low(rng_);
        for (auto &ip : benign_ips_)
            ip = (1u << 24) + low(rng_);
        clock_ns_ = 1'000'000'000ULL;
        dt_ns_ = (uint64_t)(1e9 / o.rate_pps);
        if (dt_ns_ == 0)
            dt_ns_ = 1;
    }

    void fill(std::vector<fsx_flow_record> &out, size_t n) {
        out.resize(n);
        std::uniform_real_distribution<double> u01(0.0, 1.0);
        for (size_t i = 0; i < n; i++) {
            fsx_flow_record &r = out[i];
            std::memset(&r, 0, sizeof(r));
            bool attack = u01(rng_) < o_.attack_fraction;
            r.ts_ns = clock_ns_;
            clock_ns_ += dt_ns_;
            if (attack) {
                r.saddr = attack_ips_[rng_() % attack_ips_.size()];
                r.pkt_len = 60 + rng_() % 20;
                r.ip_proto = 17;  // UDP flood
                r.feat[0] = 80;
                uint32_t size = r.pkt_len;
                r.feat[1] = size;
                r.feat[2] = rng_() % 3;
                r.feat[3] = r.feat[2] * r.feat[2];
                r.feat[4] = size;
                uint32_t iat = 1 + rng_() % 50;
                r.feat[5] = iat;
                r.feat[6] = rng_() % 20;
                r.feat[7] = iat * (1 + rng_() % 3);
            } else {
                r.saddr = benign_ips_[rng_() % benign_ips_.size()];
                r.pkt_len = 100 + rng_() % 1400;
                r.ip_proto = 6;
                r.flags = FSX_FLAG_TCP;
                r.feat[0] = 443;
                uint32_t size = r.pkt_len;
                uint32_t std_ = 100 + rng_() % 500;
                r.feat[1] = size;
                r.feat[2] = std_;
                r.feat[3] = std_ * std_;
                r.feat[4] = size;
                uint32_t iat = 5'000 + rng_() % 495'000;
                r.feat[5] = iat;
                r.feat[6] = iat / (1 + rng_() % 3);
                r.feat[7] = iat * (2 + rng_() % 6);
            }
        }
    }

private:
    Options o_;
    std::mt19937_64 rng_;
    std::vector<uint32_t> attack_ips_, benign_ips_;
    uint64_t clock_ns_, dt_ns_;
};

}  // namespace

int main(int argc, char **argv) {
    Options o = parse(argc, argv);
    std::signal(SIGINT, on_signal);
    std::signal(SIGTERM, on_signal);

    if (o.mode == "bpf") {
#ifdef FSX_HAVE_LIBBPF
        // libbpf path: load kern/fsx_kern.o, attach XDP, drain the BPF
        // feature ring into the shm ring, apply verdict-ring entries to
        // blacklist_map via bpf_map_update_elem.  (Compiled only where
        // libbpf headers exist; see daemon/README.md.)
#else
        std::fprintf(stderr,
                     "fsxd: built without libbpf (FSX_HAVE_LIBBPF); "
                     "--bpf unavailable. Use --sim or --replay.\n");
        return 1;
#endif
    }

    auto fring = fsx::ShmRing::create(o.feature_ring, o.ring_capacity,
                                      sizeof(fsx_flow_record));
    auto vring = fsx::ShmRing::create(o.verdict_ring, 1 << 14,
                                      sizeof(fsx_verdict_record));

    std::fprintf(stderr, "fsxd: mode=%s feature_ring=%s verdict_ring=%s\n",
                 o.mode.c_str(), o.feature_ring.c_str(), o.verdict_ring.c_str());

    uint64_t produced = 0, dropped_ring_full = 0, verdicts = 0, suppressed = 0;
    std::unordered_map<uint32_t, uint64_t> blacklist;  // saddr -> until_ns

    FILE *replay = nullptr;
    if (o.mode == "replay") {
        replay = std::fopen(o.replay_file.c_str(), "rb");
        if (!replay) {
            std::perror("fsxd: open replay file");
            return 1;
        }
    }

    SimSource sim(o);
    std::vector<fsx_flow_record> batch;
    std::vector<fsx_verdict_record> vbatch(4096);
    const size_t CHUNK = 2048;
    uint64_t t_start = now_ns();
    uint64_t next_report = t_start + 1'000'000'000ULL;
    uint64_t drain_deadline = 0;  // set once total_packets is reached

    while (!g_stop) {
        // ---- produce features -------------------------------------------
        size_t want = CHUNK;
        if (o.total_packets && produced + want > o.total_packets)
            want = o.total_packets - produced;
        if (want > 0) {
            if (replay) {
                batch.resize(want);
                size_t got = std::fread(batch.data(), sizeof(fsx_flow_record),
                                        want, replay);
                batch.resize(got);
                if (got == 0)
                    g_stop = 1;
            } else {
                sim.fill(batch, want);
            }

            // Blacklist suppression: records from blocked sources never
            // reach the engine (the sim analog of XDP_DROP).
            uint64_t tnow = batch.empty() ? 0 : batch.back().ts_ns;
            size_t w = 0;
            for (size_t i = 0; i < batch.size(); i++) {
                auto it = blacklist.find(batch[i].saddr);
                if (it != blacklist.end()) {
                    if (tnow < it->second) {
                        suppressed++;
                        continue;
                    }
                    blacklist.erase(it);  // TTL expired
                }
                if (w != i)
                    batch[w] = batch[i];
                w++;
            }

            uint64_t pushed = fring.produce(batch.data(), w);
            dropped_ring_full += w - pushed;
            produced += batch.size();
        }

        // ---- consume verdicts -------------------------------------------
        uint64_t n = vring.consume(vbatch.data(), vbatch.size());
        for (uint64_t i = 0; i < n; i++)
            blacklist[vbatch[i].saddr] = vbatch[i].until_ns;
        verdicts += n;

        // ---- bounds / pacing --------------------------------------------
        uint64_t t = now_ns();
        if (o.total_packets && produced >= o.total_packets) {
            // wait (bounded) for the consumer to drain + send verdicts
            if (drain_deadline == 0)
                drain_deadline = t + 3'000'000'000ULL;
            if (fring.readable() == 0 || t > drain_deadline) {
                uint64_t extra = vring.consume(vbatch.data(), vbatch.size());
                for (uint64_t i = 0; i < extra; i++)
                    blacklist[vbatch[i].saddr] = vbatch[i].until_ns;
                verdicts += extra;
                break;
            }
            std::this_thread::sleep_for(std::chrono::microseconds(200));
        }
        if (o.duration_s > 0 && (t - t_start) > (uint64_t)(o.duration_s * 1e9))
            break;
        if (t >= next_report) {
            std::fprintf(stderr,
                         "fsxd: produced=%" PRIu64 " verdicts=%" PRIu64
                         " vring_readable=%" PRIu64 " vring_head=%" PRIu64
                         " blacklisted=%zu suppressed=%" PRIu64 "\n",
                         produced, verdicts, vring.readable(),
                         vring.load_head(__ATOMIC_ACQUIRE),
                         blacklist.size(), suppressed);
            next_report = t + 1'000'000'000ULL;
        }
        if (fring.readable() >= fring.capacity() - CHUNK)
            std::this_thread::sleep_for(std::chrono::microseconds(200));
    }

    // Final verdict drain on every exit path: verdicts racing the
    // shutdown still get counted (and, in --bpf mode, applied), so an
    // engine that was mid-flush when the duration expired is not lost.
    {
        uint64_t extra = vring.consume(vbatch.data(), vbatch.size());
        for (uint64_t i = 0; i < extra; i++)
            blacklist[vbatch[i].saddr] = vbatch[i].until_ns;
        verdicts += extra;
    }

    if (replay)
        std::fclose(replay);
    std::printf("{\"produced\": %" PRIu64 ", \"verdicts\": %" PRIu64
                ", \"blacklisted\": %zu, \"suppressed\": %" PRIu64
                ", \"dropped_ring_full\": %" PRIu64 "}\n",
                produced, verdicts, blacklist.size(), suppressed,
                dropped_ring_full);
    return 0;
}
