"""Small MLP detector head — the framework's model-extensibility proof.

The reference's model zoo is exactly one logistic regression
(``model/model.py:124-137``); its README floats "per-attack-class"
detection as future work.  This MLP (8 → hidden → hidden → 1) is the
second registered model family: same 8-feature input contract, same
scalar-probability output contract, so the engine can swap models via
config without code changes.  bfloat16 by default — the MXU-native
float dtype.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from flowsentryx_tpu.core.schema import NUM_FEATURES


class MlpParams(NamedTuple):
    w1: jnp.ndarray  # [8, H]
    b1: jnp.ndarray  # [H]
    w2: jnp.ndarray  # [H, H]
    b2: jnp.ndarray  # [H]
    w3: jnp.ndarray  # [H, 1]
    b3: jnp.ndarray  # [1]


def init_params(
    key: jax.Array, hidden: int = 32, dtype: jnp.dtype = jnp.bfloat16
) -> MlpParams:
    k1, k2, k3 = jax.random.split(key, 3)

    def he(k, fan_in, shape):
        return (jax.random.normal(k, shape) * jnp.sqrt(2.0 / fan_in)).astype(dtype)

    return MlpParams(
        w1=he(k1, NUM_FEATURES, (NUM_FEATURES, hidden)),
        b1=jnp.zeros((hidden,), dtype),
        w2=he(k2, hidden, (hidden, hidden)),
        b2=jnp.zeros((hidden,), dtype),
        w3=he(k3, hidden, (hidden, 1)),
        b3=jnp.zeros((1,), dtype),
    )


def logits(params: MlpParams, x: jnp.ndarray) -> jnp.ndarray:
    """``[B, 8] → [B]`` pre-sigmoid logits — the single forward pass all
    entry points share.  Plain matmuls: XLA tiles these onto the MXU; no
    vmap needed when the math is already batched.

    Inputs pass through a symmetric log compression,
    ``sign(x)·log1p(|x|)``: CIC flow features are heavy-tailed
    (1e0..1e6) and raw magnitudes at bf16 destroy He-initialized
    training.  Part of this model family's feature contract — applied
    identically at train and serve time."""
    x = jnp.sign(x) * jnp.log1p(jnp.abs(x))
    h = jax.nn.relu(x.astype(params.w1.dtype) @ params.w1 + params.b1)
    h = jax.nn.relu(h @ params.w2 + params.b2)
    return (h @ params.w3 + params.b3)[:, 0].astype(jnp.float32)


def classify(params: MlpParams, x: jnp.ndarray) -> jnp.ndarray:
    """Score one 8-feature vector → probability."""
    return jax.nn.sigmoid(logits(params, x[None, :])[0])


@jax.jit
def classify_batch(params: MlpParams, x: jnp.ndarray) -> jnp.ndarray:
    """Batched scoring → ``[B]`` probabilities."""
    return jax.nn.sigmoid(logits(params, x))


@jax.jit
def loss_fn(params: MlpParams, x: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean binary cross-entropy (numerically stable logit form)."""
    lg = logits(params, x)
    losses = jnp.maximum(lg, 0) - lg * labels + jnp.log1p(jnp.exp(-jnp.abs(lg)))
    return losses.mean()


# ---------------------------------------------------------------------------
# Artifact I/O (same .npz discipline as logreg.save_params)
# ---------------------------------------------------------------------------

ARTIFACT_SCHEMA_VERSION = 1


def _npz_path(path: str) -> str:
    return path if path.endswith(".npz") else path + ".npz"


def save_params(params: MlpParams, path: str) -> str:
    """Persist as .npz; bf16 has no numpy dtype, so weights are stored
    as float32 with the original dtype name recorded for exact restore.
    Returns the actual path written."""
    import numpy as np

    path = _npz_path(path)
    np.savez(
        path,
        **{k: np.asarray(v, np.float32) for k, v in params._asdict().items()},
        dtype=str(params.w1.dtype),
        schema_version=ARTIFACT_SCHEMA_VERSION,
    )
    return path


def load_params(path: str) -> MlpParams:
    import numpy as np

    with np.load(_npz_path(path)) as z:
        version = int(z["schema_version"]) if "schema_version" in z else 0
        if version != ARTIFACT_SCHEMA_VERSION:
            raise ValueError(
                f"mlp artifact schema version {version} != {ARTIFACT_SCHEMA_VERSION}"
            )
        dtype = jnp.dtype(str(z["dtype"]))
        return MlpParams(
            **{k: jnp.asarray(z[k], dtype) for k in MlpParams._fields}
        )
