"""In-repo static verifier + cross-layer contract checker tests.

Two halves of ``fsx check`` (ISSUE 2):

* the abstract-interpreter verifier accepts every shipped program and
  rejects each table-driven violation — missing packet bounds check,
  uninitialized stack read, map-value overflow, bad exit, pointer
  leaks, ringbuf reference bugs — with an instruction-level diagnostic;
* the contract checker catches every flavor of cross-layer drift
  (stale generated header, baked progs.py offset vs schema, stale
  sealed image) loudly, in pytest, with no kernel in the loop.

None of this needs bpf(2): that is the point.
"""

from __future__ import annotations

import re

import pytest

from flowsentryx_tpu.bpf import contracts, image, loader, progs, verifier
from flowsentryx_tpu.bpf.asm import Asm, Program
from flowsentryx_tpu.bpf.isa import (
    BPF_ADD, BPF_AND, BPF_B, BPF_DIV, BPF_DW, BPF_JEQ, BPF_JGT, BPF_JNE,
    BPF_LSH, BPF_W,
    FN_map_lookup_elem, FN_ringbuf_reserve, FN_ringbuf_submit,
    R0, R1, R2, R3, R4, R5, R6, R7, R10,
    XDP_MD_DATA, XDP_MD_DATA_END,
    alu64, alu64_imm, call, exit_, ldx, mov32, mov64, mov64_imm, st_imm,
    stx,
)

# ---- acceptance: every shipped program verifies clean ----------------


@pytest.mark.parametrize("compact", [False, True])
def test_accepts_shipped_programs(compact):
    prog = progs.build(compact=compact)
    rep = verifier.check_program_cached(prog)
    assert rep.n_insns == len(prog.insns)
    assert rep.insns_visited > rep.n_insns  # real exploration, not a stub
    assert rep.subprog_entries  # the isqrt bpf-to-bpf callee
    assert set(rep.map_names) == set(prog.map_names)


def test_accepts_checked_in_images():
    """The sealed daemon hand-off images decode back to verifiable
    programs under their own embedded map specs."""
    for path in contracts.IMAGE_PATHS.values():
        prog, maps = image.to_program(path.read_bytes(), name=path.name)
        infos = {m.name: verifier.MapInfo(m.name, m.map_type, m.key_size,
                                          m.value_size) for m in maps}
        rep = verifier.check_program(prog, infos)
        assert rep.n_insns == len(prog.insns)


def test_image_roundtrip_is_lossless():
    """to_program(emit(p)) reproduces p's instructions and relocations
    exactly — the decode the CLI trusts for --image verification."""
    prog = progs.build()
    back, maps = image.to_program(image.emit(prog=prog))
    assert back.insns == prog.insns
    assert [(r.slot, r.map_name) for r in back.relocs] == \
        [(r.slot, r.map_name) for r in prog.relocs]
    assert {m.name for m in maps} == set(prog.map_names)


def test_corrupt_image_raises_value_error():
    """Truncated/corrupt blobs reject with ValueError (never a raw
    struct.error), so fsx check --image reports instead of crashing."""
    good = image.emit()
    for blob in (b"", good[:10], good[:60], b"XXXXXXXX" + good[8:],
                 good[:-4]):
        with pytest.raises(ValueError):
            image.to_program(blob)


def test_bad_register_number_rejected():
    """A 4-bit reg nibble of 11-15 (corrupt image, hand-built insn)
    rejects with a diagnostic, not an IndexError."""
    from flowsentryx_tpu.bpf.isa import BPF_ALU64, BPF_K, BPF_MOV, Insn

    bad = [Insn(BPF_ALU64 | BPF_MOV | BPF_K, dst=13, imm=1)] + exit_()
    with pytest.raises(verifier.StaticVerifierError,
                       match="invalid register number"):
        verifier.check_program(bad)


def test_ldx_into_frame_pointer_rejected():
    a = Asm("neg")
    _pkt_prologue(a)
    a += mov64(R4, R2)
    a += alu64_imm(BPF_ADD, R4, 8)
    a.jmp_reg(BPF_JGT, R4, R3, "out")
    a += ldx(BPF_B, R10, R2, 0)  # overwrite the frame pointer
    a.label("out")
    _ret0(a)
    with pytest.raises(verifier.StaticVerifierError,
                       match="frame pointer"):
        verifier.check_program(a.assemble())


def test_cache_is_content_addressed():
    prog = progs.build()
    assert verifier.check_program_cached(prog) is \
        verifier.check_program_cached(prog)


# ---- negative table: each violation rejects with a diagnostic --------


def _pkt_prologue(a: Asm) -> None:
    """r2 = data, r3 = data_end (r1 = ctx on entry)."""
    a += ldx(BPF_W, R2, R1, XDP_MD_DATA)
    a += ldx(BPF_W, R3, R1, XDP_MD_DATA_END)


def _ret0(a: Asm) -> None:
    a += mov64_imm(R0, 0)
    a += exit_()


def missing_bounds_check() -> Program:
    a = Asm("neg")
    _pkt_prologue(a)
    a += ldx(BPF_B, R0, R2, 12)  # no compare against data_end
    _ret0(a)
    return a.assemble()


def bounds_check_too_small() -> Program:
    a = Asm("neg")
    _pkt_prologue(a)
    a += mov64(R4, R2)
    a += alu64_imm(BPF_ADD, R4, 14)
    a.jmp_reg(BPF_JGT, R4, R3, "out")  # proves 14 bytes
    a += ldx(BPF_B, R0, R2, 14)        # reads the 15th
    a.label("out")
    _ret0(a)
    return a.assemble()


def stale_proof_after_variable_advance() -> Program:
    """The IPv6 ext-header cursor bug the walk in progs.py must not
    have: advance by a packet-derived amount, then reuse the OLD
    bounds proof without re-checking."""
    a = Asm("neg")
    _pkt_prologue(a)
    a += mov64(R4, R2)
    a += alu64_imm(BPF_ADD, R4, 8)
    a.jmp_reg(BPF_JGT, R4, R3, "out")  # proves 8 bytes
    a += ldx(BPF_B, R5, R2, 1)         # in bounds
    a += alu64_imm(BPF_AND, R5, 0xFF)
    a += alu64_imm(BPF_LSH, R5, 3)     # bounded advance, [0, 2040]
    a += alu64(BPF_ADD, R2, R5)        # cursor moves: proof invalid
    a += ldx(BPF_B, R0, R2, 0)         # no re-check -> reject
    a.label("out")
    _ret0(a)
    return a.assemble()


def unbounded_variable_advance() -> Program:
    a = Asm("neg")
    _pkt_prologue(a)
    a += mov64(R4, R2)
    a += alu64_imm(BPF_ADD, R4, 8)
    a.jmp_reg(BPF_JGT, R4, R3, "out")
    a += ldx(BPF_W, R5, R2, 0)
    a += alu64_imm(BPF_LSH, R5, 4)     # umax 2^36: no sane bound
    a += alu64(BPF_ADD, R2, R5)
    a.label("out")
    _ret0(a)
    return a.assemble()


def uninitialized_stack_read() -> Program:
    a = Asm("neg")
    a += ldx(BPF_DW, R0, R10, -8)  # never written
    a += exit_()
    return a.assemble()


def partially_initialized_stack_read() -> Program:
    a = Asm("neg")
    a += st_imm(BPF_W, R10, -8, 7)   # bytes [-8,-4) only
    a += ldx(BPF_DW, R0, R10, -8)    # reads [-8,0)
    a += exit_()
    return a.assemble()


def stack_out_of_frame() -> Program:
    a = Asm("neg")
    a += mov64_imm(R1, 1)
    a += stx(BPF_DW, R10, -520, R1)
    _ret0(a)
    return a.assemble()


def _lookup(a: Asm, map_name: str) -> None:
    a += st_imm(BPF_W, R10, -4, 0)
    a.ld_map(R1, map_name)
    a += mov64(R2, R10)
    a += alu64_imm(BPF_ADD, R2, -4)
    a += call(FN_map_lookup_elem)


def map_value_overflow() -> Program:
    a = Asm("neg")
    _lookup(a, "config_map")
    a.jmp_imm(BPF_JEQ, R0, 0, "out")
    a += ldx(BPF_DW, R1, R0, progs.CFG_SIZE)  # one past the end
    a.label("out")
    _ret0(a)
    return a.assemble()


def map_value_null_deref() -> Program:
    a = Asm("neg")
    _lookup(a, "config_map")
    a += ldx(BPF_DW, R1, R0, 0)  # no == 0 check
    _ret0(a)
    return a.assemble()


def uninit_key_lookup() -> Program:
    a = Asm("neg")
    a.ld_map(R1, "config_map")
    a += mov64(R2, R10)
    a += alu64_imm(BPF_ADD, R2, -4)  # key bytes never written
    a += call(FN_map_lookup_elem)
    _ret0(a)
    return a.assemble()


def fall_off_the_end() -> Program:
    a = Asm("neg")
    a += mov64_imm(R0, 0)  # no exit
    return a.assemble()


def r0_uninit_at_exit() -> Program:
    a = Asm("neg")
    a += mov64_imm(R1, 1)
    a += exit_()
    return a.assemble()


def unreachable_insn() -> Program:
    a = Asm("neg")
    _ret0(a)
    a += mov64_imm(R0, 1)  # dead
    a += exit_()
    return a.assemble()


def jump_into_ld_imm64() -> list:
    from flowsentryx_tpu.bpf.isa import ja, ld_imm64

    return ja(1) + ld_imm64(R0, 7) + exit_()  # lands on the low slot


def pointer_leak_to_map() -> Program:
    a = Asm("neg")
    _lookup(a, "config_map")
    a.jmp_imm(BPF_JEQ, R0, 0, "out")
    a += stx(BPF_DW, R0, 0, R10)  # frame pointer into a map value
    a.label("out")
    _ret0(a)
    return a.assemble()


def write_to_ctx() -> Program:
    a = Asm("neg")
    a += mov64_imm(R2, 1)
    a += stx(BPF_W, R1, 0, R2)
    _ret0(a)
    return a.assemble()


def ringbuf_reference_leak() -> Program:
    a = Asm("neg")
    a.ld_map(R1, "feature_ring")
    a += mov64_imm(R2, 16)
    a += mov64_imm(R3, 0)
    a += call(FN_ringbuf_reserve)
    _ret0(a)  # record neither submitted nor discarded
    return a.assemble()


def _spill_submit_reload(a: Asm) -> None:
    """reserve; spill the record pointer; submit; reload the spill into
    r1.  Register aliases die at the submit and the spill is scrubbed
    (release_reference semantics), so r1 comes back an unknown scalar —
    any use of it as the record must reject."""
    a.ld_map(R1, "feature_ring")
    a += mov64_imm(R2, 16)
    a += mov64_imm(R3, 0)
    a += call(FN_ringbuf_reserve)
    a.jmp_imm(BPF_JEQ, R0, 0, "out")
    a += stx(BPF_DW, R10, -16, R0)  # spill the record pointer
    a += mov64(R1, R0)
    a += mov64_imm(R2, 0)
    a += call(FN_ringbuf_submit)
    a += ldx(BPF_DW, R1, R10, -16)  # stale pointer back


def ringbuf_double_submit() -> Program:
    a = Asm("neg")
    _spill_submit_reload(a)
    a += mov64_imm(R2, 0)
    a += call(FN_ringbuf_submit)  # reference already released
    a.label("out")
    _ret0(a)
    return a.assemble()


def ringbuf_use_after_release() -> Program:
    """Store through the record pointer AFTER submit — the kernel
    invalidates every copy (including spills) at release_reference and
    rejects; the static pass must too."""
    a = Asm("neg")
    _spill_submit_reload(a)
    a += mov64_imm(R2, 1)
    a += stx(BPF_DW, R1, 0, R2)  # write through the released record
    a.label("out")
    _ret0(a)
    return a.assemble()


def ringbuf_record_overflow() -> Program:
    a = Asm("neg")
    a.ld_map(R1, "feature_ring")
    a += mov64_imm(R2, 16)
    a += mov64_imm(R3, 0)
    a += call(FN_ringbuf_reserve)
    a.jmp_imm(BPF_JEQ, R0, 0, "out")
    a += mov64(R6, R0)
    a += mov64_imm(R1, 1)
    a += stx(BPF_DW, R6, 16, R1)  # reserved 16, writes [16, 24)
    a += mov64(R1, R6)
    a += mov64_imm(R2, 0)
    a += call(FN_ringbuf_submit)
    a.label("out")
    _ret0(a)
    return a.assemble()


def atomic_stale_spill_bounds_abuse() -> Program:
    """Atomic add into a stack slot must invalidate its tracked spill:
    otherwise the slot reloads as the old constant 0 and a packet
    pointer advanced by the (actually unknown) value keeps the stale
    1-byte bounds proof."""
    from flowsentryx_tpu.bpf.isa import FN_ktime_get_ns, atomic_add64

    a = Asm("neg")
    a += stx(BPF_DW, R10, -16, R1)  # park ctx across the helper call
    a += mov64_imm(R1, 0)
    a += stx(BPF_DW, R10, -8, R1)   # spill const 0
    a += call(FN_ktime_get_ns)
    a += atomic_add64(R10, -8, R0)  # slot += unknown scalar
    a += ldx(BPF_DW, R1, R10, -16)  # ctx back
    _pkt_prologue(a)
    a += mov64(R4, R2)
    a += alu64_imm(BPF_ADD, R4, 1)
    a.jmp_reg(BPF_JGT, R4, R3, "out")  # proves 1 byte
    a += ldx(BPF_DW, R5, R10, -8)   # must be unknown now, not const 0
    a += alu64(BPF_ADD, R2, R5)     # variable advance: proof reset
    a += ldx(BPF_B, R0, R2, 0)      # stale proof may not be reused
    a.label("out")
    _ret0(a)
    return a.assemble()


def division_by_zero() -> Program:
    a = Asm("neg")
    a += mov64_imm(R0, 7)
    a += alu64_imm(BPF_DIV, R0, 0)
    a += exit_()
    return a.assemble()


def unknown_helper() -> Program:
    a = Asm("neg")
    a += call(999)
    _ret0(a)
    return a.assemble()


def read_uninit_register() -> Program:
    a = Asm("neg")
    a += mov64_imm(R0, 0)
    a += alu64(BPF_ADD, R0, R7)  # r7 never initialized
    a += exit_()
    return a.assemble()


def truncate_pointer_32bit() -> Program:
    a = Asm("neg")
    _pkt_prologue(a)
    a += mov32(R0, R2)  # 32-bit move of a packet pointer
    a += exit_()
    return a.assemble()


def branch_on_uninit() -> Program:
    a = Asm("neg")
    a.jmp_imm(BPF_JNE, R6, 0, "out")
    a.label("out")
    _ret0(a)
    return a.assemble()


NEGATIVE_CASES = [
    ("missing_bounds_check", missing_bounds_check,
     r"invalid packet access.*data_end"),
    ("bounds_check_too_small", bounds_check_too_small,
     r"invalid packet access.*proven range=14"),
    ("stale_proof_after_variable_advance",
     stale_proof_after_variable_advance,
     r"invalid packet access.*proven range=none"),
    ("unbounded_variable_advance", unbounded_variable_advance,
     r"variable packet advance unbounded"),
    ("uninitialized_stack_read", uninitialized_stack_read,
     r"uninitialized stack byte fp-8"),
    ("partially_initialized_stack_read", partially_initialized_stack_read,
     r"uninitialized stack byte fp-4"),
    ("stack_out_of_frame", stack_out_of_frame,
     r"stack access out of frame"),
    ("map_value_overflow", map_value_overflow,
     r"map value access out of bounds.*config_map.*value_size=88"),
    ("map_value_null_deref", map_value_null_deref,
     r"possible NULL map-value dereference"),
    ("uninit_key_lookup", uninit_key_lookup,
     r"map_lookup_elem arg2.*uninitialized stack byte"),
    ("fall_off_the_end", fall_off_the_end,
     r"falls off the end"),
    ("r0_uninit_at_exit", r0_uninit_at_exit,
     r"R0 not initialized at exit"),
    ("unreachable_insn", unreachable_insn,
     r"unreachable instruction"),
    ("jump_into_ld_imm64", jump_into_ld_imm64,
     r"into a ld_imm64"),
    ("pointer_leak_to_map", pointer_leak_to_map,
     r"pointer leak"),
    ("write_to_ctx", write_to_ctx,
     r"write to ctx"),
    ("ringbuf_reference_leak", ringbuf_reference_leak,
     r"reference leak.*ringbuf"),
    ("ringbuf_double_submit", ringbuf_double_submit,
     r"expected the reserved ringbuf record pointer"),
    ("ringbuf_use_after_release", ringbuf_use_after_release,
     r"invalid write"),
    ("ringbuf_record_overflow", ringbuf_record_overflow,
     r"ringbuf record access out of bounds"),
    ("atomic_stale_spill_bounds_abuse", atomic_stale_spill_bounds_abuse,
     r"invalid packet access|variable packet advance unbounded"),
    ("division_by_zero", division_by_zero,
     r"division by zero"),
    ("unknown_helper", unknown_helper,
     r"unknown/unsupported helper id 999"),
    ("read_uninit_register", read_uninit_register,
     r"read of uninitialized r"),
    ("truncate_pointer_32bit", truncate_pointer_32bit,
     r"truncates a pointer"),
    ("branch_on_uninit", branch_on_uninit,
     r"branch on uninitialized r6"),
]


@pytest.mark.parametrize("name,build,pattern",
                         NEGATIVE_CASES, ids=[c[0] for c in NEGATIVE_CASES])
def test_negative_cases_reject_with_diagnostics(name, build, pattern):
    with pytest.raises(verifier.StaticVerifierError) as ei:
        verifier.check_program(build())
    e = ei.value
    assert re.search(pattern, str(e)), f"{name}: {e}"
    # instruction-level diagnostics: index + disassembly of the slot
    assert 0 <= e.insn_idx
    assert e.insn_txt


def test_complexity_budget_enforced():
    with pytest.raises(verifier.StaticVerifierError, match="budget"):
        verifier.check_program(progs.build(), budget=500)


def test_empty_program_rejected():
    with pytest.raises(verifier.StaticVerifierError, match="empty"):
        verifier.check_program([])


def test_unknown_map_rejected():
    a = Asm("neg")
    a.ld_map(R1, "no_such_map")
    _ret0(a)
    with pytest.raises(verifier.StaticVerifierError, match="unknown maps"):
        verifier.check_program(a.assemble())


# ---- seal/load hooks -------------------------------------------------


def test_loader_refuses_bad_program_before_any_syscall():
    """prog_load runs the static verifier FIRST: a mis-assembled
    program dies with a precise diagnostic even where bpf(2) itself is
    unavailable (this container)."""
    with pytest.raises(verifier.StaticVerifierError):
        loader.prog_load(missing_bounds_check())


def test_image_emit_refuses_bad_program():
    with pytest.raises(verifier.StaticVerifierError):
        image.emit(prog=map_value_null_deref())


def test_skip_env_var(monkeypatch):
    monkeypatch.setenv("FSX_SKIP_STATIC_VERIFY", "1")
    blob = image.emit(prog=ringbuf_reference_leak())
    assert blob  # sealed unchecked, explicitly


# ---- cross-layer contracts -------------------------------------------


def test_contracts_clean_tree():
    rep = contracts.run_all()
    assert rep.ok, rep.failures


def test_header_drift_detected(tmp_path):
    """A hand edit (or un-regenerated schema change) in fsx_schema.h
    fails both the freshness and the layout diff."""
    bad = tmp_path / "fsx_schema.h"
    text = contracts.HEADER_PATH.read_text()
    assert "\t__u64 block_ns;" in text
    bad.write_text(text.replace("\t__u64 block_ns;", "\t__u32 block_ns;"))
    assert contracts.check_header_fresh(bad)
    fails = contracts.check_header_layouts(bad)
    assert any("fsx_config" in f for f in fails)


def test_header_define_drift_detected(tmp_path):
    bad = tmp_path / "fsx_schema.h"
    text = contracts.HEADER_PATH.read_text()
    bad.write_text(text.replace("#define FSX_FLAG_TCP 4",
                                "#define FSX_FLAG_TCP 2"))
    fails = contracts.check_header_defines(bad)
    assert any("FSX_FLAG_TCP" in f for f in fails)


def test_progs_offset_drift_detected(monkeypatch):
    """A struct edit that forgot the assembler: progs constant vs the
    schema layout."""
    monkeypatch.setattr(progs, "CFG_BLOCK_NS", progs.CFG_BLOCK_NS + 8)
    fails = contracts.check_progs_offsets()
    assert any("CFG_BLOCK_NS" in f and "offsetof(fsx_config, block_ns)"
               in f for f in fails)


def test_map_spec_drift_detected(monkeypatch):
    specs = dict(progs.MAP_SPECS)
    mtype, ks, vs, ent = specs["ip_state_map"]
    specs["ip_state_map"] = (mtype, ks, vs - 8, ent)
    monkeypatch.setattr(progs, "MAP_SPECS", specs)
    fails = contracts.check_map_specs()
    assert any("ip_state_map" in f for f in fails)


def test_stale_image_detected(tmp_path):
    stale = tmp_path / "fsx_prog.img"
    stale.write_bytes(image.emit(sizes=progs.MapSizes(max_track_ips=64)))
    fails = contracts.check_images({False: stale})
    assert fails and "stale" in fails[0]


def test_missing_image_detected(tmp_path):
    fails = contracts.check_images({True: tmp_path / "nope.img"})
    assert fails and "missing" in fails[0]


def test_cli_check_reports_corrupt_image(tmp_path, capsys):
    """fsx check --image on garbage exits 1 with a report entry, not a
    traceback."""
    import json

    from flowsentryx_tpu import cli

    bad = tmp_path / "corrupt.img"
    bad.write_bytes(b"\x00" * 10)
    rc = cli.main(["check", "--json", "--no-images",
                   "--image", str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and not out["ok"]
    entry = next(p for p in out["programs"] if p["program"] == str(bad))
    assert not entry["ok"] and "truncated" in entry["error"]


def test_cli_check_passes_on_clean_tree(capsys):
    """`fsx check` — the operator surface — exits 0 and reports every
    program + contract on the current tree."""
    import json

    from flowsentryx_tpu import cli

    rc = cli.main(["check", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"]
    assert {p["program"] for p in out["programs"]} == \
        {"fsx[raw48]", "fsx[compact16]",
         "fsx[ml_raw48]", "fsx[ml_compact16]"}
    assert all(c["ok"] for c in out["contracts"]["checks"].values())
