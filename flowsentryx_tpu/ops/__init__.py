from flowsentryx_tpu.ops import agg, hashtable, limiters  # noqa: F401
