"""Test harness: run everything on a virtual 8-device CPU mesh.

Real TPU hardware is single-chip in CI; sharding correctness is tested
on the CPU backend with 8 virtual devices (SURVEY.md §4 "Distributed").
These env vars must be set before jax initializes its backends.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(42)
