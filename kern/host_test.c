/* host_test.c — userspace harness for the kernel-plane compute.
 *
 * Tests the packet parsers with crafted byte buffers and the integer
 * limiters against their specs — no root, no NIC, no kernel
 * (SURVEY.md §4; the reference has no tests at all, TODO.md:272).
 * Compile: gcc -DFSX_HOST_BUILD -I. host_test.c && ./a.out
 */
#include <stdio.h>
#include <string.h>
#include <stdlib.h>
#include <math.h>

#include "fsx_schema.h"
#include "parsing.h"
#include "fsx_compute.h"

static int failures;

#define CHECK(cond, name) do { \
	if (cond) { printf("ok   %s\n", name); } \
	else { printf("FAIL %s (line %d)\n", name, __LINE__); failures++; } \
} while (0)

/* ---- packet builders --------------------------------------------------- */

static size_t build_eth(unsigned char *p, __u16 ethertype)
{
	memset(p, 0xAA, 12);
	p[12] = ethertype >> 8;
	p[13] = ethertype & 0xFF;
	return 14;
}

static size_t build_ip4(unsigned char *p, __u32 saddr, __u8 proto,
			__u16 total_len, int ihl_words)
{
	memset(p, 0, (size_t)ihl_words * 4);
	p[0] = 0x40 | ihl_words;      /* version 4, IHL */
	p[2] = total_len >> 8;
	p[3] = total_len & 0xFF;
	p[8] = 64;                    /* TTL */
	p[9] = proto;
	memcpy(p + 12, &saddr, 4);    /* network order not needed for test */
	p[16] = 10; p[17] = 0; p[18] = 0; p[19] = 1;
	return (size_t)ihl_words * 4;
}

static size_t build_udp(unsigned char *p, __u16 sport, __u16 dport)
{
	p[0] = sport >> 8; p[1] = sport & 0xFF;
	p[2] = dport >> 8; p[3] = dport & 0xFF;
	p[4] = 0; p[5] = 8; p[6] = 0; p[7] = 0;
	return 8;
}

static size_t build_tcp(unsigned char *p, __u16 sport, __u16 dport, __u8 flags)
{
	memset(p, 0, 20);
	p[0] = sport >> 8; p[1] = sport & 0xFF;
	p[2] = dport >> 8; p[3] = dport & 0xFF;
	p[12] = 5 << 4;               /* data offset 5 words */
	p[13] = flags;
	return 20;
}

/* ---- parser tests ------------------------------------------------------ */

static void test_parse_udp4(void)
{
	unsigned char buf[128];
	size_t off = build_eth(buf, 0x0800);
	__u32 src = 0x01020304;
	off += build_ip4(buf + off, src, 17 /*UDP*/, 28, 5);
	off += build_udp(buf + off, 1234, 53);
	struct fsx_pkt pkt;

	CHECK(fsx_parse_packet(buf, buf + off, &pkt) == 0, "udp4 parses");
	CHECK(pkt.saddr == src, "udp4 saddr");
	CHECK(pkt.l4_proto == 17, "udp4 proto");
	CHECK(fsx_htons(pkt.dport) == 53, "udp4 dport");
	CHECK(!pkt.is_ipv6, "udp4 not v6");
}

static void test_parse_tcp_syn(void)
{
	unsigned char buf[128];
	size_t off = build_eth(buf, 0x0800);
	off += build_ip4(buf + off, 0x05060708, 6 /*TCP*/, 40, 5);
	off += build_tcp(buf + off, 40000, 443, FSX_TCP_SYN);
	struct fsx_pkt pkt;

	CHECK(fsx_parse_packet(buf, buf + off, &pkt) == 0, "tcp parses");
	CHECK(pkt.tcp_flags & FSX_TCP_SYN, "tcp SYN flag seen");
	CHECK(fsx_htons(pkt.dport) == 443, "tcp dport");
}

static void test_parse_ip4_options(void)
{
	/* IHL=8 words (options): parser must honor variable header length */
	unsigned char buf[128];
	size_t off = build_eth(buf, 0x0800);
	off += build_ip4(buf + off, 0x0A0B0C0D, 17, 40, 8);
	off += build_udp(buf + off, 9, 99);
	struct fsx_pkt pkt;

	CHECK(fsx_parse_packet(buf, buf + off, &pkt) == 0, "ip4+options parses");
	CHECK(fsx_htons(pkt.dport) == 99, "options: dport after IHL skip");
}

static void test_truncated_drops(void)
{
	unsigned char buf[128];
	struct fsx_pkt pkt;
	size_t eth = build_eth(buf, 0x0800);
	size_t full = eth + build_ip4(buf + eth, 1, 17, 28, 5);

	CHECK(fsx_parse_packet(buf, buf + 10, &pkt) < 0, "truncated eth -> drop");
	CHECK(fsx_parse_packet(buf, buf + eth + 10, &pkt) < 0,
	      "truncated ip4 -> drop");
	/* IP ok but UDP header missing: must refuse, not read OOB */
	CHECK(fsx_parse_packet(buf, buf + full + 4, &pkt) < 0,
	      "truncated udp -> drop");
	/* bogus IHL < 5 must be rejected */
	buf[eth] = 0x42;
	CHECK(fsx_parse_packet(buf, buf + full, &pkt) < 0, "ihl<5 -> drop");
}

static void test_non_ip_passes(void)
{
	unsigned char buf[64];
	size_t off = build_eth(buf, 0x0806 /* ARP */);
	struct fsx_pkt pkt;

	CHECK(fsx_parse_packet(buf, buf + off + 28, &pkt) == 1, "arp -> pass");
}

static void test_parse_ip6(void)
{
	unsigned char buf[128];
	size_t off = build_eth(buf, 0x86DD);
	unsigned char *ip6 = buf + off;

	memset(ip6, 0, 40);
	ip6[0] = 0x60;                 /* version 6 */
	ip6[6] = 17;                   /* next header: UDP */
	ip6[7] = 64;                   /* hop limit */
	for (int i = 0; i < 16; i++)
		ip6[8 + i] = i + 1;    /* src addr 0102..10 */
	off += 40;
	off += build_udp(buf + off, 1, 2);
	struct fsx_pkt pkt;

	CHECK(fsx_parse_packet(buf, buf + off, &pkt) == 0, "ip6 parses");
	CHECK(pkt.is_ipv6 == 1, "ip6 flagged");
	/* fold = xor of 4 words of the source address */
	__u32 w[4];
	memcpy(w, ip6 + 8, 16);
	CHECK(pkt.saddr == (w[0] ^ w[1] ^ w[2] ^ w[3]), "ip6 fold");
	/* full source captured for the EXACT v6 blacklist key */
	CHECK(memcmp(pkt.saddr6, ip6 + 8, 16) == 0, "ip6 exact saddr6");
}

static void test_parse_ip6_ext_walk(void)
{
	unsigned char buf[256];
	size_t off = build_eth(buf, 0x86DD);
	unsigned char *ip6 = buf + off;
	struct fsx_pkt pkt;

	memset(ip6, 0, 40);
	ip6[0] = 0x60;
	ip6[6] = IPPROTO_HOPOPTS;      /* hop-by-hop first */
	ip6[7] = 64;
	for (int i = 0; i < 16; i++)
		ip6[8 + i] = i + 1;
	off += 40;
	/* hop-by-hop: next = routing, hdr_ext_len 0 (8 bytes) */
	memset(buf + off, 0, 8);
	buf[off] = IPPROTO_ROUTING;
	off += 8;
	/* routing: next = TCP, hdr_ext_len 1 (16 bytes) */
	memset(buf + off, 0, 16);
	buf[off] = 6;
	buf[off + 1] = 1;
	off += 16;
	size_t l4 = off;
	off += build_tcp(buf + off, 1234, 443, FSX_TCP_SYN);

	/* the walk reaches TCP behind two extension headers */
	CHECK(fsx_parse_packet(buf, buf + off, &pkt) == 0, "ip6+ext parses");
	CHECK(pkt.l4_proto == 6, "ip6+ext walks to tcp");
	CHECK(pkt.dport == ((443 >> 8) | ((443 & 0xFF) << 8)),
	      "ip6+ext tcp dport");
	CHECK(pkt.tcp_flags == FSX_TCP_SYN, "ip6+ext syn visible");

	/* truncated extension header must refuse, not read OOB */
	CHECK(fsx_parse_packet(buf, buf + l4 - 12, &pkt) < 0,
	      "truncated ext hdr -> drop");

	/* a fragment header stops the walk: L3-only classification */
	ip6[6] = 44;                   /* IPPROTO_FRAGMENT */
	CHECK(fsx_parse_packet(buf, buf + off, &pkt) == 0,
	      "ip6+frag parses");
	CHECK(pkt.l4_proto == 44, "fragment not walked");
	CHECK(pkt.dport == 0, "fragment: no L4 port");
}

static void test_parse_icmp6(void)
{
	unsigned char buf[128];
	size_t off = build_eth(buf, 0x86DD);
	unsigned char *ip6 = buf + off;

	memset(ip6, 0, 40);
	ip6[0] = 0x60;
	ip6[6] = 58;                   /* next header: ICMPv6 */
	ip6[7] = 64;
	for (int i = 0; i < 16; i++)
		ip6[8 + i] = 0x20 + i;
	off += 40;
	memset(buf + off, 0, 8);
	buf[off] = 128;                /* echo request */
	struct fsx_pkt pkt;

	/* full icmp6 header present: parses with proto 58 */
	CHECK(fsx_parse_packet(buf, buf + off + 8, &pkt) == 0, "icmp6 parses");
	CHECK(pkt.l4_proto == IPPROTO_ICMPV6, "icmp6 proto 58");
	CHECK(pkt.is_ipv6 == 1, "icmp6 is ipv6");
	CHECK(pkt.sport == 0 && pkt.dport == 0, "icmp6 no ports");
	/* truncated icmp6 header must refuse, not read OOB */
	CHECK(fsx_parse_packet(buf, buf + off + 4, &pkt) < 0,
	      "truncated icmp6 -> drop");
}

/* ---- limiter tests (mirror tests/test_ops.py semantics) ---------------- */

static struct fsx_config mkcfg(void)
{
	struct fsx_config c;

	memset(&c, 0, sizeof(c));
	c.pps_threshold = 100;
	c.bps_threshold = 1000000;
	c.window_ns = 1000000000ULL;        /* 1 s */
	c.block_ns = 10000000000ULL;
	c.bucket_rate_pps = 100;
	c.bucket_burst = 200;
	return c;
}

static void test_fixed_window(void)
{
	struct fsx_config cfg = mkcfg();
	struct fsx_ip_state st;
	int over = 0;

	memset(&st, 0, sizeof(st));
	st.win_start_ns = 0;
	for (int i = 0; i < 100; i++)
		over = fsx_limiter_fixed_window(&cfg, &st, 500000000ULL, 100);
	CHECK(!over, "fixed: 100 pkts under threshold");
	over = fsx_limiter_fixed_window(&cfg, &st, 600000000ULL, 100);
	CHECK(over, "fixed: 101st over");
	/* window roll: seeds with this packet (reference bug fixed) */
	over = fsx_limiter_fixed_window(&cfg, &st, 2000000000ULL, 100);
	CHECK(!over && st.win_pps == 1, "fixed: roll seeds 1");
}

static void test_sliding_window(void)
{
	struct fsx_config cfg = mkcfg();
	struct fsx_ip_state st;
	int over = 0;

	memset(&st, 0, sizeof(st));
	/* 90 pkts at t=0.9s */
	for (int i = 0; i < 90; i++)
		over = fsx_limiter_sliding_window(&cfg, &st, 900000000ULL, 10);
	CHECK(!over, "sliding: 90 in window1 ok");
	/* 90 more just after the boundary: est ~ 90*0.95 + 90 > 100 */
	for (int i = 0; i < 90 && !over; i++)
		over = fsx_limiter_sliding_window(&cfg, &st, 1050000000ULL, 10);
	CHECK(over, "sliding: boundary burst caught");
	/* long idle clears history */
	memset(&st, 0, sizeof(st));
	st.prev_pps = 90;
	st.win_pps = 90;
	over = fsx_limiter_sliding_window(&cfg, &st, 5000000000ULL, 10);
	CHECK(!over && st.prev_pps == 0, "sliding: idle clears");
}

static void test_token_bucket(void)
{
	struct fsx_config cfg = mkcfg();
	struct fsx_ip_state st;
	int over;

	memset(&st, 0, sizeof(st));
	/* fresh flow at t=10s: full burst of 200 */
	int dropped = 0;
	for (int i = 0; i < 250; i++) {
		over = fsx_limiter_token_bucket(&cfg, &st, 10000000000ULL, 0);
		dropped += over;
	}
	CHECK(dropped == 50, "bucket: burst 200 then drops");
	/* 1 s later: 100 refilled */
	dropped = 0;
	for (int i = 0; i < 150; i++) {
		over = fsx_limiter_token_bucket(&cfg, &st, 11000000000ULL, 0);
		dropped += over;
	}
	CHECK(dropped == 50, "bucket: refill 100/s");
}

static void test_token_bucket_subms_refill(void)
{
	/* 2000 pps flow (0.5 ms gaps) against rate=10000: sub-ms refill
	 * credit must accumulate — ms-truncated refill would starve it */
	struct fsx_config cfg = mkcfg();
	struct fsx_ip_state st;
	__u64 t = 1000000000ULL;
	int dropped = 0;

	cfg.bucket_rate_pps = 10000;
	cfg.bucket_burst = 10;
	memset(&st, 0, sizeof(st));
	for (int i = 0; i < 4000; i++) {
		dropped += fsx_limiter_token_bucket(&cfg, &st, t, 0);
		t += 500000;       /* +0.5 ms */
	}
	CHECK(dropped == 0, "bucket: sub-ms refill sustains 2kpps under 10k rate");
	/* and a huge idle gap must not overflow the refill multiply */
	dropped = fsx_limiter_token_bucket(&cfg, &st, t + (1ULL << 62), 0);
	CHECK(dropped == 0 && st.tokens_milli <= 10000,
	      "bucket: multi-year idle clamps, no overflow");
}

static void test_token_bucket_bytes(void)
{
	/* Byte dimension (README.md:153-162 bandwidth limit): 1500 B
	 * packets against a 10 kB bucket refilling 1 kB/s; the packet
	 * dimension is kept out of reach so only bytes govern. */
	struct fsx_config cfg = mkcfg();
	struct fsx_ip_state st;
	int dropped = 0;

	cfg.bucket_rate_pps = 1000000;
	cfg.bucket_burst = 2000000;
	cfg.bucket_rate_bps = 1000;
	cfg.bucket_burst_bytes = 10000;
	memset(&st, 0, sizeof(st));
	/* fresh flow at t=100s: clamped refill fills the 10 kB burst ->
	 * 6 x 1500 B pass, the rest lack byte credit */
	for (int i = 0; i < 10; i++)
		dropped += fsx_limiter_token_bucket(&cfg, &st,
						    100000000000ULL, 1500);
	CHECK(dropped == 4, "byte bucket: 6x1500B burst then drops");
	/* 3 s later: 3000 B refilled -> exactly 2 more pass */
	dropped = 0;
	for (int i = 0; i < 4; i++)
		dropped += fsx_limiter_token_bucket(&cfg, &st,
						    103000000000ULL, 1500);
	CHECK(dropped == 2, "byte bucket: refill 1 kB/s");
	/* a refused packet spends from NEITHER dimension */
	CHECK(st.tokens_milli >= 1000, "refused spends no pkt tokens");
	CHECK(st.tok_bytes == 1000, "refused spends no byte tokens");
}

static void test_isqrt(void)
{
	int bad = 0;

	for (__u64 i = 0; i < 100000; i += 7) {
		__u64 x = i * i;
		if (fsx_isqrt_u64(x) != i)
			bad++;
	}
	CHECK(bad == 0, "isqrt exact on squares");
	CHECK(fsx_isqrt_u64(2) == 1 && fsx_isqrt_u64(3) == 1 &&
	      fsx_isqrt_u64(8) == 2, "isqrt floors");
	CHECK(fsx_isqrt_u64(0xFFFFFFFFFFFFFFFFULL) == 0xFFFFFFFF,
	      "isqrt max");
}

static void test_struct_sizes(void)
{
	CHECK(sizeof(struct fsx_flow_record) == 48, "flow_record 48B");
	CHECK(sizeof(struct fsx_config) == 88, "config 88B");
}

static void test_minifloat(void)
{
	int bad = 0;

	/* small values verbatim; decode(q) within 6.25% everywhere */
	for (__u64 f = 0; f < 8; f++)
		if (fsx_minifloat8(f) != (__u32)f)
			bad++;
	CHECK(bad == 0, "minifloat: 0..7 verbatim");
	bad = 0;
	for (__u64 f = 8; f < (1ULL << 33); f = f + f / 64 + 1) {
		__u32 q = fsx_minifloat8(f);
		__u64 dec = q < 8 ? q : (8ULL + q % 8) << (q / 8 - 1);
		__u64 err = dec > f ? dec - f : f - dec;
		if (err * 16 > f)   /* > 6.25% relative */
			bad++;
		if (q > 255)
			bad++;
	}
	CHECK(bad == 0, "minifloat: <=6.25% rel err over full range");
	CHECK(fsx_minifloat8(0xFFFFFFFFFFFFFFFFULL) == 255,
	      "minifloat: saturates at 255");
}

int main(void)
{
	test_parse_udp4();
	test_parse_tcp_syn();
	test_parse_ip4_options();
	test_truncated_drops();
	test_non_ip_passes();
	test_parse_ip6();
	test_parse_ip6_ext_walk();
	test_parse_icmp6();
	test_fixed_window();
	test_sliding_window();
	test_token_bucket();
	test_token_bucket_subms_refill();
	test_token_bucket_bytes();
	test_isqrt();
	test_struct_sizes();
	test_minifloat();

	if (failures) {
		printf("\n%d FAILURES\n", failures);
		return 1;
	}
	printf("\nall kern host tests passed\n");
	return 0;
}
