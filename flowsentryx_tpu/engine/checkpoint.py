"""Checkpoint/resume of the device-resident serving state.

The reference's only persistence is BPF map pinning under /sys/fs/bpf
(``src/Makefile:22``, ``TODO.md:289``) — kernel state survives loader
restarts, user state does not exist.  Here the TPU-plane state (per-IP
limiter/blacklist table + global stats + the t0 clock anchor) round-
trips through one ``.npz``, so a restarted engine resumes with every
tracked flow, window counter, and blacklist expiry intact — the
user-plane analog of map pinning.

(Plain npz rather than orbax: the state is a flat dict of 11 arrays,
~40 MB at 1M rows; zero-dependency and byte-inspectable wins here.)
"""

from __future__ import annotations

from pathlib import Path

import jax
import numpy as np

from flowsentryx_tpu.core import schema

CHECKPOINT_SCHEMA_VERSION = 1


def save_state(
    path: str | Path,
    table: schema.IpTableState,
    stats: schema.GlobalStats,
    t0_ns: int,
    hash_salt: int = 0,
) -> Path:
    """Snapshot serving state.  Arrays are fetched from device (the one
    deliberate D2H of the engine's lifetime).  ``hash_salt`` is the
    salt the table's slot layout was built under — a restore into an
    engine hashing with a different salt would mislocate every key, so
    it travels with the state."""
    path = Path(path)
    # np.savez silently appends .npz to a suffix-less path; normalize so
    # the returned path is the file actually written (same contract as
    # models.logreg._npz_path).
    if path.suffix != ".npz":
        path = path.with_suffix(path.suffix + ".npz")
    # One array per column (not the in-memory [N, 12] matrix): the
    # column-per-key format predates the matrix layout, keeps old
    # snapshots loadable, and lets future columns default cleanly.
    state = np.asarray(table.state)
    cols = {f"table_{name}": state[:, i]
            for i, name in enumerate(schema.TABLE_COLUMN_NAMES)}
    np.savez_compressed(
        path,
        table_key=np.asarray(table.key),
        **cols,
        **{f"stats_{k}": np.asarray(v) for k, v in stats._asdict().items()},
        t0_ns=np.uint64(t0_ns),
        hash_salt=np.uint64(hash_salt),
        schema_version=CHECKPOINT_SCHEMA_VERSION,
    )
    return path


def peek_salt(path: str | Path) -> int:
    """The hash salt a checkpoint's table was built under, WITHOUT
    loading the arrays — so a server can adopt it before compiling its
    step (pre-salt checkpoints read as 0, the unsalted hash)."""
    with np.load(Path(path)) as z:
        return int(z["hash_salt"]) if "hash_salt" in z else 0


def load_state(
    path: str | Path,
) -> tuple[schema.IpTableState, schema.GlobalStats, int, int, tuple]:
    """Restore serving state to device.
    Returns (table, stats, t0_ns, hash_salt, missing_columns) —
    ``missing_columns`` names table columns the snapshot predates (they
    load zero-filled; the caller decides whether zero is the right
    default, e.g. Engine.restore refills byte-bucket credit)."""
    with np.load(Path(path)) as z:
        version = int(z["schema_version"])
        if version != CHECKPOINT_SCHEMA_VERSION:
            raise ValueError(
                f"checkpoint schema {version} != {CHECKPOINT_SCHEMA_VERSION}"
            )
        # Columns added after a checkpoint was written load as their
        # empty-table default (e.g. tok_bytes on pre-byte-bucket
        # snapshots: zero byte credit, refilled on first sight).
        cap = int(z["table_key"].shape[0])
        state = np.zeros((cap, schema.NUM_TABLE_COLS), np.float32)
        missing = []
        for i, name in enumerate(schema.TABLE_COLUMN_NAMES):
            if f"table_{name}" in z:
                state[:, i] = z[f"table_{name}"]
            else:
                missing.append(name)
        table = schema.IpTableState(
            key=jax.device_put(z["table_key"]),
            state=jax.device_put(state),
        )
        stats = schema.GlobalStats(
            **{k: jax.device_put(z[f"stats_{k}"]) for k in schema.GlobalStats._fields}
        )
        salt = int(z["hash_salt"]) if "hash_salt" in z else 0
        return table, stats, int(z["t0_ns"]), salt, tuple(missing)
