"""The crash-consistency model checker (``fsx crash``).

The fifth static leg (docs/STATIC.md): where ``fsx sync`` proves the
shm protocols ordered, ``fsx interleave`` the concurrency protocols
linearizable, ``fsx units`` the arithmetic dimensioned and ``fsx
contracts`` the jax surface banned from the control plane, this leg
proves the DURABLE-STATE protocols crash-consistent — by running the
REAL protocol code (``cluster/rebalance.py`` handoff state machine,
``cluster/supervisor.py`` coordination, ``engine/checkpoint.py``
write/rotate/fallback) over a simulated filesystem and mailbox with
honest POSIX semantics (simfs.py), forking a crash at EVERY atomic
step, reconstructing every legal post-crash durable state, running
the real recovery path (``reconcile()``, spool adoption, ``.prev``
fallback, ``_neutralize_stale_handoff``, abort-and-retry under a
fresh handoff id), and asserting the invariant catalog below.

Four scenarios × four crash modes:

* ``checkpoint_rotate`` — three real ``save_state`` calls through the
  write → fsync → rotate → publish → dir-fsync pipeline, power crash
  at each step plus the media-fault flavor (corrupt-last-published,
  PR 13's bit-flip fault) that the ``.prev`` retention exists for.
* ``layout_flip`` — four generations of ``ShardAssignment.save``;
  a reboot may never read a torn layout or a generation older than
  one whose save returned.
* ``handoff`` — the full fenced donor → recipient span move with
  post-flip checkpoints, crashed as power / donor / recipient /
  supervisor at every step.
* ``adoption`` — ``adopt_dead_span``: the supervisor ships a dead
  rank's span from its checkpoint, crashed as power / recipient /
  supervisor.

Planted regressions (each must produce a PRINTED crash schedule, and
each must come from a run whose unplanted control is clean):
``spool_ack_reorder`` (HP_STAGED acked before the spool write),
``fsync_skipped`` (every fsync a no-op — the pre-PR-17 reality),
``prev_rotation_dropped`` (no ``.prev`` retention) and
``dual_ownership_flip`` (reconcile stops dropping foreign rows).

Everything here is jax-free: the checker rides the same sub-second
import path as the other static legs (scripts/verify_tier1.sh).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time

import numpy as np

from flowsentryx_tpu.cluster import rebalance as rb
from flowsentryx_tpu.core import durable, schema

from .simfs import CrashNow, SimFS, eligible_points
from .world import (MiniEngine, SimSupervisor, World, ckpt_path,
                    restore_mini)

#: Tick budget for one protocol run INCLUDING its recovery retries —
#: a clean handoff converges in ~5 ticks, every recovery path in a
#: handful more; a run that needs 40 is wedged, and "wedged" is the
#: ``converged`` invariant's violation, not a hang.
MAX_TICKS = 40

#: The invariant catalog — every violation names one of these.
INVARIANTS = {
    "row_conservation":
        "post-recovery engine rows are byte-exact the pre-protocol "
        "multiset: nothing lost, nothing duplicated, nothing resident "
        "off its assigned owner",
    "no_dual_ownership":
        "no table key is held by two engines at once",
    "layout_gen_monotone":
        "a reboot never reads a layout generation older than one "
        "whose save returned (gen resurrection = un-fsynced rename)",
    "layout_never_torn":
        "layout.json always parses: the publish is atomic, old or "
        "new, never a mix",
    "ckpt_current_or_prev":
        "after any completed save, a checkpoint is loadable from the "
        "current file or its .prev twin",
    "ckpt_monotone":
        "a recovered checkpoint is the last completed save or its "
        "immediate predecessor, never older",
    "ckpt_no_garbage":
        "a checkpoint that loads is byte-exact the table that was "
        "saved under that marker",
    "retry_fresh_id":
        "every handoff retry after an abort/crash uses a strictly "
        "larger handoff id",
    "spsc_single_consumer":
        "no handoff mailbox is ever drained by a second consumer",
    "converged":
        "the fleet reaches goal layout + matching acks within the "
        "tick budget after every crash (recovery is live, not wedged)",
}


@dataclasses.dataclass
class Violation:
    invariant: str
    detail: str


@dataclasses.dataclass
class CrashSchedule:
    """A counterexample: the executed op schedule up to the crash,
    the crash itself, the durable-state flavor it left, and the
    invariant the recovery then violated — printed the way
    ``fsx interleave`` prints its interleavings."""

    scenario: str
    mode: str
    crash_op: str
    flavor: str
    schedule: list[str]
    violation: Violation

    def render(self) -> str:
        lines = [f"crash schedule — scenario {self.scenario}, "
                 f"mode {self.mode}:"]
        for i, op in enumerate(self.schedule):
            lines.append(f"  {i:3d}. {op}")
        lines.append(f"  >>> CRASH ({self.mode}) before: {self.crash_op}")
        if self.flavor and self.flavor != "-":
            lines.append(f"  >>> durable state: {self.flavor}")
        lines.append(f"  >>> VIOLATED {self.violation.invariant}: "
                     f"{self.violation.detail}")
        return "\n".join(lines)


# -- row helpers ------------------------------------------------------------

def _keys_for_shard(shard: int, total: int, count: int,
                    start: int = 1) -> np.ndarray:
    """``count`` u32 keys that hash to ``shard`` under the real
    Fibonacci shard rule — the scenarios place rows by searching the
    actual hash, not by assuming one."""
    out: list[int] = []
    k = start
    while len(out) < count:
        if int(schema.shard_of(np.uint32(k), total)) == shard:
            out.append(k)
        k += 1
    return np.asarray(out, np.uint32)


def _states_for(keys) -> np.ndarray:
    keys = np.asarray(keys, np.uint32)
    base = np.arange(len(keys) * schema.NUM_TABLE_COLS,
                     dtype=np.float32).reshape(len(keys), -1)
    return base + keys[:, None].astype(np.float32)


def _concat_rows(parts):
    ks = [np.asarray(k, np.uint32).reshape(-1) for k, _ in parts]
    ss = [np.asarray(s, np.float32).reshape(len(k), -1)
          for (k, s), kk in zip(parts, ks)]
    return (np.concatenate(ks) if ks else np.empty(0, np.uint32),
            np.concatenate(ss) if ss
            else np.empty((0, schema.NUM_TABLE_COLS), np.float32))


def _row_bytes(rows) -> bytes:
    k, s = rows
    p = rb.pack_rows(k, s)
    if len(p):
        p = p[np.lexsort(p.T[::-1])]
    return p.tobytes()


# -- scenario: checkpoint write/rotate/fallback ------------------------------

class CheckpointScenario:
    """Three real ``save_state`` generations over one engine; power
    crash at every primitive write/fsync/rename step, plus the
    media-fault flavor (a pure power crash with correct fsync can
    never damage an already-published file — only media corruption
    can, and ``.prev`` is the answer to exactly that)."""

    name = "checkpoint_rotate"
    modes = ("power",)
    media_fault = True

    def build(self, **kw) -> World:
        return World(n=1, **kw)

    def setup(self, w: World) -> None:
        eng = MiniEngine()
        keys = np.arange(1, 4, dtype=np.uint32)
        eng.adopt_rows(keys, _states_for(keys))
        w.engines[0] = eng
        w.meta["tables"] = {}

    def script(self, w: World) -> None:
        eng = w.engines[0]
        for m in (1, 2, 3):
            def save(m=m):
                k = np.asarray([100 + m], np.uint32)
                eng.adopt_rows(k, _states_for(k))
                w.meta["tables"][m] = _row_bytes(eng.rows())
                eng.save(ckpt_path(w.dir, 0), m)
            w.act("rank0", save)
            w.saved_markers[0].append(m)
        w.meta["converged"] = True

    def recover_power(self, w: World, state: dict,
                      flavor: str) -> World:
        w2 = World(n=1, fsync_is_noop=w.fs.fsync_is_noop)
        w2.fs = SimFS.from_state(state, w2.tracer,
                                 fsync_is_noop=w.fs.fsync_is_noop)
        w2.meta = w.power_snapshot_meta()
        w2.saved_markers = {r: list(v)
                            for r, v in w.saved_markers.items()}
        with w2.installed():
            res = restore_mini(ckpt_path(w2.dir, 0))
        completed = w2.saved_markers[0]
        tables = w2.meta["tables"]
        if res is None:
            # the FIRST generation has no .prev to fall back to: a
            # media fault on the only copy is unrecoverable by design
            must_load = (len(completed) >= 2
                         or (completed and "media fault" not in flavor))
            if must_load:
                w2.meta["violations"].append(Violation(
                    "ckpt_current_or_prev",
                    f"no checkpoint loadable after "
                    f"{len(completed)} completed save(s)"))
        else:
            eng, marker = res
            inflight = (max(tables) if tables
                        and max(tables) not in completed else None)
            allowed = set(completed[-2:])
            if inflight is not None:
                allowed.add(inflight)
            if marker not in allowed:
                w2.meta["violations"].append(Violation(
                    "ckpt_monotone",
                    f"recovered marker {marker}, allowed {sorted(allowed)} "
                    f"(completed saves: {completed})"))
            elif _row_bytes(eng.rows()) != tables.get(marker):
                w2.meta["violations"].append(Violation(
                    "ckpt_no_garbage",
                    f"marker {marker} loaded rows differ from the "
                    "table that was saved under it"))
        w2.meta["converged"] = True
        return w2

    def judge(self, w: World) -> list[Violation]:
        return list(w.meta["violations"])


# -- scenario: layout generation flip ---------------------------------------

class FlipScenario:
    """Four generations of the real ``ShardAssignment.save`` publish;
    after a power crash the layout must parse and must not be older
    than any generation whose save RETURNED (the gen-resurrection bug
    an un-fsynced rename causes — the ``fsync_skipped`` plant's
    forcing function)."""

    name = "layout_flip"
    modes = ("power",)
    media_fault = False

    def build(self, **kw) -> World:
        return World(n=2, **kw)

    def setup(self, w: World) -> None:
        pass

    def script(self, w: World) -> None:
        asg = rb.ShardAssignment.initial(w.n * w.w, w.w, w.n)
        for i in range(4):
            cur = asg
            w.act("supervisor", lambda cur=cur: cur.save(w.dir))
            w.published_gens.append(cur.generation)
            asg = cur.reassign([1], (i + 1) % w.n)
        w.meta["converged"] = True

    def recover_power(self, w: World, state: dict,
                      flavor: str) -> World:
        w2 = World(n=w.n, fsync_is_noop=w.fs.fsync_is_noop)
        w2.fs = SimFS.from_state(state, w2.tracer,
                                 fsync_is_noop=w.fs.fsync_is_noop)
        w2.meta = w.power_snapshot_meta()
        w2.published_gens = list(w.published_gens)
        with w2.installed():
            asg = None
            try:
                asg = rb.ShardAssignment.load(w2.dir)
            except (ValueError, KeyError, TypeError) as e:
                w2.meta["violations"].append(Violation(
                    "layout_never_torn",
                    f"layout.json unreadable after reboot: "
                    f"{type(e).__name__}: {e}"))
        pub = w2.published_gens
        if pub and (asg is None or asg.generation < max(pub)):
            got = "absent" if asg is None else f"gen {asg.generation}"
            w2.meta["violations"].append(Violation(
                "layout_gen_monotone",
                f"rebooted into {got} after gen {max(pub)}'s save "
                "returned (resurrected an un-fsynced rename)"))
        w2.meta["converged"] = True
        return w2

    def judge(self, w: World) -> list[Violation]:
        return list(w.meta["violations"])


# -- scenarios: the fenced handoff + dead-span adoption ----------------------

class _FleetScenario:
    """Shared machinery for the two fleet protocols: the tick loop
    that drives the real supervisor + rebalancer halves, party
    respawn through the real recovery path (restore → fresh
    rebalancer → ``reconcile``), supervisor recovery through the real
    ``_neutralize_stale_handoff``, full-host power recovery, and the
    conservation judge."""

    media_fault = False

    def _specs(self, w: World):
        return None

    # -- goal/convergence (subclass-specific goal) ---------------------------

    def _goal_met(self, w: World) -> bool:
        raise NotImplementedError

    def _start(self, w: World) -> None:
        raise NotImplementedError

    def _converged(self, w: World) -> bool:
        if w.sup is None or "supervisor" in w.dead:
            return False
        if w.sup._handoff is not None:
            return False
        if any(f"rank{r}" in w.dead and r not in w.failed_ranks
               for r in range(w.n)):
            return False
        if not self._goal_met(w):
            return False
        asg = rb.ShardAssignment.load(w.dir)
        return all(
            w.statuses[r].ctl_get("c_layout_ack") == asg.generation
            and w.statuses[r].ctl_get("c_fence") == 0
            for r in range(w.n) if r not in w.failed_ranks)

    # -- the recovery paths (all REAL protocol code) -------------------------

    def _respawn_rank(self, w: World, r: int) -> None:
        """The runner's boot path: restore from checkpoint (with
        ``.prev`` fallback), fresh rebalancer, ``reconcile`` — spool
        adoption and foreign-row drop included."""
        w.dead.discard(f"rank{r}")

        def boot():
            res = restore_mini(ckpt_path(w.dir, r))
            if res is None:
                if w.saved_markers[r]:
                    w.meta["violations"].append(Violation(
                        "ckpt_current_or_prev",
                        f"rank{r} respawn found no loadable checkpoint "
                        f"after completed save(s) "
                        f"{w.saved_markers[r]}"))
                eng = MiniEngine()
            else:
                eng = res[0]
            w.engines[r] = eng
            rz = rb.EngineRebalancer(w.dir, r, w.statuses[r])
            rz.reconcile(eng)
            w.rebalancers[r] = rz

        w.act(f"rank{r}", boot)

    def _recover_sup(self, w: World) -> None:
        """A successor supervisor re-attaching: fresh object, the real
        adopt-path hygiene (stale-handoff neutralize-or-resume)."""
        w.dead.discard("supervisor")

        def boot():
            sup = SimSupervisor(w, specs=self._specs(w))
            sup._neutralize_stale_handoff()
            w.sup = sup

        w.act("supervisor", boot)

    def _note_published(self, w: World) -> None:
        asg = rb.ShardAssignment.load(w.dir)
        if asg is not None and (not w.published_gens
                                or asg.generation > w.published_gens[-1]):
            w.published_gens.append(asg.generation)

    def _tick(self, w: World) -> None:
        if "supervisor" in w.dead:
            self._recover_sup(w)
        else:
            w.act("supervisor",
                  lambda: w.sup._handoff_tick(time.monotonic()))
            self._note_published(w)
        for r in range(w.n):
            if f"rank{r}" in w.dead and r not in w.failed_ranks:
                self._respawn_rank(w, r)
        if ("supervisor" not in w.dead and w.sup is not None
                and w.sup._handoff is None and not self._goal_met(w)):
            self._start(w)
        for r in range(w.n):
            if r in w.failed_ranks:
                continue
            w.act(f"rank{r}",
                  lambda r=r: w.rebalancers[r].step(w.engines[r]))

    def _drive(self, w: World) -> None:
        for _ in range(MAX_TICKS):
            if self._converged(w):
                break
            self._tick(w)
        w.meta["converged"] = self._converged(w)

    def script(self, w: World) -> None:
        self._start(w)
        self._drive(w)
        if not w.meta["converged"]:
            return
        # post-flip checkpoints: the death window where one side's
        # snapshot predates the flip and the other's follows it —
        # recovery must reconcile them against the committed layout
        for r in range(w.n):
            if r in w.failed_ranks:
                continue
            def save(r=r):
                w.engines[r].save(ckpt_path(w.dir, r), 2)
                # the runner's post-checkpoint spool release
                w.rebalancers[r].note_checkpointed()
            w.act(f"rank{r}", save)
            if f"rank{r}" not in w.dead:
                w.saved_markers[r].append(2)
        for r in range(w.n):
            if f"rank{r}" in w.dead and r not in w.failed_ranks:
                self._respawn_rank(w, r)

    def recover_power(self, w: World, state: dict,
                      flavor: str) -> World:
        w2 = World(n=w.n, w=w.w, fsync_is_noop=w.fs.fsync_is_noop,
                   chunk_rows=w.hub.chunk_rows)
        w2.fs = SimFS.from_state(state, w2.tracer,
                                 fsync_is_noop=w.fs.fsync_is_noop)
        w2.meta = w.power_snapshot_meta()
        w2.saved_markers = {r: list(v)
                            for r, v in w.saved_markers.items()}
        w2.handoff_ids = list(w.handoff_ids)
        w2.published_gens = list(w.published_gens)
        w2.failed_ranks = set(w.failed_ranks)
        w2.dead = {f"rank{r}" for r in w2.failed_ranks}
        with w2.installed():
            asg = None
            try:
                asg = rb.ShardAssignment.load(w2.dir)
            except (ValueError, KeyError, TypeError) as e:
                w2.meta["violations"].append(Violation(
                    "layout_never_torn",
                    f"layout.json unreadable after reboot: "
                    f"{type(e).__name__}: {e}"))
            pub = w2.published_gens
            if pub and (asg is None or asg.generation < max(pub)):
                got = ("absent" if asg is None
                       else f"gen {asg.generation}")
                w2.meta["violations"].append(Violation(
                    "layout_gen_monotone",
                    f"rebooted into {got} after gen {max(pub)}'s "
                    "save returned"))
            for r in range(w2.n):
                if r not in w2.failed_ranks:
                    self._respawn_rank(w2, r)
            sup = SimSupervisor(w2, specs=self._specs(w2))
            sup._neutralize_stale_handoff()
            w2.sup = sup
            self._drive(w2)
        return w2

    def judge(self, w: World) -> list[Violation]:
        out = list(w.meta["violations"])
        spsc = (w.meta.get("pre_spsc", [])
                + list(w.hub.second_consumer))
        if spsc:
            out.append(Violation("spsc_single_consumer",
                                 "; ".join(spsc)))
        ids = w.handoff_ids
        if any(b <= a for a, b in zip(ids, ids[1:])):
            out.append(Violation(
                "retry_fresh_id",
                f"handoff ids not strictly increasing: {ids}"))
        if not w.meta.get("converged"):
            out.append(Violation(
                "converged",
                f"fleet did not converge within {MAX_TICKS} ticks"))
            return out
        with w.installed():
            asg = rb.ShardAssignment.load(w.dir)
            parts, part_ranks = [], []
            for r in range(w.n):
                if r in w.failed_ranks:
                    continue
                parts.append(w.engines[r].rows())
                part_ranks.append(r)
            res = rb.rows_conserved(w.meta["pre"], parts,
                                    owners=asg.owners,
                                    part_ranks=part_ranks)
        if res["dup_keys"]:
            out.append(Violation("no_dual_ownership", res["detail"]))
        if not res["ok"]:
            out.append(Violation("row_conservation", res["detail"]))
        return out


class HandoffScenario(_FleetScenario):
    """The full fenced handoff: donor rank0 moves shard 1 to
    recipient rank1 while both keep rows on shards that do not move —
    so a recovery that over-drops, over-adopts, or resurrects a layout
    shows up as a conservation or dual-ownership violation."""

    name = "handoff"
    modes = ("power", "rank0", "rank1", "supervisor")

    def build(self, **kw) -> World:
        return World(n=2, w=2, **kw)

    def setup(self, w: World) -> None:
        rb.ShardAssignment.initial(w.n * w.w, w.w, w.n).save(w.dir)
        w.published_gens.append(0)
        d_keys = np.concatenate([_keys_for_shard(0, 4, 4),
                                 _keys_for_shard(1, 4, 4)])
        r_keys = _keys_for_shard(2, 4, 3)
        for r, keys in ((0, d_keys), (1, r_keys)):
            eng = MiniEngine()
            eng.adopt_rows(keys, _states_for(keys))
            w.engines[r] = eng
            eng.save(ckpt_path(w.dir, r), 1)
            w.saved_markers[r].append(1)
            rz = rb.EngineRebalancer(w.dir, r, w.statuses[r])
            rz.reconcile(eng)
            w.rebalancers[r] = rz
        w.sup = SimSupervisor(w)
        w.meta["pre"] = _concat_rows([w.engines[0].rows(),
                                      w.engines[1].rows()])
        w.meta["span"] = [1]

    def _goal_met(self, w: World) -> bool:
        asg = rb.ShardAssignment.load(w.dir)
        return asg is not None and asg.owners[1] == 1

    def _start(self, w: World) -> None:
        def go():
            hid = w.sup.start_handoff(w.meta["span"], 0, 1)
            w.handoff_ids.append(hid)
        w.act("supervisor", go)


class AdoptionScenario(_FleetScenario):
    """``adopt_dead_span``: rank0 is confirmed dead (parked), the
    supervisor ships its whole span to rank1 from rank0's last
    checkpoint — supervisor-as-donor, so the ship itself is part of
    the supervisor's crash surface."""

    name = "adoption"
    modes = ("power", "rank1", "supervisor")

    def build(self, **kw) -> World:
        return World(n=2, w=2, **kw)

    def _specs(self, w: World):
        return [{"checkpoint": str(ckpt_path(w.dir, 0))}, {}]

    def setup(self, w: World) -> None:
        rb.ShardAssignment.initial(w.n * w.w, w.w, w.n).save(w.dir)
        w.published_gens.append(0)
        d_keys = np.concatenate([_keys_for_shard(0, 4, 3),
                                 _keys_for_shard(1, 4, 3)])
        r_keys = _keys_for_shard(2, 4, 3)
        for r, keys in ((0, d_keys), (1, r_keys)):
            eng = MiniEngine()
            eng.adopt_rows(keys, _states_for(keys))
            w.engines[r] = eng
            eng.save(ckpt_path(w.dir, r), 1)
            w.saved_markers[r].append(1)
        rz = rb.EngineRebalancer(w.dir, 1, w.statuses[1])
        rz.reconcile(w.engines[1])
        w.rebalancers[1] = rz
        # rank0 is dead for good: its table survives only as its
        # checkpoint, which is exactly what adoption conserves
        w.failed_ranks = {0}
        w.dead.add("rank0")
        w.sup = SimSupervisor(w, specs=self._specs(w))
        w.meta["pre"] = _concat_rows([(d_keys, _states_for(d_keys)),
                                      w.engines[1].rows()])

    def _goal_met(self, w: World) -> bool:
        asg = rb.ShardAssignment.load(w.dir)
        return asg is not None and all(o == 1 for o in asg.owners)

    def _start(self, w: World) -> None:
        def go():
            entry = w.sup.adopt_dead_span(0, 1)
            w.handoff_ids.append(entry["handoff_id"])
        w.act("supervisor", go)


# -- the exploration harness -------------------------------------------------

def _run(sc, *, crash_at=None, crash_actor=None, build_kw=None):
    """One scenario execution: setup untraced, protocol traced with
    the given crash injected.  Returns the (possibly crashed) world;
    ``world.tracer.fired`` says whether the crash point was reached."""
    w = sc.build(**(build_kw or {}))
    with w.installed():
        sc.setup(w)
        t = w.tracer
        t.enabled = True
        t.crash_at = crash_at
        t.crash_actor = crash_actor
        try:
            sc.script(w)
        except CrashNow:
            pass  # power crash: the harness reconstructs from disk
        finally:
            t.enabled = False
    return w


def explore_scenario(sc, *, quick: bool = False, modes=None,
                     build_kw=None,
                     stop_on_violation: bool = False) -> dict:
    """Exhaustively crash one scenario: every crash point of every
    mode; for power modes, every legal durable state at each point."""
    t0 = time.perf_counter()
    res = {"scenario": sc.name, "modes": [], "crash_points": 0,
           "states_explored": 0, "recoveries": 0, "violations": 0,
           "capped": False, "first_invariant": None,
           "counterexample": None}

    def record(viols, mode, crashed_op, flavor, schedule):
        res["violations"] += len(viols)
        if res["counterexample"] is None and viols:
            res["first_invariant"] = viols[0].invariant
            res["counterexample"] = CrashSchedule(
                sc.name, mode, crashed_op, flavor, schedule,
                viols[0]).render()

    base = _run(sc, build_kw=build_kw)
    base_viols = sc.judge(base)
    if base_viols:
        record(base_viols, "none", "(no crash injected)", "-",
               base.tracer.rendered())
        res["elapsed_s"] = round(time.perf_counter() - t0, 3)
        return res  # the protocol fails without any crash: stop here
    base_ops = base.tracer.ops
    for mode in (modes if modes is not None else sc.modes):
        actor = None if mode == "power" else mode
        n_pts = eligible_points(base_ops, actor)
        res["modes"].append({"mode": mode, "crash_points": n_pts})
        for p in range(n_pts):
            res["crash_points"] += 1
            w = _run(sc, crash_at=p, crash_actor=actor,
                     build_kw=build_kw)
            if not w.tracer.fired:
                continue
            if actor is None:
                states, capped = w.fs.durable_states(
                    media_fault=getattr(sc, "media_fault", False),
                    quick=quick)
                res["capped"] = res["capped"] or capped
                for flavor, st in states:
                    res["states_explored"] += 1
                    res["recoveries"] += 1
                    w2 = sc.recover_power(w, st, flavor)
                    viols = sc.judge(w2)
                    record(viols, mode, w.tracer.crashed_op, flavor,
                           w.tracer.rendered())
                    if viols and stop_on_violation:
                        res["elapsed_s"] = round(
                            time.perf_counter() - t0, 3)
                        return res
            else:
                res["recoveries"] += 1
                viols = sc.judge(w)
                record(viols, mode, w.tracer.crashed_op, "-",
                       w.tracer.rendered())
                if viols and stop_on_violation:
                    res["elapsed_s"] = round(
                        time.perf_counter() - t0, 3)
                    return res
    res["elapsed_s"] = round(time.perf_counter() - t0, 3)
    return res


# -- planted regressions -----------------------------------------------------

@contextlib.contextmanager
def plant_fsync_skipped():
    """No patch needed: the plant is ``fsync_is_noop=True`` on the sim
    fs (every durable claim a lie) — kept as a context manager so the
    plant table drives all four plants uniformly."""
    yield


@contextlib.contextmanager
def plant_prev_rotation_dropped():
    """Publish checkpoints WITHOUT retaining the incumbent at .prev —
    the retention regression only a media fault exposes."""
    orig = durable.atomic_write

    def patched(path, data, *, fsync=True, rotate_prev=None):
        orig(path, data, fsync=fsync, rotate_prev=None)

    durable.atomic_write = patched
    try:
        yield
    finally:
        durable.atomic_write = orig


@contextlib.contextmanager
def plant_spool_ack_reorder():
    """Ack HP_STAGED BEFORE the spool write lands: the deferred write
    happens at the recipient's NEXT step — after the supervisor has
    already committed the flip on the ack.  A crash in between leaves
    a committed flip whose rows exist nowhere durable."""
    orig_save = rb.save_spool
    orig_step = rb.EngineRebalancer.step
    deferred: list[tuple] = []

    def save_later(path, keys, states, **kw):
        deferred.append((path, np.asarray(keys, np.uint32).copy(),
                         np.asarray(states, np.float32).copy(),
                         dict(kw)))

    def step(self, eng):
        while deferred:
            path, keys, states, kw = deferred.pop(0)
            orig_save(path, keys, states, **kw)
        return orig_step(self, eng)

    rb.save_spool = save_later
    rb.EngineRebalancer.step = step
    try:
        yield
    finally:
        rb.save_spool = orig_save
        rb.EngineRebalancer.step = orig_step


@contextlib.contextmanager
def plant_dual_ownership_flip():
    """Reconcile stops dropping foreign rows: a donor that dies after
    the flip and reboots then KEEPS the span it no longer owns while
    the recipient holds the shipped copy — dual ownership."""
    orig = rb.EngineRebalancer.reconcile

    class _NoDrop:
        def __init__(self, eng):
            self._eng = eng

        def __getattr__(self, name):
            return getattr(self._eng, name)

        def drop_span_rows(self, shards, total_shards):
            return 0

    def patched(self, eng):
        return orig(self, _NoDrop(eng))

    rb.EngineRebalancer.reconcile = patched
    try:
        yield
    finally:
        rb.EngineRebalancer.reconcile = orig


#: plant name -> (description, scenario factory, explore kwargs,
#: patch contextmanager, control scenario name)
_PLANTS = [
    ("spool_ack_reorder",
     "HP_STAGED acked before the spool write is durable",
     HandoffScenario, {"modes": ("power", "rank1")},
     plant_spool_ack_reorder, "handoff"),
    ("fsync_skipped",
     "every fsync a no-op (the pre-durable.py publish sites)",
     FlipScenario, {"build_kw": {"fsync_is_noop": True}},
     plant_fsync_skipped, "layout_flip"),
    ("prev_rotation_dropped",
     "checkpoints published without .prev retention",
     CheckpointScenario, {},
     plant_prev_rotation_dropped, "checkpoint_rotate"),
    ("dual_ownership_flip",
     "reconcile no longer drops foreign rows after a flip",
     HandoffScenario, {"modes": ("rank0", "power")},
     plant_dual_ownership_flip, "handoff"),
]


def _check_plants(quick: bool, control_ok: dict) -> list[dict]:
    out = []
    for name, desc, factory, kw, patch, control in _PLANTS:
        with patch():
            r = explore_scenario(factory(), quick=quick,
                                 stop_on_violation=True, **kw)
        out.append({
            "plant": name,
            "description": desc,
            "caught": r["violations"] > 0,
            "caught_by": r["first_invariant"],
            "control_ok": bool(control_ok.get(control)),
            "crash_points": r["crash_points"],
            "schedule": r["counterexample"],
        })
    return out


# -- entry point -------------------------------------------------------------

def run_crash(quick: bool = False) -> dict:
    """Run the full checker: four scenarios exhaustively crashed,
    then the four planted regressions (each must be caught AND its
    unplanted control must be clean).  ``quick`` trims the torn-file
    fan-out (2 tear variants instead of 5) — same crash points, same
    protocols, a fraction of the durable states."""
    t0 = time.perf_counter()
    scenarios = [CheckpointScenario(), FlipScenario(),
                 HandoffScenario(), AdoptionScenario()]
    scen_results = [explore_scenario(sc, quick=quick)
                    for sc in scenarios]
    control_ok = {r["scenario"]: r["violations"] == 0
                  for r in scen_results}
    plants = _check_plants(quick, control_ok)
    protocols_ok = all(control_ok.values())
    plants_ok = all(p["caught"] and p["control_ok"] for p in plants)
    return {
        "schema": "fsx-crash-report-v1",
        "quick": bool(quick),
        "ok": protocols_ok and plants_ok,
        "protocols_ok": protocols_ok,
        "plants_ok": plants_ok,
        "invariants": dict(INVARIANTS),
        "scenarios": scen_results,
        "plants": plants,
        "totals": {
            "crash_points": sum(r["crash_points"]
                                for r in scen_results),
            "states_explored": sum(r["states_explored"]
                                   for r in scen_results),
            "recoveries": sum(r["recoveries"] for r in scen_results),
            "violations": sum(r["violations"] for r in scen_results),
        },
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
