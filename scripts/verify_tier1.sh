#!/usr/bin/env bash
# Tier-1 verification gate — the EXACT invocation from ROADMAP.md, so
# the builder, CI, and any reviewer run the same thing.  Keep this in
# lockstep with the "Tier-1 verify" line in ROADMAP.md; if they ever
# disagree, ROADMAP.md wins and this file is the bug.
#
# Usage: scripts/verify_tier1.sh                (from anywhere)
#        scripts/verify_tier1.sh --sanitizers   (ALSO run the opt-in
#            C-plane sanitizer stage first: the daemon's TSAN shm-ring
#            torture plus ASan/UBSan builds+runs of kern/host_test,
#            kern/prop_driver and an fsxd --sim smoke)
# Always-on pre-stages (each failure exits early, before pytest):
#   * scripts/lint.py — syntax, unused-import, local-import,
#     device-loop-purity and sync_contracts gates
#   * fsx sync        — host thread contracts + bounded-interleaving
#     model checks (arena bound tightness re-proved per run); writes
#     artifacts/SYNC_r13.json
#   * fsx crash       — exhaustive crash-consistency model check of
#     the durable-state protocols (planted regressions must be
#     caught); writes artifacts/CRASH_r21.json
#   * fsx audit       — static dtype/donation/transfer/retrace/
#     collective/in-place contracts over every staged step variant (8
#     virtual CPU devices so the sharded variant stages too); writes
#     the machine-readable artifacts/AUDIT_r08.json byte-budget
#     artifact
#   * fsx ranges      — whole-pipeline integer value-range proof over
#     the same staged variants (+ the WRAP_OK staleness audit, the
#     planted negative controls and the BPF<->jaxpr containment
#     bridge); writes artifacts/RANGES_r16.json
# Exit code: pytest's (a pre-stage failure exits early).  Prints
# DOTS_PASSED=<n> as a tamper-evident passed-test count derived from
# the progress dots, not the summary.
set -u
cd "$(dirname "$0")/.."

if [ "${1:-}" = "--sanitizers" ]; then
    shift
    echo "== sanitizers: daemon TSAN torture (shm-ring protocol) =="
    make -C daemon tsan || exit 1

    SAN="-fsanitize=address,undefined -fno-sanitize-recover=all -O1 -g"
    export ASAN_OPTIONS=detect_leaks=1

    echo "== sanitizers: kern/host_test under ASan+UBSan =="
    mkdir -p kern/build
    gcc $SAN -Wall -Wextra -Werror -DFSX_HOST_BUILD -Ikern \
        kern/host_test.c -o kern/build/host_test_asan -lm || exit 1
    kern/build/host_test_asan || exit 1

    echo "== sanitizers: kern/prop_driver under ASan+UBSan =="
    gcc $SAN -Wall -Wextra -Werror -DFSX_HOST_BUILD -Ikern \
        kern/prop_driver.c -o kern/build/prop_driver_asan || exit 1
    # tiny smoke trace: fixed-window limiter, 3 aggregated ticks
    printf '0 100 1000000 1000000000 200 200 0 0\n3\n1 100 0\n200 20000 500000000\n1 100 2000000000\n' \
        | kern/build/prop_driver_asan > /dev/null || exit 1

    echo "== sanitizers: fsxd --sim smoke under ASan+UBSan =="
    mkdir -p daemon/build
    g++ $SAN -std=c++17 -Wall -Wextra -Werror -Ikern \
        daemon/fsxd.cpp -o daemon/build/fsxd_asan -lpthread || exit 1
    daemon/build/fsxd_asan --sim --duration 2 --rate 2e5 \
        --feature-ring /tmp/fsx_t1_asan_ring \
        --verdict-ring /tmp/fsx_t1_asan_verdicts > /dev/null || exit 1
    rm -f /tmp/fsx_t1_asan_ring /tmp/fsx_t1_asan_verdicts
    echo "== sanitizers: all clean =="
fi

echo "== lint gate (scripts/lint.py) =="
python scripts/lint.py || exit 1

echo "== fsx sync: host thread contracts + interleaving model checks =="
# The host-plane leg of the static suite (docs/CONCURRENCY.md):
# re-proves every registered thread contract over the real source,
# runs the bounded-interleaving model checker on the real protocol
# objects (SinkChannel crash atomicity, SealedBatchQueue wraparound),
# and re-proves the arena reuse bound TIGHT — all interleavings pass
# at depth+ring+1 slots, a staged-copy-overwrite counterexample is
# emitted one below.  Jax-free; writes the machine-readable artifact.
python -m flowsentryx_tpu.cli sync --out artifacts/SYNC_r13.json \
    || exit 1

echo "== fsx crash: crash-consistency model check of the durable protocols =="
# The fifth static leg (docs/CRASH.md): drives the REAL checkpoint-
# rotate, layout-flip, fenced-handoff and dead-span-adoption code over
# a simulated POSIX fs, crashing at every atomic step (power loss +
# each party's death), reconstructing every legal post-crash durable
# state, running real recovery, and asserting the ten-invariant
# catalog (row conservation, single ownership, generation
# monotonicity, checkpoint fallback, ...).  Four planted regressions
# must each be CAUGHT with a printed crash schedule and their
# unplanted controls must be clean.  Jax-free; --quick trims tear
# variants per un-synced file (full fan-out stays on `fsx crash`).
python -m flowsentryx_tpu.cli crash --quick --quiet-plants \
    --out artifacts/CRASH_r21.json || exit 1

echo "== fsx live: liveness + progress model check of the blocking protocols =="
# The sixth static leg (docs/LIVENESS.md): state-graph search over the
# REAL protocol objects proving deadlock-freedom (every park names its
# wake edge), livelock-freedom under weak fairness and bounded
# starvation — the SinkChannel drain, the fenced handoff with a stamp
# dropped at every edge (a lost fence-lift must recover, not wedge),
# autoscale flap-freedom, shed deferral bounds, quiesce termination —
# plus the PROGRESS registry audit closing every blocking loop over
# its declared wake source.  Four planted regressions (deleted notify,
# dropped fence-lift, removed streak cap, zeroed cooldown) must each
# be CAUGHT with a printed schedule from clean controls.  Jax-free;
# --quick trims the handoff drop-edge fan-out (full set on `fsx live`).
python -m flowsentryx_tpu.cli live --quick --quiet-plants \
    --out artifacts/LIVE_r23.json || exit 1

echo "== fsx live: jax-free import path =="
# The liveness leg rides the supervisor's sub-second import path: the
# whole flowsentryx_tpu.live package plus the cluster plane it drives
# must import without pulling jax (the same contract the
# cluster_jax_free lint stage proves for cluster/ module levels).
python - <<'PY' || exit 1
import sys, time
t0 = time.perf_counter()
import flowsentryx_tpu.live.checker  # noqa: F401
import flowsentryx_tpu.cluster.supervisor  # noqa: F401
dt = time.perf_counter() - t0
assert "jax" not in sys.modules, "fsx live import path pulled jax"
assert dt < 1.0, f"cluster+live import took {dt:.2f}s (budget 1.0s)"
print(f"live+cluster import: {dt*1000:.0f} ms, jax-free")
PY

echo "== fsx audit: static step-graph contracts (docs/AUDIT.md) =="
# --device-loop 2 also stages the drain-ring deep scans (single-device
# and sharded) so the 528 B-per-slot wire pin and the ring-carry
# donation proof are re-proved on every run.
env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m flowsentryx_tpu.cli audit --mesh 8 --mega 2 \
    --device-loop 2 --out artifacts/AUDIT_r08.json || exit 1

echo "== fsx audit: eviction-epoch step variants (quick shapes) =="
# The in-step aging sweep changes every staged graph (a rolling
# gather + victim-only-scatter window at step start), so the
# eviction-enabled family is audited as its own artifact set: donation
# through the sweep, the 528 B wire pin, and the unchanged collective
# census (the eviction count rides the existing stats psum) are
# re-proved each run.
env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m flowsentryx_tpu.cli audit --mesh 8 --mega 2 \
    --device-loop 2 --evict-ttl 30 --quick \
    --out artifacts/AUDIT_evict_r12.json || exit 1

echo "== fsx ranges: whole-pipeline integer value-range proof =="
# The fourth static leg (docs/RANGES.md): interval abstract
# interpretation over every staged variant — singles, sharded, every
# rung of the adaptive mega ladder, the drain-ring deep scan, the
# eviction-epoch family (--evict-ttl stages the rolling-window
# batches-counter arithmetic) — proving no equation can silently wrap
# modulo the audited WRAP_OK registry (staleness-checked per run).
# Also re-proves the planted negative controls fire and the BPF<->jaxpr
# interval-containment bridge on the shipped distill artifact.
env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m flowsentryx_tpu.cli ranges --mesh 8 --mega auto \
    --device-loop 2 --evict-ttl 30 --quick \
    --out artifacts/RANGES_r16.json || exit 1

echo "== table-scale smoke: eviction + occupancy bound + shard-local rows =="
# Bounded CPU smoke of the production flow table: re-proves that the
# eviction epoch fires under churn, occupancy stays bounded at the
# live-flow count, every occupied key is resident on its owner shard,
# and a mesh=4 checkpoint reshards losslessly into mesh=8 — rewriting
# the "smoke" section of artifacts/TABLESCALE_r12.json (the paced
# 4M-row drain/ladder evidence in the same file is preserved).
env JAX_PLATFORMS=cpu python scripts/table_scale_smoke.py || exit 1

echo "== fsx distill: kernel-tier compile + static check + JAX<->BPF parity =="
# Compiles the shipped artifact into the kernel tier, statically
# verifies both --ml program variants, and proves bit-exact band
# parity by EXECUTING the emitted scorer bytecode over a 10k-vector
# corpus (docs/DISTILL.md); rewrites artifacts/DISTILL_r10.json.
env JAX_PLATFORMS=cpu python -m flowsentryx_tpu.cli distill \
    artifacts/logreg_int8.npz --check --emulate \
    --report artifacts/DISTILL_r10.json || exit 1

echo "== dispatch smoke: single-copy staging + adaptive coalescing =="
# Bounded CPU smoke of the zero-copy dispatch pipeline: proves
# host copies/batch == 1.0 (shm slot view -> arena -> device) and that
# adaptive grouping fires, re-writing the "smoke" section of
# artifacts/DISPATCH_r09.json (the paced PR-4 comparison evidence in
# the same file is preserved).
env JAX_PLATFORMS=cpu python scripts/dispatch_smoke.py || exit 1

echo "== cluster smoke: 2-engine drain + gossip + kill/restart =="
# Bounded CPU smoke of the coordinator-less scale-out (docs/
# CLUSTER.md): two supervised engine processes each drain their own
# prefilled ring shard losslessly (per-rank counts), their blacklists
# gossip-converge to byte-identical digests under the shared t0
# epoch, and one SIGKILL'd engine is restarted from its checkpoint
# while the survivor keeps serving — re-writing the "smoke" section
# of artifacts/CLUSTER_r14.json (the paced 2-engine-vs-single
# scaling evidence in the same file is preserved).
env JAX_PLATFORMS=cpu python scripts/cluster_smoke.py || exit 1

echo "== rebalance smoke: live shard handoff + autoscale grow + mid-ship kill =="
# The elastic-fleet gate (docs/CLUSTER.md §elastic): a 3-rank-
# provisioned fleet (2 live) moves shard 2 between engines UNDER LIVE
# LOAD through the full fence->ship->stage->flip protocol with exact
# row conservation (donor rows_shipped == recipient rows_adopted,
# CRC-sealed byte identity) and nonzero survivor throughput; an
# ElasticPolicy grows the fleet 2->3 off the real ring-cursor backlog
# signal (hysteresis-confirmed, decision logged with its signal
# vector) and the new rank serves its moved span; a donor SIGKILLed
# mid-ship aborts cleanly (nothing moves), respawns gen-1 from its
# checkpoint, and the RETRY conserves exactly — rewriting
# artifacts/REBALANCE_r20.json each run.
env JAX_PLATFORMS=cpu python scripts/rebalance_smoke.py || exit 1

echo "== net smoke: multi-host gossip transport on loopback =="
# The network leg of the gossip plane (docs/CLUSTER.md §multi-host):
# two simulated hosts with epochs 250 s apart drain verdict streams
# losslessly over real UDP (digests converge byte-identically on the
# canonical rebased form; a sampled absolute expiry survives the
# rebase within f32 quantization), a partition is injected and healed
# (anti-entropy re-converges within a bounded tick count, pinned),
# a dead peer host is detected by the federation beacons, and the
# u64 sequence split crosses the 2^32 word boundary intact on BOTH
# transports.  ~2 s; rewrites artifacts/NET_r19.json.  (The transport
# itself is jax-free; the GossipPlane merge path pulls the writeback
# decoder's jax import chain, hence the cpu pin.)
env JAX_PLATFORMS=cpu python scripts/net_smoke.py || exit 1

echo "== chaos smoke: seeded fault-injection campaign + planted regressions =="
# The robustness gate (docs/CHAOS.md): the seeded quick campaign over
# the REAL stack — supervised rank kill/respawn, crash-loop park with
# backoff, corrupt/truncated checkpoint refusal + loud .prev fallback
# on a live engine, shm slot corruption (bad magic/seq gap) skipped
# and counted, poisoned-batch quarantine (counted + spooled), gossip
# stall/flood drop accounting, clock jumps, the wedged-sink watchdog
# trip, and the six network faults over real loopback UDP (partition,
# heal, reorder, duplication, loss burst, lying epoch) — every
# invariant green AND all five planted regressions (split-atomicity,
# CRC skipped, backoff removed, dup-suppression removed, epoch-rebase
# skipped) caught by their named invariants.  Rewrites
# artifacts/CHAOS_r17.json each run.
env JAX_PLATFORMS=cpu python scripts/chaos_smoke.py || exit 1

echo "== latency smoke: seal->verdict plane + SLO degradation =="
# Bounded CPU smoke of the per-record latency plane (docs/ENGINE.md
# §latency): re-proves the seal/launch/sink stamps are monotone
# (negatives == 0), the HDR percentile chain is finite and ordered
# with every record accounted, --slo-us keeps stats/blacklist
# byte-identical while provably degrading the ladder under a breached
# budget, and warm() seeds the per-rung EWMA table — re-writing the
# "smoke" section of artifacts/LATENCY_r15.json (the paced pulse-wave
# A/B evidence in the same file is preserved).
env JAX_PLATFORMS=cpu python scripts/latency_smoke.py || exit 1

echo "== predict smoke: burst forecast + pre-warm + pressure shedding =="
# Bounded CPU smoke of the predictive dispatch governor (docs/ENGINE.md
# §prediction): re-proves the forecaster goes confident on the pulse
# schedule, a pre-warm was issued AND hit, the forecast-end early
# flush fired, gossip anti-entropy was deferred under measured budget
# pressure (and ONLY then — the quiescent high-budget control leg
# actuates nothing and defers nothing), the latency plane stays sound
# (negatives == 0), and the fsx sync registry is clean — re-writing
# the "smoke" section of artifacts/PREDICT_r22.json (the paced A/B
# evidence in the same file is preserved).
env JAX_PLATFORMS=cpu python scripts/predict_smoke.py || exit 1

echo "== device-loop smoke: drain ring + double-buffered H2D =="
# Bounded CPU smoke of the device-resident drain ring: re-proves that
# full deep-scan rounds fire, copies/batch stays 1.0, and H2D overlap
# (uploads issued while a round is in flight) is > 0, re-writing the
# "smoke" section of artifacts/DEVLOOP_r11.json (the paced PR-6
# comparison evidence in the same file is preserved).
env JAX_PLATFORMS=cpu python scripts/device_loop_smoke.py || exit 1

echo "== boot smoke: persistent compile cache + tiered warm + GROW spare =="
# Bounded CPU smoke of boot-to-serving (docs/ENGINE.md §boot), each leg
# a FRESH subprocess: re-proves a cold boot stores the full ladder, a
# cached boot is all-cache-hit and reaches SERVING >= 3x faster, the
# tiered background fill completes with nothing pending, a GROW spare
# booting from a prewarm_main-filled cache recompiles NOTHING, and all
# legs serve byte-identical verdicts (stats + blacklist digests equal)
# — re-writing the "smoke" section of artifacts/BOOT_r24.json (the
# cold-vs-cached A/B evidence in the same file is preserved).
env JAX_PLATFORMS=cpu python scripts/boot_smoke.py || exit 1

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
