"""The host concurrency plane's static suite (`fsx sync`,
docs/CONCURRENCY.md): the thread-contract checker over the real repo
AND over planted violations of every contract class, the bounded
interleaving model checker (positives + planted negatives + the arena
bound tightness proof), the shared tuning table, and the unified
crash-propagation path for every worker type."""

import ast
import threading

import pytest

from flowsentryx_tpu.sync import contracts, tuning
from flowsentryx_tpu.sync.channel import SinkChannel, WorkerCrash
from flowsentryx_tpu.sync.contracts import (
    ClassPlan,
    CursorPlan,
    FieldContract,
    check_class,
    check_ctl,
    check_cursors,
    run_contracts,
)


# ---------------------------------------------------------------------------
# thread-contract checker: the real repo
# ---------------------------------------------------------------------------

class TestContractsOnRepo:
    def test_repo_passes_clean(self):
        rep = run_contracts()
        assert rep.ok, "\n".join(str(f) for f in rep.findings)
        assert rep.stats["classes"] >= 3
        assert rep.stats["registered_fields"] >= 40
        assert rep.stats["cursor_classes"] == 4
        assert rep.stats["ctl_sites"] > 0

    def test_quick_mode_runs_same_checks(self):
        rep = run_contracts(quick=True)
        assert rep.ok and rep.stats["quick"] is True

    def test_every_ctl_field_has_one_writer_side(self):
        # the SealedBatchQueue ctl block's documented one-writer rule
        # is fully covered by the declaration table
        from flowsentryx_tpu.core import schema

        declared = set(contracts.CTL_WRITERS)
        assert declared == {"hbeat", "first_ts", "t0", "stop",
                            "wstate", "emit_drop", "spin_us", "idle_us",
                            # cluster status block (PR 10): engine line
                            "c_hbeat", "c_state", "c_batches", "c_records",
                            # supervisor line (c_t0_wall: ISSUE 15,
                            # the monotonic epoch's wall twin)
                            "c_stop", "c_gen", "c_t0", "c_t0_wall",
                            # rebalance plane (ISSUE 16): the engine
                            # ack line vs the supervisor fence line
                            "c_pid", "c_handoff", "c_layout_ack",
                            "c_layout_gen", "c_fence"}
        for name in declared:
            if name.startswith("c_"):
                # cluster status-block fields live in the STATUS_*
                # layout (cluster/mailbox.py StatusBlock)
                assert hasattr(schema, f"STATUS_{name[2:].upper()}_OFFSET")
            else:
                assert hasattr(schema, f"SHM_{name.upper()}_OFFSET")


# ---------------------------------------------------------------------------
# thread-contract checker: planted violations, one per contract class
# ---------------------------------------------------------------------------

def _plan(fields, **kw):
    return ClassPlan(module="planted.py", cls="C", fields=fields, **kw)


def _check(src, plan):
    return check_class(ast.parse(src), "planted.py", plan)


class TestPlantedViolations:
    def test_dispatch_field_touched_from_worker(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        t = threading.Thread(target=self._worker)\n"
            "        t.start()\n"
            "        self._staged += 1\n"
            "    def _worker(self):\n"
            "        self._staged = 0\n")
        out = _check(src, _plan(
            {"_staged": FieldContract("dispatch", "dispatch-owned")},
            worker_targets=("_worker",)))
        assert len(out) == 1
        f = out[0]
        assert f.contract == "discipline" and f.line == 8
        assert "C._worker" in f.where and "_staged" in f.reason
        assert "planted.py" in str(f) and ":8:" in str(f)

    def test_worker_context_propagates_through_calls(self):
        # the violation hides one call deep: the checker must flood the
        # worker context through the intra-class call graph
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._worker).start()\n"
            "    def _worker(self):\n"
            "        self._helper()\n"
            "    def _helper(self):\n"
            "        self._staged += 1\n")
        out = _check(src, _plan(
            {"_staged": FieldContract("dispatch", "dispatch-owned")},
            worker_targets=("_worker",)))
        assert [f.line for f in out] == [8]

    def test_cv_field_accessed_unlocked(self):
        src = (
            "class C:\n"
            "    def good(self):\n"
            "        with self.cv:\n"
            "            self._q.append(1)\n"
            "    def bad(self):\n"
            "        self._q.append(1)\n")
        out = _check(src, _plan(
            {"_q": FieldContract("cv", "queue")}, lock_attr="cv"))
        assert len(out) == 1
        assert out[0].line == 6 and "outside" in out[0].reason

    def test_cv_write_allows_unlocked_read(self):
        src = (
            "class C:\n"
            "    def read(self):\n"
            "        return self._pending\n"
            "    def bad_write(self):\n"
            "        self._pending += 1\n")
        out = _check(src, _plan(
            {"_pending": FieldContract("cv-write", "count")},
            lock_attr="cv"))
        assert len(out) == 1
        assert out[0].line == 5 and "WRITTEN" in out[0].reason

    def test_atomic_ref_rejects_read_modify_write(self):
        src = (
            "class C:\n"
            "    def swap(self, p):\n"
            "        self.params = p\n"        # plain rebind: legal
            "    def bad(self):\n"
            "        self.params['w'] = 0\n")  # item store: racy
        out = _check(src, _plan(
            {"params": FieldContract("atomic-ref", "hot swap")}))
        assert len(out) == 1
        assert out[0].line == 5
        assert "read-modify-write" in out[0].reason

    def test_quiescent_write_outside_quiescent_set(self):
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self._active = False\n"
            "    def serve(self):\n"
            "        self._active = True\n")
        out = _check(src, _plan(
            {"_active": FieldContract("quiescent-write", "mode flag")},
            quiescent=("__init__",)))
        assert len(out) == 1 and out[0].line == 5

    def test_section_field_touched_outside_section(self):
        src = (
            "class C:\n"
            "    def _launch(self):\n"
            "        self.table = 1\n"
            "    def elsewhere(self):\n"
            "        self.table = 2\n")
        out = _check(src, _plan(
            {"table": FieldContract("section:launch", "device carry")},
            sections={"launch": ("_launch",)}))
        assert len(out) == 1
        assert out[0].line == 5 and "'launch' section" in out[0].reason

    def test_unregistered_shared_state_detected(self):
        # mutated under BOTH contexts with no registry entry: the
        # registry-rot guard the tentpole requires
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._worker).start()\n"
            "        self._count += 1\n"
            "    def _worker(self):\n"
            "        self._count += 1\n")
        out = _check(src, _plan({}, worker_targets=("_worker",)))
        assert len(out) == 1
        f = out[0]
        assert f.contract == "unregistered"
        assert "_count" in f.reason and "no sync-registry entry" in f.reason
        assert f.line == 7  # points at the worker-reachable half

    def test_single_context_mutation_not_flagged(self):
        src = (
            "class C:\n"
            "    def a(self):\n"
            "        self._count = 1\n"
            "    def b(self):\n"
            "        self._count += 1\n")
        assert _check(src, _plan({})) == []

    def test_undeclared_thread_target(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._rogue).start()\n"
            "    def _rogue(self):\n"
            "        pass\n")
        out = _check(src, _plan({}))
        assert len(out) == 1
        assert out[0].contract == "registry"
        assert "_rogue" in out[0].reason

    def test_stale_registry_entries_are_findings(self):
        src = "class C:\n    def a(self):\n        self._x = 1\n"
        out = _check(src, _plan(
            {"_x": FieldContract("dispatch", "x"),
             "_ghost": FieldContract("dispatch", "gone")},
            worker_targets=("_no_such_worker",),
            quiescent=("_no_such_quiescent",),
            sections={"s": ("_no_such_member",)}))
        reasons = "\n".join(f.reason for f in out)
        assert "declared thread target does not exist" in reasons
        assert "never accessed" in reasons
        assert "missing method" in reasons
        assert "quiescent list names a missing method" in reasons

    def test_missing_class_is_a_finding(self):
        out = _check("class Other:\n    pass\n", _plan({}))
        assert out and out[0].contract == "registry"

    def test_extra_grant_silences(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._worker).start()\n"
            "    def _worker(self):\n"
            "        return self._staged\n")
        plan = _plan(
            {"_staged": FieldContract("dispatch", "x",
                                      extra=("_worker",))},
            worker_targets=("_worker",))
        assert [f for f in _check(src, plan)
                if f.contract == "discipline"] == []


class TestCursorAndCtlViolations:
    def test_tail_store_on_producer_side(self):
        # queue-cursor misuse: the producer releasing slots would let
        # it overwrite unread records
        src = (
            "class Q:\n"
            "    def produce(self, n):\n"
            "        self._head[0] = n\n"
            "        self._tail[0] = n\n"
            "    def consume(self, n):\n"
            "        self._tail[0] = n\n")
        out = check_cursors(ast.parse(src), "planted.py", CursorPlan(
            module="planted.py", cls="Q",
            producer=("produce",), consumer=("consume",)))
        assert len(out) == 1
        f = out[0]
        assert f.contract == "cursor" and f.line == 4
        assert "tail cursor stored outside the consumer side" in f.reason

    def test_head_store_on_consumer_side(self):
        src = (
            "class Q:\n"
            "    def consume(self, n):\n"
            "        self._head[0] = n\n")
        out = check_cursors(ast.parse(src), "planted.py", CursorPlan(
            module="planted.py", cls="Q",
            producer=("produce",), consumer=("consume",)))
        assert len(out) == 1 and "head cursor" in out[0].reason

    def test_repo_shm_obeys_cursor_plans(self):
        from pathlib import Path

        root = Path(contracts.__file__).resolve().parents[2]
        for plan in contracts.CURSORS:
            tree = ast.parse((root / plan.module).read_text())
            assert check_cursors(
                tree, plan.module, plan) == []

    def test_undeclared_ctl_field(self):
        src = "def f(q):\n    q.ctl_set('rogue_field', 1)\n"
        out = check_ctl(ast.parse(src), "planted.py", "worker")
        assert len(out) == 1 and "UNDECLARED" in out[0].reason

    def test_ctl_write_from_wrong_side(self):
        src = "def f(q):\n    q.ctl_set('hbeat', 1)\n"  # worker-owned
        out = check_ctl(ast.parse(src), "planted.py", "engine")
        assert len(out) == 1
        assert "hbeat" in out[0].reason and "worker-written" in out[0].reason

    def test_ctl_write_with_no_declared_side(self):
        src = "def f(q):\n    q.ctl_set('stop', 1)\n"
        out = check_ctl(ast.parse(src), "planted.py", None)
        assert len(out) == 1 and "no declared writer side" in out[0].reason

    # -- cluster plane (PR 10): planted negatives -----------------------

    def test_cluster_supervisor_field_written_from_engine(self):
        # an engine writing the supervisor-owned restart generation
        # would forge its own restart epoch — two writers on the
        # plain-store lifecycle line
        src = "def f(sb):\n    sb.ctl_set('c_gen', 2)\n"
        out = check_ctl(ast.parse(src), "planted.py", "cluster-engine")
        assert len(out) == 1
        assert "c_gen" in out[0].reason
        assert "supervisor-written" in out[0].reason

    def test_cluster_mailbox_tail_store_on_publish_side(self):
        # gossip-mailbox misuse: the publisher releasing slots would
        # let it overwrite verdict wires the peer has not merged yet
        src = (
            "class M:\n"
            "    def publish(self, n):\n"
            "        self._head[0] = n\n"
            "        self._tail[0] = n\n"
            "    def pop_wires(self, n):\n"
            "        self._tail[0] = n\n")
        out = check_cursors(ast.parse(src), "planted.py", CursorPlan(
            module="planted.py", cls="M",
            producer=("publish",), consumer=("pop_wires",)))
        assert len(out) == 1
        assert "tail cursor stored outside the consumer side" \
            in out[0].reason


# ---------------------------------------------------------------------------
# the tuning table
# ---------------------------------------------------------------------------

class TestNetRegistry:
    """ISSUE 15 satellite: the transport's contracts — owner sections
    for the NetMailbox (publish=queue_tx only, merge=everything
    network-facing), the cross-section handoff deque, the epoch-rebase
    fields, and the c_t0_wall writer side — with one planted negative
    per new discipline."""

    def test_netmailbox_plan_pins_expected_disciplines(self):
        plan = contracts.NETMAILBOX_PLAN
        assert plan.sections["publish"] == ("queue_tx",)
        assert "pump" in plan.sections["merge"]
        assert "_accept" in plan.sections["merge"]
        f = plan.fields
        assert f["txq_dropped"].discipline == "section:publish"
        assert f["_outq"].discipline == "documented"
        for merge_field in ("_sock", "_tx_seq", "_own_map", "net_map",
                            "_rx_state", "_ready", "epoch_skew_max",
                            "epoch_skew_dropped", "rx_gap", "rx_dup",
                            "reorder_evict"):
            assert f[merge_field].discipline == "section:merge", \
                merge_field
        assert f["peers"].discipline == "quiescent-write"
        # the engine plane registers its net leg
        assert contracts.GOSSIP_PLAN.fields["net"].discipline \
            == "documented"

    def test_planted_publish_counter_written_from_merge_side(self):
        # txq_dropped belongs to the publish section alone: a pump-side
        # bump would be a second writer racing the sink section
        src = (
            "class C:\n"
            "    def queue_tx(self):\n"
            "        self._txq += 1\n"
            "    def pump(self):\n"
            "        self._txq += 1\n")
        out = _check(src, _plan(
            {"_txq": FieldContract("section:publish", "drops")},
            sections={"publish": ("queue_tx",), "merge": ("pump",)}))
        assert [f.line for f in out] == [5]
        assert "publish" in out[0].reason

    def test_planted_canonical_map_written_from_publish_side(self):
        # net_map (the canonical rebased map) is merge-owned: folding
        # it at queue_tx time would race the rx fold
        src = (
            "class C:\n"
            "    def queue_tx(self):\n"
            "        self.net_map[1] = 2\n"
            "    def pump(self):\n"
            "        self.net_map[1] = 2\n")
        out = _check(src, _plan(
            {"net_map": FieldContract("section:merge",
                                      "canonical map")},
            sections={"publish": ("queue_tx",), "merge": ("pump",)}))
        assert [f.line for f in out] == [3]
        assert "merge" in out[0].reason

    def test_planted_peer_table_written_while_serving(self):
        # peers is quiescent-write: a merge-side mutation would race
        # the publish side's... nothing mechanical guards it but the
        # quiescent rule — which is exactly what must flag it
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self.peers = {}\n"
            "    def add_peer(self, k, a):\n"
            "        self.peers[k] = a\n"
            "    def pump(self):\n"
            "        self.peers[1] = ('x', 2)\n")
        out = _check(src, _plan(
            {"peers": FieldContract("quiescent-write", "peer table")},
            quiescent=("__init__", "add_peer")))
        assert [f.line for f in out] == [7]

    def test_repo_netmailbox_obeys_its_plan(self):
        rep = run_contracts()
        assert not [f for f in rep.findings
                    if "transport" in f.path]

    def test_ctl_t0_wall_is_supervisor_written(self):
        assert contracts.CTL_WRITERS["c_t0_wall"] == "supervisor"
        # a cluster-engine-side write of the wall epoch would be a
        # second writer on a supervisor-owned TSO field
        src = "st.ctl_set('c_t0_wall', 5)\n"
        out = check_ctl(ast.parse(src), "planted.py",
                        "cluster-engine")
        assert len(out) == 1 and "supervisor" in out[0].reason


class TestRebalanceRegistry:
    """ISSUE 16 satellite: the elastic plane's contracts — the
    EngineRebalancer's dispatch-owned handoff state, the ElasticPolicy
    decision state, the HandoffMailbox SPSC cursors, and the five new
    ctl lines split engine-ack vs supervisor-fence — with one planted
    negative per new discipline."""

    def test_rebalance_plans_pin_expected_disciplines(self):
        rb = contracts.REBALANCE_PLAN
        assert rb.cls == "EngineRebalancer"
        for f in ("_acked_gen", "_fence_seen", "_staged", "_receiver",
                  "_mbx"):
            assert rb.fields[f].discipline == "dispatch", f
        el = contracts.ELASTIC_PLAN
        assert el.cls == "ElasticPolicy"
        for f in ("_streak", "_cooldown_until", "suppressed",
                  "decisions"):
            assert el.fields[f].discipline == "dispatch", f
        # the engine plane registers its rebalance counter line
        assert contracts.ENGINE_PLAN.fields["_rebalance"].discipline \
            == "dispatch"

    def test_planted_rebalancer_state_written_from_worker(self):
        # a worker thread staging handoff rows would race the serving
        # loop's reconcile/step — _staged is dispatch-owned
        src = (
            "class C:\n"
            "    def step(self):\n"
            "        self._staged = None\n"
            "    def run(self):\n"
            "        self._staged = 1\n")
        out = _check(src, _plan(
            {"_staged": FieldContract("dispatch", "staged rows")},
            worker_targets=("run",)))
        assert [f.line for f in out] == [5]

    def test_planted_fence_stamped_from_engine_side(self):
        # only the supervisor stamps the fence: an engine stamping its
        # own fence could unfence itself mid-commit and serve a
        # half-flipped route
        assert contracts.CTL_WRITERS["c_fence"] == "supervisor"
        src = "def f(st):\n    st.ctl_set('c_fence', 0)\n"
        out = check_ctl(ast.parse(src), "planted.py", "cluster-engine")
        assert len(out) == 1 and "supervisor" in out[0].reason

    def test_planted_layout_ack_forged_by_supervisor(self):
        # the ack line is the ENGINE's proof it observed the flip; the
        # supervisor acking for a rank would lift the fence without
        # convergence
        assert contracts.CTL_WRITERS["c_layout_ack"] == "cluster-engine"
        src = "def f(st):\n    st.ctl_set('c_layout_ack', 2)\n"
        out = check_ctl(ast.parse(src), "planted.py", "supervisor")
        assert len(out) == 1 and "cluster-engine" in out[0].reason

    def test_planted_handoff_mailbox_consumer_stores_head(self):
        # the SPSC rule on the handoff stream: the recipient storing
        # the head cursor would republish slots under the donor
        src = (
            "class M:\n"
            "    def _publish(self, n):\n"
            "        self._head[0] = n\n"
            "    def pop_slots(self, n):\n"
            "        self._head[0] = n\n"
            "        self._tail[0] = n\n")
        out = check_cursors(ast.parse(src), "planted.py", CursorPlan(
            module="planted.py", cls="M",
            producer=("_publish",), consumer=("pop_slots",)))
        assert len(out) == 1
        assert "head cursor stored outside the producer side" \
            in out[0].reason

    def test_repo_rebalance_obeys_its_plan(self):
        rep = run_contracts()
        assert not [f for f in rep.findings
                    if "rebalance" in f.path or "elastic" in f.path]

    def test_rebalance_module_is_engine_side(self):
        assert contracts.CTL_MODULE_SIDE[
            "flowsentryx_tpu/cluster/rebalance.py"] == "cluster-engine"


class TestTuningTable:
    def test_engine_and_ingest_reference_the_table(self):
        from flowsentryx_tpu.ingest import worker

        assert worker.IDLE_SLEEP_S == tuning.IDLE_SLEEP_S
        assert worker.EMIT_STOP_TIMEOUT_S == tuning.EMIT_STOP_TIMEOUT_S
        # the engine sources import the module (not copied literals)
        import flowsentryx_tpu.engine.engine as eng_mod

        assert eng_mod.tuning is tuning

    def test_values_are_the_measured_ones(self):
        assert tuning.GIL_YIELD_S == 20e-6
        assert tuning.IDLE_SLEEP_S == 200e-6
        assert tuning.SPIN_US_DEFAULT == 150
        assert tuning.EMIT_STOP_TIMEOUT_S == 2.0

    def test_jax_free(self):
        import sys
        import subprocess

        r = subprocess.run(
            [sys.executable, "-c",
             "import sys; import flowsentryx_tpu.sync.contracts; "
             "import flowsentryx_tpu.sync.interleave; "
             "import flowsentryx_tpu.sync.tuning; "
             "sys.exit(1 if 'jax' in sys.modules else 0)"],
            capture_output=True)
        assert r.returncode == 0, r.stderr.decode()


# ---------------------------------------------------------------------------
# the model checker
# ---------------------------------------------------------------------------

class TestExploreFramework:
    def test_finds_a_classic_lost_update(self):
        from flowsentryx_tpu.sync.interleave import (
            ModelViolation, explore)

        def mk():
            box = [0]

            def racer(name):
                yield f"{name}:read"
                v = box[0]
                yield f"{name}:write"
                box[0] = v + 1

            def finale():
                if box[0] != 2:
                    raise ModelViolation(f"lost update: {box[0]}")

            return ([("a", racer("a")), ("b", racer("b"))], finale)

        res = explore("lost_update", mk, expect_violation=True)
        assert res.ok and res.counterexample is not None
        assert "lost update" in res.counterexample.detail

    def test_expect_marker_pins_the_bug_class(self):
        # a negative demo must not stay green on an UNRELATED
        # violation (e.g. a workload deadlock): only a counterexample
        # carrying the expected marker counts
        from flowsentryx_tpu.sync.interleave import (
            ModelViolation, explore)

        def mk():
            def t():
                yield "boom"
                raise ModelViolation("some other defect")

            return ([("t", t())], None)

        hit = explore("neg", mk, expect_violation=True,
                      expect_marker="some other defect")
        assert hit.ok
        miss = explore("neg", mk, expect_violation=True,
                       expect_marker="the intended bug")
        assert not miss.ok
        # the non-matching counterexample is still surfaced for debug
        assert "some other defect" in miss.counterexample.detail

    def test_deadlock_is_reported(self):
        from flowsentryx_tpu.sync.interleave import explore

        def mk():
            def stuck():
                yield (lambda: False, "never")

            return ([("t", stuck())], None)

        res = explore("deadlock", mk)
        assert not res.ok
        assert "deadlock" in res.counterexample.detail

    def test_exhaustive_count_is_exact(self):
        from flowsentryx_tpu.sync.interleave import explore

        def mk():
            def t(name, n):
                for i in range(n):
                    yield f"{name}{i}"

            return ([("a", t("a", 2)), ("b", t("b", 2))], None)

        res = explore("count", mk)
        # interleavings of 2+2 independent steps: C(4,2) = 6
        assert res.ok and res.interleavings == 6


class TestProtocolModels:
    def test_channel_crash_atomicity_holds(self):
        from flowsentryx_tpu.sync import interleave as il

        res = il.explore("atomic", il._mk_channel_crash(False))
        assert res.ok and res.interleavings > 0 and not res.capped

    def test_split_complete_counterexample_found(self):
        from flowsentryx_tpu.sync import interleave as il

        res = il.explore("split", il._mk_channel_crash(True),
                         expect_violation=True)
        assert res.ok
        assert "crash-atomicity violated" in res.counterexample.detail
        # the schedule names the planted split step
        assert any("decrement-only" in s
                   for s in res.counterexample.schedule)

    def test_stop_drains_under_all_schedules(self):
        from flowsentryx_tpu.sync import interleave as il

        res = il.explore("drain", lambda: il._mk_channel_stop_drain())
        assert res.ok and res.interleavings > 100 and not res.capped

    def test_queue_wraparound_views_stable(self, tmp_path):
        from flowsentryx_tpu.sync import interleave as il

        res = il.explore(
            "wrap", il._mk_queue(tmp_path / "q.shm", False))
        assert res.ok and res.interleavings > 0 and not res.capped

    def test_premature_release_counterexample(self, tmp_path):
        from flowsentryx_tpu.sync import interleave as il

        res = il.explore(
            "misuse", il._mk_queue(tmp_path / "q.shm", True),
            expect_violation=True)
        assert res.ok
        assert "overwritten before release" in res.counterexample.detail


class TestArenaBoundTight:
    """The headline proof: ring_safe_slots passes ALL interleavings,
    one slot fewer yields a concrete staged-copy-overwrite schedule."""

    def test_shipped_bound_passes_all_interleavings(self):
        from flowsentryx_tpu.engine.arena import DispatchArena
        from flowsentryx_tpu.sync import interleave as il

        depth, ring = il._ARENA_DEPTH, il._ARENA_RING
        safe = DispatchArena.ring_safe_slots(depth, ring)
        assert safe == depth + ring + 1
        res = il.explore("safe", il._mk_arena(
            safe, depth, ring, il._ARENA_SINGLES, il._ARENA_ROUNDS))
        assert res.ok and res.interleavings > 0 and not res.capped

    def test_one_below_yields_staged_copy_overwrite(self):
        from flowsentryx_tpu.sync import interleave as il

        depth, ring = il._ARENA_DEPTH, il._ARENA_RING
        res = il.explore("tight", il._mk_arena(
            depth + ring, depth, ring,
            il._ARENA_SINGLES, il._ARENA_ROUNDS),
            expect_violation=True)
        assert res.ok
        cx = res.counterexample
        assert "staged-copy overwrite" in cx.detail
        # the schedule is a concrete replayable thread:step list
        assert any(s.startswith("dispatch:claim") for s in cx.schedule)
        assert cx.schedule[-1].startswith("worker:launch")

    def test_full_report_shape(self):
        from flowsentryx_tpu.sync.interleave import run_interleave

        rep = run_interleave()
        assert rep.ok
        assert rep.bound["safe_slots"] == (
            rep.bound["readback_depth"] + rep.bound["ring"] + 1)
        assert rep.bound["counterexample_found"] is True
        assert rep.bound["interleavings_at_safe"] > 0
        j = rep.to_json()
        assert {"ok", "interleavings", "steps", "bound",
                "checks"} <= set(j)
        neg = [c for c in j["checks"] if c["expect_violation"]]
        assert neg and all(c["counterexample"] for c in neg)


# ---------------------------------------------------------------------------
# SinkChannel unit behavior (the engine-facing surface)
# ---------------------------------------------------------------------------

class TestSinkChannel:
    def test_pending_counts_chunks_not_entries(self):
        ch = SinkChannel()
        ch.submit("mega", n_chunks=4)
        ch.submit_many(["a", "b"], lambda _: 2)
        assert ch.pending == 8
        assert ch.try_pop() == ["mega"]
        ch.complete(4)
        assert ch.pending == 4

    def test_coalesce_folds_consecutive_ready(self):
        ch = SinkChannel()
        ch.submit_many([1, 2, 9, 3], lambda _: 1)
        # first item pops unconditionally, the fold takes consecutive
        # predicate-passing followers (the sink's ready-group shape)
        assert ch.try_pop(coalesce=lambda x: x < 5) == [1, 2]
        assert ch.try_pop(coalesce=lambda x: x < 5) == [9, 3]
        assert ch.try_pop() is None

    def test_check_raises_named_worker_crash(self):
        ch = SinkChannel("device-pipeline worker")
        ch.complete(0, exc=ValueError("boom"))
        with pytest.raises(WorkerCrash,
                           match="device-pipeline worker crashed"):
            ch.check()
        assert isinstance(ch.crashed(), ValueError)

    def test_wait_below_released_by_crash(self):
        ch = SinkChannel()
        ch.submit("x", 3)

        def killer():
            ch.record_exc(RuntimeError("dead"))

        t = threading.Thread(target=killer)
        t.start()
        ch.wait_below(0, quantum=0.01)  # must not hang
        t.join()
        with pytest.raises(WorkerCrash):
            ch.check()

    def test_blocking_pop_drains_then_none_after_stop(self):
        ch = SinkChannel()
        ch.submit("tail", 1)
        ch.request_stop()
        assert ch.pop(quantum=0.01) == ["tail"]
        assert ch.pop(quantum=0.01) is None
        assert ch.drained()


# ---------------------------------------------------------------------------
# unified crash propagation: one loud shape per worker type
# ---------------------------------------------------------------------------

class TestCrashPropagationPerWorker:
    """docs/CONCURRENCY.md §crash: sink thread, device-pipeline worker
    and strict-mode ingest death all surface as the same loud
    WorkerCrash on the dispatch side (the sink-thread case is pinned
    in test_engine.py::test_sink_crash_fails_engine_loudly)."""

    def test_pipeline_worker_crash_is_loud(self):
        from flowsentryx_tpu.engine import Engine, TrafficSource
        from flowsentryx_tpu.engine.traffic import Scenario, TrafficSpec
        from tests.test_engine import small_cfg

        class BoomSink:
            def apply(self, update):
                if len(update.key):
                    raise ValueError("verdict ring gone")

        cfg = small_cfg(batch=256, pps_threshold=200.0,
                        bps_threshold=1e9)
        src = TrafficSource(
            TrafficSpec(scenario=Scenario.UDP_FLOOD_MULTI,
                        rate_pps=1e7, n_attack_ips=8,
                        attack_fraction=0.8, seed=7),
            total=256 * 40)
        # readback_depth defaults and auto-raises to cover a ring round
        eng = Engine(cfg, src, BoomSink(), mega_n="auto", device_loop=2)
        with pytest.raises(WorkerCrash,
                           match="device-pipeline worker crashed"):
            eng.run()
        assert not eng._sink_active  # joined, not wedged

    def test_strict_ingest_crash_is_loud_after_drain(self, tmp_path):
        import time

        from flowsentryx_tpu.core import schema
        from flowsentryx_tpu.core.config import BatchConfig
        from flowsentryx_tpu.engine.shm import ShmRing
        from flowsentryx_tpu.ingest import ShardedIngest
        from tests.test_ingest import make_records

        base = str(tmp_path / "fring")
        n = 2
        rings = [ShmRing.create(
            schema.shard_ring_path(base, k, n), 1 << 14,
            schema.FLOW_RECORD_DTYPE) for k in range(n)]
        rec = make_records(256 * 2, n_ips=64)
        parts = [rec[schema.shard_of(rec["saddr"], n) == k]
                 for k in range(n)]
        for ring, part in zip(rings, parts):
            assert ring.produce(part) == len(part)
        ing = ShardedIngest(base, n, queue_slots=16, precompact=False,
                            t0_grace_s=0.2, strict=True)
        ing.start(BatchConfig(max_batch=64, deadline_us=10_000),
                  schema.WIRE_RAW48, None)
        try:
            ing.wait_ready()
            deadline = time.monotonic() + 20
            while ing.t0_ns is None:
                ing.poll_batches(0)
                assert time.monotonic() < deadline
                time.sleep(0.01)
            ing._procs[0].terminate()
            ing._procs[0].join(timeout=10)
            # strict mode: keep consuming — the corpse's queue must
            # drain first (no sealed batch lost), THEN the death
            # surfaces as the unified loud WorkerCrash
            with pytest.raises(WorkerCrash,
                               match="ingest worker 0 crashed"):
                deadline = time.monotonic() + 30
                while True:
                    ing.poll_batches(8)
                    assert time.monotonic() < deadline, \
                        "strict crash never surfaced"
                    time.sleep(0.005)
        finally:
            ing.close()
        stats = ing.ingest_stats()
        assert stats["strict"] is True and stats["crashed"] is True

    def test_default_posture_stays_fail_open(self):
        # the strict flag defaults off: constructing without it keeps
        # the per-shard fail-open behavior test_ingest pins
        from flowsentryx_tpu.ingest import ShardedIngest
        import inspect

        sig = inspect.signature(ShardedIngest.__init__)
        assert sig.parameters["strict"].default is False


# ---------------------------------------------------------------------------
# SLO / latency-plane registry (PR 11): the new shared fields are
# registered with the correct disciplines, and each discipline's
# planted violation is caught — the PR 9 convention for every new
# piece of cross-thread engine state.
# ---------------------------------------------------------------------------

class TestSloRegistry:
    def test_new_fields_registered_with_expected_disciplines(self):
        f = contracts.ENGINE_PLAN.fields
        assert f["_rung_ewma_s"].discipline == "section:launch"
        # the dispatch-thread policy readers are explicit grants, part
        # of the documented discipline (advisory float reads)
        for reader in ("_slo_cap", "_slo_pressed", "_slo_round_fits",
                       "_deadline_flush_due"):
            assert reader in f["_rung_ewma_s"].extra
        assert f["_lat"].discipline == "section:sink"
        assert f["slo_us"].discipline == "quiescent-write"
        assert f["_slo_budget_s"].discipline == "quiescent-write"
        # the EWMA writer is part of the launch section
        assert "_note_step_s" in contracts.ENGINE_PLAN.sections["launch"]

    def test_planted_ewma_write_outside_launch_section(self):
        # an EWMA store from a worker-reachable method that is NOT in
        # the launch section (and not a granted reader) must be a
        # discipline finding — this is what makes the registry entry
        # enforceable rather than documentation
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._sink_worker).start()\n"
            "    def _launch(self):\n"
            "        self._ewma[1] = 0.5\n"
            "    def _sink_worker(self):\n"
            "        self._ewma[1] = 0.9\n")
        out = check_class(ast.parse(src), "planted.py", ClassPlan(
            module="planted.py", cls="C",
            worker_targets=("_sink_worker",),
            sections={"launch": ("_launch",)},
            fields={"_ewma": FieldContract("section:launch",
                                           "per-rung EWMA")}))
        assert len(out) == 1
        assert out[0].line == 8 and "_ewma" in out[0].reason

    def test_planted_latency_recorder_touched_off_sink_section(self):
        src = (
            "class C:\n"
            "    def _sink(self):\n"
            "        self._lat.record(1)\n"
            "    def poll(self):\n"
            "        self._lat.record(2)\n")
        out = check_class(ast.parse(src), "planted.py", ClassPlan(
            module="planted.py", cls="C",
            sections={"sink": ("_sink",)},
            fields={"_lat": FieldContract("section:sink",
                                          "latency plane")}))
        assert len(out) == 1
        assert out[0].line == 5 and "'sink' section" in out[0].reason

    def test_planted_slo_flag_written_while_serving(self):
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self.slo_us = 0\n"
            "    def serve(self):\n"
            "        self.slo_us = 100\n")
        out = check_class(ast.parse(src), "planted.py", ClassPlan(
            module="planted.py", cls="C", quiescent=("__init__",),
            fields={"slo_us": FieldContract("quiescent-write",
                                            "budget flag")}))
        assert len(out) == 1 and out[0].line == 5

    def test_unregistered_ewma_like_state_is_flagged(self):
        # deleting the registry entry must not be silent: a dict
        # mutated from both the dispatch path and a worker without an
        # entry trips the unregistered-shared-state detector
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._worker).start()\n"
            "        self._ewma[1] = 0.1\n"
            "    def _worker(self):\n"
            "        self._ewma[2] = 0.2\n")
        out = check_class(ast.parse(src), "planted.py", ClassPlan(
            module="planted.py", cls="C",
            worker_targets=("_worker",), fields={}))
        assert any(f.contract == "unregistered"
                   and "_ewma" in f.reason for f in out)


# ---------------------------------------------------------------------------
# Predictive-governor registry (ISSUE 18): every new piece of shared
# state — the governor itself, the ring-round refinement floor, the
# pre-warm buffer, and the shed-deferral counters on both gossip
# planes — registered with the correct discipline, and each new
# discipline surface's planted violation caught.
# ---------------------------------------------------------------------------

class TestPredictRegistry:
    def test_new_fields_registered_with_expected_disciplines(self):
        f = contracts.ENGINE_PLAN.fields
        assert f["_gov"].discipline == "dispatch"
        assert f["_warm_buf"].discipline == "dispatch"
        assert f["_round_floor_s"].discipline == "section:launch"
        # the prewarm site reads the EWMA table from the serving loop:
        # an explicit documented grant, like the PR 11 policy readers
        assert "_run_inline" in f["_rung_ewma_s"].extra
        assert "_note_round_s" in contracts.ENGINE_PLAN.sections["launch"]
        g = contracts.GOSSIP_PLAN.fields
        assert g["_ticks_deferred"].discipline == "section:merge"
        assert g["_defer_streak"].discipline == "section:merge"
        n = contracts.NETMAILBOX_PLAN.fields
        assert n["resync_deferred"].discipline == "section:merge"
        assert n["_resync_defer_streak"].discipline == "section:merge"

    def test_governor_plan_covers_every_mutable_attr(self):
        # registry-rot guard in the forward direction: every attribute
        # DispatchGovernor.__init__/reset_counters assigns is a
        # registered field — a new counter added without a contract
        # entry fails here by name
        import flowsentryx_tpu.engine.predict as predict_mod

        gov = predict_mod.DispatchGovernor()
        public = {k for k in vars(gov)
                  if k not in ("rung_sizes", "batch_records",
                               "conf_min")}  # quiescent config
        assert public == set(contracts.PREDICT_PLAN.fields)

    def test_planted_governor_touched_from_worker(self):
        # the dispatch discipline on the governor: a worker thread
        # driving any hook (here: the forecast swap) must be a finding
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._worker).start()\n"
            "        self.forecast = None\n"
            "    def _worker(self):\n"
            "        self.forecast = 1\n")
        out = check_class(ast.parse(src), "planted.py", ClassPlan(
            module="planted.py", cls="C",
            worker_targets=("_worker",),
            fields={"forecast": FieldContract("dispatch",
                                              "live forecast")}))
        assert len(out) == 1
        assert out[0].line == 7 and "forecast" in out[0].reason

    def test_planted_deferral_counter_outside_merge_section(self):
        # the shed-deferral counters ride the merge section: a bump
        # from the publish side (sink section territory) is a finding
        src = (
            "class C:\n"
            "    def tick(self):\n"
            "        self._ticks_deferred += 1\n"
            "    def publish(self):\n"
            "        self._ticks_deferred += 1\n")
        out = check_class(ast.parse(src), "planted.py", ClassPlan(
            module="planted.py", cls="C",
            sections={"merge": ("tick",)},
            fields={"_ticks_deferred": FieldContract(
                "section:merge", "shed deferral accounting")}))
        assert len(out) == 1
        assert out[0].line == 5 and "'merge' section" in out[0].reason

    def test_planted_round_floor_written_outside_launch(self):
        # the ring-round floor is launch-section state (written by the
        # warm seed and read by the refinement): a sink-side write is
        # a finding
        src = (
            "import threading\n"
            "class C:\n"
            "    def run(self):\n"
            "        threading.Thread(target=self._sink_worker).start()\n"
            "    def _note_round_s(self):\n"
            "        self._round_floor_s[-16] = 0.1\n"
            "    def _sink_worker(self):\n"
            "        self._round_floor_s[-16] = 0.2\n")
        out = check_class(ast.parse(src), "planted.py", ClassPlan(
            module="planted.py", cls="C",
            worker_targets=("_sink_worker",),
            sections={"launch": ("_note_round_s",)},
            fields={"_round_floor_s": FieldContract(
                "section:launch", "warm-seed round floor")}))
        assert len(out) == 1
        assert out[0].line == 8 and "_round_floor_s" in out[0].reason
