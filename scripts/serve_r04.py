"""SERVE_r04: sustained kernel-path serving artifact (VERDICT r3 next #7).

tests/test_daemon_bpf.py proves the kernel↔daemon↔engine seam works
once; this harness records it under SUSTAINED load for minutes:

    BPF_PROG_TEST_RUN flood driver (this script, the "NIC role")
      → real in-kernel XDP program (compact 16 B emit variant)
      → kernel BPF ringbuf → fsxd drain (daemon/fsxd.cpp run_bpf)
      → shm feature ring → fsx serve engine (micro-batch → fused step
        → verdicts) → shm verdict ring → fsxd → kernel blacklist map.

Recorded: offered packets (syscall count × repeat), kernel per-CPU
verdict stats, records forwarded through both rings, verdict
round-trips applied to the kernel map, ring-full drops at the shm seam
(the kernel ringbuf fails open silently by design — its loss shows up
as offered/16 vs forwarded), and the engine's own report.

The engine runs on CPU (JAX_PLATFORMS=cpu) so this artifact measures
the KERNEL-PATH plumbing independent of the axon tunnel's state; TPU
compute rates are bench.py's job (BENCH_r04 / link_baseline.json).

Usage: sudo python scripts/serve_r04.py [duration_s] — writes
SERVE_r04.json at the repo root.  Maps pin under /sys/fs/bpf/fsx_serve.
"""
from __future__ import annotations

import json
import os
import re
import struct
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from flowsentryx_tpu.bpf import loader  # noqa: E402

PIN = "/sys/fs/bpf/fsx_serve"
DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 150.0
N_ATTACK = 64          # flood sources
N_BENIGN = 64          # background sources
REPEAT = 2048          # kernel runs per PROG_TEST_RUN syscall


def eth(proto=0x0800):
    return b"\xff" * 6 + b"\x00" * 6 + struct.pack(">H", proto)


def udp_pkt(saddr: int, plen: int = 120, dport: int = 443) -> bytes:
    ihl = 5
    hdr = bytes([0x40 | ihl, 0]) + struct.pack(">H", plen - 14)
    hdr += b"\x00\x00\x00\x00" + bytes([64, 17]) + b"\x00\x00"
    hdr += struct.pack("<I", saddr)
    hdr += b"\x01\x02\x03\x04"
    l4 = struct.pack(">HHHH", 1234, dport, plen - 14 - ihl * 4, 0)
    pkt = eth() + hdr + l4
    return pkt + b"X" * max(0, plen - len(pkt))


def main() -> int:
    t_wall0 = time.time()
    # 1. fresh compact image with the production-default map sizes
    img = tempfile.mktemp(prefix="fsx_serve_", suffix=".img")
    r = subprocess.run(
        [sys.executable, "-m", "flowsentryx_tpu.bpf.image", img, "--compact"],
        capture_output=True, text=True, cwd=str(REPO))
    assert r.returncode == 0, r.stderr

    subprocess.run(["make", "-C", str(REPO / "daemon"), "-q"], check=False)
    subprocess.run(["rm", "-rf", PIN], check=False)
    fring = tempfile.mktemp(prefix="fsx_fring_")
    vring = tempfile.mktemp(prefix="fsx_vring_")

    # 2. daemon: kernel seam owner.  pps threshold sized BETWEEN the
    # two flood tiers the driver offers (~14 kpps "loud" sources vs
    # ~3.5 kpps "quiet" ones): the kernel limiter autonomously blocks
    # the loud tier while the quiet tier is left for the ML plane —
    # so the artifact shows BOTH kernel-limiter drops and ML verdict
    # round-trips, each attributable.
    fsxd = subprocess.Popen(
        [str(REPO / "daemon/build/fsxd"), "--bpf", "none", "--compact",
         "--prog-image", img, "--pin", PIN,
         "--duration", str(DURATION + 20),
         "--feature-ring", fring, "--verdict-ring", vring,
         "--pps-threshold", "8000", "--window", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    serve = None
    out: dict = {
        "round": 4,
        "purpose": ("Sustained kernel-path serving: PROG_TEST_RUN flood -> "
                    "in-kernel XDP (compact emit) -> ringbuf -> fsxd -> shm "
                    "-> engine -> verdict ring -> fsxd -> kernel blacklist "
                    "map, for minutes at max sim rate (VERDICT r3 next #7)"),
        "duration_s": DURATION,
        "engine_backend": "cpu (decoupled from axon tunnel state; TPU rates "
                          "are bench.py's artifact)",
        "analysis": {
            "offered_rate": ("PROG_TEST_RUN is a single-core syscall loop "
                             "(~4 us/packet in-kernel incl. map ops): the "
                             "~0.4 Mpps offered rate measures the DRIVER, "
                             "not XDP line rate (which needs a NIC)"),
            "benign_blocking": (
                "benign sources are eventually ML-blocked too: their FIRST "
                "1-2 packets carry no length variance and sparse IATs — "
                "indistinguishable from a slow attack at that flow age "
                "(the slow-attack confusion MODEL_METRICS_r04.json "
                "quantifies). Once mature (3+ varied frames), benign "
                "records score benign ('allowed' > 0); a k-record vote "
                "before first block is the policy lever, at the cost of "
                "k records of attack latency"),
        },
    }
    try:
        deadline = time.time() + 10
        while not os.path.exists(f"{PIN}/prog"):
            if fsxd.poll() is not None:
                print(fsxd.stderr.read(), file=sys.stderr)
                raise RuntimeError("fsxd died before pinning")
            assert time.time() < deadline, "daemon never pinned"
            time.sleep(0.1)
        prog_fd = loader.obj_get(f"{PIN}/prog")

        # 3. engine on the shm rings (CPU; small table for 1-core jit)
        cfgf = tempfile.mktemp(prefix="fsx_cfg_", suffix=".json")
        Path(cfgf).write_text(json.dumps({
            "table": {"capacity": 65536},
            "batch": {"max_batch": 2048, "deadline_us": 2000},
        }))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        serve = subprocess.Popen(
            [sys.executable, "-m", "flowsentryx_tpu.cli", "serve",
             "--config", cfgf, "--feature-ring", fring,
             "--verdict-ring", vring, "--seconds", str(DURATION + 10),
             "--artifact", str(REPO / "artifacts/logreg_int8.npz")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(REPO), env=env)

        # 4. flood driver at max PROG_TEST_RUN rate
        t0 = time.perf_counter()
        offered = 0
        syscalls = 0
        attack = [udp_pkt(0xC0A80000 + i, plen=80) for i in range(N_ATTACK)]
        # benign frames VARY in size per flow (web-like mix): a
        # constant-size one-packet-per-2s flow is correctly scored as
        # slowloris-shaped by the model — realistic background traffic
        # needs length variance, which drives PKT_LEN_STD/VAR
        benign = [[udp_pkt(0x0A000000 + i, plen=pl, dport=443 if i % 3
                           else 8000 + i)
                   for pl in (120, 600, 1400)]
                  for i in range(N_BENIGN)]
        k = 0
        while time.perf_counter() - t0 < DURATION:
            i = k % N_ATTACK
            # two flood tiers: the first quarter of sources run 4x
            # louder (kernel-limiter territory); the rest sit under the
            # rate threshold, detectable only by their ML features
            rep = REPEAT * 4 if i < N_ATTACK // 4 else REPEAT
            loader.prog_test_run(prog_fd, attack[i], repeat=rep)
            offered += rep
            syscalls += 1
            if k % 2 == 0:
                # benign minority at repeat=1: the kernel stamps REAL
                # inter-arrival times, so with 64 rotating sources each
                # benign flow sees ~1-2 s gaps and normal frames —
                # features the model should pass (a repeat-burst benign
                # driver would hand the kernel genuine µs IATs and be
                # correctly flagged as flood behavior)
                b = benign[(k // 2) % N_BENIGN][(k // 2) % 3]
                loader.prog_test_run(prog_fd, b, repeat=1)
                offered += 1
                syscalls += 1
            k += 1
        drive_wall = time.perf_counter() - t0
        out["offered_packets"] = offered
        out["prog_test_run_syscalls"] = syscalls
        out["offered_mpps"] = round(offered / drive_wall / 1e6, 3)
        out["drive_wall_s"] = round(drive_wall, 1)

        # 5. kernel-side truth: per-CPU stats + both blacklist maps
        st = subprocess.run(
            [sys.executable, "-m", "flowsentryx_tpu.cli", "status",
             "--pin", PIN], capture_output=True, text=True, cwd=str(REPO))
        out["kernel"] = json.loads(st.stdout).get("kernel", {})

        bl = subprocess.run(
            [sys.executable, "-m", "flowsentryx_tpu.cli", "blacklist",
             "--pin", PIN], capture_output=True, text=True, cwd=str(REPO))
        try:
            out["blacklist"] = json.loads(bl.stdout)
        except json.JSONDecodeError:
            out["blacklist"] = {"raw": bl.stdout[-500:]}
    finally:
        # 6. orderly teardown: daemon first (it drains the verdict ring
        # on exit), then the engine
        try:
            fsxd_out, fsxd_err = fsxd.communicate(timeout=40)
        except subprocess.TimeoutExpired:
            fsxd.kill()
            fsxd_out, fsxd_err = fsxd.communicate()
        if serve is not None:
            try:
                s_out, s_err = serve.communicate(timeout=40)
            except subprocess.TimeoutExpired:
                serve.kill()
                s_out, s_err = serve.communicate()
            try:
                out["engine_report"] = json.loads(s_out)
            except json.JSONDecodeError:
                out["engine_error"] = (s_err or s_out)[-800:]

        # daemon periodic stats: keep first/last lines + totals
        lines = [ln for ln in fsxd_err.splitlines() if "forwarded=" in ln]
        if lines:
            out["fsxd_first_report"] = lines[0]
            out["fsxd_last_report"] = lines[-1]
            m = re.search(
                r"forwarded=(\d+) verdicts=(\d+) skipped=(\d+)", lines[-1])
            if m:
                fwd, ver, skip = map(int, m.groups())
                out["forwarded_records"] = fwd
                out["verdict_roundtrips_applied"] = ver
                out["skipped_records"] = skip
                if "drive_wall_s" in out:
                    out["forwarded_mrps"] = round(
                        fwd / out["drive_wall_s"] / 1e6, 3)
        tail = [ln for ln in fsxd_err.splitlines()
                if "ring_full" in ln or "final" in ln]
        if tail:
            out["fsxd_tail"] = tail[-3:]
        out["wall_s"] = round(time.time() - t_wall0, 1)
        Path(REPO / "SERVE_r04.json").write_text(
            json.dumps(out, indent=2) + "\n")
        print(json.dumps({k: out.get(k) for k in
                          ("offered_mpps", "forwarded_records",
                           "verdict_roundtrips_applied", "wall_s")}))
        subprocess.run(["rm", "-rf", PIN], check=False)
        for f in (img, fring, vring):
            try:
                os.unlink(f)
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
