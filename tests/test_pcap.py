"""pcap ingestion tests: crafted captures through the kernel-mirror
parser + streaming feature tracker, plus real-kernel parity."""

from __future__ import annotations

import json
import struct

import numpy as np
import pytest

from flowsentryx_tpu.core import schema
from flowsentryx_tpu.engine import pcap


def eth(proto=0x0800):
    return b"\x02" * 6 + b"\x04" * 6 + struct.pack(">H", proto)


def udp4(saddr: int, dport=53, plen=100):
    hdr = bytes([0x45, 0]) + struct.pack(">H", plen - 14) + b"\x00" * 4
    hdr += bytes([64, 17]) + b"\x00\x00" + struct.pack("<I", saddr)
    hdr += b"\x01\x02\x03\x04"
    l4 = struct.pack(">HHHH", 1234, dport, plen - 34, 0)
    p = eth() + hdr + l4
    return p + b"X" * (plen - len(p))


def syn4(saddr: int, dport=80, plen=74):
    hdr = bytes([0x45, 0]) + struct.pack(">H", plen - 14) + b"\x00" * 4
    hdr += bytes([64, 6]) + b"\x00\x00" + struct.pack("<I", saddr)
    hdr += b"\x01\x02\x03\x04"
    l4 = struct.pack(">HH", 1234, dport) + b"\x00" * 9 + bytes([0x02]) \
        + b"\x00" * 6
    p = eth() + hdr + l4
    return p + b"X" * max(0, plen - len(p))


def udp6(words, dport=443, plen=120):
    hdr = b"\x60\x00\x00\x00" + struct.pack(">H", plen - 54) + bytes([17, 64])
    hdr += b"".join(struct.pack("<I", w) for w in words) + b"\xaa" * 16
    l4 = struct.pack(">HHHH", 1234, dport, plen - 54, 0)
    p = eth(0x86DD) + hdr + l4
    return p + b"X" * max(0, plen - len(p))


def tcp6_ext(words, ext_chain=((0, 0), (43, 1)), dport=443, plen=160):
    """v6 TCP SYN behind a chain of (proto, hdr_ext_len) ext headers."""
    first = ext_chain[0][0] if ext_chain else 6
    hdr = b"\x60\x00\x00\x00" + struct.pack(">H", plen - 54) + \
        bytes([first, 64])
    hdr += b"".join(struct.pack("<I", w) for w in words) + b"\xaa" * 16
    body = b""
    for i, (_, elen) in enumerate(ext_chain):
        nxt = ext_chain[i + 1][0] if i + 1 < len(ext_chain) else 6
        body += bytes([nxt, elen]) + b"\x00" * ((elen + 1) * 8 - 2)
    body += struct.pack(">HH", 1234, dport) + b"\x00" * 9 + b"\x02" \
        + b"\x00" * 6
    p = eth(0x86DD) + hdr + body
    return p + b"X" * max(0, plen - len(p))


def test_parse_frame_ipv6_ext_walk():
    """kern/parsing.h twin: the bounded ext-header walk reaches the TCP
    SYN, a truncated ext header refuses, a fragment stops the walk."""
    words = (5, 6, 7, 8)
    f = pcap.parse_frame(tcp6_ext(words))
    assert f is not None
    saddr, dport, proto, flags, _ = f
    assert proto == 6 and dport == 443
    assert flags & schema.FLAG_TCP_SYN and flags & schema.FLAG_IPV6
    assert saddr == 5 ^ 6 ^ 7 ^ 8
    # truncated inside the second ext header -> refused like the kernel
    assert pcap.parse_frame(tcp6_ext(words)[:66]) is None
    # fragment (44) is not walked: L3-only facts
    f = pcap.parse_frame(tcp6_ext(words, ext_chain=((44, 0),)))
    assert f is not None
    _, dport, proto, flags, _ = f
    assert proto == 44 and dport == 0
    assert not flags & (schema.FLAG_TCP | schema.FLAG_UDP)


def write_pcap(path, frames, t0_s=1000, dt_us=100, nanos=False):
    """Classic pcap: little-endian, µs (or ns) timestamp format."""
    magic = 0xA1B23C4D if nanos else 0xA1B2C3D4
    blob = struct.pack("<IHHiIII", magic, 2, 4, 0, 0, 65535, 1)
    for i, f in enumerate(frames):
        frac = i * dt_us * (1000 if nanos else 1)
        blob += struct.pack("<IIII", t0_s, frac, len(f), len(f)) + f
    path.write_bytes(blob)
    return path


def test_parse_and_features(tmp_path):
    frames = [udp4(0x0A000001, plen=100), udp4(0x0A000001, plen=200),
              syn4(0x0B000001), udp6((1, 2, 3, 4)),
              eth(0x0806) + b"\x00" * 28]  # ARP: skipped
    p = write_pcap(tmp_path / "t.pcap", frames)
    rec = pcap.pcap_to_records(p)
    assert len(rec) == 4  # ARP dropped
    assert rec["saddr"][0] == 0x0A000001
    # two-packet flow: second record's byte mean = (100+200)//2
    assert rec["feat"][1][1] == 150
    # IAT of 100 µs between the two packets
    assert rec["feat"][1][5] == 100
    assert rec["flags"][2] == schema.FLAG_TCP | schema.FLAG_TCP_SYN
    assert rec["feat"][2][0] == 80  # SYN dst_port host order
    assert rec["saddr"][3] == 1 ^ 2 ^ 3 ^ 4  # v6 fold
    assert rec["flags"][3] & schema.FLAG_IPV6
    # timestamps carried through (µs format → ns)
    assert rec["ts_ns"][1] - rec["ts_ns"][0] == 100_000


def test_nanosecond_pcap_and_gating(tmp_path):
    frames = [udp4(0x0C000001)] * 40
    p = write_pcap(tmp_path / "ns.pcap", frames, nanos=True, dt_us=10)
    rec = pcap.pcap_to_records(p)
    # kernel gating: first 16 all emit, then every 16th → 17th..40th
    # emit at counts 32 (1 more)... counts emitting: 1..16, 32 → wait:
    # n>16 and n%16 != 0 skip → emits at n<=16 plus n=32 → 17 records;
    # n=48 > 40.
    assert len(rec) == 17
    rec_all = pcap.pcap_to_records(p, emit_all=True)
    assert len(rec_all) == 40
    assert rec_all["ts_ns"][1] - rec_all["ts_ns"][0] == 10_000


def test_cli_roundtrip_serve(tmp_path, capsys):
    from flowsentryx_tpu import cli

    frames = [udp4(0x0A0A0A0A, plen=100 + 7 * i) for i in range(30)]
    p = write_pcap(tmp_path / "c.pcap", frames)
    out = tmp_path / "records.bin"
    assert cli.main(["pcap", str(p), str(out), "--emit-all"]) == 0
    meta = json.loads(capsys.readouterr().out)
    assert meta["packets_emitted"] == 30 and meta["flows"] == 1
    # the records file drives the serving engine end to end
    assert cli.main(["serve", "--records", str(out), "--packets", "30"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["records"] == 30


def test_tracker_matches_live_kernel(tmp_path):
    """pcap-derived features == the real XDP program's emitted features
    for the byte dimension (single-packet flows: the time dimension is
    zero on both sides, so the FULL vector must match)."""
    from flowsentryx_tpu.bpf import loader

    if not loader.bpf_available():
        pytest.skip("bpf(2) not permitted")
    from tests.test_bpf import Fsx, ip4_pkt

    f = Fsx()
    f.push_config()
    sources = [(0x0D000000 + i, 60 + 91 * i) for i in range(6)]
    frames = []
    for saddr, plen in sources:
        pkt = ip4_pkt(saddr, proto=17, dport=53, plen=plen)
        assert f.run(pkt) == 2
        frames.append(pkt)
    kern = f.records()
    p = write_pcap(tmp_path / "k.pcap", frames)
    ours = pcap.pcap_to_records(p)
    assert len(kern) == len(ours) == 6
    np.testing.assert_array_equal(kern["feat"], ours["feat"])
    np.testing.assert_array_equal(kern["saddr"], ours["saddr"])
    np.testing.assert_array_equal(kern["flags"], ours["flags"])


def test_snaplen_uses_original_length(tmp_path, capsys):
    """Byte features must come from the ON-WIRE length even when the
    capture truncated the payload (tcpdump -s); frames whose headers
    were cut off are dropped with a warning."""
    full = udp4(0x0A000009, plen=1500)
    magic = struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 96, 1)
    blob = magic
    # packet 1: captured 96 of 1500 bytes — headers intact
    blob += struct.pack("<IIII", 1000, 0, 96, 1500) + full[:96]
    # packet 2: captured 20 of 1500 — L3 header cut off
    blob += struct.pack("<IIII", 1000, 100, 20, 1500) + full[:20]
    p = tmp_path / "snap.pcap"
    p.write_bytes(blob)
    rec = pcap.pcap_to_records(p)
    err = capsys.readouterr().err
    assert len(rec) == 1
    assert rec["pkt_len"][0] == 1500       # on-wire, not captured
    assert rec["feat"][0][1] == 1500       # byte mean from orig too
    assert "snaplen truncated" in err and "1 frames dropped" in err
