"""The fsx live liveness checker (flowsentryx_tpu/live/ +
sync/interleave.explore_live): detector units on synthetic thread
sets, the PROGRESS registry's two-way closure, the real-protocol
proofs, the four planted regressions with their catching schedules,
the liveness_waits lint stage, and the CLI contract."""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import pytest

from flowsentryx_tpu.live import registry
from flowsentryx_tpu.live import checker as live_checker
from flowsentryx_tpu.sync import tuning
from flowsentryx_tpu.sync.interleave import (
    CvWait, InstrumentedCv, LiveSpec, ModelViolation, Obligation,
    explore_live,
)

_spec = importlib.util.spec_from_file_location(
    "fsx_lint_live",
    Path(__file__).resolve().parents[1] / "scripts" / "lint.py")
lint = importlib.util.module_from_spec(_spec)
sys.modules["fsx_lint_live"] = lint
_spec.loader.exec_module(lint)


# ---------------------------------------------------------------------------
# explore_live detector units (synthetic thread sets)
# ---------------------------------------------------------------------------

class TestExplorerDetectors:
    def test_deadlock_names_wait_and_wake_source(self):
        def mk():
            cv = InstrumentedCv()
            box = {"ready": False}

            def a():
                yield CvWait(lambda: box["ready"], "ready-wait", cv,
                             source="b's notify (never sent)")

            def b():
                yield CvWait(lambda: False, "never", cv,
                             source="nobody")

            return ([("a", a()), ("b", b())],
                    LiveSpec(fingerprint=lambda: (box["ready"],)))

        r = explore_live("deadlock-unit", mk)
        assert not r.ok and r.detector == "deadlock"
        d = r.counterexample.detail
        assert "a waits on ready-wait" in d
        assert "wake source: b's notify (never sent)" in d

    def test_missed_wakeup_is_deadlock(self):
        # the notify fires BEFORE the waiter parks, and the waiter's
        # predicate is false at park time: classic missed wakeup
        def mk():
            cv = InstrumentedCv()
            box = {"n": 0}

            def waiter():
                yield CvWait(lambda: box["n"] >= 2, "n>=2", cv,
                             source="bump notify")

            def bumper():
                yield "bump"
                with cv:
                    box["n"] += 1
                    cv.notify_all()

            return ([("waiter", waiter()), ("bumper", bumper())],
                    LiveSpec(fingerprint=lambda: (box["n"],)))

        r = explore_live("missed-wakeup", mk)
        assert not r.ok and r.detector == "deadlock"

    def test_entry_ok_predicate_needs_no_notify(self):
        # predicate already true when the thread parks: it proceeds
        # without any notify ever arriving
        def mk():
            cv = InstrumentedCv()

            def t():
                yield CvWait(lambda: True, "always", cv, source="-")
                yield "work"

            return ([("t", t())],
                    LiveSpec(fingerprint=lambda: ()))

        r = explore_live("entry-ok", mk)
        assert r.ok and r.terminals == 1

    def test_livelock_spin_cycle_detected(self):
        def mk():
            box = {"flag": False}

            def spinner():
                while not box["flag"]:
                    yield "spin"

            return ([("spinner", spinner())],
                    LiveSpec(fingerprint=lambda: (box["flag"],)))

        r = explore_live("livelock-unit", mk)
        assert not r.ok and r.detector == "livelock"
        assert "[cycle]" in r.counterexample.schedule[-1]

    def test_fair_poll_with_live_setter_is_clean(self):
        # the spinner's exit condition is owned by a continuously
        # runnable setter: weak fairness says the setter eventually
        # runs, so the spin cycle is not a fair livelock
        def mk():
            box = {"flag": False}

            def spinner():
                while not box["flag"]:
                    yield "spin"

            def setter():
                yield "set"
                box["flag"] = True

            return ([("spinner", spinner()), ("setter", setter())],
                    LiveSpec(fingerprint=lambda: (box["flag"],)))

        r = explore_live("fair-poll", mk)
        assert r.ok, (r.detector, r.counterexample)

    def test_starvation_trips_at_declared_bound(self):
        def mk():
            box = {"i": 0}

            def t():
                for _ in range(10):
                    yield "noop"
                    box["i"] += 1

            spec = LiveSpec(
                fingerprint=lambda: (box["i"],),
                obligations=[Obligation("never-fires",
                                        lambda: True,
                                        lambda: 0, 4)])
            return [("t", t())], spec

        r = explore_live("starve-unit", mk)
        assert not r.ok and r.detector == "starvation"
        assert "'never-fires'" in r.counterexample.detail
        assert "> 4 steps" in r.counterexample.detail

    def test_obligation_firing_resets_clock(self):
        def mk():
            box = {"i": 0}

            def t():
                for _ in range(10):
                    yield "tick"
                    box["i"] += 1

            spec = LiveSpec(
                fingerprint=lambda: (box["i"],),
                obligations=[Obligation("fires-every-step",
                                        lambda: True,
                                        lambda: box["i"], 4)])
            return [("t", t())], spec

        r = explore_live("oblige-unit", mk)
        assert r.ok

    def test_finale_violation_reported_with_schedule(self):
        def mk():
            box = {"done": False}

            def t():
                yield "step"

            def finale():
                if not box["done"]:
                    raise ModelViolation("work never done")

            return ([("t", t())],
                    LiveSpec(fingerprint=lambda: (box["done"],),
                             finale=finale))

        r = explore_live("finale-unit", mk)
        assert not r.ok and r.detector == "violation"
        assert "work never done" in r.counterexample.detail

    def test_expect_marker_mismatch_fails_the_demo(self):
        def mk():
            def t():
                yield "boom"
                raise ModelViolation("actual failure text")

            return [("t", t())], LiveSpec(fingerprint=lambda: ())

        hit = explore_live("demo-hit", mk, expect_violation=True,
                          expect_marker="actual failure")
        miss = explore_live("demo-miss", mk, expect_violation=True,
                            expect_marker="some other bug")
        assert hit.ok and not miss.ok

    def test_state_cap_reported_not_silent(self):
        def mk():
            box = {"i": 0}

            def t():
                while True:
                    yield "grow"
                    box["i"] += 1  # unbounded fingerprint

            return [("t", t())], LiveSpec(
                fingerprint=lambda: (box["i"],))

        r = explore_live("cap-unit", mk, max_states=10)
        assert r.capped and not r.ok


# ---------------------------------------------------------------------------
# PROGRESS registry closure
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_repo_registry_validates_clean(self):
        rep = registry.validate()
        assert rep["ok"], rep["findings"]
        assert rep["entries"] == len(registry.PROGRESS)
        assert rep["sites"] > 0

    def test_every_bound_is_a_tuning_constant(self):
        for e in registry.PROGRESS:
            assert hasattr(tuning, e.bound), e.name
            assert getattr(tuning, e.bound) > 0, e.name

    def test_every_scanned_site_is_registered(self):
        # the drift pin: add a blocking loop to the protocol scope
        # without registering it and this fails
        reg = registry.registered_sites()
        for rec in registry.scan_blocking_sites():
            assert (rec["path"], rec["qualname"]) in reg, rec

    def test_unregistered_loop_is_a_finding(self, tmp_path):
        mod = tmp_path / registry.SCAN_MODULES[0]
        mod.parent.mkdir(parents=True)
        mod.write_text("def rogue():\n"
                       "    while True:\n"
                       "        pass\n")
        rep = registry.validate(root=tmp_path)
        assert not rep["ok"]
        assert any("unregistered blocking loop" in f
                   and "rogue" in f for f in rep["findings"])

    def test_stale_entry_is_a_finding(self, tmp_path):
        # an empty tree: every entry points at nothing
        rep = registry.validate(root=tmp_path)
        assert any(f.startswith("stale entry") for f in rep["findings"])

    def test_never_exercised_proof_is_a_finding(self):
        rep = registry.validate(exercised=set())
        assert any("never exercised" in f for f in rep["findings"])
        proved = {e.proof for e in registry.PROGRESS if e.proof}
        rep = registry.validate(exercised=proved)
        assert not any("never exercised" in f for f in rep["findings"])

    def test_scan_sees_waits_and_loops_noqa_exempts(self, tmp_path):
        mod = tmp_path / registry.SCAN_MODULES[0]
        mod.parent.mkdir(parents=True)
        mod.write_text(
            "class C:\n"
            "    def w(self):\n"
            "        self.cv.wait(0.1)\n"
            "    def p(self):\n"
            "        while True:\n"
            "            pass\n"
            "    def exempt(self):\n"
            "        while True:  # noqa: licensed spin\n"
            "            pass\n")
        sites = registry.scan_blocking_sites(root=tmp_path)
        by_qn = {s["qualname"]: s for s in sites}
        assert "cv-wait" in by_qn["C.w"]["kinds"]
        assert "while-true" in by_qn["C.p"]["kinds"]
        assert "C.exempt" not in by_qn


# ---------------------------------------------------------------------------
# the real protocol proofs + plants (one quick run, module-scoped)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def live_report():
    return live_checker.run_live(quick=True)


class TestLiveReport:
    def test_report_green(self, live_report):
        assert live_report["ok"]
        assert live_report["schema"] == "fsx-live-report-v1"
        assert live_report["quick"] is True

    def test_five_protocols_proved(self, live_report):
        base = {c["check"].split("[")[0]
                for c in live_report["checks"]}
        assert {"channel_stop_drain_live", "handoff_drop",
                "autoscale_flap", "shed_bounded",
                "quiesce_terminates"} <= base

    def test_every_check_clean_and_uncapped(self, live_report):
        for c in live_report["checks"]:
            assert c["ok"] and not c["capped"], c["check"]
            assert c["states"] > 0 and c["edges"] > 0, c["check"]

    def test_handoff_drop_edges_recover(self, live_report):
        edges = [c for c in live_report["checks"]
                 if c["check"].startswith("handoff_drop[")]
        assert len(edges) >= 3  # clean + >=2 dropped stamps (quick)
        assert all(c["ok"] for c in edges)

    def test_registry_audited_in_report(self, live_report):
        assert live_report["registry"]["ok"], \
            live_report["registry"]["findings"]

    def test_all_four_plants_caught_with_clean_controls(
            self, live_report):
        plants = {p["plant"]: p for p in live_report["plants"]}
        assert set(plants) == {"notify_deleted", "fence_lift_dropped",
                               "streak_cap_removed", "cooldown_zeroed"}
        for name, p in plants.items():
            assert p["caught"] and p["control_ok"], name
            assert p["schedule"], name

    def test_plants_exercise_every_detector_class(self, live_report):
        dets = {p["caught_by"] for p in live_report["plants"]}
        assert dets == {"deadlock", "livelock", "starvation",
                        "violation"}

    def test_catching_schedules_name_the_protocol(self, live_report):
        plants = {p["plant"]: p for p in live_report["plants"]}
        assert "wait_below(0)" in plants["notify_deleted"]["detail"]
        assert "wake source" in plants["notify_deleted"]["detail"]
        assert "livelock" in plants["fence_lift_dropped"]["detail"]
        assert "anti_entropy_runs" in \
            plants["streak_cap_removed"]["detail"]
        assert "flap" in plants["cooldown_zeroed"]["detail"]

    def test_report_json_serialisable(self, live_report):
        json.dumps(live_report)


class TestScenarioUnits:
    def test_channel_scenario_clean(self):
        r = live_checker._check_channel()
        assert r.ok and r.terminals >= 1

    def test_autoscale_boundary_shrink_at_cooldown_is_legal(self):
        # cooldown_s left at the real tuning value: the first legal
        # SHRINK lands exactly at the cooldown boundary and the model
        # proves no interleaving beats it
        r = live_checker._check_autoscale()
        assert r.ok, (r.detector, r.counterexample)

    def test_shed_bound_frozen_at_import(self):
        # the plant patches tuning.SHED_MAX_DEFER at runtime; the
        # checker's declared bound must NOT move with it
        assert live_checker._SHED_BOUND == tuning.SHED_MAX_DEFER + 2
        orig = tuning.SHED_MAX_DEFER
        tuning.SHED_MAX_DEFER = 1 << 30
        try:
            assert live_checker._SHED_BOUND == orig + 2
        finally:
            tuning.SHED_MAX_DEFER = orig

    def test_plant_contextmanagers_restore(self):
        from flowsentryx_tpu.sync import channel as channel_mod
        from flowsentryx_tpu.cluster import supervisor as sup_mod

        orig_c = channel_mod.SinkChannel.complete
        orig_r = sup_mod.ClusterSupervisor._redeliver_stamps
        orig_s = tuning.SHED_MAX_DEFER
        with live_checker._plant_notify_deleted():
            assert channel_mod.SinkChannel.complete is not orig_c
        with live_checker._plant_fence_lift_dropped():
            assert (sup_mod.ClusterSupervisor._redeliver_stamps
                    is not orig_r)
        with live_checker._plant_streak_cap_removed():
            assert tuning.SHED_MAX_DEFER == 1 << 30
        assert channel_mod.SinkChannel.complete is orig_c
        assert sup_mod.ClusterSupervisor._redeliver_stamps is orig_r
        assert tuning.SHED_MAX_DEFER == orig_s


# ---------------------------------------------------------------------------
# the supervisor stamp re-delivery fix (found by handoff_drop)
# ---------------------------------------------------------------------------

class TestRedeliverStamps:
    def _world_sup(self):
        from flowsentryx_tpu.crash.world import SimSupervisor, World

        w = World(n=2, w=2)
        return w, SimSupervisor(w)

    def test_lost_fence_lift_is_recleared(self):
        w, sup = self._world_sup()
        w.statuses[0].ctl_set("c_fence", 7)  # lift was lost
        sup._handoff_tick(0.0)               # no handoff in flight
        assert w.statuses[0].ctl_get("c_fence") == 0

    def test_committing_restamps_lost_layout_gen(self):
        w, sup = self._world_sup()
        w.statuses[0].ctl_set("c_layout_gen", 3)
        w.statuses[1].ctl_set("c_layout_gen", 2)  # stamp was lost
        sup._redeliver_stamps({"phase": "committing", "to_gen": 3})
        assert w.statuses[1].ctl_get("c_layout_gen") == 3

    def test_steady_state_writes_nothing(self):
        w, sup = self._world_sup()
        for r in (0, 1):
            w.statuses[r].ctl_set("c_layout_gen", 3)
        writes = []
        orig = type(w.statuses[0]).ctl_set
        for r in (0, 1):
            st = w.statuses[r]
            st.ctl_set = (lambda name, value, _st=st:
                          (writes.append(name),
                           orig(_st, name, value)))
        sup._redeliver_stamps(None)
        sup._redeliver_stamps({"phase": "committing", "to_gen": 3})
        assert writes == []  # guarded by reads: clean runs write 0 ctl


# ---------------------------------------------------------------------------
# liveness_waits lint stage
# ---------------------------------------------------------------------------

def _lw(tmp_path, src, registered=frozenset()):
    p = tmp_path / "mod.py"
    p.write_text(src)
    return lint._liveness_wait_findings(p, "mod.py", set(registered))


class TestLivenessWaitsStage:
    def test_untimed_wait_flagged(self, tmp_path):
        out = _lw(tmp_path, "def f(cv):\n    cv.wait()\n")
        assert len(out) == 1 and "untimed .wait()" in out[0]
        assert "mod.py:2" in out[0]

    def test_timed_wait_clean(self, tmp_path):
        assert _lw(tmp_path, "def f(cv):\n    cv.wait(0.25)\n") == []

    def test_while_true_unregistered_flagged(self, tmp_path):
        out = _lw(tmp_path,
                  "class C:\n"
                  "    def loop(self):\n"
                  "        while True:\n"
                  "            self.step()\n")
        assert len(out) == 1
        assert "C.loop" in out[0] and "PROGRESS registry" in out[0]

    def test_while_true_registered_clean(self, tmp_path):
        src = ("class C:\n"
               "    def loop(self):\n"
               "        while True:\n"
               "            self.step()\n")
        assert _lw(tmp_path, src,
                   registered={("mod.py", "C.loop")}) == []

    def test_while_true_with_bounded_sleep_clean(self, tmp_path):
        assert _lw(tmp_path,
                   "import time\n"
                   "def f():\n"
                   "    while True:\n"
                   "        time.sleep(0.1)\n") == []

    def test_noqa_exempts_both_findings(self, tmp_path):
        assert _lw(tmp_path,
                   "def f(cv):\n"
                   "    cv.wait()  # noqa: wedge on purpose\n"
                   "    while True:  # noqa: licensed\n"
                   "        pass\n") == []

    def test_repo_scope_is_clean(self):
        assert lint.stage_liveness_waits() == []


# ---------------------------------------------------------------------------
# hoisted tuning constants (satellite b)
# ---------------------------------------------------------------------------

class TestTuningHoist:
    def test_liveness_bounds_exist(self):
        for name in ("GOSSIP_QUIESCE_S", "NET_HANDOFF_TIMEOUT_S",
                     "SUPERVISOR_DRAIN_TIMEOUT_S",
                     "SUPERVISOR_CLOSE_TIMEOUT_S"):
            assert getattr(tuning, name) > 0, name

    def test_protocol_defaults_reference_tuning(self):
        import inspect

        from flowsentryx_tpu.cluster import rebalance as rb
        from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor

        def default(fn, name):
            return inspect.signature(fn).parameters[name].default

        assert default(rb.ship_rows, "timeout_s") \
            == tuning.HANDOFF_SHIP_TIMEOUT_S
        assert default(rb.NetHandoff.send_stream, "timeout_s") \
            == tuning.NET_HANDOFF_TIMEOUT_S
        assert default(rb.NetHandoff.recv_stream, "timeout_s") \
            == tuning.NET_HANDOFF_TIMEOUT_S
        assert default(ClusterSupervisor.run, "drain_timeout_s") \
            == tuning.SUPERVISOR_DRAIN_TIMEOUT_S
        assert default(ClusterSupervisor.close, "timeout_s") \
            == tuning.SUPERVISOR_CLOSE_TIMEOUT_S

    def test_quiesce_generator_bounded_by_model_clock(self, tmp_path):
        from flowsentryx_tpu.cluster.gossip import (GossipPlane,
                                                    create_plane)

        create_plane(str(tmp_path), 2)
        plane = GossipPlane(str(tmp_path), 0, 2)
        plane.tick = lambda force=False, pressure=0.0: 7  # never idle
        t = {"v": 0.0}
        n = 0
        gen = plane._quiesce_steps(1.0, clock=lambda: t["v"])
        for _ in gen:
            n += 1
            t["v"] += 0.25
        assert n <= 5  # deadline-bounded even when never converging


# ---------------------------------------------------------------------------
# CLI contract + import hygiene
# ---------------------------------------------------------------------------

class TestCli:
    def test_live_quick_json_out(self, tmp_path, capsys):
        from flowsentryx_tpu.cli import main

        out = tmp_path / "LIVE.json"
        rc = main(["live", "--quick", "--json", "--out", str(out)])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["ok"] and rep["schema"] == "fsx-live-report-v1"
        disk = json.loads(out.read_text())
        assert disk["schema"] == rep["schema"]
        assert len(disk["plants"]) == 4

    def test_jax_free_import(self):
        r = subprocess.run(
            [sys.executable, "-c",
             "import sys; from flowsentryx_tpu.live import checker; "
             "from flowsentryx_tpu.live import registry; "
             "sys.exit(1 if 'jax' in sys.modules else 0)"],
            capture_output=True)
        assert r.returncode == 0, r.stderr.decode()
