"""Verdict writeback: the TPU plane's output side of the map seam.

The fused step returns, per batch, the flow keys newly condemned and
their blacklist expiries (``StepOutput.block_key`` / ``block_until``).
A :class:`VerdictSink` carries them back toward the kernel's
``blacklist_map`` — closing the loop the reference never built
(``fsx_load.py:5-12`` intent).  Sinks:

* :class:`NullSink` — benching the compute path alone.
* :class:`CollectSink` — tests/offline analysis: keeps everything.
* :class:`~flowsentryx_tpu.engine.shm.ShmVerdictSink` — production:
  pushes updates into the daemon's verdict ring; the daemon applies
  them to the pinned BPF map (kept with the shm transport).
"""

from __future__ import annotations

from typing import NamedTuple, Protocol

import numpy as np

from flowsentryx_tpu.ops.agg import INVALID_KEY


class BlacklistUpdate(NamedTuple):
    """One batch's newly blocked sources."""

    key: np.ndarray        # [K] uint32 folded source addrs
    until_s: np.ndarray    # [K] f32 expiry, engine-relative seconds


def extract_updates(block_key: np.ndarray, block_until: np.ndarray) -> BlacklistUpdate:
    """Compact a step's padded block arrays to the real updates."""
    block_key = np.asarray(block_key)
    mask = block_key != INVALID_KEY
    return BlacklistUpdate(
        key=block_key[mask], until_s=np.asarray(block_until)[mask]
    )


class VerdictWire(NamedTuple):
    """Host-side view of one decoded compact verdict wire
    (:func:`flowsentryx_tpu.ops.fused.pack_verdict_wire`)."""

    key: np.ndarray      # [count] uint32 newly-blocked keys (in order)
    until_s: np.ndarray  # [count] f32 matching expiries
    count: int           # TRUE newly-blocked count (may exceed len(key))
    overflow: bool       # count > k_max: fall back to the full fetch
    route_drop: int      # sharded routing fail-opens (0 single-device)
    now: float           # batch device clock (t0-relative seconds)


def decode_verdict_wire(wire: np.ndarray) -> VerdictWire:
    """Decode a fetched ``[2K+4]`` uint32 verdict wire (numpy only —
    the layout is self-describing, K = (len - 4) / 2).

    When ``overflow`` is set the key/until slots are INCOMPLETE (the
    device parked the tail): the caller must fetch the full
    ``block_key``/``block_until`` arrays for that batch instead, so a
    block is never lost."""
    wire = np.asarray(wire)
    k = (wire.shape[0] - 4) // 2
    count = int(wire[2 * k])
    n = min(count, k)
    return VerdictWire(
        key=wire[:n],
        until_s=wire[k:k + n].view(np.float32),
        count=count,
        overflow=bool(wire[2 * k + 1]),
        route_drop=int(wire[2 * k + 2]),
        now=float(wire[2 * k + 3:2 * k + 4].view(np.float32)[0]),
    )


class VerdictSink(Protocol):
    def apply(self, update: BlacklistUpdate) -> None: ...


class NullSink:
    def apply(self, update: BlacklistUpdate) -> None:
        pass


class CollectSink:
    """Accumulates updates (last expiry wins per key, like the kernel map)."""

    def __init__(self) -> None:
        self.blocked: dict[int, float] = {}
        self.updates = 0

    def apply(self, update: BlacklistUpdate) -> None:
        self.updates += 1
        # dict.update over zip is the vectorized last-wins write: zip
        # yields pairs in array order, and dict assignment keeps the
        # LAST value per key — the same semantics the per-key loop had
        # and the kernel map's overwrite-on-update gives.
        self.blocked.update(zip(update.key.tolist(),
                                update.until_s.tolist()))
