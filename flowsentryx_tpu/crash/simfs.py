"""Simulated filesystem with honest POSIX crash semantics.

The ``fsx crash`` model checker (checker.py) drives the REAL
durable-state protocols — checkpoint rotation, the layout generation
flip, the fenced handoff, dead-span adoption — against this fs through
the ``core/durable.py`` seam, and at every atomic step forks a crash.
For that to prove anything, the crash semantics here must be the ones
POSIX actually gives you, no kinder:

* ``os.replace`` is ATOMIC: after a crash the name maps to the old
  file or the new one, never a mix.  But the rename is a NAMESPACE op,
  durable only once the parent directory's metadata reaches disk — an
  un-fsynced rename lives in the page cache and is LOST at power loss.
* ``fsync(file)`` makes the file's DATA durable.  A file whose data
  was never fsynced can land torn at any byte boundary: empty, a
  prefix, or complete — the page cache flushes what it pleases.
* Power crash loses everything volatile: un-applied namespace ops,
  un-synced data (torn), and every shm mapping (the mailbox hub and
  ctl words live in ``world.py`` and are cleared by the harness).
* PROCESS crash loses none of that: the page cache and shm belong to
  the kernel, not the process.  Party-crash modes therefore keep the
  same fs instance; only power crashes reconstruct one from
  :meth:`SimFS.durable_states`.

Reads are not crash points: a crash "before a read" is
indistinguishable from a crash before the next mutating op, so
tracing them would only multiply identical explorations.

``fsync_is_noop=True`` is the ``fsync_skipped`` planted regression:
every write claims durability it does not have — exactly what the
protocol code did before ``core/durable.py`` centralized the
fsync-file-then-parent-dir discipline.
"""

from __future__ import annotations

import dataclasses

#: Durable-state fan-out bound per crash point, applied LOUDLY (the
#: report carries ``capped``): the cross product of torn files can
#: explode only when many un-synced files coexist, i.e. under the
#: fsync plants — where the first few states already violate.
MAX_STATES_PER_POINT = 96


class CrashNow(Exception):
    """Raised by :meth:`Tracer.point` at the injected crash point —
    BEFORE the op it names applies, so the op is lost with the crash."""


class Tracer:
    """Execution trace + crash injector shared by the sim fs, the sim
    mailbox hub and the sim ctl words.  Every durable-or-shared-state
    mutation calls :meth:`point` with a human-readable label; the
    label sequence of a clean run IS the crash-point enumeration, and
    the prefix up to an injected crash IS the printed schedule."""

    def __init__(self):
        self.ops: list[tuple[str, str]] = []  # (actor, label) applied
        self.actor = "world"
        #: False during scenario setup/recovery: those phases model
        #: state that was already durable (or a recovery we assume
        #: crash-free — the single-fault model, docs/CRASH.md)
        self.enabled = False
        self.crash_at: int | None = None
        #: None = power (any actor's op); otherwise only that actor's
        #: ops count toward ``crash_at`` — a process crash boundary
        self.crash_actor: str | None = None
        self.fired = False
        self.crashed_op: str | None = None
        self._seen = 0

    def point(self, label: str) -> None:
        if not self.enabled:
            return
        if (self.crash_at is not None and not self.fired
                and (self.crash_actor is None
                     or self.actor == self.crash_actor)):
            if self._seen == self.crash_at:
                self.fired = True
                self.crashed_op = f"{self.actor}: {label}"
                raise CrashNow(self.crashed_op)
            self._seen += 1
        self.ops.append((self.actor, label))

    def rendered(self) -> list[str]:
        return [f"{a}: {op}" for a, op in self.ops]


def eligible_points(ops: list[tuple[str, str]],
                    actor: str | None) -> int:
    """How many crash points a clean run exposes for ``actor`` (None =
    power: every op).  The checker enumerates ``crash_at`` over this."""
    return sum(1 for a, _ in ops if actor is None or a == actor)


@dataclasses.dataclass
class _File:
    """One inode: content is immutable after create (every write here
    is a fresh temp file), so durability is a single bit."""

    data: bytes
    synced: bool


def _base(path) -> str:
    return str(path).rsplit("/", 1)[-1]


class SimFS:
    """The ``core/durable.py`` seam's simulated twin (module
    docstring).  State is split the way the kernel splits it:

    * ``files``: inode id -> :class:`_File` (data + synced bit),
    * ``ns``: the VOLATILE namespace every read sees (page cache view),
    * ``durable_ns``: the namespace as of the last directory fsync,
    * ``pending``: namespace ops applied to ``ns`` but not yet to
      ``durable_ns`` — a power crash preserves any PREFIX of them
      (single-directory world: one fsync flushes the whole journal,
      and the kernel applies metadata ops in order).
    """

    name = "sim"

    def __init__(self, tracer: Tracer, *, fsync_is_noop: bool = False):
        self.tracer = tracer
        self.fsync_is_noop = fsync_is_noop
        self.files: dict[int, _File] = {}
        self.ns: dict[str, int] = {}
        self.durable_ns: dict[str, int] = {}
        self.pending: list[tuple] = []
        #: destination of the most recent publish rename — the
        #: media-fault flavor's target (corrupt-last-published)
        self.last_published: str | None = None
        self._fid = 0

    @classmethod
    def from_state(cls, state: dict[str, bytes], tracer: Tracer, *,
                   fsync_is_noop: bool = False) -> "SimFS":
        """The post-reboot fs: one legal durable state (from
        :meth:`durable_states`), everything on it clean and synced —
        the disk after a power crash IS the durable state."""
        fs = cls(tracer, fsync_is_noop=fsync_is_noop)
        for name, data in state.items():
            fs._fid += 1
            fs.files[fs._fid] = _File(data, True)
            fs.ns[name] = fs._fid
            fs.durable_ns[name] = fs._fid
        return fs

    # -- the seam (core/durable.py RealFS's method set) ----------------------

    def exists(self, path) -> bool:
        return str(path) in self.ns

    def size(self, path) -> int:
        return len(self.read_bytes(path))

    def read_bytes(self, path) -> bytes:
        name = str(path)
        if name not in self.ns:
            raise FileNotFoundError(2, "no such file", name)
        return self.files[self.ns[name]].data

    def read_text(self, path) -> str:
        return self.read_bytes(path).decode()

    def unlink(self, path) -> None:
        name = str(path)
        if name not in self.ns:
            raise FileNotFoundError(2, "no such file", name)
        self.tracer.point(f"unlink {_base(name)}")
        del self.ns[name]
        self.pending.append(("unlink", name))

    def write_atomic(self, path, data, *, fsync: bool = True,
                     rotate_prev=None) -> None:
        """The five-step publish, decomposed into its primitive ops so
        each is a crash point (durable.py's RealFS does the same steps
        against the kernel).  ``fsync_is_noop`` models the pre-PR-17
        sites: the calls happen, durability does not."""
        if isinstance(data, str):
            data = data.encode()
        name = str(path)
        tmp = name + ".tmp"
        do_sync = fsync and not self.fsync_is_noop
        # 1. write the temp file (data volatile, possibly torn)
        self.tracer.point(f"write {_base(tmp)} ({len(data)} B)")
        self._fid += 1
        fid = self._fid
        self.files[fid] = _File(bytes(data), False)
        self.ns[tmp] = fid
        self.pending.append(("create", tmp, fid))
        # 2. fsync the temp file (data durable)
        if do_sync:
            self.tracer.point(f"fsync {_base(tmp)}")
            self.files[fid].synced = True
        # 3. rotate the incumbent to .prev (atomic rename)
        if rotate_prev is not None and name in self.ns:
            prev = str(rotate_prev)
            self.tracer.point(f"rename {_base(name)} -> {_base(prev)}")
            pfid = self.ns.pop(name)
            self.ns.pop(prev, None)
            self.pending.append(("rename", name, prev, pfid))
            self.ns[prev] = pfid
        # 4. publish (atomic rename over the destination)
        self.tracer.point(f"rename {_base(tmp)} -> {_base(name)}")
        del self.ns[tmp]
        self.ns[name] = fid
        self.pending.append(("rename", tmp, name, fid))
        self.last_published = name
        # 5. fsync the parent directory (namespace ops durable)
        if do_sync:
            self.tracer.point(f"fsync parent dir of {_base(name)}")
            self._apply_all_pending()

    # -- crash-state enumeration ---------------------------------------------

    def _apply_all_pending(self) -> None:
        for op in self.pending:
            _apply_ns_op(self.durable_ns, op)
        self.pending.clear()

    def durable_states(self, *, media_fault: bool = False,
                       quick: bool = False):
        """Every distinct on-disk state a power crash RIGHT NOW can
        legally leave: each prefix of the pending namespace journal,
        crossed with every tear variant of each un-synced file visible
        under that prefix.  ``media_fault=True`` adds, per base state
        whose last-published file is intact, a twin with one bit
        flipped in it — the PR 13 media-corruption fault the ``.prev``
        retention exists for (a pure power crash with correct fsync
        can never damage an already-published file).

        Returns ``(states, capped)`` where each state is
        ``(label, {path: bytes})`` and ``capped`` says the
        :data:`MAX_STATES_PER_POINT` bound truncated the fan-out."""
        out: list[tuple[str, dict[str, bytes]]] = []
        seen: set = set()
        capped = False
        for k in range(len(self.pending) + 1):
            ns = dict(self.durable_ns)
            for op in self.pending[:k]:
                _apply_ns_op(ns, op)
            # content choices per surviving name
            names = sorted(ns)
            choices: list[list[tuple[bytes, str]]] = []
            for name in names:
                f = self.files[ns[name]]
                if f.synced:
                    choices.append([(f.data, "")])
                else:
                    choices.append([
                        (t, f"{_base(name)} torn to {len(t)}/"
                            f"{len(f.data)} B" if t != f.data else "")
                        for t in _tears(f.data, quick)])
            for combo in _product(choices):
                state = {n: c for n, (c, _) in zip(names, combo)}
                key = tuple(sorted(state.items()))
                if key in seen:
                    continue
                seen.add(key)
                notes = [lbl for _, lbl in combo if lbl]
                label = (f"{k}/{len(self.pending)} pending namespace "
                         f"op(s) applied"
                         + ("; " + "; ".join(notes) if notes else ""))
                out.append((label, state))
                if len(out) >= MAX_STATES_PER_POINT:
                    capped = True
                    break
            if capped:
                break
        if media_fault and not capped:
            lp = self.last_published
            extra = []
            for label, state in out:
                if lp and lp in state and len(state[lp]) > 0 \
                        and "torn" not in label:
                    bad = bytearray(state[lp])
                    bad[len(bad) // 2] ^= 0x40
                    extra.append((
                        label + f"; media fault: one bit flipped in "
                                f"{_base(lp)}",
                        {**state, lp: bytes(bad)}))
                if len(out) + len(extra) >= MAX_STATES_PER_POINT:
                    capped = True
                    break
            out.extend(extra)
        return out, capped


def _apply_ns_op(ns: dict, op: tuple) -> None:
    if op[0] == "create":
        _, name, fid = op
        ns[name] = fid
    elif op[0] == "rename":
        _, src, dst, fid = op
        ns.pop(src, None)
        ns[dst] = fid
    else:  # unlink
        ns.pop(op[1], None)


def _tears(data: bytes, quick: bool) -> list[bytes]:
    """Legal post-crash contents of an un-synced file: the page cache
    flushed none, some prefix, or all of it."""
    if quick:
        variants = [b"", data]
    else:
        variants = [b"", data[:1], data[:max(1, len(data) // 2)],
                    data[:-1], data]
    out: list[bytes] = []
    for v in variants:
        if v not in out:
            out.append(v)
    return out


def _product(choices: list[list]):
    """itertools.product over per-file content choices (inline so the
    empty-choices case yields one empty combo, matching product())."""
    if not choices:
        yield ()
        return
    head, *rest = choices
    for h in head:
        for r in _product(rest):
            yield (h,) + r
