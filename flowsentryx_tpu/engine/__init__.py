"""Host runtime: ring drain → micro-batch → TPU step → verdict writeback.

Successor of the reference's user-space control plane, which exists only
as a broken loader stub (``src/fsx_load.py:15`` crashes on an undefined
variable).  The engine is the Python half of the host pipeline; the C++
daemon (``daemon/``) is the kernel-facing half.  They meet at a
shared-memory record ring with the same layout as the BPF feature ring's
records (``flowsentryx_tpu.core.schema.FLOW_RECORD_DTYPE``), so the
engine is indifferent to whether records come from a real XDP plane, the
daemon's replay mode, or an in-process traffic generator.

Pipeline stages (SURVEY.md §7.2 "daemon"):

    source.poll() → MicroBatcher (size/deadline) → raw [B+1,12] u32
    → fused step on device → readiness-based verdict sink → VerdictSink

Stage latencies are tracked per batch (:mod:`.metrics`) — the reference
has no profiling at all (SURVEY.md §5.1).
"""

# Lazy re-exports (PEP 562): the ingest drain workers
# (flowsentryx_tpu/ingest/worker.py) import engine.shm / engine.batcher
# in freshly spawned pure-numpy processes; an eager `from .engine import
# Engine` here would tax every worker spawn with the multi-second jax
# import for code the worker never runs.
_EXPORTS = {
    "MicroBatcher": "flowsentryx_tpu.engine.batcher",
    "Engine": "flowsentryx_tpu.engine.engine",
    "EngineReport": "flowsentryx_tpu.engine.engine",
    "ArraySource": "flowsentryx_tpu.engine.sources",
    "PacedSource": "flowsentryx_tpu.engine.sources",
    "RecordSource": "flowsentryx_tpu.engine.sources",
    "TrafficSource": "flowsentryx_tpu.engine.sources",
    "BlacklistUpdate": "flowsentryx_tpu.engine.writeback",
    "CollectSink": "flowsentryx_tpu.engine.writeback",
    "NullSink": "flowsentryx_tpu.engine.writeback",
    "VerdictSink": "flowsentryx_tpu.engine.writeback",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
