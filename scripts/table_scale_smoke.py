"""Bounded CPU table-scale smoke — the production-flow-table CI gate.

Serves sustained flow CHURN (a fresh keyset every batch, the workload
whose occupancy only eviction can bound) through a mesh-sharded
eviction-epoch engine and re-proves, on every ``verify_tier1.sh`` run:

* **eviction fires** — ``stats.evicted > 0`` and the sweep actually
  freed rows (a no-eviction control run over the same records tracks
  strictly more);
* **occupancy stays bounded** — final ``table.tracked`` is held near
  the live (ttl-recent) flow count, not the cumulative distinct-flow
  count the control run reaches;
* **shard-local residency** — every occupied key in shard *i*
  satisfies ``owner_of(key) == i`` (the host hash twin,
  ``engine/table.py``), which is the "lookups stay shard-local"
  invariant measured rather than asserted from the design;
* **restore-with-reshard** — the run's checkpoint round-trips
  mesh=4 → mesh=8 with every key and its row intact, owner-correct
  under the new geometry, and zero dropped rows.

Results merge into ``artifacts/TABLESCALE_r12.json`` under ``"smoke"``
(the ``"paced"`` 4M-row drain/ladder evidence in the same artifact is
preserved).

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
           python scripts/table_scale_smoke.py [out.json]
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla:
    os.environ["XLA_FLAGS"] = (
        xla + " --xla_force_host_platform_device_count=8").strip()

BATCH = 256
PHASES = 24
CAP = 1 << 14
TTL_S = 2.0
EVERY = 4
SALT = 0xC0FFEE


def _cfg(ttl: float):
    from flowsentryx_tpu.core.config import (
        BatchConfig, FsxConfig, LimiterConfig, TableConfig,
    )

    return FsxConfig(
        table=TableConfig(capacity=CAP, stale_s=1e6, salt=SALT,
                          evict_ttl_s=ttl, evict_every=EVERY),
        batch=BatchConfig(max_batch=BATCH),
        limiter=LimiterConfig(pps_threshold=1e9, bps_threshold=1e18),
    )


def _churn():
    import numpy as np

    from flowsentryx_tpu.core import schema

    bufs = []
    for i in range(PHASES):
        buf = np.zeros(BATCH, schema.FLOW_RECORD_DTYPE)
        buf["saddr"] = 20_000 * (i + 1) + np.arange(BATCH)
        buf["pkt_len"] = 100
        buf["ts_ns"] = int(i * 1e9) + np.arange(BATCH) * 1000
        buf["feat"][:, 0] = 80.0
        bufs.append(buf)
    return np.concatenate(bufs)


def main() -> int:
    import numpy as np

    from flowsentryx_tpu.engine import ArraySource, CollectSink, Engine
    from flowsentryx_tpu.engine import table as tbl
    from flowsentryx_tpu.parallel import make_mesh

    t_start = time.perf_counter()
    recs = _churn()
    failures: list[str] = []

    # no-eviction control (single-device is fine — occupancy is
    # layout-independent up to arbitration losses)
    ctl = Engine(_cfg(0.0), ArraySource(recs.copy()), CollectSink(),
                 sink_thread=False)
    rep_ctl = ctl.run()

    # the eviction-epoch mesh engine
    mesh4 = make_mesh(4)
    eng = Engine(_cfg(TTL_S), ArraySource(recs.copy()), CollectSink(),
                 sink_thread=False, mesh=mesh4)
    rep = eng.run()

    evicted = rep.stats["evicted"]
    tracked = rep.table["tracked"]
    tracked_ctl = rep_ctl.table["tracked"]
    if evicted <= 0:
        failures.append("eviction never fired under 24 phases of churn")
    # live flows = the phases younger than ttl (+ the sweep period's
    # slack); 2x that is a generous bound, and far under the control's
    # cumulative occupancy
    live_bound = (int(TTL_S) + 1 + EVERY) * BATCH
    if tracked > live_bound:
        failures.append(
            f"occupancy {tracked} exceeds the live-flow bound "
            f"{live_bound} — eviction is not bounding churn")
    if tracked >= tracked_ctl:
        failures.append(
            f"evicting engine tracks {tracked} >= control "
            f"{tracked_ctl} — the sweep freed nothing")

    # shard-local residency, measured: every occupied key in shard i
    # hashes to owner i
    key = np.asarray(eng.table.key)
    local = CAP // 4
    occ = np.flatnonzero(key != 0)
    owners = tbl.owner_of(key[occ], SALT, 4)
    misplaced = int(np.sum(owners != occ // local))
    if misplaced:
        failures.append(
            f"{misplaced} occupied key(s) resident outside their "
            "owner shard — lookups are not shard-local")

    # restore-with-reshard: mesh=4 checkpoint → mesh=8 engine
    tmp = tempfile.mkdtemp(prefix="fsx_tblsmoke_")
    try:
        path = eng.checkpoint(os.path.join(tmp, "m4.npz"))
        e8 = Engine(_cfg(TTL_S), ArraySource(recs[:BATCH].copy()),
                    CollectSink(), sink_thread=False, mesh=make_mesh(8))
        info = e8.restore(path)
        k8 = np.asarray(e8.table.key)
        occ8 = np.flatnonzero(k8 != 0)
        if not info["resharded"] or info["dropped_rows"]:
            failures.append(f"mesh 4->8 reshard: {info}")
        if set(k8[occ8]) != set(key[occ]):
            failures.append("mesh 4->8 reshard lost/invented keys")
        own8 = tbl.owner_of(k8[occ8], SALT, 8)
        if int(np.sum(own8 != occ8 // (CAP // 8))):
            failures.append("resharded keys not owner-correct at mesh=8")
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)

    smoke = {
        "ts": time.time(),
        "wall_s": round(time.perf_counter() - t_start, 2),
        "capacity": CAP,
        "mesh": 4,
        "phases": PHASES,
        "evict_ttl_s": TTL_S,
        "evict_every": EVERY,
        "invariants": {
            "evicted": evicted,
            "tracked": tracked,
            "tracked_no_evict_control": tracked_ctl,
            "live_flow_bound": live_bound,
            "misplaced_keys": misplaced,
            "reshard_4_to_8": info,
        },
        "ok": not failures,
        "failures": failures,
    }

    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "TABLESCALE_r12.json")
    try:
        artifact = json.loads(open(out_path).read())
    except (OSError, ValueError):
        artifact = {}
    artifact["smoke"] = smoke
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"table-scale smoke: wrote {out_path}")
    print(f"table-scale smoke: evicted={evicted} tracked={tracked} "
          f"(control {tracked_ctl}, bound {live_bound}) "
          f"misplaced={misplaced}")
    for msg in failures:
        print(f"table-scale smoke: FAIL {msg}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
