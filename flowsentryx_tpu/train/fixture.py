"""CICIDS2017-calibrated evaluation fixture.

This image has no network egress and ships no CIC CSVs (the reference
repo itself checks in only an md5 stub,
``model/dataset/archive/MachineLearningCSV.md5``), so the BASELINE
metric (CICDDoS2019 F1) cannot be computed on real flows here.  This
module is the documented, distribution-faithful stand-in the round-2
review asked for — with its provenance stated per field rather than
pretending to be real data.

Calibrated to REAL published statistics (the reference notebook's
``df_concat.describe()`` over the cleaned 2,520,798-flow CICIDS2017
set, ``model/model.ipynb`` cell 20):

* label rate 0.1688914 (label column mean — real),
* destination_port quantiles (min 0, 25% 53, 50% 80, 75% 443,
  max 65535, mean 8690.59 — real),
* dataset/test-split sizes (2,520,798 / 504,160 — real,
  ``model.ipynb:1658-1665,4538``).

NOT calibrated to real data (the notebook's rendered describe()
truncates the middle columns): the remaining 7 features use
class-conditional lognormal/mixture models built from CICFlowMeter
semantics — volumetric floods send small fixed-size packets at µs
inter-arrival times; benign flows are heavy-tailed in both — with
ranges consistent with the published neighbours (flow_duration max
1.2e8 µs bounds every IAT).  No parameter below was tuned to reproduce
the reference's 83.02 % accuracy; whatever the golden model scores
here is reported as a FIXTURE number, never as CICIDS performance.
"""

from __future__ import annotations

import numpy as np

from flowsentryx_tpu.core.schema import NUM_FEATURES, Feature

#: Real aggregate marginals from model.ipynb cell 20 (describe()).
LABEL_RATE = 0.1688914
DPORT_QUANTILES = ((0.0, 0.0), (0.25, 53.0), (0.5, 80.0),
                   (0.75, 443.0), (1.0, 65535.0))
N_CLEANED = 2_520_798
N_TEST_SPLIT = 504_160


def _dport(rng: np.random.Generator, n: int) -> np.ndarray:
    """Piecewise-linear inverse-CDF sampler through the real quantiles.

    Real quartiles are tiny (53/80/443) with a long tail to 65535; the
    published mean 8690 confirms the tail mass.  Linear interpolation
    between published quantiles is the assumption-free choice."""
    u = rng.random(n)
    qs = np.array([q for q, _ in DPORT_QUANTILES])
    vs = np.array([v for _, v in DPORT_QUANTILES])
    return np.interp(u, qs, vs)


def _lognormal(rng, n, median, sigma, cap):
    return np.minimum(rng.lognormal(np.log(median), sigma, n), cap)


def _benign(rng: np.random.Generator, n: int) -> np.ndarray:
    X = np.zeros((n, NUM_FEATURES), np.float32)
    X[:, Feature.DST_PORT] = _dport(rng, n)
    # packet sizes: web/dns/bulk mix, heavy-tailed across flows
    mean_len = _lognormal(rng, n, 180.0, 0.9, 1460.0)
    rel_std = rng.beta(2.0, 3.0, n)  # most flows vary, none absurdly
    std_len = mean_len * rel_std * 2.0
    X[:, Feature.PKT_LEN_MEAN] = mean_len
    X[:, Feature.PKT_LEN_STD] = std_len
    # IATs (µs): interactive ms-scale to idle-dominated seconds-scale,
    # bounded by the real flow_duration max (1.2e8 µs)
    iat_mean = _lognormal(rng, n, 2.0e4, 2.2, 1.2e8)
    iat_rel = rng.lognormal(0.0, 0.8, n)
    X[:, Feature.FWD_IAT_MEAN] = iat_mean
    X[:, Feature.FWD_IAT_STD] = np.minimum(iat_mean * iat_rel, 1.2e8)
    X[:, Feature.FWD_IAT_MAX] = np.minimum(
        iat_mean * (1.0 + 3.0 * iat_rel), 1.2e8
    )
    # flow-age slots: duration = iat_mean x (n_pkts - 1) under the real
    # 1.2e8 us duration cap; rate follows (kernel-estimator identity
    # pps_x1000 = n * 1e9 / dur_us)
    npkts = np.maximum(_lognormal(rng, n, 10.0, 1.2, 1e5), 2.0)
    dur_us = np.clip(iat_mean * (npkts - 1.0), 1.0, 1.2e8)
    X[:, Feature.FLOW_DUR_MS] = dur_us / 1e3
    X[:, Feature.FLOW_PPS_X1000] = npkts * 1e9 / dur_us
    return X


#: Attack subtype ids — aligned with models.multiclass.ATTACK_CLASSES
#: (0 benign, 1 volumetric, 2 syn, 3 slow).
CLASS_BENIGN, CLASS_VOLUMETRIC, CLASS_SYN, CLASS_SLOW = 0, 1, 2, 3


def _attack(rng: np.random.Generator, n: int) -> tuple[np.ndarray, np.ndarray]:
    """DoS/DDoS flow features + subtype labels: volumetric floods
    (fixed small frames, µs IATs, low variance), SYN floods (minimal
    TCP frames on service ports, µs-ms IATs), and a slow-attack
    minority (Slowloris-style: sparse, long idle gaps).  NOTE on
    separability: the 8 flow features carry no protocol bits, so
    syn-vs-volumetric attribution rests on frame-size/IAT signatures
    only — the per-class eval reports that confusion honestly."""
    X = np.zeros((n, NUM_FEATURES), np.float32)
    cls = rng.choice(
        [CLASS_VOLUMETRIC, CLASS_SYN, CLASS_SLOW], n, p=[0.60, 0.25, 0.15]
    ).astype(np.int32)
    vol, syn, slow = (cls == CLASS_VOLUMETRIC), (cls == CLASS_SYN), \
        (cls == CLASS_SLOW)
    nv, ny, ns = int(vol.sum()), int(syn.sum()), int(slow.sum())

    X[:, Feature.DST_PORT] = np.where(
        rng.random(n) < 0.85,
        rng.choice([80.0, 443.0, 53.0], n),  # floods hit a service port
        _dport(rng, n),
    )
    # frame sizes: volumetric small-ish constant; SYN minimal TCP
    # (54-74 B, near-zero variance); slow: small but varied
    mean_len = np.empty(n)
    std_len = np.empty(n)
    mean_len[vol] = rng.uniform(54.0, 120.0, nv)
    std_len[vol] = rng.uniform(0.0, 4.0, nv)
    mean_len[syn] = rng.uniform(54.0, 74.0, ny)
    std_len[syn] = rng.uniform(0.0, 1.0, ny)
    mean_len[slow] = rng.uniform(60.0, 400.0, ns)
    std_len[slow] = rng.uniform(0.0, 60.0, ns)
    X[:, Feature.PKT_LEN_MEAN] = mean_len
    X[:, Feature.PKT_LEN_STD] = std_len

    iat_mean = np.empty(n)
    iat_max = np.empty(n)
    npkts = np.empty(n)
    if nv:
        iat_mean[vol] = _lognormal(rng, nv, 50.0, 1.5, 1e6)
        iat_max[vol] = iat_mean[vol] * rng.uniform(1.0, 20.0, nv)
        npkts[vol] = _lognormal(rng, nv, 3000.0, 1.0, 1e7)
    if ny:
        # handshake-rate floods: slower per flow than raw volumetric,
        # and per-flow SHORT (a few SYNs per spoofed source)
        iat_mean[syn] = _lognormal(rng, ny, 800.0, 1.2, 1e6)
        iat_max[syn] = iat_mean[syn] * rng.uniform(1.0, 10.0, ny)
        npkts[syn] = rng.uniform(3.0, 20.0, ny)
    if ns:
        # Slowloris-style: long-lived by construction (holding
        # connections open IS the attack), tens-to-hundreds of sparse
        # keepalive frames
        iat_mean[slow] = _lognormal(rng, ns, 5.0e6, 1.0, 1.2e8)
        iat_max[slow] = np.minimum(
            iat_mean[slow] * rng.uniform(2.0, 10.0, ns), 1.2e8
        )
        npkts[slow] = rng.uniform(10.0, 200.0, ns)
    X[:, Feature.FWD_IAT_MEAN] = iat_mean
    X[:, Feature.FWD_IAT_STD] = np.minimum(
        iat_mean * rng.lognormal(-0.5, 0.6, n), 1.2e8
    )
    X[:, Feature.FWD_IAT_MAX] = iat_max
    dur_us = np.clip(iat_mean * (npkts - 1.0), 1.0, 1.2e8)
    X[:, Feature.FLOW_DUR_MS] = dur_us / 1e3
    X[:, Feature.FLOW_PPS_X1000] = np.minimum(npkts * 1e9 / dur_us,
                                              4.0e9)
    return X, cls


def cicids_fixture(
    n: int = N_CLEANED, seed: int = 42, return_classes: bool = False
):
    """``(X [n,8] f32, y [n] f32)`` with the real 16.89 % label rate;
    with ``return_classes`` additionally ``y_class [n] i32`` (attack
    subtype ids aligned with models.multiclass.ATTACK_CLASSES)."""
    rng = np.random.default_rng(seed)
    n_attack = int(round(n * LABEL_RATE))
    Xa, cls_a = _attack(rng, n_attack)
    X = np.concatenate([_benign(rng, n - n_attack), Xa])
    y = np.concatenate([
        np.zeros(n - n_attack, np.float32), np.ones(n_attack, np.float32)
    ])
    y_class = np.concatenate([
        np.full(n - n_attack, CLASS_BENIGN, np.int32), cls_a
    ])
    order = rng.permutation(n)
    if return_classes:
        return X[order], y[order], y_class[order]
    return X[order], y[order]


def provenance() -> dict:
    """Machine-readable provenance block for metrics artifacts."""
    return {
        "kind": "synthetic-calibrated-fixture",
        "why_not_real_data": (
            "no network egress in the build image; CICIDS2017/CICDDoS2019 "
            "CSVs absent (reference repo ships only an md5 stub: "
            "model/dataset/archive/MachineLearningCSV.md5)"
        ),
        "real_calibration": {
            "label_rate": {
                "value": LABEL_RATE,
                "source": "reference model.ipynb cell 20 describe(): label mean",
            },
            "destination_port_quantiles": {
                "value": dict((str(q), v) for q, v in DPORT_QUANTILES),
                "source": "reference model.ipynb cell 20 describe()",
            },
            "sizes": {
                "cleaned_rows": N_CLEANED,
                "test_split": N_TEST_SPLIT,
                "source": "reference model.ipynb:1658-1665,4538",
            },
        },
        "synthetic_assumptions": (
            "7 of 8 feature marginals are class-conditional lognormal/"
            "mixture models from CICFlowMeter semantics (floods: fixed "
            "small frames, microsecond IATs; benign: heavy-tailed), NOT "
            "fit to real data and NOT tuned toward the reference's "
            "83.02% accuracy"
        ),
    }
