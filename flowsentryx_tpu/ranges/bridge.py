"""The cross-lane interval-containment proof: BPF verifier ⊇ jaxpr.

The distilled kernel scorer (``bpf/progs.py fn_ml_score``) and the
served int8 lane (``models/logreg.classify_batch_int8_matmul``) compute
the same weighted rank sum.  PR 6 proved them equal *concretely* (the
lock-step bytecode emulator over a corpus); this module adds the first
**static** parity bridge: for the shipped distill artifact,

* the BPF verifier's ``umin/umax`` at the scorer's MAC accumulate
  instructions and at the band-select exit (read through the
  observational probe API, :func:`~flowsentryx_tpu.bpf.verifier
  .check_program` ``probes=``) must **contain**
* the jaxpr-derived accumulator interval for the same computation
  (the range prover run over the staged int8 matmul lane with the
  artifact's exact parameter values as seeds), mapped into the
  kernel's raw-``Σ w·q`` domain (zero-point folded the way the
  distiller folds it) and into u64 two's-complement.

A containment failure means one lane's emission or staging drifted —
the scorer packs registers differently, the matmul recentering
changed, the verifier lost range precision where the proof needs it —
caught with no kernel and no execution, before the concrete emulator
ever runs.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from flowsentryx_tpu.core import schema

U64 = (1 << 64) - 1


def locate_probe_sites(prog: Any) -> dict:
    """Find the MAC accumulates and the band-select exit in the
    assembled scorer (``bpf/progs.build_ml_scorer``) by instruction
    pattern, not by hard-coded offsets — re-emission may shift
    indices, never shapes:

    * MAC: ``r6 += r4`` (ALU64 ADD X, dst=6, src=4) — one per feature;
      probed one slot later, where r6 holds the partial sum.
    * band: the ``exit`` directly following ``r0 -= r1`` (the
      branch-free band-select tail); probed at the exit, where r0
      holds the band code.
    """
    from flowsentryx_tpu.bpf import isa

    mac_after: list[int] = []
    band_exit = None
    add_r6 = isa.BPF_ALU64 | isa.BPF_ADD | isa.BPF_X
    sub_r0 = isa.BPF_ALU64 | isa.BPF_SUB | isa.BPF_X
    exit_op = isa.BPF_JMP | isa.BPF_EXIT
    for i, ins in enumerate(prog.insns):
        if ins.op == add_r6 and ins.dst == 6 and ins.src == 4:
            mac_after.append(i + 1)
        if (ins.op == exit_op and i > 0
                and prog.insns[i - 1].op == sub_r0
                and prog.insns[i - 1].dst == 0):
            band_exit = i
    if len(mac_after) != schema.NUM_FEATURES or band_exit is None:
        raise ValueError(
            f"fn_ml_score shape drift: found {len(mac_after)} MAC "
            f"accumulates (want {schema.NUM_FEATURES}) and band exit "
            f"{band_exit} — the containment bridge's instruction "
            "patterns no longer match the emitted scorer")
    return {"mac_after": mac_after, "band_exit": band_exit}


def _twos_complement_segments(lo: int, hi: int) -> list[tuple]:
    """A signed interval as u64 two's-complement segment(s)."""
    if lo >= 0:
        return [(lo, hi)]
    if hi < 0:
        return [(lo + (1 << 64), hi + (1 << 64))]
    return [(0, hi), (lo + (1 << 64), U64)]


def _contained(lo: int, hi: int, umin: int, umax: int) -> bool:
    return all(umin <= s0 and s1 <= umax
               for s0, s1 in _twos_complement_segments(lo, hi))


def jax_acc_interval(params: Any, batch: int = 8) -> tuple:
    """The served int8 lane's accumulator interval, derived from its
    STAGED jaxpr by the range prover (exact artifact values seeding
    the parameter leaves; features unconstrained floats):
    ``(acc_jax_lo, acc_jax_hi)`` in the jax zero-point-folded domain
    ``Σ w·(q - zp)``."""
    import jax

    from flowsentryx_tpu.models import logreg
    from flowsentryx_tpu.ranges import interval as iv
    from flowsentryx_tpu.ranges import prover

    jitted = jax.jit(logreg.classify_batch_int8_matmul)
    x = np.zeros((batch, schema.NUM_FEATURES), np.float32)
    closed = jitted.trace(params, x).jaxpr
    leaves = jax.tree_util.tree_leaves(params)
    seeds = [iv.const_of(np.asarray(leaf)) for leaf in leaves]
    seeds.append(iv.float_top())
    an = prover.analyze(
        closed, seeds,
        collect=lambda w, e: ("dot" if e.primitive.name == "dot_general"
                              else None))
    if an.findings:
        raise ValueError(
            "range prover found escapes in the int8 classifier lane: "
            + "; ".join(str(f) for f in an.findings))
    if "dot" not in an.collected:
        raise ValueError("no dot_general in the staged int8 lane — "
                         "the MXU matmul form changed; retarget the "
                         "bridge's collect hook")
    dlo, dhi = an.collected["dot"]
    # undo the [-128, 127] recentering the MXU form applies:
    # acc_jax = dot + (128 - in_zp) * Σw  (classify_batch_int8_matmul)
    w_sum = int(np.asarray(params.w_int8, np.int64).sum())
    in_zp = int(np.asarray(params.in_zp))
    corr = (128 - in_zp) * w_sum
    return int(dlo) + corr, int(dhi) + corr


def containment_proof(params: Any, budget: int = 2_000_000) -> dict:
    """Run both sides and check containment (module docstring).

    Returns the JSON-able proof record; ``ok`` is True iff the full
    kernel-domain accumulator interval is contained in the verifier's
    range at the FINAL MAC accumulate and the jax band set {PASS,
    ESCALATE, DROP} is contained at the band-select exit."""
    from flowsentryx_tpu.bpf import progs, verifier

    prog = progs.build_ml_scorer()
    sites = locate_probe_sites(prog)
    probes = {i: 6 for i in sites["mac_after"]}
    probes[sites["band_exit"]] = 0
    # entry_main=False: fn_ml_score is a local-call target in the
    # shipped programs (r1-r4 carry the packed features as scalars)
    rep = verifier.check_program(prog, name="fsx_ml_scorer",
                                 budget=budget, probes=probes,
                                 entry_main=False)

    acc_jax = jax_acc_interval(params)
    w_sum = int(np.asarray(params.w_int8, np.int64).sum())
    in_zp = int(np.asarray(params.in_zp))
    # kernel domain: s = Σ w·q = acc_jax + zp·Σw (the distiller's fold)
    acc_lo = acc_jax[0] + in_zp * w_sum
    acc_hi = acc_jax[1] + in_zp * w_sum

    final_mac = sites["mac_after"][-1]
    mac_probe = rep.probes.get(final_mac)
    band_probe = rep.probes.get(sites["band_exit"])
    mac_ok = (mac_probe is not None and mac_probe["hits"] > 0
              and _contained(acc_lo, acc_hi,
                             mac_probe["umin"], mac_probe["umax"]))
    bands = (int(schema.ML_BAND_PASS), int(schema.ML_BAND_DROP))
    band_ok = (band_probe is not None and band_probe["hits"] > 0
               and _contained(bands[0], bands[1],
                              band_probe["umin"], band_probe["umax"]))
    return {
        "ok": bool(mac_ok and band_ok),
        "jax_acc_zp_folded": [acc_jax[0], acc_jax[1]],
        "kernel_acc": [acc_lo, acc_hi],
        "jax_bands": list(bands),
        "mac_sites": sites["mac_after"],
        "band_exit": sites["band_exit"],
        "bpf_final_mac": mac_probe,
        "bpf_band": band_probe,
        "bpf_mac_all": {str(i): rep.probes.get(i)
                        for i in sites["mac_after"]},
        "mac_contained": bool(mac_ok),
        "band_contained": bool(band_ok),
    }
