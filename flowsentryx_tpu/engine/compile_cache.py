"""Persistent AOT executable store: boot-to-serving without the
recompile.

Every ``Engine.warm()`` used to pay the full XLA compile for every
staged variant — each coalescing-ladder rung, the deep-scan ring, the
eviction epochs folded into each — seconds of wall per boot, paid
again by every crash-respawn and every elastic GROW spare while the
burst it was spawned for is already landing.  The compile is a pure
function of the staged shape and the toolchain, so it is paid ONCE:
``jit_fn.lower(*abstract_args).compile()`` produces an executable that
``jax.experimental.serialize_executable`` round-trips through bytes,
and later boots of the same shape deserialize it in tens of
milliseconds instead of recompiling (measured on the smoke geometry:
~1.4 s compile vs ~70 ms load per mega/ring variant —
``scripts/boot_smoke.py`` re-proves the ratio per verify run).

The key discipline is the repo's ONE staged-shape signature
(:func:`flowsentryx_tpu.core.signature.staging_signature` — the same
rule the audit boot cache keys on), with the toolchain layered on top
in each entry's header: jax / jaxlib versions, backend and its
platform version.  A serialized executable is only valid for the
exact toolchain that produced it, but a version bump must read as
*drift* (an ops-visible counter), not as a crash and not as silence.

Fail-open is the contract: any miss, version drift, corrupt entry, or
serialization failure recompiles through the live jit path,
loudly-counted in :meth:`CompileCache.report` (surfaced in
``EngineReport.boot`` and ``fsx monitor --alert-cold-boot``) — the
cache accelerates boots, it never refuses one.

Entry format (one file per (signature, variant))::

    b"FSXAOT1\\n"                      magic
    <u32 little-endian header length>
    <header JSON: sig digest, variant, jax/jaxlib/backend versions>
    <u32 little-endian CRC32 of the blob>
    <blob: pickle of (payload, in_tree, out_tree) from serialize()>

Entries publish through :func:`core.durable.atomic_write` (the
``durable_writes`` lint scope covers this module): a crash mid-store
leaves the previous complete entry or none — never a torn file that
a later boot would have to CRC-reject.
"""

from __future__ import annotations

import io
import json
import os
import pickle
import struct
import sys
import time
import zlib
from pathlib import Path
from typing import Any

import jax
from jax.experimental.serialize_executable import (
    deserialize_and_load, serialize,
)

from flowsentryx_tpu.core import durable
from flowsentryx_tpu.core.signature import signature_digest

MAGIC = b"FSXAOT1\n"


def toolchain_versions() -> dict:
    """The toolchain fields a serialized executable is only valid
    under — compared header-vs-live at load, mismatch counted as
    ``version_drift`` (distinct from miss and corrupt: a silent
    fleet-wide cold boot after an upgrade is an ops event)."""
    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "unknown")
    except Exception:  # pragma: no cover - jaxlib ships with jax
        jaxlib_v = "unknown"
    try:
        platform_v = str(jax.devices()[0].client.platform_version)
    except Exception:
        platform_v = "unknown"
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib_v,
        "backend": jax.default_backend(),
        "platform_version": platform_v,
    }


class CompileCache:
    """On-disk AOT executable store for one staged shape.

    One instance serves one engine boot: the signature is fixed at
    construction, entries are addressed by ``(digest, variant)``, and
    the counters tell the boot's whole cache story — ``hits`` loaded
    executables, ``misses`` absent entries, ``corrupt`` CRC/decode
    refusals, ``version_drift`` toolchain mismatches, ``stores``
    published entries.  Used by at most one thread at a time by
    protocol: the quiescent warm pass first, then the background warm
    fill thread it hands off to (sync registry: the engine's
    ``_cache`` reference is never rebound)."""

    def __init__(self, root: str | Path, sig: dict):
        self.root = Path(root)
        self.sig = sig
        self.digest = signature_digest(sig)
        self.versions = toolchain_versions()
        self.hits = 0
        self.misses = 0
        self.corrupt = 0
        self.version_drift = 0
        self.stores = 0
        self.store_errors = 0

    def path(self, variant: str) -> Path:
        return self.root / f"{self.digest[:20]}-{variant}.aot"

    # -- load (fail-open) ---------------------------------------------------

    def load(self, variant: str) -> Any | None:
        """Deserialize-and-load the entry for ``variant``; None on any
        miss/drift/corruption (counted — the caller recompiles)."""
        p = self.path(variant)
        try:
            data = durable.get_fs().read_bytes(p)
        except (OSError, KeyError):
            self.misses += 1
            return None
        try:
            if data[: len(MAGIC)] != MAGIC:
                raise ValueError("bad magic")
            off = len(MAGIC)
            (hlen,) = struct.unpack_from("<I", data, off)
            off += 4
            header = json.loads(data[off:off + hlen].decode())
            off += hlen
            (crc,) = struct.unpack_from("<I", data, off)
            off += 4
            blob = data[off:]
            if zlib.crc32(blob) & 0xFFFFFFFF != crc:
                raise ValueError("CRC mismatch")
        except Exception as e:
            self.corrupt += 1
            print(f"fsx compile-cache: corrupt entry {p.name} ({e}); "
                  "recompiling (fail-open)", file=sys.stderr)
            return None
        if header.get("sig_digest") != self.digest:
            # filename-prefix collision with a different shape: not our
            # entry — a plain miss, the store below will overwrite
            self.misses += 1
            return None
        if header.get("versions") != self.versions:
            self.version_drift += 1
            print(f"fsx compile-cache: toolchain drift on {p.name} "
                  f"(entry {header.get('versions')} vs live "
                  f"{self.versions}); recompiling (fail-open)",
                  file=sys.stderr)
            return None
        try:
            payload, in_tree, out_tree = pickle.loads(blob)
            exe = deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            self.corrupt += 1
            print(f"fsx compile-cache: entry {p.name} failed to "
                  f"deserialize ({e!r}); recompiling (fail-open)",
                  file=sys.stderr)
            return None
        self.hits += 1
        return exe

    # -- store (atomic publish, never raises) -------------------------------

    def store(self, variant: str, compiled: Any) -> bool:
        """Serialize ``compiled`` and publish its entry atomically.
        Best-effort: a failure is counted and announced, never raised —
        the executable in memory still serves this boot."""
        try:
            payload, in_tree, out_tree = serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
            header = json.dumps({
                "sig_digest": self.digest,
                "variant": variant,
                "versions": self.versions,
                "created_s": round(time.time(), 3),
            }).encode()
            buf = io.BytesIO()
            buf.write(MAGIC)
            buf.write(struct.pack("<I", len(header)))
            buf.write(header)
            buf.write(struct.pack("<I", zlib.crc32(blob) & 0xFFFFFFFF))
            buf.write(blob)
            os.makedirs(self.root, exist_ok=True)
            durable.atomic_write(self.path(variant), buf.getvalue())
        except Exception as e:
            self.store_errors += 1
            print(f"fsx compile-cache: failed to store {variant} "
                  f"({e!r}); this boot serves from memory, the next "
                  "one recompiles", file=sys.stderr)
            return False
        self.stores += 1
        return True

    def report(self) -> dict:
        """The boot's cache story (``EngineReport.boot["cache"]``)."""
        return {
            "dir": str(self.root),
            "sig_digest": self.digest[:20],
            "hits": self.hits,
            "misses": self.misses,
            "corrupt": self.corrupt,
            "version_drift": self.version_drift,
            "stores": self.stores,
            "store_errors": self.store_errors,
        }
