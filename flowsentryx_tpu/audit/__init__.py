"""Static dtype/donation/transfer auditor for the staged step graphs.

The TPU-plane twin of :mod:`flowsentryx_tpu.bpf.verifier` (``fsx
check``): where the BPF verifier proves the *kernel* fast path safe
before load, this package proves the *device* fast path's serving
contracts on the compiled artifact itself — jaxpr and HLO level, no
batch ever executed.  See :mod:`flowsentryx_tpu.audit.graph` for the
individual contract checks and :mod:`flowsentryx_tpu.audit.runner` for
variant staging, the JSON report, and the engine-boot hook.
"""

from flowsentryx_tpu.audit.graph import (  # noqa: F401
    AuditError, Finding, check_callbacks, check_collectives,
    check_donation, check_dtypes, check_quantized_lane,
    iter_eqns, parse_alias_map, staging_cache_check,
)
from flowsentryx_tpu.audit.runner import (  # noqa: F401
    AuditReport, VariantReport, audit_serving, boot_audit, run_audit,
)
