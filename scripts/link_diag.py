"""Tunnel diagnosis: which RPC path is degraded, exactly?

Compares, in one process (order chosen so each measurement cannot
poison the next):

  a. async-dispatch chain cost of a trivial jitted fn (tanh matmul);
  b. device-resident fused-step loop, donate=False;
  c. device-resident fused-step loop, donate=True (the bench's shape);
  d. H2D bandwidth, 1 MB and 24 MB transfers.

Motivated by the r04 observation that a simple-chain probe read
"healthy" (0.02 ms dispatch, 343 MB/s) seconds before the real
pipeline measured 7 ms/step and 20 MB/s: if (b) is fast and (c) slow,
donation bookkeeping is the degraded path; if both are slow, dispatch
of large-argument-tree executables is; if only (d) is slow, it's pure
bandwidth metering.  Prints ONE JSON line.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

out = {"ts": time.time()}
t0 = time.perf_counter()
import jax
import jax.numpy as jnp

dev = jax.devices()[0]
out["backend"] = dev.platform
out["init_s"] = round(time.perf_counter() - t0, 1)

from _probe_common import make_step_fixture

B = 16384
CAP = 1 << 16  # small table: the probe must not drain the link filling HBM


def bench_loop(step, feeds, table, stats, params, iters):
    t0 = time.perf_counter()
    for i in range(iters):
        table, stats, o = step(table, stats, params, feeds[i % len(feeds)])
    jax.block_until_ready(o.verdict)
    return (time.perf_counter() - t0) / iters


# a. trivial-chain dispatch
f = jax.jit(lambda x: jnp.tanh(x @ x))
x = jax.device_put(jnp.ones((1024, 1024), jnp.bfloat16))
jax.block_until_ready(f(x))
t0 = time.perf_counter()
for _ in range(100):
    y = f(x)
jax.block_until_ready(y)
out["tanh_chain_ms"] = round((time.perf_counter() - t0) / 100 * 1e3, 3)

for donate in (False, True):
    tag = "donated" if donate else "undonated"
    t0 = time.perf_counter()
    step, table, stats, params, wire, quant = make_step_fixture(
        B, CAP, donate=donate)
    feeds = [jax.device_put(wire) for _ in range(4)]
    jax.block_until_ready(feeds)
    table, stats, o = step(table, stats, params, feeds[0])
    jax.block_until_ready(o.verdict)
    out[f"compile_{tag}_s"] = round(time.perf_counter() - t0, 1)
    per = bench_loop(step, feeds, table, stats, params, 20)
    iters = max(20, min(300, int(2.0 / max(per, 1e-6))))
    per = bench_loop(step, feeds, table, stats, params, iters)
    out[f"step_{tag}_ms"] = round(per * 1e3, 3)
    out[f"step_{tag}_mpps"] = round(B / per / 1e6, 1)

for mb, n in (("h2d_1mb_mbps", 1 << 20), ("h2d_24mb_mbps", 24 << 20)):
    buf = np.zeros(n, np.uint8)
    jax.block_until_ready(jax.device_put(buf[:1024]))
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(buf))
    out[mb] = round(n / (time.perf_counter() - t0) / 1e6, 1)

print(json.dumps(out), flush=True)
