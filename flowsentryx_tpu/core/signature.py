"""The staged-shape signature: ONE definition of what keys a compiled
serving artifact.

Three subsystems cache or prove work per *staged shape* — the audit
boot cache (``audit/runner.boot_audit``), the range certifier riding
the same staging surface (``ranges/runner``), and the persistent AOT
compile cache (``engine/compile_cache.py``).  Each used to be one
hand-rolled key away from drifting on what "the same shape" means
(the r-audit params-signature bug was exactly such a drift: a cache
that ignored params dtypes kept serving a stale verdict for an
f64-poisoned artifact).  This module is the single copy of the rule:

    a staged shape is keyed by everything that changes the compiled
    graph — the full config JSON (eviction knobs included), the wire
    format, the mesh device count, the coalescing-ladder size set, the
    drain-ring depth, donation, and the params leaves' dtypes/shapes.

What it deliberately does NOT include: toolchain versions (jax /
jaxlib / XLA backend).  Version drift invalidates *serialized
executables* but not *proofs about the staged jaxpr re-derived per
process* — so the compile cache layers versions on top (in its entry
header, counted distinctly as ``version_drift``) while the in-process
audit cache does not need them.

jax-free at module level (function-local import for params leaves):
``core/`` sits on jax-free import paths (cluster supervisor spawn).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any

import numpy as np


def params_signature(params: Any | None, model_name: str) -> list:
    """Dtype/shape signature of a params pytree — the part of the
    staged shape the config cannot describe.  ``None`` params key on
    the model's default-init identity instead (the model name), which
    is what makes two default-booted engines shape-equal."""
    if params is None:
        return ["default", model_name]
    import jax  # function-local: keep core importable jax-free

    leaves = jax.tree_util.tree_leaves(params)
    return [
        [str(np.dtype(getattr(leaf, "dtype", type(leaf)))),
         [int(d) for d in getattr(leaf, "shape", ())]]
        for leaf in leaves
    ]


def staging_signature(
    cfg: Any,
    *,
    wire: str,
    mesh_devices: int = 1,
    mega_sizes: tuple[int, ...] | list[int] | None = None,
    device_loop: int = 0,
    params: Any | None = None,
    donate: bool | None = None,
) -> dict:
    """Build the canonical signature dict of one staged serving shape.

    Pure data (JSON-able, deterministic ordering via
    :func:`signature_digest`): callers hash it, tuple it, or embed it
    in artifacts.  ``donate=None`` means "backend default" and is kept
    distinct from an explicit bool — the caller that resolved the
    default should pass the resolved value (the compile cache does;
    the audit key never resolved it and keeps ``None``)."""
    return {
        "cfg": cfg.to_json(),
        "wire": wire,
        "mesh_devices": int(mesh_devices or 1),
        "mega_sizes": [int(s) for s in (mega_sizes or ())],
        "device_loop": int(device_loop),
        "donate": None if donate is None else bool(donate),
        "params": params_signature(params, cfg.model.name),
    }


def signature_digest(sig: dict) -> str:
    """Stable hex digest of a signature dict (sorted-key canonical
    JSON, sha256) — the compile cache's filename key and the audit
    cache's hashable key half."""
    blob = json.dumps(sig, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()
