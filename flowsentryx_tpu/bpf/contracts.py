"""Cross-layer wire-format contract checker (the ``fsx check`` half
that is not the instruction verifier).

Four layers speak the same packed structs and must never disagree:

* :mod:`flowsentryx_tpu.core.schema` / ``core.config`` — the ground
  truth (``schema.struct_layouts()``);
* ``kern/fsx_schema.h`` — GENERATED from it by ``core.codegen``;
  compiled into the C++ daemon (``daemon/fsxd.cpp``), the BPF C twin
  (``kern/fsx_kern.c``) and every host-side C harness, so checking the
  header checks all of C;
* ``bpf/progs.py`` — bakes the same offsets into bytecode IMMEDIATES
  (``CFG_*``/``IPS_*``/``FS_*``/``REC_*``/``ST_*``) and map value sizes
  into ``MAP_SPECS``;
* the sealed program images under ``kern/build/`` — the
  assembler→daemon hand-off, which goes stale the moment progs.py or a
  map spec changes.

Each check returns a list of human-readable failure strings; an empty
list means the layers agree.  ``run_all()`` aggregates them into the
report ``fsx check`` prints and the tier-1 test asserts on — so a
schema drift fails in pytest, not as a kernel ``EACCES`` (or worse, a
silently misdecoded wire) at load time.

The C header is parsed with a purpose-built reader for the generated
format (packed structs of ``__uNN`` scalars/arrays + ``#define``\\ s) —
not a C parser; hand-edited headers that stray from codegen's output
fail the freshness check first anyway.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import NamedTuple

from flowsentryx_tpu.core import schema

#: Repo root (contracts run against a source checkout; ``fsx check``
#: reports the header as missing otherwise).
REPO_ROOT = Path(__file__).resolve().parents[2]
HEADER_PATH = REPO_ROOT / "kern" / "fsx_schema.h"
#: Sealed image per (compact, ml) build variant.  ``check_images`` also
#: accepts plain-bool keys (compact only, ml=False) for back-compat.
IMAGE_PATHS = {
    (False, False): REPO_ROOT / "kern" / "build" / "fsx_prog.img",
    (True, False): REPO_ROOT / "kern" / "build" / "fsx_prog_compact.img",
    (False, True): REPO_ROOT / "kern" / "build" / "fsx_prog_ml.img",
    (True, True): REPO_ROOT / "kern" / "build" / "fsx_prog_ml_compact.img",
}

_C_SIZES = {"__u64": 8, "__u32": 4, "__u16": 2, "__u8": 1, "float": 4}

_STRUCT_RE = re.compile(
    r"struct\s+(\w+)\s*\{(.*?)\}\s*__attribute__\(\(packed\)\)\s*;",
    re.S)
_FIELD_RE = re.compile(
    r"^\s*(__u64|__u32|__u16|__u8|float)\s+(\w+)(?:\[(\d+)\])?\s*;")
_DEFINE_RE = re.compile(
    r"^#define\s+(\w+)\s+\(?\s*([0-9xXa-fA-F]+(?:\s*<<\s*\d+)?)\s*\)?"
    r"(?:ULL)?\s*(?:/\*.*)?$")


def parse_header(text: str) -> tuple[dict[str, schema.StructLayout],
                                     dict[str, int]]:
    """(structs, defines) from a GENERATED fsx_schema.h."""
    structs: dict[str, schema.StructLayout] = {}
    for m in _STRUCT_RE.finditer(text):
        name, body = m.group(1), m.group(2)
        fields, off = [], 0
        for line in body.splitlines():
            fm = _FIELD_RE.match(line)
            if not fm:
                continue
            ctype, fname, count = fm.group(1), fm.group(2), fm.group(3)
            n = int(count) if count else 1
            size = _C_SIZES[ctype]
            fields.append(schema.FieldLayout(fname, off, size, n))
            off += size * n
        structs[name] = schema.StructLayout(name, off, tuple(fields))
    defines: dict[str, int] = {}
    for line in text.splitlines():
        dm = _DEFINE_RE.match(line.rstrip())
        if not dm:
            continue
        expr = dm.group(2)
        if "<<" in expr:
            a, b = (int(x.strip(), 0) for x in expr.split("<<"))
            defines[dm.group(1)] = a << b
        else:
            defines[dm.group(1)] = int(expr, 0)
    return structs, defines


# ---------------------------------------------------------------------------
# Individual checks — each returns failure strings, [] when clean
# ---------------------------------------------------------------------------


def check_header_fresh(header_path: Path = HEADER_PATH) -> list[str]:
    """The checked-in header is byte-identical to what codegen emits
    from the CURRENT schemas (a hand edit or a schema change without
    regeneration both trip this)."""
    from flowsentryx_tpu.core import codegen

    if not header_path.exists():
        return [f"{header_path}: missing (run fsx codegen)"]
    disk = header_path.read_text()
    want = codegen.generate()
    if disk == want:
        return []
    for i, (a, b) in enumerate(zip(disk.splitlines(), want.splitlines())):
        if a != b:
            return [f"{header_path}: stale — first divergence at line "
                    f"{i + 1}: {a!r} != generated {b!r} (run fsx codegen)"]
    return [f"{header_path}: stale — length differs from generated "
            "output (run fsx codegen)"]


def check_header_layouts(header_path: Path = HEADER_PATH) -> list[str]:
    """Struct offsets/sizes in the C header vs schema.struct_layouts().

    Redundant with check_header_fresh only while codegen is correct —
    this one would catch a codegen bug that renders the right fields at
    the wrong width, which freshness alone blesses."""
    if not header_path.exists():
        return [f"{header_path}: missing (run fsx codegen)"]
    structs, _ = parse_header(header_path.read_text())
    fails = []
    for name, want in schema.struct_layouts().items():
        got = structs.get(name)
        if got is None:
            fails.append(f"header lacks struct {name}")
            continue
        if got.size != want.size:
            fails.append(f"struct {name}: C size {got.size} != schema "
                         f"{want.size}")
        # match by offset, not name: the generated header may annotate
        # a word with its meaning (dtype "w0" -> C "w0_saddr")
        cfields = {f.offset: f for f in got.fields}
        for f in want.fields:
            cf = cfields.get(f.offset)
            if cf is None:
                fails.append(f"struct {name}: no C field at offset "
                             f"{f.offset} (schema field {f.name})")
            elif (cf.size, cf.count) != (f.size, f.count) or not (
                    cf.name == f.name or cf.name.startswith(f.name + "_")):
                fails.append(
                    f"struct {name}.{f.name}: C field {cf.name} "
                    f"(size={cf.size}, n={cf.count}) != schema "
                    f"(size={f.size}, n={f.count})")
    return fails


#: progs.py constant -> (struct, field) it must equal the offset of;
#: None field = total struct size.
_PROGS_OFFSETS: dict[str, tuple[str, str | None]] = {
    "CFG_LIMITER_KIND": ("fsx_config", "limiter_kind"),
    "CFG_VALID": ("fsx_config", "valid"),
    "CFG_PPS_THRESHOLD": ("fsx_config", "pps_threshold"),
    "CFG_BPS_THRESHOLD": ("fsx_config", "bps_threshold"),
    "CFG_WINDOW_NS": ("fsx_config", "window_ns"),
    "CFG_BLOCK_NS": ("fsx_config", "block_ns"),
    "CFG_BUCKET_RATE_PPS": ("fsx_config", "bucket_rate_pps"),
    "CFG_BUCKET_BURST": ("fsx_config", "bucket_burst"),
    "CFG_BUCKET_RATE_BPS": ("fsx_config", "bucket_rate_bps"),
    "CFG_BUCKET_BURST_BYTES": ("fsx_config", "bucket_burst_bytes"),
    "CFG_RULE_COUNT": ("fsx_config", "rule_count"),
    "CFG_HASH_SALT": ("fsx_config", "hash_salt"),
    "CFG_SIZE": ("fsx_config", None),
    "IPS_WIN_START_NS": ("fsx_ip_state", "win_start_ns"),
    "IPS_WIN_PPS": ("fsx_ip_state", "win_pps"),
    "IPS_WIN_BPS": ("fsx_ip_state", "win_bps"),
    "IPS_PREV_PPS": ("fsx_ip_state", "prev_pps"),
    "IPS_PREV_BPS": ("fsx_ip_state", "prev_bps"),
    "IPS_TOKENS_MILLI": ("fsx_ip_state", "tokens_milli"),
    "IPS_TOK_TS_NS": ("fsx_ip_state", "tok_ts_ns"),
    "IPS_TOK_BYTES": ("fsx_ip_state", "tok_bytes"),
    "IPS_SIZE": ("fsx_ip_state", None),
    "FS_PKT_COUNT": ("fsx_flow_stats", "pkt_count"),
    "FS_BYTE_SUM": ("fsx_flow_stats", "byte_sum"),
    "FS_BYTE_SQ_SUM": ("fsx_flow_stats", "byte_sq_sum"),
    "FS_FIRST_TS_NS": ("fsx_flow_stats", "first_ts_ns"),
    "FS_LAST_TS_NS": ("fsx_flow_stats", "last_ts_ns"),
    "FS_IAT_SUM_NS": ("fsx_flow_stats", "iat_sum_ns"),
    "FS_IAT_SQ_SUM_US2": ("fsx_flow_stats", "iat_sq_sum_us2"),
    "FS_IAT_MAX_NS": ("fsx_flow_stats", "iat_max_ns"),
    "FS_DST_PORT": ("fsx_flow_stats", "dst_port"),
    "FS_SIZE": ("fsx_flow_stats", None),
    "REC_TS_NS": ("fsx_flow_record", "ts_ns"),
    "REC_SADDR": ("fsx_flow_record", "saddr"),
    "REC_PKT_LEN": ("fsx_flow_record", "pkt_len"),
    "REC_IP_PROTO": ("fsx_flow_record", "ip_proto"),
    "REC_FLAGS": ("fsx_flow_record", "flags"),
    "REC_FEAT": ("fsx_flow_record", "feat"),
    "REC_SIZE": ("fsx_flow_record", None),
    "ST_ALLOWED": ("fsx_stats", "allowed"),
    "ST_DROPPED_BLACKLIST": ("fsx_stats", "dropped_blacklist"),
    "ST_DROPPED_RATE": ("fsx_stats", "dropped_rate"),
    "ST_DROPPED_ML": ("fsx_stats", "dropped_ml"),
    "ST_DROPPED_RULE": ("fsx_stats", "dropped_rule"),
    "ST_ML_PASS": ("fsx_stats", "ml_pass"),
    "ST_ML_ESCALATED": ("fsx_stats", "ml_escalated"),
    "ST_SIZE": ("fsx_stats", None),
    "MLM_VALID": ("fsx_ml_model", "valid"),
    "MLM_FLAGS": ("fsx_ml_model", "_reserved"),
    "MLM_ACC_DROP": ("fsx_ml_model", "acc_drop"),
    "MLM_ACC_PASS": ("fsx_ml_model", "acc_pass"),
    "MLM_W": ("fsx_ml_model", "w"),
    "MLM_QBASE": ("fsx_ml_model", "qbase"),
    "MLM_BOUNDS": ("fsx_ml_model", "bounds_m1"),
    "MLM_SIZE": ("fsx_ml_model", None),
}

#: map name -> (key struct-or-size, value struct-or-size).  A string
#: names a schema struct whose packed size the map must carry.
_MAP_CONTRACTS: dict[str, tuple[object, object]] = {
    "config_map": (4, "fsx_config"),
    "blacklist_map": (4, 8),
    "blacklist_v6": (16, 8),
    "ip_state_map": (4, "fsx_ip_state"),
    "flow_stats_map": (4, "fsx_flow_stats"),
    "stats_map": (4, "fsx_stats"),
    "feature_ring": (0, 0),
    "rule_map": (4, 8),
    "ml_model_map": (4, "fsx_ml_model"),
}


def check_progs_offsets() -> list[str]:
    """Every offset/size constant progs.py bakes into instruction
    immediates vs the schema layouts (the check that catches a struct
    edit that forgot the assembler)."""
    from flowsentryx_tpu.bpf import progs

    layouts = schema.struct_layouts()
    fails = []
    for const, (sname, field) in _PROGS_OFFSETS.items():
        have = getattr(progs, const, None)
        if have is None:
            fails.append(f"progs.{const}: constant missing")
            continue
        lay = layouts[sname]
        try:
            want = lay.size if field is None else lay.offset_of(field)
        except KeyError:
            # a schema field removed without retiring the assembler
            # constant: that IS the drift, not an internal error
            fails.append(f"progs.{const}: schema struct {sname} has no "
                         f"field {field!r} anymore")
            continue
        if have != want:
            what = f"sizeof({sname})" if field is None \
                else f"offsetof({sname}, {field})"
            fails.append(f"progs.{const} = {have} != {what} = {want}")
    # record flags and the compact record size ride the same bus
    for flag in ("IPV6", "TCP_SYN", "TCP", "UDP", "ICMP"):
        if getattr(progs, f"FLAG_{flag}") != getattr(schema,
                                                     f"FLAG_{flag}"):
            fails.append(f"progs.FLAG_{flag} != schema.FLAG_{flag}")
    if progs.COMPACT_REC_SIZE != schema.COMPACT_RECORD_SIZE:
        fails.append(f"progs.COMPACT_REC_SIZE = {progs.COMPACT_REC_SIZE}"
                     f" != schema.COMPACT_RECORD_SIZE = "
                     f"{schema.COMPACT_RECORD_SIZE}")
    return fails


def check_map_specs() -> list[str]:
    """MAP_SPECS key/value sizes vs the structs the kernel and the
    drain side deserialize map values into."""
    from flowsentryx_tpu.bpf import progs

    layouts = schema.struct_layouts()

    def resolve(x: object) -> int:
        return layouts[x].size if isinstance(x, str) else int(x)  # type: ignore[index]

    fails = []
    for name, (want_key, want_val) in _MAP_CONTRACTS.items():
        spec = progs.MAP_SPECS.get(name)
        if spec is None:
            fails.append(f"MAP_SPECS lacks map {name}")
            continue
        _, ks, vs, _ = spec
        if ks != resolve(want_key):
            fails.append(f"map {name}: key_size {ks} != "
                         f"{resolve(want_key)}")
        if vs != resolve(want_val):
            fails.append(f"map {name}: value_size {vs} != "
                         f"{resolve(want_val)}")
    extra = set(progs.MAP_SPECS) - set(_MAP_CONTRACTS)
    if extra:
        fails.append(f"maps missing a contract entry: {sorted(extra)} "
                     "(add them to contracts._MAP_CONTRACTS)")
    return fails


def check_header_defines(header_path: Path = HEADER_PATH) -> list[str]:
    """#define values the decoders/daemon compile against vs schema."""
    if not header_path.exists():
        return [f"{header_path}: missing (run fsx codegen)"]
    _, defines = parse_header(header_path.read_text())
    want = {
        "FSX_NUM_FEATURES": schema.NUM_FEATURES,
        "FSX_MAX_RULES": schema.MAX_RULES,
        "FSX_RULE_DROP": schema.RULE_DROP,
        "FSX_SHM_MAGIC": schema.SHM_MAGIC,
        "FSX_ML_BOUNDS_PER_FEATURE": schema.ML_BOUNDS_PER_FEATURE,
        **{f"FSX_FLAG_{n}": getattr(schema, f"FLAG_{n}")
           for n in ("IPV6", "TCP_SYN", "TCP", "UDP", "ICMP")},
        **{f"FSX_VERDICT_{v.name}": v.value for v in schema.Verdict},
        **{f"FSX_ML_BAND_{n}": getattr(schema, f"ML_BAND_{n}")
           for n in ("PASS", "ESCALATE", "DROP", "DISABLED")},
    }
    fails = []
    for name, val in want.items():
        got = defines.get(name)
        if got is None:
            fails.append(f"header lacks #define {name}")
        elif got != val:
            fails.append(f"#define {name} = {got} != schema {val}")
    return fails


def check_images(image_paths: dict | None = None) -> list[str]:
    """The sealed FSXPROG images under kern/build/ vs a fresh emit from
    the current assembler + map specs — the artifact the daemon actually
    loads is the one that goes stale silently.  Keys are ``(compact,
    ml)`` variant tuples; a bare bool means ``(compact, ml=False)``."""
    from flowsentryx_tpu.bpf import image, verifier

    fails = []
    for key, path in (image_paths or IMAGE_PATHS).items():
        compact, ml = key if isinstance(key, tuple) else (key, False)
        tag = ("ml_" if ml else "") + ("compact" if compact else "raw48")
        flags = ("--compact " if compact else "") + ("--ml " if ml else "")
        if not path.exists():
            fails.append(f"{path}: missing ({tag} image; regenerate "
                         "with python -m flowsentryx_tpu.bpf.image "
                         + flags.strip() + ")")
            continue
        try:
            want = image.emit(compact=compact, ml=ml)
        except verifier.StaticVerifierError as e:
            # emit() verifies before sealing; a generation bug must
            # surface as a contract failure, not crash the report
            # (the per-program half of fsx check carries the details)
            fails.append(f"{tag} image cannot be re-emitted: the "
                         f"current assembler output fails static "
                         f"verification ({str(e).splitlines()[0]})")
            continue
        if path.read_bytes() != want:
            fails.append(
                f"{path}: stale {tag} image — progs.py/map specs "
                "changed since it was sealed; regenerate with "
                "python -m flowsentryx_tpu.bpf.image " + flags + str(path))
    return fails


def check_shm_layout(header_path: Path = HEADER_PATH) -> list[str]:
    """The shm transport control-field offsets: every Python-side
    constant the engine/ingest decoders mmap at must land inside the
    header struct and on a distinct u64."""
    fails = []
    hdr = schema.struct_layouts()["fsx_shm_ring_hdr"]
    named = {
        "SHM_CAPACITY_OFFSET": schema.SHM_CAPACITY_OFFSET,
        "SHM_RECORD_SIZE_OFFSET": schema.SHM_RECORD_SIZE_OFFSET,
        "SHM_HEAD_OFFSET": schema.SHM_HEAD_OFFSET,
        "SHM_TAIL_OFFSET": schema.SHM_TAIL_OFFSET,
        "SHM_HBEAT_OFFSET": schema.SHM_HBEAT_OFFSET,
        "SHM_FIRST_TS_OFFSET": schema.SHM_FIRST_TS_OFFSET,
        "SHM_T0_OFFSET": schema.SHM_T0_OFFSET,
        "SHM_STOP_OFFSET": schema.SHM_STOP_OFFSET,
        "SHM_WSTATE_OFFSET": schema.SHM_WSTATE_OFFSET,
        "SHM_EMIT_DROP_OFFSET": schema.SHM_EMIT_DROP_OFFSET,
    }
    seen: dict[int, str] = {0: "magic"}
    for name, off in named.items():
        if off % 8 or not 0 <= off < hdr.size:
            fails.append(f"schema.{name} = {off}: not a u64 slot inside "
                         f"the {hdr.size}-byte ring header")
        if off in seen:
            fails.append(f"schema.{name} = {off} collides with "
                         f"{seen[off]}")
        seen[off] = name
    if hdr.size != schema.SHM_HDR_SIZE:
        fails.append(f"fsx_shm_ring_hdr size {hdr.size} != "
                     f"schema.SHM_HDR_SIZE {schema.SHM_HDR_SIZE}")
    # the wire record sizes the ring headers advertise
    if schema.FLOW_RECORD_SIZE != schema.FLOW_RECORD_DTYPE.itemsize:
        fails.append("FLOW_RECORD_SIZE != FLOW_RECORD_DTYPE.itemsize")
    if schema.COMPACT_RECORD_SIZE != schema.COMPACT_RECORD_DTYPE.itemsize:
        fails.append("COMPACT_RECORD_SIZE != COMPACT_RECORD_DTYPE"
                     ".itemsize")
    if header_path.exists():
        structs, _ = parse_header(header_path.read_text())
        c_hdr = structs.get("fsx_shm_ring_hdr")
        if c_hdr is None:
            fails.append("header lacks struct fsx_shm_ring_hdr")
        else:
            for fname, off in (("head", schema.SHM_HEAD_OFFSET),
                               ("tail", schema.SHM_TAIL_OFFSET)):
                try:
                    c_off = c_hdr.offset_of(fname)
                except KeyError:
                    fails.append(f"fsx_shm_ring_hdr lacks {fname}")
                    continue
                if c_off != off:
                    fails.append(f"fsx_shm_ring_hdr.{fname}: C offset "
                                 f"{c_off} != python decoder's {off}")
    return fails


class ContractReport(NamedTuple):
    """Aggregated ``fsx check`` contract result."""

    ok: bool
    checks: dict[str, list[str]]  # check name -> failures ([] = clean)

    @property
    def failures(self) -> list[str]:
        return [f"{name}: {msg}" for name, msgs in self.checks.items()
                for msg in msgs]

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "checks": {n: {"ok": not msgs, "failures": msgs}
                       for n, msgs in self.checks.items()},
        }


def run_all(*, header_path: Path = HEADER_PATH,
            image_paths: dict[bool, Path] | None = None,
            with_images: bool = True) -> ContractReport:
    """Run every cross-layer contract check; see module docstring."""
    checks = {
        "header_fresh": check_header_fresh(header_path),
        "header_layouts": check_header_layouts(header_path),
        "header_defines": check_header_defines(header_path),
        "progs_offsets": check_progs_offsets(),
        "map_specs": check_map_specs(),
        "shm_layout": check_shm_layout(header_path),
    }
    if with_images:
        checks["images"] = check_images(image_paths)
    return ContractReport(
        ok=not any(checks.values()), checks=checks)
