"""Python side of the daemon's shared-memory rings.

Mirror of ``daemon/shm_ring.hpp`` (layout generated into
``kern/fsx_schema.h`` from :mod:`flowsentryx_tpu.core.schema`): a
192-byte header (magic/capacity/record_size; head and tail cursors on
their own cache lines) followed by ``capacity`` fixed-size records.
SPSC — the daemon produces features / consumes verdicts, this process
does the reverse.  On x86-TSO, numpy u64 loads/stores of the cursors
are single MOVs and the memcpy-before-cursor-publish ordering matches
the C++ side's release stores.
"""

from __future__ import annotations

import mmap
import platform
import time
from pathlib import Path

import numpy as np

from flowsentryx_tpu.core import schema

# The cursor protocol below publishes with plain u64 loads/stores and
# relies on the total-store-order guarantee of x86 (a numpy scalar store
# is a single MOV; the record memcpy precedes the cursor store in
# program order and TSO forbids store-store reordering).  On weakly
# ordered ISAs (aarch64, riscv) that ordering is NOT guaranteed and a
# consumer could observe the new cursor before the record bytes —
# silent corruption.  Refuse loudly rather than corrupt quietly; the
# C++ daemon side uses real release/acquire atomics and is portable.
# Note: no i686 — x86-TSO holds there, but a numpy u64 store is two
# 32-bit stores on 32-bit x86, so the single-MOV premise breaks.
_TSO_ARCHS = {"x86_64", "AMD64"}


def _require_tso() -> None:
    m = platform.machine()
    if m not in _TSO_ARCHS:
        raise RuntimeError(
            f"ShmRing's plain-store cursor protocol requires x86-TSO; "
            f"machine is {m!r}. Port note: replace the cursor accesses "
            f"with atomic release/acquire (e.g. via a tiny C extension) "
            f"before enabling this transport on weakly ordered ISAs."
        )


class RingNotReady(Exception):
    """The ring file exists but its creator hasn't published the header
    magic yet (transient; wait_for retries this, and only this)."""


class ShmRing:
    """One mapped ring.  ``role`` is "consumer" or "producer"."""

    @classmethod
    def create(
        cls, path: str | Path, capacity: int, record: np.dtype
    ) -> "ShmRing":
        """Create a ring from the Python side (tests and in-process
        producers; the production feature rings are created by the C++
        daemon).  Same publish protocol as ``ShmRing::create`` in
        daemon/shm_ring.hpp: header fields first, magic last."""
        _require_tso()
        if capacity <= 0 or capacity & (capacity - 1):
            raise ValueError(f"capacity must be a power of two, got {capacity}")
        path = Path(path)
        nbytes = schema.SHM_HDR_SIZE + capacity * record.itemsize
        with open(path, "wb") as f:
            f.truncate(nbytes)
        with open(path, "r+b") as f:
            mm = mmap.mmap(f.fileno(), 0)
        hdr = np.frombuffer(mm, np.uint64, 3, 0)
        hdr[1] = capacity
        hdr[2] = record.itemsize
        hdr[0] = schema.SHM_MAGIC  # publish last
        del hdr
        mm.close()
        return cls(path, record)

    def __init__(self, path: str | Path, expect_record: np.dtype):
        _require_tso()
        self.path = Path(path)
        with open(self.path, "r+b") as f:
            self._mm = mmap.mmap(f.fileno(), 0)
        hdr = np.frombuffer(self._mm, np.uint64, 3, 0)
        if int(hdr[0]) != schema.SHM_MAGIC:
            # RingNotReady, not ValueError: the creator publishes magic
            # last, so this is the retryable mid-create window — a
            # record-size mismatch below is a REAL error that wait_for
            # must not retry into a misleading timeout.
            raise RingNotReady(f"ring magic not published yet in {self.path}")
        self.capacity = int(hdr[1])
        self.record_size = int(hdr[2])
        if self.record_size != expect_record.itemsize:
            raise ValueError(
                f"{self.path}: ring record size {self.record_size} != "
                f"dtype {expect_record.itemsize}"
            )
        self.dtype = expect_record
        self._records = np.frombuffer(
            self._mm, expect_record, self.capacity, schema.SHM_HDR_SIZE
        )
        # single-element u64 views of the cursors
        self._head = np.frombuffer(self._mm, np.uint64, 1, schema.SHM_HEAD_OFFSET)
        self._tail = np.frombuffer(self._mm, np.uint64, 1, schema.SHM_TAIL_OFFSET)

    @classmethod
    def wait_for(
        cls, path: str | Path, expect_record: np.dtype, timeout_s: float = 10.0
    ) -> "ShmRing":
        """Open a ring the daemon creates, waiting for it to appear."""
        deadline = time.monotonic() + timeout_s
        path = Path(path)
        while True:
            if path.exists() and path.stat().st_size >= schema.SHM_HDR_SIZE:
                try:
                    return cls(path, expect_record)
                except RingNotReady:
                    pass  # creator publishes magic last; retry
            if time.monotonic() > deadline:
                raise TimeoutError(f"ring {path} did not appear")
            time.sleep(0.01)

    # -- consumer side ------------------------------------------------------

    def consume(self, max_records: int) -> np.ndarray:
        t = int(self._tail[0])
        h = int(self._head[0])  # plain load; producer published with release
        n = min(h - t, max_records)
        if n <= 0:
            return self._records[:0].copy()
        # at most two contiguous slice copies (memcpy-speed; a fancy-
        # indexed gather here was the single largest cost in the drain
        # workers' profile — an index-array build plus an element-wise
        # structured-record copy, per poll)
        i = t & (self.capacity - 1)
        first = min(n, self.capacity - i)
        if first == n:
            out = self._records[i:i + n].copy()
        else:
            out = np.concatenate(
                [self._records[i:i + first], self._records[: n - first]])
        self._tail[0] = t + n     # publish after the copy
        return out

    def peek(self, max_records: int) -> tuple[list[np.ndarray], int]:
        """Zero-copy drain half: up to two contiguous VIEWS of the
        oldest readable records, without releasing them.  SPSC makes
        this safe — the producer cannot overwrite a slot until
        :meth:`advance` moves the tail — so a consumer that transforms
        records anyway (the ingest drain workers packing compact16) can
        skip the :meth:`consume` copy entirely.  Views die at
        ``advance``; copy anything that must outlive it."""
        t = int(self._tail[0])
        h = int(self._head[0])
        n = min(h - t, max_records)
        if n <= 0:
            return [], 0
        i = t & (self.capacity - 1)
        first = min(n, self.capacity - i)
        views = [self._records[i:i + first]]
        if first < n:
            views.append(self._records[: n - first])
        return views, n

    def advance(self, n: int) -> None:
        """Release ``n`` peeked records back to the producer."""
        self._tail[0] = int(self._tail[0]) + n

    # -- producer side ------------------------------------------------------

    def produce(self, records: np.ndarray) -> int:
        h = int(self._head[0])
        t = int(self._tail[0])
        n = min(len(records), self.capacity - (h - t))
        if n <= 0:
            return 0
        i = h & (self.capacity - 1)
        first = min(n, self.capacity - i)
        self._records[i:i + first] = records[:first]
        if first < n:
            self._records[: n - first] = records[first:n]
        self._head[0] = h + n
        return n

    def readable(self) -> int:
        return int(self._head[0]) - int(self._tail[0])


class SealedBatchQueue:
    """SPSC shared-memory queue of SEALED wire buffers — the ingest
    worker → engine hand-off of the sharded ingest subsystem
    (``flowsentryx_tpu/ingest/``).

    Same header geometry and x86-TSO plain-store cursor protocol as
    :class:`ShmRing`, but each "record" is one batch SLOT: an 8-word
    header (seq / n_records / wire_id / seal time / fill duration — the
    cross-process batch contract, documented at
    ``schema.SHM_BATCHQ_MAGIC``) followed by a ``[max_batch+1, words]``
    wire buffer.  The meta cache line additionally carries the worker
    control block (heartbeat, first-ts/t0 epoch handshake, stop flag,
    worker lifecycle state); every control field has exactly one writer
    side, so plain u64 stores suffice under TSO.
    """

    def __init__(self, path: str | Path, expect_payload_words: int | None = None):
        _require_tso()
        self.path = Path(path)
        with open(self.path, "r+b") as f:
            self._mm = mmap.mmap(f.fileno(), 0)
        hdr = np.frombuffer(self._mm, np.uint64, 3, 0)
        if int(hdr[0]) != schema.SHM_BATCHQ_MAGIC:
            raise RingNotReady(f"batch-queue magic not published yet in {self.path}")
        self.slots = int(hdr[1])
        self.slot_words = int(hdr[2]) // 4
        self.payload_words = self.slot_words - schema.BATCHQ_SLOT_HDR_WORDS
        if (expect_payload_words is not None
                and self.payload_words != expect_payload_words):
            raise ValueError(
                f"{self.path}: queue payload {self.payload_words} words != "
                f"expected {expect_payload_words} (batch shape mismatch "
                "between worker and engine)"
            )
        self._cells = np.frombuffer(
            self._mm, np.uint32, self.slots * self.slot_words,
            schema.SHM_HDR_SIZE,
        ).reshape(self.slots, self.slot_words)
        self._head = np.frombuffer(self._mm, np.uint64, 1, schema.SHM_HEAD_OFFSET)
        self._tail = np.frombuffer(self._mm, np.uint64, 1, schema.SHM_TAIL_OFFSET)
        self._ctl = {
            name: np.frombuffer(self._mm, np.uint64, 1, off)
            for name, off in (
                ("hbeat", schema.SHM_HBEAT_OFFSET),
                ("first_ts", schema.SHM_FIRST_TS_OFFSET),
                ("t0", schema.SHM_T0_OFFSET),
                ("stop", schema.SHM_STOP_OFFSET),
                ("wstate", schema.SHM_WSTATE_OFFSET),
                ("emit_drop", schema.SHM_EMIT_DROP_OFFSET),
                ("spin_us", schema.SHM_SPIN_US_OFFSET),
                ("idle_us", schema.SHM_IDLE_US_OFFSET),
            )
        }

    @classmethod
    def create(
        cls, path: str | Path, slots: int, payload_words: int
    ) -> "SealedBatchQueue":
        """Create a queue file (the engine parent does this BEFORE
        spawning the worker, so neither side races a missing file).
        Publish protocol: geometry first, magic last."""
        _require_tso()
        if slots < 2 or slots & (slots - 1):
            raise ValueError(f"slots must be a power of two >= 2, got {slots}")
        slot_bytes = (schema.BATCHQ_SLOT_HDR_WORDS + payload_words) * 4
        nbytes = schema.SHM_HDR_SIZE + slots * slot_bytes
        path = Path(path)
        with open(path, "wb") as f:
            f.truncate(nbytes)
        with open(path, "r+b") as f:
            mm = mmap.mmap(f.fileno(), 0)
        hdr = np.frombuffer(mm, np.uint64, 3, 0)
        hdr[1] = slots
        hdr[2] = slot_bytes
        hdr[0] = schema.SHM_BATCHQ_MAGIC  # publish last
        del hdr
        mm.close()
        return cls(path)

    @classmethod
    def wait_for(
        cls,
        path: str | Path,
        expect_payload_words: int | None = None,
        timeout_s: float = 10.0,
    ) -> "SealedBatchQueue":
        deadline = time.monotonic() + timeout_s
        path = Path(path)
        while True:
            if path.exists() and path.stat().st_size >= schema.SHM_HDR_SIZE:
                try:
                    return cls(path, expect_payload_words)
                except RingNotReady:
                    pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"batch queue {path} did not appear")
            time.sleep(0.01)

    # -- control block (one writer per field; plain stores under TSO) -------

    def ctl_get(self, name: str) -> int:
        return int(self._ctl[name][0])

    def ctl_set(self, name: str, value: int) -> None:
        self._ctl[name][0] = value

    # -- producer (worker) side ---------------------------------------------

    def produce_batch(
        self,
        payload: np.ndarray,
        *,
        seq: int,
        n_records: int,
        wire_id: int,
        seal_ns: int,
        fill_dur_us: int,
    ) -> bool:
        """Copy one sealed wire buffer in; False when the queue is full
        (the worker retries — backpressure propagates to the shard ring
        and from there to the producing daemon's drop counters)."""
        h = int(self._head[0])
        t = int(self._tail[0])
        if h - t >= self.slots:
            return False
        cell = self._cells[h & (self.slots - 1)]
        cell[schema.BATCHQ_SEQ_LO_WORD] = seq & 0xFFFFFFFF
        cell[schema.BATCHQ_SEQ_HI_WORD] = (seq >> 32) & 0xFFFFFFFF
        cell[schema.BATCHQ_N_RECORDS_WORD] = n_records
        cell[schema.BATCHQ_WIRE_ID_WORD] = wire_id
        # the seal stamp: the latency plane's per-record measurement
        # anchor (schema.py seal block) — every record of this batch
        # is timestamped here, at shm seal
        cell[schema.BATCHQ_SEAL_NS_LO_WORD] = seal_ns & 0xFFFFFFFF
        cell[schema.BATCHQ_SEAL_NS_HI_WORD] = (seal_ns >> 32) & 0xFFFFFFFF
        cell[schema.BATCHQ_FILL_DUR_US_WORD] = min(int(fill_dur_us),
                                                   0xFFFFFFFF)
        cell[schema.BATCHQ_RESERVED_WORD] = 0
        cell[schema.BATCHQ_SLOT_HDR_WORDS:] = payload.reshape(-1)
        self._head[0] = h + 1  # publish after the copy
        return True

    # -- consumer (engine) side ---------------------------------------------

    def consume_batch(self) -> tuple[np.ndarray, np.ndarray] | None:
        """``(header[8] u32 copy, payload u32 copy)`` of the oldest
        sealed batch, or None when empty.  The payload is copied out
        before the tail advances: the slot may be overwritten by the
        worker the moment it is released, and the engine's dispatch
        holds batch buffers asynchronously."""
        t = int(self._tail[0])
        h = int(self._head[0])
        if h == t:
            return None
        cell = self._cells[t & (self.slots - 1)]
        hdr = cell[: schema.BATCHQ_SLOT_HDR_WORDS].copy()
        payload = cell[schema.BATCHQ_SLOT_HDR_WORDS:].copy()
        self._tail[0] = t + 1  # release after the copy
        return hdr, payload

    def peek_batches(
        self, max_batches: int
    ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Zero-copy dequeue half: ``(header[8] u32 copy, payload u32
        VIEW)`` of up to ``max_batches`` oldest sealed slots, WITHOUT
        releasing them.  SPSC makes the views safe exactly as in
        :meth:`ShmRing.peek` — the worker cannot reuse a slot until
        :meth:`release` moves the tail — so a consumer that stages the
        payload somewhere anyway (the engine's dispatch arena) skips the
        :meth:`consume_batch` copy entirely.  Views die at ``release``;
        copy anything that must outlive it.  The 32-byte header is
        copied (it is decoded into Python ints immediately either way).
        Slots come back oldest-first; ``release(n)`` frees the first
        ``n`` of them — partial release keeps the rest peekable."""
        t = int(self._tail[0])
        h = int(self._head[0])
        n = min(h - t, max_batches)
        out: list[tuple[np.ndarray, np.ndarray]] = []
        for j in range(n):
            cell = self._cells[(t + j) & (self.slots - 1)]
            out.append((cell[: schema.BATCHQ_SLOT_HDR_WORDS].copy(),
                        cell[schema.BATCHQ_SLOT_HDR_WORDS:]))
        return out

    def release(self, n: int) -> None:
        """Hand ``n`` peeked slots back to the worker.  Every payload
        view of a released slot is DEAD the moment this returns — the
        worker may overwrite the bytes concurrently (the
        mutate-after-release tests pin that staged arena copies are
        immune to exactly this)."""
        self._tail[0] = int(self._tail[0]) + n

    def readable(self) -> int:
        return int(self._head[0]) - int(self._tail[0])


class ShmRingSource:
    """RecordSource over the daemon's feature ring.

    The record format is read off the ring header: 48 B rings carry
    full-fidelity ``FLOW_RECORD_DTYPE`` records, 16 B rings carry
    KERNEL-quantized ``COMPACT_RECORD_DTYPE`` records (a compact-emit
    data plane / ``fsxd --compact``); ``precompact`` tells the engine
    which batcher path to use."""

    def __init__(self, path: str | Path, timeout_s: float = 10.0):
        deadline = time.monotonic() + timeout_s
        try:
            self.ring = ShmRing.wait_for(
                path, schema.FLOW_RECORD_DTYPE,
                max(0.01, deadline - time.monotonic()),
            )
        except ValueError:
            # size mismatch: re-open expecting the compact record
            self.ring = ShmRing.wait_for(
                path, schema.COMPACT_RECORD_DTYPE,
                max(0.01, deadline - time.monotonic()),
            )
        self.precompact = (
            self.ring.record_size == schema.COMPACT_RECORD_SIZE
        )

    def poll(self, max_records: int) -> np.ndarray:
        return self.ring.consume(max_records)

    def exhausted(self) -> bool:
        return False  # live transport; the engine stops on its own bounds


class ShmVerdictSink:
    """VerdictSink into the daemon's verdict ring.

    Expiry translation: the engine works in f32 seconds relative to its
    ``t0_ns``; the daemon/kernel want absolute kernel-clock ns."""

    def __init__(self, path: str | Path, t0_ns: int = 0, timeout_s: float = 10.0):
        self.ring = ShmRing.wait_for(path, schema.VERDICT_RECORD_DTYPE, timeout_s)
        self.t0_ns = t0_ns
        self.dropped = 0

    def apply(self, update) -> None:
        n = len(update.key)
        if not n:
            return
        rec = np.zeros(n, schema.VERDICT_RECORD_DTYPE)
        rec["saddr"] = update.key
        rec["until_ns"] = (
            update.until_s.astype(np.float64) * 1e9
        ).astype(np.uint64) + np.uint64(self.t0_ns)
        pushed = self.ring.produce(rec)
        self.dropped += n - pushed
