"""Verdict writeback: the TPU plane's output side of the map seam.

The fused step returns, per batch, the flow keys newly condemned and
their blacklist expiries (``StepOutput.block_key`` / ``block_until``).
A :class:`VerdictSink` carries them back toward the kernel's
``blacklist_map`` — closing the loop the reference never built
(``fsx_load.py:5-12`` intent).  Sinks:

* :class:`NullSink` — benching the compute path alone.
* :class:`CollectSink` — tests/offline analysis: keeps everything.
* :class:`~flowsentryx_tpu.engine.shm.ShmVerdictSink` — production:
  pushes updates into the daemon's verdict ring; the daemon applies
  them to the pinned BPF map (kept with the shm transport).
"""

from __future__ import annotations

from typing import NamedTuple, Protocol

import numpy as np

from flowsentryx_tpu.ops.agg import INVALID_KEY


class BlacklistUpdate(NamedTuple):
    """One batch's newly blocked sources."""

    key: np.ndarray        # [K] uint32 folded source addrs
    until_s: np.ndarray    # [K] f32 expiry, engine-relative seconds


def extract_updates(block_key: np.ndarray, block_until: np.ndarray) -> BlacklistUpdate:
    """Compact a step's padded block arrays to the real updates."""
    block_key = np.asarray(block_key)
    mask = block_key != INVALID_KEY
    return BlacklistUpdate(
        key=block_key[mask], until_s=np.asarray(block_until)[mask]
    )


class VerdictSink(Protocol):
    def apply(self, update: BlacklistUpdate) -> None: ...


class NullSink:
    def apply(self, update: BlacklistUpdate) -> None:
        pass


class CollectSink:
    """Accumulates updates (last expiry wins per key, like the kernel map)."""

    def __init__(self) -> None:
        self.blocked: dict[int, float] = {}
        self.updates = 0

    def apply(self, update: BlacklistUpdate) -> None:
        self.updates += 1
        for k, u in zip(update.key.tolist(), update.until_s.tolist()):
            self.blocked[k] = u
