"""Configuration system.

The reference hard-codes all policy as magic constants in the kernel
program — ``blocked_for_time = 10`` s, ``pps_threshold = 1000``,
``bps_threshold = 125000000`` (``src/fsx_kern.c:308-310``) — with a
comment that disagrees with the code (``fsx_kern.c:303-307``), and lists
"config files" as future work (``README.md:70-74,142-145``,
``TODO.md:60-61``).  This module is that promised config system:

* typed, validated dataclasses for every knob,
* JSON round-trip for files / CLI overrides,
* :func:`pack_kernel_config` — serializes the policy subset into the
  fixed binary layout of the kernel's BPF config map (generated as
  ``struct fsx_config`` in ``kern/fsx_schema.h``), replacing the
  reference's compile-time constants with a runtime-updatable map.

Configs are hashable (frozen) so they can be closed over by ``jit``-ed
functions as static arguments.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import struct
import typing
from dataclasses import dataclass, field
from typing import Any


class LimiterKind(enum.Enum):
    """Which rate-limiter algorithm guards a flow.

    The reference implements only FIXED_WINDOW (``fsx_kern.c:243-263``)
    and *specifies* sliding-window and token-bucket
    (``README.md:153-162``); all three are first-class here.
    """

    FIXED_WINDOW = "fixed_window"
    SLIDING_WINDOW = "sliding_window"
    TOKEN_BUCKET = "token_bucket"


@dataclass(frozen=True)
class LimiterConfig:
    """Rate-limiter policy (successor of ``fsx_kern.c:303-312``)."""

    kind: LimiterKind = LimiterKind.FIXED_WINDOW
    pps_threshold: float = 1000.0       # fsx_kern.c:309
    bps_threshold: float = 125_000_000.0  # fsx_kern.c:310 (125 MB/s ≈ 1 Gbit/s)
    window_s: float = 1.0               # fsx_kern.c:243 (1e9 ns window)
    bucket_rate_pps: float = 1000.0     # token refill rate (packets/s)
    bucket_burst: float = 2000.0        # token bucket depth (packets)
    #: Byte dimension of the token bucket (the spec rate-limits
    #: bandwidth as well as packets, README.md:153-162).  Both zero =
    #: byte dimension disabled (packet-count only); defaults mirror the
    #: window limiters' byte threshold.  One zero without the other is
    #: rejected: burst with no refill would permanently block a source
    #: after its first burst, refill with no depth can never admit.
    bucket_rate_bps: float = 125_000_000.0   # byte refill rate (bytes/s)
    bucket_burst_bytes: float = 250_000_000.0  # byte bucket depth
    block_s: float = 10.0               # fsx_kern.c:308 blacklist TTL

    def __post_init__(self) -> None:
        if self.window_s <= 0:
            raise ValueError("window_s must be positive")
        if self.block_s < 0:
            raise ValueError("block_s must be non-negative")
        if min(self.pps_threshold, self.bps_threshold,
               self.bucket_rate_pps, self.bucket_burst,
               self.bucket_rate_bps, self.bucket_burst_bytes) < 0:
            raise ValueError("thresholds must be non-negative")
        if (self.bucket_rate_bps == 0) != (self.bucket_burst_bytes == 0):
            raise ValueError(
                "bucket_rate_bps and bucket_burst_bytes must be both "
                "zero (byte dimension off) or both positive"
            )


#: L4 protocol names accepted in rules (number literals also work).
_PROTO_CODES = {"any": 0, "icmp": 1, "tcp": 6, "udp": 17, "icmpv6": 58}


@dataclass(frozen=True)
class RuleConfig:
    """One stateless-firewall drop rule — the reference's planned
    "basic firewall ... config files ... rules to drop certain packets"
    (``README.md:70-74``), enforced in the kernel data plane before any
    per-IP state is touched.

    ``proto``/``dport`` of 0 (or ``"any"``) are wildcards; at least one
    must be concrete.  Matching precedence per packet: exact
    (proto, dport), then (proto, any-port), then (any-proto, dport).
    """

    proto: str | int = "any"   # "tcp"/"udp"/"icmp"/"icmpv6"/number/"any"
    dport: int = 0             # 0 = any
    action: str = "drop"

    def __post_init__(self) -> None:
        if self.action != "drop":
            raise ValueError(f"unknown rule action {self.action!r}")
        if not 0 <= self.dport <= 65535:
            raise ValueError("dport must be 0..65535")
        if self.proto_code() == 0 and self.dport == 0:
            raise ValueError("a rule needs a concrete proto or dport")

    def proto_code(self) -> int:
        if isinstance(self.proto, int):
            if not 0 <= self.proto <= 255:
                raise ValueError("proto number must be 0..255")
            return self.proto
        try:
            return _PROTO_CODES[self.proto.lower()]
        except KeyError:
            raise ValueError(f"unknown protocol {self.proto!r}") from None

    def key(self) -> int:
        from flowsentryx_tpu.core import schema

        return schema.pack_rule_key(self.proto_code(), self.dport)


@dataclass(frozen=True)
class ModelConfig:
    """Classifier selection + decision policy."""

    name: str = "logreg_int8"
    threshold: float = 0.5              # sigmoid cutoff (model.py:205-208)
    quantized: bool = True
    ml_block_s: float = 10.0            # blacklist TTL for ML-flagged sources
    #: Young-flow vote (SERVE_r04 finding: a flow's first records carry
    #: no variance/IAT mass and can score malicious, so without a vote
    #: EVERY benign source eventually gets ML-blacklisted).  A flow's
    #: malicious-scored records count as votes only once the engine has
    #: seen ``vote_k`` records from it (the kernel emits every packet
    #: while a flow is young, fsx_kern.c:163-165, so maturity arrives
    #: within the first k packets); an ML block needs ``vote_m`` votes.
    #: Flows the table cannot track (arbitration loss / full table —
    #: an attacker must not escape detection by filling the table) use
    #: a batch-local form: > vote_k records in the batch with >= vote_m
    #: scored malicious (tracked flows get this burst rule too, so a
    #: dense single-batch flood can't hide behind its youth).  Votes
    #: decay with a ``vote_decay_s`` half-life and reset when a block
    #: fires — an isolated borderline mis-score hours ago must not
    #: leave a benign flow permanently one record from a block.
    #: ``vote_k=0, vote_m=1`` restores the immediate pre-vote behavior.
    vote_k: int = 4
    vote_m: int = 2
    vote_decay_s: float = 60.0  # vote half-life; 0 = no decay

    def __post_init__(self) -> None:
        if self.vote_k < 0:
            raise ValueError("vote_k must be >= 0")
        if self.vote_m < 1:
            raise ValueError("vote_m must be >= 1")
        if self.vote_decay_s < 0:
            raise ValueError("vote_decay_s must be >= 0")


@dataclass(frozen=True)
class TableConfig:
    """Per-IP state table sizing.

    ``capacity`` supersedes the reference's ``MAX_TRACK_IPS = 100000``
    LRU cap (``fsx_struct.h:7``); default 2^20 ≈ 1M concurrent source
    IPs (BASELINE config 5).  ``probes`` bounds the open-addressing
    probe sequence (static for XLA).  ``stale_s``: slots idle longer
    than this may be reclaimed on insert — the analog of
    ``BPF_MAP_TYPE_LRU_HASH`` eviction (``fsx_kern.c:66``).
    """

    capacity: int = 1 << 20
    probes: int = 8
    stale_s: float = 30.0
    #: Hash salt mixed into slot probing AND owner routing
    #: (ops/hashtable.hash_u32).  0 = deterministic/unsalted (tests,
    #: reproducible runs); ``fsx serve`` draws a random boot-time salt
    #: so an attacker cannot precompute table-slot collisions or aim
    #: every flow at one owner device (the exposure the unsalted hash
    #: created — the reference's kernel LRU maps have no analog, their
    #: hashing is kernel-internal and already seeded).  Carried in
    #: checkpoints so a restored table's slot layout stays valid, and in
    #: the packed kernel-config blob for config-file deployments that
    #: fix the salt explicitly (see ``KERNEL_CONFIG_FIELDS``).
    salt: int = 0
    #: In-step aging: slots idle longer than ``evict_ttl_s`` (device-
    #: clock seconds since last_seen, still-valid blacklist entries
    #: exempt) are freed IN-GRAPH by a rolling sweep — each batch the
    #: step opens by sweeping one ``capacity/evict_every``-row window,
    #: the window base advancing with the batch counter, so every row
    #: is re-examined once per ``evict_every`` batches
    #: (``ops/fused.evict_idle_epoch``; shard-local on a mesh, no new
    #: collectives or D2H, constant per-batch cost).  0 disables the
    #: sweep entirely: the staged step graphs are then unchanged from
    #: the pre-eviction era (stale-slot reclamation on insert still
    #: works as before), which is what keeps parity baselines
    #: byte-identical.  Distinct from ``stale_s`` (reclaim-on-insert
    #: eligibility): reclamation frees a slot only when a new flow
    #: happens to probe it; eviction bounds table occupancy under
    #: churn whether or not the slot is re-probed.
    evict_ttl_s: float = 0.0
    #: Batches per full sweep cycle: each batch sweeps
    #: ``ceil(capacity / evict_every)`` rows, and a row idle past the
    #: ttl is freed within one cycle of crossing it.
    evict_every: int = 64

    def __post_init__(self) -> None:
        if self.capacity & (self.capacity - 1) or self.capacity <= 0:
            raise ValueError("capacity must be a power of two")
        if self.capacity > 1 << 29:
            # the packed arbitration sort key (slot*2 + priority bit,
            # parked at 2*capacity) must fit int32
            raise ValueError("capacity must be <= 2^29")
        if self.probes < 1:
            raise ValueError("probes must be >= 1")
        if not 0 <= self.salt < 1 << 32:
            raise ValueError("salt must fit in u32")
        if self.evict_ttl_s < 0:
            raise ValueError("evict_ttl_s must be >= 0 (0 disables)")
        if self.evict_every < 1:
            raise ValueError("evict_every must be >= 1")


@dataclass(frozen=True)
class BatchConfig:
    """Micro-batcher policy: flush at ``max_batch`` records or after
    ``deadline_us``, whichever first (SURVEY.md §7.2: "2048 vectors or
    200 µs")."""

    max_batch: int = 2048
    deadline_us: int = 200
    #: Slots in the compact device→host verdict wire (ops/fused.py
    #: ``pack_verdict_wire``): the step compacts newly-blocked
    #: ``(key, until)`` pairs into a fixed ``[verdict_k]`` buffer plus a
    #: count, so the steady-state readback is O(verdict_k) bytes instead
    #: of 8 B/record.  A batch blocking more than ``verdict_k`` flows
    #: sets the wire's overflow flag and the engine falls back to the
    #: full-array fetch for that batch — a block is never lost, it just
    #: costs the old readback once.  0 disables compaction entirely
    #: (every batch fetches the full ``[B]`` arrays — the pre-compaction
    #: wire, kept for parity tests and measurement baselines).
    verdict_k: int = 64
    #: Engine pipe depth: how many batches may be dispatched-but-unsunk
    #: before the dispatch thread blocks on the sink (the backpressure
    #: bound engine/engine.py waits on).  Must be >= 1 — a zero-depth
    #: pipe can never dispatch, it deadlocks the loop on its first
    #: batch.  ``Engine(readback_depth=...)`` overrides per instance.
    readback_depth: int = 8

    def __post_init__(self) -> None:
        if self.max_batch <= 0 or self.deadline_us <= 0:
            raise ValueError("max_batch and deadline_us must be positive")
        if not isinstance(self.verdict_k, int):
            # a float K silently changes the jit cache key per config
            # load AND miscomputes the [2K+4] wire length downstream
            raise ValueError("verdict_k must be an int")
        if self.verdict_k < 0:
            raise ValueError("verdict_k must be >= 0 (0 disables compaction)")
        if self.verdict_k > self.max_batch:
            # at most max_batch flows can block per batch, so slots past
            # that can never fill — a config asking for them is a typo'd
            # K (or B), not a bigger wire
            raise ValueError(
                f"verdict_k ({self.verdict_k}) must be <= max_batch "
                f"({self.max_batch}): a batch cannot block more flows "
                "than it has records")
        if self.readback_depth < 1:
            raise ValueError("readback_depth must be >= 1 (the pipe "
                             "needs at least one in-flight batch)")


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout for the sharded state table + data-parallel
    scoring.  ``ip_axis`` devices shard table rows by IP hash; batch
    scoring is data-parallel over the same axis."""

    ip_axis: int = 1                    # number of devices on the 'ip' axis
    axis_name: str = "ip"


@dataclass(frozen=True)
class FsxConfig:
    """Root config."""

    limiter: LimiterConfig = field(default_factory=LimiterConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    table: TableConfig = field(default_factory=TableConfig)
    batch: BatchConfig = field(default_factory=BatchConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    #: Stateless firewall rules (kernel plane; RuleConfig docstring)
    rules: tuple[RuleConfig, ...] = ()
    interface: str = "eth0"             # XDP attach point

    def __post_init__(self) -> None:
        from flowsentryx_tpu.core import schema

        if len(self.rules) > schema.MAX_RULES:
            raise ValueError(f"at most {schema.MAX_RULES} rules")
        keys = [r.key() for r in self.rules]
        if len(set(keys)) != len(keys):
            raise ValueError("duplicate (proto, dport) rule")

    # -- JSON round-trip ----------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        def enc(obj: Any) -> Any:
            if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
                return {f.name: enc(getattr(obj, f.name))
                        for f in dataclasses.fields(obj)}
            if isinstance(obj, enum.Enum):
                return obj.value
            if isinstance(obj, (list, tuple)):
                return [enc(x) for x in obj]
            return obj

        return enc(self)

    def to_json(self, **kw: Any) -> str:
        return json.dumps(self.to_dict(), indent=2, **kw)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FsxConfig":
        def dec(tp: type, v: Any) -> Any:
            origin = typing.get_origin(tp)
            if origin in (tuple, list):
                elem = typing.get_args(tp)[0]
                return tuple(dec(elem, x) for x in v)
            if dataclasses.is_dataclass(tp):
                hints = typing.get_type_hints(tp)
                names = {f.name for f in dataclasses.fields(tp)}
                kwargs = {}
                for k, val in v.items():
                    if k not in names:
                        raise KeyError(f"unknown config key {k!r} for {tp.__name__}")
                    kwargs[k] = dec(hints[k], val)
                return tp(**kwargs)
            if isinstance(tp, type) and issubclass(tp, enum.Enum):
                return tp(v)
            return v

        return dec(cls, d)

    @classmethod
    def from_json(cls, s: str) -> "FsxConfig":
        return cls.from_dict(json.loads(s))

    # -- kernel config map --------------------------------------------------

    #: ``struct fsx_config`` fields, in wire order.  The C struct in
    #: ``kern/fsx_schema.h`` is GENERATED from this tuple (codegen.py),
    #: and the pack format below is derived from it, so the three views
    #: cannot drift.
    KERNEL_CONFIG_FIELDS: typing.ClassVar[tuple[tuple[str, str, str], ...]] = (
        ("limiter_kind", "u32", "FSX_LIMITER_*"),
        ("valid", "u32", "nonzero once a config has been pushed; the"
         " all-zero ARRAY-map default means \"no config yet\" (fail open)"),
        ("pps_threshold", "u64", "packets per window"),
        ("bps_threshold", "u64", "bytes per window"),
        ("window_ns", "u64", ""),
        ("block_ns", "u64", "blacklist TTL"),
        ("bucket_rate_pps", "u64", "token refill rate (packets/s)"),
        ("bucket_burst", "u64", "token bucket depth (packets)"),
        ("bucket_rate_bps", "u64", "byte-bucket refill rate (bytes/s);"
         " 0 with 0 depth = byte dimension off"),
        ("bucket_burst_bytes", "u64", "byte bucket depth (bytes)"),
        ("rule_count", "u64", "number of stateless firewall rules pushed"
         " into rule_map; 0 skips the rule lookups entirely"),
        ("hash_salt", "u64", "salt for user-plane slot/owner hashing"
         " (low 32 bits used).  No kernel-side consumer exists: BPF maps"
         " hash internally with their own seed.  Carried in the blob so"
         " a deployment that FIXES the salt in its config file presents"
         " one value to both planes; a serve-drawn random salt is"
         " user-plane only"),
    )

    KERNEL_CONFIG_FMT = "<" + "".join(
        {"u32": "I", "u64": "Q"}[t] for _, t, _ in KERNEL_CONFIG_FIELDS
    )
    KERNEL_CONFIG_SIZE = struct.calcsize(KERNEL_CONFIG_FMT)  # 88

    _KIND_CODE = {
        LimiterKind.FIXED_WINDOW: 0,
        LimiterKind.SLIDING_WINDOW: 1,
        LimiterKind.TOKEN_BUCKET: 2,
    }

    def pack_kernel_config(self) -> bytes:
        """Binary blob for the kernel's config array map (index 0).

        Integer units (packets, bytes, nanoseconds) because eBPF has no
        floats (``fsx_kern_ml.c:3-6``).
        """
        lim = self.limiter
        return struct.pack(
            self.KERNEL_CONFIG_FMT,
            self._KIND_CODE[lim.kind],
            1,  # valid: distinguishes a pushed config from the map's zero fill
            int(lim.pps_threshold),
            int(lim.bps_threshold),
            int(lim.window_s * 1e9),
            int(lim.block_s * 1e9),
            int(lim.bucket_rate_pps),
            int(lim.bucket_burst),
            int(lim.bucket_rate_bps),
            int(lim.bucket_burst_bytes),
            len(self.rules),
            int(self.table.salt),
        )

    def rule_entries(self) -> list[tuple[int, int]]:
        """``(key, action)`` pairs for the kernel rule map (key packing
        in :func:`flowsentryx_tpu.core.schema.pack_rule_key`)."""
        from flowsentryx_tpu.core import schema

        return [(r.key(), schema.RULE_DROP) for r in self.rules]


DEFAULT_CONFIG = FsxConfig()
