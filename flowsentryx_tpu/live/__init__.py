"""``fsx live`` — the liveness & progress leg of the static suite.

Jax-free by design: the registry scan is pure ``ast``, the checker
drives the real protocol objects through
:func:`flowsentryx_tpu.sync.interleave.explore_live` on the same
sub-second import path as the supervisor.  See docs/LIVENESS.md.
"""

from flowsentryx_tpu.live.registry import (  # noqa: F401
    PROGRESS, ProgressEntry, registered_sites, scan_blocking_sites,
    validate,
)
