"""Headline benchmark: Mpps classified through the fused TPU pipeline step.

Measures the full user-plane hot path on whatever accelerator the session
exposes (real TPU chip under axon; CPU elsewhere): raw flow records →
one contiguous host→device transfer → fused step (on-device decode →
aggregate → hash-table → limiter → int8 classifier → verdict → state
scatter) → verdict readback.

The reference publishes no throughput numbers (SURVEY.md §6); the target
is BASELINE.json's north star: >=10 Mpps classified, <1 ms p99
feature→verdict, on one chip.  ``vs_baseline`` is the ratio of measured
Mpps to the 10 Mpps target.

Budget discipline (round-1 failure mode: the whole run forfeited on one
900 s subprocess timeout, BENCH_r01.json):

* ``--budget-s`` (default $FSX_BENCH_BUDGET_S or 840) is a HARD wall-
  clock ceiling for the entire run.  The parent slices it across phases
  and always prints its one JSON line before the ceiling.
* each phase child checkpoints every completed measurement to a JSONL
  sidecar file as it lands; if the child stalls or dies, the parent
  kills it at its deadline and recovers the partial results from the
  sidecar.  A stalled tunnel costs the remaining chunks, not the round.
* iteration counts adapt: the child times one probe chunk first, then
  sizes chunks to ~5 s and runs as many as fit in its slice.

Environment honesty — the dev/CI environment reaches the TPU through the
axon tunnel, which has measured pathologies that real (locally attached)
TPU runtimes do not (each auto-detected and engineered around, see
flowsentryx_tpu/ops/fused.py:donation_supported):

* device init alone can take minutes (tunnel warm-up);
* every device→host readback of a computed result costs a fixed ~70 ms
  RPC round trip regardless of payload size — reported as
  ``sync_floor_ms`` so p99 can be read net of the floor;
* the first such readback permanently drops the process's dispatch rate
  ~40×, so each phase below runs in its own subprocess with readbacks
  only at the end;
* buffer donation wedges the client on first readback (compute keeps
  full speed), so the donated steady-state throughput phase is a
  compute-only epoch that reports before exiting.

Usage: ``python bench.py`` prints exactly ONE JSON line on stdout;
progress chatter goes to stderr.  (``--phase=...`` runs a single phase —
used internally via subprocess.)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

TARGET_MPPS = 10.0  # BASELINE.json north_star: >=10 Mpps on one v5e chip
B = 16384  # 2048-record kernel micro-batches, coalesced 8:1 under load
TABLE_CAP = 1 << 20  # BASELINE config 5: 1M concurrent source IPs

if "--smoke" in sys.argv:  # CI-shape run: small and CPU-friendly
    sys.argv.remove("--smoke")
    B = 1024
    TABLE_CAP = 1 << 12


def _argval(name: str, default: float) -> float:
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return float(a.split("=", 1)[1])
    return default


BUDGET_S = _argval("budget-s", float(os.environ.get("FSX_BENCH_BUDGET_S", "840")))
T_START = time.perf_counter()


def remaining() -> float:
    return BUDGET_S - (time.perf_counter() - T_START)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class Sidecar:
    """Append-only JSONL checkpoint stream the parent can recover from."""

    def __init__(self, path: str | None):
        self.f = open(path, "a", buffering=1) if path else None

    def emit(self, kind: str, **kv) -> None:
        if self.f:
            self.f.write(json.dumps({"kind": kind, **kv}) + "\n")
            self.f.flush()


def make_raw_batches(n_batches: int, batch: int, n_ips: int, seed: int = 0):
    """Synthetic flood traffic, pre-packed to the device wire format
    (BASELINE config 4/5 shape: mixed traffic, many concurrent IPs)."""
    from flowsentryx_tpu.core import schema

    rng = np.random.default_rng(seed)
    bufs = []
    for i in range(n_batches):
        buf = np.zeros(batch, dtype=schema.FLOW_RECORD_DTYPE)
        buf["saddr"] = rng.integers(1, n_ips + 1, batch).astype(np.uint32)
        buf["pkt_len"] = rng.integers(64, 1500, batch)
        buf["ts_ns"] = (i * batch + np.arange(batch)) * 100  # 10 Mpps spacing
        buf["ip_proto"] = rng.choice([1, 6, 17], batch)  # ICMP/TCP/UDP mix
        buf["feat"] = rng.integers(0, 1 << 20, (batch, schema.NUM_FEATURES))
        bufs.append(buf)
    return bufs


def _setup(donate: bool, side: Sidecar):
    # Breadcrumbs BEFORE and DURING device init (round-2 failure: the
    # axon tunnel can wedge inside jax.devices() for many minutes; with
    # no pre-init sidecar record the parent couldn't tell a wedged init
    # from a wedged measurement).  The parent watches for the "device"
    # record and kills + retries / falls back to CPU if it doesn't land
    # within the init deadline.
    side.emit("init", stage="import_jax",
              at_s=round(time.perf_counter() - T_START, 1))
    import jax

    # The session's sitecustomize force-registers the axon TPU platform
    # and overrides JAX_PLATFORMS from the environment; honor an explicit
    # cpu request (CI smoke + fallback runs) via the config API, which
    # still wins.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from flowsentryx_tpu.core import schema
    from flowsentryx_tpu.core.config import BatchConfig, FsxConfig, TableConfig
    from flowsentryx_tpu.models import get_model
    from flowsentryx_tpu.ops import fused

    side.emit("init", stage="devices_call",
              at_s=round(time.perf_counter() - T_START, 1))
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    init_s = round(time.perf_counter() - t0, 1)
    side.emit("device", backend=dev.platform, device_kind=dev.device_kind,
              init_s=init_s)
    log(f"device: {dev.platform}/{dev.device_kind} (init {init_s:.1f}s)")

    cfg = FsxConfig(
        table=TableConfig(capacity=TABLE_CAP), batch=BatchConfig(max_batch=B)
    )
    spec = get_model(cfg.model.name)
    params = spec.init()
    # Production hot path: the COMPACT 16 B/record wire format in
    # bit-exact "model" quantization (core/schema.py) — 3× fewer
    # host→device bytes than the 48 B ring record, which is the
    # bandwidth-critical hop at 10 Mpps (480 → 160 MB/s).
    quant = schema.model_quant_args(params)
    step = fused.make_jitted_compact_step(
        cfg, spec.classify_batch, donate=donate, **quant
    )
    table = jax.device_put(schema.make_table(cfg.table.capacity))
    stats = jax.device_put(schema.make_stats())
    raws = [
        schema.encode_compact(b, B, t0_ns=0, **quant)
        for b in make_raw_batches(16, B, n_ips=1 << 20)
    ]
    return jax, schema, cfg, params, step, table, stats, raws, init_s


def phase_throughput(side: Sidecar, deadline_rel: float) -> dict:
    """Donated steady-state loop; compute-only (see module docstring).

    Adaptive: sizes chunks to ~5 s from a timed probe chunk, then runs
    as many as fit before the deadline; every chunk checkpoints to the
    sidecar so a mid-phase stall still leaves a measurable median."""
    deadline = time.perf_counter() + deadline_rel
    jax, schema, cfg, params, step, table, stats, raws, init_s = _setup(True, side)
    dev = jax.devices()[0]

    t0 = time.perf_counter()
    table, stats, out = step(table, stats, params, raws[0])
    jax.block_until_ready(out.verdict)
    compile_s = time.perf_counter() - t0
    side.emit("compile", compile_s=round(compile_s, 1))
    log(f"compile: {compile_s:.1f}s")

    result = {
        "mpps": 0.0, "chunk_mpps": [], "iters": 0,
        "compile_s": compile_s, "backend": dev.platform,
        "device_kind": dev.device_kind, "init_s": init_s,
    }

    # Transport + device capability diagnostics FIRST, before the e2e
    # chunks below consume the link's burst budget: the dev tunnel
    # meters H2D in tiers (measured: ~150 MB burst at 1.3-1.6 GB/s,
    # then ~250 MB/s, then ~25 MB/s with dispatch penalties; idle
    # restores it), so diagnostics taken after 500 MB of chunks would
    # describe the drained tunnel, not the chip.
    #   device_mpps — device-resident step rate, no H2D in the loop:
    #   the chip's actual feature→verdict capability (what a local-PCIe
    #   deployment sees; production never binds on 16 B/record wire).
    if remaining() > 30 and time.perf_counter() + 20 < deadline:
        big = np.concatenate([np.ascontiguousarray(r).reshape(-1)
                              for r in raws])
        jax.block_until_ready(jax.device_put(big[:1024]))  # warm path
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(big))
        result["h2d_mbps"] = round(big.nbytes / (time.perf_counter() - t0)
                                   / 1e6, 1)

        dev_feeds = [jax.device_put(r) for r in raws]
        jax.block_until_ready(dev_feeds)
        iters = 200
        t0 = time.perf_counter()
        for i in range(iters):
            table, stats, out = step(table, stats, params,
                                     dev_feeds[i % len(dev_feeds)])
        jax.block_until_ready(out.verdict)
        dt = (time.perf_counter() - t0) / iters
        result["device_mpps"] = round(B / dt / 1e6, 2)
        del dev_feeds
        side.emit("transport", h2d_mbps=result["h2d_mbps"],
                  device_mpps=result["device_mpps"])
        log(f"device-resident: {result['device_mpps']:.1f} Mpps, "
            f"link {result['h2d_mbps']:.0f} MB/s")

    # Explicit H2D prefetch: device_put is async, so enqueueing the
    # next wire buffers keeps the transfer engine ahead of the compute
    # stream (the step consumes buffers whose transfer already started).
    # Depth 3 bounds host memory pinned in flight.
    PREFETCH = 3

    def feed(k: int):
        return jax.device_put(raws[k % len(raws)])

    # Probe chunk: small, times a single dispatch round trip.  The
    # pre-staged transfers complete before the clock starts so they
    # can't inflate the probe.
    probe_iters = 10 if dev.platform != "cpu" else 3
    k = 0
    pre = [feed(i) for i in range(PREFETCH)]
    jax.block_until_ready(pre)
    t0 = time.perf_counter()
    for _ in range(probe_iters):
        pre.append(feed(k + PREFETCH))
        table, stats, out = step(table, stats, params, pre.pop(0))
        k += 1
    jax.block_until_ready(out.verdict)
    dt = time.perf_counter() - t0
    probe_mpps = probe_iters * B / dt / 1e6
    per_iter = dt / probe_iters
    result["chunk_mpps"].append(round(probe_mpps, 2))
    result["iters"] += probe_iters
    side.emit("chunk", mpps=round(probe_mpps, 2), iters=probe_iters)
    log(f"probe chunk: {probe_mpps:.2f} Mpps ({per_iter * 1e3:.1f} ms/iter)")

    # Size real chunks to ~5 s each, capped; run while time permits,
    # keeping a reserve for the final block_until_ready + JSON write.
    chunk_iters = max(5, min(200, int(5.0 / max(per_iter, 1e-6))))
    reserve = max(5.0, 4 * per_iter * chunk_iters)
    max_chunks = 10
    while len(result["chunk_mpps"]) < max_chunks + 1:
        if time.perf_counter() + chunk_iters * per_iter * 2 + reserve > deadline:
            break
        t0 = time.perf_counter()
        for _ in range(chunk_iters):
            pre.append(feed(k + PREFETCH))
            table, stats, out = step(table, stats, params, pre.pop(0))
            k += 1
        jax.block_until_ready(out.verdict)
        dt = time.perf_counter() - t0
        mpps = chunk_iters * B / dt / 1e6
        per_iter = 0.5 * per_iter + 0.5 * dt / chunk_iters  # smooth estimate
        result["chunk_mpps"].append(round(mpps, 2))
        result["iters"] += chunk_iters
        side.emit("chunk", mpps=round(mpps, 2), iters=chunk_iters)
        log(f"chunk: {mpps:.2f} Mpps ({chunk_iters} iters)")

    # Median over steady-state chunks (exclude the probe when real
    # chunks exist: the probe is tiny and noisy).  The max chunk is
    # reported separately as burst_mpps: under the tunnel's tiered
    # throttle the first chunks run from burst credit at link speed,
    # later ones at the metered sustained rate — the median is the
    # honest sustained number, the max shows the burst regime a
    # local-PCIe deployment would sustain continuously.
    steady = result["chunk_mpps"][1:] or result["chunk_mpps"]
    result["mpps"] = float(np.median(steady))
    result["burst_mpps"] = float(np.max(steady))
    if "device_mpps" in result:
        result["transport_limited"] = bool(
            result["device_mpps"] > 2 * result["mpps"]
        )
    side.emit("result", **result)
    return result


def phase_latency(side: Sidecar, deadline_rel: float) -> dict:
    """Undonated per-batch round trips (feature → verdict readback) +
    cumulative verdict stats.  Readbacks degrade the axon session, which
    is why this runs in its own subprocess — the measured p50/p99
    include that degradation plus the tunnel sync floor, both absent on
    locally attached hardware."""
    deadline = time.perf_counter() + deadline_rel
    jax, schema, cfg, params, step, table, stats, raws, init_s = _setup(False, side)
    dev = jax.devices()[0]

    table, stats, out = step(table, stats, params, raws[0])
    jax.block_until_ready(out.verdict)
    side.emit("compile", compile_s=0)

    # sync floor: trivial 32-byte compute+readback round trip
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jax.device_put(jnp.zeros((8,), jnp.float32))
    np.asarray(f(x))
    floors = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(f(x))
        floors.append(time.perf_counter() - t0)
    sync_floor_ms = float(np.median(floors) * 1e3)
    side.emit("sync_floor", sync_floor_ms=round(sync_floor_ms, 1))
    log(f"sync floor: {sync_floor_ms:.0f} ms")

    lat_iters = 40 if dev.platform != "cpu" else 15
    lats = []
    for i in range(lat_iters):
        if time.perf_counter() + 3 * (lats[-1] if lats else 0.2) > deadline:
            log(f"latency: deadline after {len(lats)} iters")
            break
        t1 = time.perf_counter()
        table, stats, out = step(table, stats, params, raws[i % len(raws)])
        np.asarray(out.verdict)
        np.asarray(out.block_key)
        lats.append(time.perf_counter() - t1)
        if len(lats) % 10 == 0:
            side.emit("lat_partial", n_lat_iters=len(lats),
                      p50_ms=round(float(np.percentile(np.array(lats) * 1e3, 50)), 2))

    st = schema.GlobalStats(*stats)
    result = {
        "sync_floor_ms": sync_floor_ms,
        "n_lat_iters": len(lats),
        "init_s": init_s,
        "stats": st.to_dict(),
    }
    if lats:  # an empty sample is "missing", never "0 ms" (a fake pass)
        lats_ms = np.array(lats) * 1e3
        result["p50_ms"] = float(np.percentile(lats_ms, 50))
        result["p99_ms"] = float(np.percentile(lats_ms, 99))
    side.emit("result", **result)
    return result


def _recover_sidecar(path: str) -> dict | None:
    """Rebuild the best partial result from a dead child's sidecar.

    Per-line parsing: a child SIGKILLed mid-write leaves one truncated
    final line, which must not void the valid checkpoints before it."""
    lines = []
    try:
        for l in open(path):
            try:
                lines.append(json.loads(l))
            except json.JSONDecodeError:
                continue
    except OSError:
        return None
    if not lines:
        return None
    out: dict = {"partial": True}
    chunks = []
    for rec in lines:
        kind = rec.pop("kind")
        if kind == "result":
            rec.pop("partial", None)
            return {**rec, "partial": False}
        if kind == "chunk":
            chunks.append(rec["mpps"])
        elif kind == "init":
            # Post-mortem trail: which init stage the child reached
            # (import_jax vs devices_call) and when.
            out.setdefault("init_stages", []).append(rec)
        elif kind in ("device", "compile", "sync_floor", "lat_partial"):
            out.update(rec)
    if chunks:
        steady = chunks[1:] or chunks
        out["chunk_mpps"] = chunks
        out["mpps"] = float(np.median(steady))
    return out


def _sidecar_has(path: str, kind: str) -> bool:
    try:
        with open(path) as f:
            for l in f:
                try:
                    if json.loads(l).get("kind") == kind:
                        return True
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return False


def _run_phase(phase: str, deadline_rel: float, *,
               force_cpu: bool = False,
               init_deadline: float | None = None) -> dict | None:
    """Run one phase in a subprocess with a hard kill at its deadline;
    recover partial results from the sidecar if it dies or stalls.

    ``init_deadline``: if set, the child must publish its sidecar
    "device" record (i.e. ``jax.devices()`` must return) within that
    many seconds or it is killed early — this is how a wedged axon
    tunnel init costs its deadline, not the whole phase slice.  The
    returned partial dict then carries ``init_wedged=True``.

    ``force_cpu``: run the child with JAX_PLATFORMS=cpu (honored by
    ``_setup`` via the config API, which beats the sitecustomize's
    platform override) — the labeled-CPU fallback path.

    The kill fires at deadline_rel + 10 s — callers must leave at least
    that margin before the overall budget ceiling.  (The child's own
    SIGALRM backstop cannot fire while wedged inside a blocking C call,
    so this parent timeout is the real hard stop.)"""
    smoke = ["--smoke"] if B == 1024 else []
    fd, side_path = tempfile.mkstemp(prefix=f"fsx_bench_{phase}_",
                                     suffix=".jsonl")
    os.close(fd)
    argv = [sys.executable, __file__, f"--phase={phase}",
            f"--deadline-rel={deadline_rel:.1f}", f"--sidecar={side_path}"] + smoke
    env = dict(os.environ)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    log(f"phase {phase}: deadline {deadline_rel:.0f}s"
        + (f", init deadline {init_deadline:.0f}s" if init_deadline else "")
        + (", forced cpu" if force_cpu else ""))
    rec: dict | None = None
    init_wedged = False
    t0 = time.perf_counter()
    # Both streams go to temp files (binary, decoded with replace): a
    # PIPE would deadlock a chatty child against the 64 KB pipe buffer,
    # and a SIGKILL mid-write can truncate a multibyte sequence.
    with tempfile.TemporaryFile() as outf, tempfile.TemporaryFile() as errf:
        proc = subprocess.Popen(
            argv, stdout=outf, stderr=errf, env=env,
            cwd=str(__import__("pathlib").Path(__file__).parent),
        )
        device_seen = init_deadline is None
        while True:
            try:
                ret = proc.wait(timeout=2.0)
                break
            except subprocess.TimeoutExpired:
                pass
            now = time.perf_counter() - t0
            if not device_seen and _sidecar_has(side_path, "device"):
                device_seen = True
                log(f"phase {phase}: device init ok at {now:.0f}s")
            if not device_seen and now > init_deadline:
                log(f"phase {phase}: no device record by {now:.0f}s; "
                    f"killing wedged init")
                init_wedged = True
                proc.kill()
                proc.wait()
                ret = None
                break
            if now > deadline_rel + 10:
                log(f"phase {phase}: killed at deadline; recovering sidecar")
                proc.kill()
                proc.wait()
                ret = None
                break
        errf.seek(0)
        sys.stderr.write(errf.read().decode(errors="replace"))
        if ret == 0:
            outf.seek(0)
            out = outf.read().decode(errors="replace").strip()
            if out:
                try:
                    rec = json.loads(out.splitlines()[-1])
                except json.JSONDecodeError:
                    log(f"phase {phase}: unparseable stdout; recovering sidecar")
        elif ret is not None:
            log(f"phase {phase}: rc={ret}; recovering sidecar")
    try:
        if rec is None:
            rec = _recover_sidecar(side_path)
            if rec:
                log(f"phase {phase}: recovered partial {list(rec.keys())}")
        if init_wedged:
            rec = dict(rec or {}, partial=True, init_wedged=True,
                       init_wedged_after_s=round(time.perf_counter() - t0, 1))
    finally:
        try:
            os.unlink(side_path)
        except OSError:
            pass
    return rec


def _child_main(phase: str) -> int:
    deadline_rel = _argval("deadline-rel", 600.0)
    side_path = None
    for a in sys.argv[1:]:
        if a.startswith("--sidecar="):
            side_path = a.split("=", 1)[1]
    side = Sidecar(side_path)

    # Soft stop between bytecodes (a wedge inside a blocking C call
    # outlives this; the parent's subprocess timeout is the hard stop —
    # either way the parent recovers from the sidecar).
    def on_alarm(sig, frm):
        side.emit("alarm", at_s=round(time.perf_counter() - T_START, 1))
        log(f"phase {phase}: SIGALRM hard stop")
        os._exit(3)

    # Armed BEFORE the parent's kill at deadline_rel+10 so a pure-Python
    # overrun exits cleanly (sidecar 'alarm' record, flushed stderr)
    # instead of taking the SIGKILL.
    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(max(1, int(deadline_rel) + 5))

    fn = {"throughput": phase_throughput, "latency": phase_latency}[phase]
    result = fn(side, deadline_rel)
    print(json.dumps(result), flush=True)
    return 0


def main() -> int:
    for a in sys.argv[1:]:
        if a.startswith("--phase="):
            return _child_main(a.split("=", 1)[1])

    detail = {
        "metric": "mpps_classified",
        "value": 0.0,
        "unit": "Mpps",
        "vs_baseline": 0.0,
        "target_mpps": TARGET_MPPS,
        "target_p99_ms": 1.0,
        "batch": B,
        "table_capacity": TABLE_CAP,
        "wire_format": "compact16",  # 16 B/record, bit-exact model quant
        "bytes_per_record": 16,
        "budget_s": BUDGET_S,
    }
    try:
        # Throughput gets the lion's share; latency runs in what's left.
        tput_budget = max(0.0, min(0.70 * BUDGET_S, remaining() - 30))
        if tput_budget < 30:
            raise RuntimeError(
                f"budget {BUDGET_S:.0f}s too small to run the throughput phase")

        # Attempt 1: TPU, with device init bounded separately (the axon
        # tunnel can wedge inside jax.devices() indefinitely — round-2
        # post-mortem).  Attempt 2: one retry in a fresh subprocess with
        # a shorter init deadline.  Fallback: a forced-CPU run, clearly
        # labeled — a measured CPU number beats another 0.0.
        forced_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
        init_attempts = []
        tput: dict = {}
        if not forced_cpu:
            init_dl1 = min(300.0, 0.5 * tput_budget)
            t = _run_phase("throughput", tput_budget,
                           init_deadline=init_dl1) or {}
            init_attempts.append(
                {"deadline_s": round(init_dl1),
                 "wedged": bool(t.get("init_wedged")),
                 "init_s": t.get("init_s")})
            if t.get("init_wedged") and remaining() > 240:
                init_dl2 = min(150.0, 0.4 * remaining())
                t2 = _run_phase(
                    "throughput",
                    max(60.0, min(tput_budget, remaining() - 150)),
                    init_deadline=init_dl2) or {}
                init_attempts.append(
                    {"deadline_s": round(init_dl2),
                     "wedged": bool(t2.get("init_wedged")),
                     "init_s": t2.get("init_s")})
                t = t2
            tput = t
        if not tput.get("mpps") and remaining() > 90:
            # TPU never produced a number (or cpu was requested):
            # labeled CPU fallback so the round records real data.
            if not forced_cpu:
                log("falling back to CPU throughput (TPU init wedged "
                    f"{len(init_attempts)}x)")
                detail["tpu_fallback"] = "cpu"
            cpu_t = _run_phase("throughput",
                               max(60.0, remaining() - 120),
                               force_cpu=True) or {}
            if cpu_t.get("mpps"):
                tput = cpu_t
        if init_attempts:
            detail["tpu_init_attempts"] = init_attempts

        if tput and tput.get("mpps"):
            mpps = tput["mpps"]
            detail.update(
                value=round(mpps, 3),
                vs_baseline=round(mpps / TARGET_MPPS, 3),
                chunk_mpps=tput.get("chunk_mpps"),
                compile_s=tput.get("compile_s"),
                backend=tput.get("backend"),
                device_kind=tput.get("device_kind"),
                throughput_partial=tput.get("partial", False),
            )
            for k in ("h2d_mbps", "device_mpps", "transport_limited",
                      "burst_mpps"):
                if k in tput:
                    detail[k] = tput[k]
            log(f"throughput: {mpps:.2f} Mpps median over {tput.get('chunk_mpps')}")
        else:
            detail["error"] = "throughput phase produced no chunks"

        # Reserve 20 s past the child-kill margin (+10 in _run_phase) so
        # the final JSON always lands inside the budget ceiling.  Run on
        # the backend that actually produced the throughput number: if
        # TPU init wedged there, don't pay the wedge again here.
        # backend unset means nothing measured — default the latency
        # phase to CPU rather than paying a likely TPU wedge again.
        lat_cpu = forced_cpu or detail.get("backend", "cpu") == "cpu"
        lat_budget = remaining() - 30
        if lat_budget > 45:
            lat = _run_phase("latency", lat_budget, force_cpu=lat_cpu,
                             init_deadline=None if lat_cpu
                             else min(240.0, 0.6 * lat_budget)) or {}
            detail["latency_backend"] = "cpu" if lat_cpu else \
                lat.get("backend", detail.get("backend"))
            # Copy only what the (possibly partial) phase measured; an
            # absent p50/p99 stays absent rather than becoming 0.0.
            for key, nd in (("p50_ms", 3), ("p99_ms", 3),
                            ("sync_floor_ms", 1), ("n_lat_iters", 0)):
                if lat.get(key) is not None:
                    detail[key] = round(lat[key], nd) if nd else lat[key]
            if lat.get("p99_ms") is not None:
                detail["p99_minus_floor_ms"] = round(
                    max(0.0, lat["p99_ms"] - lat.get("sync_floor_ms", 0.0)), 3)
                log(f"latency: p50={lat.get('p50_ms', 0):.1f}ms "
                    f"p99={lat['p99_ms']:.1f}ms")
            if lat.get("stats") is not None:
                detail["stats"] = lat["stats"]
            if lat:
                detail["latency_partial"] = lat.get("partial", False)
        else:
            log(f"skipping latency phase ({lat_budget:.0f}s left)")
    except Exception as e:  # noqa: BLE001 — one JSON line, always
        msg = f"{type(e).__name__}: {e}"
        detail["error"] = f"{detail['error']}; {msg}" if "error" in detail else msg
    finally:
        detail["wall_s"] = round(time.perf_counter() - T_START, 1)
        print(json.dumps(detail), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
