"""Multi-device fused step: IP-hash-sharded state + owner-routed flows.

This is the scale-out analog of SURVEY.md §2.3's parallelism table:

* **"Sequence parallelism" analog** — the per-IP state table shards by
  IP hash across the mesh's ``ip`` axis.  A flow's owner device is
  given by the *top* hash bits, its slot within the owner's shard by
  the *low* bits — ownership and probing use disjoint bits, and a key's
  owner never changes, so limiter state never migrates between devices.
* **Data parallelism** — each device parses, scores, and locally
  aggregates its ``B/n`` slice of the packet batch (sort, classifier
  matmul, and segment ops all shrink with the mesh).
* **Flow routing** — local per-flow partial aggregates are routed to
  their owner device with one ``all_to_all`` (ICI); the owner merges
  partials (a flow's packets may land on several devices' slices),
  runs the table+limiter+ML core once per flow, and routes per-flow
  verdicts back with a second ``all_to_all``.  Nothing per-flow is
  replicated — this is what makes the step *scale* instead of merely
  not serialize (the round-3 design re-sorted the full batch on every
  device, so per-device work stayed O(B) no matter the mesh size).
* **Collectives per step** — 2 ``all_to_all`` (flow partials out,
  verdicts back) + 1 ``pmax`` (batch clock) + 1 ``psum`` (stat counts).

Routing capacity: each device sends at most ``C ≈ 2·(B/n)/n`` flows to
each owner — 2× the uniform-hash expectation.  Ownership hashing mixes
in the boot-time random salt (``TableConfig.salt``), so an attacker
cannot precompute a spoofed-source flood that lands every flow on one
owner.  Overflow remains possible in principle (natural skew at tiny
batch/mesh ratios, or a disclosed salt) and is handled
fail-open, the framework-wide discipline (SURVEY.md §5.3): overflowed
flows PASS this batch, skip their limiter update, and are counted in
``StepOutput.route_drop`` — visible, bounded, and backstopped by the
in-kernel limiter, which stands alone by design.

Everything runs under ``jax.shard_map`` over a
:func:`~flowsentryx_tpu.parallel.mesh.make_mesh` mesh; the same code
compiles for 8 virtual CPU devices (tests) or a v5e pod slice.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flowsentryx_tpu.core.config import FsxConfig
from flowsentryx_tpu.parallel import layout, mesh as mesh_lib
from flowsentryx_tpu.core.schema import (
    IpTableState, Verdict, make_table,
)
from flowsentryx_tpu.ops import agg, fused, hashtable

#: Re-export (the historical home): placement now derives from the
#: declarative partition rules in :mod:`flowsentryx_tpu.parallel.layout`.
shard_table = layout.shard_table


def make_sharded_table(cfg: FsxConfig, mesh: Mesh) -> IpTableState:
    """Fresh empty table of ``cfg.table.capacity`` rows, row-sharded."""
    return shard_table(make_table(cfg.table.capacity), mesh)


def make_sharded_step(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    donate: bool | None = None,
    emit_score: bool = False,
):
    """Build the jitted multi-device step.

    Signature matches the single-device
    :func:`~flowsentryx_tpu.ops.fused.make_jitted_step`:
    ``step(table, stats, params, batch) -> (table, stats, out)`` — the
    engine swaps one for the other based on mesh size.  ``table`` must
    be sharded with :func:`shard_table`; batch/params/stats replicated.
    """
    if donate is None:
        donate = fused.donation_supported()
    axis = mesh.axis_names[0]
    n_dev = int(mesh.devices.size)
    k_bits = n_dev.bit_length() - 1  # n_dev = 2**k_bits (validated by make_mesh)
    if cfg.table.capacity % n_dev:
        raise ValueError("table capacity must divide by device count")
    local_tbl = dataclasses.replace(cfg.table, capacity=cfg.table.capacity // n_dev)
    local_cfg = dataclasses.replace(cfg, table=local_tbl)

    def device_step(table_shard, stats, params, batch):
        d = jax.lax.axis_index(axis)
        b = batch.feat.shape[0]
        if b % n_dev:
            raise ValueError(
                f"batch size {b} must divide by the {n_dev}-device mesh "
                "(pad the batch; decode_records already pads to a static size)"
            )
        local_b = b // n_dev
        # Per-source→owner routing capacity: 2× the uniform-hash
        # expectation, floored so tiny test batches don't route at
        # capacity 1 (module docstring: overflow is fail-open+counted).
        C = min(local_b, max(64, -(-2 * local_b // n_dev)))

        def sl(x):
            return jax.lax.dynamic_slice_in_dim(x, d * local_b, local_b)

        key_l, len_l = sl(batch.key), sl(batch.pkt_len)
        ts_l, valid_l = sl(batch.ts), sl(batch.valid)
        feat_l = jax.lax.dynamic_slice_in_dim(batch.feat, d * local_b, local_b)

        # --- local slice work: classifier + per-flow aggregation -----------
        score_l = classify_batch(params, feat_l)                 # [local_b]
        fa = agg.aggregate(key_l, len_l, ts_l, valid_l)
        mal_l = (score_l > cfg.model.threshold) & valid_l
        # per-local-flow COUNT of malicious records (vote evidence;
        # owner-side merge SUMS partials so a flow spanning slices
        # votes with its full record count)
        ml_l = (jnp.zeros((local_b,), jnp.float32)
                .at[fa.inv].add(mal_l.astype(jnp.float32)))
        now = jax.lax.pmax(jnp.max(jnp.where(valid_l, ts_l, 0.0)), axis)

        # In-step aging epoch, the shard-local way: each device sweeps
        # its OWN table rows (an elementwise pass — nothing crosses the
        # mesh), gated by the replicated batch counter so every shard
        # fires the same epochs; the per-shard count rides the existing
        # stats psum below.  Statically absent when disabled.
        n_evict_l = None
        if cfg.table.evict_ttl_s > 0:
            table_shard, n_evict_l = fused.evict_idle_epoch(
                cfg.table, table_shard, stats, now)

        # --- route local flow partials to their owner ----------------------
        h1 = hashtable.hash_u32(fa.rep_key, cfg.table.salt)
        owner = ((h1 >> (32 - k_bits)).astype(jnp.int32) if k_bits
                 else jnp.zeros_like(h1, jnp.int32))
        # rank of each flow within its owner bucket: one small sort by
        # owner + a cummax gives position-within-run
        ko = agg.segment_by_key(jnp.where(fa.rep_valid, owner, n_dev))
        idx = jnp.arange(local_b, dtype=jnp.int32)
        run_start = jax.lax.cummax(jnp.where(ko.heads, idx, 0))
        rank = (jnp.zeros((local_b,), jnp.int32)
                .at[ko.order].set(idx - run_start))

        routed = fa.rep_valid & (rank < C)
        overflow = fa.rep_valid & ~routed
        flat = jnp.where(routed, owner * C + rank, n_dev * C)    # park tail

        def scatter_send(vals, fill):
            ext = jnp.full((n_dev * C + 1,), fill, vals.dtype)
            ext = ext.at[flat].set(jnp.where(routed, vals, fill))
            return ext[: n_dev * C]

        bits = jax.lax.bitcast_convert_type
        send = jnp.stack(
            [
                scatter_send(fa.rep_key, agg.INVALID_KEY),
                scatter_send(bits(fa.rep_pkts, jnp.uint32), jnp.uint32(0)),
                scatter_send(bits(fa.rep_bytes, jnp.uint32), jnp.uint32(0)),
                scatter_send(bits(fa.rep_ts, jnp.uint32), jnp.uint32(0)),
                scatter_send(bits(ml_l, jnp.uint32), jnp.uint32(0)),
            ],
            axis=1,
        ).reshape(n_dev, C, 5)
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0)
        r = recv.reshape(n_dev * C, 5)                           # [R, 5]

        # --- owner side: merge per-source partials, run the flow core ------
        # A flow's packets may have landed on several source devices;
        # each contributed one partial (≤ n_dev duplicates per key).
        ks = agg.segment_by_key(r[:, 0])
        seg, sk = ks.seg, ks.sorted_key
        rn = n_dev * C
        fvalid = sk != agg.INVALID_KEY

        def seg_sum(v):
            return jax.ops.segment_sum(
                jnp.where(fvalid, v[ks.order], 0.0), seg, num_segments=rn)

        def seg_max(v, fill):
            return jax.ops.segment_max(
                jnp.where(fvalid, v[ks.order], fill), seg, num_segments=rn)

        m_pkts = seg_sum(bits(r[:, 1], jnp.float32))
        m_bytes = seg_sum(bits(r[:, 2], jnp.float32))
        m_ts = seg_max(bits(r[:, 3], jnp.float32), -jnp.inf)
        m_ml = seg_sum(bits(r[:, 4], jnp.float32))  # vote-count merge
        m_key = jax.ops.segment_max(sk, seg, num_segments=rn)
        m_valid = m_pkts > 0
        m_key = jnp.where(m_valid, m_key, agg.INVALID_KEY)
        m_ts = jnp.where(m_valid, m_ts, 0.0)
        inv2 = ks.inv                                            # entry→flow

        mfa = agg.FlowAgg(rep_key=m_key, rep_pkts=m_pkts, rep_bytes=m_bytes,
                          rep_ts=m_ts, rep_valid=m_valid, inv=inv2)
        new_shard, dec = fused.flow_step(
            local_cfg, table_shard, mfa, m_valid, m_ml, now
        )

        # --- route per-flow verdicts back to the source devices ------------
        back = jax.lax.all_to_all(
            dec.flow_verdict[inv2].reshape(n_dev, C), axis,
            split_axis=0, concat_axis=0,
        )  # back[o, c] = verdict of my local flow with (owner o, rank c)
        rep_verdict = jnp.where(
            routed,
            back[jnp.clip(owner, 0, n_dev - 1), jnp.clip(rank, 0, C - 1)],
            int(Verdict.PASS),  # overflow: fail-open this batch (counted)
        )
        # the ML_RECORD_GATE sentinel rides the verdict all_to_all and
        # resolves per record HERE, where the local slice's scores live
        verdict_l = fused.resolve_record_verdicts(rep_verdict, fa.inv,
                                                  mal_l, valid_l)

        # --- stats: local counts, one psum ---------------------------------
        route_drop_l = jnp.sum(
            jnp.where(valid_l, overflow[fa.inv].astype(jnp.uint32),
                      jnp.uint32(0))
        )
        count_parts = [
            fused.count_verdicts(verdict_l, valid_l),
            route_drop_l[None].astype(jnp.uint32),
        ]
        if n_evict_l is not None:
            # the eviction count joins the ONE existing scalar psum —
            # the audited collective census does not grow
            count_parts.append(n_evict_l[None])
        counts = jax.lax.psum(jnp.concatenate(count_parts), axis)
        new_stats = fused.update_stats_from_counts(stats, counts[:4])
        if n_evict_l is not None:
            from flowsentryx_tpu.core.schema import u64_add

            new_stats = new_stats._replace(
                evicted=u64_add(new_stats.evicted, counts[5]))

        blk_key = jnp.where(dec.newly_blocked, m_key,
                            agg.INVALID_KEY)                      # owner-side
        blk_until = jnp.where(dec.newly_blocked,
                              dec.new_blocked_until, 0.0)
        # Compact verdict wire, the sharded way: each owner shard
        # compacts ITS newly-blocked flows (a flow blocks only on its
        # owner, so shards never duplicate keys), one all_gather moves
        # the K-slot buffers — O(n·K) over ICI, tiny next to the two
        # batch all_to_alls — and a second compaction folds them into
        # ONE replicated wire.  route_drop and the batch clock ride the
        # same buffer, so the host's steady-state readback is a single
        # O(K) fetch with no extra scalar round trips.  Overflow
        # (total > K) is exact from the psum'd true counts: a shard
        # losing entries locally implies total > K.
        k_max = cfg.batch.verdict_k
        if k_max:
            lk, lu, lcount = fused.compact_blocklist(blk_key, blk_until,
                                                     k_max)
            gk = jax.lax.all_gather(lk, axis)              # [n_dev, K]
            gu = jax.lax.all_gather(lu, axis)
            total = jax.lax.psum(lcount, axis)
            ck, cu, _ = fused.compact_blocklist(
                gk.reshape(-1), gu.reshape(-1), k_max)
            bits2 = jax.lax.bitcast_convert_type
            wire = jnp.concatenate([
                ck, bits2(cu, jnp.uint32),
                jnp.stack([total, (total > k_max).astype(jnp.uint32),
                           counts[4],
                           bits2(now, jnp.uint32)]),
            ])
        else:
            wire = None

        out = fused.StepOutput(
            verdict=verdict_l.astype(jnp.uint8),                  # P(axis)→[B]
            score=score_l if emit_score else None,                # P(axis)→[B]
            block_key=blk_key,
            block_until=blk_until,
            now=now,
            route_drop=counts[4],
            wire=wire,
        )
        return new_shard, new_stats, out

    # in/out placement comes from the declarative rule table
    # (parallel/layout.py) — the one layout declaration the engine's
    # H2D path and the checkpoint restore path also derive from
    table_specs = layout.table_specs(axis)
    stats_specs = layout.stats_specs()
    out_specs = fused.StepOutput(
        verdict=P(axis), score=P(axis) if emit_score else None,
        block_key=P(axis), block_until=P(axis),
        now=P(), route_drop=P(),
        wire=P() if cfg.batch.verdict_k else None,
    )

    sharded = mesh_lib.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(table_specs, stats_specs, P(), P()),
        out_specs=(table_specs, stats_specs, out_specs),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _make_sharded_wire_step(cfg, classify_batch, mesh, donate, decode,
                            emit_score=False):
    """Shared wrapper: replicated wire buffer → on-device ``decode`` →
    the shard-mapped step.  The wire enters as ONE contiguous H2D
    transfer (tiny next to the sharded state); all field extraction
    fuses into the jit."""
    if donate is None:
        donate = fused.donation_supported()
    base = make_sharded_step(cfg, classify_batch, mesh, donate=False,
                             emit_score=emit_score)

    def step(table, stats, params, raw):
        return base(table, stats, params, decode(raw))

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_sharded_raw_step(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    donate: bool | None = None,
    emit_score: bool = False,
):
    """Sharded step over the RAW ring wire format — the multi-device
    twin of :func:`~flowsentryx_tpu.ops.fused.make_jitted_raw_step`,
    with the same ``step(table, stats, params, raw)`` signature, so the
    serving :class:`~flowsentryx_tpu.engine.engine.Engine` swaps it in
    whenever its mesh spans more than one device."""
    from flowsentryx_tpu.core import schema

    return _make_sharded_wire_step(cfg, classify_batch, mesh, donate,
                                   schema.decode_raw,
                                   emit_score=emit_score)


def make_sharded_compact_step(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    donate: bool | None = None,
    emit_score: bool = False,
    **quant,
):
    """Sharded step over the COMPACT 16 B wire format — the multi-device
    twin of :func:`~flowsentryx_tpu.ops.fused.make_jitted_compact_step`.
    ``**quant`` are the wire-quantizer kwargs
    (:func:`~flowsentryx_tpu.core.schema.wire_quant_for`); the batch
    enters replicated and dequantizes on device before the shard-mapped
    step, so the multi-chip engine keeps the 3× wire-byte saving."""
    import functools

    from flowsentryx_tpu.core import schema

    return _make_sharded_wire_step(
        cfg, classify_batch, mesh, donate,
        functools.partial(schema.decode_compact, **quant),
        emit_score=emit_score,
    )


def make_sharded_compact_megastep(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    n_chunks: int,
    donate: bool | None = None,
    **quant,
):
    """N micro-batches in ONE dispatch over the device mesh — the
    multi-device twin of
    :func:`~flowsentryx_tpu.ops.fused.make_jitted_compact_megastep`.

    A ``lax.scan`` carries the SHARDED (table, stats) through N
    shard-mapped steps inside one jit: the per-dispatch fixed cost is
    paid once per group while every chunk still runs the full
    owner-routed all_to_all/psum pipeline, so trajectory parity with N
    sequential sharded dispatches holds by construction (test-pinned).
    Outs fields stack to ``[N, ...]`` exactly like the single-device
    megastep, which is what the serving engine's group sink expects.
    Donation matches the module's table-only policy (the replicated
    stats output cannot alias a single-device input buffer anyway).
    """
    if donate is None:
        donate = fused.donation_supported()
    base = make_sharded_compact_step(cfg, classify_batch, mesh,
                                     donate=False, **quant)
    return fused.wrap_megastep(base, n_chunks, (0,) if donate else ())


def make_sharded_compact_megastep_family(
    cfg: FsxConfig,
    classify_batch: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    sizes: tuple[int, ...],
    donate: bool | None = None,
    **quant,
) -> dict:
    """One jitted sharded megastep per group size over ONE shard-mapped
    base step — the multi-device twin of
    :func:`~flowsentryx_tpu.ops.fused.make_compact_megastep_family`.
    The adaptive engine dispatches the largest rung its backlog fills;
    every rung carries the full owner-routed collective pipeline per
    chunk, so per-rung parity with sequential sharded dispatches holds
    exactly as for the single fixed size."""
    if donate is None:
        donate = fused.donation_supported()
    base = make_sharded_compact_step(cfg, classify_batch, mesh,
                                     donate=False, **quant)
    return {
        n: fused.wrap_megastep(base, n, (0,) if donate else ())
        for n in sorted(sizes, reverse=True)
    }
