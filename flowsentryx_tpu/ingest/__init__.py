"""Sharded parallel host ingest: break the single-threaded drain ceiling.

The inline serving loop tops out where one Python thread tops out: the
r5 stress run (``SHMSTRESS_r05.json``) measured the bare ring-drain
path at 6.3 Mpps but the full drain → decode → batch-assembly → dispatch
loop at ~0.9 Mpps — the decode/seal stage between ``ShmRingSource.poll``
and the dispatch is the system bottleneck, not the device (~265 Mpps
resident).  The fix is the standard per-packet-ML answer (Taurus, FENXI):
shard the host ingest stage and pipeline it away from the accelerator
dispatch loop.

Architecture::

    kernel / fsxd --shards N          (IP-hash fan-out, per-CPU analog)
        ├── shm feature ring shard 0 ──► drain worker 0 ─┐ sealed-batch
        ├── shm feature ring shard 1 ──► drain worker 1 ─┤ SPSC queues
        │   ...                                          │ (engine/shm.py
        └── shm feature ring shard N-1 ► drain worker N-1┘  SealedBatchQueue)
                                                  │
                                engine: dequeue → dispatch → reap

* Each **drain worker** (:mod:`.worker`) is a separate pure-numpy
  process owning ONE ring shard: it drains, decodes, quantizes, and
  seals complete ``[B+1, words]`` wire buffers, so the engine's hot
  loop never touches a raw record again.
* Records fan out by IP hash (``schema.shard_of``): a flow's records
  stay on one shard, preserving their relative order end-to-end —
  the same affinity the kernel's per-CPU ringbuf production gives.
* The **engine** consumes sealed batches round-robin through
  :class:`~flowsentryx_tpu.ingest.sharded.ShardedIngest`; a worker
  crash fails open (remaining shards keep serving, the kernel limiter
  covers the dead shard's flows), a stop request drains every ring to
  empty before the workers exit.
"""

from flowsentryx_tpu.ingest.sharded import (  # noqa: F401
    SealedBatch,
    SeqTracker,
    ShardedIngest,
)
