"""Flow-table lifecycle: geometry planning, host hash twins, reshard.

The device-resident flow table's GEOMETRY — capacity, shard count,
salt, probe depth — fixes where every key's row lives: the top
``log2(n_shards)`` bits of the salted hash pick the owner shard, the
low bits drive the double-hashed probe inside the owner's rows
(``ops/hashtable.probe_slots``; disjoint bits, so ownership never
migrates).  This module owns everything about that geometry that runs
on the HOST:

* :class:`TablePlan` — the geometry as one value, derived from config
  + mesh, carried in checkpoints, compared at restore;
* :func:`validate_capacity` — the pre-boot refusal list ``fsx serve
  --table-capacity`` prints (power-of-two, batch floor, shard
  divisibility) instead of a post-compile traceback;
* numpy twins of the device hash (:func:`hash_u32_np`,
  :func:`owner_of`) — bit-identical to ``ops/hashtable.hash_u32``,
  used by the reshard below and by the table-scale smoke to PROVE
  shard-local residency (every key in shard i must satisfy
  ``owner_of(key) == i``);
* :func:`reshard_rows` — restore-with-reshard: re-place every occupied
  row of a checkpoint under a DIFFERENT geometry (mesh grew/shrank,
  capacity grew) by re-running the insert probe host-side, vectorized
  over all rows.  A checkpoint's global slot indices are meaningless
  under any other geometry — restoring them verbatim would mislocate
  every key and silently rot the table, which is exactly the failure
  the engine refuses/reshards at restore time.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flowsentryx_tpu.core import schema
from flowsentryx_tpu.core.config import TableConfig

#: Second-hash tweak constant — MUST mirror ``ops/hashtable.probe_slots``
#: (the probe-step hash is ``hash_u32(key ^ GOLDEN, salt) | 1``).
_GOLDEN = np.uint32(0x9E3779B9)


def hash_u32_np(k: np.ndarray, salt: int = 0) -> np.ndarray:
    """Numpy twin of :func:`flowsentryx_tpu.ops.hashtable.hash_u32`
    (murmur3 finalizer, salt xor-mixed ahead) — bit-identical, pinned
    by tests/test_table.py."""
    k = np.asarray(k, np.uint32) ^ np.uint32(salt)
    with np.errstate(over="ignore"):
        k = k ^ (k >> np.uint32(16))
        k = k * np.uint32(0x85EBCA6B)
        k = k ^ (k >> np.uint32(13))
        k = k * np.uint32(0xC2B2AE35)
        k = k ^ (k >> np.uint32(16))
    return k


def owner_of(keys: np.ndarray, salt: int, n_shards: int) -> np.ndarray:
    """Owner-shard index of each key — the host twin of the sharded
    step's routing (``parallel/step.py``: top hash bits)."""
    if n_shards <= 1:
        return np.zeros(np.asarray(keys).shape, np.int64)
    k_bits = int(n_shards).bit_length() - 1
    return (hash_u32_np(keys, salt) >> np.uint32(32 - k_bits)).astype(
        np.int64)


@dataclasses.dataclass(frozen=True)
class TablePlan:
    """The table geometry as one comparable value."""

    capacity: int
    n_shards: int = 1
    salt: int = 0
    probes: int = 8

    def __post_init__(self) -> None:
        problems = validate_capacity(self.capacity, n_shards=self.n_shards)
        if problems:
            raise ValueError("; ".join(problems))

    @property
    def local_capacity(self) -> int:
        return self.capacity // self.n_shards

    @property
    def k_bits(self) -> int:
        return int(self.n_shards).bit_length() - 1

    @classmethod
    def of(cls, tcfg: TableConfig, n_shards: int = 1) -> "TablePlan":
        return cls(capacity=tcfg.capacity, n_shards=n_shards,
                   salt=tcfg.salt, probes=tcfg.probes)


def validate_capacity(
    capacity: int, max_batch: int = 0, n_shards: int = 1
) -> list[str]:
    """Every reason this capacity cannot serve, each as one clear
    sentence (the ``fsx serve --table-capacity`` pre-boot refusals;
    empty list = valid)."""
    problems: list[str] = []
    if capacity <= 0 or capacity & (capacity - 1):
        problems.append(
            f"table capacity {capacity} is not a power of two (slot "
            "probing masks with capacity-1)")
        return problems  # the rest assumes pow2
    if capacity > 1 << 29:
        problems.append(
            f"table capacity {capacity} exceeds 2^29 (the packed "
            "arbitration sort key must fit int32)")
    if max_batch and capacity < max_batch:
        problems.append(
            f"table capacity {capacity} is smaller than max_batch "
            f"{max_batch}: one batch of distinct flows could not even "
            "be tracked")
    if n_shards > 1:
        if n_shards & (n_shards - 1):
            problems.append(
                f"shard count {n_shards} is not a power of two "
                "(ownership uses top hash bits)")
        elif capacity < n_shards:
            problems.append(
                f"table capacity {capacity} cannot split over "
                f"{n_shards} shards")
    return problems


def _global_candidates(keys: np.ndarray, plan: TablePlan) -> np.ndarray:
    """``[R, probes]`` GLOBAL row candidates of each key under
    ``plan`` — the host twin of the device probe sequence
    (``(h1 + p*step) & (local_capacity - 1)`` inside the owner's
    rows)."""
    h1 = hash_u32_np(keys, plan.salt)
    step = hash_u32_np(np.asarray(keys, np.uint32) ^ _GOLDEN,
                       plan.salt) | np.uint32(1)
    mask = np.uint32(plan.local_capacity - 1)
    offs = np.arange(plan.probes, dtype=np.uint32)
    with np.errstate(over="ignore"):
        local = (h1[:, None] + offs[None, :] * step[:, None]) & mask
    base = owner_of(keys, plan.salt, plan.n_shards) * plan.local_capacity
    return base[:, None] + local.astype(np.int64)


def reshard_rows(
    key: np.ndarray,
    state: np.ndarray,
    plan: TablePlan,
) -> tuple[np.ndarray, np.ndarray, int]:
    """Re-place every occupied row under a new geometry.

    ``key``/``state`` are the HOST global arrays of a loaded checkpoint
    (shard-major under whatever geometry wrote them — their positions
    are discarded; only occupancy matters).  Each occupied key re-runs
    the insert probe under ``plan`` and lands in its earliest free
    candidate row, so a subsequent lookup finds it at match priority
    exactly as if the flow had inserted live.  Returns
    ``(key, state, dropped)`` where ``dropped`` counts rows whose whole
    probe sequence was taken by other restored keys — possible only
    near capacity (a restore into a SMALLER table); fail-open, counted,
    never silent.

    Vectorized: ``probes`` passes over all rows (argsort per pass), so
    a 4M-row reshard is numpy-speed, not a Python loop.
    """
    key = np.asarray(key, np.uint32)
    state = np.asarray(state, np.float32)
    occ = np.flatnonzero(key != 0)
    new_key = np.zeros((plan.capacity,), np.uint32)
    new_state = np.zeros((plan.capacity, schema.NUM_TABLE_COLS),
                         np.float32)
    if not len(occ):
        return new_key, new_state, 0
    k_occ = key[occ]
    st_occ = state[occ]
    dropped = _probe_insert(k_occ, st_occ, new_key, new_state, plan)
    return new_key, new_state, dropped


def _probe_insert(
    k_occ: np.ndarray,
    st_occ: np.ndarray,
    new_key: np.ndarray,
    new_state: np.ndarray,
    plan: TablePlan,
) -> int:
    """Probe-insert ``(k_occ, st_occ)`` into ``new_key``/``new_state``
    IN PLACE (rows whose ``new_key`` is nonzero are occupied and
    skipped over, exactly like the device probe).  Returns the dropped
    count — keys whose whole probe sequence was taken.  Shared by
    :func:`reshard_rows` (empty target) and :func:`insert_rows`
    (populated target)."""
    cand = _global_candidates(k_occ, plan)          # [R, P]
    placed = np.zeros(len(k_occ), bool)
    taken = new_key != 0
    for p in range(plan.probes):
        idx = np.flatnonzero(~placed)
        if not len(idx):
            break
        c = cand[idx, p]
        free = ~taken[c]
        idx, c = idx[free], c[free]
        # one winner per contested slot: stable sort by slot keeps the
        # first (lowest original row) — deterministic across runs
        order = np.argsort(c, kind="stable")
        c_s, idx_s = c[order], idx[order]
        head = np.ones(len(c_s), bool)
        head[1:] = c_s[1:] != c_s[:-1]
        slots_w, rows_w = c_s[head], idx_s[head]
        new_key[slots_w] = k_occ[rows_w]
        new_state[slots_w] = st_occ[rows_w]
        taken[slots_w] = True
        placed[rows_w] = True
    return int(np.sum(~placed))


def insert_rows(
    key: np.ndarray,
    state: np.ndarray,
    add_keys: np.ndarray,
    add_states: np.ndarray,
    plan: TablePlan,
) -> tuple[np.ndarray, np.ndarray, int]:
    """Probe-insert foreign rows into an EXISTING table — the
    handoff-adoption twin of :func:`reshard_rows` (``cluster/
    rebalance.py``): the recipient's live table keeps every row where
    it is, and each adopted key runs the insert probe over the
    remaining free slots, landing exactly where a live insert would
    have.  An adopted key already present in the table can only mean
    double-ownership upstream (the conservation invariant's job to
    catch); the incoming copy is DROPPED and counted rather than
    overwriting live state.  Returns ``(key, state, dropped)`` on
    fresh host arrays — the caller re-places them on device."""
    key = np.asarray(key, np.uint32).copy()
    state = np.asarray(state, np.float32).copy()
    add_keys = np.asarray(add_keys, np.uint32).reshape(-1)
    add_states = np.asarray(add_states, np.float32).reshape(
        len(add_keys), schema.NUM_TABLE_COLS)
    live = add_keys != 0
    present = np.isin(add_keys, key[key != 0])
    sel = live & ~present
    dropped = int(np.sum(live & present))
    if np.any(sel):
        dropped += _probe_insert(add_keys[sel], add_states[sel],
                                 key, state, plan)
    return key, state, dropped
