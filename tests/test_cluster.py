"""Cluster-plane tests: gossip mailboxes, supervisor lifecycle, the
end-to-end ownership rule, and cluster-vs-single-engine parity.

The heavy real-engine choreography (two supervised engine processes
draining losslessly, gossip digest convergence across processes, a
SIGKILL/restart cycle mid-serve) is re-proved by every verify run in
``scripts/cluster_smoke.py`` → ``artifacts/CLUSTER_r14.json``; the
tests here keep tier-1 fast by exercising the same protocol objects
in-process (the mailbox/gossip planes are just mmapped files — two
:class:`GossipPlane` endpoints in one process are byte-for-byte the
cross-process protocol) plus the supervisor's restart machinery
against the millisecond lifecycle stub.
"""

import os
import platform
import time

import numpy as np
import pytest

from flowsentryx_tpu.cluster.gossip import GossipPlane, create_plane
from flowsentryx_tpu.cluster.mailbox import (
    StatusBlock, VerdictMailbox, status_path,
)
from flowsentryx_tpu.core import schema
from flowsentryx_tpu.engine.shm import RingNotReady
from flowsentryx_tpu.engine.writeback import BlacklistUpdate, CollectSink

pytestmark = pytest.mark.skipif(
    platform.system() != "Linux",
    reason="cluster plane is mmap shm + process groups (Linux)")


def _upd(keys, untils):
    return BlacklistUpdate(key=np.asarray(keys, np.uint32),
                           until_s=np.asarray(untils, np.float32))


# ---------------------------------------------------------------------------
# the shm plane: mailboxes and status blocks
# ---------------------------------------------------------------------------


class TestVerdictMailbox:
    def test_geometry_refusals(self, tmp_path):
        with pytest.raises(ValueError, match="power of two"):
            VerdictMailbox.create(tmp_path / "m", slots=3, k_max=4)
        with pytest.raises(ValueError, match="k_max"):
            VerdictMailbox.create(tmp_path / "m", slots=4, k_max=0)

    def test_unpublished_magic_refused(self, tmp_path):
        p = tmp_path / "zeroed.mbx"
        p.write_bytes(b"\0" * 4096)
        with pytest.raises(RingNotReady, match="magic"):
            VerdictMailbox(p)

    def test_roundtrip_and_k_from_geometry(self, tmp_path):
        mbx = VerdictMailbox.create(tmp_path / "m", slots=4, k_max=2)
        assert mbx.k_max == 2  # derived from slot_words, not re-passed
        wire = np.arange(2 * 2 + 4, dtype=np.uint32)
        assert mbx.publish(wire, seq=7, count=2)
        assert mbx.readable() == 1
        [(seq, got)] = mbx.pop_wires(8)
        assert seq == 7
        np.testing.assert_array_equal(got, wire)
        assert mbx.readable() == 0

    def test_full_mailbox_drops_instead_of_blocking(self, tmp_path):
        mbx = VerdictMailbox.create(tmp_path / "m", slots=2, k_max=1)
        wire = np.zeros(2 + 4, np.uint32)
        assert mbx.publish(wire, 1, 1)
        assert mbx.publish(wire, 2, 1)
        t0 = time.monotonic()
        assert not mbx.publish(wire, 3, 1)  # full: False, instantly
        assert time.monotonic() - t0 < 0.1
        assert mbx.readable() == 2

    def test_wraparound_preserves_wires(self, tmp_path):
        mbx = VerdictMailbox.create(tmp_path / "m", slots=2, k_max=1)
        for seq in range(1, 8):
            wire = np.full(2 + 4, seq, np.uint32)
            assert mbx.publish(wire, seq, 1)
            [(got_seq, got)] = mbx.pop_wires(4)
            assert got_seq == seq
            np.testing.assert_array_equal(got, wire)

    def test_u64_seq_split_across_2pow32_boundary(self, tmp_path):
        """Satellite (ISSUE 15): the u64 seq is split across two u32
        header words (cell[0]=lo, cell[1]=hi) — pin the split AND the
        reassembly exactly at the 2^32 word boundary (a lo-word-only
        regression would alias seq 2^32 to 0 and read a torn-restart
        gap where there is none)."""
        mbx = VerdictMailbox.create(tmp_path / "m", slots=4, k_max=2)
        wire = np.zeros(2 * 2 + 4, np.uint32)
        for seq in [(1 << 32) - 1, 1 << 32, (1 << 32) + 1,
                    (1 << 63) + 7]:
            assert mbx.publish(wire, seq, 0)
            cell = mbx._cells[(int(mbx._head[0]) - 1)
                              & (mbx.slots - 1)]
            assert int(cell[0]) == seq & 0xFFFFFFFF   # lo word
            assert int(cell[1]) == seq >> 32          # hi word
            [(got_seq, _w)] = mbx.pop_wires(1)
            assert got_seq == seq

    def test_popped_wire_survives_producer_overwrite(self, tmp_path):
        # pop_wires copies: the returned wire must stay intact when the
        # producer laps the ring over the same slot
        mbx = VerdictMailbox.create(tmp_path / "m", slots=2, k_max=1)
        first = np.full(2 + 4, 11, np.uint32)
        mbx.publish(first, 1, 1)
        [(_, got)] = mbx.pop_wires(1)
        for seq in range(2, 4):  # re-use both slots
            mbx.publish(np.full(2 + 4, 99, np.uint32), seq, 1)
        np.testing.assert_array_equal(got, first)


class TestStatusBlock:
    def test_create_and_writer_fields_roundtrip(self, tmp_path):
        st = StatusBlock.create(tmp_path / "s.blk", rank=3)
        assert st.rank == 3
        for f in ("c_hbeat", "c_state", "c_batches", "c_records",
                  "c_stop", "c_gen", "c_t0"):
            assert st.ctl_get(f) == 0  # zeroed = "never booted"
            st.ctl_set(f, 41)
            assert st.ctl_get(f) == 41
        st2 = StatusBlock(tmp_path / "s.blk")  # a second attacher
        assert st2.ctl_get("c_state") == 41

    def test_unpublished_magic_refused(self, tmp_path):
        p = tmp_path / "zero.blk"
        p.write_bytes(b"\0" * schema.SHM_STATUS_SIZE)
        with pytest.raises(RingNotReady, match="magic"):
            StatusBlock(p)


# ---------------------------------------------------------------------------
# the gossip plane: publish/merge protocol, in-process
# ---------------------------------------------------------------------------


class TestGossipPlane:
    def _planes(self, tmp_path, n=2, sinks=False, **kw):
        create_plane(tmp_path, n, **kw)
        return [GossipPlane(tmp_path, r, n,
                            sink=CollectSink() if sinks else None,
                            merge_interval_s=0.0)
                for r in range(n)]

    def test_create_plane_refuses_single_engine(self, tmp_path):
        with pytest.raises(ValueError, match=">= 2 engines"):
            create_plane(tmp_path, 1)

    def test_plane_requires_created_dir(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            GossipPlane(tmp_path, 0, 2)

    def test_rank_bounds(self, tmp_path):
        create_plane(tmp_path, 2)
        with pytest.raises(ValueError, match="rank"):
            GossipPlane(tmp_path, 2, 2)

    def test_attach_refuses_fleet_size_mismatch(self, tmp_path):
        # a 2-engine attach on a 3-engine plane would construct fine
        # (rank 0/1 files all exist) and serve while silently never
        # gossiping with rank 2 — the geometry stamp refuses it
        create_plane(tmp_path, 3)
        with pytest.raises(ValueError, match="created for 3"):
            GossipPlane(tmp_path, 0, 2)

    def test_block_on_a_enforced_by_b_one_tick_byte_identical(
            self, tmp_path):
        """The headline gossip claim: a block landed on engine A is in
        engine B's merged view (and B's kernel-tier sink) after ONE
        merge tick, with byte-identical untils."""
        a, b = self._planes(tmp_path, sinks=True)
        untils = np.array([12.25, 99.5, 3.125], np.float32)
        a.publish(_upd([101, 202, 303], untils), now=1.0)
        assert b.tick(force=True) == 3
        assert b.report()["merged_digest"] == \
            a.report()["published_digest"]
        assert b.report()["rx_seq_gaps"] == 0
        got = b.sink.blocked
        assert set(got) == {101, 202, 303}
        for k, u in zip([101, 202, 303], untils):
            assert np.float32(got[k]) == u  # exact, not approximate
        # and nothing came back to A (its RX side is empty)
        assert a.tick(force=True) == 0

    def test_last_wins_by_key(self, tmp_path):
        a, b = self._planes(tmp_path, sinks=True)
        a.publish(_upd([7], [10.0]), now=0.0)
        a.publish(_upd([7], [20.0]), now=0.1)
        assert b.tick(force=True) == 2
        assert b.sink.blocked[7] == 20.0
        assert b.report()["merged_digest"] == \
            a.report()["published_digest"]

    def test_group_bigger_than_k_chunks_into_wires(self, tmp_path):
        a, b = self._planes(tmp_path, k_max=4)
        keys = np.arange(10, dtype=np.uint32) + 1
        a.publish(_upd(keys, np.arange(10) + 0.5), now=0.0)
        assert a.report()["tx_wires"] == 3  # 4 + 4 + 2
        assert b.tick(force=True) == 10
        assert b.report()["merged_digest"] == \
            a.report()["published_digest"]

    def test_full_mailbox_drop_is_counted_and_gap_detected(
            self, tmp_path):
        a, b = self._planes(tmp_path, slots=2)
        for i in range(3):  # third wire hits a full 2-slot mailbox
            a.publish(_upd([i + 1], [1.0]), now=0.0)
        assert a.report()["tx_dropped"] == 1
        assert b.tick(force=True) == 2
        assert b.report()["rx_seq_gaps"] == 0
        a.publish(_upd([9], [1.0]), now=0.0)  # seq 4 after lost seq 3
        assert b.tick(force=True) == 1
        assert b.report()["rx_seq_gaps"] == 1  # counted, never silent

    def test_tick_throttled_to_merge_interval(self, tmp_path):
        create_plane(tmp_path, 2)
        a = GossipPlane(tmp_path, 0, 2, merge_interval_s=60.0)
        b = GossipPlane(tmp_path, 1, 2, merge_interval_s=60.0)
        a.publish(_upd([1], [1.0]), now=0.0)
        assert b.tick() == 1  # first tick is always live
        a.publish(_upd([2], [1.0]), now=0.0)
        assert b.tick() == 0  # throttled, nothing statted
        assert b.tick(force=True) == 1  # force bypasses the throttle

    def test_tick_heartbeats_status_block(self, tmp_path):
        (a, _b) = self._planes(tmp_path)
        assert a.status.ctl_get("c_hbeat") == 0
        a.tick(force=True)
        assert a.status.ctl_get("c_hbeat") > 0

    def test_empty_update_publishes_nothing(self, tmp_path):
        a, b = self._planes(tmp_path)
        a.publish(_upd([], []), now=0.0)
        assert a.report()["tx_wires"] == 0
        assert b.tick(force=True) == 0


# ---------------------------------------------------------------------------
# the ownership rule, one level up
# ---------------------------------------------------------------------------


class TestClusterLayout:
    def test_rank_is_fan_out_shard_over_workers(self):
        from flowsentryx_tpu.parallel.layout import cluster_rank_of

        saddr = (np.arange(4096, dtype=np.uint64)
                 * 2654435761 % (1 << 32)).astype(np.uint32)
        for n, w in ((2, 1), (2, 3), (4, 2)):
            rank = cluster_rank_of(saddr, n, w)
            want = schema.shard_of(saddr, n * w) // np.uint32(w)
            np.testing.assert_array_equal(rank, want)
            assert rank.min() >= 0 and rank.max() < n

    def test_owns_partitions_exactly_once(self):
        from flowsentryx_tpu.parallel.layout import ClusterLayout

        saddr = np.arange(2048, dtype=np.uint32) * np.uint32(40503) \
            + np.uint32(17)
        layouts = [ClusterLayout(r, 4, workers_per_engine=2)
                   for r in range(4)]
        owned = np.stack([lo.owns(saddr) for lo in layouts])
        np.testing.assert_array_equal(owned.sum(axis=0),
                                      np.ones(len(saddr)))
        assert layouts[1].total_shards == 8
        assert layouts[1].shard_span == range(2, 4)

    def test_layout_validation(self):
        from flowsentryx_tpu.parallel.layout import ClusterLayout

        with pytest.raises(ValueError, match=">= 2 engines"):
            ClusterLayout(0, 1)
        with pytest.raises(ValueError, match="rank"):
            ClusterLayout(2, 2)
        with pytest.raises(ValueError, match="workers_per_engine"):
            ClusterLayout(0, 2, workers_per_engine=0)


# ---------------------------------------------------------------------------
# supervisor lifecycle (against the millisecond stub)
# ---------------------------------------------------------------------------


class TestClusterSupervisor:
    def _sup(self, tmp_path, specs, **kw):
        from flowsentryx_tpu.cluster.runner import stub_engine_main
        from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor

        return ClusterSupervisor(tmp_path / "cl", specs,
                                 entry=stub_engine_main, **kw)

    def test_refuses_single_engine_fleet(self, tmp_path):
        from flowsentryx_tpu.cluster.supervisor import ClusterSupervisor

        with pytest.raises(ValueError, match="fsx serve"):
            ClusterSupervisor(tmp_path / "cl", [{}])

    def test_clean_lifecycle_both_ranks_done(self, tmp_path):
        sup = self._sup(tmp_path,
                        [{"stub_serve_s": 0.2}, {"stub_serve_s": 0.2}])
        sup.boot()
        agg = sup.run()
        assert agg["restarts"] == [0, 0]
        assert agg["failed_ranks"] == []
        assert sorted(r["rank"] for r in agg["reports"]) == [0, 1]
        # the supervisor stamped one shared epoch for the whole fleet
        assert agg["t0_ns"] > 0
        for r in range(2):
            st = StatusBlock(status_path(tmp_path / "cl", r))
            assert st.ctl_get("c_state") == schema.CSTATE_DONE
            assert st.ctl_get("c_t0") == agg["t0_ns"]

    def test_crash_fail_open_restart_restores_checkpoint(self, tmp_path):
        """Rank 1 hard-dies mid-serve (``os._exit``, no DONE): the
        supervisor must killpg + respawn it at gen 1 handing it its
        last checkpoint, while rank 0 finishes untouched."""
        ck = tmp_path / "ck_r1.npz"
        ck.write_bytes(b"stub flow memory")
        sup = self._sup(
            tmp_path,
            [{"stub_serve_s": 0.6},
             {"stub_serve_s": 0.6, "stub_crash_after_s": 0.1,
              "checkpoint": str(ck)}])
        sup.boot()
        agg = sup.run()
        assert agg["restarts"] == [0, 1]
        assert agg["failed_ranks"] == []
        gen1 = [r for r in agg["reports"]
                if r["rank"] == 1 and r["gen"] == 1]
        assert gen1, "no gen-1 report from the restarted rank"
        assert gen1[0]["restored"] == str(ck)
        # rank 0's report is gen 0: the survivor never restarted
        assert [r["gen"] for r in agg["reports"] if r["rank"] == 0] \
            == [0]

    def test_restart_without_checkpoint_restores_nothing(self, tmp_path):
        sup = self._sup(
            tmp_path,
            [{"stub_serve_s": 0.5},
             {"stub_serve_s": 0.5, "stub_crash_after_s": 0.1}])
        sup.boot()
        agg = sup.run()
        assert agg["restarts"] == [0, 1]
        gen1 = [r for r in agg["reports"]
                if r["rank"] == 1 and r["gen"] == 1]
        assert gen1 and gen1[0]["restored"] is None

    def test_repeated_kills_exhaust_max_restarts(self, tmp_path):
        """The chaos hook driven past the restart budget: after
        ``max_restarts`` respawns the next death is terminal and the
        rank lands in ``failed_ranks`` (the fleet keeps serving the
        other shard — fail-open, not fail-stop)."""
        sup = self._sup(tmp_path,
                        [{"stub_serve_s": 30.0}, {"stub_serve_s": 30.0}],
                        max_restarts=1)
        sup.boot()
        try:
            deadline = time.monotonic() + 30.0
            killed = 0
            st1 = StatusBlock(status_path(tmp_path / "cl", 1))
            want_gen, hbeat_floor = 0, 0
            while killed < 2 and time.monotonic() < deadline:
                sup.poll()
                # a status field is its writer's last words, so the
                # corpse still reads SERVING after a kill — only a
                # heartbeat ADVANCE past the kill-time value proves the
                # next generation is alive and ticking
                if (st1.ctl_get("c_gen") == want_gen
                        and st1.ctl_get("c_hbeat") > hbeat_floor):
                    hbeat_floor = st1.ctl_get("c_hbeat")
                    sup.kill(1)
                    killed += 1
                    want_gen += 1
                time.sleep(0.02)
            assert killed == 2
            while 1 not in sup._failed \
                    and time.monotonic() < deadline:
                sup.poll()
                time.sleep(0.02)
            assert sup.restarts[1] == 1
            assert 1 in sup._failed
            assert sup._procs[0].is_alive()  # the survivor serves on
        finally:
            sup.close()
        assert sup.aggregate()["failed_ranks"] == [1]

    def test_request_stop_drains_fleet_early(self, tmp_path):
        sup = self._sup(tmp_path,
                        [{"stub_serve_s": 30.0}, {"stub_serve_s": 30.0}])
        sup.boot()
        t0 = time.monotonic()
        agg = sup.run(max_seconds=0.3)
        assert time.monotonic() - t0 < 15.0  # not the 30 s serve
        assert agg["failed_ranks"] == []
        assert agg["restarts"] == [0, 0]

    def test_aggregate_counts_each_rank_latest_gen_once(self, tmp_path):
        import json

        # a rank that wrote a gen-0 report and was then restarted must
        # not have both generations' records summed against one wall
        sup = self._sup(tmp_path, [{}, {}])
        d = tmp_path / "cl"
        d.mkdir(parents=True, exist_ok=True)
        for r, g, n, w in [(0, 0, 100, 1.0), (0, 1, 40, 0.5),
                           (1, 0, 60, 2.0)]:
            (d / f"report_r{r}_g{g}.json").write_text(json.dumps(
                {"rank": r, "gen": g,
                 "report": {"records": n, "batches": 1, "wall_s": w}}))
        agg = sup.aggregate()
        assert agg["records"] == 40 + 60
        assert agg["max_wall_s"] == 2.0

    def test_aggregate_merges_latency_hists_exactly(self, tmp_path):
        import json

        from flowsentryx_tpu.engine.metrics import LatencyHist

        # per-rank HDR bucket counts merge into EXACT cluster
        # percentiles (never averaged per-rank p99s); a rank without
        # a latency block (a stub, an old report) is skipped
        h0, h1 = LatencyHist(), LatencyHist()
        for _ in range(99):
            h0.add(100e-6)
        h0.add(50e-3)          # rank 0's one slow record
        for _ in range(100):
            h1.add(200e-6)
        sup = self._sup(tmp_path, [{}, {}])
        d = tmp_path / "cl"
        d.mkdir(parents=True, exist_ok=True)
        for r, h in ((0, h0), (1, h1)):
            (d / f"report_r{r}_g0.json").write_text(json.dumps(
                {"rank": r, "gen": 0,
                 "report": {"records": h.n, "batches": 1, "wall_s": 1.0,
                            "latency": {
                                "seal_to_verdict": h.to_dict(),
                                "hist": h.to_counts()}}}))
        (d / "report_r2_g0.json").write_text(json.dumps(
            {"rank": 2, "gen": 0,
             "report": {"records": 0, "batches": 0, "wall_s": 0.1}}))
        agg = sup.aggregate()
        lat = agg["latency"]
        ref = LatencyHist()
        ref.merge(h0)
        ref.merge(h1)
        assert lat["seal_to_verdict"] == ref.to_dict()
        assert lat["seal_to_verdict"]["n"] == 200
        # the merged p999 sees rank 0's slow tail, the p50 the bulk
        assert lat["seal_to_verdict"]["p999"] > 10_000
        assert lat["seal_to_verdict"]["p50"] < 500
        assert set(lat["per_rank_p99"]) == {"0", "1"}

    def test_boot_stamps_wall_epoch_twin(self, tmp_path):
        # the monotonic epoch's CLOCK_REALTIME twin (ISSUE 15): what a
        # peer HOST rebases this fleet's verdict wires with — stamped
        # into every status block next to c_t0
        sup = self._sup(tmp_path,
                        [{"stub_serve_s": 0.1}, {"stub_serve_s": 0.1}])
        sup.boot()
        agg = sup.run()
        assert agg["t0_wall_ns"] > 0
        for r in range(2):
            st = StatusBlock(status_path(tmp_path / "cl", r))
            assert st.ctl_get("c_t0_wall") == agg["t0_wall_ns"]

    def test_refusal_names_ranks_ages_and_remediation(self, tmp_path):
        """Satellite (ISSUE 15): the boot-over-live-plane refusal must
        tell the operator WHICH ranks are live, HOW fresh their
        heartbeats are, and WHAT to do — not just that it refused."""
        d = tmp_path / "cl"
        create_plane(d, 2)
        now_ns = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
        for r, age_s in ((0, 1.0), (1, 3.0)):
            st = StatusBlock(status_path(d, r))
            st.ctl_set("c_state", schema.CSTATE_SERVING)
            st.ctl_set("c_hbeat", now_ns - int(age_s * 1e9))
        sup = self._sup(tmp_path,
                        [{"stub_serve_s": 0.1}, {"stub_serve_s": 0.1}])
        with pytest.raises(RuntimeError) as ei:
            sup.boot()
        msg = str(ei.value)
        assert "rank 0 heartbeated" in msg
        assert "rank 1 heartbeated" in msg
        assert "s ago" in msg            # the ages, human-readable
        assert "Remediation" in msg      # what to actually do
        assert "fresh directory" in msg

    def test_boot_ignores_future_heartbeat_as_stale(self, tmp_path):
        # CLOCK_MONOTONIC restarts at reboot: a persisted plane whose
        # heartbeats are AHEAD of the current clock is a dead fleet,
        # not a live one — boot must stomp it, not refuse
        d = tmp_path / "cl"
        create_plane(d, 2)
        st = StatusBlock(status_path(d, 0))
        st.ctl_set("c_state", schema.CSTATE_SERVING)
        st.ctl_set("c_hbeat",
                   time.clock_gettime_ns(time.CLOCK_MONOTONIC)
                   + int(1e14))
        sup = self._sup(tmp_path,
                        [{"stub_serve_s": 0.1}, {"stub_serve_s": 0.1}])
        sup.boot()
        agg = sup.run()
        assert agg["failed_ranks"] == []

    def test_boot_refuses_live_plane_stomps_dead_one(self, tmp_path):
        # create_plane re-truncates every mmap'd file: booting a new
        # fleet over a LIVE one would SIGBUS its serving engines and
        # double-consume their SPSC ring shards — refuse while
        # heartbeats are fresh, allow once the fleet is dead
        sup1 = self._sup(tmp_path,
                         [{"stub_serve_s": 30.0}, {"stub_serve_s": 30.0}])
        sup1.boot()
        try:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                sts = [StatusBlock(status_path(tmp_path / "cl", r))
                       for r in range(2)]
                if all(st.ctl_get("c_state") == schema.CSTATE_SERVING
                       and st.ctl_get("c_hbeat") for st in sts):
                    break
                time.sleep(0.02)
            sup2 = self._sup(
                tmp_path,
                [{"stub_serve_s": 30.0}, {"stub_serve_s": 30.0}])
            with pytest.raises(RuntimeError, match="live engines"):
                sup2.boot()
        finally:
            sup1.close()
        # the fleet is dead now: the same dir must boot cleanly
        sup3 = self._sup(tmp_path,
                         [{"stub_serve_s": 0.1}, {"stub_serve_s": 0.1}])
        sup3.boot()
        agg = sup3.run()
        assert agg["failed_ranks"] == []

    def test_drain_overrun_rank_is_failed_not_silent_success(
            self, tmp_path):
        # a rank that ignores stop and overruns the drain bound is
        # force-killed by close() — it MUST surface in failed_ranks
        # (the CLI exit code keys on it); reading a truncated drain
        # as success would hide lost shard records from the operator
        sup = self._sup(tmp_path, [
            {"stub_serve_s": 0.2},
            {"stub_serve_s": 30.0, "stub_ignore_stop": True},
        ])
        sup.boot()
        agg = sup.run(max_seconds=0.3, drain_timeout_s=1.0)
        assert agg["failed_ranks"] == [1]
        assert agg["restarts"] == [0, 0]  # killed, not crash-restarted


class TestPinCores:
    """The per-core deployment shape: rank r owns core r with an
    XLA pool sized to its one core (runner.pin_core_for/pin_to_core,
    `fsx cluster --pin-cores`)."""

    def test_auto_pins_when_fleet_fits_host(self):
        from flowsentryx_tpu.cluster.runner import pin_core_for

        assert [pin_core_for(r, 2, "auto", ncpu=2)
                for r in range(2)] == [0, 1]

    def test_auto_leaves_oversubscribed_fleet_to_scheduler(self):
        from flowsentryx_tpu.cluster.runner import pin_core_for

        # forcing two engines to time-slice one core while another
        # idles is worse than letting the scheduler balance
        assert pin_core_for(0, 4, "auto", ncpu=2) is None

    def test_on_pins_modulo_host(self):
        from flowsentryx_tpu.cluster.runner import pin_core_for

        assert pin_core_for(3, 4, "on", ncpu=2) == 1

    def test_off_never_pins(self):
        from flowsentryx_tpu.cluster.runner import pin_core_for

        assert pin_core_for(0, 2, "off", ncpu=2) is None

    def test_pin_to_core_sets_mask_and_right_sizes_pool(self):
        from flowsentryx_tpu.cluster.runner import pin_to_core

        mask0 = os.sched_getaffinity(0)
        env0 = os.environ.get("XLA_FLAGS")
        try:
            pin_to_core(0)
            assert os.sched_getaffinity(0) == {0}
            # the pool right-sizing must ride XLA_FLAGS (read at
            # backend init), not a jax import-order requirement
            assert ("intra_op_parallelism_threads=1"
                    in os.environ["XLA_FLAGS"])
        finally:
            os.sched_setaffinity(0, mask0)
            if env0 is None:
                os.environ.pop("XLA_FLAGS", None)
            else:
                os.environ["XLA_FLAGS"] = env0


# ---------------------------------------------------------------------------
# pre-boot CLI refusals (all jax-free, each naming its problem)
# ---------------------------------------------------------------------------


class TestClusterCLI:
    def _run(self, argv, capsys):
        from flowsentryx_tpu.cli import main

        rc = main(argv)
        return rc, capsys.readouterr()

    def test_cluster_flag_refusals(self, capsys):
        rc, cap = self._run(["cluster", "--engines", "1"], capsys)
        assert rc == 1 and "fsx serve" in cap.err
        rc, cap = self._run(
            ["cluster", "--engines", "2", "--shards", "3"], capsys)
        assert rc == 1 and "multiple" in cap.err
        # 0 % 2 == 0 must not sneak an engine fleet with no shards
        # past the refusals into N jax boots that all crash
        rc, cap = self._run(
            ["cluster", "--engines", "2", "--shards", "0"], capsys)
        assert rc == 1 and "cannot feed" in cap.err
        rc, cap = self._run(
            ["cluster", "--engines", "2", "--shards", "-2"], capsys)
        assert rc == 1 and "cannot feed" in cap.err
        rc, cap = self._run(
            ["cluster", "--checkpoint", "/tmp/same_path.npz"], capsys)
        assert rc == 1 and "{rank}" in cap.err
        # a stray placeholder must refuse pre-boot, not KeyError after
        # the jax boot; a format-spec'd {rank:02d} is a VALID template
        # (proved by falling through to the next refusal in line)
        rc, cap = self._run(
            ["cluster", "--checkpoint", "/tmp/ck_{rank}_{host}.npz"],
            capsys)
        assert rc == 1 and "rank= alone" in cap.err
        rc, cap = self._run(
            ["cluster", "--checkpoint", "/tmp/ck_{rank:02d}.npz",
             "--checkpoint-every", "-1"], capsys)
        assert rc == 1 and "--checkpoint-every must be >= 0" in cap.err
        rc, cap = self._run(
            ["cluster", "--checkpoint-every", "5"], capsys)
        assert rc == 1 and "--checkpoint" in cap.err
        rc, cap = self._run(["cluster", "--device-loop", "2"], capsys)
        assert rc == 1 and "--mega" in cap.err
        rc, cap = self._run(
            ["cluster", "--mega", "2", "--device-loop", "2",
             "--verdict-k", "0"], capsys)
        assert rc == 1 and "--verdict-k 0" in cap.err

    def test_cluster_multi_host_flag_refusals(self, capsys):
        # the --hosts trio (ISSUE 15), each refusal naming its problem
        rc, cap = self._run(
            ["cluster", "--hosts", "10.0.0.1:9000,10.0.0.2:9000"],
            capsys)
        assert rc == 1 and "--host-id" in cap.err
        rc, cap = self._run(["cluster", "--host-id", "0"], capsys)
        assert rc == 1 and "--hosts" in cap.err
        rc, cap = self._run(
            ["cluster", "--gossip-listen", "0.0.0.0:9000"], capsys)
        assert rc == 1 and "--hosts" in cap.err
        rc, cap = self._run(
            ["cluster", "--hosts", "10.0.0.1:9000,nonsense",
             "--host-id", "0"], capsys)
        assert rc == 1 and "not IP:PORT" in cap.err
        rc, cap = self._run(
            ["cluster", "--hosts", "10.0.0.1:9000", "--host-id", "0"],
            capsys)
        assert rc == 1 and "1 host(s)" in cap.err
        rc, cap = self._run(
            ["cluster", "--hosts", "10.0.0.1:9000,10.0.0.2:9000",
             "--host-id", "2"], capsys)
        assert rc == 1 and "not in [0, 2)" in cap.err
        rc, cap = self._run(
            ["cluster", "--hosts", "10.0.0.1:9000,10.0.0.2:9000",
             "--host-id", "0", "--gossip-listen", "bad"], capsys)
        assert rc == 1 and "--gossip-listen" in cap.err
        # derived engine ports (base+1+r) must fit under 65536 too —
        # otherwise the "refusal" is a bind crash-loop in a child
        rc, cap = self._run(
            ["cluster", "--hosts", "10.0.0.1:65534,10.0.0.2:9000",
             "--host-id", "0"], capsys)
        assert rc == 1 and "exceeds 65535" in cap.err
        # a 1-engine rank of a multi-host fleet is LEGITIMATE: the
        # --engines >= 2 refusal must not fire before the next check
        # in line (here: a bogus listen port keeps it jax-free)
        rc, cap = self._run(
            ["cluster", "--engines", "1", "--shards", "1",
             "--hosts", "10.0.0.1:9000,10.0.0.2:9000",
             "--host-id", "0", "--gossip-listen", "x:0"], capsys)
        assert rc == 1 and "fsx serve" not in cap.err

    def test_serve_cluster_rank_refusals(self, tmp_path, capsys):
        base = ["serve", "--scenario", "benign", "--packets", "64"]
        rc, cap = self._run(base + ["--cluster-rank", "0"], capsys)
        assert rc == 1 and "R/N" in cap.err
        rc, cap = self._run(base + ["--cluster-rank", "0/1"], capsys)
        assert rc == 1 and "fsx serve" in cap.err
        rc, cap = self._run(base + ["--cluster-rank", "2/2"], capsys)
        assert rc == 1 and "[0, 2)" in cap.err
        rc, cap = self._run(base + ["--cluster-rank", "0/2"], capsys)
        assert rc == 1 and "--ingest-workers" in cap.err
        ring = ["--feature-ring", str(tmp_path / "fring"),
                "--ingest-workers", "1"]
        rc, cap = self._run(
            base + ring + ["--cluster-rank", "0/2"], capsys)
        assert rc == 1 and "--cluster-dir" in cap.err
        rc, cap = self._run(
            base + ring + ["--cluster-rank", "0/2",
                           "--cluster-dir", str(tmp_path / "nowhere")],
            capsys)
        assert rc == 1 and "not an initialized gossip plane" in cap.err
        # an initialized plane whose epoch was never stamped: refused
        # BEFORE jax boots — an engine serving against t0=0 would
        # publish untils no peer can compare
        create_plane(tmp_path / "plane", 2)
        rc, cap = self._run(
            base + ring + ["--cluster-rank", "0/2",
                           "--cluster-dir", str(tmp_path / "plane")],
            capsys)
        assert rc == 1 and "epoch" in cap.err and "c_t0" in cap.err

    def test_device_loop_auto_requires_mega_pre_boot(self, capsys):
        # the autotuner obeys the SAME structural rule as an explicit
        # depth, refused before any calibration drain compiles
        rc, cap = self._run(
            ["serve", "--scenario", "benign", "--packets", "64",
             "--device-loop", "auto"], capsys)
        assert rc == 1 and "--mega" in cap.err
        with pytest.raises(SystemExit) as ex:
            self._run(
                ["serve", "--scenario", "benign", "--packets", "64",
                 "--device-loop", "nope"], capsys)
        assert ex.value.code == 2  # argparse: not an int, not 'auto'


# ---------------------------------------------------------------------------
# ring-depth autotuning policy (the pure half of --device-loop auto)
# ---------------------------------------------------------------------------


class TestChooseRingDepth:
    def _m(self, ring, overlap, rounds=4):
        return {"ring": ring, "overlap_fraction": overlap,
                "rounds": rounds, "ring_occupancy": 1.0}

    def test_shallowest_within_knee_wins(self):
        from flowsentryx_tpu.fused.device_loop import choose_ring_depth

        depth, detail = choose_ring_depth(
            [self._m(2, 0.85), self._m(4, 0.9), self._m(8, 0.91)])
        assert depth == 2  # 0.85 >= 0.9 * 0.91: deeper buys nothing
        assert "shallowest" in detail["reason"]

    def test_knee_requires_real_gain(self):
        from flowsentryx_tpu.fused.device_loop import choose_ring_depth

        depth, _ = choose_ring_depth(
            [self._m(2, 0.3), self._m(4, 0.88), self._m(8, 0.9)])
        assert depth == 4  # 2 is far off the knee, 4 is within it

    def test_no_completed_round_defaults_shallow(self):
        from flowsentryx_tpu.fused.device_loop import choose_ring_depth

        depth, detail = choose_ring_depth(
            [self._m(2, 0.0, rounds=0), self._m(4, 0.0, rounds=0)])
        assert depth == 2
        assert "no candidate completed" in detail["reason"]

    def test_zero_overlap_keeps_ring_shallow(self):
        from flowsentryx_tpu.fused.device_loop import choose_ring_depth

        depth, detail = choose_ring_depth(
            [self._m(2, 0.0), self._m(4, 0.0), self._m(8, 0.0)])
        assert depth == 2
        assert "no H2D overlap" in detail["reason"]

    def test_unfired_candidates_are_skipped(self):
        from flowsentryx_tpu.fused.device_loop import choose_ring_depth

        depth, _ = choose_ring_depth(
            [self._m(2, 0.9, rounds=0), self._m(4, 0.7)])
        assert depth == 4  # ring 2 measured nothing, it can't win

    def test_calibration_drive_measures_real_ring(self):
        """The drive half (``engine.calibrate_ring_depth``): one
        candidate, bounded small — the measurement must come from a
        real completed ring drain (rounds fired, overlap measured),
        and the verdict must carry the full evidence trail the CLI
        prints.  One XLA ring compile, ~10 s."""
        from test_engine import small_cfg

        from flowsentryx_tpu.engine.engine import calibrate_ring_depth

        cfg = small_cfg(batch=128, cap=1 << 12, pps_threshold=200.0,
                        bps_threshold=1e9)
        depth, detail = calibrate_ring_depth(
            cfg, mega_n=2, candidates=(2,), batches=16)
        assert depth == 2
        [m] = detail["candidates"]
        assert m["rounds"] >= 1
        assert 0.0 <= m["overlap_fraction"] <= 1.0
        assert m["records_per_s"] > 0
        assert detail["calibration_batches"] == 16
        assert detail["reason"]


# ---------------------------------------------------------------------------
# cluster-vs-single-engine parity + engine gossip wiring (in-process)
# ---------------------------------------------------------------------------


class TestClusterParity:
    """The cluster topology is the IP-hash partition rule extended to
    whole engines, and a sealed batch never mixes shards — so serving
    the SAME prefilled 2-shard fan-out as one engine with two drain
    workers or as two rank engines with one worker each must produce
    byte-identical blacklists (keys AND untils, under the shared t0
    epoch) and exactly-additive stats.  Probed empirically before this
    test pinned it: the equality is exact, not approximate, BECAUSE
    batch composition is per-shard in both topologies (contrast
    ``test_sharded_ingest_two_workers_equivalent``, where inline
    whole-stream batches legally drift at decision boundaries)."""

    BATCH = 256

    def _records(self):
        from flowsentryx_tpu.engine.traffic import (
            Scenario, TrafficGen, TrafficSpec,
        )

        return TrafficGen(TrafficSpec(
            scenario=Scenario.UDP_FLOOD_MULTI, rate_pps=1e7,
            n_attack_ips=8, n_benign_ips=24, attack_fraction=0.8,
            seed=13,
        )).next_records(self.BATCH * 8)

    def _fill(self, base, recs, total):
        from flowsentryx_tpu.engine.shm import ShmRing

        shard = schema.shard_of(recs["saddr"], total)
        for k in range(total):
            ring = ShmRing.create(
                schema.shard_ring_path(base, k, total),
                1 << 12, schema.FLOW_RECORD_DTYPE)
            part = recs[shard == np.uint32(k)]
            assert ring.produce(part) == len(part)

    def _drain(self, base, workers, offset, total, t0, gossip=None):
        import jax

        from test_engine import small_cfg

        from flowsentryx_tpu.engine import Engine
        from flowsentryx_tpu.ingest import ShardedIngest

        src = ShardedIngest(base, workers, shard_offset=offset,
                            total_shards=total, queue_slots=16,
                            precompact=False, t0_grace_s=0.2)
        sink = CollectSink()
        eng = Engine(small_cfg(batch=self.BATCH, cap=1 << 14,
                               pps_threshold=200.0, bps_threshold=1e9),
                     src, sink, readback_depth=4, t0_ns=t0,
                     sink_thread=False, gossip=gossip)
        try:
            src.request_stop()
            with jax.transfer_guard("disallow"):
                rep = eng.run()
        finally:
            src.close()
        return rep, sink

    def test_two_rank_engines_equal_one_engine_two_workers(
            self, tmp_path):
        recs = self._records()
        t0 = int(recs["ts_ns"].min())

        base_a = str(tmp_path / "single")
        self._fill(base_a, recs, 2)
        rep_a, sink_a = self._drain(base_a, 2, 0, 2, t0)

        base_b = str(tmp_path / "cluster")
        self._fill(base_b, recs, 2)
        create_plane(tmp_path / "plane", 2)
        planes = [GossipPlane(tmp_path / "plane", r, 2,
                              sink=CollectSink(), merge_interval_s=0.0)
                  for r in range(2)]
        rep_b0, sink_b0 = self._drain(base_b, 1, 0, 2, t0,
                                      gossip=planes[0])
        rep_b1, sink_b1 = self._drain(base_b, 1, 1, 2, t0,
                                      gossip=planes[1])

        # lossless, and every record on exactly one engine
        assert rep_b0.records + rep_b1.records \
            == rep_a.records == len(recs)
        # blacklist parity: keys AND untils byte-identical (the ranks'
        # shards are disjoint, so plain dict-merge is the cluster view)
        merged = dict(sink_b0.blocked)
        merged.update(sink_b1.blocked)
        assert merged == sink_a.blocked
        assert sink_b0.blocked.keys() & sink_b1.blocked.keys() == set()
        # stats parity: every counter exactly additive across ranks
        for field in rep_a.stats:
            assert rep_b0.stats[field] + rep_b1.stats[field] \
                == rep_a.stats[field], field
        # both shards actually exercised mitigation
        assert sink_b0.blocked and sink_b1.blocked

        # engine gossip wiring (Engine._apply_updates -> publish,
        # Engine._reap_ready -> tick): rank 1 served AFTER rank 0
        # published, so its merged view must already hold rank 0's
        # whole blacklist, byte-identical untils, delivered to ITS
        # gossip sink (the second path to the kernel tier)
        r1 = rep_b1.cluster
        assert r1["merged_digest"] == rep_b0.cluster["published_digest"]
        assert r1["rx_seq_gaps"] == 0
        assert planes[1].sink.blocked == sink_b0.blocked
        # the late peer's publishes converge on rank 0's next tick
        planes[0].tick(force=True)
        assert planes[0].report()["merged_digest"] == \
            r1["published_digest"]
        assert planes[0].sink.blocked == sink_b1.blocked

    def test_cluster_report_rides_engine_report(self, tmp_path):
        """EngineReport.cluster is None outside cluster serving, and
        carries the gossip accounting inside it."""
        from flowsentryx_tpu.engine import ArraySource, Engine, NullSink
        from test_engine import small_cfg

        rep = Engine(small_cfg(batch=128),
                     ArraySource(self._records()[:128]),
                     NullSink(), sink_thread=False).run()
        assert rep.cluster is None
