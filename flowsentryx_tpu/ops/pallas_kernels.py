"""Hand-written Pallas TPU kernels for the hot ops.

Two kernels, each with an XLA-composed twin elsewhere in the tree (the
twin is the correctness oracle and the fallback on non-TPU backends):

* :func:`score_int8` — the classifier's fused quantize → int8 dot →
  requant → quantized-sigmoid pipeline in ONE VPU pass over the batch
  (twin: :func:`flowsentryx_tpu.models.logreg.classify_batch_int8_matmul`).
  With K=8, N=1 the "matmul" is really a row reduction; doing it on the
  VPU in the same pass as both quantizations means the batch is read
  from VMEM exactly once and nothing round-trips through HBM between
  stages.  All intermediate values are ≤ 255·127·8 < 2^18, exactly
  representable in f32, so f32 arithmetic reproduces the int32 path
  bit-for-bit.
* :func:`table_summary` — operational scan over the device-resident
  per-IP state table (tracked/blocked/stale counts): one streamed pass
  through the [N]-row arrays with the grid pipelining HBM→VMEM blocks,
  reading key/blocked/last_seen together instead of three separate
  XLA reductions.

Kernels run in Mosaic on TPU and in interpreter mode elsewhere (CPU
tests exercise the same code path; ``interpret`` auto-detects).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flowsentryx_tpu.core.schema import NUM_FEATURES, IpTableState, TableCol
from flowsentryx_tpu.models.logreg import LogRegParams


def _interpret() -> bool:
    """Mosaic needs a real TPU; everywhere else run the interpreter."""
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Fused int8 scoring
# ---------------------------------------------------------------------------

TILE_B = 512  # batch rows per grid step (f32 sublane 8 × 64 — ample)

#: Layout of the scalar-parameter vector handed to the kernel.
_P_IN_SCALE, _P_IN_ZP, _P_WSCALE, _P_BIAS, _P_OUT_SCALE, _P_OUT_ZP, _P_LOG1P = range(7)


def _score_kernel(x_ref, w_ref, p_ref, out_ref):
    x = x_ref[:]                      # [TILE_B, 8] f32
    p = p_ref[:]                      # [1, 8] f32 scalar params
    log_domain = p[0, _P_LOG1P] > 0
    x = jnp.where(log_domain, jnp.log1p(x), x)

    # 1. input quantization (quint8 affine; f32 domain, exact)
    in_zp = p[0, _P_IN_ZP]
    q_x = jnp.clip(jnp.round(x / p[0, _P_IN_SCALE]) + in_zp, 0.0, 255.0)

    # 2. "matmul": K=8, N=1 → row reduction on the VPU.  (q_x - zp)·w
    #    with |acc| < 2^18 — exact in f32.
    acc = jnp.sum((q_x - in_zp) * w_ref[:], axis=1, keepdims=True)  # [TB,1]

    # 3. dequant + bias, then output requantization (quint8 affine)
    y = acc * (p[0, _P_IN_SCALE] * p[0, _P_WSCALE]) + p[0, _P_BIAS]
    q_y = jnp.clip(
        jnp.round(y / p[0, _P_OUT_SCALE]) + p[0, _P_OUT_ZP], 0.0, 255.0
    )
    y_dq = (q_y - p[0, _P_OUT_ZP]) * p[0, _P_OUT_SCALE]

    # 4. quantized sigmoid: fixed qparams scale 1/256, zp 0 (torch)
    prob = jax.nn.sigmoid(y_dq)
    out_ref[:] = jnp.clip(jnp.round(prob * 256.0), 0.0, 255.0) * (1.0 / 256.0)


@jax.jit
def score_int8(params: LogRegParams, x: jnp.ndarray) -> jnp.ndarray:
    """Pallas twin of ``classify_batch_int8_matmul``: ``[B, 8] → [B]``.

    Pads the batch to a TILE_B multiple (scores of the zero padding are
    discarded), runs one fused VPU pass per tile.
    """
    b = x.shape[0]
    bp = ((b + TILE_B - 1) // TILE_B) * TILE_B
    x = jnp.pad(x.astype(jnp.float32), ((0, bp - b), (0, 0)))

    w = params.w_int8.astype(jnp.float32).reshape(1, NUM_FEATURES)
    p = jnp.zeros((1, 8), jnp.float32)
    p = p.at[0, _P_IN_SCALE].set(params.in_scale.astype(jnp.float32))
    p = p.at[0, _P_IN_ZP].set(params.in_zp.astype(jnp.float32))
    p = p.at[0, _P_WSCALE].set(params.w_scale.astype(jnp.float32))
    p = p.at[0, _P_BIAS].set(params.bias.astype(jnp.float32))
    p = p.at[0, _P_OUT_SCALE].set(params.out_scale.astype(jnp.float32))
    p = p.at[0, _P_OUT_ZP].set(params.out_zp.astype(jnp.float32))
    p = p.at[0, _P_LOG1P].set(params.log1p.astype(jnp.float32))

    out = pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((bp, 1), jnp.float32),
        grid=(bp // TILE_B,),
        in_specs=[
            pl.BlockSpec((TILE_B, NUM_FEATURES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, NUM_FEATURES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_B, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(x, w, p)
    return out[:b, 0]


# ---------------------------------------------------------------------------
# Table summary scan
# ---------------------------------------------------------------------------

_CHUNK = 8 * 128  # one f32 tile per grid step


def _summary_kernel(key_ref, blocked_ref, seen_ref, now_ref, out_ref):
    """Accumulates per-LANE partials (Mosaic forbids scalar VMEM stores;
    row-wide vector adds are the natural VPU shape anyway).  Rows of the
    [4, 128] output: 0=tracked 1=blocked 2=stale as lane-partial sums,
    3=per-lane max last_seen.  The host wrapper reduces over lanes."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    now = now_ref[0, 0]
    stale_s = now_ref[0, 1]
    key = key_ref[:]                        # [8, 128]
    tracked = key != 0
    blocked = tracked & (blocked_ref[:] > now)
    stale = tracked & (now - seen_ref[:] > stale_s)

    out_ref[0, :] += jnp.sum(tracked.astype(jnp.float32), axis=0)
    out_ref[1, :] += jnp.sum(blocked.astype(jnp.float32), axis=0)
    out_ref[2, :] += jnp.sum(stale.astype(jnp.float32), axis=0)
    out_ref[3, :] = jnp.maximum(
        out_ref[3, :], jnp.max(jnp.where(tracked, seen_ref[:], 0.0), axis=0)
    )


@functools.partial(jax.jit, static_argnames=("stale_s",))
def _table_summary_device(
    key: jnp.ndarray,
    blocked_until: jnp.ndarray,
    last_seen: jnp.ndarray,
    now: jnp.ndarray,
    stale_s: float,
) -> jnp.ndarray:
    n = key.shape[0]
    rows = n // 128
    shape2d = (rows, 128)
    block = (8, 128)
    nowv = jnp.stack([now.astype(jnp.float32), jnp.float32(stale_s)]).reshape(1, 2)

    lanes = pl.pallas_call(
        _summary_kernel,
        out_shape=jax.ShapeDtypeStruct((4, 128), jnp.float32),
        grid=(rows // 8,),
        in_specs=[
            pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((4, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(
        key.reshape(shape2d),
        blocked_until.reshape(shape2d),
        last_seen.reshape(shape2d),
        nowv,
    )
    # Lane reduction: 3 sums + 1 max over the 128 partials.  Count sums
    # go through int32 — per-lane partials are exact in f32 (each lane
    # accumulates <= capacity/128 <= 2^25/128 = 2^18 unit increments),
    # but summing 128 of them in f32 would lose exactness past 2^24
    # total, where the XLA twin (integer sum) stays exact.
    counts = jnp.sum(lanes[:3].astype(jnp.int32), axis=1)  # [3] exact
    return counts, jnp.max(lanes[3])


@functools.partial(jax.jit, static_argnames=("stale_s", "use_pallas"))
def _table_summary(key, state, now, stale_s, use_pallas):
    """Column extraction + dispatch under ONE jit, so the host-side
    caller never materializes slice constants eagerly (the engine's
    transfer-guard contract)."""
    blocked_until = state[..., int(TableCol.BLOCKED_UNTIL)]
    last_seen = state[..., int(TableCol.LAST_SEEN)]
    fn = _table_summary_device if use_pallas else _table_summary_xla
    return fn(key, blocked_until, last_seen, now, stale_s)


@functools.partial(jax.jit, static_argnames=("stale_s",))
def _table_summary_xla(key, blocked_until, last_seen, now, stale_s):
    """XLA twin of the summary kernel (correctness oracle + fallback)."""
    tracked = key != 0
    counts = jnp.stack(
        [
            jnp.sum(tracked, dtype=jnp.int32),
            jnp.sum(tracked & (blocked_until > now), dtype=jnp.int32),
            jnp.sum(tracked & (now - last_seen > stale_s), dtype=jnp.int32),
        ]
    )
    return counts, jnp.max(jnp.where(tracked, last_seen, 0.0))


def table_summary(
    table: IpTableState, now: float, stale_s: float = 30.0
) -> dict:
    """Operational counters over the live state table, one device pass.

    Successor of the stats display the reference only planned
    (``README.md:143-146``) — but over the DEVICE table, so the engine
    can report tracked/blocked/stale flow counts without hauling 40 MB
    to the host.  Tables smaller than one kernel chunk (or misaligned)
    fall back to the XLA-composed reduction — same answer, no Pallas.
    """
    # device_put, not jnp.float32: the clock scalar's H2D hop stays an
    # EXPLICIT transfer, so report building runs clean under
    # jax.transfer_guard("disallow") (the engine's CI guard); same for
    # the result fetch below.  Column extraction happens INSIDE the jit
    # (_table_summary) for the same reason — the eager column-view
    # properties materialize their slice indices host-side.  A SHARDED
    # table needs the scalar replicated over its mesh up front, or the
    # jit reshards it (an implicit D2D hop) on entry.
    sh = getattr(table.key, "sharding", None)
    if isinstance(sh, jax.sharding.NamedSharding):
        dst = jax.sharding.NamedSharding(sh.mesh,
                                         jax.sharding.PartitionSpec())
        now_dev = jax.device_put(np.float32(now), dst)
    else:
        now_dev = jax.device_put(np.float32(now))
    # Pallas only on a REAL TPU: interpret-mode emulation walks the
    # grid step by step, which at production capacities turns a
    # per-report scan into tens of seconds (measured ~100 s at 4M rows
    # on CPU — it silently dominated every engine run's report).  The
    # XLA twin is the same answer at memory-bandwidth speed everywhere
    # else.
    counts, newest = _table_summary(
        table.key, table.state, now_dev,
        float(stale_s),
        use_pallas=(not table.capacity % _CHUNK and not _interpret()),
    )
    counts = jax.device_get(counts)
    return {
        "tracked": int(counts[0]),
        "blocked": int(counts[1]),
        "stale": int(counts[2]),
        "newest_seen_s": float(jax.device_get(newest)),
    }
