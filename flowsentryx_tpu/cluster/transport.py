"""The gossip plane's network leg: loss-tolerant UDP verdict transport.

PR 10's verdict-gossip plane is pairwise SPSC shm — correct on one
host, where the supervisor's single CLOCK_MONOTONIC ``t0`` makes every
gossiped ``until`` byte-identical fleet-wide and the TSO cursor
protocol makes delivery exactly-once and in-order BY CONSTRUCTION.
None of that survives a wire.  This module carries the SAME ``[2K+4]``
compact verdict wire and u64-sequence discipline over UDP datagrams
between hosts, with every unreliable-network failure made EXPLICIT:

* **loss** — sequence holes are counted (``rx_gap``), never repaired
  by waiting: a verdict stream is last-wins and TTL-bounded, so the
  periodic anti-entropy resync (own-map re-publish, ``sync/tuning.py::
  NET_RESYNC_INTERVAL_S``) repairs loss while the verdicts still
  matter, and nothing ever stalls on a retransmit.
* **duplication** — per-peer duplicate suppression on the u64 seq (a
  resent/reflected datagram is counted ``rx_dup`` and dropped, never
  re-applied).
* **reorder** — a BOUNDED per-peer reorder buffer restores sequence
  order up to ``NET_REORDER_WINDOW`` wires; past it the oldest
  buffered wire is delivered out of order and counted
  (``reorder_evict``): evict-and-count, never stall, memory bounded.
* **backpressure** — the publish side never blocks: the sink-section
  handoff queue drops-and-counts past ``NET_OUTQ_MAX``
  (``txq_dropped``), and a failed ``sendto`` drops-and-counts
  (``tx_sock_drops``) — a blocked publisher is the coordinator
  coupling the gossip plane exists to avoid.
* **epochs** — monotonic clocks are per-host, so the single-host
  byte-identical-untils trick cannot cross hosts.  Each host's
  supervisor stamps a CLOCK_REALTIME wall stamp ``t0_wall_ns`` at the
  same instant as its monotonic ``t0`` (``schema.STATUS_T0_WALL_
  OFFSET``); every datagram carries the sender's stamp, and received
  wires are REBASED tx-epoch -> rx-epoch (``until += (tx_t0_wall -
  rx_t0_wall)``) before they touch a sink.  A rebased wire whose
  device-clock ``now`` lands more than ``schema.RANGE_EPOCH_SKEW_S``
  from the receiver's own clock is a LYING epoch (pre-reboot stamp,
  no NTP) — dropped and counted (``epoch_skew_dropped``), with the
  worst observed skew kept as a gauge (``epoch_skew_max``).

**Digest convergence is re-pinned on the rebased form.**  The f32
rebase is lossy (rounding differs with the epoch delta), so two hosts
cannot byte-compare their locally-rebased maps.  The canonical rebased
form is integer ABSOLUTE wall microseconds::

    until_wall_us = tx_t0_wall_ns // 1000 + round(until_f32 * 1e6)

computed from the ORIGINATOR's stamp and f32 bits — both carried
verbatim in the datagram — so every host derives the identical u64
from identical integer arithmetic, and ``net_digest`` converges
byte-exactly.  (The anti-entropy resync re-publishes only wires this
endpoint ORIGINATED, preserving those bits exactly; engines own
disjoint IP-hash spans, so each key has exactly one originator and
last-wins convergence is deterministic.)

Threading contract (registered in ``sync/contracts.py``,
``NETMAILBOX_PLAN``): :meth:`queue_tx` is the only publish-section
method (called from ``GossipPlane.publish`` in the engine's SINK
section); everything else — the socket, every counter, the reorder
state, the canonical map — runs in the merge section
(``GossipPlane.tick``, the engine's dispatch thread).  The two sides
meet only at ``_outq``, a deque whose append/popleft ends are
single-owner (the SPSC handoff idiom).

Everything here is numpy + socket — no jax — so the supervisor, the
federation beacon and the chaos harness stay on the sub-second import
path.
"""

from __future__ import annotations

import collections
import socket
import time

import numpy as np

from flowsentryx_tpu.core import schema
from flowsentryx_tpu.sync import tuning


class NetHandshakeTimeout(RuntimeError):
    """Peer discovery exhausted its retry/backoff budget; the message
    names every peer that never answered."""


def pack_packet(kind: int, host: int, rank: int, seq: int,
                count: int, t0_wall_ns: int,
                wire: np.ndarray | None = None) -> bytes:
    """One gossip datagram (``schema.NET_*`` word layout).  The u64
    ``seq`` and ``t0_wall_ns`` are split across two u32 words each —
    the VerdictMailbox slot-header idiom, boundary-pinned in tests."""
    hdr = np.zeros(schema.NET_PKT_HDR_WORDS, np.uint32)
    hdr[schema.NET_MAGIC_WORD] = schema.NET_PKT_MAGIC
    hdr[schema.NET_KIND_WORD] = kind
    hdr[schema.NET_HOST_WORD] = host
    hdr[schema.NET_RANK_WORD] = rank
    hdr[schema.NET_SEQ_LO_WORD] = seq & 0xFFFFFFFF
    hdr[schema.NET_SEQ_HI_WORD] = (seq >> 32) & 0xFFFFFFFF
    hdr[schema.NET_COUNT_WORD] = count
    hdr[schema.NET_T0_WALL_LO_WORD] = t0_wall_ns & 0xFFFFFFFF
    hdr[schema.NET_T0_WALL_HI_WORD] = (t0_wall_ns >> 32) & 0xFFFFFFFF
    if wire is None:
        return hdr.tobytes()
    return hdr.tobytes() + np.ascontiguousarray(wire, np.uint32).tobytes()


def unpack_packet(data: bytes) -> dict | None:
    """Parse one datagram; None for anything malformed (an open UDP
    port receives whatever the network feels like sending)."""
    if len(data) < schema.NET_PKT_HDR_WORDS * 4 or len(data) % 4:
        return None
    words = np.frombuffer(data, np.uint32)
    if int(words[schema.NET_MAGIC_WORD]) != schema.NET_PKT_MAGIC:
        return None
    wire = words[schema.NET_PKT_HDR_WORDS:].copy()
    if len(wire) and (len(wire) < 6 or len(wire) % 2):
        return None  # a wire payload must be [2K+4] words, K >= 1
    return {
        "kind": int(words[schema.NET_KIND_WORD]),
        "host": int(words[schema.NET_HOST_WORD]),
        "rank": int(words[schema.NET_RANK_WORD]),
        "seq": (int(words[schema.NET_SEQ_LO_WORD])
                | (int(words[schema.NET_SEQ_HI_WORD]) << 32)),
        "count": int(words[schema.NET_COUNT_WORD]),
        "t0_wall_ns": (int(words[schema.NET_T0_WALL_LO_WORD])
                       | (int(words[schema.NET_T0_WALL_HI_WORD]) << 32)),
        "wire": wire if len(wire) else None,
    }


def _wire_entries(wire: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """``(keys u32, until-bit u32)`` of one ``[2K+4]`` wire (the tiny
    numpy-only decode — engine/writeback.py's full decoder rides the
    jax import chain this module must stay off)."""
    k = (wire.shape[0] - 4) // 2
    n = min(int(wire[2 * k]), k)
    return wire[:n], wire[k:k + n]


def until_wall_us(until_bits: np.ndarray, t0_wall_ns: int) -> np.ndarray:
    """The canonical rebased form (module docstring): absolute wall
    microseconds as i64, exact integer arithmetic from the originator's
    epoch stamp and f32 bits — identical on every host."""
    until = np.asarray(until_bits, np.uint32).view(np.float32)
    return (np.rint(until.astype(np.float64) * 1e6).astype(np.int64)
            + np.int64(t0_wall_ns // 1000))


def map_digest(d: dict) -> str:
    """Order-insensitive digest of a ``key -> until_wall_us`` map (the
    GossipPlane digest idiom, on the canonical rebased form)."""
    import zlib

    items = np.array(sorted(d.items()), np.int64)
    return f"{zlib.crc32(items.tobytes()):08x}.{len(d)}"


class NetMailbox:
    """One engine's datagram gossip endpoint (module docstring).

    ``peers`` maps an endpoint key ``(host_id, rank)`` to its UDP
    address.  One socket serves both directions; bind to port 0 and
    read :attr:`addr` for harness-assigned loopback ports.
    """

    def __init__(self, host_id: int, rank: int, t0_ns: int,
                 t0_wall_ns: int, *,
                 listen: tuple[str, int] = ("127.0.0.1", 0),
                 peers: dict | None = None,
                 k_max: int = 64,
                 reorder_window: int = tuning.NET_REORDER_WINDOW,
                 reorder_timeout_s: float = tuning.NET_REORDER_TIMEOUT_S,
                 outq_max: int = tuning.NET_OUTQ_MAX,
                 resync_interval_s: float = tuning.NET_RESYNC_INTERVAL_S):
        if t0_wall_ns <= 0:
            raise ValueError(
                "NetMailbox needs the host's stamped t0_wall_ns epoch "
                "(schema.STATUS_T0_WALL_OFFSET): without it received "
                "wires cannot be rebased into this host's clock")
        self.host_id = host_id
        self.rank = rank
        self.t0_ns = t0_ns
        self.t0_wall_ns = t0_wall_ns
        self.k_max = k_max
        self.reorder_window = reorder_window
        self.reorder_timeout_s = reorder_timeout_s
        #: bounds BOTH handoff queues: the publish-side tx deque and
        #: the rx staging deque (one knob — each is the same "consumer
        #: slower than inflow" shape, and each drops-and-counts)
        self.outq_max = outq_max
        self.resync_interval_s = resync_interval_s
        self.peers: dict[tuple[int, int], tuple[str, int]] = dict(
            peers or {})
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind(tuple(listen))
        #: actual bound address (port 0 resolves here)
        self.addr = self._sock.getsockname()
        # -- publish-side (engine sink section) -------------------------
        self._outq: collections.deque = collections.deque()
        self.txq_dropped = 0
        # -- merge-side (dispatch thread) -------------------------------
        self._tx_seq: dict[tuple[int, int], int] = {}
        #: wires this endpoint ORIGINATED: key -> until f32 bits,
        #: re-published verbatim by the anti-entropy resync
        self._own_map: dict[int, int] = {}
        #: the canonical rebased map: key -> until_wall_us (module
        #: docstring) — own publishes and accepted peer wires alike
        self.net_map: dict[int, int] = {}
        self._rx_state: dict[tuple[int, int], dict] = {}
        self._ready: collections.deque = collections.deque()
        self._peers_seen: set[tuple[int, int]] = set()
        self._resync_peers: set[tuple[int, int]] = set()
        self._next_resync = time.monotonic() + resync_interval_s
        self.tx_wires = 0
        self.tx_pkts = 0
        self.tx_sock_drops = 0
        self.rx_pkts = 0
        self.rx_wires = 0
        self.rx_dup = 0
        self.rx_gap = 0
        self.reorder_evict = 0
        self.gap_timeouts = 0
        self.rx_alien = 0
        self.peer_restarts = 0
        self.epoch_skew_dropped = 0
        self.epoch_skew_max = 0.0
        self.resyncs = 0
        self.hellos_rx = 0
        self.rx_overflow = 0
        self.pruned = 0
        # budget-pressure shedding (engine/predict.py governor):
        # PERIODIC resyncs deferred under engine SLO pressure + the
        # consecutive-deferral streak that bounds the starvation
        # (hello-triggered resyncs are never deferred — a healed
        # partition's repair must not wait on a busy engine)
        self.resync_deferred = 0
        self._resync_defer_streak = 0

    # -- lifecycle (quiescent: no serving thread alive) ---------------------

    def add_peer(self, key: tuple[int, int],
                 addr: tuple[str, int]) -> None:
        """Register one remote endpoint (harnesses with ephemeral
        ports; the CLI derives the whole peer table up front)."""
        self.peers[key] = tuple(addr)

    def close(self) -> None:
        self._sock.close()

    # -- publish side (engine sink section) ---------------------------------

    def queue_tx(self, wire: np.ndarray, count: int) -> bool:
        """Hand one outgoing verdict wire to the merge-side pump.
        False (counted) past the queue bound — the publisher NEVER
        blocks or bloats on a slow/partitioned network (module
        docstring)."""
        if len(self._outq) >= self.outq_max:
            self.txq_dropped += 1
            return False
        self._outq.append((np.array(wire, np.uint32), int(count)))
        return True

    # -- merge side (dispatch thread) ---------------------------------------

    def _sendto(self, payload: bytes, addr: tuple[str, int]) -> bool:
        """The one raw send seam (the chaos injector wraps exactly
        this).  False = dropped-and-counted, never raised: EAGAIN/
        ENOBUFS is tx backpressure, ECONNREFUSED a dead peer — both
        fail open."""
        try:
            self._sock.sendto(payload, addr)
            return True
        except OSError:
            self.tx_sock_drops += 1
            return False

    def _send_wire(self, peer: tuple[int, int], wire: np.ndarray,
                   count: int) -> None:
        seq = self._tx_seq.get(peer, 0) + 1
        self._tx_seq[peer] = seq
        pkt = pack_packet(schema.NET_KIND_WIRE, self.host_id, self.rank,
                          seq, count, self.t0_wall_ns, wire)
        self.tx_pkts += 1
        self._sendto(pkt, self.peers[peer])

    def _send_ctl(self, kind: int, peer: tuple[int, int]) -> None:
        self.tx_pkts += 1
        self._sendto(pack_packet(kind, self.host_id, self.rank, 0, 0,
                                 self.t0_wall_ns), self.peers[peer])

    def pump(self, pressure: float = 0.0) -> None:
        """One merge-section service pass: drain the publish handoff
        onto the network, run the anti-entropy resync when due, and
        ingest every pending datagram (rx machinery below).

        ``pressure > 0`` (the engine governor's budget-pressure shed
        signal, forwarded through ``GossipPlane.tick``) defers a DUE
        periodic resync — re-paced at ``SHED_TICK_STRETCH`` resync
        intervals, capped at ``SHED_MAX_DEFER`` consecutive deferrals
        so pressure can only stretch the loss-repair bound, never
        starve it.  Verdict wires (the tx drain above) and
        hello-triggered resyncs are NEVER deferred: fresh verdicts
        are the latency-critical traffic, and a (re)appeared peer's
        repair is what keeps a healed partition convergent.  Shed
        work is counted (``resync_deferred``), never silent."""
        while True:
            try:
                wire, count = self._outq.popleft()
            except IndexError:
                break
            keys, bits = _wire_entries(wire)
            self._own_map.update(zip(keys.tolist(), bits.tolist()))
            self.net_map.update(zip(
                keys.tolist(),
                until_wall_us(bits, self.t0_wall_ns).tolist()))
            self.tx_wires += 1
            for peer in self.peers:
                self._send_wire(peer, wire, count)
        now = time.monotonic()
        if (pressure > 0.0 and not self._resync_peers
                and now >= self._next_resync
                and self._resync_defer_streak < tuning.SHED_MAX_DEFER):
            self._resync_defer_streak += 1
            self.resync_deferred += 1
            self._next_resync = (
                now + self.resync_interval_s * tuning.SHED_TICK_STRETCH)
        if self._resync_peers or now >= self._next_resync:
            # HELLO-triggered resyncs serve ONLY the (re)appeared
            # peers and never consume the periodic deadline: a host
            # mid-handshake with peer C must not postpone the loss
            # repair the OTHER peers' one-interval bound promises
            targets = set(self._resync_peers)
            self._resync_peers.clear()
            if now >= self._next_resync:
                self._next_resync = now + self.resync_interval_s
                self._resync_defer_streak = 0
                targets |= set(self.peers)
            self._prune_expired()
            self._resync(targets)
        self._recv_all()
        # a sequence hole older than the reorder timeout is loss, not
        # reorder: concede it (rx_gap) so the wires parked behind it
        # deliver — a last-wins, resync-repaired stream never waits on
        # a retransmit that is not coming
        now_m = time.monotonic()
        for src, st in self._rx_state.items():
            while (st["buf"]
                   and now_m - min(v[0] for v in st["buf"].values())
                   > self.reorder_timeout_s):
                self.gap_timeouts += 1
                self._concede_hole(src, st)

    def _prune_expired(self) -> None:
        """Drop long-expired verdicts from both maps (resync cadence):
        the maps hold the LIVE blacklist, and the resync re-publishes
        ``_own_map`` in full — without pruning, a long-serving engine
        re-broadcasts every key it ever condemned, forever.  The grace
        (RANGE_EPOCH_SKEW_S) is the same declared bound the rx side
        enforces, so every host prunes the same keys by the same
        absolute-time rule and the canonical digests stay convergent
        (modulo entries inside the grace window, which both sides
        still hold)."""
        grace = schema.RANGE_EPOCH_SKEW_S
        local_now = ((time.clock_gettime_ns(time.CLOCK_MONOTONIC)
                      - self.t0_ns) * 1e-9)
        if self._own_map:
            floor = local_now - grace
            dead = [k for k, bits in self._own_map.items()
                    if float(np.uint32(bits).view(np.float32)) < floor]
            for k in dead:
                del self._own_map[k]
            self.pruned += len(dead)
        if self.net_map:
            floor_us = int((time.time_ns() // 1000) - grace * 1e6)
            dead = [k for k, us in self.net_map.items()
                    if us < floor_us]
            for k in dead:
                del self.net_map[k]

    def _resync(self, targets: set) -> None:
        """Anti-entropy: re-publish this endpoint's OWN map (original
        f32 bits — the canonical digest survives the round trip
        exactly, module docstring) to ``targets``.  Repairs UDP loss
        and healed partitions within one interval."""
        if not self._own_map or not targets:
            return
        self.resyncs += 1
        items = sorted(self._own_map.items())
        k = self.k_max
        local_now = np.float32(
            (time.clock_gettime_ns(time.CLOCK_MONOTONIC) - self.t0_ns)
            * 1e-9)
        for lo in range(0, len(items), k):
            chunk = items[lo:lo + k]
            wire = np.zeros(2 * k + 4, np.uint32)
            wire[:len(chunk)] = np.array([c[0] for c in chunk],
                                         np.uint32)
            wire[k:k + len(chunk)] = np.array([c[1] for c in chunk],
                                              np.uint32)
            wire[2 * k] = len(chunk)
            wire[2 * k + 3] = local_now.view(np.uint32)
            for peer in targets:
                if peer in self.peers:
                    self._send_wire(peer, wire, len(chunk))

    def _recv_all(self, budget: int = 256) -> None:
        for _ in range(budget):
            try:
                data, from_addr = self._sock.recvfrom(65536)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                continue  # ICMP-reflected refusals from dead peers
            pkt = unpack_packet(data)
            if pkt is None:
                self.rx_alien += 1
                continue
            self.rx_pkts += 1
            src = (pkt["host"], pkt["rank"])
            if (src not in self.peers
                    or from_addr[0] != self.peers[src][0]):
                # the claimed endpoint must arrive FROM its registered
                # host address (IP-level: source ports float through
                # NAT-less racks, and a UDP source IP is itself
                # spoofable — the real trust boundary is the network,
                # the shm plane's posture; this check just stops a
                # misconfigured process from impersonating a peer and
                # resetting its dup-suppression state)
                self.rx_alien += 1
                continue
            self._peers_seen.add(src)
            kind = pkt["kind"]
            if kind == schema.NET_KIND_HELLO:
                # a (re)booting peer announcing itself: welcome it,
                # reset its sequence space (its seqs restart at 1),
                # and queue a full-map resync so it converges without
                # waiting for the periodic sweep
                self.hellos_rx += 1
                self._rx_state.pop(src, None)
                self._resync_peers.add(src)
                self._send_ctl(schema.NET_KIND_WELCOME, src)
            elif kind == schema.NET_KIND_WIRE and pkt["wire"] is not None:
                self._rx_wire(src, pkt["seq"], pkt["count"],
                              pkt["t0_wall_ns"], pkt["wire"])
            # WELCOME/BEACON: the _peers_seen add above is the payload

    def _rx_wire(self, src: tuple, seq: int, count: int,
                 t0_wall_ns: int, wire: np.ndarray) -> None:
        """Per-peer sequence machinery: duplicate suppression, the
        bounded reorder buffer (evict-and-count, never stall), gap
        accounting, peer-restart detection (module docstring)."""
        st = self._rx_state.get(src)
        if st is None:
            # first packet from this peer: expect from one window
            # BEHIND it (seq streams start at 1, but the first packet
            # to ARRIVE may be a reordered later one — anchoring next
            # at `seq` would miscount its in-flight predecessors as
            # duplicates; scenario net_reorder pins this).  A
            # mid-stream join (our restart) parks at worst one window
            # behind and concedes the phantom hole at the timeout.
            st = self._rx_state[src] = {
                "next": max(1, seq - self.reorder_window), "buf": {}}
        if seq < st["next"] - tuning.NET_RESTART_JUMP:
            # far-backward jump: the peer restarted and its sequence
            # space began again — resetting is the only honest read
            # (treating its whole new life as "duplicates" would
            # silently drop every future verdict it publishes)
            self.peer_restarts += 1
            st["buf"].clear()
            st["next"] = seq
        if seq < st["next"] or seq in st["buf"]:
            self.rx_dup += 1
            return
        st["buf"][seq] = (time.monotonic(), count, t0_wall_ns, wire)
        self._drain_in_order(src, st)
        while len(st["buf"]) > self.reorder_window:
            # bounded memory: concede the hole instead of growing
            self.reorder_evict += 1
            self._concede_hole(src, st)

    def _drain_in_order(self, src: tuple, st: dict) -> None:
        while st["next"] in st["buf"]:
            self._accept(src, st["next"],
                         *st["buf"].pop(st["next"])[1:])
            st["next"] += 1

    def _concede_hole(self, src: tuple, st: dict) -> None:
        """Accept that the wires below ``min(buf)`` are LOST (count the
        gap, never silent) and resume in-order delivery from there."""
        s = min(st["buf"])
        self.rx_gap += s - st["next"]
        st["next"] = s
        self._drain_in_order(src, st)

    def _accept(self, src: tuple, seq: int, count: int,
                t0_wall_ns: int, wire: np.ndarray) -> None:
        """Epoch-rebase one in-sequence wire tx->rx and stage it for
        :meth:`pop_wires`; enforce the RANGE_EPOCH_SKEW_S bound."""
        k = (wire.shape[0] - 4) // 2
        n = min(count, k)
        delta_s = (t0_wall_ns - self.t0_wall_ns) * 1e-9
        local_now = ((time.clock_gettime_ns(time.CLOCK_MONOTONIC)
                      - self.t0_ns) * 1e-9)
        wire_now = float(wire[2 * k + 3:2 * k + 4].view(np.float32)[0])
        skew = abs((wire_now + delta_s) - local_now)
        self.epoch_skew_max = max(self.epoch_skew_max, skew)
        if skew > schema.RANGE_EPOCH_SKEW_S:
            self.epoch_skew_dropped += 1
            return
        rebased = wire.copy()
        untils = wire[k:k + n].view(np.float32).astype(np.float64)
        rebased[k:k + n] = ((untils + delta_s).astype(np.float32)
                            .view(np.uint32))
        rebased[2 * k + 3] = np.float32(wire_now + delta_s).view(
            np.uint32)
        keys = wire[:n].copy()
        wall_us = until_wall_us(wire[k:k + n], t0_wall_ns)
        self.net_map.update(zip(keys.tolist(), wall_us.tolist()))
        self.rx_wires += 1
        if len(self._ready) >= self.outq_max:
            # the rx staging queue is bounded like every other queue
            # in this module: a consumer slower than the inflow sees
            # drops-and-counts (the canonical map above already took
            # the entries; the next resync re-delivers them), never
            # unbounded memory or ever-staler verdicts
            self.rx_overflow += 1
            return
        self._ready.append((src, seq, rebased, keys,
                            rebased[k:k + n].view(np.float32)))

    def pop_wires(self, max_wires: int) -> list:
        """Up to ``max_wires`` accepted wires, in per-peer sequence
        order, each rebased into THIS host's epoch:
        ``(src_endpoint, seq, rebased_wire, keys u32, untils f32)``."""
        out = []
        while len(out) < max_wires:
            try:
                out.append(self._ready.popleft())
            except IndexError:
                break
        return out

    def handshake(self, timeout_s: float = tuning.NET_HANDSHAKE_TIMEOUT_S,
                  ) -> None:
        """Peer discovery: HELLO every silent peer with exponential
        backoff (``NET_HANDSHAKE_BACKOFF_*``) until each has answered
        anything, or raise :class:`NetHandshakeTimeout` naming the
        silent ones.  Callers that serve anyway (the engine runner)
        fail OPEN: a late peer's first HELLO triggers the full-map
        resync, so convergence needs no second boot ordering."""
        deadline = time.monotonic() + timeout_s
        backoff = tuning.NET_HANDSHAKE_BACKOFF_BASE_S
        while True:
            pending = set(self.peers) - self._peers_seen
            if not pending:
                return
            for peer in pending:
                self._send_ctl(schema.NET_KIND_HELLO, peer)
            slice_end = min(time.monotonic() + backoff, deadline)
            while time.monotonic() < slice_end:
                self._recv_all()
                if not set(self.peers) - self._peers_seen:
                    return
                time.sleep(0.002)
            if time.monotonic() >= deadline:
                still = sorted(set(self.peers) - self._peers_seen)
                raise NetHandshakeTimeout(
                    f"gossip peer discovery timed out after "
                    f"{timeout_s:.1f}s: no answer from "
                    f"{[f'h{h}r{r}@{self.peers[(h, r)]}' for h, r in still]} "
                    "(backoff ladder exhausted; the caller may serve "
                    "fail-open — a late peer's HELLO triggers a full "
                    "resync)")
            backoff = min(backoff * 2,
                          tuning.NET_HANDSHAKE_BACKOFF_MAX_S)

    # -- reporting (quiescent or merge section) ------------------------------

    def report(self) -> dict:
        return {
            "host": self.host_id,
            "rank": self.rank,
            "peers": len(self.peers),
            "peers_seen": len(self._peers_seen),
            "tx_wires": self.tx_wires,
            "tx_pkts": self.tx_pkts,
            # the satellite counter: EVERY dropped-on-tx path summed
            "tx_drop": self.txq_dropped + self.tx_sock_drops,
            "txq_dropped": self.txq_dropped,
            "tx_sock_drops": self.tx_sock_drops,
            "rx_pkts": self.rx_pkts,
            "rx_wires": self.rx_wires,
            "rx_dup": self.rx_dup,
            "rx_gap": self.rx_gap,
            "reorder_evict": self.reorder_evict,
            "gap_timeouts": self.gap_timeouts,
            "rx_alien": self.rx_alien,
            "peer_restarts": self.peer_restarts,
            "epoch_skew_dropped": self.epoch_skew_dropped,
            "epoch_skew_max": round(self.epoch_skew_max, 6),
            "resyncs": self.resyncs,
            "resync_deferred": self.resync_deferred,
            "hellos_rx": self.hellos_rx,
            "rx_overflow": self.rx_overflow,
            "pruned": self.pruned,
            "net_sources": len(self.net_map),
            "net_digest": map_digest(self.net_map),
        }


class HostBeacon:
    """Supervisor federation heartbeats: one per-host liveness beacon.

    Each host's supervisor beacons every ``NET_BEACON_INTERVAL_S`` and
    listens for its peers'; a peer silent past ``NET_HOST_TIMEOUT_S``
    (from its last beacon, or from OUR boot if it never spoke) is
    DEAD: :meth:`dead_hosts` feeds ``supervisor.aggregate`` — the dead
    host's IP span is announced and fleet health folds FAILED
    (engine/health.py).  Pure control plane: no verdict ever rides a
    beacon, and a dead federation changes nothing for serving engines.
    """

    def __init__(self, host_id: int, t0_wall_ns: int, *,
                 listen: tuple[str, int] = ("127.0.0.1", 0),
                 peers: dict | None = None,
                 interval_s: float = tuning.NET_BEACON_INTERVAL_S,
                 timeout_s: float = tuning.NET_HOST_TIMEOUT_S):
        self.host_id = host_id
        self.t0_wall_ns = t0_wall_ns
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.peers: dict[int, tuple[str, int]] = dict(peers or {})
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind(tuple(listen))
        self.addr = self._sock.getsockname()
        self._boot = time.monotonic()
        self._next_tx = 0.0
        self._seq = 0
        self._last_seen: dict[int, float] = {}
        self.tx_beacons = 0
        self.rx_beacons = 0

    def add_peer(self, host_id: int, addr: tuple[str, int]) -> None:
        self.peers[host_id] = tuple(addr)

    def close(self) -> None:
        self._sock.close()

    def tick(self) -> None:
        """Send when due, ingest everything pending (the supervisor
        poll cadence drives this — no thread of its own)."""
        now = time.monotonic()
        if now >= self._next_tx:
            self._next_tx = now + self.interval_s
            self._seq += 1
            pkt = pack_packet(schema.NET_KIND_BEACON, self.host_id,
                              schema.NET_RANK_BEACON, self._seq, 0,
                              self.t0_wall_ns)
            for addr in self.peers.values():
                try:
                    self._sock.sendto(pkt, addr)
                    self.tx_beacons += 1
                except OSError:
                    pass  # fail open: liveness, not delivery
        for _ in range(64):
            try:
                data, from_addr = self._sock.recvfrom(4096)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                continue
            pkt = unpack_packet(data)
            if (pkt is None or pkt["kind"] != schema.NET_KIND_BEACON
                    or pkt["host"] not in self.peers
                    or from_addr[0] != self.peers[pkt["host"]][0]):
                # same IP-level source check as the mailbox: a stray
                # process must not keep a dead host looking alive
                continue
            self.rx_beacons += 1
            self._last_seen[pkt["host"]] = time.monotonic()

    def dead_hosts(self) -> list[int]:
        """Peer hosts silent past the timeout (never-heard peers count
        from OUR boot — a host that never joined is as dead as one
        that stopped)."""
        now = time.monotonic()
        dead = []
        for h in self.peers:
            last = self._last_seen.get(h, self._boot)
            if now - last > self.timeout_s:
                dead.append(h)
        return sorted(dead)

    def report(self) -> dict:
        now = time.monotonic()
        return {
            "host_id": self.host_id,
            "tx_beacons": self.tx_beacons,
            "rx_beacons": self.rx_beacons,
            "peers": {
                str(h): {
                    "age_s": (round(now - self._last_seen[h], 3)
                              if h in self._last_seen else None),
                }
                for h in sorted(self.peers)
            },
            "dead": self.dead_hosts(),
        }


def engine_net_mailbox(netspec: dict, rank: int, t0_ns: int,
                       t0_wall_ns: int, k_max: int = 64) -> NetMailbox:
    """Build one cluster engine's :class:`NetMailbox` from the CLI's
    net spec (``fsx cluster --hosts``): host h's supervisor beacon
    binds its announced base port, engine r binds ``base + 1 + r``,
    and the peer table is every engine on every OTHER host at the same
    derived offsets (fleets must run the same ``--engines`` per host —
    the port arithmetic IS that assumption, stated once here)."""
    hosts = [tuple(h) for h in netspec["hosts"]]
    hid = int(netspec["host_id"])
    n_eng = int(netspec["engines_per_host"])
    ip, base = netspec.get("listen") or hosts[hid]
    peers = {
        (h, r): (hip, int(hport) + 1 + r)
        for h, (hip, hport) in enumerate(hosts) if h != hid
        for r in range(n_eng)
    }
    return NetMailbox(hid, rank, t0_ns, t0_wall_ns,
                      listen=(ip, int(base) + 1 + rank), peers=peers,
                      k_max=k_max)


def host_beacon(netspec: dict, t0_wall_ns: int, **kw) -> HostBeacon:
    """The supervisor-side twin of :func:`engine_net_mailbox`: the
    federation beacon on host ``host_id``'s announced base port."""
    hosts = [tuple(h) for h in netspec["hosts"]]
    hid = int(netspec["host_id"])
    ip, base = netspec.get("listen") or hosts[hid]
    peers = {h: (hip, int(hport))
             for h, (hip, hport) in enumerate(hosts) if h != hid}
    return HostBeacon(hid, t0_wall_ns, listen=(ip, int(base)),
                      peers=peers, **kw)
