"""Scenario-suite + checkpoint tests (small scale; full scale runs on TPU)."""

import numpy as np

from flowsentryx_tpu import benchmarks
from flowsentryx_tpu.core.config import BatchConfig, FsxConfig, LimiterConfig, TableConfig
from flowsentryx_tpu.engine import CollectSink, Engine, TrafficSource
from flowsentryx_tpu.engine.traffic import Scenario, TrafficSpec


class TestScenarioSuite:
    def test_suite_covers_five_configs(self):
        suite = benchmarks.scenario_suite()
        assert len(suite) == 5
        assert [int(s.name[6]) for s in suite] == [1, 2, 3, 4, 5]

    def test_flood_configs_block_attackers(self):
        # config1 at tiny scale (single source trips the bucket fast);
        # config2 at full scale — its 500 pps/window threshold needs the
        # real per-IP volume (262k pkts / 256 IPs) to be meaningful
        [r1] = benchmarks.run_suite(scale=0.02, names=["config1"])
        [r2] = benchmarks.run_suite(scale=1.0, names=["config2"])
        for r in (r1, r2):
            assert r["packets"] >= 2048
            assert r["stats"]["dropped"] > 0, r["scenario"]
            assert r["blocked_attack"] > 0, r["scenario"]
            assert r["source_recall"] > 0.5, r["scenario"]

    def test_offline_batch_runs_ml_only(self):
        [r] = benchmarks.run_suite(scale=0.02, names=["config3"])
        assert r["stats"]["dropped_rate"] == 0  # thresholds out of reach
        assert r["mpps"] > 0


class TestCheckpoint:
    def test_state_roundtrip_resumes_blocking(self, tmp_path):
        """A restored engine still knows its blacklist: flows condemned
        before the save stay condemned after restore."""
        cfg = FsxConfig(
            table=TableConfig(capacity=1 << 12),
            batch=BatchConfig(max_batch=512),
            limiter=LimiterConfig(pps_threshold=100.0, block_s=1e6),
        )
        spec = TrafficSpec(scenario=Scenario.UDP_FLOOD_MULTI, rate_pps=1e7,
                           n_attack_ips=16, attack_fraction=0.9, seed=31)
        e1 = Engine(cfg, TrafficSource(spec, total=512 * 20), CollectSink())
        rep1 = e1.run()
        assert rep1.stats["dropped"] > 0
        path = e1.checkpoint(tmp_path / "state.npz")

        e2 = Engine(cfg, TrafficSource(spec, total=512 * 4), CollectSink())
        e2.restore(path)
        np.testing.assert_array_equal(
            np.asarray(e2.table.blocked_until), np.asarray(e1.table.blocked_until)
        )
        assert e2.batcher.t0_ns == e1.batcher.t0_ns
        rep2 = e2.run()
        # the restored blacklist drops the same attackers immediately
        assert rep2.stats["dropped_blacklist"] > 0
        # and global counters carried over (resumed, not reset)
        assert rep2.stats["dropped"] >= rep1.stats["dropped"]

    def test_capacity_change_reshards(self, tmp_path):
        """A restore into a different capacity re-places every occupied
        row for the new geometry (PR 8 restore-with-reshard) — the old
        refusal would have forced a state-losing cold boot just to grow
        the table."""
        import dataclasses

        cfg = FsxConfig(table=TableConfig(capacity=1 << 12),
                        batch=BatchConfig(max_batch=256))
        e1 = Engine(cfg, TrafficSource(TrafficSpec(seed=1), total=256), CollectSink())
        e1.run()
        path = e1.checkpoint(tmp_path / "s.npz")
        cfg2 = dataclasses.replace(cfg, table=TableConfig(capacity=1 << 13))
        e2 = Engine(cfg2, TrafficSource(TrafficSpec(seed=1), total=256), CollectSink())
        info = e2.restore(path)
        assert info["resharded"] and info["dropped_rows"] == 0
        k1 = np.asarray(e1.table.key)
        k2 = np.asarray(e2.table.key)
        assert set(k2[k2 != 0]) == set(k1[k1 != 0])

    def test_pre_byte_bucket_checkpoint_refills_credit(self, tmp_path):
        """A snapshot that predates the byte bucket (no tok_bytes
        column) must restore occupied slots with FULL byte credit under
        a byte-limited config — zero credit would spuriously rate-block
        every restored flow's first batch."""
        from flowsentryx_tpu.core.config import LimiterKind

        cfg = FsxConfig(
            table=TableConfig(capacity=1 << 12),
            batch=BatchConfig(max_batch=256),
            limiter=LimiterConfig(kind=LimiterKind.TOKEN_BUCKET,
                                  bucket_rate_bps=1e4,
                                  bucket_burst_bytes=5e4),
        )
        e1 = Engine(cfg, TrafficSource(TrafficSpec(seed=4), total=512),
                    CollectSink())
        e1.run()
        path = e1.checkpoint(tmp_path / "old.npz")
        # strip the tok_bytes column, emulating an r4-era snapshot —
        # faithfully: that era predates the integrity CRC too (keeping
        # the CRC while dropping a member would read as the corruption
        # it technically is)
        with np.load(path) as z:
            d = {k: z[k] for k in z.files
                 if k not in ("table_tok_bytes", "integrity_crc32")}
        np.savez_compressed(path, **d)

        e2 = Engine(cfg, TrafficSource(TrafficSpec(seed=4), total=256),
                    CollectSink())
        e2.restore(path)
        occ = np.asarray(e2.table.key) != 0
        assert occ.any()
        tb = np.asarray(e2.table.tok_bytes)
        assert (tb[occ] == 5e4).all()   # full burst, not zero
        assert (tb[~occ] == 0).all()

    def test_salt_mismatch_rejected_and_peekable(self, tmp_path):
        """A checkpoint's slot layout is a function of the hash salt:
        restoring under a different salt must refuse (it would
        mislocate every key), and peek_salt lets a server adopt the
        right one before compiling (the `fsx serve --restore` path)."""
        import dataclasses
        import pytest

        from flowsentryx_tpu.engine.checkpoint import peek_salt

        cfg = FsxConfig(table=TableConfig(capacity=1 << 12, salt=0x1234),
                        batch=BatchConfig(max_batch=256))
        e1 = Engine(cfg, TrafficSource(TrafficSpec(seed=2), total=256),
                    CollectSink())
        e1.run()
        path = e1.checkpoint(tmp_path / "salted.npz")
        assert peek_salt(path) == 0x1234
        cfg2 = dataclasses.replace(
            cfg, table=dataclasses.replace(cfg.table, salt=0x9999))
        e2 = Engine(cfg2, TrafficSource(TrafficSpec(seed=2), total=256),
                    CollectSink())
        with pytest.raises(ValueError, match="salt"):
            e2.restore(path)
        # adopting the peeked salt restores cleanly
        e3 = Engine(cfg, TrafficSource(TrafficSpec(seed=2), total=256),
                    CollectSink())
        e3.restore(path)
        np.testing.assert_array_equal(np.asarray(e3.table.key),
                                      np.asarray(e1.table.key))


def test_meshed_engine_checkpoint_roundtrip(tmp_path):
    """A single-device checkpoint restores into an 8-device meshed
    engine (rows re-sharded) and vice versa: condemned flows stay
    condemned across the mesh-size change."""
    import jax

    from flowsentryx_tpu.parallel import make_mesh

    cfg = FsxConfig(
        limiter=LimiterConfig(pps_threshold=50.0, bps_threshold=1e9,
                              block_s=3600.0),
        table=TableConfig(capacity=1 << 12),
        batch=BatchConfig(max_batch=512),
    )
    spec = TrafficSpec(scenario=Scenario.UDP_FLOOD_MULTI, rate_pps=1e7,
                       n_attack_ips=8, attack_fraction=0.9, seed=33)
    e1 = Engine(cfg, TrafficSource(spec, total=4096), CollectSink())
    e1.run()
    blocked1 = set(e1._blocked)
    assert blocked1
    path = e1.checkpoint(tmp_path / "mesh_state.npz")

    # resume SHARDED: the blacklist must fire on the first batch
    e2 = Engine(cfg, TrafficSource(spec, total=2048), CollectSink(),
                mesh=make_mesh(8))
    e2.restore(path)
    assert e2.mesh is not None
    rep2 = e2.run()
    assert rep2.stats["dropped_blacklist"] > 0

    # and a sharded engine's own checkpoint restores single-device
    path2 = e2.checkpoint(tmp_path / "mesh_state2.npz")
    e3 = Engine(cfg, TrafficSource(spec, total=2048), CollectSink())
    e3.restore(path2)
    rep3 = e3.run()
    assert rep3.stats["dropped_blacklist"] > 0
    jax.block_until_ready(e3.stats.allowed)


def test_summarize_latencies_is_the_one_reporting_copy():
    """The percentile-summary half of the paced-latency methodology
    (benchmarks.summarize_latencies): bench.py's grid + pulse tier and
    scripts/paced_profile.py all consume this one dict shape."""
    from flowsentryx_tpu.benchmarks import summarize_latencies

    assert summarize_latencies([]) == {"n": 0}
    lats = np.array([0.001, 0.002, 0.003, 0.004, 0.100])
    d = summarize_latencies(lats)
    assert d["n"] == 5
    assert d["p50_ms"] == 3.0
    assert d["max_ms"] == 100.0
    assert d["p50_ms"] <= d["p90_ms"] <= d["p99_ms"] \
        <= d["p999_ms"] <= d["max_ms"]
